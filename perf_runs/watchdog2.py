#!/usr/bin/env python
"""Round-4 second-wave tunnel watchdog: probe every 5 min; on recovery,
run the straw2-kernel experiments (flat-layout probes, tile timings) and
the RMW silicon bench, saving outputs to perf_runs/.  Marker files make
each experiment idempotent across restarts.

Run: nohup python perf_runs/watchdog2.py >> perf_runs/watchdog2.log 2>&1 &
"""
import os
import subprocess
import sys
import time

OUT = "/root/repo/perf_runs"
os.chdir("/root/repo")

EXPERIMENTS = [
    # (marker, timeout_s, argv)
    ("flat_ln", 1500,
     [sys.executable, "perf_runs/probe_flat.py", "512", "2048", "8192"]),
    ("tile64", 900,
     [sys.executable, "perf_runs/verify_tile.py", "64"]),
    ("rmw", 900,
     [sys.executable, "-m", "ceph_tpu.bench.ec_bench", "--plugin", "jax",
      "--k", "8", "--m", "4", "--technique", "cauchy_good",
      "--workload", "rmw", "--rmw-window", "65536", "--json"]),
]


def log(msg):
    print(time.strftime("%FT%TZ", time.gmtime()), msg, flush=True)


def probe() -> bool:
    code = ("import jax\n"
            "assert jax.devices()[0].platform != 'cpu'\n")
    try:
        r = subprocess.run([sys.executable, "-c", code], timeout=90,
                           capture_output=True)
        return r.returncode == 0
    except subprocess.TimeoutExpired:
        return False


def main():
    log(f"watchdog2 started (pid {os.getpid()})")
    while True:
        todo = [e for e in EXPERIMENTS
                if not os.path.exists(f"{OUT}/{e[0]}.done")]
        if not todo:
            log("all experiments captured; exiting")
            return
        if not probe():
            log("tunnel down/wedged; sleeping 300s")
            time.sleep(300)
            continue
        log("tunnel UP")
        for marker, tmo, argv in todo:
            log(f"running {marker}: {' '.join(argv[1:])}")
            try:
                with open(f"{OUT}/{marker}.out", "w") as f:
                    r = subprocess.run(argv, timeout=tmo, stdout=f,
                                       stderr=subprocess.STDOUT)
                if r.returncode == 0:
                    open(f"{OUT}/{marker}.done", "w").close()
                    log(f"{marker} OK")
                else:
                    log(f"{marker} rc={r.returncode}")
            except subprocess.TimeoutExpired:
                log(f"{marker} TIMED OUT after {tmo}s")
            if not probe():
                log("tunnel lost mid-wave; back to sleep")
                break


if __name__ == "__main__":
    main()
