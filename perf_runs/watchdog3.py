#!/usr/bin/env python
"""Round-5 tunnel watchdog (VERDICT r4, next-round item #1).

SUPERSEDED: this job chain is folded into the bench proper —
`python bench.py --watchdog [--deadline YYYY-mm-ddTHH:MM]` runs the
same probe loop / jobs dir / done-marker contract (PR 10).  Kept for
the round-5 log provenance.

Probes the tunneled TPU backend every 5 min; on the first UP it runs the
pending capture jobs from perf_runs/jobs/*.json in filename order.  Each
job file is {"marker": str, "timeout": int, "argv": [...], "env": {...}}.
The jobs dir is rescanned every cycle, so new captures can be queued
while the watchdog runs.  Done-markers make every job idempotent.

Hard-deadline rule: no job STARTS after DEADLINE_UTC — this is the
wedge-prevention contract: the round must never end with a builder
process mid-compile on the tunnel (the r2/r4 wedge trigger was exactly
that).  After the deadline the watchdog only logs probe state.

Run: nohup python perf_runs/watchdog3.py >> perf_runs/watchdog3.log 2>&1 &
"""
import glob
import json
import os
import subprocess
import sys
import time

OUT = "/root/repo/perf_runs"
JOBS = os.path.join(OUT, "jobs")
# Round started 2026-07-31 05:31 UTC; ~12 h wall clock.  Leave a wide
# safety margin before the driver's end-of-round bench run.
DEADLINE_UTC = "2026-07-31T16:30"
os.chdir("/root/repo")
os.makedirs(JOBS, exist_ok=True)


def log(msg):
    print(time.strftime("%FT%TZ", time.gmtime()), msg, flush=True)


def past_deadline() -> bool:
    return time.strftime("%FT%H:%M", time.gmtime()) >= DEADLINE_UTC


def probe() -> bool:
    code = ("import jax\n"
            "assert jax.devices()[0].platform != 'cpu'\n")
    try:
        r = subprocess.run([sys.executable, "-c", code], timeout=90,
                           capture_output=True)
        return r.returncode == 0
    except subprocess.TimeoutExpired:
        return False


def pending_jobs():
    jobs = []
    for path in sorted(glob.glob(os.path.join(JOBS, "*.json"))):
        try:
            with open(path) as f:
                j = json.load(f)
        except Exception as e:
            log(f"bad job file {path}: {e}")
            continue
        if not os.path.exists(os.path.join(OUT, j["marker"] + ".done")):
            jobs.append(j)
    return jobs


def run_job(j):
    marker, tmo = j["marker"], int(j.get("timeout", 900))
    env = dict(os.environ)
    env.update(j.get("env", {}))
    log(f"running {marker}: {' '.join(j['argv'])}")
    try:
        with open(os.path.join(OUT, marker + ".out"), "w") as f:
            r = subprocess.run(j["argv"], timeout=tmo, stdout=f,
                               stderr=subprocess.STDOUT, env=env)
        if r.returncode == 0:
            open(os.path.join(OUT, marker + ".done"), "w").close()
            log(f"{marker} OK")
            return True
        log(f"{marker} rc={r.returncode}")
    except subprocess.TimeoutExpired:
        log(f"{marker} TIMED OUT after {tmo}s")
    return False


def main():
    log(f"watchdog3 started (pid {os.getpid()}), deadline {DEADLINE_UTC}Z")
    while True:
        if past_deadline():
            log(f"past deadline; probe={'UP' if probe() else 'down'}; "
                "no more jobs will start")
            time.sleep(600)
            continue
        todo = pending_jobs()
        if not todo:
            time.sleep(120)
            continue
        if not probe():
            log(f"tunnel down/wedged ({len(todo)} jobs pending); sleeping 300s")
            time.sleep(300)
            continue
        log(f"tunnel UP; {len(todo)} jobs pending")
        for j in todo:
            if past_deadline():
                log("deadline hit mid-wave; stopping")
                break
            run_job(j)
            if not probe():
                log("tunnel lost mid-wave; back to sleep")
                break


if __name__ == "__main__":
    main()
