#!/bin/bash
# TPU tunnel watchdog (round-3 verdict, next-round task #1): probe the
# tunneled axon backend every ~10 min; on the first success, immediately
# capture the outstanding silicon numbers before the tunnel can wedge
# again.  Ordering is deliberate: clay + shec (quick, believed fixed)
# run BEFORE the crush phase, which has wedged the tunnel twice (r2, r4)
# and is attempted last, smallest batch first.
#
# Results land in /root/repo/perf_runs/ as one timestamped JSON line per
# phase; idempotent via done-markers so a restart never re-burns a phase.
set -u
cd /root/repo
OUT=/root/repo/perf_runs
LOG=$OUT/watchdog.log
mkdir -p "$OUT"

log() { echo "$(date -u +%FT%TZ) $*" >> "$LOG"; }

probe() {
    timeout 90 python - <<'EOF' >/dev/null 2>&1
import jax
assert jax.devices()[0].platform != "cpu"
EOF
}

run_phase() {  # run_phase <name> <timeout> <marker> [env=val ...]
    local name=$1 tmo=$2 marker=$3; shift 3
    [ -e "$OUT/$marker.done" ] && return 0
    log "running phase $name ($marker)"
    if env "$@" timeout "$tmo" python bench.py --phase "$name" \
        > "$OUT/$marker.json" 2>> "$LOG"; then
        touch "$OUT/$marker.done"
        log "phase $name ($marker) OK: $(tail -1 "$OUT/$marker.json")"
        return 0
    fi
    log "phase $name ($marker) FAILED rc=$?"
    return 1
}

all_done() {
    for m in clay shec crush_small crush_full; do
        [ -e "$OUT/$m.done" ] || return 1
    done
    return 0
}

log "watchdog started (pid $$)"
while ! all_done; do
    if ! probe; then
        log "tunnel down/wedged; sleeping 600s"
        sleep 600
        continue
    fi
    log "tunnel UP"
    run_phase clay 600 clay || true
    probe || continue
    run_phase shec 600 shec || true
    probe || continue
    # crush: cautious small batch first, then the full 1M-PG headline;
    # a wedge here loses nothing already captured
    run_phase crush 900 crush_small CEPH_TPU_BENCH_CRUSH_PGS=100000 || true
    probe || continue
    run_phase crush 1200 crush_full || true
done
log "watchdog: all phases captured; exiting"
