"""Capability probe: which in-kernel lookup formulations does this
Mosaic/libtpu stack legalize, and how fast are they?

Variants:
  take    — jnp.take(table_1d, idx) inside the kernel (dynamic gather)
  takeax  — jnp.take_along_axis on a 2D broadcast table
  onehot  — current bf16 one-hot matmul against a [256,16] table
  onehot8 — int8 one-hot, s8xs8->s32 matmul
"""
import sys
import time
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

variants = sys.argv[1:] or ["take", "takeax", "onehot", "onehot8"]

B, S = 1 << 18, 128
TILE = 32
rng = np.random.default_rng(2)
idx_np = rng.integers(0, 1 << 16, (B, S), dtype=np.int32)
idx = jnp.asarray(idx_np)
tbl16_np = rng.integers(-(1 << 31), 1 << 31, (1 << 16,), dtype=np.int32)
tbl16 = jnp.asarray(tbl16_np)
tbl256_np = rng.integers(0, 256, (256, 16), dtype=np.int32)


def run(name, kernel, inputs, out_shape, want=None):
    try:
        f = pl.pallas_call(
            kernel,
            grid=(B // TILE,),
            in_specs=[
                pl.BlockSpec((TILE, S), lambda i: (i, 0)),
            ] + [pl.BlockSpec(t.shape, lambda i: tuple([0] * t.ndim))
                 for t in inputs[1:]],
            out_specs=pl.BlockSpec((TILE, S), lambda i: (i, 0)),
            out_shape=jax.ShapeDtypeStruct((B, S), out_shape),
        )
        o = f(*inputs)
        jax.block_until_ready(o)
        if want is not None:
            ok = bool((np.asarray(o) == want).all())
        else:
            ok = "?"
        ts = []
        for _ in range(6):
            t0 = time.perf_counter()
            o = f(*inputs)
            jax.block_until_ready(o)
            ts.append(time.perf_counter() - t0)
        best = min(ts[1:])
        print(f"{name:8s} OK exact={ok} best={best*1e3:.2f}ms "
              f"lookups/s={B*S/best/1e9:.2f}G", flush=True)
    except Exception as e:
        head = str(e).split("\n")[0][:200]
        print(f"{name:8s} FAIL {type(e).__name__}: {head}", flush=True)


want16 = tbl16_np[idx_np]

if "take" in variants:
    def k_take(idx_ref, tbl_ref, out_ref):
        out_ref[:] = jnp.take(tbl_ref[:], idx_ref[:], axis=0)
    run("take", k_take, (idx, tbl16), jnp.int32, want16)

if "takeax" in variants:
    def k_takeax(idx_ref, tbl_ref, out_ref):
        t = tbl_ref[:]  # [65536] -> broadcast rows? use take_along_axis
        out_ref[:] = jnp.take_along_axis(
            jnp.broadcast_to(t[None, :], (idx_ref.shape[0], t.shape[0])),
            idx_ref[:], axis=1,
        )
    run("takeax", k_takeax, (idx, tbl16), jnp.int32, want16)

idx8_np = idx_np & 0xFF
idx8 = jnp.asarray(idx8_np)
want8 = tbl256_np[idx8_np].sum(-1).astype(np.int32)

if "onehot" in variants:
    tblb = jnp.asarray(tbl256_np, jnp.bfloat16)
    def k_oh(idx_ref, tbl_ref, out_ref):
        oh = (idx_ref[:][:, :, None]
              == jax.lax.broadcasted_iota(jnp.int32, (1, 1, 256), 2)
              ).astype(jnp.bfloat16)
        rows = jax.lax.dot_general(
            oh, tbl_ref[:], dimension_numbers=(((2,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        out_ref[:] = rows.sum(-1).astype(jnp.int32)
    run("onehot", k_oh, (idx8, tblb), jnp.int32, want8)

if "onehot8" in variants:
    tbl8 = jnp.asarray(tbl256_np, jnp.int8)
    def k_oh8(idx_ref, tbl_ref, out_ref):
        oh = (idx_ref[:][:, :, None]
              == jax.lax.broadcasted_iota(jnp.int32, (1, 1, 256), 2)
              ).astype(jnp.int8)
        rows = jax.lax.dot_general(
            oh, tbl_ref[:], dimension_numbers=(((2,), (0,)), ((), ())),
            preferred_element_type=jnp.int32)
        out_ref[:] = rows.sum(-1)
    run("onehot8", k_oh8, (idx8, tbl8), jnp.int32, want8)
