"""Probe: flat-stream one-hot ln lookup — elements as a 1D stream in
[R, 1] blocks, one-hot [R, 256] 2D (vreg-natural: idx along sublanes,
table axis along lanes) vs the production kernel's 3D [32,128,256].

Also probes the FULL fused pipeline in flat layout: hash + ln, to see
end-to-end draws/s at various R.
"""
import sys
import time
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

sys.path.insert(0, "/root/repo")
from ceph_tpu.crush.hash import crush_hash32_3
from ceph_tpu.crush.ln_compute import (
    TBL1_BYTES, TBL2_BYTES, crush_ln_limbs, recombine_limbs,
)
from ceph_tpu.crush.ln_table import CRUSH_LN_TABLE

B, S = 1 << 18, 128
N = B * S  # 33.5M elements
rng = np.random.default_rng(3)
u_np = rng.integers(0, 1 << 16, N, dtype=np.int32)
u = jnp.asarray(u_np)

Rs = [int(a) for a in sys.argv[1:]] or [2048, 8192]

t1 = jnp.asarray(TBL1_BYTES, jnp.bfloat16)
t2 = jnp.asarray(TBL2_BYTES, jnp.bfloat16)


def _onehot_flat(idx, tbl_bf16):
    # idx [R] -> one-hot [R, K] -> [R, ncols] f32
    K = tbl_bf16.shape[0]
    oh = (
        idx[:, None] == jax.lax.broadcasted_iota(jnp.int32, (1, K), 1)
    ).astype(jnp.bfloat16)
    return jax.lax.dot_general(
        oh, tbl_bf16, dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )


def ln_kernel(u_ref, t1_ref, t2_ref, hi_ref, lo_ref):
    t1 = t1_ref[:]
    t2 = t2_ref[:]
    uu = u_ref[:, 0]

    def look1(i):
        rows = _onehot_flat(i, t1)
        return (
            recombine_limbs(rows, 0, 3, jnp),
            recombine_limbs(rows, 3, 2, jnp),
            recombine_limbs(rows, 5, 2, jnp),
            recombine_limbs(rows, 7, 4, jnp),
            recombine_limbs(rows, 11, 3, jnp),
        )

    def look2(i):
        rows = _onehot_flat(i, t2)
        return (
            recombine_limbs(rows, 0, 4, jnp),
            recombine_limbs(rows, 4, 3, jnp),
        )

    hi, lo = crush_ln_limbs(uu, jnp, look1, look2)
    hi_ref[:, 0] = hi
    lo_ref[:, 0] = lo


want_ln = CRUSH_LN_TABLE[u_np]

for R in Rs:
    try:
        f = pl.pallas_call(
            ln_kernel,
            grid=(N // R,),
            in_specs=[
                pl.BlockSpec((R, 1), lambda i: (i, 0)),
                pl.BlockSpec(TBL1_BYTES.shape, lambda i: (0, 0)),
                pl.BlockSpec(TBL2_BYTES.shape, lambda i: (0, 0)),
            ],
            out_specs=[
                pl.BlockSpec((R, 1), lambda i: (i, 0)),
                pl.BlockSpec((R, 1), lambda i: (i, 0)),
            ],
            out_shape=[
                jax.ShapeDtypeStruct((N, 1), jnp.int32),
                jax.ShapeDtypeStruct((N, 1), jnp.int32),
            ],
        )
        u2 = u.reshape(N, 1)
        hi, lo = f(u2, t1, t2)
        jax.block_until_ready((hi, lo))
        got = (np.asarray(hi)[:, 0].astype(np.int64) << 24) | np.asarray(lo)[
            :, 0
        ].astype(np.int64)
        ok = bool((got == want_ln).all())
        ts = []
        for _ in range(6):
            t0 = time.perf_counter()
            o = f(u2, t1, t2)
            jax.block_until_ready(o)
            ts.append(time.perf_counter() - t0)
        best = min(ts[1:])
        print(f"flat R={R:6d} exact={ok} best={best*1e3:.2f}ms "
              f"lookups/s={N/best/1e9:.2f}G", flush=True)
    except Exception as e:
        head = str(e).split("\n")[0][:250]
        print(f"flat R={R:6d} FAIL {type(e).__name__}: {head}", flush=True)
