"""One-off silicon profile of the batched CRUSH mapper's pieces.

Usage: python perf_runs/profile_crush.py <piece>
Pieces: score32 score64 score128 score256 choose full gather_na
Each run is a separate process so a Mosaic failure can't poison the rest.
"""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def timeit(fn, n=5):
    fn()  # warm/compile
    t0 = time.perf_counter()
    for _ in range(n):
        r = fn()
    np.asarray(r)  # sync
    return (time.perf_counter() - t0) / n


def main():
    piece = sys.argv[1]
    import jax
    import jax.numpy as jnp

    print("backend:", jax.default_backend(), flush=True)
    B, S = 1 << 18, 128
    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.integers(0, 1 << 31, B, dtype=np.int64).astype(np.int32))
    r = jnp.asarray(np.zeros(B, np.int32))
    items = jnp.asarray(
        np.broadcast_to(np.arange(S, dtype=np.int32), (B, S)).copy()
    )

    if piece.startswith("score"):
        tile = int(piece[5:])
        from ceph_tpu.ops.pallas_crush import straw2_scores_pallas

        def f():
            hi, lo = straw2_scores_pallas(x, r, items, tile=tile)
            return lo

        dt = timeit(f)
        print(f"score launch tile={tile}: {dt*1e3:.2f} ms "
              f"({B/dt/1e6:.1f} M lane-draws/s over S={S})", flush=True)

    elif piece == "choose":
        from ceph_tpu.crush import CompiledCrushMap, build_hierarchical_map
        from ceph_tpu.crush.batched import straw2_choose_b, ln_scores_pallas
        from ceph_tpu.crush.mapper import enable_x64

        cmap = build_hierarchical_map(128, 8)
        cm = CompiledCrushMap(cmap)
        with enable_x64():
            bidx = jnp.zeros(B, jnp.int32)  # root bucket row

            @jax.jit
            def g(bidx, x, r):
                return straw2_choose_b(
                    cm, ln_scores_pallas, bidx, x, r, None,
                    jnp.zeros(B, jnp.int32),
                )

            xx = x
            dt = timeit(lambda: g(bidx, xx, r))
        print(f"straw2_choose_b (score+div+argmax): {dt*1e3:.2f} ms", flush=True)

    elif piece == "div":
        # isolate the int64 draw division + argmax at [B, S]
        from ceph_tpu.crush.mapper import enable_x64
        with enable_x64():
            ln = jnp.asarray(
                rng.integers(-(1 << 48), 0, (B, S)), jnp.int64
            )
            w = jnp.asarray(
                rng.integers(1, 1 << 20, (B, S)), jnp.int64
            )

            @jax.jit
            def g(ln, w):
                q = jnp.abs(ln) // jnp.abs(w)
                d = jnp.where((ln < 0) != (w < 0), -q, q)
                return jnp.argmax(d, axis=1)

            dt = timeit(lambda: g(ln, w))
        print(f"i64 div+argmax [B,S]: {dt*1e3:.2f} ms", flush=True)

    elif piece == "full":
        from ceph_tpu.crush import (
            CompiledCrushMap, build_hierarchical_map, crush_do_rule_batch,
        )

        cmap = build_hierarchical_map(128, 8)
        cm = CompiledCrushMap(cmap)
        weights = np.full(1024, 0x10000, dtype=np.uint32)
        xs = np.arange(B, dtype=np.int64)
        np.asarray(crush_do_rule_batch(cm, 0, xs[:1024], 3, weights))
        t0 = time.perf_counter()
        out = np.asarray(crush_do_rule_batch(cm, 0, xs, 3, weights))
        dt = time.perf_counter() - t0
        print(f"full rule chunk B={B}: {dt*1e3:.1f} ms "
              f"({B/dt:.0f} maps/s)", flush=True)

    else:
        print("unknown piece", piece)
        sys.exit(2)


if __name__ == "__main__":
    main()
