"""Verify straw2 Pallas kernel output at a given tile vs the XLA gather
path, on device, and retime with a per-launch block."""
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, "/root/repo")
from ceph_tpu.ops.pallas_crush import straw2_scores_pallas
from ceph_tpu.crush.ln_table import CRUSH_LN_TABLE
from ceph_tpu.crush.hash import crush_hash32_3

tiles = [int(t) for t in sys.argv[1:]] or [32, 64]

B, S = 1 << 18, 128
rng = np.random.default_rng(1)
x = jnp.asarray(rng.integers(0, 1 << 31, B, dtype=np.int32))
r = jnp.asarray(rng.integers(0, 4, B, dtype=np.int32))
items = jnp.asarray(rng.integers(0, 1024, (B, S), dtype=np.int32))

# ground truth on host (numpy gather)
xn = np.asarray(x).astype(np.uint32)
rn = np.asarray(r).astype(np.uint32)
inn = np.asarray(items).astype(np.uint32)


def hash3_np(a, b, c):
    import ceph_tpu.crush.hash as H
    return np.asarray(
        crush_hash32_3(jnp.asarray(a[:, None]), jnp.asarray(inn),
                       jnp.asarray(c[:, None]))
    )


u = hash3_np(xn, inn, rn) & 0xFFFF
want = CRUSH_LN_TABLE[u]

for tile in tiles:
    hi, lo = straw2_scores_pallas(x, r, items, tile=tile)
    hi, lo = np.asarray(hi), np.asarray(lo)
    got = (hi.astype(np.int64) << 24) | lo.astype(np.int64)
    ok = (got == want).all()
    nbad = int((got != want).sum())
    print(f"tile={tile:4d} exact={ok} mismatches={nbad}/{got.size}", flush=True)
    # careful retime: block after EVERY launch
    ts = []
    for i in range(8):
        t0 = time.perf_counter()
        o = straw2_scores_pallas(x, r + i, items, tile=tile)
        jax.block_until_ready(o)
        ts.append(time.perf_counter() - t0)
    best = min(ts[2:])
    print(
        f"tile={tile:4d} per-launch best={best*1e3:.2f}ms "
        f"draws/s={B*S/best/1e9:.2f}G all={[round(t*1e3,1) for t in ts]}",
        flush=True,
    )
