"""Probe straw2 Pallas kernel tiles on silicon: compile + time each tile.

Usage: python perf_runs/probe_tiles.py [tile ...]
Prints one line per tile: ok/fail, compile time, steady-state time, draws/s.
"""
import os
import sys
import time
import traceback

tiles = [int(t) for t in sys.argv[1:]] or [32, 64, 128, 256]

import jax
import jax.numpy as jnp
import numpy as np

print("backend:", jax.default_backend(), jax.devices(), flush=True)

from ceph_tpu.ops.pallas_crush import straw2_scores_pallas, TileShapeError

B, S = 1 << 18, 128
rng = np.random.default_rng(0)
x = jnp.asarray(rng.integers(0, 1 << 31, B, dtype=np.int32))
r = jnp.asarray(rng.integers(0, 4, B, dtype=np.int32))
items = jnp.asarray(rng.integers(0, 1024, (B, S), dtype=np.int32))

for tile in tiles:
    try:
        t0 = time.perf_counter()
        hi, lo = straw2_scores_pallas(x, r, items, tile=tile)
        jax.block_until_ready((hi, lo))
        t_compile = time.perf_counter() - t0
        # steady state: chain a few launches, block at the end
        n = 5
        t0 = time.perf_counter()
        for i in range(n):
            hi, lo = straw2_scores_pallas(x, r + i, items, tile=tile)
        jax.block_until_ready((hi, lo))
        dt = (time.perf_counter() - t0) / n
        print(
            f"tile={tile:4d} OK compile+first={t_compile:.2f}s "
            f"steady={dt*1e3:.1f}ms draws/s={B*S/dt/1e9:.2f}G",
            flush=True,
        )
    except Exception as e:
        msg = str(e).split("\n")
        head = msg[0][:300]
        print(f"tile={tile:4d} FAIL {type(e).__name__}: {head}", flush=True)
        # full traceback to a side file for the first failure
        with open(f"/root/repo/perf_runs/tile_{tile}_fail.txt", "w") as f:
            f.write(traceback.format_exc())
