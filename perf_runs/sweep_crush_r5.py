#!/usr/bin/env python
"""Round-5 silicon sweep: straw2 score-kernel shapes + end-to-end CRUSH
remap with the limb engine.

Each configuration runs in a SUBPROCESS with a hard timeout so one bad
Mosaic shape cannot wedge the whole sweep (the r2/r4 lesson); results
append to perf_runs/sweep_crush_r5.jsonl as one JSON line each.

Usage: python perf_runs/sweep_crush_r5.py            # run the sweep
       python perf_runs/sweep_crush_r5.py --one CFG  # child mode
"""
import json
import os
import subprocess
import sys
import time

OUT = "/root/repo/perf_runs/sweep_crush_r5.jsonl"
os.chdir("/root/repo")

# (name, env overrides) — score-kernel shape sweeps at a fixed bench,
# then the full 256k-PG remap per engine.  Loop-slab tiles beyond 2048
# test whether wide tiles pay off now that compile cost is constant.
CONFIGS = [
    ("score_loop_t512", {"CEPH_TPU_STRAW2_LOOP": "1",
                         "CEPH_TPU_STRAW2_TILE": "512"}),
    ("score_loop_t2048", {"CEPH_TPU_STRAW2_LOOP": "1",
                          "CEPH_TPU_STRAW2_TILE": "2048"}),
    ("score_loop_t8192", {"CEPH_TPU_STRAW2_LOOP": "1",
                          "CEPH_TPU_STRAW2_TILE": "8192"}),
    ("score_static_t256", {"CEPH_TPU_STRAW2_LOOP": "0",
                           "CEPH_TPU_STRAW2_TILE": "256"}),
    ("remap_limb_loop", {"CEPH_TPU_CRUSH_ENGINE": "limb",
                         "CEPH_TPU_STRAW2_LOOP": "1",
                         "CEPH_TPU_BENCH_CRUSH_PGS": "262144"}),
    ("remap_limb_static", {"CEPH_TPU_CRUSH_ENGINE": "limb",
                           "CEPH_TPU_STRAW2_LOOP": "0",
                           "CEPH_TPU_STRAW2_TILE": "256",
                           "CEPH_TPU_BENCH_CRUSH_PGS": "262144"}),
    ("remap_i64_gather", {"CEPH_TPU_CRUSH_ENGINE": "i64",
                          "CEPH_TPU_BENCH_CRUSH_PGS": "262144"}),
]


def child(name: str) -> None:
    env = dict(CONFIGS)[name]
    os.environ.update(env)
    import numpy as np

    if name.startswith("score_"):
        import jax.numpy as jnp

        from ceph_tpu.ops import pallas_crush
        from ceph_tpu.ops.pallas_crush import straw2_scores_pallas

        B, S = 1 << 18, 128
        rng = np.random.default_rng(1)
        x = jnp.asarray(rng.integers(0, 1 << 31, B).astype(np.int32))
        r = jnp.asarray(np.zeros(B, np.int32))
        items = jnp.asarray(
            np.broadcast_to(np.arange(S, dtype=np.int32), (B, S)).copy())
        tile = pallas_crush.DEFAULT_TILE
        loop = pallas_crush.LOOP_SLABS
        t0 = time.perf_counter()
        np.asarray(straw2_scores_pallas(x, r, items, tile=tile,
                                        loop_slabs=loop)[1])
        compile_s = time.perf_counter() - t0
        t0 = time.perf_counter()
        n = 5
        for _ in range(n):
            out = straw2_scores_pallas(x, r, items, tile=tile,
                                       loop_slabs=loop)[1]
        np.asarray(out)
        dt = (time.perf_counter() - t0) / n
        print(json.dumps({
            "cfg": name, "tile": tile, "loop": loop,
            "compile_s": round(compile_s, 2),
            "launch_ms": round(dt * 1e3, 2),
            "mdraws_per_s": round(B * S / dt / 1e6, 1),
        }))
    else:
        sys.argv = ["bench.py", "--phase", "crush"]
        import runpy

        t0 = time.perf_counter()
        runpy.run_path("bench.py", run_name="__main__")
        # phase prints its own JSON; add wall time on stderr
        print(f"# wall {time.perf_counter() - t0:.1f}s", file=sys.stderr)


def main() -> None:
    for name, _env in CONFIGS:
        marker = f"perf_runs/sweep_{name}.done"
        if os.path.exists(marker):
            continue
        print(f"=== {name}", flush=True)
        try:
            r = subprocess.run(
                [sys.executable, os.path.abspath(__file__), "--one", name],
                timeout=900, capture_output=True, text=True,
            )
            line = (r.stdout.strip().splitlines() or ["{}"])[-1]
            rec = {"cfg": name, "rc": r.returncode}
            try:
                rec.update(json.loads(line))
            except ValueError:
                rec["tail"] = " | ".join(r.stderr.splitlines()[-2:])
        except subprocess.TimeoutExpired:
            rec = {"cfg": name, "rc": -1, "error": "timeout 900s"}
        with open(OUT, "a") as f:
            f.write(json.dumps(rec) + "\n")
        print(json.dumps(rec), flush=True)
        if rec.get("rc") != 0:
            # probe the tunnel before continuing: a wedge poisons the rest
            try:
                p = subprocess.run(
                    [sys.executable, "-c",
                     "import jax; assert jax.devices()[0].platform != 'cpu'"],
                    timeout=90)
                if p.returncode != 0:
                    print("tunnel lost; stopping sweep", flush=True)
                    return
            except subprocess.TimeoutExpired:
                print("tunnel wedged; stopping sweep", flush=True)
                return
        open(marker, "w").close()


if __name__ == "__main__":
    if len(sys.argv) > 2 and sys.argv[1] == "--one":
        child(sys.argv[2])
    else:
        main()
