#!/usr/bin/env bash
# qa/ci_gate.sh — both analyzers outside pytest, SARIF artifacts for CI.
#
#   qa/ci_gate.sh [BASE_REF] [SEED]
#
# 1. cephlint --diff BASE_REF  (default origin/main, falling back to
#    HEAD~1): whole-package static analysis, report narrowed to the
#    files changed since BASE_REF — then a timed FULL default-check run
#    as the scan-cost regression guard: the whole-package scan must
#    stay <=10s (the fast tier-1 budget), cost printed into
#    cephlint-full.txt next to the SARIF artifact.
# 2. cephrace --seed SEED (default 1): the short seeded thrash scenario
#    under the dynamic detector.
# 3. traffic smoke (ceph_tpu/bench/traffic.py): CPU backend, 2 clients,
#    ~5 s — fails when the batched/per-op encode throughput ratio drops
#    below 1.0 (the write-batcher regression gate); JSON lands next to
#    the SARIF artifacts.
# 4. trace smoke (--trace-smoke): the 2-client CLUSTER traffic run,
#    untraced vs sampling=1.0 — fails when the traced run produces no
#    connected trace tree (client submit -> replica commit), when the
#    per-stage breakdown misses one of admission/queue/encode/subop/
#    commit, or when tracing-enabled overhead exceeds 10% of the
#    untraced smoke.  Artifacts: traffic_trace.json (bench JSON) and
#    trace_perfetto.json (open in ui.perfetto.dev).
# 5. backend health smoke (ceph_tpu/qa/health_smoke.py): simulated
#    wedge must raise TPU_BACKEND_DEGRADED + KERNEL_FALLBACK_LATCHED in
#    `health detail` and on the prometheus exporter, dump_kernel_
#    telemetry must answer with its full schema, and recovery must
#    clear both checks.
# 6. wedged bench (CEPH_TPU_BENCH_FORCE_WEDGED): bench.py must exit
#    rc=3 carrying last_known_silicon + sentinel state + per-phase
#    stale captures instead of a null headline.
# 7. accounting smoke (ceph_tpu/qa/accounting_smoke.py): a 2-client
#    cluster must render per-(client,pool) labeled series on the
#    prometheus exporter with per-client bytes summing to the aggregate
#    within tolerance, `perf history` must answer from the mon, and a
#    failpoint-delayed op must surface in dump_historic_slow_ops with
#    per-stage attribution and a tail-promoted cross-entity trace
#    (trace_sampling_rate=0 — the head coin flip said no).
# 8. QoS smoke (ceph_tpu/qa/qos_smoke.py): the bully scenario (1 heavy
#    streamer vs N small Poisson writers) on a real LocalCluster,
#    controller off vs on — fails when worst-victim satisfaction
#    (achieved/offered) drops below the 0.5 starvation floor,
#    aggregate GiB/s regresses >10%, victim p99 improves <1.5x, or
#    the controller never pushed.
# 9. recovery smoke (ceph_tpu/qa/recovery_smoke.py): kill/revive an OSD
#    under 2-client traffic — fails unless PG_DEGRADED raises and
#    clears, progress events complete at 1.0, degraded objects drain to
#    0, ceph_recovery_*{pool,codec} series render on the exporter with
#    a plausible repair ratio (~k for RS), and the tail-promoted
#    recovery trace tree is connected cross-entity at sampling=0.
# 10. device pool smoke (ceph_tpu/qa/device_pool_smoke.py): the batcher
#    traffic run with ec_device_pool=false (control) vs true — fails
#    unless host-copy bytes per fused flush drop >= 50%, aggregate
#    throughput does not regress (>= 0.85x control, CPU noise margin),
#    control flushes are sync points while pooled flushes are async
#    with their commit sync on the encode_wait record, and parity
#    buffers recycle through the pool.
# 11. placement smoke (ceph_tpu/qa/placement_smoke.py): mark an OSD out
#    under a small live cluster — the placement module's remap forecast
#    (batched-CRUSH epoch diff, `placement diff`) must match the
#    observed acting-set churn within 10%, ceph_placement_*/ceph_remap_*
#    /ceph_balancer_* series must render on the exporter, a balancer
#    run against a stacked imbalance must commit moves and improve the
#    exported score, and PG_IMBALANCE must raise then clear.
# 12. topology smoke (ceph_tpu/qa/topology_smoke.py): the same
#    production encode must be bit-identical under a cpu-1, mesh-8, and
#    sentinel-degraded (two devices pinned failed) DevicePolicy, the
#    degraded mesh must shrink to the survivors, and the device-pool
#    budget must shrink with it.  Step 1's cephlint run includes the
#    CL9/CL10 device-topology & sharding checks that pin the policy
#    refactor behind this smoke, and the CL11/CL12 determinism +
#    observability-drift checks (no extra step: the run uses the
#    default check set, so new checks ride it automatically).
#
# Analyzers emit SARIF 2.1.0 into qa/_sarif/ (github code-scanning uploads
# resolve URIs against the repo root, which is where this script runs
# from).  Exit is non-zero if EITHER gate reports active findings —
# the same exit contracts the pytest gates (tests/test_analyzer.py,
# tests/test_race.py) enforce.
set -u
cd "$(dirname "$0")/.."

BASE_REF="${1:-}"
SEED="${2:-1}"
OUT_DIR="qa/_sarif"
mkdir -p "$OUT_DIR"

if [ -z "$BASE_REF" ]; then
    if git rev-parse --verify -q origin/main >/dev/null; then
        BASE_REF=origin/main
    else
        BASE_REF=HEAD~1
    fi
fi

rc=0

echo "== cephlint (diff vs $BASE_REF) =="
python -m ceph_tpu.qa.analyzer --diff "$BASE_REF" --format=sarif \
    > "$OUT_DIR/cephlint.sarif"
lint_rc=$?
if [ $lint_rc -ge 2 ]; then
    # usage/parse error, not findings: the sarif on stdout is garbage —
    # drop it rather than hand CI an empty/invalid artifact
    rm -f "$OUT_DIR/cephlint.sarif"
    echo "cephlint: ERROR (exit $lint_rc):"
    python -m ceph_tpu.qa.analyzer --diff "$BASE_REF" || true
    rc=1
elif [ $lint_rc -eq 1 ]; then
    echo "cephlint: findings on changed files:"
    python -m ceph_tpu.qa.analyzer --diff "$BASE_REF" || true
    rc=1
else
    echo "cephlint: clean"
fi

echo "== cephlint scan-cost guard (full default-check run) =="
# the fast tier-1 class budgets the whole-package scan at 10s; a new
# check that blows the budget must fail HERE, not slowly eat tier-1
lint_t0=$(python -c 'import time; print(time.monotonic())')
python -m ceph_tpu.qa.analyzer ceph_tpu > "$OUT_DIR/cephlint-full.txt" \
    || true
lint_cost=$(python -c "import time; print(round(time.monotonic() - $lint_t0, 2))")
echo "cephlint full-scan cost: ${lint_cost}s (budget 10s)" \
    | tee -a "$OUT_DIR/cephlint-full.txt"
if python -c "import sys; sys.exit(0 if float('$lint_cost') <= 10.0 else 1)"; then
    echo "cephlint scan cost: OK"
else
    echo "cephlint scan cost: ${lint_cost}s EXCEEDS the 10s tier-1 budget"
    rc=1
fi

echo "== cephrace (seeded thrash, seed=$SEED) =="
JAX_PLATFORMS=cpu python -m ceph_tpu.qa.race --seed "$SEED" \
    --scenario thrash --events 4 --format=sarif \
    > "$OUT_DIR/cephrace.sarif"
race_rc=$?
if [ $race_rc -ge 2 ]; then
    rm -f "$OUT_DIR/cephrace.sarif"
    echo "cephrace: ERROR (exit $race_rc) — scenario crashed or baseline unreadable"
    rc=1
elif [ $race_rc -eq 1 ]; then
    echo "cephrace: findings:"
    JAX_PLATFORMS=cpu python -m ceph_tpu.qa.race --seed "$SEED" \
        --scenario thrash --events 4 || true
    rc=1
else
    echo "cephrace: clean"
fi

echo "== traffic smoke (batched vs per-op encode) =="
CEPH_TPU_BENCH_FORCE_CPU=1 python -m ceph_tpu.bench.traffic \
    --cpu --clients 2 --seconds 2 --json --smoke \
    > "$OUT_DIR/traffic.json"
traffic_rc=$?
if [ $traffic_rc -eq 0 ]; then
    echo "traffic smoke: ok"
elif python -c "import json,sys; json.load(open('$OUT_DIR/traffic.json'))" \
        2>/dev/null; then
    # the scenario ran and produced a result: rc!=0 means the ratio gate
    echo "traffic smoke: FAILED (batched/per-op ratio < 1.0):"
    cat "$OUT_DIR/traffic.json"
    rc=1
else
    # crashed before producing JSON: an error, not a perf regression
    rm -f "$OUT_DIR/traffic.json"
    echo "traffic smoke: ERROR (exit $traffic_rc) — scenario crashed"
    rc=1
fi

echo "== trace smoke (cluster traffic, untraced vs sampling=1.0) =="
CEPH_TPU_BENCH_FORCE_CPU=1 JAX_PLATFORMS=cpu python -m ceph_tpu.bench.traffic \
    --cpu --trace-smoke --clients 2 --seconds 2 --json \
    --trace-out "$OUT_DIR/trace_perfetto.json" \
    > "$OUT_DIR/traffic_trace.json"
trace_rc=$?
if [ $trace_rc -eq 0 ]; then
    echo "trace smoke: ok"
elif python -c "import json,sys; json.load(open('$OUT_DIR/traffic_trace.json'))" \
        2>/dev/null; then
    # ran to completion: rc!=0 means a gate fired (disconnected tree,
    # missing stage, or >10% tracing overhead) — details in the JSON
    echo "trace smoke: FAILED:"
    python -c "import json; [print(' -', p) for p in json.load(open('$OUT_DIR/traffic_trace.json'))['problems']]" || true
    rc=1
else
    rm -f "$OUT_DIR/traffic_trace.json" "$OUT_DIR/trace_perfetto.json"
    echo "trace smoke: ERROR (exit $trace_rc) — scenario crashed"
    rc=1
fi

echo "== backend health smoke (simulated wedge -> raise -> clear) =="
# forces a wedge through the sentinel's env probe override + a latched
# codec fallback, asserts TPU_BACKEND_DEGRADED / KERNEL_FALLBACK_LATCHED
# raise in `health detail` and on the prometheus exporter, smoke-checks
# the dump_kernel_telemetry JSON schema, then recovers and asserts the
# checks clear (ceph_tpu/qa/health_smoke.py; docs/observability.md)
python -m ceph_tpu.qa.health_smoke > "$OUT_DIR/health_smoke.json"
health_rc=$?
if [ $health_rc -eq 0 ]; then
    echo "health smoke: ok"
elif python -c "import json; json.load(open('$OUT_DIR/health_smoke.json'))" \
        2>/dev/null; then
    echo "health smoke: FAILED:"
    python -c "import json; [print(' -', p) for p in json.load(open('$OUT_DIR/health_smoke.json'))['problems']]" || true
    rc=1
else
    rm -f "$OUT_DIR/health_smoke.json"
    echo "health smoke: ERROR (exit $health_rc) — scenario crashed"
    rc=1
fi

echo "== wedged bench degradation (rc discrimination) =="
# a forced-wedge bench must exit rc=3 carrying last_known_silicon (+
# sentinel state + per-phase stale captures) — never a null headline
CEPH_TPU_BENCH_FORCE_WEDGED=1 CEPH_TPU_BENCH_SKIP_CPU=1 \
    python bench.py > "$OUT_DIR/bench_wedged.json" 2>/dev/null
bench_rc=$?
if [ $bench_rc -ne 3 ]; then
    echo "wedged bench: FAILED — rc=$bench_rc, want 3 (degraded-with-stale-data)"
    rc=1
elif python - "$OUT_DIR/bench_wedged.json" <<'EOF'
import json, sys
doc = json.load(open(sys.argv[1]))
extra = doc.get("extra") or {}
assert doc.get("value") is not None, "null headline on wedge"
assert extra.get("value_is_last_known_silicon") is True, "stale flag missing"
assert (extra.get("sentinel") or {}).get("state") == "degraded", "no sentinel state"
assert extra.get("last_known_silicon_phases"), "no per-phase stale captures"
EOF
then
    echo "wedged bench: ok (rc=3, last_known_silicon carried)"
else
    echo "wedged bench: FAILED — degradation contract violated:"
    cat "$OUT_DIR/bench_wedged.json"
    rc=1
fi

echo "== accounting smoke (labeled series + slow-op forensics) =="
# per-client labeled series on the exporter, bytes conservation, `perf
# history` through the mon, and a failpoint-delayed op surfacing in
# dump_historic_slow_ops with a tail-promoted cross-entity trace
# (ceph_tpu/qa/accounting_smoke.py; docs/observability.md)
python -m ceph_tpu.qa.accounting_smoke > "$OUT_DIR/accounting_smoke.json"
acct_rc=$?
if [ $acct_rc -eq 0 ]; then
    echo "accounting smoke: ok"
elif python -c "import json; json.load(open('$OUT_DIR/accounting_smoke.json'))" \
        2>/dev/null; then
    echo "accounting smoke: FAILED:"
    python -c "import json; [print(' -', p) for p in json.load(open('$OUT_DIR/accounting_smoke.json'))['problems']]" || true
    rc=1
else
    rm -f "$OUT_DIR/accounting_smoke.json"
    echo "accounting smoke: ERROR (exit $acct_rc) — scenario crashed"
    rc=1
fi

echo "== QoS smoke (bully scenario, controller off vs on) =="
# per-client mClock classes + batcher share + live controller must
# improve victim fairness and p99 without costing >10% aggregate
# (ceph_tpu/qa/qos_smoke.py; docs/qos.md)
python -m ceph_tpu.qa.qos_smoke > "$OUT_DIR/qos_smoke.json"
qos_rc=$?
if [ $qos_rc -eq 0 ]; then
    echo "qos smoke: ok"
elif python -c "import json; json.load(open('$OUT_DIR/qos_smoke.json'))" \
        2>/dev/null; then
    echo "qos smoke: FAILED:"
    python -c "import json; [print(' -', p) for p in json.load(open('$OUT_DIR/qos_smoke.json'))['problems']]" || true
    rc=1
else
    rm -f "$OUT_DIR/qos_smoke.json"
    echo "qos smoke: ERROR (exit $qos_rc) — scenario crashed"
    rc=1
fi

echo "== recovery smoke (kill/revive observability) =="
# PG_DEGRADED/progress raise and clear around a kill/revive under
# 2-client traffic, ceph_recovery_* renders with a plausible repair
# ratio, and the recovery trace tree assembles cross-entity at
# sampling=0 (ceph_tpu/qa/recovery_smoke.py; docs/observability.md)
python -m ceph_tpu.qa.recovery_smoke > "$OUT_DIR/recovery_smoke.json"
heal_rc=$?
if [ $heal_rc -eq 0 ]; then
    echo "recovery smoke: ok"
elif python -c "import json; json.load(open('$OUT_DIR/recovery_smoke.json'))" \
        2>/dev/null; then
    echo "recovery smoke: FAILED:"
    python -c "import json; [print(' -', p) for p in json.load(open('$OUT_DIR/recovery_smoke.json'))['problems']]" || true
    rc=1
else
    rm -f "$OUT_DIR/recovery_smoke.json"
    echo "recovery smoke: ERROR (exit $heal_rc) — scenario crashed"
    rc=1
fi

echo "== device pool smoke (control vs pooled async encode) =="
# host-copy bytes per fused flush must drop >= 50% with the pool on,
# throughput must not regress, and the flush/commit sync split must be
# honest (ceph_tpu/qa/device_pool_smoke.py; docs/write_path.md)
CEPH_TPU_BENCH_FORCE_CPU=1 JAX_PLATFORMS=cpu \
    python -m ceph_tpu.qa.device_pool_smoke > "$OUT_DIR/device_pool_smoke.json"
dpool_rc=$?
if [ $dpool_rc -eq 0 ]; then
    echo "device pool smoke: ok"
elif python -c "import json; json.load(open('$OUT_DIR/device_pool_smoke.json'))" \
        2>/dev/null; then
    echo "device pool smoke: FAILED:"
    python -c "import json; [print(' -', p) for p in json.load(open('$OUT_DIR/device_pool_smoke.json'))['problems']]" || true
    rc=1
else
    rm -f "$OUT_DIR/device_pool_smoke.json"
    echo "device pool smoke: ERROR (exit $dpool_rc) — scenario crashed"
    rc=1
fi

echo "== placement smoke (remap forecast + balancer scoring) =="
# forecast-vs-observed churn on an osd-out, balancer score improvement
# against a stacked imbalance, PG_IMBALANCE raise/clear, and the
# ceph_placement_*/ceph_remap_*/ceph_balancer_* series on the exporter
# (ceph_tpu/qa/placement_smoke.py; docs/observability.md)
JAX_PLATFORMS=cpu python -m ceph_tpu.qa.placement_smoke \
    > "$OUT_DIR/placement_smoke.json"
place_rc=$?
if [ $place_rc -eq 0 ]; then
    echo "placement smoke: ok"
elif python -c "import json; json.load(open('$OUT_DIR/placement_smoke.json'))" \
        2>/dev/null; then
    echo "placement smoke: FAILED:"
    python -c "import json; [print(' -', p) for p in json.load(open('$OUT_DIR/placement_smoke.json'))['problems']]" || true
    rc=1
else
    rm -f "$OUT_DIR/placement_smoke.json"
    echo "placement smoke: ERROR (exit $place_rc) — scenario crashed"
    rc=1
fi

echo "== topology smoke (cpu-1 / mesh-N / degraded parity) =="
# the same sharded encode through three injected DevicePolicy variants
# must be bit-identical, the sentinel-degraded mesh must shrink instead
# of wedging, and the pool budget must track the survivors
# (ceph_tpu/qa/topology_smoke.py; docs/static_analysis.md CL9)
python -m ceph_tpu.qa.topology_smoke > "$OUT_DIR/topology_smoke.json"
topo_rc=$?
if [ $topo_rc -eq 0 ]; then
    echo "topology smoke: ok"
elif python -c "import json; json.load(open('$OUT_DIR/topology_smoke.json'))" \
        2>/dev/null; then
    echo "topology smoke: FAILED:"
    python -c "import json; [print(' -', p) for p in json.load(open('$OUT_DIR/topology_smoke.json'))['problems']]" || true
    rc=1
else
    rm -f "$OUT_DIR/topology_smoke.json"
    echo "topology smoke: ERROR (exit $topo_rc) — scenario crashed"
    rc=1
fi

echo "== read smoke (coalesced READ plane: speedup / GET / boot / degraded / ranged) =="
# batched >= 3x per-op at 32 CPU clients, GET-heavy cache promotion,
# boot-storm coalescing, degraded p99 under the CI bar, and the ranged
# degraded decode dispatching exactly k x window bytes into the kernel
# (ceph_tpu/qa/read_smoke.py; docs/read_path.md)
JAX_PLATFORMS=cpu python -m ceph_tpu.qa.read_smoke \
    > "$OUT_DIR/read_smoke.json"
read_rc=$?
if [ $read_rc -eq 0 ]; then
    echo "read smoke: ok"
elif python -c "import json; json.load(open('$OUT_DIR/read_smoke.json'))" \
        2>/dev/null; then
    echo "read smoke: FAILED:"
    python -c "import json; [print(' -', p) for p in json.load(open('$OUT_DIR/read_smoke.json'))['problems']]" || true
    rc=1
else
    rm -f "$OUT_DIR/read_smoke.json"
    echo "read smoke: ERROR (exit $read_rc) — scenario crashed"
    rc=1
fi

echo "== storm smoke (250-stub seeded failure storm + invariant gates) =="
# real mon+mgr over 250 stub OSDs: seeded kill/revive waves, rack
# netsplit, reweight churn under 2-tenant traffic; every invariant
# green (no acked-write loss, PGs clean, forecast-vs-observed <=10%,
# bounded oscillation, class conservation, health symmetry, replay
# determinism) plus a bare-map remap storm cross-check
# (ceph_tpu/qa/storm_smoke.py; docs/storm_sim.md)
JAX_PLATFORMS=cpu python -m ceph_tpu.qa.storm_smoke \
    > "$OUT_DIR/storm_smoke.json"
storm_rc=$?
if [ $storm_rc -eq 0 ]; then
    echo "storm smoke: ok"
elif python -c "import json; json.load(open('$OUT_DIR/storm_smoke.json'))" \
        2>/dev/null; then
    echo "storm smoke: FAILED:"
    python -c "import json; [print(' -', p) for p in json.load(open('$OUT_DIR/storm_smoke.json'))['problems']]" || true
    rc=1
else
    rm -f "$OUT_DIR/storm_smoke.json"
    echo "storm smoke: ERROR (exit $storm_rc) — scenario crashed"
    rc=1
fi

echo "Artifacts in $OUT_DIR/ (cephlint.sarif, cephrace.sarif, traffic.json, traffic_trace.json, trace_perfetto.json, health_smoke.json, bench_wedged.json, accounting_smoke.json, qos_smoke.json, recovery_smoke.json, device_pool_smoke.json, placement_smoke.json, topology_smoke.json, read_smoke.json, storm_smoke.json)"
exit $rc
