#!/usr/bin/env bash
# qa/ci_gate.sh — both analyzers outside pytest, SARIF artifacts for CI.
#
#   qa/ci_gate.sh [BASE_REF] [SEED]
#
# 1. cephlint --diff BASE_REF  (default origin/main, falling back to
#    HEAD~1): whole-package static analysis, report narrowed to the
#    files changed since BASE_REF.
# 2. cephrace --seed SEED (default 1): the short seeded thrash scenario
#    under the dynamic detector.
#
# Both emit SARIF 2.1.0 into qa/_sarif/ (github code-scanning uploads
# resolve URIs against the repo root, which is where this script runs
# from).  Exit is non-zero if EITHER gate reports active findings —
# the same exit contracts the pytest gates (tests/test_analyzer.py,
# tests/test_race.py) enforce.
set -u
cd "$(dirname "$0")/.."

BASE_REF="${1:-}"
SEED="${2:-1}"
OUT_DIR="qa/_sarif"
mkdir -p "$OUT_DIR"

if [ -z "$BASE_REF" ]; then
    if git rev-parse --verify -q origin/main >/dev/null; then
        BASE_REF=origin/main
    else
        BASE_REF=HEAD~1
    fi
fi

rc=0

echo "== cephlint (diff vs $BASE_REF) =="
python -m ceph_tpu.qa.analyzer --diff "$BASE_REF" --format=sarif \
    > "$OUT_DIR/cephlint.sarif"
lint_rc=$?
if [ $lint_rc -ge 2 ]; then
    # usage/parse error, not findings: the sarif on stdout is garbage —
    # drop it rather than hand CI an empty/invalid artifact
    rm -f "$OUT_DIR/cephlint.sarif"
    echo "cephlint: ERROR (exit $lint_rc):"
    python -m ceph_tpu.qa.analyzer --diff "$BASE_REF" || true
    rc=1
elif [ $lint_rc -eq 1 ]; then
    echo "cephlint: findings on changed files:"
    python -m ceph_tpu.qa.analyzer --diff "$BASE_REF" || true
    rc=1
else
    echo "cephlint: clean"
fi

echo "== cephrace (seeded thrash, seed=$SEED) =="
JAX_PLATFORMS=cpu python -m ceph_tpu.qa.race --seed "$SEED" \
    --scenario thrash --events 4 --format=sarif \
    > "$OUT_DIR/cephrace.sarif"
race_rc=$?
if [ $race_rc -ge 2 ]; then
    rm -f "$OUT_DIR/cephrace.sarif"
    echo "cephrace: ERROR (exit $race_rc) — scenario crashed or baseline unreadable"
    rc=1
elif [ $race_rc -eq 1 ]; then
    echo "cephrace: findings:"
    JAX_PLATFORMS=cpu python -m ceph_tpu.qa.race --seed "$SEED" \
        --scenario thrash --events 4 || true
    rc=1
else
    echo "cephrace: clean"
fi

echo "SARIF written to $OUT_DIR/ (cephlint.sarif, cephrace.sarif)"
exit $rc
