#!/usr/bin/env python
"""Headline benchmark — one JSON line for the driver.

Headline metric (BASELINE.json north star): RS(8,4) cauchy_good encode
GiB/s on one TPU chip via the fused Pallas kernel, vs the CPU AVX2
split-table oracle (native/gf_oracle.cc — the ISA-L ec_encode_data
formulation) on this host.  Acceptance bar: >= 10x.

WEDGE-PROOF CONTRACT (round-2 verdict, weak #1): the tunneled TPU backend
can hang indefinitely (not error) on first touch or mid-compile.  So the
parent process NEVER imports jax; every phase — including the first
jax.devices() probe — runs in its own subprocess with a hard timeout.
CPU baseline columns are computed in a child pinned to the CPU backend
via jax.config.update("jax_platforms", "cpu") — the JAX_PLATFORMS env
var is IGNORED by this box's sitecustomize — and therefore always
survive a wedged tunnel.  The first phase timeout marks the tunnel
wedged and skips the remaining TPU phases, so the whole bench is bounded
at roughly (cpu + probe + one phase) timeouts.  On a wedge the JSON line
still appears, carrying the CPU columns plus an "error" field, and the
exit code is non-zero when the headline is missing on a TPU host.

LOUD-FAILURE CONTRACT (round-2 verdict item 1): on a TPU platform the
Pallas kernel MUST compile and run — a Mosaic failure exits non-zero with
the error in the JSON line instead of silently reporting the XLA fallback.
The XLA number is still measured and reported in "extra" for comparison.

"extra" carries the rest of the BASELINE.json matrix: RS(2,1) reed_sol_van,
CRUSH 1M-object remap on 1024 OSDs, SHEC(6,3,2) single-erasure decode and
CLAY(8,4) repair-bandwidth configs.  Timing subtleties live in
ceph_tpu/bench/timing.py.

EXIT CODES (the driver's rc discrimination): 0 = healthy run with a
live headline; 3 = tunnel wedged but DEGRADED — the JSON line carries
`last_known_silicon` (+ per-phase stale captures and the sentinel
state) instead of a null headline; 1 = hard failure with no usable
number.  CEPH_TPU_BENCH_FORCE_WEDGED=1 simulates the wedge instantly
(the CI gate's knob); CEPH_TPU_BENCH_SKIP_CPU=1 skips the CPU-oracle
phase (pairs with the forced wedge so the gate runs in seconds).

WATCHDOG MODE (`bench.py --watchdog`, folding perf_runs/watchdog3.py
into the bench proper per the ROADMAP): probes the tunnel on the same
fast subprocess timeout the bench uses, and on the first UP runs the
pending capture jobs from perf_runs/jobs/*.json in filename order.
Done-markers (`<marker>.done` next to the jobs dir) make every job
idempotent so captures resume across rounds; `--deadline
YYYY-mm-ddTHH:MM` (UTC) is the hard no-job-starts-after line (the
r2/r4 wedge trigger was a builder mid-compile at round end).
CEPH_TPU_SENTINEL_STATE=ok|degraded[:reason] short-circuits the probe
(shared with the backend sentinel, common/kernel_telemetry.py).
"""
import argparse
import json
import os
import subprocess
import sys
import time

import numpy as np

# (name, timeout_seconds).  Remote compiles are ~20-40 s each; chained
# 256 MiB measurement loops take tens of seconds over the tunnel.
# probe is compile-free (jax.devices() only): a healthy tunnel answers
# in seconds, a wedged one never answers — 150 s here just burned most
# of a round's patience confirming what 25 s already proves.
PHASE_TIMEOUTS = {
    "cpu": 600,
    "probe": 25,
    "rs84": 600,
    "rs21": 420,
    "crush": 600,
    "shec": 420,
    "clay": 420,
    "traffic": 300,
}

#: last good on-silicon capture: when the tunnel is wedged the JSON line
#: degrades to this instead of "value": null, so the perf trajectory
#: keeps a number (clearly flagged stale) across wedged rounds
LAST_SILICON_CAPTURE = "perf_runs/full_bench_r4_early.json"

#: per-phase last-good captures (the watchdog's job outputs): a wedged
#: round reports each phase's stale number alongside the headline's
PHASE_CAPTURES = {
    "shec": "perf_runs/shec.json",
    "clay": "perf_runs/clay.json",
    "crush": "perf_runs/crush_full.json",
}
# crush LAST: the 1M-PG batch launch is the one phase that has wedged
# the tunnel (r2, r4) — a wedge there must not cost the shec/clay columns
TPU_PHASES = ("rs84", "rs21", "shec", "clay", "traffic", "crush")


# ---------------------------------------------------------------- measurement

def cpu_baseline_gibps(coding, k, data_mib=64, reps=3) -> float:
    """AVX2 oracle throughput.  Note (round-2 verdict, weak #10): measured
    at 64 MiB resident — cache-friendlier than the 256 MiB the TPU column
    chains on-device, i.e. generous to the CPU; see PERF.md."""
    from ceph_tpu import native_oracle

    data = np.random.default_rng(0).integers(
        0, 256, (k, data_mib * 2**20 // k), dtype=np.uint8
    )
    native_oracle.encode(coding, data, fast=True)  # warm
    t0 = time.perf_counter()
    for _ in range(reps):
        native_oracle.encode(coding, data, fast=True)
    dt = (time.perf_counter() - t0) / reps
    return data.nbytes / dt / 2**30


def tpu_gibps(coding, k, kernel, data_mib=256, iters=50) -> float:
    from ceph_tpu.bench.timing import time_chained_encode

    if not on_tpu():
        # CPU-host CI fallback: the full 256 MiB x 50-iter chain takes
        # >10 min through the XLA CPU backend and would eat the phase
        # timeout; a small chain still proves the path end-to-end
        data_mib, iters = min(data_mib, 32), 10
    data = np.random.default_rng(1).integers(
        0, 256, (k, data_mib * 2**20 // k), dtype=np.uint8
    )
    secs = time_chained_encode(
        coding, data, iters, kernel=kernel, subtract_overhead=True, repeats=3,
    )
    return data.nbytes * iters / secs / 2**30


def on_tpu() -> bool:
    import jax

    return jax.devices()[0].platform not in ("cpu",)


def _decode_kernel_gibps(M, n_in, out_bytes_per_iter, chunk_cols,
                         kernel: str, iters: int = 50) -> float:
    """Chained on-device applies of a decode/repair matrix M to resident
    input — the same methodology as the encode headline.  (A per-call
    host round-trip on this box measures the ~10 MB/s tunnel, not the
    kernel; real deployments hold recovery batches device-resident.)"""
    from ceph_tpu.bench.timing import time_chained_encode

    x = np.random.default_rng(7).integers(
        0, 256, (n_in, chunk_cols), dtype=np.uint8
    )
    secs = time_chained_encode(
        M, x, iters, kernel=kernel, subtract_overhead=True, repeats=3
    )
    return out_bytes_per_iter * iters / secs / 2**30


# --------------------------------------------------- shared config factories

def _shec_matrix():
    """(decode matrix, avail chunk count) for the SHEC(6,3,2) single-erasure
    local-recovery plan — shared by the CPU and TPU columns."""
    from ceph_tpu.ec.registry import ErasureCodePluginRegistry

    codec = ErasureCodePluginRegistry.instance().factory(
        {"plugin": "shec", "k": "6", "m": "3", "c": "2"}
    )
    plan = codec.minimum_to_decode({2}, set(range(9)) - {2})
    avail_t = tuple(sorted(plan))
    M = np.ascontiguousarray(codec._decode_matrix(frozenset({2}), avail_t),
                             np.uint8)
    return M, avail_t


def _clay_setup():
    """(repair matrix, chunk size, sub-chunk len, helpers, codec) for the
    CLAY(8,4,d=11) single-shard repair config."""
    from ceph_tpu.ec.registry import ErasureCodePluginRegistry

    codec = ErasureCodePluginRegistry.instance().factory(
        {"plugin": "clay", "k": "8", "m": "4"}
    )
    chunk = codec.get_chunk_size(8 * (4 << 20))  # ~4 MiB chunks
    Z = codec.get_sub_chunk_count()
    helpers = tuple(i for i in range(12) if i != 0)
    M = np.ascontiguousarray(codec.repair_matrix(0, helpers), np.uint8)
    return M, chunk, chunk // Z, helpers, codec


# ------------------------------------------------------------------- phases
# Each runs in its own subprocess and prints one JSON dict on stdout.

def phase_cpu() -> dict:
    """Every CPU-oracle column, computed with jax pinned to the CPU
    backend so a wedged tunnel can never take the baselines down."""
    from ceph_tpu.gf import cauchy_good_coding_matrix, vandermonde_coding_matrix

    out = {}
    coding84 = np.ascontiguousarray(cauchy_good_coding_matrix(8, 4), np.uint8)
    out["cpu_avx2_rs8_4_encode_gibps"] = round(
        cpu_baseline_gibps(coding84, 8), 2
    )
    coding21 = np.ascontiguousarray(vandermonde_coding_matrix(2, 1), np.uint8)
    out["rs2_1_van_encode_cpu_gibps"] = round(cpu_baseline_gibps(coding21, 2), 2)

    try:
        M, avail_t = _shec_matrix()
        out["shec_632_reads_chunks"] = len(avail_t)  # < k: the SHEC claim
        # recovered-bytes/s basis: oracle timer counts input bytes, so
        # scale by out_rows/in_rows
        out["shec_632_decode1_cpu_gibps"] = round(
            cpu_baseline_gibps(M, len(avail_t), data_mib=len(avail_t) * 8)
            * M.shape[0] / len(avail_t),
            3,
        )
    except Exception as e:
        print(f"# shec cpu baseline failed: {e}", file=sys.stderr)

    try:
        M, chunk, sub_len, helpers, codec = _clay_setup()
        n_in = M.shape[1]
        out["clay_84_repair_cpu_gibps"] = round(
            cpu_baseline_gibps(M, n_in, data_mib=max(16, n_in * sub_len >> 20))
            * M.shape[0] / n_in,
            3,
        )
        # repair bandwidth: bytes fetched from helpers vs naive k full
        # chunks (the MSR claim BASELINE config 4 measures)
        need = codec.minimum_to_decode({0}, set(helpers))
        fetched = 0
        for ranges in need.values():
            for off, ln in ranges:
                fetched += chunk if ln == -1 else ln * sub_len
        out["clay_84_repair_bw_frac_of_naive"] = round(
            fetched / (codec.k * chunk), 3
        )
    except Exception as e:
        print(f"# clay cpu baseline failed: {e}", file=sys.stderr)

    try:
        from ceph_tpu.crush import build_hierarchical_map
        from ceph_tpu.crush.oracle_bridge import do_rule_batch_oracle

        cmap = build_hierarchical_map(128, 8)
        weights = np.full(1024, 0x10000, dtype=np.uint32)
        n_or = 100_000
        xs = np.arange(n_or)
        do_rule_batch_oracle(cmap, 0, xs[:1024], 3, weights)  # warm
        t0 = time.perf_counter()
        do_rule_batch_oracle(cmap, 0, xs, 3, weights)
        dt = time.perf_counter() - t0
        out["crush_remap_oracle_maps_per_s"] = round(n_or / dt)
    except Exception as e:
        print(f"# crush oracle baseline failed: {e}", file=sys.stderr)
    return out


def phase_probe() -> dict:
    import jax

    out = {"platform": jax.devices()[0].platform,
           "n_devices": jax.device_count()}
    # one synchronous sentinel cycle: the probe child is the first jax
    # toucher of the round, so its sentinel verdict is the freshest
    # liveness evidence the JSON line can carry
    from ceph_tpu.common.kernel_telemetry import SENTINEL

    st = SENTINEL.probe_once()
    out["sentinel"] = {k: st.get(k) for k in
                       ("state", "reason", "platform", "last_probe")}
    return out


def _kernel_provenance() -> dict:
    """The telemetry registry's compact summary — phases attach it so
    the JSON line records WHICH silicon served each number (the wedge
    postmortems kept asking; docs/observability.md)."""
    from ceph_tpu.common.kernel_telemetry import TELEMETRY

    return TELEMETRY.summary()


def phase_rs84() -> dict:
    """Headline RS(8,4) cauchy_good: XLA bitplane path + fused Pallas
    kernel.  A Pallas failure is reported as a key, not an exit code, so
    the XLA column survives; the parent applies the loud-failure rule."""
    from ceph_tpu.gf import cauchy_good_coding_matrix

    coding = np.ascontiguousarray(cauchy_good_coding_matrix(8, 4), np.uint8)
    out = {}
    try:
        out["rs8_4_encode_xla_gibps"] = round(tpu_gibps(coding, 8, "xla"), 2)
    except Exception as e:
        out["xla_error"] = f"{type(e).__name__}: {e}"
    try:
        out["rs8_4_encode_pallas_gibps"] = round(
            tpu_gibps(coding, 8, "pallas"), 2
        )
    except Exception as e:
        out["pallas_error"] = f"{type(e).__name__}: {e}"
    out["kernel_telemetry"] = _kernel_provenance()
    return out


def phase_rs21() -> dict:
    """BASELINE config 1: jerasure RS(2,1) reed_sol_van, 4 KiB stripes."""
    from ceph_tpu.gf import vandermonde_coding_matrix

    coding = np.ascontiguousarray(vandermonde_coding_matrix(2, 1), np.uint8)
    kernel = "pallas" if on_tpu() else "xla"
    return {"rs2_1_van_encode_gibps": round(
        tpu_gibps(coding, 2, kernel, data_mib=128, iters=50), 2
    )}


def phase_crush(num_pgs=None) -> dict:
    """BASELINE config 5: straw2 remap over 1024 OSDs (maps/s), TPU batch
    mapper (Pallas scorer — the gather path is never compiled on TPU; it
    has wedged the tunnel before).  CEPH_TPU_BENCH_CRUSH_PGS shrinks the
    batch for the tunnel watchdog's cautious first probe (the full 1M-PG
    launch is implicated in wedging the tunnel, r4)."""
    if num_pgs is None:
        raw = os.environ.get("CEPH_TPU_BENCH_CRUSH_PGS", "1000000")
        try:
            num_pgs = int(raw)
        except ValueError:
            raise ValueError(
                f"CEPH_TPU_BENCH_CRUSH_PGS={raw!r}: integer required"
            ) from None
        if num_pgs < 1024:
            raise ValueError(
                f"CEPH_TPU_BENCH_CRUSH_PGS={num_pgs}: must be >= 1024 "
                f"(the warm-up batch size)"
            )
    from ceph_tpu.crush import (
        CompiledCrushMap,
        build_hierarchical_map,
        crush_do_rule_batch,
    )

    cmap = build_hierarchical_map(128, 8)
    weights = np.full(1024, 0x10000, dtype=np.uint32)
    xs = np.arange(num_pgs, dtype=np.int64)
    cm = CompiledCrushMap(cmap)
    np.asarray(crush_do_rule_batch(cm, 0, xs[:1024], 3, weights))  # compile
    t0 = time.perf_counter()
    np.asarray(crush_do_rule_batch(cm, 0, xs, 3, weights))
    dt = time.perf_counter() - t0
    return {"crush_remap_maps_per_s": round(num_pgs / dt),
            "kernel_telemetry": _kernel_provenance()}


def phase_shec() -> dict:
    """BASELINE config 3: SHEC(6,3,2) single-erasure local recovery — one
    cached decode-matrix apply (the ShecTableCache role), chained
    device-resident."""
    M, avail_t = _shec_matrix()
    kernel = "pallas" if on_tpu() else "xla"
    chunk = 8 << 20
    return {"shec_632_decode1_gibps": round(
        _decode_kernel_gibps(M, len(avail_t), chunk, chunk, kernel), 3
    )}


def phase_clay() -> dict:
    """BASELINE config 4: CLAY(8,4,d=11) repair GiB/s — one cached
    [Z, d*nB] matrix apply (clay.py repair_matrix), chained
    device-resident."""
    M, chunk, sub_len, helpers, _ = _clay_setup()
    kernel = "pallas" if on_tpu() else "xla"
    return {"clay_84_repair_gibps": round(
        _decode_kernel_gibps(M, M.shape[1], chunk, sub_len, kernel), 3
    )}


def phase_traffic() -> dict:
    """Sustained-traffic scenario (ceph_tpu/bench/traffic.py): N
    simulated clients x 4 KiB writes through the production
    WriteBatcher, batched vs per-op — aggregate GiB/s + p99 latency,
    the ROADMAP "millions of users" metric.  Runs on whatever backend
    the child gets (TPU when the tunnel is healthy, CPU fallback
    otherwise); the batched/per-op ratio is meaningful either way."""
    from ceph_tpu.bench.traffic import run_scenario

    return run_scenario(n_clients=32, seconds=3.0, write_size=4096)


PHASES = {
    "cpu": phase_cpu,
    "probe": phase_probe,
    "rs84": phase_rs84,
    "rs21": phase_rs21,
    "crush": phase_crush,
    "shec": phase_shec,
    "clay": phase_clay,
    "traffic": phase_traffic,
}


# ------------------------------------------------------------- orchestration

def run_phase(name: str):
    """Run one phase in a subprocess.  Returns (result dict | None,
    error string | None, timed_out bool).  Phase stderr is passed through
    for diagnostics; the last stdout line must be the JSON result.
    (Platform pinning happens child-side via jax.config.update — the
    JAX_PLATFORMS env var is ignored on this box's sitecustomize.)"""
    cmd = [sys.executable, os.path.abspath(__file__), "--phase", name]
    timeout = PHASE_TIMEOUTS[name]
    try:
        p = subprocess.run(cmd, capture_output=True, text=True,
                           timeout=timeout)
    except subprocess.TimeoutExpired as e:
        for s in (e.stderr or b""), (e.stdout or b""):
            if s:
                sys.stderr.write(s.decode("utf-8", "replace")
                                 if isinstance(s, bytes) else s)
        return None, f"{name}: timed out after {timeout}s", True
    if p.stderr:
        sys.stderr.write(p.stderr)
    if p.returncode != 0:
        tail = " | ".join((p.stderr or "").strip().splitlines()[-3:])
        return None, f"{name}: rc={p.returncode}: {tail}", False
    try:
        return json.loads(p.stdout.strip().splitlines()[-1]), None, False
    except Exception as e:
        return None, f"{name}: unparseable phase output ({e})", False


def last_known_silicon() -> dict | None:
    """The persisted last-good TPU capture, or None if unreadable."""
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        LAST_SILICON_CAPTURE)
    try:
        with open(path) as f:
            doc = json.loads(f.read().strip())
    except (OSError, ValueError) as e:
        print(f"# last-silicon capture unreadable: {e}", file=sys.stderr)
        return None
    if doc.get("value") is None:
        return None
    return {
        "metric": doc.get("metric"),
        "value": doc["value"],
        "vs_baseline": doc.get("vs_baseline"),
        "source": LAST_SILICON_CAPTURE,
    }


def last_known_phase_captures() -> dict:
    """{phase: {metric, value, source}} from the per-phase capture files
    (perf_runs/*.json, the watchdog's job outputs) — the stale-but-
    numeric view of every TPU phase a wedged round could not run."""
    base = os.path.dirname(os.path.abspath(__file__))
    out = {}
    for phase, rel in PHASE_CAPTURES.items():
        try:
            with open(os.path.join(base, rel)) as f:
                doc = json.loads(f.read().strip())
        except (OSError, ValueError):
            continue
        for k, v in doc.items():
            if isinstance(v, (int, float)):
                out[phase] = {"metric": k, "value": v, "source": rel}
                break
    return out


def emit_wedged(extra, errors):
    """Wedged-tunnel degradation: carry the last good silicon number
    (flagged stale) plus the per-phase stale captures and the sentinel
    view of the wedge, instead of a null headline — the perf loop keeps
    numbers AND knows they are stale.  Exit is rc=3 when degraded data
    is carried (rc discrimination for the driver/CI gate; a wedge with
    no stale capture at all stays the hard rc=1)."""
    # the bench's sentinel view: the probe outcome IS the liveness
    # evidence (the parent never imports jax, so it cannot ask the
    # in-process SENTINEL — same latch semantics, subprocess probe)
    extra["sentinel"] = {
        "state": "degraded",
        "reason": next((e for e in errors if "wedged" in e),
                       errors[-1] if errors else "tunnel wedged"),
        "since": time.time(),
        "source": "bench probe",
    }
    extra["last_known_silicon_phases"] = last_known_phase_captures()
    lks = last_known_silicon()
    if lks is None:
        emit("rs8_4_cauchy_good_encode_throughput_pallas", None, None,
             extra, errors, 1)
    extra["last_known_silicon"] = lks
    extra["value_is_last_known_silicon"] = True
    emit("rs8_4_cauchy_good_encode_throughput_pallas", lks["value"],
         lks.get("vs_baseline"), extra, errors, 3)


def emit(metric, value, vs, extra, errors, rc):
    line = {"metric": metric, "value": value, "unit": "GiB/s",
            "vs_baseline": vs, "extra": extra}
    if errors:
        line["error"] = "; ".join(errors)
    print(json.dumps(line))
    sys.exit(rc)


def main():
    extra: dict = {}
    errors: list = []

    if os.environ.get("CEPH_TPU_BENCH_SKIP_CPU"):
        # CI-gate knob: the CPU-oracle columns take minutes and prove
        # nothing about the wedge path under test
        errors.append("cpu: skipped (CEPH_TPU_BENCH_SKIP_CPU)")
    else:
        res, err, _ = run_phase("cpu")
        if res:
            extra.update(res)
        elif err:
            errors.append(err)
    cpu = extra.get("cpu_avx2_rs8_4_encode_gibps")

    if os.environ.get("CEPH_TPU_BENCH_FORCE_WEDGED"):
        # simulated wedge (env probe override): the degradation contract
        # — sentinel state + last_known_silicon, rc=3 — exercised in
        # seconds, no 25 s probe timeout burned (qa/ci_gate.sh)
        errors.append("TPU backend wedged: probe skipped "
                      "(CEPH_TPU_BENCH_FORCE_WEDGED)")
        emit_wedged(extra, errors)

    res, err, timed_out = run_phase("probe")
    if res is None:
        errors.append(err if not timed_out
                      else f"TPU backend wedged: {err}")
        emit_wedged(extra, errors)
    platform = res["platform"]
    extra["platform"] = platform
    if res.get("sentinel"):
        # healthy-run liveness evidence (the probe child's sentinel
        # cycle) rides the JSON line like the wedged path's verdict does
        extra["sentinel"] = res["sentinel"]

    wedged = False
    for name in TPU_PHASES:
        if wedged:
            errors.append(f"{name}: skipped (tunnel wedged)")
            continue
        res, err, timed_out = run_phase(name)
        if res:
            extra.update(res)
        if err:
            errors.append(err)
        if timed_out:
            wedged = True

    pallas = extra.pop("rs8_4_encode_pallas_gibps", None)
    pallas_err = extra.pop("pallas_error", None)
    if pallas is not None:
        vs = round(pallas / cpu, 2) if cpu else None
        emit("rs8_4_cauchy_good_encode_throughput_pallas", pallas, vs,
             extra, errors, 0)
    if platform != "cpu":
        # loud failure: on TPU the Pallas headline is mandatory.  A
        # mid-run wedge (phase timeout after a healthy probe) degrades
        # to the stale capture like a wedged probe does
        if pallas_err:
            errors.append(f"Pallas kernel failed on TPU: {pallas_err}")
        if wedged:
            emit_wedged(extra, errors)
        emit("rs8_4_cauchy_good_encode_throughput_pallas", None, None,
             extra, errors, 1)
    # CPU-only host (CI): fall back to the XLA number, clearly labeled.
    xla = extra.get("rs8_4_encode_xla_gibps")
    if xla is None:
        errors.append(f"XLA and Pallas kernels both failed "
                      f"(pallas: {pallas_err})")
        emit("rs8_4_cauchy_good_encode_throughput", None, None,
             extra, errors, 1)
    vs = round(xla / cpu, 2) if cpu else None
    emit("rs8_4_cauchy_good_encode_throughput_xla_cpuhost", xla, vs,
         extra, errors, 0)


# ----------------------------------------------------------- watchdog mode
# perf_runs/watchdog3.py folded into the bench proper (ROADMAP "fold the
# watchdog job chain into bench.py"): same probe, same job files, same
# done-marker idempotence — captures resume across rounds because the
# markers live next to the jobs, not in a watchdog process's memory.

def watchdog_probe() -> bool:
    """Tunnel liveness for the watchdog: the bench's own subprocess
    probe (25 s fast-fail), short-circuited by CEPH_TPU_SENTINEL_STATE
    so tests/CI never touch the tunnel."""
    forced = os.environ.get("CEPH_TPU_SENTINEL_STATE", "")
    if forced:
        return not forced.startswith("degraded")
    res, _err, _timed_out = run_phase("probe")
    return res is not None and res.get("platform") not in (None, "cpu")


def watchdog_pending_jobs(jobs_dir: str, out_dir: str) -> list:
    """Job files ({marker, timeout, argv, env}) whose done-marker is
    absent, in filename order."""
    import glob

    jobs = []
    for path in sorted(glob.glob(os.path.join(jobs_dir, "*.json"))):
        try:
            with open(path) as f:
                j = json.load(f)
        except (OSError, ValueError) as e:
            print(f"# watchdog: bad job file {path}: {e}", file=sys.stderr)
            continue
        if not os.path.exists(os.path.join(out_dir, j["marker"] + ".done")):
            jobs.append(j)
    return jobs


def watchdog_run_job(j: dict, out_dir: str) -> bool:
    marker, tmo = j["marker"], int(j.get("timeout", 900))
    env = dict(os.environ)
    env.update(j.get("env", {}))
    print(f"# watchdog: running {marker}: {' '.join(j['argv'])}",
          file=sys.stderr)
    try:
        with open(os.path.join(out_dir, marker + ".out"), "w") as f:
            r = subprocess.run(j["argv"], timeout=tmo, stdout=f,
                               stderr=subprocess.STDOUT, env=env)
        if r.returncode == 0:
            open(os.path.join(out_dir, marker + ".done"), "w").close()
            print(f"# watchdog: {marker} OK", file=sys.stderr)
            return True
        print(f"# watchdog: {marker} rc={r.returncode}", file=sys.stderr)
    except subprocess.TimeoutExpired:
        print(f"# watchdog: {marker} TIMED OUT after {tmo}s",
              file=sys.stderr)
    return False


def watchdog_main(args) -> int:
    """Probe loop + job chain.  Hard-deadline rule: no job STARTS after
    --deadline (UTC, YYYY-mm-ddTHH:MM) — the round must never end with
    a builder mid-compile on the tunnel (the r2/r4 wedge trigger).
    --once runs a single cycle (tests/CI); the default loops forever."""
    if args.deadline:
        # fail LOUDLY on a malformed deadline (also covers the env-var
        # source, which argparse `type` would not): the comparison is
        # lexicographic, so an unpadded "2026-8-4T16:30" would never
        # fire — silently recreating the r2/r4 mid-compile wedge — and
        # a stray word would permanently trip it
        try:
            # round-trip: strptime alone accepts unpadded fields, which
            # the string comparison does not
            parsed = time.strptime(args.deadline, "%Y-%m-%dT%H:%M")
            if time.strftime("%Y-%m-%dT%H:%M", parsed) != args.deadline:
                raise ValueError("unpadded field")
        except ValueError:
            print(f"# watchdog: bad --deadline {args.deadline!r}: want "
                  f"UTC YYYY-mm-ddTHH:MM (zero-padded)", file=sys.stderr)
            return 2
    # anchor at the repo root regardless of invocation cwd (watchdog3
    # pinned os.chdir the same way): the default jobs dir AND the job
    # files' relative argv ("python bench.py --phase crush") both
    # resolve against it
    os.chdir(os.path.dirname(os.path.abspath(__file__)))
    jobs_dir = os.path.abspath(args.jobs_dir)
    out_dir = os.path.dirname(jobs_dir) or "."
    os.makedirs(jobs_dir, exist_ok=True)

    def past_deadline() -> bool:
        return bool(args.deadline) and \
            time.strftime("%Y-%m-%dT%H:%M", time.gmtime()) >= args.deadline

    def log(msg):
        print(f"# watchdog {time.strftime('%FT%TZ', time.gmtime())}: "
              f"{msg}", file=sys.stderr)

    log(f"started (pid {os.getpid()}), jobs={jobs_dir}, "
        f"deadline={args.deadline or 'none'}")
    while True:
        if past_deadline():
            log(f"past deadline; probe="
                f"{'UP' if watchdog_probe() else 'down'}; "
                f"no more jobs will start")
            if args.once:
                return 0
            time.sleep(600)
            continue
        todo = watchdog_pending_jobs(jobs_dir, out_dir)
        if not todo:
            if args.once:
                return 0
            time.sleep(120)
            continue
        if not watchdog_probe():
            log(f"tunnel down/wedged ({len(todo)} jobs pending)")
            if args.once:
                return 0
            time.sleep(args.probe_interval)
            continue
        log(f"tunnel UP; {len(todo)} jobs pending")
        for j in todo:
            if past_deadline():
                log("deadline hit mid-wave; stopping")
                break
            watchdog_run_job(j, out_dir)
            if not watchdog_probe():
                log("tunnel lost mid-wave; back to sleep")
                break
        if args.once:
            return 0


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--phase", choices=sorted(PHASES))
    ap.add_argument("--watchdog", action="store_true",
                    help="probe loop + perf_runs/jobs/*.json capture "
                         "chain (ex-perf_runs/watchdog3.py)")
    ap.add_argument("--jobs-dir", default="perf_runs/jobs")
    ap.add_argument("--deadline",
                    default=os.environ.get("CEPH_TPU_WATCHDOG_DEADLINE",
                                           ""),
                    help="UTC YYYY-mm-ddTHH:MM; no job starts after it")
    ap.add_argument("--probe-interval", type=float, default=300.0)
    ap.add_argument("--once", action="store_true",
                    help="one watchdog cycle, then exit (tests/CI)")
    args = ap.parse_args()
    if args.watchdog:
        sys.exit(watchdog_main(args))
    if args.phase:
        if args.phase == "cpu" or os.environ.get("CEPH_TPU_BENCH_FORCE_CPU"):
            # sitecustomize pins the axon platform at interpreter start and
            # IGNORES the JAX_PLATFORMS env var; config.update is the one
            # reliable spelling (see tests/conftest.py).  The cpu phase
            # must never touch the tunnel or a wedge takes the CPU
            # baselines down with it.
            import jax

            jax.config.update("jax_platforms", "cpu")
        print(json.dumps(PHASES[args.phase]()))
    else:
        main()
