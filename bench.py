#!/usr/bin/env python
"""Headline benchmark — one JSON line for the driver.

Measures the north-star metric (BASELINE.json): RS(8,4) cauchy_good encode
throughput on one TPU chip via the bitplane kernel (best of XLA and Pallas),
against the CPU SIMD oracle (native/gf_oracle.cc — the ISA-L-formulation
baseline) on this host.  vs_baseline = TPU GiB/s / CPU GiB/s; the acceptance
bar is >= 10x.  Timing subtleties live in ceph_tpu/bench/timing.py.
"""
import json
import sys
import time

import numpy as np


def cpu_baseline_gibps(coding, k, data_mib=64, reps=3) -> float:
    from ceph_tpu import native_oracle

    data = np.random.default_rng(0).integers(
        0, 256, (k, data_mib * 2**20 // k), dtype=np.uint8
    )
    native_oracle.encode(coding, data, fast=True)  # warm
    t0 = time.perf_counter()
    for _ in range(reps):
        native_oracle.encode(coding, data, fast=True)
    dt = (time.perf_counter() - t0) / reps
    return data.nbytes / dt / 2**30


def tpu_gibps(coding, k, data_mib=256, iters=50) -> tuple[float, str]:
    from ceph_tpu.bench.timing import time_chained_encode

    data = np.random.default_rng(1).integers(
        0, 256, (k, data_mib * 2**20 // k), dtype=np.uint8
    )
    best = 0.0
    best_kernel = "xla"
    for kernel in ("xla", "pallas"):
        try:
            secs = time_chained_encode(
                coding, data, iters, kernel=kernel,
                subtract_overhead=True, repeats=3,
            )
        except Exception as e:  # pallas may be unavailable on some backends
            print(f"# kernel {kernel} failed: {e}", file=sys.stderr)
            continue
        gibps = data.nbytes * iters / secs / 2**30
        if gibps > best:
            best, best_kernel = gibps, kernel
    return best, best_kernel


def main():
    from ceph_tpu.gf import cauchy_good_coding_matrix

    k, m = 8, 4
    coding = np.ascontiguousarray(cauchy_good_coding_matrix(k, m), dtype=np.uint8)
    try:
        cpu = cpu_baseline_gibps(coding, k)
    except Exception as e:  # oracle build failure shouldn't kill the bench
        print(f"# cpu baseline unavailable: {e}", file=sys.stderr)
        cpu = None
    tpu, kernel = tpu_gibps(coding, k)
    print(
        json.dumps(
            {
                "metric": f"rs8_4_cauchy_good_encode_throughput_{kernel}",
                "value": round(tpu, 2),
                "unit": "GiB/s",
                "vs_baseline": round(tpu / cpu, 2) if cpu else None,
            }
        )
    )


if __name__ == "__main__":
    main()
