#!/usr/bin/env python
"""Headline benchmark — one JSON line for the driver.

Headline metric (BASELINE.json north star): RS(8,4) cauchy_good encode
GiB/s on one TPU chip via the fused Pallas kernel, vs the CPU AVX2
split-table oracle (native/gf_oracle.cc — the ISA-L ec_encode_data
formulation) on this host.  Acceptance bar: >= 10x.

LOUD-FAILURE CONTRACT (round-2 verdict item 1): on a TPU platform the
Pallas kernel MUST compile and run — a Mosaic failure exits non-zero with
the error in the JSON line instead of silently reporting the XLA fallback.
The XLA number is still measured and reported in "extra" for comparison.

"extra" carries the rest of the BASELINE.json matrix (configs measured so
far: RS(2,1) reed_sol_van 4 KiB, CRUSH 1M-object remap on 1024 OSDs, the
SHEC(6,3,2) single-erasure decode and CLAY(8,4) repair-bandwidth configs).
Timing subtleties live in ceph_tpu/bench/timing.py.
"""
import json
import sys
import time

import numpy as np


def cpu_baseline_gibps(coding, k, data_mib=64, reps=3) -> float:
    from ceph_tpu import native_oracle

    data = np.random.default_rng(0).integers(
        0, 256, (k, data_mib * 2**20 // k), dtype=np.uint8
    )
    native_oracle.encode(coding, data, fast=True)  # warm
    t0 = time.perf_counter()
    for _ in range(reps):
        native_oracle.encode(coding, data, fast=True)
    dt = (time.perf_counter() - t0) / reps
    return data.nbytes / dt / 2**30


def tpu_gibps(coding, k, kernel, data_mib=256, iters=50) -> float:
    from ceph_tpu.bench.timing import time_chained_encode

    data = np.random.default_rng(1).integers(
        0, 256, (k, data_mib * 2**20 // k), dtype=np.uint8
    )
    secs = time_chained_encode(
        coding, data, iters, kernel=kernel, subtract_overhead=True, repeats=3,
    )
    return data.nbytes * iters / secs / 2**30


def on_tpu() -> bool:
    import jax

    return jax.devices()[0].platform not in ("cpu",)


def bench_rs21_van(extra: dict) -> None:
    """BASELINE config 1: jerasure RS(2,1) reed_sol_van, 4 KiB stripes."""
    from ceph_tpu.gf import vandermonde_coding_matrix

    coding = np.ascontiguousarray(vandermonde_coding_matrix(2, 1), np.uint8)
    # CPU first: a TPU-kernel failure must not discard the independently-
    # obtainable baseline column
    extra["rs2_1_van_encode_cpu_gibps"] = round(
        cpu_baseline_gibps(coding, 2), 2
    )
    extra["rs2_1_van_encode_gibps"] = round(
        tpu_gibps(coding, 2, "pallas", data_mib=128, iters=50), 2
    )


def bench_crush_remap(extra: dict, num_pgs=1_000_000) -> None:
    """BASELINE config 5: straw2 remap over 1024 OSDs (maps/s), TPU batch
    mapper vs the C mapper oracle."""
    from ceph_tpu.crush import (
        CompiledCrushMap,
        build_hierarchical_map,
        crush_do_rule_batch,
    )

    cmap = build_hierarchical_map(128, 8)
    weights = np.full(1024, 0x10000, dtype=np.uint32)
    xs = np.arange(num_pgs, dtype=np.int64)
    cm = CompiledCrushMap(cmap)
    np.asarray(crush_do_rule_batch(cm, 0, xs[:1024], 3, weights))  # compile
    t0 = time.perf_counter()
    np.asarray(crush_do_rule_batch(cm, 0, xs, 3, weights))
    dt = time.perf_counter() - t0
    extra["crush_remap_maps_per_s"] = round(num_pgs / dt)
    try:
        from ceph_tpu.crush.oracle_bridge import do_rule_batch_oracle

        n_or = min(num_pgs, 100_000)
        t0 = time.perf_counter()
        do_rule_batch_oracle(cmap, 0, np.arange(n_or), 3, weights)
        dt = time.perf_counter() - t0
        extra["crush_remap_oracle_maps_per_s"] = round(n_or / dt)
    except Exception as e:
        print(f"# crush oracle baseline unavailable: {e}", file=sys.stderr)


def _decode_kernel_gibps(M, n_in, out_bytes_per_iter, chunk_cols,
                         kernel: str, iters: int = 50) -> float:
    """Chained on-device applies of a decode/repair matrix M to resident
    input — the same methodology as the encode headline.  (A per-call
    host round-trip on this box measures the ~10 MB/s tunnel, not the
    kernel; real deployments hold recovery batches device-resident.)"""
    from ceph_tpu.bench.timing import time_chained_encode

    x = np.random.default_rng(7).integers(
        0, 256, (n_in, chunk_cols), dtype=np.uint8
    )
    secs = time_chained_encode(
        M, x, iters, kernel=kernel, subtract_overhead=True, repeats=3
    )
    return out_bytes_per_iter * iters / secs / 2**30


def bench_shec_decode(extra: dict) -> None:
    """BASELINE config 3: SHEC(6,3,2) single-erasure local recovery.

    The whole recovery is one cached decode-matrix apply (the
    ShecTableCache role); measured as chained device-resident applies,
    plus the CPU AVX2 oracle applying the identical matrix."""
    try:
        from ceph_tpu.ec.registry import ErasureCodePluginRegistry

        codec = ErasureCodePluginRegistry.instance().factory(
            {"plugin": "shec", "k": "6", "m": "3", "c": "2"}
        )
        want = frozenset({2})
        plan = codec.minimum_to_decode({2}, set(range(9)) - {2})
        avail_t = tuple(sorted(plan))
        M = np.ascontiguousarray(
            codec._decode_matrix(want, avail_t), np.uint8
        )
        extra["shec_632_reads_chunks"] = len(avail_t)  # < k: the SHEC claim
        chunk = 8 << 20
        # both columns count RECOVERED bytes/s: the oracle timer measures
        # input bytes, so scale by out_rows/in_rows
        extra["shec_632_decode1_cpu_gibps"] = round(
            cpu_baseline_gibps(M, len(avail_t), data_mib=len(avail_t) * 8)
            * M.shape[0] / len(avail_t),
            3,
        )
        kernel = "pallas" if on_tpu() else "xla"
        extra["shec_632_decode1_gibps"] = round(
            _decode_kernel_gibps(M, len(avail_t), chunk, chunk, kernel), 3
        )
    except Exception as e:
        print(f"# shec decode bench failed: {e}", file=sys.stderr)


def bench_clay_repair(extra: dict) -> None:
    """BASELINE config 4: CLAY(8,4,d=11) repair — GiB/s of repaired data
    plus the sub-chunk repair-bandwidth ratio vs naive RS repair.

    Single-shard repair collapses to one cached [Z, d*nB] matrix apply
    (clay.py repair_matrix); measured chained device-resident, vs the CPU
    AVX2 oracle applying the identical matrix."""
    try:
        from ceph_tpu.ec.registry import ErasureCodePluginRegistry

        codec = ErasureCodePluginRegistry.instance().factory(
            {"plugin": "clay", "k": "8", "m": "4"}
        )
        chunk = codec.get_chunk_size(8 * (4 << 20))  # ~4 MiB chunks
        Z = codec.get_sub_chunk_count()
        sub_len = chunk // Z
        helpers = tuple(i for i in range(12) if i != 0)
        M = np.ascontiguousarray(codec.repair_matrix(0, helpers), np.uint8)
        n_in = M.shape[1]  # d * nB fetched sub-chunk rows
        # recovered-bytes/s basis, as above
        extra["clay_84_repair_cpu_gibps"] = round(
            cpu_baseline_gibps(
                M, n_in, data_mib=max(16, n_in * sub_len >> 20)
            )
            * M.shape[0] / n_in,
            3,
        )
        kernel = "pallas" if on_tpu() else "xla"
        extra["clay_84_repair_gibps"] = round(
            _decode_kernel_gibps(M, n_in, chunk, sub_len, kernel), 3
        )
        # repair bandwidth: bytes fetched from helpers vs naive k full
        # chunks (the MSR claim BASELINE config 4 measures)
        need = codec.minimum_to_decode({0}, set(helpers))
        fetched = 0
        for ranges in need.values():
            for off, ln in ranges:
                fetched += chunk if ln == -1 else ln * sub_len
        extra["clay_84_repair_bw_frac_of_naive"] = round(
            fetched / (codec.k * chunk), 3
        )
    except Exception as e:
        print(f"# clay repair bench failed: {e}", file=sys.stderr)


def main():
    from ceph_tpu.gf import cauchy_good_coding_matrix

    k, m = 8, 4
    coding = np.ascontiguousarray(cauchy_good_coding_matrix(k, m), np.uint8)
    try:
        cpu = cpu_baseline_gibps(coding, k)
    except Exception as e:  # oracle build failure shouldn't kill the bench
        print(f"# cpu baseline unavailable: {e}", file=sys.stderr)
        cpu = None

    extra: dict = {}
    if cpu:
        extra["cpu_avx2_rs8_4_encode_gibps"] = round(cpu, 2)

    # XLA bitplane path (round-1 fallback) for comparison
    try:
        extra["rs8_4_encode_xla_gibps"] = round(tpu_gibps(coding, k, "xla"), 2)
    except Exception as e:
        print(f"# xla kernel failed: {e}", file=sys.stderr)

    # headline: the fused Pallas kernel.  On TPU a failure here is FATAL.
    pallas_err = None
    tpu = None
    try:
        tpu = tpu_gibps(coding, k, "pallas")
    except Exception as e:
        pallas_err = f"{type(e).__name__}: {e}"

    if tpu is None:
        if on_tpu():
            print(
                json.dumps(
                    {
                        "metric": "rs8_4_cauchy_good_encode_throughput_pallas",
                        "value": None,
                        "unit": "GiB/s",
                        "vs_baseline": None,
                        "error": f"Pallas kernel failed on TPU: {pallas_err}",
                        "extra": extra,
                    }
                )
            )
            sys.exit(1)
        # CPU-only host (CI): fall back to the XLA number, clearly labeled.
        # Both kernels failing is a real regression even here — fail loudly
        # instead of emitting a zero that reads as a measurement.
        if "rs8_4_encode_xla_gibps" not in extra:
            print(
                json.dumps(
                    {
                        "metric": "rs8_4_cauchy_good_encode_throughput",
                        "value": None,
                        "unit": "GiB/s",
                        "vs_baseline": None,
                        "error": f"XLA and Pallas kernels both failed "
                                 f"(pallas: {pallas_err})",
                        "extra": extra,
                    }
                )
            )
            sys.exit(1)
        tpu = extra["rs8_4_encode_xla_gibps"]
        metric = "rs8_4_cauchy_good_encode_throughput_xla_cpuhost"
    else:
        metric = "rs8_4_cauchy_good_encode_throughput_pallas"

    for fn in (bench_rs21_van, bench_crush_remap, bench_shec_decode,
               bench_clay_repair):
        try:
            fn(extra)
        except Exception as e:
            print(f"# {fn.__name__} failed: {e}", file=sys.stderr)

    print(
        json.dumps(
            {
                "metric": metric,
                "value": round(tpu, 2),
                "unit": "GiB/s",
                "vs_baseline": round(tpu / cpu, 2) if cpu else None,
                "extra": extra,
            }
        )
    )


if __name__ == "__main__":
    main()
