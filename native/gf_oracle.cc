// GF(2^8) oracle — independent C++ implementation of the matrix constructions
// and codec math in ceph_tpu/gf, used as (a) the bit-exactness referee for the
// JAX/Pallas path and (b) the CPU throughput baseline the TPU must beat.
//
// Plays the role of the reference's native jerasure/gf-complete/ISA-L stack
// (reference: src/erasure-code/jerasure/jerasure/src/{reed_sol.c,cauchy.c,
// jerasure.c,galois.c}, src/isa-l).  Algorithms are re-implemented from their
// documented behavior; field is GF(2^8) mod 0x11D as in jerasure w=8 / ISA-L.
//
// Parity semantics: byte-wise GF(2^8) matrix multiply for every technique
// (ISA-L's ec_encode_data convention).  jerasure's bitmatrix techniques
// produce packetsize-dependent layouts instead; byte-wise is the
// layout-independent formulation and equals jerasure for reed_sol_van.
//
// The fast path (gfo_encode_fast) is the ISA-L analog: 4-bit split tables,
// SSSE3 PSHUFB when available — this is the number the "10x on one v5e chip"
// target is measured against (BASELINE.md).
//
// Exposed via a plain C ABI for ctypes (no pybind11 in this image).

#include <cstdint>
#include <cstring>
#include <vector>

#if defined(__SSSE3__)
#include <tmmintrin.h>
#endif
#if defined(__AVX2__)
#include <immintrin.h>
#endif

namespace {

constexpr int GF_POLY = 0x11D;

struct Tables {
  uint8_t exp[512];
  int log[256];
  uint8_t inv[256];
  uint8_t mul[256][256];
  Tables() {
    int x = 1;
    for (int i = 0; i < 255; ++i) {
      exp[i] = (uint8_t)x;
      log[x] = i;
      x <<= 1;
      if (x & 0x100) x ^= GF_POLY;
    }
    for (int i = 255; i < 512; ++i) exp[i] = exp[i - 255];
    log[0] = 0;
    for (int a = 0; a < 256; ++a)
      for (int b = 0; b < 256; ++b)
        mul[a][b] = (a && b) ? exp[log[a] + log[b]] : 0;
    inv[0] = 0;
    for (int a = 1; a < 256; ++a) inv[a] = exp[(255 - log[a]) % 255];
  }
};

const Tables T;

inline int gmul(int a, int b) { return T.mul[a & 0xff][b & 0xff]; }
inline int gdiv(int a, int b) {
  if (b == 0) return -1;
  if (a == 0) return 0;
  return T.exp[(T.log[a] - T.log[b] + 255) % 255];
}

}  // namespace

extern "C" {

int gfo_mul(int a, int b) { return gmul(a, b); }
int gfo_div(int a, int b) { return gdiv(a, b); }

void gfo_mul_table(uint8_t* out) { std::memcpy(out, T.mul, 256 * 256); }

// reed_sol.c :: reed_sol_big_vandermonde_distribution_matrix (w=8), returning
// the bottom m rows (reed_sol_vandermonde_coding_matrix).
int gfo_vandermonde(int k, int m, uint8_t* out) {
  const int rows = k + m, cols = k;
  if (rows >= 256 || rows < cols) return -1;
  std::vector<int> d((size_t)rows * cols, 0);
  auto at = [&](int r, int c) -> int& { return d[(size_t)r * cols + c]; };
  for (int i = 0; i < rows; ++i) {
    at(i, 0) = 1;
    for (int j = 1; j < cols; ++j) at(i, j) = gmul(at(i, j - 1), i);
  }
  for (int i = 1; i < cols; ++i) {
    int j = i;
    while (j < cols && at(i, j) == 0) ++j;
    if (j == cols) return -2;
    if (j != i)
      for (int r = 0; r < rows; ++r) std::swap(at(r, i), at(r, j));
    if (at(i, i) != 1) {
      const int inv = gdiv(1, at(i, i));
      for (int r = 0; r < rows; ++r) at(r, i) = gmul(inv, at(r, i));
    }
    for (int j2 = 0; j2 < cols; ++j2) {
      const int tmp = at(i, j2);
      if (j2 != i && tmp != 0)
        for (int r = 0; r < rows; ++r) at(r, j2) ^= gmul(tmp, at(r, i));
    }
  }
  for (int j = 0; j < cols; ++j) {
    const int tmp = at(cols, j);
    if (tmp == 0) return -3;
    if (tmp != 1) {
      const int inv = gdiv(1, tmp);
      at(cols, j) = 1;
      for (int r = cols + 1; r < rows; ++r) at(r, j) = gmul(inv, at(r, j));
    }
  }
  for (int i = 0; i < m; ++i)
    for (int j = 0; j < k; ++j) out[i * k + j] = (uint8_t)at(cols + i, j);
  return 0;
}

// cauchy.c :: cauchy_original_coding_matrix: M[i][j] = 1/(i ^ (m+j)).
int gfo_cauchy_original(int k, int m, uint8_t* out) {
  if (k + m > 256) return -1;
  for (int i = 0; i < m; ++i)
    for (int j = 0; j < k; ++j) out[i * k + j] = T.inv[i ^ (m + j)];
  return 0;
}

// cauchy.c :: cauchy_n_ones — ones in the 8x8 bitmatrix of multiply-by-n.
int gfo_n_ones(int n) {
  int total = 0, e = n & 0xff;
  for (int x = 0; x < 8; ++x) {
    total += __builtin_popcount(e);
    e = gmul(e, 2);
  }
  return total;
}

// cauchy.c :: cauchy_improve_coding_matrix + cauchy_good_general_coding_matrix
// (no m==2 precomputed-best special case; see ceph_tpu/gf/matrix.py note).
int gfo_cauchy_good(int k, int m, uint8_t* out) {
  if (gfo_cauchy_original(k, m, out) != 0) return -1;
  for (int j = 0; j < k; ++j) {
    if (out[j] != 1) {
      const int inv = gdiv(1, out[j]);
      for (int i = 0; i < m; ++i) out[i * k + j] = (uint8_t)gmul(out[i * k + j], inv);
    }
  }
  for (int i = 1; i < m; ++i) {
    uint8_t* row = out + (size_t)i * k;
    int bno = 0;
    for (int j = 0; j < k; ++j) bno += gfo_n_ones(row[j]);
    int bno_index = -1;
    for (int j = 0; j < k; ++j) {
      if (row[j] != 1) {
        const int inv = gdiv(1, row[j]);
        int tno = 0;
        for (int x = 0; x < k; ++x) tno += gfo_n_ones(gmul(row[x], inv));
        if (tno < bno) {
          bno = tno;
          bno_index = j;
        }
      }
    }
    if (bno_index != -1) {
      const int inv = gdiv(1, row[bno_index]);
      for (int j = 0; j < k; ++j) row[j] = (uint8_t)gmul(row[j], inv);
    }
  }
  return 0;
}

// jerasure.c :: jerasure_invert_matrix (Gauss-Jordan over GF(2^8)).
int gfo_invert(const uint8_t* in, int n, uint8_t* out) {
  std::vector<int> a(in, in + (size_t)n * n);
  std::vector<int> b((size_t)n * n, 0);
  for (int i = 0; i < n; ++i) b[(size_t)i * n + i] = 1;
  auto A = [&](int r, int c) -> int& { return a[(size_t)r * n + c]; };
  auto B = [&](int r, int c) -> int& { return b[(size_t)r * n + c]; };
  for (int i = 0; i < n; ++i) {
    if (A(i, i) == 0) {
      int r = i + 1;
      while (r < n && A(r, i) == 0) ++r;
      if (r == n) return -1;  // singular
      for (int c = 0; c < n; ++c) {
        std::swap(A(i, c), A(r, c));
        std::swap(B(i, c), B(r, c));
      }
    }
    if (A(i, i) != 1) {
      const int pinv = gdiv(1, A(i, i));
      for (int c = 0; c < n; ++c) {
        A(i, c) = gmul(A(i, c), pinv);
        B(i, c) = gmul(B(i, c), pinv);
      }
    }
    for (int r = 0; r < n; ++r) {
      const int f = A(r, i);
      if (r != i && f != 0)
        for (int c = 0; c < n; ++c) {
          A(r, c) ^= gmul(f, A(i, c));
          B(r, c) ^= gmul(f, B(i, c));
        }
    }
  }
  for (size_t i = 0; i < (size_t)n * n; ++i) out[i] = (uint8_t)b[i];
  return 0;
}

// Scalar byte-wise matrix apply: rows x n matrix over chunks [n][len].
void gfo_apply(const uint8_t* mat, int rows, int n, const uint8_t* chunks,
               long len, uint8_t* out) {
  for (int i = 0; i < rows; ++i) {
    uint8_t* dst = out + (size_t)i * len;
    std::memset(dst, 0, (size_t)len);
    for (int j = 0; j < n; ++j) {
      const uint8_t e = mat[i * n + j];
      if (e == 0) continue;
      const uint8_t* src = chunks + (size_t)j * len;
      const uint8_t* mrow = T.mul[e];
      if (e == 1) {
        for (long s = 0; s < len; ++s) dst[s] ^= src[s];
      } else {
        for (long s = 0; s < len; ++s) dst[s] ^= mrow[src[s]];
      }
    }
  }
}

void gfo_encode(const uint8_t* coding, int k, int m, const uint8_t* data,
                long len, uint8_t* parity) {
  gfo_apply(coding, m, k, data, len, parity);
}

// Fast CPU path — the ISA-L analog (reference: src/isa-l ec_encode_data):
// per-(i,j) 4-bit split tables applied 16 bytes at a time with PSHUFB.
#if defined(__SSSE3__)
[[maybe_unused]] static void apply_fast_ssse3(
    const uint8_t* mat, int rows, int n,
    const uint8_t* chunks, long len, uint8_t* out) {
  // Build split tables: lo[b] = e*(b), hi[b] = e*(b<<4) for b in 0..15.
  std::vector<uint8_t> tbl((size_t)rows * n * 32);
  for (int i = 0; i < rows; ++i)
    for (int j = 0; j < n; ++j) {
      uint8_t* t = tbl.data() + ((size_t)i * n + j) * 32;
      const int e = mat[i * n + j];
      for (int b = 0; b < 16; ++b) {
        t[b] = (uint8_t)gmul(e, b);
        t[16 + b] = (uint8_t)gmul(e, b << 4);
      }
    }
  const __m128i mask0f = _mm_set1_epi8(0x0f);
  const long vlen = len & ~15L;
  for (int i = 0; i < rows; ++i) {
    uint8_t* dst = out + (size_t)i * len;
    std::memset(dst, 0, (size_t)len);
    for (int j = 0; j < n; ++j) {
      const int e = mat[i * n + j];
      if (e == 0) continue;
      const uint8_t* src = chunks + (size_t)j * len;
      const uint8_t* t = tbl.data() + ((size_t)i * n + j) * 32;
      const __m128i tlo = _mm_loadu_si128((const __m128i*)t);
      const __m128i thi = _mm_loadu_si128((const __m128i*)(t + 16));
      for (long s = 0; s < vlen; s += 16) {
        const __m128i d = _mm_loadu_si128((const __m128i*)(src + s));
        const __m128i lo = _mm_and_si128(d, mask0f);
        const __m128i hi = _mm_and_si128(_mm_srli_epi64(d, 4), mask0f);
        const __m128i p =
            _mm_xor_si128(_mm_shuffle_epi8(tlo, lo), _mm_shuffle_epi8(thi, hi));
        __m128i acc = _mm_loadu_si128((__m128i*)(dst + s));
        _mm_storeu_si128((__m128i*)(dst + s), _mm_xor_si128(acc, p));
      }
      const uint8_t* mrow = T.mul[e];
      for (long s = vlen; s < len; ++s) dst[s] ^= mrow[src[s]];
    }
  }
}
#endif

#if defined(__AVX2__)
// ISA-L's actual formulation (reference: src/isa-l :: ec_encode_data AVX2
// gf_vect_mad loops): 4-bit split tables broadcast to both 128-bit lanes,
// 32 bytes per VPSHUFB pair.  This is the honest "beat ISA-L" baseline —
// the SSSE3 path above understates what ISA-L reaches on this host.
static void apply_fast_avx2(const uint8_t* mat, int rows, int n,
                            const uint8_t* chunks, long len, uint8_t* out) {
  std::vector<uint8_t> tbl((size_t)rows * n * 32);
  for (int i = 0; i < rows; ++i)
    for (int j = 0; j < n; ++j) {
      uint8_t* t = tbl.data() + ((size_t)i * n + j) * 32;
      const int e = mat[i * n + j];
      for (int b = 0; b < 16; ++b) {
        t[b] = (uint8_t)gmul(e, b);
        t[16 + b] = (uint8_t)gmul(e, b << 4);
      }
    }
  const __m256i mask0f = _mm256_set1_epi8(0x0f);
  const long vlen = len & ~31L;
  for (int i = 0; i < rows; ++i) {
    uint8_t* dst = out + (size_t)i * len;
    std::memset(dst, 0, (size_t)len);
    for (int j = 0; j < n; ++j) {
      const int e = mat[i * n + j];
      if (e == 0) continue;
      const uint8_t* src = chunks + (size_t)j * len;
      const uint8_t* t = tbl.data() + ((size_t)i * n + j) * 32;
      const __m256i tlo = _mm256_broadcastsi128_si256(
          _mm_loadu_si128((const __m128i*)t));
      const __m256i thi = _mm256_broadcastsi128_si256(
          _mm_loadu_si128((const __m128i*)(t + 16)));
      for (long s = 0; s < vlen; s += 32) {
        const __m256i d = _mm256_loadu_si256((const __m256i*)(src + s));
        const __m256i lo = _mm256_and_si256(d, mask0f);
        const __m256i hi =
            _mm256_and_si256(_mm256_srli_epi64(d, 4), mask0f);
        const __m256i p = _mm256_xor_si256(_mm256_shuffle_epi8(tlo, lo),
                                           _mm256_shuffle_epi8(thi, hi));
        __m256i acc = _mm256_loadu_si256((__m256i*)(dst + s));
        _mm256_storeu_si256((__m256i*)(dst + s), _mm256_xor_si256(acc, p));
      }
      const uint8_t* mrow = T.mul[e];
      for (long s = vlen; s < len; ++s) dst[s] ^= mrow[src[s]];
    }
  }
}
#endif

// Returns 2 for AVX2, 1 for SSSE3, 0 for scalar fallback.
int gfo_apply_fast(const uint8_t* mat, int rows, int n, const uint8_t* chunks,
                   long len, uint8_t* out) {
#if defined(__AVX2__)
  apply_fast_avx2(mat, rows, n, chunks, len, out);
  return 2;
#elif defined(__SSSE3__)
  apply_fast_ssse3(mat, rows, n, chunks, len, out);
  return 1;
#else
  gfo_apply(mat, rows, n, chunks, len, out);
  return 0;
#endif
}

int gfo_encode_fast(const uint8_t* coding, int k, int m, const uint8_t* data,
                    long len, uint8_t* parity) {
  return gfo_apply_fast(coding, m, k, data, len, parity);
}

// Decode: rebuild data chunks from the first k available shard rows of the
// systematic generator [I_k ; coding] (jerasure_make_decoding_matrix shape).
int gfo_decode(const uint8_t* coding, int k, int m, const int* avail_rows,
               int n_avail, const uint8_t* shards, long len, uint8_t* data_out) {
  if (n_avail < k) return -1;
  std::vector<uint8_t> sub((size_t)k * k);
  for (int r = 0; r < k; ++r) {
    const int row = avail_rows[r];
    if (row < 0 || row >= k + m) return -2;
    for (int c = 0; c < k; ++c)
      sub[(size_t)r * k + c] =
          (row < k) ? (uint8_t)(row == c ? 1 : 0) : coding[(row - k) * k + c];
  }
  std::vector<uint8_t> dm((size_t)k * k);
  if (gfo_invert(sub.data(), k, dm.data()) != 0) return -3;
  gfo_apply_fast(dm.data(), k, k, shards, len, data_out);
  return 0;
}

}  // extern "C"
