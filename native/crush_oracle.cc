// CRUSH oracle — C++ mirror of the straw2 mapper, third implementation for
// bit-exactness voting and the CPU maps/s baseline (BASELINE.json config 5).
//
// Plays the role of the reference's native mapper (reference:
// src/crush/mapper.c :: crush_do_rule, crush_choose_firstn,
// crush_choose_indep, bucket_straw2_choose, is_out; src/crush/hash.c).
// Semantics are the modern-tunables subset documented in
// ceph_tpu/crush/reference_mapper.py; the three implementations (Python
// scalar, JAX batch, this) must agree bit-for-bit.
//
// Uses the generated crush_tables.h (emitted by ceph_tpu/crush/ln_table.py)
// so the fixed-point log table is byte-identical across all implementations.

#include <algorithm>
#include <cstdint>
#include <cstring>
#include <vector>

#include "crush_tables.h"

namespace {

constexpr int64_t LN_BIAS = 0x1000000000000LL;
constexpr int32_t ITEM_NONE_V = -0x7FFFFFFE;
constexpr uint32_t SEED = 1315423911u;

#define MIX(a, b, c)      \
  do {                    \
    a = a - b;  a = a - c;  a = a ^ (c >> 13); \
    b = b - c;  b = b - a;  b = b ^ (a << 8);  \
    c = c - a;  c = c - b;  c = c ^ (b >> 13); \
    a = a - b;  a = a - c;  a = a ^ (c >> 12); \
    b = b - c;  b = b - a;  b = b ^ (a << 16); \
    c = c - a;  c = c - b;  c = c ^ (b >> 5);  \
    a = a - b;  a = a - c;  a = a ^ (c >> 3);  \
    b = b - c;  b = b - a;  b = b ^ (a << 10); \
    c = c - a;  c = c - b;  c = c ^ (b >> 15); \
  } while (0)

uint32_t hash3(uint32_t a, uint32_t b, uint32_t c) {
  uint32_t h = SEED ^ a ^ b ^ c;
  uint32_t x = 231232u, y = 1232u;
  MIX(a, b, h);
  MIX(c, x, h);
  MIX(y, a, h);
  MIX(b, x, h);
  MIX(y, c, h);
  return h;
}

uint32_t hash4(uint32_t a, uint32_t b, uint32_t c, uint32_t d) {
  // hash.c :: crush_hash32_rjenkins1_4 (must match crush/hash.py)
  uint32_t h = SEED ^ a ^ b ^ c ^ d;
  uint32_t x = 231232u, y = 1232u;
  MIX(a, b, h);
  MIX(c, d, h);
  MIX(a, x, h);
  MIX(y, b, h);
  MIX(c, x, h);
  MIX(y, d, h);
  return h;
}

uint32_t hash2(uint32_t a, uint32_t b) {
  uint32_t h = SEED ^ a ^ b;
  uint32_t x = 231232u, y = 1232u;
  MIX(a, b, h);
  MIX(x, a, h);
  MIX(b, y, h);
  return h;
}

struct Map {
  const int32_t* items;    // [n_buckets * max_size]
  const int64_t* weights;  // [n_buckets * max_size] 16.16
  const int32_t* sizes;    // [n_buckets]
  const int32_t* types;    // [n_buckets]
  int n_buckets;
  int max_size;
  // legacy bucket algorithms (crush.h CRUSH_BUCKET_*): null = all straw2
  const int32_t* algs = nullptr;          // [n_buckets]
  const int64_t* straws = nullptr;        // [n_buckets * max_size] 16.16
  const int64_t* node_weights = nullptr;  // [n_buckets * max_nodes]
  int max_nodes = 0;
  // TRUE per-bucket node counts (len of the bucket's node_weights) —
  // an ingested tree bucket's structural count is authoritative; the
  // size-derived fallback below only serves legacy callers (r4 verdict
  // #5: pass true counts instead of reconstructing)
  const int32_t* num_nodes = nullptr;  // [n_buckets] or null
  const uint32_t* weightvec;  // [n_devices] device reweights 16.16
  int n_devices;
  // choose_args weight-set (crush_choose_arg_map analog):
  // [positions * n_buckets * max_size] or null; position clamps to the
  // last row (get_choose_arg_weights)
  const int64_t* cweights;
  int positions;

  const int64_t* bucket_weights(int bucket_idx, int position) const {
    if (!cweights) return weights + (size_t)bucket_idx * max_size;
    int p = position < positions ? position : positions - 1;
    return cweights +
           ((size_t)p * n_buckets + bucket_idx) * max_size;
  }

  int item_type(int item) const {
    if (item >= 0) return 0;
    const int idx = -1 - item;
    if (idx >= n_buckets) return 0;
    return types[idx];
  }
};

int64_t div_trunc(int64_t a, int64_t b) { return a / b; }  // C is truncating

// reference: crush_work_bucket — per-do_rule scratch holding uniform
// buckets' lazily built permutations (the cache is SEMANTIC: mixing r
// values for one x must walk one permutation, r==0 shortcut included)
struct PermWork {
  std::vector<int32_t> perm;
  std::vector<uint32_t> perm_x;
  std::vector<uint32_t> perm_n;
  std::vector<uint8_t> fresh;
  int max_size = 0;
  void init(int n_buckets, int ms) {
    max_size = ms;
    perm.assign((size_t)n_buckets * ms, 0);
    perm_x.assign(n_buckets, 0);
    perm_n.assign(n_buckets, 0);
    fresh.assign(n_buckets, 1);
  }
  void reset() {
    std::fill(fresh.begin(), fresh.end(), 1);
  }
};

// mapper.c :: bucket_perm_choose (uniform buckets)
int uniform_choose(const Map& m, PermWork& work, int bucket_idx, uint32_t x,
                   uint32_t r) {
  const int size = m.sizes[bucket_idx];
  const int32_t* items = m.items + (size_t)bucket_idx * m.max_size;
  const int32_t bid = -1 - bucket_idx;
  const unsigned pr = r % (unsigned)size;
  int32_t* perm = work.perm.data() + (size_t)bucket_idx * work.max_size;
  if (work.fresh[bucket_idx] || work.perm_x[bucket_idx] != x ||
      work.perm_n[bucket_idx] == 0) {
    work.fresh[bucket_idx] = 0;
    work.perm_x[bucket_idx] = x;
    if (pr == 0) {
      const unsigned s0 = hash3(x, (uint32_t)bid, 0) % (unsigned)size;
      perm[0] = (int32_t)s0;
      work.perm_n[bucket_idx] = 0xffff;  // magic: only slot 0 is real
      return items[s0];
    }
    for (int i = 0; i < size; ++i) perm[i] = i;
    work.perm_n[bucket_idx] = 0;
  } else if (work.perm_n[bucket_idx] == 0xffff) {
    // clean up after the r==0 shortcut
    const int32_t s0 = perm[0];
    for (int i = 0; i < size; ++i) perm[i] = i;
    perm[0] = s0;
    perm[s0] = 0;
    work.perm_n[bucket_idx] = 1;
  }
  while (work.perm_n[bucket_idx] <= pr) {
    const unsigned p = work.perm_n[bucket_idx];
    if ((int)p < size - 1) {
      const unsigned i = hash3(x, (uint32_t)bid, p) % (unsigned)(size - p);
      if (i) {
        const int32_t t = perm[p + i];
        perm[p + i] = perm[p];
        perm[p] = t;
      }
    }
    work.perm_n[bucket_idx]++;
  }
  return items[perm[pr]];
}

// mapper.c :: bucket_list_choose — tail-first cumulative-weight race
int list_choose(const Map& m, int bucket_idx, uint32_t x, uint32_t r) {
  const int size = m.sizes[bucket_idx];
  const int32_t* items = m.items + (size_t)bucket_idx * m.max_size;
  const int64_t* weights = m.weights + (size_t)bucket_idx * m.max_size;
  const int32_t bid = -1 - bucket_idx;
  std::vector<int64_t> sums((size_t)size);
  int64_t cum = 0;
  for (int i = 0; i < size; ++i) {
    cum += weights[i];
    sums[i] = cum;
  }
  for (int i = size - 1; i >= 0; --i) {
    uint64_t w = hash4(x, (uint32_t)items[i], r, (uint32_t)bid) & 0xffff;
    w = (w * (uint64_t)sums[i]) >> 16;
    if ((int64_t)w < weights[i]) return items[i];
  }
  return items[0];  // "bad list sums" fallback
}

// mapper.c :: bucket_tree_choose — implicit binary tree descent
int tree_choose(const Map& m, int bucket_idx, uint32_t x, uint32_t r) {
  const int32_t* items = m.items + (size_t)bucket_idx * m.max_size;
  const int64_t* nodes = m.node_weights + (size_t)bucket_idx * m.max_nodes;
  const int32_t bid = -1 - bucket_idx;
  // the bucket's own num_nodes is structural — the smallest power of
  // two covering 2*size leaf slots (builder.c crush_make_tree_bucket) —
  // so the root is num_nodes >> 1 exactly as mapper.c starts, with no
  // zero-weight collapse (advisor r3).  A weighted descent never lands
  // on an empty leaf (t < w and the left subtree carries all the weight
  // when the right is empty); only an ALL-ZERO tree descends right into
  // padding, where upstream reads out of bounds — pin that degenerate
  // case to the last real item instead of padding (which aliased a
  // bucket id and cycled forever).
  const int size = m.sizes[bucket_idx];
  int nn;
  if (m.num_nodes && m.num_nodes[bucket_idx] > 1) {
    nn = m.num_nodes[bucket_idx];
  } else {
    nn = 2;
    while (nn < 2 * size) nn <<= 1;
  }
  int n = nn >> 1;
  while (!(n & 1)) {
    const uint64_t w = (uint64_t)nodes[n];
    const uint64_t t =
        ((uint64_t)hash4(x, (uint32_t)n, r, (uint32_t)bid) * w) >> 32;
    const int h = (n & -n) >> 1;
    const int left = n - h;
    n = ((int64_t)t < nodes[left]) ? left : n + h;
  }
  const int leaf = n >> 1;
  return items[leaf < size ? leaf : size - 1];
}

// mapper.c :: bucket_straw_choose — hashed draw times build-time straw
int straw_choose(const Map& m, int bucket_idx, uint32_t x, uint32_t r) {
  const int size = m.sizes[bucket_idx];
  const int32_t* items = m.items + (size_t)bucket_idx * m.max_size;
  const int64_t* straws = m.straws + (size_t)bucket_idx * m.max_size;
  int high = 0;
  int64_t high_draw = 0;
  for (int i = 0; i < size; ++i) {
    const int64_t draw =
        (int64_t)(hash3(x, (uint32_t)items[i], r) & 0xffff) * straws[i];
    if (i == 0 || draw > high_draw) {
      high = i;
      high_draw = draw;
    }
  }
  return items[high];
}

int straw2_choose(const Map& m, int bucket_idx, uint32_t x, uint32_t r,
                  int position) {
  if (bucket_idx < 0 || bucket_idx >= m.n_buckets) return ITEM_NONE_V;
  const int size = m.sizes[bucket_idx];
  if (size == 0) return ITEM_NONE_V;
  const int32_t* items = m.items + (size_t)bucket_idx * m.max_size;
  const int64_t* weights = m.bucket_weights(bucket_idx, position);
  int high = 0;
  int64_t high_draw = 0;
  for (int i = 0; i < size; ++i) {
    int64_t draw;
    if (weights[i]) {
      const uint32_t u = hash3(x, (uint32_t)items[i], r) & 0xffff;
      const int64_t ln = CRUSH_LN_TABLE[u] - LN_BIAS;
      draw = div_trunc(ln, weights[i]);
    } else {
      draw = INT64_MIN;
    }
    if (i == 0 || draw > high_draw) {
      high = i;
      high_draw = draw;
    }
  }
  return items[high];
}

int bucket_choose(const Map& m, PermWork& work, int bucket_idx, uint32_t x,
                  uint32_t r, int position) {
  if (bucket_idx < 0 || bucket_idx >= m.n_buckets) return ITEM_NONE_V;
  if (m.sizes[bucket_idx] == 0) return ITEM_NONE_V;
  const int alg = m.algs ? m.algs[bucket_idx] : 5;
  switch (alg) {
    case 1: return uniform_choose(m, work, bucket_idx, x, r);
    case 2: return list_choose(m, bucket_idx, x, r);
    case 3: return tree_choose(m, bucket_idx, x, r);
    case 4: return straw_choose(m, bucket_idx, x, r);
    default: return straw2_choose(m, bucket_idx, x, r, position);
  }
}

bool is_out(const Map& m, int item, uint32_t x) {
  if (item >= m.n_devices) return true;
  const uint32_t w = m.weightvec[item];
  if (w >= 0x10000u) return false;
  if (w == 0) return true;
  return (hash2(x, (uint32_t)item) & 0xffff) >= w;
}

int descend(const Map& m, PermWork& work, int root, uint32_t x, uint32_t r,
            int want_type, int position) {
  int item = root;
  while (item < 0 && item != ITEM_NONE_V && m.item_type(item) != want_type)
    item = bucket_choose(m, work, -1 - item, x, r, position);
  // a device of the wrong type is a dead end (mapper.c "bad item type")
  if (want_type != 0 && item >= 0) return ITEM_NONE_V;
  return item;
}

// crush_choose_firstn, modern tunables (stable=1, vary_r=1, local retries 0)
int choose_firstn(const Map& m, PermWork& work, int root, uint32_t x,
                  int numrep, int want_type, int tries, bool recurse,
                  int recurse_tries, int32_t* out, int32_t* out2) {
  int outpos = 0;
  for (int rep = 0; rep < numrep; ++rep) {
    bool done = false;
    int item = ITEM_NONE_V, leaf = ITEM_NONE_V;
    for (int ftotal = 0; ftotal < tries && !done; ++ftotal) {
      const uint32_t r = (uint32_t)(rep + ftotal);
      const int cand = descend(m, work, root, x, r, want_type, outpos);
      if (cand == ITEM_NONE_V) continue;
      bool collide = false;
      for (int i = 0; i < outpos; ++i)
        if (out[i] == cand) { collide = true; break; }
      if (collide) continue;
      if (recurse && cand < 0) {
        // nested chooseleaf: one rep, r' = sub_r + f, collide vs out2
        bool lok = false;
        int lf_leaf = ITEM_NONE_V;
        for (int lf = 0; lf < recurse_tries && !lok; ++lf) {
          const int l =
              descend(m, work, cand, x, r + (uint32_t)lf, 0, outpos);
          if (l < 0) continue;
          bool lcol = false;
          for (int i = 0; i < outpos; ++i)
            if (out2[i] == l) { lcol = true; break; }
          if (lcol || is_out(m, l, x)) continue;
          lok = true;
          lf_leaf = l;
        }
        if (!lok) continue;
        item = cand;
        leaf = lf_leaf;
        done = true;
      } else {
        if (cand >= 0 && is_out(m, cand, x)) continue;
        if (recurse && cand >= 0 && is_out(m, cand, x)) continue;
        item = cand;
        leaf = cand;
        done = true;
      }
    }
    if (!done) continue;
    out[outpos] = item;
    out2[outpos] = leaf;
    ++outpos;
  }
  return outpos;
}

// crush_choose_indep: positional retries r = rep + numrep*ftotal
void choose_indep(const Map& m, PermWork& work, int root, uint32_t x,
                  int numrep, int want_type, int tries, bool recurse,
                  int recurse_tries, int32_t* out, int32_t* out2) {
  for (int i = 0; i < numrep; ++i) out[i] = out2[i] = ITEM_NONE_V;
  bool placed[64] = {false};
  for (int ftotal = 0; ftotal < tries; ++ftotal) {
    for (int rep = 0; rep < numrep; ++rep) {
      if (placed[rep]) continue;
      const uint32_t r = (uint32_t)(rep + numrep * ftotal);
      // weight-set position: the choose's outpos (0 at top level);
      // only the leaf recursion, whose outpos is rep, varies by shard
      const int cand =
          descend(m, work, root, x, r, want_type, /*position=*/0);
      if (cand == ITEM_NONE_V) {
        // structural dead end: permanent NONE (crush_choose_indep keeps the
        // position at CRUSH_ITEM_NONE and never retries it)
        placed[rep] = true;
        continue;
      }
      bool collide = false;
      for (int i = 0; i < numrep; ++i)
        if (placed[i] && out[i] == cand) { collide = true; break; }
      if (collide) continue;
      int leaf = cand;
      if (recurse && cand < 0) {
        bool lok = false;
        for (int lf = 0; lf < recurse_tries && !lok; ++lf) {
          const int l = descend(
              m, work, cand, x, (uint32_t)(rep + numrep * lf) + r, 0, rep);
          if (l < 0) continue;
          if (is_out(m, l, x)) continue;
          lok = true;
          leaf = l;
        }
        if (!lok) continue;
      } else if (cand >= 0) {
        if (is_out(m, cand, x)) continue;
      } else if (!recurse) {
        // bucket of wanted type without recursion: accepted as-is
      }
      out[rep] = cand;
      out2[rep] = leaf;
      placed[rep] = true;
    }
  }
}

}  // namespace

extern "C" {

// Batched do_rule for a single-choose rule plan (see
// ceph_tpu/crush/mapper.py :: compile_rule).  out is [n_x * want], filled
// with OSD ids / ITEM_NONE.  Returns 0, or -1 on bad args.
int cro_do_rule_batch(const int32_t* items, const int64_t* weights,
                      const int32_t* sizes, const int32_t* types,
                      int n_buckets, int max_size, int take, int want,
                      int want_type, int firstn, int recurse, int tries,
                      int recurse_tries, const uint32_t* xs, long n_x,
                      const uint32_t* weightvec, int n_devices,
                      const int64_t* cweights, int positions,
                      const int32_t* algs, const int64_t* straws,
                      const int64_t* node_weights, int max_nodes,
                      const int32_t* num_nodes, int32_t* out) {
  if (want <= 0 || want > 64) return -1;
  if (cweights && positions <= 0) return -1;
  Map m{items,     weights,  sizes,     types,        n_buckets,
        max_size,  algs,     straws,    node_weights, max_nodes,
        num_nodes, weightvec, n_devices, cweights, positions};
  PermWork work;
  work.init(n_buckets, max_size);
  int32_t buf[64], buf2[64];
  for (long i = 0; i < n_x; ++i) {
    const uint32_t x = xs[i];
    work.reset();  // crush_work is per do_rule invocation
    int32_t* dst = out + (size_t)i * want;
    if (firstn) {
      for (int j = 0; j < want; ++j) buf[j] = buf2[j] = ITEM_NONE_V;
      const int n = choose_firstn(m, work, take, x, want, want_type, tries,
                                  recurse != 0, recurse_tries, buf, buf2);
      for (int j = 0; j < want; ++j)
        dst[j] = (j < n) ? (recurse ? buf2[j] : buf[j]) : ITEM_NONE_V;
    } else {
      choose_indep(m, work, take, x, want, want_type, tries, recurse != 0,
                   recurse_tries, buf, buf2);
      for (int j = 0; j < want; ++j) dst[j] = recurse ? buf2[j] : buf[j];
    }
  }
  return 0;
}

// Batched do_rule over an arbitrary step plan — the crush_do_rule
// working-vector loop (reference: src/crush/mapper.c :: crush_do_rule's
// step switch).  steps is [n_steps * 3] of (op, arg1, arg2) with op codes
// matching ceph_tpu/crush/types.py :: RuleOp (crush.h codes): 1=TAKE,
// 2=CHOOSE_FIRSTN, 3=CHOOSE_INDEP, 4=EMIT, 6=CHOOSELEAF_FIRSTN,
// 7=CHOOSELEAF_INDEP, 8=SET_CHOOSE_TRIES, 9=SET_CHOOSELEAF_TRIES.
// out is [n_x * numrep].
int cro_do_rule_steps(const int32_t* items, const int64_t* weights,
                      const int32_t* sizes, const int32_t* types,
                      int n_buckets, int max_size, const int32_t* steps,
                      int n_steps, int numrep, int default_tries,
                      const uint32_t* xs, long n_x,
                      const uint32_t* weightvec, int n_devices,
                      const int64_t* cweights, int positions,
                      const int32_t* algs, const int64_t* straws,
                      const int64_t* node_weights, int max_nodes,
                      const int32_t* num_nodes, int32_t* out) {
  if (numrep <= 0 || numrep > 64) return -1;
  if (cweights && positions <= 0) return -1;
  Map m{items,     weights,  sizes,     types,        n_buckets,
        max_size,  algs,     straws,    node_weights, max_nodes,
        num_nodes, weightvec, n_devices, cweights, positions};
  PermWork work;
  work.init(n_buckets, max_size);
  for (long i = 0; i < n_x; ++i) {
    const uint32_t x = xs[i];
    work.reset();
    int32_t* dst = out + (size_t)i * numrep;
    int32_t working[256];
    int wsize = 0;
    int32_t result[256];
    int rsize = 0;
    int choose_tries = default_tries;
    int chooseleaf_tries = 0;
    for (int s = 0; s < n_steps; ++s) {
      const int op = steps[3 * s], a1 = steps[3 * s + 1],
                a2 = steps[3 * s + 2];
      if (op == 1) {  // TAKE
        working[0] = a1;
        wsize = 1;
      } else if (op == 8) {
        choose_tries = a1;
      } else if (op == 9) {
        chooseleaf_tries = a1;
      } else if (op == 2 || op == 3 || op == 6 || op == 7) {  // CHOOSE*
        const bool firstn = (op == 2 || op == 6);
        const bool recurse = (op == 6 || op == 7);
        int want = a1 > 0 ? a1 : numrep + a1;
        if (want <= 0 || want > 64) return -1;
        int32_t nw[256];
        int nwsize = 0;
        for (int wi = 0; wi < wsize; ++wi) {
          const int parent = working[wi];
          if (parent >= 0 || parent == ITEM_NONE_V) {
            // not a bucket: nothing to choose from (the batched mapper
            // emits NONEs here; firstn packs them away, indep keeps
            // positional holes)
            if (!firstn)
              for (int j = 0; j < want && nwsize < 256; ++j)
                nw[nwsize++] = ITEM_NONE_V;
            continue;
          }
          int32_t buf[64], buf2[64];
          for (int j = 0; j < want; ++j) buf[j] = buf2[j] = ITEM_NONE_V;
          if (firstn) {
            const int rt = chooseleaf_tries ? chooseleaf_tries
                                            : choose_tries;
            const int n = choose_firstn(m, work, parent, x, want, a2,
                                        choose_tries, recurse,
                                        recurse ? rt : choose_tries, buf,
                                        buf2);
            for (int j = 0; j < n && nwsize < 256; ++j)
              nw[nwsize++] = recurse ? buf2[j] : buf[j];
          } else {
            choose_indep(m, work, parent, x, want, a2, choose_tries,
                         recurse, chooseleaf_tries ? chooseleaf_tries : 1,
                         buf, buf2);
            for (int j = 0; j < want && nwsize < 256; ++j)
              nw[nwsize++] = recurse ? buf2[j] : buf[j];
          }
        }
        std::memcpy(working, nw, nwsize * sizeof(int32_t));
        wsize = nwsize;
      } else if (op == 4) {  // EMIT
        for (int j = 0; j < wsize && rsize < 256; ++j)
          result[rsize++] = working[j];
        wsize = 0;
      } else {
        return -1;
      }
    }
    // un-emitted working items are DROPPED (mapper.c: only EMIT moves
    // results out), matching the scalar and batch interpreters
    for (int j = 0; j < numrep; ++j)
      dst[j] = (j < rsize) ? result[j] : ITEM_NONE_V;
  }
  return 0;
}

uint32_t cro_hash3(uint32_t a, uint32_t b, uint32_t c) { return hash3(a, b, c); }
uint32_t cro_hash2(uint32_t a, uint32_t b) { return hash2(a, b); }
int64_t cro_ln(uint32_t u) { return CRUSH_LN_TABLE[u & 0xffff]; }
void cro_ln_table(int64_t* out) {
  std::memcpy(out, CRUSH_LN_TABLE, sizeof(CRUSH_LN_TABLE));
}

}  // extern "C"
