// Block allocator for the BlueStore-analog backend (reference role:
// src/os/bluestore/BitmapAllocator.cc / AvlAllocator.cc — the component
// BlueStore uses to carve its raw block device; SURVEY.md §2.4).
//
// Design: a word-packed free bitmap (1 = free) with a next-fit cursor.
// allocate() returns up to max_extents (start, len) runs, preferring one
// contiguous run but falling back to fragmented harvesting exactly like
// the reference's allocators under fragmentation.  C ABI via ctypes; the
// Python side (ceph_tpu/store/alloc.py) carries a pure-Python fallback
// with identical behavior for hosts without the built .so.
#include <cstdint>
#include <cstdlib>
#include <cstring>

namespace {

struct Allocator {
  uint64_t n_blocks;
  uint64_t n_words;
  uint64_t cursor;   // next-fit hint (block index)
  uint64_t n_free;
  uint64_t* bits;    // 1 = free
};

inline bool get_bit(const Allocator* a, uint64_t i) {
  return (a->bits[i >> 6] >> (i & 63)) & 1;
}
inline void set_bit(Allocator* a, uint64_t i, bool v) {
  if (v)
    a->bits[i >> 6] |= (1ull << (i & 63));
  else
    a->bits[i >> 6] &= ~(1ull << (i & 63));
}

}  // namespace

extern "C" {

void* ctpu_alloc_create(uint64_t n_blocks) {
  auto* a = static_cast<Allocator*>(std::malloc(sizeof(Allocator)));
  if (!a) return nullptr;
  a->n_blocks = n_blocks;
  a->n_words = (n_blocks + 63) / 64;
  a->cursor = 0;
  a->n_free = n_blocks;
  a->bits = static_cast<uint64_t*>(std::malloc(a->n_words * 8));
  if (!a->bits) {
    std::free(a);
    return nullptr;
  }
  std::memset(a->bits, 0xff, a->n_words * 8);
  // clear the tail past n_blocks so word scans never see ghost blocks
  for (uint64_t i = n_blocks; i < a->n_words * 64; i++) set_bit(a, i, false);
  return a;
}

void ctpu_alloc_destroy(void* h) {
  auto* a = static_cast<Allocator*>(h);
  if (!a) return;
  std::free(a->bits);
  std::free(a);
}

uint64_t ctpu_alloc_free_blocks(void* h) {
  return static_cast<Allocator*>(h)->n_free;
}

// Mark [start, start+len) used (0) or free (1).  Returns 0, or -1 on
// out-of-range.  Double-free / double-use are accepted idempotently (the
// mount-time freelist rebuild marks extents in arbitrary order).
int ctpu_alloc_mark(void* h, uint64_t start, uint64_t len, int free_) {
  auto* a = static_cast<Allocator*>(h);
  if (start + len > a->n_blocks) return -1;
  for (uint64_t i = start; i < start + len; i++) {
    bool cur = get_bit(a, i);
    if (cur != (free_ != 0)) {
      set_bit(a, i, free_ != 0);
      a->n_free += free_ ? 1 : -1;
    }
  }
  return 0;
}

// Allocate `want` blocks as up to max_extents (start, len) runs written
// into out[2*i], out[2*i+1].  Next-fit from the cursor, wrapping once.
// Returns the number of extents, or -1 if the space or the extent budget
// cannot satisfy the request (nothing is allocated on failure).
int ctpu_alloc_allocate(void* h, uint64_t want, uint64_t* out,
                        int max_extents) {
  auto* a = static_cast<Allocator*>(h);
  if (want == 0) return 0;
  if (want > a->n_free) return -1;
  int n_ext = 0;
  uint64_t got = 0;
  uint64_t pos = a->cursor % (a->n_blocks ? a->n_blocks : 1);
  uint64_t scanned = 0;
  while (got < want && scanned < a->n_blocks) {
    // skip used region (word-at-a-time when aligned and fully used)
    while (scanned < a->n_blocks && !get_bit(a, pos)) {
      if ((pos & 63) == 0 && a->bits[pos >> 6] == 0 &&
          pos + 64 <= a->n_blocks && scanned + 64 <= a->n_blocks) {
        pos += 64;
        scanned += 64;
      } else {
        pos++;
        scanned++;
      }
      if (pos >= a->n_blocks) pos = 0;
    }
    if (scanned >= a->n_blocks) break;
    // harvest a free run
    uint64_t run_start = pos;
    uint64_t run_len = 0;
    while (scanned < a->n_blocks && got + run_len < want &&
           pos < a->n_blocks && get_bit(a, pos)) {
      run_len++;
      pos++;
      scanned++;
    }
    if (run_len) {
      if (n_ext >= max_extents) return -1;  // nothing committed yet
      out[2 * n_ext] = run_start;
      out[2 * n_ext + 1] = run_len;
      n_ext++;
      got += run_len;
    }
    if (pos >= a->n_blocks) pos = 0;
  }
  if (got < want) return -1;
  // commit: clear the bits
  for (int e = 0; e < n_ext; e++)
    for (uint64_t i = out[2 * e]; i < out[2 * e] + out[2 * e + 1]; i++)
      set_bit(a, i, false);
  a->n_free -= want;
  a->cursor = pos;
  return n_ext;
}

}  // extern "C"
