// crc32c (Castagnoli) — the checksum used across the reference's runtime
// (reference: src/common/crc32c.cc :: ceph_crc32c, with SSE4.2/armv8
// hardware paths under src/common/crc32c_intel_fast.c).  Convention matches
// the reference: caller passes the running crc (seed, typically ~0u) and no
// final inversion is applied — the hardware crc32 instruction implements
// exactly this reflected-CRC32C update.
//
// Consumers: bufferlist::crc32c, store checksums, messenger frame crcs
// (ceph_tpu/common/buffer.py, ceph_tpu/os/, ceph_tpu/msg/).

#include <cstddef>
#include <cstdint>

#if defined(__SSE4_2__)
#include <nmmintrin.h>
#endif

namespace {

// Software fallback: standard reflected table for poly 0x1EDC6F41
// (reflected form 0x82F63B78), built once at load.
struct SwTables {
  uint32_t t[8][256];
  SwTables() {
    for (uint32_t i = 0; i < 256; i++) {
      uint32_t c = i;
      for (int k = 0; k < 8; k++) c = (c & 1) ? 0x82F63B78u ^ (c >> 1) : c >> 1;
      t[0][i] = c;
    }
    for (uint32_t i = 0; i < 256; i++)
      for (int s = 1; s < 8; s++)
        t[s][i] = (t[s - 1][i] >> 8) ^ t[0][t[s - 1][i] & 0xff];
  }
};
const SwTables tables;

uint32_t crc32c_sw(uint32_t crc, const uint8_t* p, size_t len) {
  // slicing-by-8
  while (len >= 8) {
    crc ^= (uint32_t)p[0] | ((uint32_t)p[1] << 8) | ((uint32_t)p[2] << 16) |
           ((uint32_t)p[3] << 24);
    crc = tables.t[7][crc & 0xff] ^ tables.t[6][(crc >> 8) & 0xff] ^
          tables.t[5][(crc >> 16) & 0xff] ^ tables.t[4][crc >> 24] ^
          tables.t[3][p[4]] ^ tables.t[2][p[5]] ^ tables.t[1][p[6]] ^
          tables.t[0][p[7]];
    p += 8;
    len -= 8;
  }
  while (len--) crc = tables.t[0][(crc ^ *p++) & 0xff] ^ (crc >> 8);
  return crc;
}

}  // namespace

extern "C" uint32_t ceph_tpu_crc32c(uint32_t crc, const uint8_t* data,
                                    size_t len) {
#if defined(__SSE4_2__)
  const uint8_t* p = data;
  while (len && ((uintptr_t)p & 7)) {
    crc = _mm_crc32_u8(crc, *p++);
    len--;
  }
  uint64_t c64 = crc;
  while (len >= 8) {
    c64 = _mm_crc32_u64(c64, *(const uint64_t*)p);
    p += 8;
    len -= 8;
  }
  crc = (uint32_t)c64;
  while (len--) crc = _mm_crc32_u8(crc, *p++);
  return crc;
#else
  return crc32c_sw(crc, data, len);
#endif
}

// Exposed so tests can cross-check the hardware path against the table path.
extern "C" uint32_t ceph_tpu_crc32c_sw(uint32_t crc, const uint8_t* data,
                                       size_t len) {
  return crc32c_sw(crc, data, len);
}
