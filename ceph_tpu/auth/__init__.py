"""Authentication (reference: src/auth — cephx; SURVEY.md §2.7)."""
from .cephx import AuthError, CephxAuthenticator, generate_secret

__all__ = ["AuthError", "CephxAuthenticator", "generate_secret"]
