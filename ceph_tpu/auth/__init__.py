"""Authentication (reference: src/auth — cephx; SURVEY.md §2.7)."""
from .cephx import (
    AuthError,
    CephxAuthenticator,
    derive_s3_secret,
    derive_service_key,
    frame_tag,
    generate_secret,
    mint_ticket,
    proof_hex,
    seal,
    session_key_from_nonces,
    unseal,
    validate_ticket,
)

__all__ = [
    "AuthError",
    "CephxAuthenticator",
    "derive_s3_secret",
    "derive_service_key",
    "frame_tag",
    "generate_secret",
    "mint_ticket",
    "proof_hex",
    "seal",
    "session_key_from_nonces",
    "unseal",
    "validate_ticket",
]
