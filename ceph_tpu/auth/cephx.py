"""cephx-style mutual authentication for messenger connections.

Reference: src/auth/cephx (CephxProtocol.h: challenge/proof exchange with
HMAC over a shared secret; src/msg ProtocolV2's auth frames carry it).

Two credential modes, mirroring the reference's split between
intra-cluster keys and mon-brokered service tickets:

- Shared-secret peers (daemons, admin clients holding the keyring): the
  wire exchange (server challenge -> client proof + counter-challenge ->
  server proof) matches CephxProtocol's session-key handshake; the
  per-connection frame key is derived from both nonces
  (`session_key_from_nonces`).
- Ticket clients (no cluster secret): the mon mints a per-service ticket
  (`auth get-ticket` -> `mint_ticket`); the client presents the sealed
  blob and proves possession of the session key inside it; the serving
  daemon opens the blob with its DERIVED service key at the OSDMap's
  current auth generation (`validate_ticket`), so `auth rotate` cuts
  stale tickets off cluster-wide through the normal map-propagation path
  (the CephxKeyServer rotating_secrets role).

Wire form (one line each, after the messenger banner/ident):

    S->C  auth-challenge <snonce-hex> <service>
    C->S  auth-proof <hmac-hex> <cnonce-hex>            (secret holders)
    C->S  auth-ticket <blob-hex> <hmac-hex> <cnonce-hex>  (ticket clients)
    S->C  auth-ok <hmac-hex>

proofs: HMAC-SHA256(key, nonce || peer-entity-name), key = cluster
secret or the ticket session key.  After an authenticated handshake
EVERY frame carries a 16-byte HMAC tag over (per-direction counter ||
body) under the negotiated session key (`frame_tag`) — the ProtocolV2
signed-frames role; a bad tag is connection-fatal.  A server with auth
disabled sends no challenge (wire-compatible with unauthenticated
peers); a client expecting auth then times out — the same hard failure a
cephx-required cluster gives unauthenticated clients.
"""
from __future__ import annotations

import base64
import hashlib
import hmac
import json as _json
import os
import struct as _struct
import time as _time


class AuthError(Exception):
    pass


def generate_secret() -> str:
    """A fresh base64 cluster secret (`ceph-authtool --gen-key` analog)."""
    return base64.b64encode(os.urandom(32)).decode()


def proof_hex(key: bytes, nonce_hex: str, name: str) -> str:
    """HMAC(key, nonce || name) — the handshake proof shape, shared by the
    shared-secret and ticket-session-key flows."""
    return hmac.new(
        key, bytes.fromhex(nonce_hex) + name.encode(), hashlib.sha256
    ).hexdigest()


def session_key_from_nonces(secret: bytes, snonce_hex: str,
                            cnonce_hex: str) -> bytes:
    """Per-connection frame-signing key for two shared-secret holders —
    both sides saw both handshake nonces, so both derive it without an
    extra round trip (the role CephxProtocol's session_key plays for
    intra-cluster peers)."""
    return hmac.new(
        secret,
        b"sess:" + bytes.fromhex(snonce_hex) + bytes.fromhex(cnonce_hex),
        hashlib.sha256,
    ).digest()


def frame_tag(key: bytes, ctr: int, body: bytes) -> bytes:
    """16-byte per-frame auth tag: HMAC(session key, counter || body).
    The counter is per-direction, per-socket-incarnation, so a frame can
    be neither tampered with nor replayed/reordered within a session
    (reference: ProtocolV2 signed frames' rx/tx segment signatures)."""
    return hmac.new(
        key, _struct.pack("<Q", ctr) + body, hashlib.sha256
    ).digest()[:16]


class CephxAuthenticator:
    """Per-messenger auth engine; stateless besides the secret."""

    def __init__(self, secret_b64: str):
        try:
            self._secret = base64.b64decode(secret_b64.encode(), validate=True)
        except Exception as e:
            raise AuthError(f"bad auth_shared_secret: {e}") from e
        if len(self._secret) < 16:
            raise AuthError("auth_shared_secret shorter than 16 bytes")

    @property
    def secret(self) -> bytes:
        return self._secret

    def make_nonce(self) -> str:
        return os.urandom(16).hex()

    def proof(self, nonce_hex: str, name: str) -> str:
        return proof_hex(self._secret, nonce_hex, name)

    def verify(self, nonce_hex: str, name: str, proof_hex_: str) -> bool:
        return hmac.compare_digest(self.proof(nonce_hex, name), proof_hex_)

    def session_key(self, snonce_hex: str, cnonce_hex: str) -> bytes:
        return session_key_from_nonces(self._secret, snonce_hex, cnonce_hex)


# -- tickets (reference: src/auth/cephx CephxKeyServer / CephXTicketBlob) --
#
# Service keys are DERIVED, not distributed: key(service, gen) =
# HMAC(cluster-secret, "svc:{service}:{gen}").  The current generation per
# service lives in the OSDMap (OSDMap.auth_gens), so `auth rotate` is a
# map change that reaches every daemon through the normal paxos/subscribe
# path — the role CephxKeyServer's rotating_secrets distribution plays.
# Daemons accept {gen, gen-1} (the reference keeps the previous rotating
# secret for a grace window); anything older unseals to nothing and the
# ticket is refused.


def _keystream(key: bytes, n: int) -> bytes:
    """SHA256-counter keystream (stand-in for the reference's AES-CBC —
    the properties the tests pin are integrity, expiry, and rotation
    refusal; the stream hides the session key from a passive reader)."""
    out = bytearray()
    ctr = 0
    while len(out) < n:
        out += hashlib.sha256(key + _struct.pack("<Q", ctr)).digest()
        ctr += 1
    return bytes(out[:n])


def seal(key: bytes, obj: dict) -> str:
    """Encrypt-then-MAC a JSON payload under `key`; hex blob."""
    pt = _json.dumps(obj, sort_keys=True).encode()
    iv = os.urandom(8)
    ct = bytes(a ^ b for a, b in zip(pt, _keystream(key + iv, len(pt))))
    tag = hmac.new(key, iv + ct, hashlib.sha256).digest()[:16]
    return (iv + tag + ct).hex()


def unseal(key: bytes, blob_hex: str) -> dict | None:
    """None on ANY failure (wrong key/generation, tamper, garbage)."""
    try:
        raw = bytes.fromhex(blob_hex)
        iv, tag, ct = raw[:8], raw[8:24], raw[24:]
        want = hmac.new(key, iv + ct, hashlib.sha256).digest()[:16]
        if not hmac.compare_digest(tag, want):
            return None
        pt = bytes(a ^ b for a, b in zip(ct, _keystream(key + iv, len(ct))))
        return _json.loads(pt.decode())
    except Exception:
        return None


def derive_service_key(secret: bytes, service: str, gen: int) -> bytes:
    return hmac.new(secret, f"svc:{service}:{gen}".encode(),
                    hashlib.sha256).digest()


def derive_s3_secret(secret: bytes, access_key: str, gen: int) -> str:
    """Hex S3 secret key for the RGW SigV4 surface — same
    derive-don't-store pattern as service keys, rotated by the "rgw"
    auth generation (used by the mon's `auth get-s3-key` and the
    gateway's verifier; reference: RGWUserInfo credentials, here backed
    by the cephx cluster secret instead of a user database)."""
    return hmac.new(
        secret, f"s3:{access_key}:{gen}".encode(), hashlib.sha256
    ).hexdigest()


def mint_ticket(secret: bytes, entity: str, service: str, gen: int,
                ttl: float) -> tuple[str, str]:
    """(sealed ticket blob, session_key_hex).  The blob is sealed under
    the SERVICE key — only daemons of that service can open it; the
    session key returns to the requesting client over its authenticated,
    frame-signed mon session (`auth get-ticket`), standing in for the
    reference's seal-under-client-key step."""
    session_key = os.urandom(32).hex()
    blob = seal(derive_service_key(secret, service, gen), {
        "entity": entity,
        "service": service,
        "session_key": session_key,
        "expires": _time.time() + ttl,
        "gen": gen,
    })
    return blob, session_key


def validate_ticket(secret: bytes, service: str, current_gen: int,
                    blob_hex: str) -> dict | None:
    """Daemon-side check: try the current generation and one before (the
    rotation grace window); enforce service binding and expiry.  None =
    refuse the connection."""
    for gen in (current_gen, current_gen - 1):
        if gen < 1:
            continue
        t = unseal(derive_service_key(secret, service, gen), blob_hex)
        if t is None:
            continue
        if t.get("service") != service or t.get("gen") != gen:
            return None
        if t.get("expires", 0) < _time.time():
            return None
        return t
    return None
