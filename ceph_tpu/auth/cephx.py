"""cephx-style mutual authentication for messenger connections.

Reference: src/auth/cephx (CephxProtocol.h: challenge/proof exchange with
HMAC over a shared secret; src/msg ProtocolV2's auth frames carry it).

Scope vs the reference, by design: one shared cluster secret (the
`auth_shared_secret` option) stands in for the mon-brokered per-service
ticket hierarchy — the wire exchange (server challenge -> client proof +
counter-challenge -> server proof) and its properties (mutual proof of
key possession, per-connection nonces so transcripts never replay) match
CephxProtocol's session-key handshake; what's elided is ticket issuance
and rotation, which need the mon KeyServer state machine.

Wire form (one line each, after the messenger banner/ident):

    S->C  auth-challenge <snonce-hex>
    C->S  auth-proof <hmac-hex> <cnonce-hex>
    S->C  auth-ok <hmac-hex>

proofs: HMAC-SHA256(secret, nonce || peer-entity-name).  A server with
auth disabled sends no challenge (wire-compatible with unauthenticated
peers); a client expecting auth then times out — the same hard failure a
cephx-required cluster gives unauthenticated clients.
"""
from __future__ import annotations

import base64
import hashlib
import hmac
import os


class AuthError(Exception):
    pass


def generate_secret() -> str:
    """A fresh base64 cluster secret (`ceph-authtool --gen-key` analog)."""
    return base64.b64encode(os.urandom(32)).decode()


class CephxAuthenticator:
    """Per-messenger auth engine; stateless besides the secret."""

    def __init__(self, secret_b64: str):
        try:
            self._secret = base64.b64decode(secret_b64.encode(), validate=True)
        except Exception as e:
            raise AuthError(f"bad auth_shared_secret: {e}") from e
        if len(self._secret) < 16:
            raise AuthError("auth_shared_secret shorter than 16 bytes")

    def make_nonce(self) -> str:
        return os.urandom(16).hex()

    def proof(self, nonce_hex: str, name: str) -> str:
        return hmac.new(
            self._secret, bytes.fromhex(nonce_hex) + name.encode(),
            hashlib.sha256,
        ).hexdigest()

    def verify(self, nonce_hex: str, name: str, proof_hex: str) -> bool:
        return hmac.compare_digest(self.proof(nonce_hex, name), proof_hex)
