"""ceph_tpu — TPU-native erasure coding + CRUSH placement framework.

A ground-up JAX/XLA/Pallas rebuild of the capabilities of the reference's
erasure-code and placement subsystems (reference: src/erasure-code, src/crush
— see SURVEY.md), with C++ oracles standing in for the reference's native
jerasure/gf-complete/mapper.c as bit-exactness referees and CPU baselines.

Layout (SURVEY.md §7):
    gf/        GF(2^8) tables, jerasure-exact matrix construction, inversion
    ops/       bitplane packing + XLA/Pallas GF(2) matmul encode kernels
    ec/        ErasureCodeInterface-style codec layer, registry, plugins
    crush/     rjenkins hash, crush_ln, straw2, rule interpreter, batch mapper
    parallel/  device-mesh sharding of stripe batches and CRUSH x-batches
    bench/     ceph_erasure_code_benchmark-compatible CLI
    common/    context, layered config, perf counters, log ring, bufferlist,
               throttles, admin socket, heartbeat map, op tracker
    osd/       OSDMap placement + upmap balancer (+ data plane)
    tools/     crushtool / osdmaptool CLI analogs
"""

__version__ = "0.1.0"
