"""Compressor plugin registry (reference: src/compressor —
Compressor::create + the zlib/snappy/zstd/lz4 plugins; SURVEY.md §2.7).

Mirrors the EC plugin registry's shape: plugins self-register, creation
goes through one factory, and unavailable native libraries surface as a
clean error instead of an import crash (snappy/zstd/lz4 gate on their
modules being importable; zlib is stdlib and always present).

    c = Compressor.create("zlib")
    blob = c.compress(data)
    assert c.decompress(blob) == data
"""
from __future__ import annotations


class CompressorError(Exception):
    pass


class Compressor:
    """Plugin contract (reference: src/compressor/Compressor.h)."""

    NAME = ""

    def compress(self, data: bytes) -> bytes:  # pragma: no cover - abstract
        raise NotImplementedError

    def decompress(self, data: bytes) -> bytes:  # pragma: no cover
        raise NotImplementedError

    @staticmethod
    def create(name: str) -> "Compressor":
        cls = _REGISTRY.get(name)
        if cls is None:
            raise CompressorError(
                f"unknown compressor {name!r}; available: {available()}"
            )
        return cls()


_REGISTRY: dict[str, type] = {}


def register(cls: type) -> type:
    _REGISTRY[cls.NAME] = cls
    return cls


def available() -> list[str]:
    return sorted(_REGISTRY)


@register
class ZlibCompressor(Compressor):
    NAME = "zlib"

    def __init__(self, level: int = 5):
        self.level = level

    def compress(self, data: bytes) -> bytes:
        import zlib

        return zlib.compress(bytes(data), self.level)

    def decompress(self, data: bytes) -> bytes:
        import zlib

        try:
            return zlib.decompress(bytes(data))
        except zlib.error as e:
            raise CompressorError(f"zlib: {e}") from e

    def decompress_bounded(self, data: bytes, max_out: int) -> bytes:
        """Inflate at most max_out bytes (decompression-bomb guard for
        untrusted frames): a stream that would exceed the bound raises
        instead of allocating it."""
        import zlib

        d = zlib.decompressobj()
        try:
            out = d.decompress(bytes(data), max_out)
        except zlib.error as e:
            raise CompressorError(f"zlib: {e}") from e
        if d.unconsumed_tail or (d.decompress(b"", 1) if not d.eof else b""):
            raise CompressorError(
                f"zlib: inflated stream exceeds bound ({max_out})"
            )
        return out


def _try_register_optional() -> None:
    """snappy / zstd / lz4 exist only if their modules are importable —
    the plugin-.so-present gate of the reference's registry."""
    try:
        import snappy  # type: ignore[import-not-found]

        @register
        class SnappyCompressor(Compressor):
            NAME = "snappy"

            def compress(self, data: bytes) -> bytes:
                return snappy.compress(bytes(data))

            def decompress(self, data: bytes) -> bytes:
                try:
                    return snappy.decompress(bytes(data))
                except Exception as e:
                    raise CompressorError(f"snappy: {e}") from e
    except ImportError:
        pass
    try:
        import zstandard  # type: ignore[import-not-found]

        @register
        class ZstdCompressor(Compressor):
            NAME = "zstd"

            def compress(self, data: bytes) -> bytes:
                return zstandard.ZstdCompressor().compress(bytes(data))

            def decompress(self, data: bytes) -> bytes:
                try:
                    return zstandard.ZstdDecompressor().decompress(bytes(data))
                except Exception as e:
                    raise CompressorError(f"zstd: {e}") from e

            def decompress_bounded(self, data: bytes,
                                   max_out: int) -> bytes:
                try:
                    return zstandard.ZstdDecompressor().decompress(
                        bytes(data), max_output_size=max_out)
                except Exception as e:
                    raise CompressorError(f"zstd: {e}") from e
    except ImportError:
        pass
    try:
        import lz4.frame  # type: ignore[import-not-found]

        @register
        class Lz4Compressor(Compressor):
            NAME = "lz4"

            def compress(self, data: bytes) -> bytes:
                return lz4.frame.compress(bytes(data))

            def decompress(self, data: bytes) -> bytes:
                try:
                    return lz4.frame.decompress(bytes(data))
                except Exception as e:
                    raise CompressorError(f"lz4: {e}") from e
    except ImportError:
        pass


_try_register_optional()
