"""CRUSH map data model — crush.h structs re-done as Python dataclasses.

Reference: src/crush/crush.h :: crush_map, crush_bucket_* variants,
crush_rule, crush_rule_step.  All five bucket algorithms are modeled:
straw2 (the default and recommended algorithm since Hammer), plus the
legacy uniform/list/tree/straw types real decompiled maps still carry
(allowed_bucket_algs in
the modern tunable profiles), and the balancer/upmap machinery the north star
accelerates assumes it.  Bucket ids are negative (-1-index), devices are
non-negative ints, exactly as in the reference.

Tunables: the modern ("jewel"/default) profile is the supported semantics —
choose_local_tries=0, choose_local_fallback_tries=0, choose_total_tries=50,
chooseleaf_descend_once=1, chooseleaf_vary_r=1, chooseleaf_stable=1
(reference: src/crush/CrushWrapper.h set_tunables_jewel; legacy pre-Hammer
retry modes are intentionally out of scope).
"""
from __future__ import annotations

from dataclasses import dataclass, field
from enum import IntEnum


class RuleOp(IntEnum):
    """reference: crush.h :: crush_rule_step op codes (subset: the ops
    emitted by modern CrushWrapper rule builders)."""

    NOOP = 0
    TAKE = 1
    CHOOSE_FIRSTN = 2
    CHOOSE_INDEP = 3
    EMIT = 4
    CHOOSELEAF_FIRSTN = 6
    CHOOSELEAF_INDEP = 7
    SET_CHOOSE_TRIES = 8
    SET_CHOOSELEAF_TRIES = 9


#: out[] sentinel values (reference: crush.h CRUSH_ITEM_UNDEF/NONE)
ITEM_UNDEF = -0x7FFFFFFF
ITEM_NONE = -0x7FFFFFFE


#: bucket algorithms (reference: crush.h CRUSH_BUCKET_*)
BUCKET_UNIFORM = 1
BUCKET_LIST = 2
BUCKET_TREE = 3
BUCKET_STRAW = 4
BUCKET_STRAW2 = 5

BUCKET_ALG_NAMES = {
    BUCKET_UNIFORM: "uniform", BUCKET_LIST: "list", BUCKET_TREE: "tree",
    BUCKET_STRAW: "straw", BUCKET_STRAW2: "straw2",
}


@dataclass
class Straw2Bucket:
    """reference: crush.h :: crush_bucket_straw2 and siblings (the
    crush_bucket header + per-alg payload).  The class predates the
    legacy algorithms and keeps its name; `alg` selects the choose
    function.  Aux fields:
    - straw buckets carry `straws` (16.16 scaling factors derived from
      the weights at build time, reference: builder.c crush_calc_straw);
    - tree buckets carry `node_weights` (the implicit binary tree of
      builder.c, leaves at odd indices, internal nodes summing children);
    - uniform buckets treat weights[0] as the shared item weight."""

    id: int  # negative
    type: int  # bucket type id (>0; devices are type 0)
    items: list[int] = field(default_factory=list)
    weights: list[int] = field(default_factory=list)  # 16.16 fixed-point
    hash_id: int = 0  # CRUSH_HASH_RJENKINS1
    alg: int = BUCKET_STRAW2
    straws: list[int] = field(default_factory=list)        # straw only
    node_weights: list[int] = field(default_factory=list)  # tree only

    @property
    def size(self) -> int:
        return len(self.items)

    @property
    def weight(self) -> int:
        return sum(self.weights)


@dataclass
class RuleStep:
    op: RuleOp
    arg1: int = 0
    arg2: int = 0


@dataclass
class Rule:
    """reference: crush.h :: crush_rule; rule_id selects it from the pool."""

    rule_id: int
    steps: list[RuleStep] = field(default_factory=list)
    type: int = 1  # 1=replicated, 3=erasure (pg_pool_t convention)


@dataclass
class Tunables:
    choose_total_tries: int = 50
    choose_local_tries: int = 0
    choose_local_fallback_tries: int = 0
    chooseleaf_descend_once: int = 1
    chooseleaf_vary_r: int = 1
    chooseleaf_stable: int = 1


@dataclass
class CrushMap:
    """reference: crush.h :: crush_map."""

    buckets: dict[int, Straw2Bucket] = field(default_factory=dict)
    rules: dict[int, Rule] = field(default_factory=dict)
    max_devices: int = 0
    type_names: dict[int, str] = field(default_factory=lambda: {0: "osd"})
    bucket_names: dict[int, str] = field(default_factory=dict)
    device_names: dict[int, str] = field(default_factory=dict)
    tunables: Tunables = field(default_factory=Tunables)
    #: device classes (reference: CrushWrapper class_map / class_name):
    #: class id -> name, osd id -> class id
    class_names: dict[int, str] = field(default_factory=dict)
    device_classes: dict[int, int] = field(default_factory=dict)
    #: shadow trees per class (reference: CrushWrapper::class_bucket,
    #: device_class_clone): original bucket id -> class id -> shadow id
    class_bucket: dict[int, dict[int, int]] = field(default_factory=dict)
    #: choose_args weight-sets (reference: crush.h :: crush_choose_arg_map;
    #: the balancer's crush-compat mode writes these): name ->
    #: {bucket id -> weight_set [positions][bucket size] 16.16}.  Item-id
    #: remapping (crush_choose_arg::ids) is not modeled — the balancer only
    #: adjusts weights.
    choose_args: dict[str, dict[int, list[list[int]]]] = field(
        default_factory=dict
    )

    def bucket(self, bid: int) -> Straw2Bucket:
        return self.buckets[bid]

    def item_type(self, item: int) -> int:
        return 0 if item >= 0 else self.buckets[item].type

    def max_depth(self) -> int:
        """Longest bucket chain — static bound for the vectorized descent."""

        def depth(bid: int, seen: frozenset[int]) -> int:
            if bid >= 0:
                return 0
            if bid in seen:
                raise ValueError(f"bucket cycle at {bid}")
            b = self.buckets[bid]
            if not b.items:
                return 1
            return 1 + max(depth(i, seen | {bid}) for i in b.items)

        roots = set(self.buckets)
        for b in self.buckets.values():
            roots -= set(i for i in b.items if i < 0)
        if not roots:
            return 0
        return max(depth(r, frozenset()) for r in roots)
