"""ctypes bridge to the C++ CRUSH oracle (native/crush_oracle.cc).

Third bit-exactness implementation and the CPU maps/s baseline for
BASELINE.json config 5 (straw2 10M-object remap) — the role mapper.c's
compiled C plays in the reference.
"""
from __future__ import annotations

import ctypes
from functools import lru_cache

import numpy as np

from ..native_oracle import _lib
from .mapper import CompiledCrushMap, compile_rule
from .types import CrushMap, ITEM_NONE

_i32p = np.ctypeslib.ndpointer(dtype=np.int32, flags="C_CONTIGUOUS")
_i64p = np.ctypeslib.ndpointer(dtype=np.int64, flags="C_CONTIGUOUS")
_u32p = np.ctypeslib.ndpointer(dtype=np.uint32, flags="C_CONTIGUOUS")


@lru_cache(maxsize=1)
def _crush_lib() -> ctypes.CDLL:
    lib = _lib()
    lib.cro_do_rule_batch.argtypes = [
        _i32p, _i64p, _i32p, _i32p,
        ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_int,
        ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_int,
        ctypes.c_int, _u32p, ctypes.c_long, _u32p, ctypes.c_int,
        ctypes.c_void_p, ctypes.c_int,
        _i32p, _i64p, _i64p, ctypes.c_int,  # algs/straws/nodes/max_nodes
        _i32p,  # num_nodes (true per-bucket counts, r4 verdict #5)
        _i32p,
    ]
    lib.cro_do_rule_batch.restype = ctypes.c_int
    lib.cro_do_rule_steps.argtypes = [
        _i32p, _i64p, _i32p, _i32p,
        ctypes.c_int, ctypes.c_int, _i32p, ctypes.c_int, ctypes.c_int,
        ctypes.c_int, _u32p, ctypes.c_long, _u32p, ctypes.c_int,
        ctypes.c_void_p, ctypes.c_int,
        _i32p, _i64p, _i64p, ctypes.c_int,  # algs/straws/nodes/max_nodes
        _i32p,  # num_nodes (true per-bucket counts, r4 verdict #5)
        _i32p,
    ]
    lib.cro_do_rule_steps.restype = ctypes.c_int
    lib.cro_hash3.argtypes = [ctypes.c_uint32] * 3
    lib.cro_hash3.restype = ctypes.c_uint32
    lib.cro_hash2.argtypes = [ctypes.c_uint32] * 2
    lib.cro_hash2.restype = ctypes.c_uint32
    lib.cro_ln.argtypes = [ctypes.c_uint32]
    lib.cro_ln.restype = ctypes.c_int64
    lib.cro_ln_table.argtypes = [_i64p]
    lib.cro_ln_table.restype = None
    return lib


def ln_table_full() -> np.ndarray:
    out = np.empty(0x10000, dtype=np.int64)
    _crush_lib().cro_ln_table(out)
    return out


def hash3(a: int, b: int, c: int) -> int:
    return _crush_lib().cro_hash3(a & 0xFFFFFFFF, b & 0xFFFFFFFF, c & 0xFFFFFFFF)


def hash2(a: int, b: int) -> int:
    return _crush_lib().cro_hash2(a & 0xFFFFFFFF, b & 0xFFFFFFFF)


def crush_ln(u: int) -> int:
    return _crush_lib().cro_ln(u)


def _marshal(cm: CompiledCrushMap, xs, weightvec,
             choose_args: str | None):
    """Dense C-ready views of a compiled map + inputs.  Returns a dict;
    the `cw` entry must stay referenced through the ctypes call."""
    m = dict(
        items=np.ascontiguousarray(np.asarray(cm.items), dtype=np.int32),
        weights=np.ascontiguousarray(np.asarray(cm.weights), dtype=np.int64),
        sizes=np.ascontiguousarray(np.asarray(cm.sizes), dtype=np.int32),
        types=np.ascontiguousarray(np.asarray(cm.types), dtype=np.int32),
        xs=np.ascontiguousarray(xs, dtype=np.uint32),
        wv=np.ascontiguousarray(weightvec, dtype=np.uint32),
        cw=None, positions=0, cw_ptr=None,
        algs=np.ascontiguousarray(cm.algs, dtype=np.int32),
        straws=np.ascontiguousarray(cm.straws, dtype=np.int64),
        nodes=np.ascontiguousarray(cm.node_weights, dtype=np.int64),
        max_nodes=int(cm.max_nodes),
        num_nodes=np.ascontiguousarray(cm.node_counts, dtype=np.int32),
    )
    if choose_args is not None:
        cw = np.ascontiguousarray(
            np.asarray(cm.choose_args_arrays(choose_args)), dtype=np.int64
        )
        m.update(cw=cw, positions=cw.shape[0],
                 cw_ptr=cw.ctypes.data_as(ctypes.c_void_p))
    return m


def _pad_to_numrep(out: np.ndarray, numrep: int) -> np.ndarray:
    """crush_do_rule_batch's [N, numrep] contract: NONE tail for a CHOOSE
    with arg1 < 0, truncate any excess."""
    if out.shape[1] < numrep:
        pad = np.full((out.shape[0], numrep - out.shape[1]), ITEM_NONE,
                      dtype=np.int32)
        out = np.concatenate([out, pad], axis=1)
    return out[:, :numrep]


def do_rule_steps_oracle(
    cmap: CrushMap,
    rule_id: int,
    xs,
    numrep: int,
    weightvec,
    choose_args: str | None = None,
    cm: CompiledCrushMap | None = None,
) -> np.ndarray:
    """Batched crush_do_rule via the oracle's full step interpreter —
    handles multi-choose chains; same contract as crush_do_rule_batch."""
    if cm is None:
        cm = CompiledCrushMap(cmap)
    rule = cmap.rules[rule_id]
    steps = np.ascontiguousarray(
        [[int(s.op), int(s.arg1), int(s.arg2)] for s in rule.steps],
        dtype=np.int32,
    )
    a = _marshal(cm, xs, weightvec, choose_args)
    out = np.empty((len(a["xs"]), numrep), dtype=np.int32)
    rc = _crush_lib().cro_do_rule_steps(
        a["items"].reshape(-1), a["weights"].reshape(-1), a["sizes"],
        a["types"], a["items"].shape[0], a["items"].shape[1],
        steps.reshape(-1), len(rule.steps), numrep,
        cmap.tunables.choose_total_tries, a["xs"], len(a["xs"]), a["wv"],
        len(a["wv"]), a["cw_ptr"], a["positions"],
        a["algs"], a["straws"].reshape(-1), a["nodes"].reshape(-1),
        a["max_nodes"], a["num_nodes"], out.reshape(-1),
    )
    if rc != 0:
        raise ValueError(f"cro_do_rule_steps failed rc={rc}")
    return out


def do_rule_batch_oracle(
    cmap: CrushMap,
    rule_id: int,
    xs,
    numrep: int,
    weightvec,
    choose_args: str | None = None,
) -> np.ndarray:
    """Batched crush_do_rule via the C++ oracle; same contract as
    ceph_tpu.crush.mapper.crush_do_rule_batch."""
    cm = CompiledCrushMap(cmap)
    try:
        p = compile_rule(cm, rule_id, numrep)
    except NotImplementedError:
        # multi-choose chain: the step interpreter speaks those
        return do_rule_steps_oracle(
            cmap, rule_id, xs, numrep, weightvec, choose_args, cm=cm
        )
    a = _marshal(cm, xs, weightvec, choose_args)
    out = np.empty((len(a["xs"]), p["want"]), dtype=np.int32)
    recurse_tries = (
        (p["leaf_tries"] or p["tries"]) if p["firstn"] else (p["leaf_tries"] or 1)
    )
    rc = _crush_lib().cro_do_rule_batch(
        a["items"].reshape(-1), a["weights"].reshape(-1), a["sizes"],
        a["types"], a["items"].shape[0], a["items"].shape[1], p["take"],
        p["want"], p["type"], int(p["firstn"]), int(p["recurse"]),
        p["tries"], recurse_tries, a["xs"], len(a["xs"]), a["wv"],
        len(a["wv"]), a["cw_ptr"], a["positions"],
        a["algs"], a["straws"].reshape(-1), a["nodes"].reshape(-1),
        a["max_nodes"], a["num_nodes"], out.reshape(-1),
    )
    if rc != 0:
        raise ValueError(f"cro_do_rule_batch failed rc={rc}")
    return _pad_to_numrep(out, numrep)
