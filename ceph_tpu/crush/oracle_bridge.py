"""ctypes bridge to the C++ CRUSH oracle (native/crush_oracle.cc).

Third bit-exactness implementation and the CPU maps/s baseline for
BASELINE.json config 5 (straw2 10M-object remap) — the role mapper.c's
compiled C plays in the reference.
"""
from __future__ import annotations

import ctypes
from functools import lru_cache

import numpy as np

from ..native_oracle import _lib
from .mapper import CompiledCrushMap, compile_rule
from .types import CrushMap

_i32p = np.ctypeslib.ndpointer(dtype=np.int32, flags="C_CONTIGUOUS")
_i64p = np.ctypeslib.ndpointer(dtype=np.int64, flags="C_CONTIGUOUS")
_u32p = np.ctypeslib.ndpointer(dtype=np.uint32, flags="C_CONTIGUOUS")


@lru_cache(maxsize=1)
def _crush_lib() -> ctypes.CDLL:
    lib = _lib()
    lib.cro_do_rule_batch.argtypes = [
        _i32p, _i64p, _i32p, _i32p,
        ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_int,
        ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_int,
        ctypes.c_int, _u32p, ctypes.c_long, _u32p, ctypes.c_int,
        ctypes.c_void_p, ctypes.c_int, _i32p,
    ]
    lib.cro_do_rule_batch.restype = ctypes.c_int
    lib.cro_hash3.argtypes = [ctypes.c_uint32] * 3
    lib.cro_hash3.restype = ctypes.c_uint32
    lib.cro_hash2.argtypes = [ctypes.c_uint32] * 2
    lib.cro_hash2.restype = ctypes.c_uint32
    lib.cro_ln.argtypes = [ctypes.c_uint32]
    lib.cro_ln.restype = ctypes.c_int64
    lib.cro_ln_table.argtypes = [_i64p]
    lib.cro_ln_table.restype = None
    return lib


def ln_table_full() -> np.ndarray:
    out = np.empty(0x10000, dtype=np.int64)
    _crush_lib().cro_ln_table(out)
    return out


def hash3(a: int, b: int, c: int) -> int:
    return _crush_lib().cro_hash3(a & 0xFFFFFFFF, b & 0xFFFFFFFF, c & 0xFFFFFFFF)


def hash2(a: int, b: int) -> int:
    return _crush_lib().cro_hash2(a & 0xFFFFFFFF, b & 0xFFFFFFFF)


def crush_ln(u: int) -> int:
    return _crush_lib().cro_ln(u)


def do_rule_batch_oracle(
    cmap: CrushMap,
    rule_id: int,
    xs,
    numrep: int,
    weightvec,
    choose_args: str | None = None,
) -> np.ndarray:
    """Batched crush_do_rule via the C++ oracle; same contract as
    ceph_tpu.crush.mapper.crush_do_rule_batch."""
    cm = CompiledCrushMap(cmap)
    p = compile_rule(cm, rule_id, numrep)
    items = np.ascontiguousarray(np.asarray(cm.items), dtype=np.int32)
    weights = np.ascontiguousarray(np.asarray(cm.weights), dtype=np.int64)
    sizes = np.ascontiguousarray(np.asarray(cm.sizes), dtype=np.int32)
    types = np.ascontiguousarray(np.asarray(cm.types), dtype=np.int32)
    xs = np.ascontiguousarray(xs, dtype=np.uint32)
    wv = np.ascontiguousarray(weightvec, dtype=np.uint32)
    out = np.empty((len(xs), p["want"]), dtype=np.int32)
    recurse_tries = (
        (p["leaf_tries"] or p["tries"]) if p["firstn"] else (p["leaf_tries"] or 1)
    )
    if choose_args is not None:
        cw = np.ascontiguousarray(
            np.asarray(cm.choose_args_arrays(choose_args)), dtype=np.int64
        )
        positions = cw.shape[0]
        cw_ptr = cw.ctypes.data_as(ctypes.c_void_p)
    else:
        cw = None  # noqa: F841 — keep the buffer alive through the call
        positions = 0
        cw_ptr = None
    rc = _crush_lib().cro_do_rule_batch(
        items.reshape(-1), weights.reshape(-1), sizes, types,
        items.shape[0], items.shape[1], p["take"], p["want"], p["type"],
        int(p["firstn"]), int(p["recurse"]), p["tries"], recurse_tries,
        xs, len(xs), wv, len(wv), cw_ptr, positions, out.reshape(-1),
    )
    if rc != 0:
        raise ValueError(f"cro_do_rule_batch failed rc={rc}")
    return out
