"""CRUSH rjenkins1 hash — vectorized, bit-exact uint32 semantics.

Reference: src/crush/hash.c :: crush_hash32_rjenkins1{_2,_3,_4} — Robert
Jenkins' 32-bit integer mix.  All arithmetic is mod 2^32 (wrapping
subtraction, XOR, shifts); trivially vectorizable (SURVEY.md §2.2 "Trivial
to vectorize; must match bit-for-bit").  Implemented over jnp.uint32 so the
same code runs scalar (host) and batched (TPU) under vmap/jit; the C++
oracle (native/crush_oracle.cc) implements the same functions for
cross-checking.

Provenance caveat (SURVEY.md §0): written from the documented hash.c
structure; the reference mount was empty, so upstream equality could not be
diffed this round — oracle<->JAX equality is what tests enforce.
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

CRUSH_HASH_SEED = 1315423911


def _mix(a, b, c):
    """hash.c :: crush_hashmix(a, b, c) — mutates all three, returns them."""
    a = a - b
    a = a - c
    a = a ^ (c >> 13)
    b = b - c
    b = b - a
    b = b ^ (a << 8)
    c = c - a
    c = c - b
    c = c ^ (b >> 13)
    a = a - b
    a = a - c
    a = a ^ (c >> 12)
    b = b - c
    b = b - a
    b = b ^ (a << 16)
    c = c - a
    c = c - b
    c = c ^ (b >> 5)
    a = a - b
    a = a - c
    a = a ^ (c >> 3)
    b = b - c
    b = b - a
    b = b ^ (a << 10)
    c = c - a
    c = c - b
    c = c ^ (b >> 15)
    return a, b, c


def _u32(x):
    if isinstance(x, int):
        # raw Python ints >= 2^31 would overflow jnp's int32 weak-type
        # inference when x64 is off (the production config)
        x = np.uint32(x & 0xFFFFFFFF)
    return jnp.asarray(x).astype(jnp.uint32)


def crush_hash32(a):
    """hash.c :: crush_hash32_rjenkins1."""
    a = _u32(a)
    hash_ = _u32(CRUSH_HASH_SEED) ^ a
    b = a
    x = _u32(231232)
    y = _u32(1232)
    b, x, hash_ = _mix(b, x, hash_)
    y, a, hash_ = _mix(y, a, hash_)
    return hash_


def crush_hash32_2(a, b):
    """hash.c :: crush_hash32_rjenkins1_2."""
    a, b = _u32(a), _u32(b)
    hash_ = _u32(CRUSH_HASH_SEED) ^ a ^ b
    x = _u32(231232)
    y = _u32(1232)
    a, b, hash_ = _mix(a, b, hash_)
    x, a, hash_ = _mix(x, a, hash_)
    b, y, hash_ = _mix(b, y, hash_)
    return hash_


def crush_hash32_3(a, b, c):
    """hash.c :: crush_hash32_rjenkins1_3 — the straw2 draw hash."""
    a, b, c = _u32(a), _u32(b), _u32(c)
    hash_ = _u32(CRUSH_HASH_SEED) ^ a ^ b ^ c
    x = _u32(231232)
    y = _u32(1232)
    a, b, hash_ = _mix(a, b, hash_)
    c, x, hash_ = _mix(c, x, hash_)
    y, a, hash_ = _mix(y, a, hash_)
    b, x, hash_ = _mix(b, x, hash_)
    y, c, hash_ = _mix(y, c, hash_)
    return hash_


def crush_hash32_4(a, b, c, d):
    """hash.c :: crush_hash32_rjenkins1_4 (chooseleaf / descend_once salt)."""
    a, b, c, d = _u32(a), _u32(b), _u32(c), _u32(d)
    hash_ = _u32(CRUSH_HASH_SEED) ^ a ^ b ^ c ^ d
    x = _u32(231232)
    y = _u32(1232)
    a, b, hash_ = _mix(a, b, hash_)
    c, d, hash_ = _mix(c, d, hash_)
    a, x, hash_ = _mix(a, x, hash_)
    y, b, hash_ = _mix(y, b, hash_)
    c, x, hash_ = _mix(c, x, hash_)
    y, d, hash_ = _mix(y, d, hash_)
    return hash_


def crush_hash32_2_np(a, b) -> np.ndarray:
    """Numpy twin of crush_hash32_2 (pg→pps seeding, primary affinity)."""
    with np.errstate(over="ignore"):
        a = np.asarray(a, dtype=np.uint32)
        b = np.asarray(b, dtype=np.uint32)
        hash_ = np.uint32(CRUSH_HASH_SEED) ^ a ^ b
        x = np.uint32(231232)
        y = np.uint32(1232)
        a, b, hash_ = _mix(a, b, hash_)
        x, a, hash_ = _mix(x, a, hash_)
        b, y, hash_ = _mix(b, y, hash_)
        return hash_


def crush_hash32_3_np(a, b, c) -> np.ndarray:
    """Numpy twin of crush_hash32_3 (host-side golden generator)."""
    with np.errstate(over="ignore"):
        a = np.asarray(a, dtype=np.uint32)
        b = np.asarray(b, dtype=np.uint32)
        c = np.asarray(c, dtype=np.uint32)
        hash_ = np.uint32(CRUSH_HASH_SEED) ^ a ^ b ^ c
        x = np.uint32(231232)
        y = np.uint32(1232)
        a, b, hash_ = _mix(a, b, hash_)
        c, x, hash_ = _mix(c, x, hash_)
        y, a, hash_ = _mix(y, a, hash_)
        b, x, hash_ = _mix(b, x, hash_)
        y, c, hash_ = _mix(y, c, hash_)
        return hash_
