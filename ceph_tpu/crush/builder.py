"""Programmatic CRUSH map construction — builder.c + CrushWrapper rule helpers.

Reference: src/crush/builder.c :: crush_make_straw2_bucket / crush_add_bucket,
and src/crush/CrushWrapper.cc :: add_simple_rule (replicated) plus the EC rule
OSDMonitor creates for erasure pools.  Also the standard test topology
generator used by golden tests (the analog of crushtool --build).
"""
from __future__ import annotations

from .types import (
    BUCKET_LIST,
    BUCKET_STRAW,
    BUCKET_STRAW2,
    BUCKET_TREE,
    BUCKET_UNIFORM,
    CrushMap,
    Rule,
    RuleOp,
    RuleStep,
    Straw2Bucket,
)


def calc_straws(weights: list[int]) -> list[int]:
    """16.16 straw scaling factors for a legacy straw bucket
    (reference: builder.c :: crush_calc_straw).  Items are processed in
    increasing weight order; each distinct weight tier lengthens the
    straws of everything still standing so the expected win probability
    tracks the weights.  (The classic straw algorithm this reproduces is
    the one straw2 replaced precisely because this scaling is only
    approximately fair for some weight patterns.)

    NOTE: the reference mount is empty this round, so this is a
    reconstruction of the published algorithm; what the repo GUARANTEES
    is internal bit-exactness — straws are computed once, here, and all
    three mappers consume the same table."""
    size = len(weights)
    if size == 0:
        return []
    order = sorted(range(size), key=lambda i: (weights[i], i))
    straws = [0] * size
    numleft = size
    straw = 1.0
    wbelow = 0.0
    lastw = 0.0
    i = 0
    while i < size:
        idx = order[i]
        if weights[idx] == 0:
            straws[idx] = 0
            i += 1
            continue
        straws[idx] = int(straw * 0x10000)
        i += 1
        if i == size:
            break
        nxt = order[i]
        if weights[nxt] == weights[idx]:
            continue  # same tier: same straw length
        # close the tier: probability mass below this weight
        wbelow += (float(weights[idx]) - lastw) * numleft
        numleft = size - i  # items still standing (strictly heavier)
        wnext = float(numleft * (weights[nxt] - weights[idx]))
        pbelow = wbelow / (wbelow + wnext)
        straw *= pbelow ** (-1.0 / numleft) if numleft else 1.0
        lastw = float(weights[idx])
    return straws


def calc_tree_nodes(weights: list[int]) -> list[int]:
    """Implicit-binary-tree node weights for a tree bucket (reference:
    builder.c :: crush_make_tree_bucket): leaves live at odd indices
    1,3,..,2i+1; an internal node's weight is the sum of its subtree.
    Array length is 1 << depth where depth covers 2*size slots."""
    size = len(weights)
    if size == 0:
        return []
    depth = 1
    while (1 << depth) < size * 2:
        depth += 1
    nodes = [0] * (1 << depth)
    for i, w in enumerate(weights):
        node = i * 2 + 1
        nodes[node] = w
        n = node
        while n != (1 << (depth - 1)):
            # parent(n): set the bit above the lowest set bit, clear it
            kb = n & -n
            parent = (n | (kb << 1)) & ~kb
            if parent >= len(nodes):
                break
            nodes[parent] += w
            n = parent
    return nodes


def make_straw2_bucket(
    cmap: CrushMap,
    type_id: int,
    items: list[int],
    weights: list[int],
    bucket_id: int | None = None,
    name: str | None = None,
    alg: int = BUCKET_STRAW2,
) -> Straw2Bucket:
    """builder.c :: crush_make_<alg>_bucket + crush_add_bucket — one
    constructor covering all five algorithms (alg selects; straw/tree
    aux tables are derived here, at build time, like the reference
    builder does)."""
    if len(items) != len(weights):
        raise ValueError("items and weights must have equal length")
    if bucket_id is None:
        bucket_id = -1
        while bucket_id in cmap.buckets:
            bucket_id -= 1
    if bucket_id >= 0:
        raise ValueError("bucket ids are negative")
    if bucket_id in cmap.buckets:
        raise ValueError(f"bucket {bucket_id} exists")
    b = Straw2Bucket(id=bucket_id, type=type_id, items=list(items),
                     weights=list(weights), alg=alg)
    if alg == BUCKET_STRAW:
        b.straws = calc_straws(b.weights)
    elif alg == BUCKET_TREE:
        b.node_weights = calc_tree_nodes(b.weights)
    elif alg == BUCKET_UNIFORM and len(set(weights)) > 1:
        raise ValueError("uniform buckets need equal item weights")
    cmap.buckets[bucket_id] = b
    for it in items:
        if it >= 0:
            cmap.max_devices = max(cmap.max_devices, it + 1)
    if name:
        cmap.bucket_names[bucket_id] = name
    return b


def add_simple_rule(
    cmap: CrushMap,
    root: int,
    failure_domain_type: int,
    rule_id: int | None = None,
    firstn: bool = True,
    num_replicas: int = 0,
) -> Rule:
    """CrushWrapper.cc :: add_simple_rule — take root, chooseleaf over the
    failure domain, emit.  num_replicas 0 means 'use the requested numrep'
    (CRUSH_CHOOSE_N)."""
    if rule_id is None:
        rule_id = max(cmap.rules, default=-1) + 1
    op = RuleOp.CHOOSELEAF_FIRSTN if firstn else RuleOp.CHOOSELEAF_INDEP
    if failure_domain_type == 0:
        op = RuleOp.CHOOSE_FIRSTN if firstn else RuleOp.CHOOSE_INDEP
    rule = Rule(
        rule_id=rule_id,
        type=1 if firstn else 3,
        steps=[
            RuleStep(RuleOp.TAKE, root),
            RuleStep(op, num_replicas, failure_domain_type),
            RuleStep(RuleOp.EMIT),
        ],
    )
    cmap.rules[rule_id] = rule
    return rule


def build_flat_map(n_osds: int, device_weight: float = 1.0) -> CrushMap:
    """One root straw2 bucket holding every OSD (simplest useful map)."""
    cmap = CrushMap()
    cmap.type_names.update({1: "root"})
    w = int(device_weight * 0x10000)
    make_straw2_bucket(
        cmap, 1, list(range(n_osds)), [w] * n_osds, bucket_id=-1, name="default"
    )
    cmap.max_devices = n_osds
    add_simple_rule(cmap, -1, 0, rule_id=0)
    return cmap


def build_hierarchical_map(
    n_hosts: int,
    osds_per_host: int,
    device_weight: float = 1.0,
    firstn: bool = True,
    racks: int = 0,
) -> CrushMap:
    """root -> (racks ->) hosts -> osds, replicated + erasure rules.

    The standard topology of the reference's CRUSH tests (reference:
    src/test/crush/crush.cc builds analogous root/host trees).
    """
    cmap = CrushMap()
    cmap.type_names.update({1: "host", 2: "rack", 10: "root"})
    w = int(device_weight * 0x10000)
    host_ids = []
    osd = 0
    for h in range(n_hosts):
        items = list(range(osd, osd + osds_per_host))
        osd += osds_per_host
        b = make_straw2_bucket(
            cmap, 1, items, [w] * len(items), bucket_id=-(h + 2), name=f"host{h}"
        )
        host_ids.append(b.id)
    top_children = host_ids
    if racks:
        rack_ids = []
        per = max(1, n_hosts // racks)
        for r in range(racks):
            hs = host_ids[r * per : (r + 1) * per] or host_ids[-1:]
            b = make_straw2_bucket(
                cmap,
                2,
                hs,
                [cmap.buckets[h].weight for h in hs],
                bucket_id=-(n_hosts + 2 + r),
                name=f"rack{r}",
            )
            rack_ids.append(b.id)
        top_children = rack_ids
    make_straw2_bucket(
        cmap,
        10,
        top_children,
        [cmap.buckets[c].weight for c in top_children],
        bucket_id=-1,
        name="default",
    )
    cmap.max_devices = osd
    add_simple_rule(cmap, -1, 1, rule_id=0, firstn=firstn)
    # erasure-style indep rule over hosts (OSDMonitor's EC rule shape)
    add_simple_rule(cmap, -1, 1, rule_id=1, firstn=False)
    return cmap
