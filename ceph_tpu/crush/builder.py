"""Programmatic CRUSH map construction — builder.c + CrushWrapper rule helpers.

Reference: src/crush/builder.c :: crush_make_straw2_bucket / crush_add_bucket,
and src/crush/CrushWrapper.cc :: add_simple_rule (replicated) plus the EC rule
OSDMonitor creates for erasure pools.  Also the standard test topology
generator used by golden tests (the analog of crushtool --build).
"""
from __future__ import annotations

from .types import CrushMap, Rule, RuleOp, RuleStep, Straw2Bucket


def make_straw2_bucket(
    cmap: CrushMap,
    type_id: int,
    items: list[int],
    weights: list[int],
    bucket_id: int | None = None,
    name: str | None = None,
) -> Straw2Bucket:
    """builder.c :: crush_make_straw2_bucket + crush_add_bucket."""
    if len(items) != len(weights):
        raise ValueError("items and weights must have equal length")
    if bucket_id is None:
        bucket_id = -1
        while bucket_id in cmap.buckets:
            bucket_id -= 1
    if bucket_id >= 0:
        raise ValueError("bucket ids are negative")
    if bucket_id in cmap.buckets:
        raise ValueError(f"bucket {bucket_id} exists")
    b = Straw2Bucket(id=bucket_id, type=type_id, items=list(items), weights=list(weights))
    cmap.buckets[bucket_id] = b
    for it in items:
        if it >= 0:
            cmap.max_devices = max(cmap.max_devices, it + 1)
    if name:
        cmap.bucket_names[bucket_id] = name
    return b


def add_simple_rule(
    cmap: CrushMap,
    root: int,
    failure_domain_type: int,
    rule_id: int | None = None,
    firstn: bool = True,
    num_replicas: int = 0,
) -> Rule:
    """CrushWrapper.cc :: add_simple_rule — take root, chooseleaf over the
    failure domain, emit.  num_replicas 0 means 'use the requested numrep'
    (CRUSH_CHOOSE_N)."""
    if rule_id is None:
        rule_id = max(cmap.rules, default=-1) + 1
    op = RuleOp.CHOOSELEAF_FIRSTN if firstn else RuleOp.CHOOSELEAF_INDEP
    if failure_domain_type == 0:
        op = RuleOp.CHOOSE_FIRSTN if firstn else RuleOp.CHOOSE_INDEP
    rule = Rule(
        rule_id=rule_id,
        type=1 if firstn else 3,
        steps=[
            RuleStep(RuleOp.TAKE, root),
            RuleStep(op, num_replicas, failure_domain_type),
            RuleStep(RuleOp.EMIT),
        ],
    )
    cmap.rules[rule_id] = rule
    return rule


def build_flat_map(n_osds: int, device_weight: float = 1.0) -> CrushMap:
    """One root straw2 bucket holding every OSD (simplest useful map)."""
    cmap = CrushMap()
    cmap.type_names.update({1: "root"})
    w = int(device_weight * 0x10000)
    make_straw2_bucket(
        cmap, 1, list(range(n_osds)), [w] * n_osds, bucket_id=-1, name="default"
    )
    cmap.max_devices = n_osds
    add_simple_rule(cmap, -1, 0, rule_id=0)
    return cmap


def build_hierarchical_map(
    n_hosts: int,
    osds_per_host: int,
    device_weight: float = 1.0,
    firstn: bool = True,
    racks: int = 0,
) -> CrushMap:
    """root -> (racks ->) hosts -> osds, replicated + erasure rules.

    The standard topology of the reference's CRUSH tests (reference:
    src/test/crush/crush.cc builds analogous root/host trees).
    """
    cmap = CrushMap()
    cmap.type_names.update({1: "host", 2: "rack", 10: "root"})
    w = int(device_weight * 0x10000)
    host_ids = []
    osd = 0
    for h in range(n_hosts):
        items = list(range(osd, osd + osds_per_host))
        osd += osds_per_host
        b = make_straw2_bucket(
            cmap, 1, items, [w] * len(items), bucket_id=-(h + 2), name=f"host{h}"
        )
        host_ids.append(b.id)
    top_children = host_ids
    if racks:
        rack_ids = []
        per = max(1, n_hosts // racks)
        for r in range(racks):
            hs = host_ids[r * per : (r + 1) * per] or host_ids[-1:]
            b = make_straw2_bucket(
                cmap,
                2,
                hs,
                [cmap.buckets[h].weight for h in hs],
                bucket_id=-(n_hosts + 2 + r),
                name=f"rack{r}",
            )
            rack_ids.append(b.id)
        top_children = rack_ids
    make_straw2_bucket(
        cmap,
        10,
        top_children,
        [cmap.buckets[c].weight for c in top_children],
        bucket_id=-1,
        name="default",
    )
    cmap.max_devices = osd
    add_simple_rule(cmap, -1, 1, rule_id=0, firstn=firstn)
    # erasure-style indep rule over hosts (OSDMonitor's EC rule shape)
    add_simple_rule(cmap, -1, 1, rule_id=1, firstn=False)
    return cmap
