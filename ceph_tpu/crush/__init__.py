"""CRUSH placement: straw2 + rule interpreter, batched for TPU.

TPU-native rebuild of the reference's src/crush subsystem (SURVEY.md §2.2).
"""
from .builder import (
    add_simple_rule,
    build_flat_map,
    build_hierarchical_map,
    make_straw2_bucket,
)
from .mapper import CompiledCrushMap, crush_do_rule_batch
from .reference_mapper import bucket_straw2_choose, crush_do_rule
from .types import ITEM_NONE, CrushMap, Rule, RuleOp, RuleStep, Straw2Bucket, Tunables
from .wrapper import CrushWrapper

__all__ = [
    "ITEM_NONE",
    "CompiledCrushMap",
    "CrushMap",
    "CrushWrapper",
    "Rule",
    "RuleOp",
    "RuleStep",
    "Straw2Bucket",
    "Tunables",
    "add_simple_rule",
    "bucket_straw2_choose",
    "build_flat_map",
    "build_hierarchical_map",
    "crush_do_rule",
    "crush_do_rule_batch",
    "make_straw2_bucket",
]
