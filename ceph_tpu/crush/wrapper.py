"""CrushWrapper analog — name/id management, rule building, text form.

Reference: src/crush/CrushWrapper.{h,cc} — owns a crush_map, resolves
names<->ids, creates rules (add_simple_rule), and drives crush_do_rule with
allocated work buffers; plus src/crush/CrushCompiler.{h,cc} — the text <->
binary map grammar used by crushtool compile/decompile.

The text grammar here mirrors the crushtool decompile format closely enough
to be familiar (tunables / devices / types / buckets / rules sections), and
round-trips losslessly through parse_text/format_text — the property the
reference's cram tests assert for crushtool (reference:
src/test/cli/crushtool/*.t, SURVEY.md §4 ring 1).
"""
from __future__ import annotations

import numpy as np

from .mapper import CompiledCrushMap, crush_do_rule_batch
from .reference_mapper import crush_do_rule
from .types import CrushMap, Rule, RuleOp, RuleStep, Straw2Bucket, Tunables

_OP_NAMES = {
    RuleOp.TAKE: "take",
    RuleOp.CHOOSE_FIRSTN: "choose firstn",
    RuleOp.CHOOSE_INDEP: "choose indep",
    RuleOp.CHOOSELEAF_FIRSTN: "chooseleaf firstn",
    RuleOp.CHOOSELEAF_INDEP: "chooseleaf indep",
    RuleOp.EMIT: "emit",
    RuleOp.SET_CHOOSE_TRIES: "set_choose_tries",
    RuleOp.SET_CHOOSELEAF_TRIES: "set_chooseleaf_tries",
}


class CrushWrapper:
    """Owns a CrushMap; the API surface OSDMap and the tools build on."""

    def __init__(self, cmap: CrushMap | None = None):
        self.map = cmap or CrushMap()
        self._compiled: CompiledCrushMap | None = None

    # -- names ------------------------------------------------------------
    def name_of(self, item: int) -> str:
        if item >= 0:
            return self.map.device_names.get(item, f"osd.{item}")
        return self.map.bucket_names.get(item, f"bucket{item}")

    def id_of(self, name: str) -> int:
        if name.startswith("osd."):
            return int(name[4:])
        for bid, n in self.map.bucket_names.items():
            if n == name:
                return bid
        for did, n in self.map.device_names.items():
            if n == name:
                return did
        raise KeyError(f"unknown crush name {name!r}")

    def type_name(self, t: int) -> str:
        return self.map.type_names.get(t, f"type{t}")

    def type_id(self, name: str) -> int:
        for tid, n in self.map.type_names.items():
            if n == name:
                return tid
        raise KeyError(f"unknown crush type {name!r}")

    # -- mapping ----------------------------------------------------------
    def invalidate(self) -> None:
        self._compiled = None

    def compiled(self) -> CompiledCrushMap:
        if self._compiled is None:
            self._compiled = CompiledCrushMap(self.map)
        return self._compiled

    def do_rule(self, rule_id: int, x: int, numrep: int, weights) -> list[int]:
        """Single mapping (reference: CrushWrapper::do_rule)."""
        return crush_do_rule(self.map, rule_id, x, numrep, list(weights))

    def do_rule_batch(self, rule_id: int, xs, numrep: int, weights):
        """Batched mapping on device (the north-star sibling entry point)."""
        return crush_do_rule_batch(self.compiled(), rule_id, xs, numrep, weights)

    # -- text form (CrushCompiler analog) ---------------------------------
    def format_text(self) -> str:
        m = self.map
        t = m.tunables
        lines = ["# begin crush map"]
        for k in (
            "choose_total_tries",
            "choose_local_tries",
            "choose_local_fallback_tries",
            "chooseleaf_descend_once",
            "chooseleaf_vary_r",
            "chooseleaf_stable",
        ):
            lines.append(f"tunable {k} {getattr(t, k)}")
        lines.append("")
        lines.append("# devices")
        for d in range(m.max_devices):
            lines.append(f"device {d} {self.name_of(d)}")
        lines.append("")
        lines.append("# types")
        for tid in sorted(m.type_names):
            lines.append(f"type {tid} {m.type_names[tid]}")
        lines.append("")
        lines.append("# buckets")
        # topological order (children before parents) so parse_text never
        # sees a forward reference — crushtool decompile does the same
        emitted: list[int] = []
        done: set[int] = set()

        def emit(bid: int) -> None:
            if bid in done:
                return
            done.add(bid)
            for child in m.buckets[bid].items:
                if child < 0:
                    emit(child)
            emitted.append(bid)

        for bid in sorted(m.buckets):
            emit(bid)
        for bid in emitted:
            b = m.buckets[bid]
            lines.append(f"{self.type_name(b.type)} {self.name_of(bid)} {{")
            lines.append(f"\tid {bid}")
            lines.append("\talg straw2")
            lines.append("\thash 0\t# rjenkins1")
            for it, w in zip(b.items, b.weights):
                lines.append(f"\titem {self.name_of(it)} weight {w / 0x10000:.5f}")
            lines.append("}")
        lines.append("")
        lines.append("# rules")
        for rid in sorted(m.rules):
            r = m.rules[rid]
            lines.append(f"rule rule{rid} {{")
            lines.append(f"\tid {rid}")
            lines.append(f"\ttype {'replicated' if r.type == 1 else 'erasure'}")
            for s in r.steps:
                if s.op == RuleOp.TAKE:
                    lines.append(f"\tstep take {self.name_of(s.arg1)}")
                elif s.op == RuleOp.EMIT:
                    lines.append("\tstep emit")
                elif s.op in (RuleOp.SET_CHOOSE_TRIES, RuleOp.SET_CHOOSELEAF_TRIES):
                    lines.append(f"\tstep {_OP_NAMES[s.op]} {s.arg1}")
                else:
                    lines.append(
                        f"\tstep {_OP_NAMES[s.op]} {s.arg1} type "
                        f"{self.type_name(s.arg2)}"
                    )
            lines.append("}")
        lines.append("# end crush map")
        return "\n".join(lines) + "\n"

    @classmethod
    def parse_text(cls, text: str) -> "CrushWrapper":
        """Inverse of format_text (CrushCompiler::compile analog)."""
        w = cls(CrushMap())
        m = w.map
        m.type_names = {}
        cur_bucket: Straw2Bucket | None = None
        cur_rule: Rule | None = None
        pending_items: list[tuple[str, float]] = []
        bucket_header: tuple[str, str] | None = None
        names_to_resolve: dict[str, int] = {}

        def resolve(name: str) -> int:
            if name.startswith("osd."):
                return int(name[4:])
            if name in names_to_resolve:
                return names_to_resolve[name]
            raise KeyError(f"forward reference to {name!r}")

        for raw in text.splitlines():
            line = raw.split("#", 1)[0].strip()
            if not line:
                continue
            tok = line.split()
            # block context first: keywords like "type" also appear inside
            # rule/bucket bodies
            if cur_rule is not None:
                if tok[0] == "id":
                    cur_rule.rule_id = int(tok[1])
                elif tok[0] == "type":
                    cur_rule.type = 1 if tok[1] == "replicated" else 3
                elif tok[0] == "step":
                    op = " ".join(tok[1:3]) if tok[1] in ("choose", "chooseleaf") else tok[1]
                    if op == "take":
                        cur_rule.steps.append(
                            RuleStep(RuleOp.TAKE, resolve(tok[2]))
                        )
                    elif op == "emit":
                        cur_rule.steps.append(RuleStep(RuleOp.EMIT))
                        m.rules[cur_rule.rule_id] = cur_rule
                    elif op in ("set_choose_tries", "set_chooseleaf_tries"):
                        o = (
                            RuleOp.SET_CHOOSE_TRIES
                            if op == "set_choose_tries"
                            else RuleOp.SET_CHOOSELEAF_TRIES
                        )
                        cur_rule.steps.append(RuleStep(o, int(tok[2])))
                    else:
                        ops = {
                            "choose firstn": RuleOp.CHOOSE_FIRSTN,
                            "choose indep": RuleOp.CHOOSE_INDEP,
                            "chooseleaf firstn": RuleOp.CHOOSELEAF_FIRSTN,
                            "chooseleaf indep": RuleOp.CHOOSELEAF_INDEP,
                        }
                        n = int(tok[3])
                        tname = tok[5]
                        tid = next(
                            t for t, nm in m.type_names.items() if nm == tname
                        )
                        cur_rule.steps.append(RuleStep(ops[op], n, tid))
                elif tok[0] == "}":
                    cur_rule = None
            elif cur_bucket is not None:
                if tok[0] == "id":
                    cur_bucket.id = int(tok[1])
                elif tok[0] == "alg":
                    if tok[1] != "straw2":
                        raise ValueError(
                            f"bucket alg {tok[1]!r} unsupported (straw2 only; "
                            "see ceph_tpu/crush/types.py)"
                        )
                elif tok[0] == "hash":
                    cur_bucket.hash_id = int(tok[1])
                elif tok[0] == "item":
                    pending_items.append((tok[1], float(tok[3])))
                elif tok[0] == "}":
                    tname, bname = bucket_header
                    cur_bucket.type = next(
                        t for t, nm in m.type_names.items() if nm == tname
                    )
                    for iname, wf in pending_items:
                        cur_bucket.items.append(resolve(iname))
                        cur_bucket.weights.append(int(round(wf * 0x10000)))
                    m.buckets[cur_bucket.id] = cur_bucket
                    m.bucket_names[cur_bucket.id] = bname
                    names_to_resolve[bname] = cur_bucket.id
                    cur_bucket = None
            elif tok[0] == "tunable":
                setattr(m.tunables, tok[1], int(tok[2]))
            elif tok[0] == "device":
                did = int(tok[1])
                m.max_devices = max(m.max_devices, did + 1)
                if tok[2] != f"osd.{did}":
                    m.device_names[did] = tok[2]
            elif tok[0] == "type":
                m.type_names[int(tok[1])] = tok[2]
            elif tok[0] == "rule":
                cur_rule = Rule(rule_id=-1)
            elif tok[-1] == "{":
                bucket_header = (tok[0], tok[1])
                pending_items = []
                cur_bucket = Straw2Bucket(id=0, type=0)
        if 0 not in m.type_names:
            m.type_names[0] = "osd"
        return w
