"""CrushWrapper analog — name/id management, rule building, text form.

Reference: src/crush/CrushWrapper.{h,cc} — owns a crush_map, resolves
names<->ids, creates rules (add_simple_rule), and drives crush_do_rule with
allocated work buffers; plus src/crush/CrushCompiler.{h,cc} — the text <->
binary map grammar used by crushtool compile/decompile.

The text grammar here mirrors the crushtool decompile format closely enough
to be familiar (tunables / devices / types / buckets / rules sections), and
round-trips losslessly through parse_text/format_text — the property the
reference's cram tests assert for crushtool (reference:
src/test/cli/crushtool/*.t, SURVEY.md §4 ring 1).
"""
from __future__ import annotations

import copy
import hashlib
import threading
from collections import OrderedDict

import numpy as np

from .mapper import CompiledCrushMap, crush_do_rule_batch, validate_choose_args
from .reference_mapper import crush_do_rule
from .types import BUCKET_ALG_NAMES, BUCKET_STRAW, BUCKET_TREE, BUCKET_UNIFORM, CrushMap, Rule, RuleOp, RuleStep, Straw2Bucket, Tunables

#: process-wide CompiledCrushMap cache keyed by map CONTENT digest.
#: Every osdmap epoch the mon streams out decodes to a FRESH CrushWrapper
#: whose compiled form would otherwise rebuild (and re-trace every jitted
#: rule fn — seconds of host time) even though the crush content is
#: byte-identical; with per-epoch batch consumers (the mgr placement
#: scan, the balancer eval pair, `ceph osd df` deviation columns) that
#: retrace dominates everything.  Entries own a PRIVATE deepcopy of the
#: map so a source wrapper mutating its live map in place (mon-side
#: edits) can never skew a cached entry other wrappers share.
_COMPILED_CACHE_MAX = 8
_COMPILED_CACHE: OrderedDict[str, CompiledCrushMap] = OrderedDict()
_COMPILED_CACHE_LOCK = threading.Lock()

_OP_NAMES = {
    RuleOp.TAKE: "take",
    RuleOp.CHOOSE_FIRSTN: "choose firstn",
    RuleOp.CHOOSE_INDEP: "choose indep",
    RuleOp.CHOOSELEAF_FIRSTN: "chooseleaf firstn",
    RuleOp.CHOOSELEAF_INDEP: "chooseleaf indep",
    RuleOp.EMIT: "emit",
    RuleOp.SET_CHOOSE_TRIES: "set_choose_tries",
    RuleOp.SET_CHOOSELEAF_TRIES: "set_chooseleaf_tries",
}


class CrushWrapper:
    """Owns a CrushMap; the API surface OSDMap and the tools build on."""

    def __init__(self, cmap: CrushMap | None = None):
        self.map = cmap or CrushMap()
        self._compiled: CompiledCrushMap | None = None
        self._content_digest: str | None = None

    def __deepcopy__(self, memo):
        # a scratch copy (balancer pass) must not deep-copy the compiled
        # device tables and jitted rule fns — the copy re-resolves them
        # from the content-digest cache (crush content is unchanged by
        # pg_upmap edits, so it's a hit)
        cls = self.__class__
        new = cls.__new__(cls)
        memo[id(self)] = new
        new.map = copy.deepcopy(self.map, memo)
        new._compiled = None
        # a copy has identical content by definition — keep the digest
        # (None if never computed) so the scratch's first compiled()
        # lookup skips the O(map) format_text+sha1 rebuild
        new._content_digest = self._content_digest
        return new

    # -- names ------------------------------------------------------------
    def name_of(self, item: int) -> str:
        if item >= 0:
            return self.map.device_names.get(item, f"osd.{item}")
        return self.map.bucket_names.get(item, f"bucket{item}")

    def id_of(self, name: str) -> int:
        if name.startswith("osd."):
            return int(name[4:])
        for bid, n in self.map.bucket_names.items():
            if n == name:
                return bid
        for did, n in self.map.device_names.items():
            if n == name:
                return did
        raise KeyError(f"unknown crush name {name!r}")

    def type_name(self, t: int) -> str:
        return self.map.type_names.get(t, f"type{t}")

    def type_id(self, name: str) -> int:
        for tid, n in self.map.type_names.items():
            if n == name:
                return tid
        raise KeyError(f"unknown crush type {name!r}")

    # -- device classes ----------------------------------------------------
    # reference: CrushWrapper::class_name / set_item_class /
    # populate_classes / device_class_clone — per-class "shadow trees" so a
    # rule can `take default class ssd` and descend only over devices of
    # that class.  Shadow buckets are ordinary straw2 buckets (negative ids
    # past the originals, named "<bucket>~<class>"), so the batch mapper and
    # the C++ oracle need no special casing.

    def class_id(self, name: str, create: bool = False) -> int:
        for cid, n in self.map.class_names.items():
            if n == name:
                return cid
        if not create:
            raise KeyError(f"unknown device class {name!r}")
        cid = max(self.map.class_names, default=-1) + 1
        self.map.class_names[cid] = name
        return cid

    def set_device_class(self, osd: int, name: str) -> None:
        """Tag a device; call populate_classes() once after tagging."""
        self.map.device_classes[osd] = self.class_id(name, create=True)

    def get_device_class(self, osd: int) -> str | None:
        cid = self.map.device_classes.get(osd)
        return None if cid is None else self.map.class_names[cid]

    def _shadow_index(self) -> dict[int, tuple[int, int]]:
        """shadow bucket id -> (original bucket id, class id) — the single
        inversion of class_bucket shared by the shadow-tree builder, the
        original-bucket filter, and the text form."""
        return {
            sid: (bid, cid)
            for bid, per in self.map.class_bucket.items()
            for cid, sid in per.items()
        }

    def _original_buckets(self) -> list[int]:
        shadows = self._shadow_index()
        return [b for b in self.map.buckets if b not in shadows]

    def _topo_order(self, bucket_ids) -> list[int]:
        """Children-before-parents order over the given buckets — shared by
        the text form and the shadow-tree builder so both orderings can
        never drift apart."""
        order: list[int] = []
        done: set[int] = set()

        def emit(bid: int) -> None:
            if bid in done:
                return
            done.add(bid)
            for child in self.map.buckets[bid].items:
                if child < 0:
                    emit(child)
            order.append(bid)

        for bid in sorted(bucket_ids):
            emit(bid)
        return order

    def populate_classes(self) -> None:
        """(Re)build the per-class shadow trees (reference:
        CrushWrapper::populate_classes -> device_class_clone).

        Existing rules that TAKE a shadow bucket are re-pointed at the
        rebuilt shadow for the same (original bucket, class)."""
        m = self.map
        old_shadow = self._shadow_index()
        for sid in old_shadow:
            m.buckets.pop(sid, None)
            m.bucket_names.pop(sid, None)
        m.class_bucket = {}
        if m.class_names:
            # children-before-parents so a shadow can reference its
            # children's shadows
            order = self._topo_order(list(m.buckets))
            next_id = min(m.buckets, default=0) - 1
            for cid in sorted(m.class_names):
                shadow_of: dict[int, int] = {}
                for bid in order:
                    b = m.buckets[bid]
                    items: list[int] = []
                    weights: list[int] = []
                    for it, w in zip(b.items, b.weights):
                        if it >= 0:
                            if m.device_classes.get(it) == cid:
                                items.append(it)
                                weights.append(w)
                        else:
                            sid = shadow_of[it]
                            items.append(sid)
                            weights.append(m.buckets[sid].weight)
                    sid = next_id
                    next_id -= 1
                    m.buckets[sid] = Straw2Bucket(
                        id=sid, type=b.type, items=items, weights=weights
                    )
                    m.bucket_names[sid] = (
                        f"{self.name_of(bid)}~{m.class_names[cid]}"
                    )
                    shadow_of[bid] = sid
                    m.class_bucket.setdefault(bid, {})[cid] = sid
        for rule in m.rules.values():
            for step in rule.steps:
                if step.op == RuleOp.TAKE and step.arg1 in old_shadow:
                    bid, cid = old_shadow[step.arg1]
                    step.arg1 = m.class_bucket[bid][cid]
        self.invalidate()

    def shadow_root(self, root: int, class_name: str) -> int:
        """Shadow bucket id for (root, class) — what `take X class c`
        compiles to."""
        cid = self.class_id(class_name)
        try:
            return self.map.class_bucket[root][cid]
        except KeyError:
            raise KeyError(
                f"no shadow tree for bucket {root} class {class_name!r}; "
                "call populate_classes() after tagging devices"
            ) from None

    def add_simple_rule(
        self,
        root_name: str,
        failure_domain: str,
        device_class: str | None = None,
        rule_id: int | None = None,
        firstn: bool = True,
        num_replicas: int = 0,
    ):
        """reference: CrushWrapper::add_simple_rule (incl. the device-class
        form used by `ceph osd crush rule create-replicated`)."""
        from .builder import add_simple_rule as _add

        root = self.id_of(root_name)
        if device_class is not None:
            root = self.shadow_root(root, device_class)
        rule = _add(
            self.map,
            root,
            self.type_id(failure_domain),
            rule_id=rule_id,
            firstn=firstn,
            num_replicas=num_replicas,
        )
        self.invalidate()
        return rule

    def reweight_item(self, name: str, weight: float) -> None:
        """`ceph osd crush reweight` (reference: CrushWrapper::
        adjust_item_weightf + the upward weight propagation of
        crush_reweight_bucket): set a DEVICE's crush weight and
        recompute every ancestor bucket-entry weight bottom-up —
        including legacy straw/tree aux tables, which derive from
        weights and must follow a legitimate weight change (unlike
        ingest, where they are authoritative and kept verbatim)."""
        item = self.id_of(name)
        if item < 0:
            raise ValueError(f"{name!r} is a bucket; reweight devices")
        fixed = int(round(weight * 0x10000))
        if fixed < 0:
            raise ValueError(f"weight {weight} must be >= 0")
        found = False
        for b in self.map.buckets.values():
            for i, it in enumerate(b.items):
                if it == item:
                    b.weights[i] = fixed
                    found = True
        if not found:
            raise KeyError(f"device {name!r} is in no bucket")
        self._propagate_weights()
        self.invalidate()

    def add_bucket(self, name: str, type_name: str) -> int:
        """`ceph osd crush add-bucket` (reference:
        CrushWrapper::add_bucket): a new empty straw2 bucket, detached
        until `move` places it under a parent."""
        from .types import BUCKET_STRAW2, Straw2Bucket

        if name in {*self.map.bucket_names.values(),
                    *self.map.device_names.values()}:
            raise ValueError(f"name {name!r} exists")
        t = self.type_id(type_name)
        if t <= 0:
            raise ValueError(f"bad bucket type {type_name!r}")
        bid = min(self.map.buckets, default=0) - 1
        self.map.buckets[bid] = Straw2Bucket(
            id=bid, type=t, alg=BUCKET_STRAW2, items=[], weights=[])
        self.map.bucket_names[bid] = name
        self.invalidate()
        return bid

    def move_item(self, name: str, parent_name: str) -> None:
        """`ceph osd crush move` / `crush add` placement (reference:
        CrushWrapper::move_bucket / insert_item): detach `name` from
        its current parent (if any) and attach under `parent_name`,
        keeping its subtree weight; ancestors re-propagate."""
        item = self.id_of(name)
        dest = self.id_of(parent_name)
        if dest >= 0:
            raise ValueError(f"{parent_name!r} is a device")
        if dest not in self.map.buckets:
            raise KeyError(f"no bucket {parent_name!r}")
        if item >= 0 and item not in self.map.device_names \
                and item >= self.map.max_devices:
            # upstream rejects with ENOENT; inserting a ghost device
            # would map PGs onto an id no OSD owns
            raise KeyError(f"no device {name!r}")
        if item < 0:
            # moving a bucket under its own subtree would cycle
            probe = dest
            seen = set()
            while probe is not None and probe not in seen:
                if probe == item:
                    raise ValueError(
                        f"cannot move {name!r} under its own subtree")
                seen.add(probe)
                probe = next(
                    (b.id for b in self.map.buckets.values()
                     if probe in b.items), None)
        shadows = set(self._shadow_index())
        weight = None
        for b in self.map.buckets.values():
            if b.id not in shadows and item in b.items:
                i = b.items.index(item)
                weight = b.weights[i]
                del b.items[i]
                del b.weights[i]
        if weight is None:
            weight = (sum(self.map.buckets[item].weights)
                      if item < 0 else 0x10000)
        dst = self.map.buckets[dest]
        dst.items.append(item)
        dst.weights.append(weight)
        self._propagate_weights()
        if self.map.class_bucket:
            # class shadow trees mirror the real topology — rebuild
            # them or `take X class c` rules lose the moved subtree
            self.populate_classes()
        self.invalidate()

    def remove_item(self, name: str) -> None:
        """`ceph osd crush rm` (reference: CrushWrapper::remove_item):
        detach a device or EMPTY bucket from the tree."""
        item = self.id_of(name)
        if item < 0:
            if self.map.buckets.get(item) is None:
                raise KeyError(name)
            if self.map.buckets[item].items:
                raise ValueError(f"bucket {name!r} is not empty")
        shadows = set(self._shadow_index())
        found = False
        for b in self.map.buckets.values():
            if b.id not in shadows and item in b.items:
                i = b.items.index(item)
                del b.items[i]
                del b.weights[i]
                found = True
        if item >= 0 and not found:
            raise KeyError(f"{name!r} is in no bucket")
        if item < 0:
            del self.map.buckets[item]
            self.map.bucket_names.pop(item, None)
            for orig, per_class in list(self.map.class_bucket.items()):
                if orig == item:
                    for sid in per_class.values():
                        self.map.buckets.pop(sid, None)
                        self.map.bucket_names.pop(sid, None)
                    del self.map.class_bucket[orig]
        self._propagate_weights()
        if self.map.class_bucket:
            self.populate_classes()
        self.invalidate()

    def _propagate_weights(self) -> None:
        """Bottom-up: a bucket entry that IS a bucket weighs the sum of
        that bucket's items; straw/tree aux tables recompute from the
        new weights."""
        from .builder import calc_straws, calc_tree_nodes
        from .types import (BUCKET_STRAW, BUCKET_STRAW2,
                            BUCKET_TREE)

        order = self._topo_order(list(self.map.buckets))
        totals: dict[int, int] = {}
        for bid in order:  # children before parents
            b = self.map.buckets[bid]
            for i, it in enumerate(b.items):
                if it < 0:
                    b.weights[i] = totals.get(it, b.weights[i])
            totals[bid] = sum(b.weights)
            if getattr(b, "alg", BUCKET_STRAW2) == BUCKET_STRAW:
                b.straws = calc_straws(b.weights)
            elif getattr(b, "alg", BUCKET_STRAW2) == BUCKET_TREE:
                b.node_weights = calc_tree_nodes(b.weights)

    def get_rule_weight_osd_map(self, rule_id: int) -> dict[int, float]:
        """reference: CrushWrapper::get_rule_weight_osd_map — the crush
        weight of every device reachable from the rule's TAKE roots (so a
        device-class rule only counts its shadow subtree).  Consumers:
        utilization expectations (CrushTester) and pool balance targets."""
        out: dict[int, float] = {}

        def walk(bid: int) -> None:
            b = self.map.buckets[bid]
            for it, w in zip(b.items, b.weights):
                if it >= 0:
                    out[it] = out.get(it, 0.0) + w / 0x10000
                else:
                    walk(it)

        for step in self.map.rules[rule_id].steps:
            if step.op == RuleOp.TAKE:
                if step.arg1 >= 0:
                    out[step.arg1] = out.get(step.arg1, 0.0) + 1.0
                else:
                    walk(step.arg1)
        return out

    # -- choose_args (weight-sets) ----------------------------------------
    def set_choose_args(
        self, name: str, bucket_id: int, weight_set: list[list[int]]
    ) -> None:
        """Install an alternate weight set for one bucket (reference:
        crush_choose_arg_map; written by the balancer's crush-compat mode).

        weight_set: [positions][bucket size] 16.16 fixed-point weights."""
        if not weight_set:
            raise ValueError("weight_set must have at least one position row")
        b = self.map.buckets[bucket_id]
        for ws in weight_set:
            if len(ws) != b.size:
                raise ValueError(
                    f"weight_set row has {len(ws)} entries, bucket "
                    f"{bucket_id} has {b.size} items"
                )
        self.map.choose_args.setdefault(name, {})[bucket_id] = [
            list(ws) for ws in weight_set
        ]
        self.invalidate()

    def rm_choose_args(self, name: str) -> None:
        self.map.choose_args.pop(name, None)
        self.invalidate()

    # -- mapping ----------------------------------------------------------
    def invalidate(self) -> None:
        self._compiled = None
        self._content_digest = None

    def content_digest(self) -> str:
        """Digest of the full text form — the same canonical content an
        osdmap round-trips (to_json carries crush as text), so two
        wrappers mapping identically share one digest."""
        if self._content_digest is None:
            self._content_digest = hashlib.sha1(
                self.format_text().encode()).hexdigest()
        return self._content_digest

    def compiled(self) -> CompiledCrushMap:
        if self._compiled is None:
            key = self.content_digest()
            with _COMPILED_CACHE_LOCK:
                hit = _COMPILED_CACHE.get(key)
                if hit is not None:
                    _COMPILED_CACHE.move_to_end(key)
            if hit is None:
                built = CompiledCrushMap(copy.deepcopy(self.map))
                with _COMPILED_CACHE_LOCK:
                    # first build wins so concurrent callers share one
                    # entry (and its lazily-built jitted rule fns)
                    hit = _COMPILED_CACHE.setdefault(key, built)
                    _COMPILED_CACHE.move_to_end(key)
                    while len(_COMPILED_CACHE) > _COMPILED_CACHE_MAX:
                        _COMPILED_CACHE.popitem(last=False)
            self._compiled = hit
        return self._compiled

    def do_rule(
        self,
        rule_id: int,
        x: int,
        numrep: int,
        weights,
        choose_args: str | None = None,
    ) -> list[int]:
        """Single mapping (reference: CrushWrapper::do_rule; choose_args
        names a weight-set, the choose_args_index analog)."""
        ca = (
            validate_choose_args(self.map, choose_args)
            if choose_args is not None
            else None
        )
        return crush_do_rule(
            self.map, rule_id, x, numrep, list(weights), choose_args=ca
        )

    def do_rule_batch(
        self,
        rule_id: int,
        xs,
        numrep: int,
        weights,
        choose_args: str | None = None,
    ):
        """Batched mapping on device (the north-star sibling entry point)."""
        return crush_do_rule_batch(
            self.compiled(),
            rule_id,
            xs,
            numrep,
            weights,
            choose_args=choose_args,
        )

    # -- text form (CrushCompiler analog) ---------------------------------
    def format_text(self) -> str:
        m = self.map
        t = m.tunables
        lines = ["# begin crush map"]
        for k in (
            "choose_total_tries",
            "choose_local_tries",
            "choose_local_fallback_tries",
            "chooseleaf_descend_once",
            "chooseleaf_vary_r",
            "chooseleaf_stable",
        ):
            lines.append(f"tunable {k} {getattr(t, k)}")
        if m.class_names:
            # Divergence from crushtool's grammar, on purpose: class ids are
            # explicit (and precede the devices that name them) so
            # decompile→compile preserves them.  Shadow-tree bucket ids
            # derive from class-id order, and those ids feed the straw2
            # descent hash — inferring class ids from device-line order
            # would silently remap every class-rule pool whose classes were
            # created in non-device-id order.
            lines.append("")
            lines.append("# classes")
            for cid in sorted(m.class_names):
                lines.append(f"class {cid} {m.class_names[cid]}")
        lines.append("")
        lines.append("# devices")
        for d in range(m.max_devices):
            cls = self.get_device_class(d)
            suffix = f" class {cls}" if cls else ""
            lines.append(f"device {d} {self.name_of(d)}{suffix}")
        lines.append("")
        lines.append("# types")
        for tid in sorted(m.type_names):
            lines.append(f"type {tid} {m.type_names[tid]}")
        lines.append("")
        lines.append("# buckets")
        # topological order (children before parents) so parse_text never
        # sees a forward reference — crushtool decompile does the same.
        # Shadow buckets are omitted: like crushtool, the text form shows
        # only the original hierarchy and class-annotated take steps, and
        # the compiler rebuilds the shadow trees.
        emitted = self._topo_order(self._original_buckets())
        for bid in emitted:
            b = m.buckets[bid]
            lines.append(f"{self.type_name(b.type)} {self.name_of(bid)} {{")
            lines.append(f"\tid {bid}")
            lines.append(f"\talg {BUCKET_ALG_NAMES[getattr(b, 'alg', 5)]}")
            lines.append("\thash 0\t# rjenkins1")
            for it, w in zip(b.items, b.weights):
                lines.append(f"\titem {self.name_of(it)} weight {w / 0x10000:.5f}")
            lines.append("}")
        lines.append("")
        lines.append("# rules")
        shadow_to = self._shadow_index()
        for rid in sorted(m.rules):
            r = m.rules[rid]
            lines.append(f"rule rule{rid} {{")
            lines.append(f"\tid {rid}")
            lines.append(f"\ttype {'replicated' if r.type == 1 else 'erasure'}")
            for s in r.steps:
                if s.op == RuleOp.TAKE:
                    if s.arg1 in shadow_to:
                        bid, cid = shadow_to[s.arg1]
                        lines.append(
                            f"\tstep take {self.name_of(bid)} "
                            f"class {m.class_names[cid]}"
                        )
                    else:
                        lines.append(f"\tstep take {self.name_of(s.arg1)}")
                elif s.op == RuleOp.EMIT:
                    lines.append("\tstep emit")
                elif s.op in (RuleOp.SET_CHOOSE_TRIES, RuleOp.SET_CHOOSELEAF_TRIES):
                    lines.append(f"\tstep {_OP_NAMES[s.op]} {s.arg1}")
                else:
                    lines.append(
                        f"\tstep {_OP_NAMES[s.op]} {s.arg1} type "
                        f"{self.type_name(s.arg2)}"
                    )
            lines.append("}")
        if m.choose_args:
            lines.append("")
            lines.append("# choose_args")
            for name in sorted(m.choose_args):
                lines.append(f"choose_args {name} {{")
                for bid in sorted(m.choose_args[name]):
                    rows = " ".join(
                        "[" + " ".join(f"{w / 0x10000:.5f}" for w in ws) + "]"
                        for ws in m.choose_args[name][bid]
                    )
                    lines.append(f"\tbucket {bid} weight_set {rows}")
                lines.append("}")
        lines.append("# end crush map")
        return "\n".join(lines) + "\n"

    @classmethod
    def parse_text(cls, text: str) -> "CrushWrapper":
        """Inverse of format_text (CrushCompiler::compile analog)."""
        w = cls(CrushMap())
        m = w.map
        m.type_names = {}
        cur_bucket: Straw2Bucket | None = None
        cur_rule: Rule | None = None
        cur_choose_args: str | None = None
        pending_items: list[tuple[str, float]] = []
        bucket_header: tuple[str, str] | None = None
        names_to_resolve: dict[str, int] = {}
        # take-with-class steps resolve only after the shadow trees are
        # rebuilt at the end of the parse: (RuleStep, root name, class name)
        pending_class_takes: list[tuple[RuleStep, str, str]] = []

        def resolve(name: str) -> int:
            if name.startswith("osd."):
                return int(name[4:])
            if name in names_to_resolve:
                return names_to_resolve[name]
            raise KeyError(f"forward reference to {name!r}")

        for raw in text.splitlines():
            line = raw.split("#", 1)[0].strip()
            if not line:
                continue
            tok = line.split()
            # block context first: keywords like "type" also appear inside
            # rule/bucket bodies
            if cur_rule is not None:
                if tok[0] == "id":
                    cur_rule.rule_id = int(tok[1])
                elif tok[0] == "type":
                    cur_rule.type = 1 if tok[1] == "replicated" else 3
                elif tok[0] == "step":
                    op = " ".join(tok[1:3]) if tok[1] in ("choose", "chooseleaf") else tok[1]
                    if op == "take":
                        step = RuleStep(RuleOp.TAKE, 0)
                        if len(tok) >= 5 and tok[3] == "class":
                            pending_class_takes.append((step, tok[2], tok[4]))
                        else:
                            step.arg1 = resolve(tok[2])
                        cur_rule.steps.append(step)
                    elif op == "emit":
                        cur_rule.steps.append(RuleStep(RuleOp.EMIT))
                        m.rules[cur_rule.rule_id] = cur_rule
                    elif op in ("set_choose_tries", "set_chooseleaf_tries"):
                        o = (
                            RuleOp.SET_CHOOSE_TRIES
                            if op == "set_choose_tries"
                            else RuleOp.SET_CHOOSELEAF_TRIES
                        )
                        cur_rule.steps.append(RuleStep(o, int(tok[2])))
                    else:
                        ops = {
                            "choose firstn": RuleOp.CHOOSE_FIRSTN,
                            "choose indep": RuleOp.CHOOSE_INDEP,
                            "chooseleaf firstn": RuleOp.CHOOSELEAF_FIRSTN,
                            "chooseleaf indep": RuleOp.CHOOSELEAF_INDEP,
                        }
                        n = int(tok[3])
                        tname = tok[5]
                        tid = next(
                            t for t, nm in m.type_names.items() if nm == tname
                        )
                        cur_rule.steps.append(RuleStep(ops[op], n, tid))
                elif tok[0] == "}":
                    cur_rule = None
            elif cur_bucket is not None:
                if tok[0] == "id":
                    cur_bucket.id = int(tok[1])
                elif tok[0] == "alg":
                    by_name = {v: k for k, v in BUCKET_ALG_NAMES.items()}
                    if tok[1] not in by_name:
                        raise ValueError(f"bucket alg {tok[1]!r} unknown")
                    cur_bucket.alg = by_name[tok[1]]
                elif tok[0] == "hash":
                    cur_bucket.hash_id = int(tok[1])
                elif tok[0] == "item":
                    pending_items.append((tok[1], float(tok[3])))
                elif tok[0] == "}":
                    tname, bname = bucket_header
                    cur_bucket.type = next(
                        t for t, nm in m.type_names.items() if nm == tname
                    )
                    for iname, wf in pending_items:
                        cur_bucket.items.append(resolve(iname))
                        cur_bucket.weights.append(int(round(wf * 0x10000)))
                    # legacy aux tables are BUILD-time artifacts: derive
                    # them on ingest exactly as the builder does — and
                    # apply the builder's validation so the same invalid
                    # map is rejected regardless of entry point
                    if (
                        cur_bucket.alg == BUCKET_UNIFORM
                        and len(set(cur_bucket.weights)) > 1
                    ):
                        raise ValueError(
                            f"uniform bucket {bname!r} has unequal item "
                            f"weights"
                        )
                    if cur_bucket.alg == BUCKET_STRAW:
                        from .builder import calc_straws

                        cur_bucket.straws = calc_straws(cur_bucket.weights)
                    elif cur_bucket.alg == BUCKET_TREE:
                        from .builder import calc_tree_nodes

                        cur_bucket.node_weights = calc_tree_nodes(
                            cur_bucket.weights)
                    m.buckets[cur_bucket.id] = cur_bucket
                    m.bucket_names[cur_bucket.id] = bname
                    names_to_resolve[bname] = cur_bucket.id
                    cur_bucket = None
            elif cur_choose_args is not None:
                if tok[0] == "bucket":
                    bid = int(tok[1])
                    rows = " ".join(tok[3:])
                    weight_set = [
                        [
                            int(round(float(v) * 0x10000))
                            for v in row.split()
                        ]
                        for row in rows.replace("[", " ").split("]")
                        if row.strip()
                    ]
                    m.choose_args.setdefault(cur_choose_args, {})[bid] = (
                        weight_set
                    )
                elif tok[0] == "}":
                    cur_choose_args = None
            elif tok[0] == "tunable":
                setattr(m.tunables, tok[1], int(tok[2]))
            elif tok[0] == "device":
                did = int(tok[1])
                m.max_devices = max(m.max_devices, did + 1)
                if tok[2] != f"osd.{did}":
                    m.device_names[did] = tok[2]
                if len(tok) >= 5 and tok[3] == "class":
                    m.device_classes[did] = w.class_id(tok[4], create=True)
            elif tok[0] == "choose_args":
                cur_choose_args = tok[1]
            elif tok[0] == "type":
                m.type_names[int(tok[1])] = tok[2]
            elif tok[0] == "class":
                m.class_names[int(tok[1])] = tok[2]
            elif tok[0] == "rule":
                cur_rule = Rule(rule_id=-1)
            elif tok[-1] == "{":
                bucket_header = (tok[0], tok[1])
                pending_items = []
                cur_bucket = Straw2Bucket(id=0, type=0)
        if 0 not in m.type_names:
            m.type_names[0] = "osd"
        if m.class_names:
            w.populate_classes()
        for step, root_name, cls_name in pending_class_takes:
            step.arg1 = w.shadow_root(
                names_to_resolve[root_name], cls_name
            )
        return w
