"""Explicitly-batched CRUSH choose kernels — the SPMD core of the TPU
mapper (reference: src/crush/mapper.c :: crush_choose_firstn /
crush_choose_indep / bucket_straw2_choose / is_out, batched over x).

Every function here takes [B]-shaped lane arrays instead of scalars —
manual SPMD rather than jax.vmap — for two reasons:

- the straw2 hot loop ([B, S] hash + ln + draw) can then be swapped
  between a jnp formulation (CPU) and one fused Pallas launch per retry
  iteration (TPU) without fighting vmap's pallas_call batching rules;
- retry loops become masked lax.while_loops whose trip count is the
  max over lanes, exactly the semantics vmap gives, but with the state
  laid out for full-tile VPU work at every iteration.

Bit-exactness contract: identical output to reference_mapper.crush_do_rule
and the C++ oracle for every input — enforced by tests/test_crush.py.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .hash import crush_hash32_2, crush_hash32_3
from .ln_table import LN_BIAS
from .types import ITEM_NONE

S64_MIN = np.int64(np.iinfo(np.int64).min)


def _div64_trunc(a, b):
    """C-style truncating signed division (div64_s64)."""
    q = jnp.abs(a) // jnp.abs(b)
    return jnp.where((a < 0) != (b < 0), -q, q).astype(jnp.int64)


def ln_scores_jnp(cm, x, items, r):
    """[B, S] crush_ln(hash3(x, item, r) & 0xffff) via XLA: elementwise
    rjenkins hash + full-table gather — fast on CPU backends."""
    u = (
        crush_hash32_3(
            x[:, None].astype(jnp.uint32),
            items.astype(jnp.uint32),
            r[:, None].astype(jnp.uint32),
        ).astype(jnp.int64)
        & 0xFFFF
    )
    return jnp.take(cm.ln_table, u, axis=None)


def ln_planes_pallas(cm, x, items, r):
    """[B, S] hash+ln via the fused Pallas kernel (TPU: no vector gather —
    see ops/pallas_crush.py), returned as (hi, lo) int32 planes (bits
    24..47 / 0..23).  Pads B to the tile multiple and S to the 128-lane
    multiple, slices back."""
    from ..ops import pallas_crush
    from ..ops.pallas_crush import straw2_scores_pallas

    from ..ops.pallas_crush import CHUNK

    B, S = items.shape
    # clamp the tile to the CHUNK-aligned batch: padding every small
    # batch up to a wide tile would compute up to tile/B times the
    # needed hash+ln work (review r5); tile and loop_slabs are CALL-TIME
    # module attrs so the mapper's fallback mutations take effect on the
    # next call
    tile = min(pallas_crush.DEFAULT_TILE, -(-B // CHUNK) * CHUNK)
    Bp = -(-B // tile) * tile
    Sp = -(-S // 128) * 128
    xi = x.astype(jnp.int32)
    ri = r.astype(jnp.int32)
    ii = items.astype(jnp.int32)
    if Bp != B:
        xi = jnp.pad(xi, (0, Bp - B))
        ri = jnp.pad(ri, (0, Bp - B))
        ii = jnp.pad(ii, ((0, Bp - B), (0, 0)))
    if Sp != S:
        ii = jnp.pad(ii, ((0, 0), (0, Sp - S)))
    # interpret mode keeps this path testable on CPU hosts; the backend
    # name comes from the policy seam (cephtopo)
    from ..common.device_policy import get_device_policy

    hi, lo = straw2_scores_pallas(
        xi, ri, ii, tile=tile,
        loop_slabs=pallas_crush.LOOP_SLABS,
        interpret=get_device_policy().backend() == "cpu",
    )
    return hi[:B, :S], lo[:B, :S]


def ln_planes_jnp(cm, x, items, r):
    """(hi, lo) int32 crush_ln planes via the int32 plane-table gather —
    the CPU twin of ln_planes_pallas for the limb engine (no x64)."""
    u = (
        crush_hash32_3(
            x[:, None].astype(jnp.uint32),
            items.astype(jnp.uint32),
            r[:, None].astype(jnp.uint32),
        ).astype(jnp.int32)
        & 0xFFFF
    )
    return (jnp.take(cm.ln_hi_table, u, axis=None),
            jnp.take(cm.ln_lo_table, u, axis=None))


def ln_scores_pallas(cm, x, items, r):
    """int64 crush_ln via the Pallas kernel (the x64 gather-engine path)."""
    hi, lo = ln_planes_pallas(cm, x, items, r)
    return (hi.astype(jnp.int64) << 24) | lo.astype(jnp.int64)


def straw2_choose_b(cm, score_fn, bucket_idx, x, r, cweights, position):
    """bucket_straw2_choose over lanes: bucket_idx/x/r/position are [B];
    returns the chosen item per lane ([B] int32, ITEM_NONE for empty
    buckets).  `score_fn(cm, x, items, r) -> int64 crush_ln values` is the
    pluggable hot path (hash + table gather on CPU, fused Pallas on TPU).
    """
    bidx = jnp.clip(bucket_idx, 0, cm.items.shape[0] - 1)
    items = jnp.take(cm.items, bidx, axis=0)          # [B, S] row gather
    if cweights is None:
        weights = jnp.take(cm.weights, bidx, axis=0)  # [B, S]
    else:
        pos = jnp.minimum(position, cweights.shape[0] - 1)
        flat = cweights.reshape(-1, cweights.shape[-1])
        weights = jnp.take(flat, pos * cm.items.shape[0] + bidx, axis=0)
    size = jnp.take(cm.sizes, bidx)                   # [B]
    ln = score_fn(cm, x, items, r) - LN_BIAS
    draw = _div64_trunc(ln, jnp.maximum(weights, 1))
    slot = jnp.arange(items.shape[1])
    valid = (slot[None, :] < size[:, None]) & (weights > 0)
    draw = jnp.where(valid, draw, S64_MIN)
    choice = jnp.argmax(draw, axis=1)                 # first max, like C
    picked = jnp.take_along_axis(items, choice[:, None], axis=1)[:, 0]
    return jnp.where(size > 0, picked, ITEM_NONE)


def item_type_b(cm, item):
    """Type of each item: devices 0, buckets their declared type."""
    idx = jnp.clip(jnp.where(item < 0, -1 - item, 0), 0, cm.types.shape[0] - 1)
    return jnp.where(item < 0, jnp.take(cm.types, idx), 0)


def is_out_b(weightvec, item, x):
    """mapper.c :: is_out over lanes (probabilistic reweight reject)."""
    n = weightvec.shape[0]
    idx = jnp.clip(item, 0, n - 1)
    w = jnp.take(weightvec, idx).astype(jnp.int64)
    oob = item >= n
    h = (
        crush_hash32_2(x.astype(jnp.uint32), item.astype(jnp.uint32))
        .astype(jnp.int64)
        & 0xFFFF
    )
    return oob | (w == 0) | ((w < 0x10000) & (h >= w))


class I64Engine:
    """The original draw engine: int64 crush_ln, div64 draws, jnp.take
    row gathers — native-fast on CPU backends, requires an x64 scope."""

    needs_x64 = True

    def __init__(self, cm, score_fn, weightvec, cweights):
        self.cm = cm
        self.score_fn = score_fn
        self.weightvec = weightvec
        self.cweights = cweights

    def choose(self, bucket_idx, x, r, position):
        return straw2_choose_b(self.cm, self.score_fn, bucket_idx, x, r,
                               self.cweights, position)

    def item_type(self, item):
        return item_type_b(self.cm, item)

    def is_out(self, item, x):
        return is_out_b(self.weightvec, item, x)


class LimbEngine:
    """TPU draw engine (crush/engine.py): one-hot fat-table gathers on
    the MXU + magic-divisor limb draws — no int64, no x64 scope, no
    vector gathers (round-4 verdict item #2)."""

    needs_x64 = False

    def __init__(self, cm, score_fn, weightvec, cweights):
        from .engine import build_weightvec_planes, is_out_limb

        self.cm = cm
        self.score_fn = score_fn  # returns (hi, lo) int32 planes
        self.cweights = cweights  # LimbTables with .positions, or None
        self.n_osd = weightvec.shape[0]
        self.wplanes = build_weightvec_planes(weightvec)
        self._is_out = is_out_limb

    def choose(self, bucket_idx, x, r, position):
        from .engine import straw2_choose_limb

        return straw2_choose_limb(self.cm, self.score_fn, bucket_idx, x,
                                  r, self.cweights, position)

    def item_type(self, item):
        from .engine import item_type_limb

        return item_type_limb(self.cm, item)

    def is_out(self, item, x):
        return self._is_out(self.wplanes, self.n_osd, item, x)


def descend_b(eng, root, x, r, want_type: int, position):
    """Walk intervening buckets until an item of want_type appears
    (mapper.c's retry_bucket descent), all lanes in lock-step; dead ends
    (empty bucket, device of the wrong type) yield ITEM_NONE."""

    def cond(item):
        live = (item < 0) & (item != ITEM_NONE)
        return jnp.any(live & (eng.item_type(item) != want_type))

    def body(item):
        live = (item < 0) & (item != ITEM_NONE)
        go = live & (eng.item_type(item) != want_type)
        nxt = eng.choose(-1 - item, x, r, position)
        return jnp.where(go, nxt, item)

    item = jax.lax.while_loop(
        cond, body, jnp.broadcast_to(jnp.asarray(root, jnp.int32), x.shape)
    )
    if want_type != 0:
        item = jnp.where(item >= 0, ITEM_NONE, item)
    return item


def _leaf_firstn_b(
    eng, x, item, sub_r, outpos, out2, recurse_tries, active,
):
    """Nested chooseleaf descent over lanes (stable=1: one rep,
    r = sub_r + ftotal, collisions vs out2[:, :outpos])."""
    S = out2.shape[1]

    def body(state):
        ftotal, leaf0, done = state
        leaf = descend_b(eng, item, x, sub_r + ftotal, 0, outpos)
        is_dev = leaf >= 0
        collide = (
            jnp.any(
                (out2 == leaf[:, None])
                & (jnp.arange(S)[None, :] < outpos[:, None]),
                axis=1,
            )
            & is_dev
        )
        reject = jnp.where(is_dev, eng.is_out(leaf, x), True)
        ok = is_dev & ~collide & ~reject & active
        return (
            ftotal + 1,
            jnp.where(ok & ~done, leaf, leaf0),
            done | ok,
        )

    def cond(state):
        ftotal, _, done = state
        return jnp.any(active & ~done & (ftotal < recurse_tries))

    B = x.shape[0]
    _, leaf, done = jax.lax.while_loop(
        cond,
        body,
        (
            jnp.zeros((B,), jnp.int32),
            jnp.full((B,), ITEM_NONE, jnp.int32),
            jnp.zeros((B,), bool),
        ),
    )
    return jnp.where(done, leaf, ITEM_NONE), done


def choose_firstn_b(
    eng, x, root, numrep: int, want_type: int,
    tries: int, recurse: bool, recurse_tries: int, parent_ok,
):
    """crush_choose_firstn over lanes.  `root` is [B] (per-lane parent —
    multi-choose steps descend from different buckets per lane);
    `parent_ok` masks lanes whose parent is a real bucket.  Returns
    (out [B, numrep], out2 [B, numrep], count [B])."""
    B = x.shape[0]
    S = numrep
    out = jnp.full((B, S), ITEM_NONE, jnp.int32)
    out2 = jnp.full((B, S), ITEM_NONE, jnp.int32)
    outpos = jnp.zeros((B,), jnp.int32)

    for rep in range(numrep):

        def try_body(state, rep=rep):
            ftotal, item0, leaf0, done = state
            active = parent_ok & ~done & (ftotal < tries)
            r = rep + ftotal
            cand = descend_b(eng, root, x, r, want_type, outpos)
            dead = cand == ITEM_NONE
            collide = (
                jnp.any(
                    (out == cand[:, None])
                    & (jnp.arange(S)[None, :] < outpos[:, None]),
                    axis=1,
                )
                & ~dead
            )
            if recurse:
                use_leaf = (cand < 0) & ~dead & ~collide
                leaf_r, leaf_ok_r = _leaf_firstn_b(
                    eng, x, cand, r, outpos, out2,
                    recurse_tries, active & use_leaf,
                )
                direct_ok = (cand >= 0) & ~eng.is_out(cand, x)
                leaf = jnp.where(use_leaf, leaf_r, cand)
                leaf_ok = jnp.where(use_leaf, leaf_ok_r, direct_ok)
                reject = ~leaf_ok
            else:
                leaf = cand
                reject = dead | jnp.where(
                    cand >= 0, eng.is_out(cand, x), False
                )
            ok = active & ~dead & ~collide & ~reject
            return (
                ftotal + 1,
                jnp.where(ok & ~done, cand, item0),
                jnp.where(ok & ~done, leaf, leaf0),
                done | ok,
            )

        def try_cond(state):
            ftotal, _, _, done = state
            return jnp.any(parent_ok & ~done & (ftotal < tries))

        _, item, leaf, done = jax.lax.while_loop(
            try_cond,
            try_body,
            (
                jnp.zeros((B,), jnp.int32),
                jnp.full((B,), ITEM_NONE, jnp.int32),
                jnp.full((B,), ITEM_NONE, jnp.int32),
                jnp.zeros((B,), bool),
            ),
        )
        slotmask = jnp.arange(S)[None, :] == outpos[:, None]
        put = done[:, None] & slotmask
        out = jnp.where(put, item[:, None], out)
        out2 = jnp.where(put, leaf[:, None], out2)
        outpos = outpos + done.astype(jnp.int32)
    return out, out2, outpos


def choose_indep_b(
    eng, x, root, numrep: int, want_type: int,
    tries: int, recurse: bool, recurse_tries: int, parent_ok,
):
    """crush_choose_indep over lanes: positional retries
    r = rep + numrep*ftotal; failed positions stay ITEM_NONE (EC shard
    holes).  Returns (out [B, numrep], out2 [B, numrep])."""
    B = x.shape[0]
    S = numrep
    out = jnp.full((B, S), ITEM_NONE, jnp.int32)
    out2 = jnp.full((B, S), ITEM_NONE, jnp.int32)
    placed = ~parent_ok[:, None] & jnp.ones((B, S), bool)

    def ft_body(state):
        ftotal, out, out2, placed = state
        for rep in range(numrep):
            active = parent_ok & ~placed[:, rep]
            # indep rounds share one global ftotal (scalar) — broadcast to
            # lanes for the descend/straw2 [B] contract
            r = jnp.broadcast_to(rep + numrep * ftotal, x.shape).astype(jnp.int32)
            # weight-set position is the choose's outpos — 0 at the top
            # level (mapper.c); the leaf recursion below uses rep
            cand = descend_b(
                eng, root, x, r, want_type, jnp.zeros((B,), jnp.int32),
            )
            dead = cand == ITEM_NONE
            collide = jnp.any((out == cand[:, None]) & placed, axis=1) & ~dead

            if recurse:
                use_leaf = (cand < 0) & ~dead & ~collide

                def lbody(state, rep=rep, r=r, cand=cand):
                    lf, leaf0, done = state
                    leaf = descend_b(
                        eng, cand, x, rep + numrep * lf + r, 0,
                        jnp.full((B,), rep, jnp.int32),
                    )
                    ok = (leaf >= 0) & ~eng.is_out(leaf, x)
                    return lf + 1, jnp.where(ok & ~done, leaf, leaf0), done | ok

                def lcond(state):
                    lf, _, done = state
                    return jnp.any(~done & (lf < recurse_tries))

                _, lleaf, lok = jax.lax.while_loop(
                    lcond,
                    lbody,
                    (
                        jnp.zeros((B,), jnp.int32),
                        jnp.full((B,), ITEM_NONE, jnp.int32),
                        jnp.zeros((B,), bool),
                    ),
                )
                direct_ok = (cand >= 0) & ~eng.is_out(cand, x)
                leaf = jnp.where(use_leaf, jnp.where(lok, lleaf, ITEM_NONE), cand)
                leaf_ok = jnp.where(use_leaf, lok, direct_ok)
                ok = ~dead & ~collide & leaf_ok
            else:
                leaf = cand
                reject = dead | jnp.where(
                    cand >= 0, eng.is_out(cand, x), False
                )
                ok = ~dead & ~collide & ~reject

            take = active & ok
            # structural dead end: permanent NONE for this position
            # (mapper.c keeps out[rep] = ITEM_NONE and never retries it)
            dead_perm = active & dead
            slotmask = jnp.arange(S)[None, :] == rep
            out = jnp.where(take[:, None] & slotmask, cand[:, None], out)
            out2 = jnp.where(take[:, None] & slotmask, leaf[:, None], out2)
            placed = placed | ((take | dead_perm)[:, None] & slotmask)
        return ftotal + 1, out, out2, placed

    def ft_cond(state):
        ftotal, _, _, placed = state
        return (ftotal < tries) & jnp.any(~placed)

    _, out, out2, _ = jax.lax.while_loop(
        ft_cond, ft_body, (jnp.int32(0), out, out2, placed)
    )
    return out, out2
