"""Scalar CRUSH mapper — the Python mirror of mapper.c, semantic ground truth.

Reference: src/crush/mapper.c :: crush_do_rule, crush_choose_firstn,
crush_choose_indep, the per-algorithm bucket chooses (straw2 plus the
legacy uniform/list/tree/straw types), is_out.  This is the slow,
readable twin of the vectorized TPU mapper (ceph_tpu/crush/mapper.py) and of
the C++ oracle (native/crush_oracle.cc); all three must agree bit-for-bit.

Implemented tunable profile: the modern defaults (Tunables dataclass) —
choose_local_tries=0 and choose_local_fallback_tries=0 collapse the
legacy local-retry modes, so on collision/rejection the descent restarts
from the TAKE bucket with r' = r + ftotal (firstn) or r + numrep*ftotal
(indep), bounded by choose_total_tries.  chooseleaf_stable=1 and
chooseleaf_vary_r=1 semantics are implemented for the recursive leaf step.

Provenance caveat (SURVEY.md §0): mirrors documented mapper.c behavior; the
empty reference mount means upstream equality is asserted between the three
in-repo implementations, not against Ceph binaries, this round.
"""
from __future__ import annotations

from .ln_table import CRUSH_LN_TABLE, LN_BIAS
from .types import (
    BUCKET_LIST,
    BUCKET_STRAW,
    BUCKET_STRAW2,
    BUCKET_TREE,
    BUCKET_UNIFORM,
    ITEM_NONE,
    CrushMap,
    RuleOp,
    Straw2Bucket,
)

S64_MIN = -(1 << 63)
_M32 = 0xFFFFFFFF
_SEED = 1315423911


def _mix_int(a: int, b: int, c: int) -> tuple[int, int, int]:
    """crush_hashmix over plain ints (mod 2^32) — fast scalar path."""
    a = (a - b - c) & _M32
    a ^= c >> 13
    b = (b - c - a) & _M32
    b ^= (a << 8) & _M32
    c = (c - a - b) & _M32
    c ^= b >> 13
    a = (a - b - c) & _M32
    a ^= c >> 12
    b = (b - c - a) & _M32
    b ^= (a << 16) & _M32
    c = (c - a - b) & _M32
    c ^= b >> 5
    a = (a - b - c) & _M32
    a ^= c >> 3
    b = (b - c - a) & _M32
    b ^= (a << 10) & _M32
    c = (c - a - b) & _M32
    c ^= b >> 15
    return a, b, c


def _hash3(x: int, b: int, r: int) -> int:
    """crush_hash32_rjenkins1_3 over plain ints."""
    a, b, c = x & _M32, b & _M32, r & _M32
    h = _SEED ^ a ^ b ^ c
    x_, y = 231232, 1232
    a, b, h = _mix_int(a, b, h)
    c, x_, h = _mix_int(c, x_, h)
    y, a, h = _mix_int(y, a, h)
    b, x_, h = _mix_int(b, x_, h)
    y, c, h = _mix_int(y, c, h)
    return h


def _hash4(a: int, b: int, c: int, d: int) -> int:
    """hash.c :: crush_hash32_rjenkins1_4 over plain ints (the jnp twin
    in crush/hash.py is for traced code; these scalar loops need the
    sub-microsecond path like _hash2/_hash3 above)."""
    a, b, c, d = a & _M32, b & _M32, c & _M32, d & _M32
    h = (_SEED ^ a ^ b ^ c ^ d) & _M32
    x, y = 231232, 1232
    a, b, h = _mix_int(a, b, h)
    c, d, h = _mix_int(c, d, h)
    a, x, h = _mix_int(a, x, h)
    y, b, h = _mix_int(y, b, h)
    c, x, h = _mix_int(c, x, h)
    y, d, h = _mix_int(y, d, h)
    return h


def _hash2(a: int, b: int) -> int:
    """crush_hash32_rjenkins1_2 over plain ints."""
    a, b = a & _M32, b & _M32
    h = _SEED ^ a ^ b
    x_, y = 231232, 1232
    a, b, h = _mix_int(a, b, h)
    x_, a, h = _mix_int(x_, a, h)
    b, y, h = _mix_int(b, y, h)
    return h


def _div_trunc(a: int, b: int) -> int:
    """C-style truncating s64 division (div64_s64)."""
    q = abs(a) // abs(b)
    if (a < 0) != (b < 0):
        q = -q
    return q


def _arg_weights(choose_args, bucket: Straw2Bucket, position: int):
    """Weight vector for a bucket under a choose_args weight-set
    (reference: mapper.c :: get_choose_arg_weights — position clamps to the
    last weight_set row).  None -> the bucket's own weights."""
    if not choose_args:
        return None
    ws = choose_args.get(bucket.id)
    if not ws:
        return None
    return ws[min(position, len(ws) - 1)]


def bucket_straw2_choose(
    bucket: Straw2Bucket, x: int, r: int, weights=None
) -> int:
    """mapper.c :: bucket_straw2_choose — max of ln(u)/w fixed-point draws.

    ln = crush_ln(u) - 2^48 is negative (log2 of u/2^16 in 16.44 fixed
    point); dividing by the 16.16 item weight makes larger weights less
    negative, so argmax favors heavier items with exactly the exponential
    race distribution.  Zero-weight items draw S64_MIN.  `weights`
    substitutes a choose_args weight_set row for the bucket's own weights.
    """
    if weights is None:
        weights = bucket.weights
    high = 0
    high_draw = 0
    for i, (item, weight) in enumerate(zip(bucket.items, weights)):
        if weight:
            u = _hash3(x, item, r) & 0xFFFF
            ln = int(CRUSH_LN_TABLE[u]) - LN_BIAS
            draw = _div_trunc(ln, weight)
        else:
            draw = S64_MIN
        if i == 0 or draw > high_draw:
            high = i
            high_draw = draw
    return bucket.items[high]


def bucket_uniform_choose(bucket, work: dict, x: int, r: int) -> int:
    """mapper.c :: bucket_perm_choose — uniform buckets pick via a lazily
    built pseudo-random permutation CACHED PER (bucket, x) in the
    rule-invocation work space (reference: crush_work_bucket).  The
    cache is semantic, not an optimization: mixing r values for one x
    must walk ONE permutation, including the optimized r==0 shortcut's
    cleanup, to reproduce mapper.c bit-for-bit."""
    size = bucket.size
    pr = r % size
    st = work.setdefault(bucket.id, {"perm_x": None, "perm_n": 0, "perm": []})
    if st["perm_x"] != x or st["perm_n"] == 0:
        st["perm_x"] = x
        if pr == 0:
            s0 = _hash3(x, bucket.id, 0) % size
            st["perm"] = [s0]
            st["perm_n"] = 0xFFFF  # magic: only slot 0 materialized
            return bucket.items[s0]
        st["perm"] = list(range(size))
        st["perm_n"] = 0
    elif st["perm_n"] == 0xFFFF:
        # clean up after the r==0 shortcut: materialize the identity and
        # swap slot 0's winner into place
        s0 = st["perm"][0]
        st["perm"] = list(range(size))
        st["perm"][0], st["perm"][s0] = st["perm"][s0], st["perm"][0]
        st["perm_n"] = 1
    perm = st["perm"]
    while st["perm_n"] <= pr:
        p = st["perm_n"]
        if p < size - 1:
            i = _hash3(x, bucket.id, p) % (size - p)
            if i:
                perm[p], perm[p + i] = perm[p + i], perm[p]
        st["perm_n"] += 1
    return bucket.items[perm[pr]]


def bucket_list_choose(bucket, x: int, r: int) -> int:
    """mapper.c :: bucket_list_choose — walk from the TAIL; each item
    wins with probability weight/sum-so-far via a 16-bit draw scaled by
    the cumulative weight."""
    cum = 0
    sums = []
    for w in bucket.weights:
        cum += w
        sums.append(cum)
    for i in range(bucket.size - 1, -1, -1):
        w = _hash4(x, bucket.items[i], r, bucket.id) & 0xFFFF
        w = (w * sums[i]) >> 16
        if w < bucket.weights[i]:
            return bucket.items[i]
    return bucket.items[0]  # "bad list sums" fallback


def bucket_tree_choose(bucket, x: int, r: int) -> int:
    """mapper.c :: bucket_tree_choose — descend the implicit binary tree
    (leaves at odd indices), hashing a split point against the left
    subtree's weight at each internal node."""
    nodes = bucket.node_weights
    # root = num_nodes >> 1, unconditionally (mapper.c) — no zero-weight
    # collapse (advisor r3).  A weighted descent can never reach an
    # empty leaf: t in [0, w) and the left subtree holds all of w when
    # the right is empty, so t < left always steers left.  The one
    # exception is an ALL-ZERO tree (t = 0, comparisons all false,
    # descend right into padding) — upstream reads out-of-bounds there;
    # we pin that degenerate case to the last real item.
    n = len(nodes) >> 1
    while not (n & 1):
        w = nodes[n]
        t = (_hash4(x, n, r, bucket.id) * w) >> 32
        h = (n & -n) >> 1  # half the subtree span
        left = n - h
        n = left if t < nodes[left] else n + h
    return bucket.items[min(n >> 1, len(bucket.items) - 1)]


def bucket_straw_choose(bucket, x: int, r: int) -> int:
    """mapper.c :: bucket_straw_choose — 16-bit draw times the
    build-time straw scaling factor; longest straw wins."""
    high = 0
    high_draw = -1
    for i, item in enumerate(bucket.items):
        draw = (_hash3(x, item, r) & 0xFFFF) * bucket.straws[i]
        if i == 0 or draw > high_draw:
            high = i
            high_draw = draw
    return bucket.items[high]


def bucket_choose(bucket, x: int, r: int, weights=None,
                  work: dict | None = None) -> int:
    """Per-algorithm dispatch (mapper.c :: crush_bucket_choose).
    choose_args weight-set overrides apply to straw2 only — the legacy
    algorithms predate weight sets."""
    alg = getattr(bucket, "alg", BUCKET_STRAW2)
    if alg == BUCKET_STRAW2:
        return bucket_straw2_choose(bucket, x, r, weights)
    if alg == BUCKET_STRAW:
        return bucket_straw_choose(bucket, x, r)
    if alg == BUCKET_LIST:
        return bucket_list_choose(bucket, x, r)
    if alg == BUCKET_TREE:
        return bucket_tree_choose(bucket, x, r)
    if alg == BUCKET_UNIFORM:
        return bucket_uniform_choose(bucket, work if work is not None else {},
                                     x, r)
    raise ValueError(f"unknown bucket alg {alg}")


def is_out(cmap: CrushMap, weight: list[int], item: int, x: int) -> bool:
    """mapper.c :: is_out — probabilistic rejection by OSD reweight
    (the `weight` vector is the per-device reweight, 16.16)."""
    if item >= len(weight):
        return True
    w = weight[item]
    if w >= 0x10000:
        return False
    if w == 0:
        return True
    return (_hash2(x, item) & 0xFFFF) >= w


def _choose_firstn(
    cmap: CrushMap,
    bucket: Straw2Bucket,
    weight: list[int],
    x: int,
    numrep: int,
    type_: int,
    out: list[int],
    outpos: int,
    tries: int,
    recurse_tries: int,
    recurse_to_leaf: bool,
    out2: list[int] | None,
    parent_r: int,
    choose_args=None,
    work: dict | None = None,
) -> int:
    """mapper.c :: crush_choose_firstn under modern tunables."""
    if work is None:
        work = {}
    t = cmap.tunables
    stable = t.chooseleaf_stable
    rep_range = range(0, numrep) if stable else range(outpos, numrep)
    for rep in rep_range:
        ftotal = 0
        skip_rep = False
        item = 0
        while True:  # retry_descent
            in_bucket = bucket
            r = rep + parent_r + ftotal
            reject = False
            collide = False
            while True:  # descend / retry_bucket
                if in_bucket.size == 0:
                    reject = True
                    break
                item = bucket_choose(
                    in_bucket, x, r,
                    _arg_weights(choose_args, in_bucket, outpos),
                    work,
                )
                itemtype = cmap.item_type(item)
                if itemtype != type_:
                    if item >= 0:
                        # device of the wrong type (mapper.c "bad item type"):
                        # reject and burn a try
                        reject = True
                        break
                    in_bucket = cmap.buckets[item]
                    continue
                collide = item in out[:outpos]
                reject = False
                if not collide and recurse_to_leaf:
                    if item < 0:
                        sub_r = r >> (t.chooseleaf_vary_r - 1) if t.chooseleaf_vary_r else 0
                        out2_pos = _choose_firstn(
                            cmap,
                            cmap.buckets[item],
                            weight,
                            x,
                            1 if stable else outpos + 1,
                            0,
                            out2,
                            outpos,
                            recurse_tries,
                            0,
                            False,
                            None,
                            sub_r,
                            choose_args,
                            work,
                        )
                        if out2_pos <= outpos:
                            reject = True  # didn't get a leaf
                    else:
                        out2[outpos] = item
                if not reject and not collide and itemtype == 0:
                    reject = is_out(cmap, weight, item, x)
                break
            if reject or collide:
                ftotal += 1
                if ftotal < tries:
                    continue  # retry descent from the top
                skip_rep = True
            break
        if skip_rep:
            continue
        out[outpos] = item
        if out2 is not None and cmap.item_type(item) == 0:
            out2[outpos] = item
        outpos += 1
    return outpos


def _choose_indep(
    cmap: CrushMap,
    bucket: Straw2Bucket,
    weight: list[int],
    x: int,
    left: int,
    numrep: int,
    type_: int,
    out: list[int],
    outpos: int,
    tries: int,
    recurse_tries: int,
    recurse_to_leaf: bool,
    out2: list[int] | None,
    parent_r: int,
    choose_args=None,
    work: dict | None = None,
) -> None:
    """mapper.c :: crush_choose_indep — positional (EC) variant; failed
    positions end as ITEM_NONE so shard ids stay stable."""
    if work is None:
        work = {}
    endpos = outpos + left
    for rep in range(outpos, endpos):
        out[rep] = None  # CRUSH_ITEM_UNDEF stand-in
        if out2 is not None:
            out2[rep] = None
    ftotal = 0
    left_count = left
    while left_count > 0 and ftotal < tries:
        for rep in range(outpos, endpos):
            if out[rep] is not None:
                continue
            in_bucket = bucket
            while True:
                r = rep + parent_r + numrep * ftotal
                if in_bucket.size == 0:
                    # structural dead end: permanent NONE for this position
                    out[rep] = ITEM_NONE
                    if out2 is not None:
                        out2[rep] = ITEM_NONE
                    left_count -= 1
                    break
                # mapper.c passes the choose's outpos (0 at top level) as the
                # weight-set position here; only the leaf recursion, whose
                # outpos is the shard position, varies by rep
                item = bucket_choose(
                    in_bucket, x, r,
                    _arg_weights(choose_args, in_bucket, outpos),
                    work,
                )
                itemtype = cmap.item_type(item)
                if itemtype != type_:
                    if item >= 0:
                        # bad item type: permanent NONE for this position
                        # (mapper.c crush_choose_indep semantics)
                        out[rep] = ITEM_NONE
                        if out2 is not None:
                            out2[rep] = ITEM_NONE
                        left_count -= 1
                        break
                    in_bucket = cmap.buckets[item]
                    continue
                collide = any(out[i] == item for i in range(outpos, endpos))
                if collide:
                    break
                if recurse_to_leaf:
                    if item < 0:
                        _choose_indep(
                            cmap, cmap.buckets[item], weight, x, 1, numrep,
                            0, out2, rep, recurse_tries, 0, False, None, r,
                            choose_args, work,
                        )
                        if out2[rep] == ITEM_NONE:
                            break
                    else:
                        out2[rep] = item
                if itemtype == 0 and is_out(cmap, weight, item, x):
                    break
                out[rep] = item
                left_count -= 1
                break
        ftotal += 1
    for rep in range(outpos, endpos):
        if out[rep] is None:
            out[rep] = ITEM_NONE
        if out2 is not None and out2[rep] is None:
            out2[rep] = ITEM_NONE


def crush_do_rule(
    cmap: CrushMap,
    rule_id: int,
    x: int,
    numrep: int,
    weight: list[int],
    choose_args: dict[int, list[list[int]]] | None = None,
) -> list[int]:
    """mapper.c :: crush_do_rule — interpret the rule's steps for input x.

    weight: per-device reweight vector (16.16), the OSDMap::osd_weight analog.
    choose_args: bucket id -> weight_set rows (crush_choose_arg_map analog);
    position selects the row (clamped), outpos for firstn / rep for indep.
    Returns the raw OSD list (ITEM_NONE holes preserved for indep rules).
    """
    rule = cmap.rules[rule_id]
    t = cmap.tunables
    working: list[int] = []
    result: list[int] = []
    # per-invocation scratch (reference: crush_work) — uniform buckets'
    # permutation cache lives here, shared across the rule's steps
    work: dict = {}
    choose_tries = t.choose_total_tries
    chooseleaf_tries = 0
    for step in rule.steps:
        if step.op == RuleOp.TAKE:
            working = [step.arg1]
        elif step.op == RuleOp.SET_CHOOSE_TRIES:
            choose_tries = step.arg1
        elif step.op == RuleOp.SET_CHOOSELEAF_TRIES:
            chooseleaf_tries = step.arg1
        elif step.op in (
            RuleOp.CHOOSE_FIRSTN,
            RuleOp.CHOOSE_INDEP,
            RuleOp.CHOOSELEAF_FIRSTN,
            RuleOp.CHOOSELEAF_INDEP,
        ):
            recurse = step.op in (RuleOp.CHOOSELEAF_FIRSTN, RuleOp.CHOOSELEAF_INDEP)
            firstn = step.op in (RuleOp.CHOOSE_FIRSTN, RuleOp.CHOOSELEAF_FIRSTN)
            want = step.arg1 if step.arg1 > 0 else numrep
            if step.arg1 < 0:
                want = numrep + step.arg1
            out: list[int] = [0] * want
            out2: list[int] = [0] * want if recurse else None
            new_working: list[int] = []
            for wi in working:
                bucket = cmap.buckets[wi]
                if firstn:
                    rt = chooseleaf_tries or choose_tries
                    pos = _choose_firstn(
                        cmap, bucket, weight, x, want, step.arg2, out, 0,
                        choose_tries, rt if recurse else choose_tries,
                        recurse, out2, 0, choose_args, work,
                    )
                    chosen = (out2 if recurse else out)[:pos]
                else:
                    _choose_indep(
                        cmap, bucket, weight, x, want, want, step.arg2, out,
                        0, choose_tries,
                        chooseleaf_tries or 1, recurse, out2, 0, choose_args,
                        work,
                    )
                    chosen = (out2 if recurse else out)[:want]
                new_working.extend(chosen)
            working = new_working
        elif step.op == RuleOp.EMIT:
            result.extend(working)
            working = []
        else:
            raise ValueError(f"unhandled rule op {step.op}")
    return result
