"""Scalar CRUSH mapper — the Python mirror of mapper.c, semantic ground truth.

Reference: src/crush/mapper.c :: crush_do_rule, crush_choose_firstn,
crush_choose_indep, bucket_straw2_choose, is_out.  This is the slow,
readable twin of the vectorized TPU mapper (ceph_tpu/crush/mapper.py) and of
the C++ oracle (native/crush_oracle.cc); all three must agree bit-for-bit.

Implemented tunable profile: the modern defaults (Tunables dataclass) —
choose_local_tries=0 and choose_local_fallback_tries=0 collapse the
legacy local-retry modes, so on collision/rejection the descent restarts
from the TAKE bucket with r' = r + ftotal (firstn) or r + numrep*ftotal
(indep), bounded by choose_total_tries.  chooseleaf_stable=1 and
chooseleaf_vary_r=1 semantics are implemented for the recursive leaf step.

Provenance caveat (SURVEY.md §0): mirrors documented mapper.c behavior; the
empty reference mount means upstream equality is asserted between the three
in-repo implementations, not against Ceph binaries, this round.
"""
from __future__ import annotations

from .ln_table import CRUSH_LN_TABLE, LN_BIAS
from .types import ITEM_NONE, CrushMap, RuleOp, Straw2Bucket

S64_MIN = -(1 << 63)
_M32 = 0xFFFFFFFF
_SEED = 1315423911


def _mix_int(a: int, b: int, c: int) -> tuple[int, int, int]:
    """crush_hashmix over plain ints (mod 2^32) — fast scalar path."""
    a = (a - b - c) & _M32
    a ^= c >> 13
    b = (b - c - a) & _M32
    b ^= (a << 8) & _M32
    c = (c - a - b) & _M32
    c ^= b >> 13
    a = (a - b - c) & _M32
    a ^= c >> 12
    b = (b - c - a) & _M32
    b ^= (a << 16) & _M32
    c = (c - a - b) & _M32
    c ^= b >> 5
    a = (a - b - c) & _M32
    a ^= c >> 3
    b = (b - c - a) & _M32
    b ^= (a << 10) & _M32
    c = (c - a - b) & _M32
    c ^= b >> 15
    return a, b, c


def _hash3(x: int, b: int, r: int) -> int:
    """crush_hash32_rjenkins1_3 over plain ints."""
    a, b, c = x & _M32, b & _M32, r & _M32
    h = _SEED ^ a ^ b ^ c
    x_, y = 231232, 1232
    a, b, h = _mix_int(a, b, h)
    c, x_, h = _mix_int(c, x_, h)
    y, a, h = _mix_int(y, a, h)
    b, x_, h = _mix_int(b, x_, h)
    y, c, h = _mix_int(y, c, h)
    return h


def _hash2(a: int, b: int) -> int:
    """crush_hash32_rjenkins1_2 over plain ints."""
    a, b = a & _M32, b & _M32
    h = _SEED ^ a ^ b
    x_, y = 231232, 1232
    a, b, h = _mix_int(a, b, h)
    x_, a, h = _mix_int(x_, a, h)
    b, y, h = _mix_int(b, y, h)
    return h


def _div_trunc(a: int, b: int) -> int:
    """C-style truncating s64 division (div64_s64)."""
    q = abs(a) // abs(b)
    if (a < 0) != (b < 0):
        q = -q
    return q


def _arg_weights(choose_args, bucket: Straw2Bucket, position: int):
    """Weight vector for a bucket under a choose_args weight-set
    (reference: mapper.c :: get_choose_arg_weights — position clamps to the
    last weight_set row).  None -> the bucket's own weights."""
    if not choose_args:
        return None
    ws = choose_args.get(bucket.id)
    if not ws:
        return None
    return ws[min(position, len(ws) - 1)]


def bucket_straw2_choose(
    bucket: Straw2Bucket, x: int, r: int, weights=None
) -> int:
    """mapper.c :: bucket_straw2_choose — max of ln(u)/w fixed-point draws.

    ln = crush_ln(u) - 2^48 is negative (log2 of u/2^16 in 16.44 fixed
    point); dividing by the 16.16 item weight makes larger weights less
    negative, so argmax favors heavier items with exactly the exponential
    race distribution.  Zero-weight items draw S64_MIN.  `weights`
    substitutes a choose_args weight_set row for the bucket's own weights.
    """
    if weights is None:
        weights = bucket.weights
    high = 0
    high_draw = 0
    for i, (item, weight) in enumerate(zip(bucket.items, weights)):
        if weight:
            u = _hash3(x, item, r) & 0xFFFF
            ln = int(CRUSH_LN_TABLE[u]) - LN_BIAS
            draw = _div_trunc(ln, weight)
        else:
            draw = S64_MIN
        if i == 0 or draw > high_draw:
            high = i
            high_draw = draw
    return bucket.items[high]


def is_out(cmap: CrushMap, weight: list[int], item: int, x: int) -> bool:
    """mapper.c :: is_out — probabilistic rejection by OSD reweight
    (the `weight` vector is the per-device reweight, 16.16)."""
    if item >= len(weight):
        return True
    w = weight[item]
    if w >= 0x10000:
        return False
    if w == 0:
        return True
    return (_hash2(x, item) & 0xFFFF) >= w


def _choose_firstn(
    cmap: CrushMap,
    bucket: Straw2Bucket,
    weight: list[int],
    x: int,
    numrep: int,
    type_: int,
    out: list[int],
    outpos: int,
    tries: int,
    recurse_tries: int,
    recurse_to_leaf: bool,
    out2: list[int] | None,
    parent_r: int,
    choose_args=None,
) -> int:
    """mapper.c :: crush_choose_firstn under modern tunables."""
    t = cmap.tunables
    stable = t.chooseleaf_stable
    rep_range = range(0, numrep) if stable else range(outpos, numrep)
    for rep in rep_range:
        ftotal = 0
        skip_rep = False
        item = 0
        while True:  # retry_descent
            in_bucket = bucket
            r = rep + parent_r + ftotal
            reject = False
            collide = False
            while True:  # descend / retry_bucket
                if in_bucket.size == 0:
                    reject = True
                    break
                item = bucket_straw2_choose(
                    in_bucket, x, r,
                    _arg_weights(choose_args, in_bucket, outpos),
                )
                itemtype = cmap.item_type(item)
                if itemtype != type_:
                    if item >= 0:
                        # device of the wrong type (mapper.c "bad item type"):
                        # reject and burn a try
                        reject = True
                        break
                    in_bucket = cmap.buckets[item]
                    continue
                collide = item in out[:outpos]
                reject = False
                if not collide and recurse_to_leaf:
                    if item < 0:
                        sub_r = r >> (t.chooseleaf_vary_r - 1) if t.chooseleaf_vary_r else 0
                        out2_pos = _choose_firstn(
                            cmap,
                            cmap.buckets[item],
                            weight,
                            x,
                            1 if stable else outpos + 1,
                            0,
                            out2,
                            outpos,
                            recurse_tries,
                            0,
                            False,
                            None,
                            sub_r,
                            choose_args,
                        )
                        if out2_pos <= outpos:
                            reject = True  # didn't get a leaf
                    else:
                        out2[outpos] = item
                if not reject and not collide and itemtype == 0:
                    reject = is_out(cmap, weight, item, x)
                break
            if reject or collide:
                ftotal += 1
                if ftotal < tries:
                    continue  # retry descent from the top
                skip_rep = True
            break
        if skip_rep:
            continue
        out[outpos] = item
        if out2 is not None and cmap.item_type(item) == 0:
            out2[outpos] = item
        outpos += 1
    return outpos


def _choose_indep(
    cmap: CrushMap,
    bucket: Straw2Bucket,
    weight: list[int],
    x: int,
    left: int,
    numrep: int,
    type_: int,
    out: list[int],
    outpos: int,
    tries: int,
    recurse_tries: int,
    recurse_to_leaf: bool,
    out2: list[int] | None,
    parent_r: int,
    choose_args=None,
) -> None:
    """mapper.c :: crush_choose_indep — positional (EC) variant; failed
    positions end as ITEM_NONE so shard ids stay stable."""
    endpos = outpos + left
    for rep in range(outpos, endpos):
        out[rep] = None  # CRUSH_ITEM_UNDEF stand-in
        if out2 is not None:
            out2[rep] = None
    ftotal = 0
    left_count = left
    while left_count > 0 and ftotal < tries:
        for rep in range(outpos, endpos):
            if out[rep] is not None:
                continue
            in_bucket = bucket
            while True:
                r = rep + parent_r + numrep * ftotal
                if in_bucket.size == 0:
                    # structural dead end: permanent NONE for this position
                    out[rep] = ITEM_NONE
                    if out2 is not None:
                        out2[rep] = ITEM_NONE
                    left_count -= 1
                    break
                # mapper.c passes the choose's outpos (0 at top level) as the
                # weight-set position here; only the leaf recursion, whose
                # outpos is the shard position, varies by rep
                item = bucket_straw2_choose(
                    in_bucket, x, r,
                    _arg_weights(choose_args, in_bucket, outpos),
                )
                itemtype = cmap.item_type(item)
                if itemtype != type_:
                    if item >= 0:
                        # bad item type: permanent NONE for this position
                        # (mapper.c crush_choose_indep semantics)
                        out[rep] = ITEM_NONE
                        if out2 is not None:
                            out2[rep] = ITEM_NONE
                        left_count -= 1
                        break
                    in_bucket = cmap.buckets[item]
                    continue
                collide = any(out[i] == item for i in range(outpos, endpos))
                if collide:
                    break
                if recurse_to_leaf:
                    if item < 0:
                        _choose_indep(
                            cmap, cmap.buckets[item], weight, x, 1, numrep,
                            0, out2, rep, recurse_tries, 0, False, None, r,
                            choose_args,
                        )
                        if out2[rep] == ITEM_NONE:
                            break
                    else:
                        out2[rep] = item
                if itemtype == 0 and is_out(cmap, weight, item, x):
                    break
                out[rep] = item
                left_count -= 1
                break
        ftotal += 1
    for rep in range(outpos, endpos):
        if out[rep] is None:
            out[rep] = ITEM_NONE
        if out2 is not None and out2[rep] is None:
            out2[rep] = ITEM_NONE


def crush_do_rule(
    cmap: CrushMap,
    rule_id: int,
    x: int,
    numrep: int,
    weight: list[int],
    choose_args: dict[int, list[list[int]]] | None = None,
) -> list[int]:
    """mapper.c :: crush_do_rule — interpret the rule's steps for input x.

    weight: per-device reweight vector (16.16), the OSDMap::osd_weight analog.
    choose_args: bucket id -> weight_set rows (crush_choose_arg_map analog);
    position selects the row (clamped), outpos for firstn / rep for indep.
    Returns the raw OSD list (ITEM_NONE holes preserved for indep rules).
    """
    rule = cmap.rules[rule_id]
    t = cmap.tunables
    working: list[int] = []
    result: list[int] = []
    choose_tries = t.choose_total_tries
    chooseleaf_tries = 0
    for step in rule.steps:
        if step.op == RuleOp.TAKE:
            working = [step.arg1]
        elif step.op == RuleOp.SET_CHOOSE_TRIES:
            choose_tries = step.arg1
        elif step.op == RuleOp.SET_CHOOSELEAF_TRIES:
            chooseleaf_tries = step.arg1
        elif step.op in (
            RuleOp.CHOOSE_FIRSTN,
            RuleOp.CHOOSE_INDEP,
            RuleOp.CHOOSELEAF_FIRSTN,
            RuleOp.CHOOSELEAF_INDEP,
        ):
            recurse = step.op in (RuleOp.CHOOSELEAF_FIRSTN, RuleOp.CHOOSELEAF_INDEP)
            firstn = step.op in (RuleOp.CHOOSE_FIRSTN, RuleOp.CHOOSELEAF_FIRSTN)
            want = step.arg1 if step.arg1 > 0 else numrep
            if step.arg1 < 0:
                want = numrep + step.arg1
            out: list[int] = [0] * want
            out2: list[int] = [0] * want if recurse else None
            new_working: list[int] = []
            for wi in working:
                bucket = cmap.buckets[wi]
                if firstn:
                    rt = chooseleaf_tries or choose_tries
                    pos = _choose_firstn(
                        cmap, bucket, weight, x, want, step.arg2, out, 0,
                        choose_tries, rt if recurse else choose_tries,
                        recurse, out2, 0, choose_args,
                    )
                    chosen = (out2 if recurse else out)[:pos]
                else:
                    _choose_indep(
                        cmap, bucket, weight, x, want, want, step.arg2, out,
                        0, choose_tries,
                        chooseleaf_tries or 1, recurse, out2, 0, choose_args,
                    )
                    chosen = (out2 if recurse else out)[:want]
                new_working.extend(chosen)
            working = new_working
        elif step.op == RuleOp.EMIT:
            result.extend(working)
            working = []
        else:
            raise ValueError(f"unhandled rule op {step.op}")
    return result
