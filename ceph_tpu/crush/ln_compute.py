"""crush_ln computed on-device from the small RH/LH/LL tables — no 2^16
gather (reference: src/crush/mapper.c :: crush_ln + crush_ln_table.h).

Why this exists: TPUs have no hardware vector gather, so the straw2 hot
loop's per-(x, item) lookup into the 65,536-entry CRUSH_LN_TABLE serializes
at ~9 ns/element and dominates the whole batched mapper (measured ~0.55 s of
a 0.62 s straw2 launch at 262k x 128 draws on v5e).  The reference's own
formulation of crush_ln only ever consults two tables of 129 and 256
entries; lookups that small vectorize as one-hot matmuls on the MXU, and
the remaining arithmetic is exact 32-bit limb math on the VPU.

Everything here is int32/float32-safe — no int64, so it runs identically
as plain jnp (CPU, tests) and inside a Mosaic kernel (ops/pallas_crush.py).
Bit-exactness vs the scalar generator is asserted for all 2^16 inputs in
tests/test_crush.py.

Layout of the 64-bit intermediates in 32-bit limbs:

- RH = ceil(2^56/index1) <= 2^48 splits into three 16-bit limbs r2,r1,r0
  (r2 can reach 2^16, still int32/f32-exact).
- xn*RH needs only its bits 48..55 (index2).  With xn = a*2^9 + b
  (a < 2^8, b < 2^9), every partial product a*r_i, b*r_i < 2^26, and the
  products accumulate into base-2^16 limbs L0..L3 with cascaded carries —
  all < 2^27, exact in int32.
- LH, LL <= 2^48 split into 24-bit limbs (hi can reach 2^24 when
  index1 = 512: f32-exact, and the carry math never assumes hi < 2^24).
- The result (<= 2^48) returns as two int32 planes (hi = bits 24..47,
  lo = bits 0..23); the straw2 caller recombines into int64 and subtracts
  the 2^48 bias under its x64 scope.
"""
from __future__ import annotations

import numpy as np

from .ln_table import LL_TBL, RH_LH_TBL

_MASK24 = 0xFFFFFF


def _tables_f32() -> tuple[np.ndarray, np.ndarray]:
    """(TBL1 [129, 8], TBL2 [256, 8]) f32 lookup matrices for the one-hot
    matmul path.  TBL1 columns: r2, r1, r0 (16-bit limbs of RH), lh_hi,
    lh_lo (24-bit limbs of LH), 3 zero pads.  TBL2: ll_hi, ll_lo + pads.
    Every value < 2^25, exact in f32."""
    rh = RH_LH_TBL[0::2].astype(object)  # 129 entries, python ints
    lh = RH_LH_TBL[1::2].astype(object)
    t1 = np.zeros((129, 8), np.float32)
    t1[:, 0] = [int(v) >> 32 for v in rh]
    t1[:, 1] = [(int(v) >> 16) & 0xFFFF for v in rh]
    t1[:, 2] = [int(v) & 0xFFFF for v in rh]
    t1[:, 3] = [int(v) >> 24 for v in lh]
    t1[:, 4] = [int(v) & _MASK24 for v in lh]
    t2 = np.zeros((256, 8), np.float32)
    t2[:, 0] = [int(v) >> 24 for v in LL_TBL]
    t2[:, 1] = [int(v) & _MASK24 for v in LL_TBL]
    return t1, t2


TBL1_F32, TBL2_F32 = _tables_f32()


def _byte_limb_tables() -> tuple[np.ndarray, np.ndarray]:
    """The same tables split into 8-bit limbs for single-pass bf16 matmul
    lookups (bf16 represents 0..255 exactly; the MXU's default f32 path
    truncates operands to bf16, so full-width f32 columns need the slow
    HIGHEST-precision multi-pass mode — byte limbs don't).

    TBL1_BYTES [256, 16]: r2[3], r1[2], r0[2], lh_hi[4], lh_lo[3], pad.
    TBL2_BYTES [256, 8]:  ll_hi[4], ll_lo[3], pad.
    Limb j of a value v is (v >> 8j) & 0xFF; recombine with shifts+ors.
    """
    rh = [int(v) for v in RH_LH_TBL[0::2]]
    lh = [int(v) for v in RH_LH_TBL[1::2]]
    ll = [int(v) for v in LL_TBL]

    def limbs(vals, n):
        return np.array(
            [[(v >> (8 * j)) & 0xFF for j in range(n)] for v in vals],
            np.float32,
        )

    t1 = np.zeros((256, 16), np.float32)
    t1[:129, 0:3] = limbs([v >> 32 for v in rh], 3)
    t1[:129, 3:5] = limbs([(v >> 16) & 0xFFFF for v in rh], 2)
    t1[:129, 5:7] = limbs([v & 0xFFFF for v in rh], 2)
    t1[:129, 7:11] = limbs([v >> 24 for v in lh], 4)
    t1[:129, 11:14] = limbs([v & _MASK24 for v in lh], 3)
    t2 = np.zeros((256, 8), np.float32)
    t2[:, 0:4] = limbs([v >> 24 for v in ll], 4)
    t2[:, 4:7] = limbs([v & _MASK24 for v in ll], 3)
    return t1, t2


TBL1_BYTES, TBL2_BYTES = _byte_limb_tables()


def recombine_limbs(rows, start: int, n: int, jnp):
    """Byte limbs rows[..., start:start+n] (f32) -> int32 value.

    Accumulates in f32 (exact: limbs <= 255, every partial sum <= the
    table value <= 2^24, all f32-representable) with ONE final int32
    convert — Mosaic miscompiles 3-term int32 shift/or chains over sliced
    dot results, while the f32 Horner form lowers correctly."""
    v = rows[..., start + n - 1]
    for j in range(n - 2, -1, -1):
        v = v * np.float32(256.0) + rows[..., start + j]
    return v.astype(jnp.int32)


def crush_ln_limbs(u, jnp, lookup1, lookup2):
    """crush_ln(u) -> (hi, lo) int32 planes (bits 24..47 / 0..23).

    `u`: int32 array in [0, 0xffff].  `jnp`: the array namespace (jax.numpy
    both outside and inside Pallas kernels).  `lookup1(idx) -> (r2, r1,
    r0, lh_hi, lh_lo)`, `lookup2(idx) -> (ll_hi, ll_lo)`: int32 limb
    fetchers — one-hot matmuls in kernels, jnp.take outside.
    """
    x = (u + 1).astype(jnp.int32)  # [1, 0x10000]
    # bit_length via the f32 exponent field (exact: x <= 2^16 < 2^24)
    xf = x.astype(jnp.float32)
    bl = (
        jnp.right_shift(
            jax_bitcast(jnp, xf), 23
        )
        - 126
    )
    bits = jnp.maximum(0, 16 - bl)  # normalization shift count
    xn = jnp.left_shift(x, bits)    # [0x8000, 0x10000*? ] -> [2^15, 2^16]
    iexpon = 15 - bits

    idx1 = jnp.right_shift(xn, 8) - 128  # (index1 - 256)/2 in [0, 128]
    r2, r1, r0, lh_hi, lh_lo = lookup1(idx1)

    # index2 = bits 48..55 of xn * RH, in 32-bit limb arithmetic
    a = jnp.right_shift(xn, 9)      # < 2^8
    b = xn & 0x1FF                  # < 2^9
    t0 = b * r0
    t1 = a * r0
    t2 = b * r1
    t3 = a * r1
    t4 = b * r2
    t5 = a * r2
    L0 = t0 + jnp.left_shift(t1 & 0x7F, 9)
    L1 = jnp.right_shift(t1, 7) + t2 + jnp.left_shift(t3 & 0x7F, 9)
    L2 = jnp.right_shift(t3, 7) + t4 + jnp.left_shift(t5 & 0x7F, 9)
    L3 = jnp.right_shift(t5, 7)
    c0 = jnp.right_shift(L0, 16)
    c1 = jnp.right_shift(L1 + c0, 16)
    c2 = jnp.right_shift(L2 + c1, 16)
    index2 = (L3 + c2) & 0xFF

    ll_hi, ll_lo = lookup2(index2)

    # result = (iexpon << 44) + ((LH + LL) >> 4), in 24-bit limbs
    lo_sum = lh_lo + ll_lo                      # < 2^25
    hi_sum = lh_hi + ll_hi + jnp.right_shift(lo_sum, 24)
    low24 = lo_sum & _MASK24
    out_lo = jnp.left_shift(hi_sum & 0xF, 20) | jnp.right_shift(low24, 4)
    out_hi = jnp.left_shift(iexpon, 20) + jnp.right_shift(hi_sum, 4)
    return out_hi, out_lo


def jax_bitcast(jnp, xf):
    """f32 -> int32 bit pattern (works in jnp and Mosaic)."""
    import jax

    return jax.lax.bitcast_convert_type(xf, jnp.int32)


def crush_ln_jnp(u):
    """Plain-jnp spelling (jnp.take row lookups) — the CPU/test path and
    the reference for the Pallas kernel's one-hot variant."""
    import jax.numpy as jnp

    t1 = jnp.asarray(TBL1_F32, jnp.int32)
    t2 = jnp.asarray(TBL2_F32, jnp.int32)

    def look1(i):
        rows = jnp.take(t1, i, axis=0)
        return tuple(rows[..., j] for j in range(5))

    def look2(i):
        rows = jnp.take(t2, i, axis=0)
        return rows[..., 0], rows[..., 1]

    return crush_ln_limbs(jnp.asarray(u, jnp.int32), jnp, look1, look2)
