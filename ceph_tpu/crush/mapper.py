"""crush_do_rule_batch — the vectorized TPU CRUSH mapper (north-star #2).

Reference: src/crush/mapper.c :: crush_do_rule / crush_choose_firstn /
crush_choose_indep / bucket_straw2_choose, vectorized over the placement
input x exactly as SURVEY.md §3.3 prescribes: all batch consumers (balancer,
crushtool --test, osdmaptool --test-map-pgs) are embarrassingly parallel over
x, and the data-dependent retry loops become fixed-trip masked loops bounded
by choose_total_tries (default 50).

Design:
- The CrushMap is compiled once into dense arrays (items/weights/sizes/types
  padded to the max bucket size) — the analog of CrushWrapper holding the
  crush_map ready for crush_do_rule (reference: src/crush/CrushWrapper.h).
- A rule compiles at trace time: step structure and replica counts are
  static (static shapes for XLA), while every per-x decision — straw2
  draws, descent, collisions, is_out rejections, retries — is traced jnp.
- One x is evaluated by a single-x function; the batch is jax.vmap over x,
  so the straw2 hash+ln-gather+argmax inner loop (HOT LOOP #3, SURVEY.md
  §3.3) runs across the whole batch on the VPU.
- int64-exact: draws are div64_s64-style truncating divisions on int64
  (requires jax_enable_x64; SURVEY.md §7 hard parts).

Scope matches the scalar twin (ceph_tpu/crush/reference_mapper.py): straw2
buckets, modern tunables (stable=1, vary_r=1, local retries 0), rules of the
shape TAKE -> (SET_*)* -> one CHOOSE/CHOOSELEAF -> EMIT (what
add_simple_rule and OSDMonitor's EC rules emit).  The scalar Python, the C++
oracle, and this mapper must agree bit-for-bit on every input — enforced by
tests/test_crush.py over random maps and large x sweeps.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .hash import crush_hash32_2, crush_hash32_3
from .ln_table import CRUSH_LN_TABLE, LN_BIAS
from .types import ITEM_NONE, CrushMap, RuleOp

# straw2 is 64-bit fixed-point integer math (SURVEY.md §7 hard parts).  x64
# is enabled ONLY around the CRUSH traces (enable_x64 context below) — a
# global jax_enable_x64 flip leaks i64 into unrelated traces and breaks
# Mosaic compilation of the Pallas GF kernel on real TPUs.


def enable_x64():
    """Thread-scoped x64 context (jax.experimental.enable_x64 was removed
    in jax 0.9; the config State object is the surviving spelling)."""
    try:
        from jax._src.config import enable_x64 as _e

        return _e(True)
    except ImportError:  # older jax
        from jax.experimental import enable_x64 as _e

        return _e()

S64_MIN = np.int64(np.iinfo(np.int64).min)

# Max x per device launch.  Empirically (v5e, 1024-OSD hierarchical map):
# one vmapped launch at 1M x crashes the TPU worker process outright, while
# <=512k launches complete; 256k leaves 2x margin and still amortizes
# dispatch to noise.
_BATCH_CHUNK = 1 << 18


def validate_choose_args(
    cmap: CrushMap, name: str
) -> dict[int, list[list[int]]]:
    """Resolve and sanity-check a named choose_args weight-set: the name
    must exist, every bucket id must be a real (negative) bucket, every
    weight_set must be non-empty with rows matching the bucket size.
    Shared by the scalar and batch entry points so malformed maps (e.g.
    hand-edited text) fail identically everywhere."""
    if name not in cmap.choose_args:
        raise KeyError(
            f"unknown choose_args {name!r}; known: {sorted(cmap.choose_args)}"
        )
    ca = cmap.choose_args[name]
    for bid, ws in ca.items():
        if bid >= 0 or bid not in cmap.buckets:
            raise ValueError(f"choose_args {name!r}: no such bucket {bid}")
        if not ws:
            raise ValueError(
                f"choose_args {name!r}: empty weight_set for bucket {bid}"
            )
        size = len(cmap.buckets[bid].items)
        for row in ws:
            if len(row) != size:
                raise ValueError(
                    f"choose_args {name!r}: weight_set row of {len(row)} "
                    f"for bucket {bid} of size {size}"
                )
    return ca


class CompiledCrushMap:
    """Dense-array form of a CrushMap for device execution."""

    def __init__(self, cmap: CrushMap):
        self.cmap = cmap
        ids = sorted(cmap.buckets)
        n_idx = max((-1 - bid for bid in ids), default=-1) + 1
        max_size = max((b.size for b in cmap.buckets.values()), default=1)
        items = np.full((max(n_idx, 1), max_size), ITEM_NONE, dtype=np.int32)
        weights = np.zeros((max(n_idx, 1), max_size), dtype=np.int64)
        sizes = np.zeros(max(n_idx, 1), dtype=np.int32)
        types = np.zeros(max(n_idx, 1), dtype=np.int32)
        for bid, b in cmap.buckets.items():
            i = -1 - bid
            items[i, : b.size] = b.items
            weights[i, : b.size] = b.weights
            sizes[i] = b.size
            types[i] = b.type
        with enable_x64():
            self.items = jnp.asarray(items)
            self.weights = jnp.asarray(weights)
            self.sizes = jnp.asarray(sizes)
            self.types = jnp.asarray(types)
            self.ln_table = jnp.asarray(CRUSH_LN_TABLE)
        self.n_idx = n_idx
        self.max_size = max_size
        self._choose_args_cache: dict[str, jnp.ndarray] = {}
        self._rule_fn_cache: dict = {}

    def choose_args_arrays(self, name: str) -> jnp.ndarray:
        """Dense [positions, n_idx, max_size] weight array for a named
        choose_args weight-set (reference: crush_choose_arg_map).  Buckets
        without an entry keep their own weights; buckets with fewer
        weight_set rows than the max are clamped to their last row — the
        get_choose_arg_weights position clamp, applied at build time."""
        cached = self._choose_args_cache.get(name)
        if cached is not None:
            return cached
        ca = validate_choose_args(self.cmap, name)
        P = max((len(ws) for ws in ca.values()), default=1)
        base = np.asarray(self.weights)
        dense = np.broadcast_to(base, (P,) + base.shape).copy()
        for bid, ws in ca.items():
            i = -1 - bid
            size = len(self.cmap.buckets[bid].items)
            for p in range(P):
                row = ws[min(p, len(ws) - 1)]
                dense[p, i, :size] = row
        with enable_x64():
            arr = jnp.asarray(dense)
        self._choose_args_cache[name] = arr
        return arr

    def item_type(self, item):
        """type of an item id: devices 0, buckets their declared type."""
        idx = jnp.clip(jnp.where(item < 0, -1 - item, 0), 0, self.types.shape[0] - 1)
        return jnp.where(item < 0, jnp.take(self.types, idx), 0)


def _div64_trunc(a, b):
    """C-style truncating signed division (div64_s64)."""
    q = jnp.abs(a) // jnp.abs(b)
    return jnp.where((a < 0) != (b < 0), -q, q).astype(jnp.int64)


def _straw2_choose(cm: CompiledCrushMap, bucket_idx, x, r, cweights, position):
    """mapper.c :: bucket_straw2_choose for one x (vmap-friendly).

    Exponential-race draw per slot; first argmax matches the C loop's
    strict-greater update.  Empty bucket -> ITEM_NONE; all-zero-weight
    bucket -> items[0] (C semantics: high stays 0).  cweights is an optional
    [P, n_idx, S] choose_args weight array; position picks the row (clamped,
    as get_choose_arg_weights does)."""
    bucket_idx = jnp.clip(bucket_idx, 0, cm.items.shape[0] - 1)
    # jnp.take (gather), NOT arr[idx]: scalar dynamic indexing lowers to
    # dynamic_slice, whose vmap batching rule BROADCASTS the whole bucket
    # matrix per batch element — [N, n_idx, S] blew HBM at N=1M on v5e
    items = jnp.take(cm.items, bucket_idx, axis=0)        # [S]
    if cweights is None:
        weights = jnp.take(cm.weights, bucket_idx, axis=0)    # [S]
    else:
        pos = jnp.minimum(position, cweights.shape[0] - 1)
        flat = cweights.reshape(-1, cweights.shape[-1])
        weights = jnp.take(flat, pos * cm.items.shape[0] + bucket_idx, axis=0)
    size = jnp.take(cm.sizes, bucket_idx)
    u = (
        crush_hash32_3(
            jnp.uint32(x), items.astype(jnp.uint32), jnp.uint32(r)
        ).astype(jnp.int64)
        & 0xFFFF
    )
    ln = cm.ln_table[u] - LN_BIAS
    draw = _div64_trunc(ln, jnp.maximum(weights, 1))
    slot = jnp.arange(items.shape[0])
    valid = (slot < size) & (weights > 0)
    draw = jnp.where(valid, draw, S64_MIN)
    return jnp.where(size > 0, items[jnp.argmax(draw)], ITEM_NONE)


def _is_out(weightvec, item, x):
    """mapper.c :: is_out — probabilistic reject by device reweight."""
    n = weightvec.shape[0]
    idx = jnp.clip(item, 0, n - 1)
    w = jnp.take(weightvec, idx).astype(jnp.int64)
    oob = item >= n
    h = crush_hash32_2(jnp.uint32(x), jnp.uint32(item)).astype(jnp.int64) & 0xFFFF
    return oob | (w == 0) | ((w < 0x10000) & (h >= w))


def _descend(cm: CompiledCrushMap, root, x, r, want_type: int, cweights, position):
    """Walk intervening buckets until an item of want_type appears
    (mapper.c's inner retry_bucket descent); dead ends yield ITEM_NONE.

    Dead ends are: an empty bucket mid-descent, and a *device* of the wrong
    type (mapper.c "bad item type" — e.g. an OSD placed directly under the
    root when the rule wants hosts); both reject rather than mis-place."""

    def cond(item):
        return (item < 0) & (item != ITEM_NONE) & (cm.item_type(item) != want_type)

    def body(item):
        return _straw2_choose(cm, -1 - item, x, r, cweights, position)

    item = jax.lax.while_loop(cond, body, jnp.asarray(root, jnp.int32))
    if want_type != 0:
        item = jnp.where(item >= 0, ITEM_NONE, item)
    return item


def _leaf_firstn(
    cm, weightvec, x, item, sub_r, outpos, out2, S, recurse_tries, cweights
):
    """Nested chooseleaf descent (crush_choose_firstn recursion with
    stable=1: one rep, r = sub_r + ftotal, collisions vs out2[:outpos])."""

    def body(state):
        ftotal, _, done = state
        leaf = _descend(cm, item, x, sub_r + ftotal, 0, cweights, outpos)
        is_dev = leaf >= 0
        collide = jnp.any((out2 == leaf) & (jnp.arange(S) < outpos)) & is_dev
        reject = jnp.where(is_dev, _is_out(weightvec, leaf, x), True)
        ok = is_dev & ~collide & ~reject
        return ftotal + 1, leaf, done | ok

    def cond(state):
        ftotal, _, done = state
        return (~done) & (ftotal < recurse_tries)

    _, leaf, done = jax.lax.while_loop(
        cond, body, (jnp.int32(0), jnp.int32(ITEM_NONE), False)
    )
    return jnp.where(done, leaf, ITEM_NONE), done


def _choose_firstn_single(
    cm, weightvec, x, root, numrep, want_type, tries, recurse, recurse_tries,
    cweights,
):
    """crush_choose_firstn for one x under modern tunables.

    Returns (out, out2, count); out holds failure-domain items, out2 leaves
    (== out when not recursing); both dense in [0, count)."""
    S = numrep
    out = jnp.full((S,), ITEM_NONE, dtype=jnp.int32)
    out2 = jnp.full((S,), ITEM_NONE, dtype=jnp.int32)

    def rep_body(rep, carry):
        out, out2, outpos = carry

        def try_body(state):
            ftotal, _, _, done = state
            r = rep + ftotal
            cand = _descend(cm, root, x, r, want_type, cweights, outpos)
            dead = cand == ITEM_NONE
            collide = jnp.any((out == cand) & (jnp.arange(S) < outpos)) & ~dead
            if recurse:
                # both paths computed + jnp.where, NOT lax.cond: a batched-
                # predicate cond inside a while_loop makes vmap broadcast
                # the branch constants (the whole bucket matrix) to
                # [N, n_idx, S] — the HBM blowup found at 1M x on v5e.
                # vmap executes both branches of a cond anyway.
                use_leaf = (cand < 0) & ~dead & ~collide
                leaf_r, leaf_ok_r = _leaf_firstn(
                    cm, weightvec, x, cand, r, outpos, out2, S,
                    recurse_tries, cweights,
                )
                direct_ok = (cand >= 0) & ~_is_out(weightvec, cand, x)
                leaf = jnp.where(use_leaf, leaf_r, jnp.asarray(cand, jnp.int32))
                leaf_ok = jnp.where(use_leaf, leaf_ok_r, direct_ok)
                reject = ~leaf_ok
            else:
                leaf = cand
                reject = dead | jnp.where(
                    cand >= 0, _is_out(weightvec, cand, x), False
                )
            ok = ~dead & ~collide & ~reject
            return ftotal + 1, cand, leaf, done | ok

        def try_cond(state):
            ftotal, _, _, done = state
            return (~done) & (ftotal < tries)

        _, item, leaf, done = jax.lax.while_loop(
            try_cond,
            try_body,
            (jnp.int32(0), jnp.int32(ITEM_NONE), jnp.int32(ITEM_NONE), False),
        )
        out = jnp.where(done, out.at[outpos].set(item), out)
        out2 = jnp.where(done, out2.at[outpos].set(leaf), out2)
        return out, out2, outpos + done.astype(jnp.int32)

    out, out2, outpos = jax.lax.fori_loop(
        0, numrep, rep_body, (out, out2, jnp.int32(0))
    )
    return out, out2, outpos


def _choose_indep_single(
    cm, weightvec, x, root, numrep, want_type, tries, recurse, recurse_tries,
    cweights,
):
    """crush_choose_indep for one x: positional retries r = rep +
    numrep*ftotal; failed positions stay ITEM_NONE (EC shard holes).
    Leaf recursion checks no cross-rep collisions (mapper.c passes the
    recursion outpos=rep, left=1, so its collide scan covers only [rep])."""
    S = numrep
    out = jnp.full((S,), ITEM_NONE, dtype=jnp.int32)
    out2 = jnp.full((S,), ITEM_NONE, dtype=jnp.int32)
    placed = jnp.zeros((S,), dtype=bool)

    def ft_body(ftotal, carry):
        out, out2, placed = carry

        def rep_body(rep, carry2):
            out, out2, placed = carry2
            r = rep + numrep * ftotal
            # weight-set position is the choose's outpos — 0 at the top
            # level (mapper.c); the leaf recursion below uses rep, its outpos
            cand = _descend(cm, root, x, r, want_type, cweights, 0)
            dead = cand == ITEM_NONE
            collide = jnp.any((out == cand) & placed) & ~dead
            if recurse:
                # both paths + jnp.where instead of lax.cond (see
                # _choose_firstn_single: batched cond in a while broadcasts
                # the bucket matrices per x)
                def lbody(state):
                    lf, _, done = state
                    leaf = _descend(
                        cm, cand, x, rep + numrep * lf + r, 0, cweights,
                        rep,
                    )
                    ok = (leaf >= 0) & ~_is_out(weightvec, leaf, x)
                    return lf + 1, leaf, done | ok

                def lcond(state):
                    lf, _, done = state
                    return (~done) & (lf < recurse_tries)

                _, lleaf, lok = jax.lax.while_loop(
                    lcond, lbody, (jnp.int32(0), jnp.int32(ITEM_NONE), False)
                )
                lleaf = jnp.where(lok, lleaf, ITEM_NONE)
                use_leaf = (cand < 0) & ~dead & ~collide
                direct_ok = (cand >= 0) & ~_is_out(weightvec, cand, x)
                leaf = jnp.where(use_leaf, lleaf, jnp.asarray(cand, jnp.int32))
                leaf_ok = jnp.where(use_leaf, lok, direct_ok)
                ok = ~dead & ~collide & leaf_ok
            else:
                leaf = cand
                reject = dead | jnp.where(
                    cand >= 0, _is_out(weightvec, cand, x), False
                )
                ok = ~dead & ~collide & ~reject
            take = ok & ~placed[rep]
            out = jnp.where(take, out.at[rep].set(cand), out)
            out2 = jnp.where(take, out2.at[rep].set(leaf), out2)
            # structural dead end (empty bucket / bad item type): permanent
            # NONE for this position, matching mapper.c's crush_choose_indep
            # (out[rep] stays ITEM_NONE and is never retried)
            dead_perm = (cand == ITEM_NONE) & ~placed[rep]
            placed = placed.at[rep].set(placed[rep] | take | dead_perm)
            return out, out2, placed

        return jax.lax.fori_loop(0, numrep, rep_body, (out, out2, placed))

    def ft_cond(state):
        ftotal, (_, _, placed) = state
        return (ftotal < tries) & ~placed.all()

    def ft_step(state):
        ftotal, carry = state
        return ftotal + 1, ft_body(ftotal, carry)

    _, (out, out2, placed) = jax.lax.while_loop(
        ft_cond, ft_step, (jnp.int32(0), (out, out2, placed))
    )
    return out, out2, jnp.sum(placed.astype(jnp.int32))


def compile_rule(cm: CompiledCrushMap, rule_id: int, numrep: int) -> dict:
    """Static plan for a TAKE -> CHOOSE -> EMIT rule (trace-time)."""
    rule = cm.cmap.rules[rule_id]
    t = cm.cmap.tunables
    plan = []
    tries = t.choose_total_tries
    leaf_tries = 0
    take = None
    for step in rule.steps:
        if step.op == RuleOp.TAKE:
            take = step.arg1
        elif step.op == RuleOp.SET_CHOOSE_TRIES:
            tries = step.arg1
        elif step.op == RuleOp.SET_CHOOSELEAF_TRIES:
            leaf_tries = step.arg1
        elif step.op in (
            RuleOp.CHOOSE_FIRSTN,
            RuleOp.CHOOSE_INDEP,
            RuleOp.CHOOSELEAF_FIRSTN,
            RuleOp.CHOOSELEAF_INDEP,
        ):
            if take is None:
                raise ValueError("CHOOSE before TAKE")
            want = step.arg1 if step.arg1 > 0 else numrep + step.arg1
            plan.append(
                dict(
                    take=take,
                    want=want,
                    type=step.arg2,
                    firstn=step.op
                    in (RuleOp.CHOOSE_FIRSTN, RuleOp.CHOOSELEAF_FIRSTN),
                    recurse=step.op
                    in (RuleOp.CHOOSELEAF_FIRSTN, RuleOp.CHOOSELEAF_INDEP),
                    tries=tries,
                    leaf_tries=leaf_tries,
                )
            )
        elif step.op == RuleOp.EMIT:
            pass
        else:
            raise ValueError(f"unsupported rule op {step.op}")
    if not plan:
        raise ValueError("rule has no CHOOSE step")
    if len(plan) != 1:
        raise NotImplementedError(
            "multi-choose rule chains not yet supported by the batch mapper"
        )
    return plan[0]


def crush_do_rule_batch(
    cm: CompiledCrushMap,
    rule_id: int,
    xs,
    numrep: int,
    weightvec,
    choose_args: str | None = None,
) -> jnp.ndarray:
    """Batched crush_do_rule: xs [N] -> [N, numrep] OSD ids.

    The new sibling entry point of CrushWrapper::do_rule that the north star
    adds (SURVEY.md §1 seam #2); consumed by the balancer simulation, the
    crushtool-analog --test, and the osdmaptool-analog --test-map-pgs.
    firstn results are dense with ITEM_NONE tail padding; indep results keep
    positional ITEM_NONE holes (EC shard semantics)."""
    key = (rule_id, numrep, choose_args)
    vf = cm._rule_fn_cache.get(key)
    if vf is None:
        p = compile_rule(cm, rule_id, numrep)
        cweights = (
            cm.choose_args_arrays(choose_args)
            if choose_args is not None
            else None
        )
        fn = _choose_firstn_single if p["firstn"] else _choose_indep_single
        tries = p["tries"]
        recurse_tries = (
            (p["leaf_tries"] or tries) if p["firstn"] else (p["leaf_tries"] or 1)
        )

        def single(x, wv):
            out, out2, cnt = fn(
                cm,
                wv,
                x,
                p["take"],
                p["want"],
                p["type"],
                tries,
                p["recurse"],
                recurse_tries,
                cweights,
            )
            res = out2 if p["recurse"] else out
            if p["firstn"]:
                res = jnp.where(jnp.arange(res.shape[0]) < cnt, res, ITEM_NONE)
            return res

        # jit once per (rule, numrep, choose_args) and cache on the map:
        # a fresh jit-wrapped closure per call would recompile every call
        # (jax caches by function identity), which at 256k x costs minutes
        vf = jax.jit(jax.vmap(single, in_axes=(0, None)))
        cm._rule_fn_cache[key] = vf

    with enable_x64():
        xs_np = np.asarray(xs, dtype=np.int32)
        weightvec = jnp.asarray(weightvec, dtype=jnp.int64)
        N = xs_np.shape[0]
        if N <= _BATCH_CHUNK:
            # pad to the next power of two: bounds the number of distinct
            # compiled shapes to log2(_BATCH_CHUNK) across all callers
            Np = max(1, 1 << (max(N, 1) - 1).bit_length())
            out = vf(jnp.asarray(np.resize(xs_np, Np)), weightvec)
            return out[:N] if Np != N else out
        # Large batches run as fixed-size device calls: one Mosaic launch
        # over >~512k x (vmapped int64 while-loops) hard-faults the v5e
        # worker, and a single huge launch would also hold the whole
        # [N, trace] intermediate set live in HBM.  Chunking keeps each
        # launch inside the envelope at ~zero throughput cost (the per-x
        # math dwarfs dispatch).
        pieces = []
        for lo in range(0, N, _BATCH_CHUNK):
            part = xs_np[lo : lo + _BATCH_CHUNK]
            # ragged tail: pad to its own next power of two (a shape the
            # small-batch path compiles anyway), not to a full chunk —
            # padding 1 element to 256k would be pure discarded compute
            width = (
                _BATCH_CHUNK
                if len(part) == _BATCH_CHUNK
                else 1 << (len(part) - 1).bit_length()
            )
            chunk = np.resize(part, width)
            pieces.append(np.asarray(vf(jnp.asarray(chunk), weightvec))[: len(part)])
        out = np.concatenate(pieces)
        return jnp.asarray(out)
