"""crush_do_rule_batch — the vectorized TPU CRUSH mapper (north-star #2).

Reference: src/crush/mapper.c :: crush_do_rule / crush_choose_firstn /
crush_choose_indep / bucket_straw2_choose, vectorized over the placement
input x exactly as SURVEY.md §3.3 prescribes: all batch consumers (balancer,
crushtool --test, osdmaptool --test-map-pgs) are embarrassingly parallel over
x, and the data-dependent retry loops become masked fixed-trip loops bounded
by choose_total_tries (default 50).

Design:
- The CrushMap is compiled once into dense arrays (items/weights/sizes/types
  padded to the max bucket size) — the analog of CrushWrapper holding the
  crush_map ready for crush_do_rule (reference: src/crush/CrushWrapper.h).
- A rule compiles into a static step plan (TAKE/CHOOSE/EMIT sequence with
  static replica counts — static shapes for XLA); every per-x decision —
  straw2 draws, descent, collisions, is_out rejections, retries — is traced
  jnp over explicit [B] lane arrays (ceph_tpu/crush/batched.py).
- Multi-choose chains (TAKE → CHOOSE rack → CHOOSE host → EMIT) flatten the
  parent axis into the lane axis: a step with W working items per x runs
  one batched choose over N*W lanes, mirroring mapper.c's `for (i = 0;
  i < wsize; i++)` loop over the working vector.
- The straw2 score path is pluggable: full-table ln gather on CPU, the
  fused Pallas hash+ln kernel on TPU (TPUs have no vector gather — see
  ceph_tpu/crush/ln_compute.py).
- int64-exact: draws are div64_s64-style truncating divisions on int64
  (x64 scoped to the CRUSH traces; a global flip breaks Mosaic compiles).

Scope: modern tunables (stable=1, vary_r=1, local retries 0).  The jax
lanes implement straw2 — the algorithm every real deployment uses for
data; maps carrying LEGACY bucket algorithms (uniform/list/tree/straw,
crush.h CRUSH_BUCKET_*) are detected at compile time and the batch API
routes them to the compiled C oracle (tests/test_crush_legacy_buckets.py
proves 3-way bit-exactness).  The scalar Python, the C++ oracle, and
this mapper must agree bit-for-bit on every input — enforced by
tests/test_crush.py over random maps and large x sweeps.
"""
from __future__ import annotations

import threading

import time

import jax
import jax.numpy as jnp
import numpy as np

from ..common.kernel_telemetry import TELEMETRY
from .batched import (
    I64Engine,
    LimbEngine,
    choose_firstn_b,
    choose_indep_b,
    ln_planes_jnp,
    ln_planes_pallas,
    ln_scores_jnp,
    ln_scores_pallas,
)
from .ln_table import CRUSH_LN_TABLE
from .types import ITEM_NONE, CrushMap, RuleOp

# serializes the one-shot straw2 tile downshift in crush_do_rule_batch
_TILE_LOCK = threading.Lock()

# straw2 is 64-bit fixed-point integer math (SURVEY.md §7 hard parts).  x64
# is enabled ONLY around the CRUSH traces (enable_x64 context below) — a
# global jax_enable_x64 flip leaks i64 into unrelated traces and breaks
# Mosaic compilation of the Pallas GF kernel on real TPUs.


def enable_x64():
    """Thread-scoped x64 context for the CRUSH traces."""
    from ..common.jaxutil import x64_ctx

    return x64_ctx(True)


# Max LANES (x times working-set width) per device launch.  Empirically
# (v5e, 1024-OSD hierarchical map): one launch at 1M lanes crashes the TPU
# worker process outright, while <=512k complete; 256k leaves 2x margin and
# still amortizes dispatch to noise.
_BATCH_CHUNK = 1 << 18


def validate_choose_args(
    cmap: CrushMap, name: str
) -> dict[int, list[list[int]]]:
    """Resolve and sanity-check a named choose_args weight-set: the name
    must exist, every bucket id must be a real (negative) bucket, every
    weight_set must be non-empty with rows matching the bucket size.
    Shared by the scalar and batch entry points so malformed maps (e.g.
    hand-edited text) fail identically everywhere."""
    if name not in cmap.choose_args:
        raise KeyError(
            f"unknown choose_args {name!r}; known: {sorted(cmap.choose_args)}"
        )
    ca = cmap.choose_args[name]
    for bid, ws in ca.items():
        if bid >= 0 or bid not in cmap.buckets:
            raise ValueError(f"choose_args {name!r}: no such bucket {bid}")
        if not ws:
            raise ValueError(
                f"choose_args {name!r}: empty weight_set for bucket {bid}"
            )
        size = len(cmap.buckets[bid].items)
        for row in ws:
            if len(row) != size:
                raise ValueError(
                    f"choose_args {name!r}: weight_set row of {len(row)} "
                    f"for bucket {bid} of size {size}"
                )
    return ca


class CompiledCrushMap:
    """Dense-array form of a CrushMap for device execution."""

    def __init__(self, cmap: CrushMap):
        self.cmap = cmap
        ids = sorted(cmap.buckets)
        n_idx = max((-1 - bid for bid in ids), default=-1) + 1
        max_size = max((b.size for b in cmap.buckets.values()), default=1)
        items = np.full((max(n_idx, 1), max_size), ITEM_NONE, dtype=np.int32)
        weights = np.zeros((max(n_idx, 1), max_size), dtype=np.int64)
        sizes = np.zeros(max(n_idx, 1), dtype=np.int32)
        types = np.zeros(max(n_idx, 1), dtype=np.int32)
        algs = np.full(max(n_idx, 1), 5, dtype=np.int32)  # straw2
        straws = np.zeros((max(n_idx, 1), max_size), dtype=np.int64)
        max_nodes = 1
        for b in cmap.buckets.values():
            if getattr(b, "node_weights", None):
                max_nodes = max(max_nodes, len(b.node_weights))
        nodes = np.zeros((max(n_idx, 1), max_nodes), dtype=np.int64)
        counts = np.zeros(max(n_idx, 1), dtype=np.int32)
        for bid, b in cmap.buckets.items():
            i = -1 - bid
            items[i, : b.size] = b.items
            weights[i, : b.size] = b.weights
            sizes[i] = b.size
            types[i] = b.type
            algs[i] = getattr(b, "alg", 5)
            if getattr(b, "straws", None):
                straws[i, : b.size] = b.straws
            if getattr(b, "node_weights", None):
                nodes[i, : len(b.node_weights)] = b.node_weights
                counts[i] = len(b.node_weights)
        self.algs = algs
        self.straws = straws
        self.node_weights = nodes
        self.max_nodes = max_nodes
        #: true per-bucket tree node counts (len(node_weights); 0 = not
        #: a tree bucket) — passed to the oracle verbatim so an ingested
        #: bucket's structural count is authoritative (r4 verdict #5)
        self.node_counts = counts
        #: True iff every bucket is straw2 — the jax/Pallas batch path
        #: covers exactly this; legacy maps route to the C oracle
        self.straw2_only = bool((algs[: max(n_idx, 1)] == 5).all()) if n_idx else True
        with enable_x64():
            self.items = jnp.asarray(items)
            self.weights = jnp.asarray(weights)
            self.sizes = jnp.asarray(sizes)
            self.types = jnp.asarray(types)
            self.ln_table = jnp.asarray(CRUSH_LN_TABLE)
        # int32 plane tables for the limb engine (no x64 anywhere)
        self.ln_hi_table = jnp.asarray(
            (CRUSH_LN_TABLE >> 24).astype(np.int32))
        self.ln_lo_table = jnp.asarray(
            (CRUSH_LN_TABLE & 0xFFFFFF).astype(np.int32))
        self._np_items = items
        self._np_weights = weights
        self._np_sizes = sizes
        self._np_types = types
        self.n_idx = n_idx
        self.max_size = max_size
        self._limb_tables = None
        self._choose_args_cache: dict[str, jnp.ndarray] = {}
        self._choose_args_limb_cache: dict = {}
        self._rule_fn_cache: dict = {}

    @property
    def limb_tables(self):
        """Lazy fat-table build for the TPU limb engine (crush/engine.py)
        — magic divisors + 8-bit gather planes, host-side once per map."""
        if self._limb_tables is None:
            from .engine import LimbTables

            self._limb_tables = LimbTables(
                self._np_items, self._np_weights,
                self._np_sizes, self._np_types,
            )
        return self._limb_tables

    def choose_args_limb(self, name: str):
        """LimbTables over [P * n_idx] rows for a named choose_args
        weight-set (limb-engine twin of choose_args_arrays)."""
        cached = self._choose_args_limb_cache.get(name)
        if cached is not None:
            return cached
        from .engine import LimbTables

        validate_choose_args(self.cmap, name)
        dense = np.asarray(self.choose_args_arrays(name))  # [P, n_idx, S]
        P = dense.shape[0]
        tiled = lambda a: np.tile(a, (P,) + (1,) * (a.ndim - 1)).reshape(
            (P * a.shape[0],) + a.shape[1:]
        )
        tabs = LimbTables(
            tiled(self._np_items),
            dense.reshape(P * self.n_idx, -1),
            tiled(self._np_sizes),
            tiled(self._np_types),
        )
        tabs.positions = P
        self._choose_args_limb_cache[name] = tabs
        return tabs

    def choose_args_arrays(self, name: str) -> jnp.ndarray:
        """Dense [positions, n_idx, max_size] weight array for a named
        choose_args weight-set (reference: crush_choose_arg_map).  Buckets
        without an entry keep their own weights; buckets with fewer
        weight_set rows than the max are clamped to their last row — the
        get_choose_arg_weights position clamp, applied at build time."""
        cached = self._choose_args_cache.get(name)
        if cached is not None:
            return cached
        ca = validate_choose_args(self.cmap, name)
        P = max((len(ws) for ws in ca.values()), default=1)
        base = np.asarray(self.weights)
        dense = np.broadcast_to(base, (P,) + base.shape).copy()
        for bid, ws in ca.items():
            i = -1 - bid
            size = len(self.cmap.buckets[bid].items)
            for p in range(P):
                row = ws[min(p, len(ws) - 1)]
                dense[p, i, :size] = row
        with enable_x64():
            arr = jnp.asarray(dense)
        self._choose_args_cache[name] = arr
        return arr


def compile_plan(cm: CompiledCrushMap, rule_id: int, numrep: int) -> list[dict]:
    """Static step plan for an arbitrary TAKE/(SET_*)/CHOOSE*/EMIT rule
    (the trace-time analog of crush_do_rule's step switch)."""
    rule = cm.cmap.rules[rule_id]
    t = cm.cmap.tunables
    plan: list[dict] = []
    tries = t.choose_total_tries
    leaf_tries = 0
    for step in rule.steps:
        if step.op == RuleOp.TAKE:
            plan.append(dict(op="take", take=step.arg1))
        elif step.op == RuleOp.SET_CHOOSE_TRIES:
            tries = step.arg1
        elif step.op == RuleOp.SET_CHOOSELEAF_TRIES:
            leaf_tries = step.arg1
        elif step.op in (
            RuleOp.CHOOSE_FIRSTN,
            RuleOp.CHOOSE_INDEP,
            RuleOp.CHOOSELEAF_FIRSTN,
            RuleOp.CHOOSELEAF_INDEP,
        ):
            want = step.arg1 if step.arg1 > 0 else numrep + step.arg1
            firstn = step.op in (RuleOp.CHOOSE_FIRSTN, RuleOp.CHOOSELEAF_FIRSTN)
            plan.append(
                dict(
                    op="choose",
                    want=want,
                    type=step.arg2,
                    firstn=firstn,
                    recurse=step.op
                    in (RuleOp.CHOOSELEAF_FIRSTN, RuleOp.CHOOSELEAF_INDEP),
                    tries=tries,
                    leaf_tries=leaf_tries,
                )
            )
        elif step.op == RuleOp.EMIT:
            plan.append(dict(op="emit"))
        else:
            raise ValueError(f"unsupported rule op {step.op}")
    if not any(p["op"] == "choose" for p in plan):
        raise ValueError("rule has no CHOOSE step")
    return plan


def compile_rule(cm: CompiledCrushMap, rule_id: int, numrep: int) -> dict:
    """Single-choose plan (the C++ oracle bridge's fast path); raises on
    anything but the canonical TAKE-CHOOSE-EMIT shape — multi-choose
    chains and EMIT-less rules go through the step interpreter."""
    steps = compile_plan(cm, rule_id, numrep)
    ops = [p["op"] for p in steps]
    if ops != ["take", "choose", "emit"]:
        raise NotImplementedError(
            "the C++ oracle fast path speaks TAKE-CHOOSE-EMIT only"
        )
    return dict(steps[0], **steps[1])


def _firstn_compact(work: jnp.ndarray) -> jnp.ndarray:
    """Dense-pack non-NONE entries left, preserving order (crush_do_rule
    concatenates each parent's successes contiguously into the working
    vector)."""
    is_none = work == ITEM_NONE
    order = jnp.argsort(is_none, axis=1, stable=True)
    return jnp.take_along_axis(work, order, axis=1)


def _build_rule_fn(cm: CompiledCrushMap, rule_id: int, numrep: int,
                   choose_args: str | None, engine_mode: str, score_fn):
    plan = compile_plan(cm, rule_id, numrep)
    if choose_args is None:
        cweights = None
    elif engine_mode == "limb":
        cweights = cm.choose_args_limb(choose_args)
    else:
        cweights = cm.choose_args_arrays(choose_args)
    engine_cls = LimbEngine if engine_mode == "limb" else I64Engine
    if engine_mode == "limb":
        cm.limb_tables  # build the fat tables OUTSIDE the trace

    def fn(xs, weightvec):
        N = xs.shape[0]
        eng = engine_cls(cm, score_fn, weightvec, cweights)
        work = None          # [N, W] current working vector
        emitted = []         # list of [N, w] blocks
        for p in plan:
            if p["op"] == "take":
                work = jnp.full((N, 1), p["take"], jnp.int32)
            elif p["op"] == "choose":
                if work is None:
                    raise ValueError("CHOOSE before TAKE")
                W = work.shape[1]
                want = p["want"]
                parents = work.reshape(N * W)
                x_b = jnp.repeat(xs, W) if W > 1 else xs
                parent_ok = (parents < 0) & (parents != ITEM_NONE)
                fn_b = choose_firstn_b if p["firstn"] else choose_indep_b
                tries = p["tries"]
                recurse_tries = (
                    (p["leaf_tries"] or tries)
                    if p["firstn"]
                    else (p["leaf_tries"] or 1)
                )
                res = fn_b(
                    eng, x_b, parents, want, p["type"],
                    tries, p["recurse"], recurse_tries, parent_ok,
                )
                out, out2 = res[0], res[1]
                chosen = out2 if p["recurse"] else out
                if p["firstn"]:
                    cnt = res[2]
                    chosen = jnp.where(
                        jnp.arange(want)[None, :] < cnt[:, None],
                        chosen,
                        ITEM_NONE,
                    )
                chosen = chosen.reshape(N, W * want)
                if p["firstn"] and W > 1:
                    chosen = _firstn_compact(chosen)
                work = chosen
            else:  # emit
                if work is not None:
                    emitted.append(work)
                work = None
        # un-emitted working items are DROPPED, like crush_do_rule (the
        # scalar mapper agrees; a rule without EMIT maps to nothing)
        if not emitted:
            return jnp.full((N, numrep), ITEM_NONE, jnp.int32)
        result = emitted[0] if len(emitted) == 1 else jnp.concatenate(
            emitted, axis=1
        )
        # contract: [N, numrep] — truncate extra width, pad scarcity
        if result.shape[1] > numrep:
            result = result[:, :numrep]
        elif result.shape[1] < numrep:
            result = jnp.concatenate(
                [
                    result,
                    jnp.full((N, numrep - result.shape[1]), ITEM_NONE, jnp.int32),
                ],
                axis=1,
            )
        return result

    # max lanes any step fans out to, for memory-aware chunking
    width = 1
    max_width = 1
    for p in plan:
        if p["op"] == "take":
            width = 1
        elif p["op"] == "choose":
            width *= p["want"]
            max_width = max(max_width, width)
    return jax.jit(fn), max_width


def default_engine_config(policy=None) -> tuple[str, object, bool]:
    """(engine_mode, score_fn, uses_pallas) for the current backend/env.

    Engine (CEPH_TPU_CRUSH_ENGINE = auto|limb|i64): the LIMB engine
    (crush/engine.py — one-hot fat-table gathers + magic-divisor limb
    draws, no int64/x64) on TPU backends; the I64 gather engine (native
    64-bit divides, fast row gathers) on CPU.

    Score path (CEPH_TPU_CRUSH_SCORE = auto|pallas|gather): the fused
    Pallas hash+ln kernel on TPU (no hardware vector gather — the
    2^16-entry table gather serializes there), the XLA table gather
    elsewhere ('axon' is this box's tunneled-TPU alias)."""
    import os

    emode = os.environ.get("CEPH_TPU_CRUSH_ENGINE", "auto")
    if emode not in ("auto", "limb", "i64"):
        raise ValueError(
            f"CEPH_TPU_CRUSH_ENGINE={emode!r}: want auto|limb|i64"
        )
    smode = os.environ.get("CEPH_TPU_CRUSH_SCORE", "auto")
    if smode not in ("auto", "pallas", "gather"):
        # a typo'd override silently auto-detecting would defeat its
        # purpose (forcing Pallas on unrecognized TPU aliases)
        raise ValueError(
            f"CEPH_TPU_CRUSH_SCORE={smode!r}: want auto|pallas|gather"
        )
    # backend resolves through the policy seam (cephtopo): a
    # cpu-fallback topology keeps the i64 engine + gather scorer even
    # when an accelerator is visible; callers may inject their own
    # policy (crush_do_rule_batch threads one through)
    from ..common.device_policy import get_device_policy

    pol = policy if policy is not None else get_device_policy()
    on_tpu = pol.backend() in ("tpu", "axon")
    if emode == "auto":
        emode = "limb" if on_tpu else "i64"
    use_pallas = smode == "pallas" or (smode == "auto" and on_tpu)
    if emode == "limb":
        score = ln_planes_pallas if use_pallas else ln_planes_jnp
    else:
        score = ln_scores_pallas if use_pallas else ln_scores_jnp
    return emode, score, use_pallas


def crush_do_rule_batch(
    cm: CompiledCrushMap,
    rule_id: int,
    xs,
    numrep: int,
    weightvec,
    choose_args: str | None = None,
    policy=None,
) -> jnp.ndarray:
    """Batched crush_do_rule: xs [N] -> [N, numrep] OSD ids.

    The new sibling entry point of CrushWrapper::do_rule that the north star
    adds (SURVEY.md §1 seam #2); consumed by the balancer simulation, the
    crushtool-analog --test, and the osdmaptool-analog --test-map-pgs.
    firstn results are dense with ITEM_NONE tail padding; indep results keep
    positional ITEM_NONE holes (EC shard semantics).  Arbitrary
    TAKE/CHOOSE/EMIT chains are interpreted (multi-choose rules flatten the
    working vector into the lane axis).

    Maps containing LEGACY bucket algorithms (uniform/list/tree/straw)
    route to the compiled C oracle: the jax/Pallas lanes implement
    straw2 — the algorithm every real deployment uses for data — and the
    legacy types exist for map-ingest parity, where C-speed batch
    evaluation is ample (uniform buckets are additionally STATEFUL per
    (x, rule) via their permutation cache, which is hostile to the
    fixed-trip vectorization).

    `policy` (cephtopo) injects a DevicePolicy for the engine/scorer
    pick; None consults the process-wide policy the daemon configured."""
    tm = TELEMETRY
    if not getattr(cm, "straw2_only", True):
        from .oracle_bridge import do_rule_steps_oracle

        t0 = time.perf_counter() if tm.enabled else 0.0
        out = do_rule_steps_oracle(
            cm.cmap, rule_id, np.asarray(xs), numrep,
            np.asarray(weightvec), choose_args, cm=cm,
        )
        if tm.enabled:
            tm.record("crush_do_rule_batch", "oracle",
                      time.perf_counter() - t0,
                      bytes_in=int(np.asarray(xs).nbytes),
                      bytes_out=int(out.nbytes), synced=True)
        return jnp.asarray(out)
    engine_mode, score_fn, uses_pallas = default_engine_config(policy)
    key = (rule_id, numrep, choose_args, engine_mode, uses_pallas)

    def build_and_cache():
        emode, score, _ = default_engine_config(policy)
        built = _build_rule_fn(
            cm, rule_id, numrep, choose_args, emode, score
        ) + (emode,)
        cm._rule_fn_cache[key] = built
        return built

    cached = cm._rule_fn_cache.get(key)
    compiled = cached is None
    if cached is None:
        cached = build_and_cache()
    t0 = time.perf_counter() if tm.enabled else 0.0
    try:
        out = _launch_rule_fn(cm, cached, xs, numrep, weightvec)
    except Exception as e:
        # one-shot downshift: an unattended bench must not lose the CRUSH
        # metric to a straw2-tile shape the installed Mosaic rejects —
        # fall back to the proven 32-row single-slab tile and rebuild.
        # Our own shape-validation errors are typed (TileShapeError) and
        # never retried; anything else gets ONE downshifted retry, and a
        # second failure restores the tile (the error wasn't tile-related)
        # before propagating.
        from ..ops import pallas_crush
        from ..ops.pallas_crush import TileShapeError

        if (
            isinstance(e, TileShapeError)
            or pallas_crush.DEFAULT_TILE == pallas_crush.CHUNK
            # the tile can only be implicated when the Pallas scorer is
            # the active path; on gather/CPU hosts the error is someone
            # else's and a rebuild would just repeat it slower
            or not uses_pallas
        ):
            raise
        import sys

        # the downshift mutates module-global shape knobs; serialize so
        # concurrent callers can't observe a half-applied downshift or
        # cache rule fns built against a shape mid-restore
        shape0 = (pallas_crush.LOOP_SLABS, pallas_crush.DEFAULT_TILE)
        with _TILE_LOCK:
            if (pallas_crush.LOOP_SLABS,
                    pallas_crush.DEFAULT_TILE) != shape0:
                # another thread settled a different shape while we
                # waited (our failure is stale evidence against the NEW
                # shape) — rebuild against it and retry once before
                # touching the knobs ourselves
                return _launch_rule_fn(
                    cm, build_and_cache(), xs, numrep, weightvec
                )
            if pallas_crush.LOOP_SLABS:
                # step 1: maybe the fori_loop/pl.ds walk is what Mosaic
                # rejected — restore the r4-proven static unroll at the
                # proven tile, keep going from there on the next failure
                print(
                    f"# crush straw2 loop-slab kernel failed "
                    f"({type(e).__name__}); retrying with the static "
                    f"unroll at tile 256", file=sys.stderr,
                )
                pallas_crush.LOOP_SLABS = False
                pallas_crush.DEFAULT_TILE = min(
                    pallas_crush.DEFAULT_TILE, 256
                )
                try:
                    return _launch_rule_fn(
                        cm, build_and_cache(), xs, numrep, weightvec
                    )
                except Exception as e2:
                    e = e2  # fall through to the tile downshift
            orig_tile = pallas_crush.DEFAULT_TILE
            if orig_tile == pallas_crush.CHUNK:
                raise
            print(
                f"# crush straw2 tile {orig_tile} failed "
                f"({type(e).__name__}); retrying with tile "
                f"{pallas_crush.CHUNK}", file=sys.stderr,
            )
            pallas_crush.DEFAULT_TILE = pallas_crush.CHUNK
            try:
                return _launch_rule_fn(
                    cm, build_and_cache(), xs, numrep, weightvec
                )
            except Exception:
                # not a tile problem after all: undo the downshift so the
                # process doesn't run 8x the grid steps forever
                pallas_crush.DEFAULT_TILE = orig_tile
                cm._rule_fn_cache.pop(key, None)
                raise
    else:
        if tm.enabled:
            # dispatch-side wall time (the result is a device array);
            # the rare one-shot downshift retries above go unrecorded
            tm.record("crush_do_rule_batch",
                      "pallas" if uses_pallas else "xla",
                      time.perf_counter() - t0,
                      bytes_in=int(getattr(xs, "nbytes", 0) or 0),
                      bytes_out=int(getattr(out, "nbytes", 0) or 0),
                      compiled=compiled)
        return out


def _launch_rule_fn(cm, cached, xs, numrep, weightvec) -> jnp.ndarray:
    import contextlib

    vf, max_width, engine_mode = cached

    # the limb engine traces WITHOUT x64 (its whole point); weightvec
    # semantics survive the int32 clamp because is_out only compares
    # weights below 0x10000 (values above mean "always in")
    ctx = enable_x64() if engine_mode != "limb" else contextlib.nullcontext()
    with ctx:
        xs_np = np.asarray(xs, dtype=np.int32)
        if engine_mode == "limb":
            weightvec = jnp.asarray(
                np.minimum(
                    np.asarray(weightvec, dtype=np.int64), 0x10000
                ).astype(np.int32)
            )
        else:
            weightvec = jnp.asarray(weightvec, dtype=jnp.int64)
        N = xs_np.shape[0]
        # chunk by LANES (N x max step width), not raw N: a multi-choose
        # step fans each x out to its working-vector width
        chunk_n = max(1, _BATCH_CHUNK // max_width)

        def padded_width(n: int) -> int:
            # next power of two, capped at chunk_n: bounds compiled-shape
            # count to log2(chunk_n) while never exceeding the lane budget
            # (an uncapped pow2 pad of a non-pow2 chunk_n could launch ~2x
            # _BATCH_CHUNK lanes — the empirical v5e fault boundary)
            p = max(1, 1 << (max(n, 1) - 1).bit_length())
            return chunk_n if p > chunk_n else p

        if N <= chunk_n:
            Np = padded_width(N)
            out = vf(jnp.asarray(np.resize(xs_np, Np)), weightvec)
            return out[:N] if Np != N else out
        # Large batches run as fixed-size device calls: one launch over
        # >~512k lanes (int64 while-loops) hard-faults the v5e worker, and
        # a single huge launch would also hold the whole [lanes, S]
        # intermediate set live in HBM.  Chunking keeps each launch inside
        # the envelope at ~zero throughput cost (per-x math dwarfs
        # dispatch).
        pieces = []
        for lo in range(0, N, chunk_n):
            part = xs_np[lo : lo + chunk_n]
            # ragged tail: pad to its own (capped) power of two — a shape
            # the small-batch path compiles anyway — not to a full chunk
            width = padded_width(len(part))
            padded = np.resize(part, width)
            pieces.append(
                np.asarray(vf(jnp.asarray(padded), weightvec))[: len(part)]
            )
        return jnp.asarray(np.concatenate(pieces))
