"""Exact division-by-invariant-integer magic for the fused straw2 kernel.

The straw2 draw is ``div64_s64(crush_ln(u) - 2**48, weight)`` (reference:
src/crush/mapper.c :: bucket_straw2_choose).  On TPU there is no 64-bit
integer divide — XLA lowers s64 division to a long software sequence and
forces the whole mapper under an x64 scope.  But CRUSH weights are *map
constants*, not data: every (bucket, slot) divisor is known on the host
when the map compiles.  So we precompute, per divisor ``w``, a magic
multiplier ``(M, k, a)`` with

    floor(p / w) == ((p + a) * M) >> k      for all 0 <= p <= P_MAX

(Granlund & Montgomery's classic technique; Hacker's Delight 10-9/10-10:
the round-up magic ``a=0`` or the round-down-with-increment ``a=1``
variant always exists at modest k).  The kernel then needs only 16-bit
limb multiplies and shifts — all exact in int32 lanes.

``p`` here is the *negated* draw numerator: ln = crush_ln(u) - 2**48 is
in [-2**48, 0], so p = -ln = 2**48 - crush_ln(u) is in [0, 2**48] and
draw = -floor(p / w).  Arg-MAX over draws (first max wins, mapper.c's
strict ``>`` scan) becomes arg-MIN over quotients (first min wins).

Everything in this module is host-side numpy/bignum; the traced twin
lives in ops/pallas_crush.py (fused kernel) with a jnp reference in
crush/batched.py.  Bit-exactness of the magic contract is proven per
divisor at build time by the analytic bound (not sampling), and
tests/test_magic_div.py re-checks against bignum division on random and
adversarial p.
"""
from __future__ import annotations

import numpy as np

# p = 2**48 - crush_ln(u) <= 2**48 inclusive
P_MAX = 1 << 48

# Magic multipliers fit 4 x 16-bit limbs for every divisor (M ~ 2**49..
# 2**51 regardless of w — see magic_for_divisor's postcondition check)
M_LIMBS = 4
# (p + a) fits 4 x 16-bit limbs (p <= 2**48, so limb 3 is 0 or 1)
P_LIMBS = 4
# full product fits 7 limbs (2**48 * 2**51 < 2**112)
PROD_LIMBS = 7


def magic_for_divisor(w: int) -> tuple[int, int, int]:
    """(M, k, a) with ((p + a) * M) >> k == p // w for all 0 <= p <= P_MAX.

    Proof obligations (checked, not assumed):
    - round-up (a=0): M = 2**k // w + 1, e = M*w - 2**k in (0, w];
      exact iff P_MAX * e < 2**k  (then the quotient error term
      p*e/2**k < 1 can never carry the floor past the true quotient).
    - round-down + increment (a=1): M = 2**k // w, e = 2**k - M*w in
      [0, w); exact iff (P_MAX + 1) * e <= 2**k.
    """
    if w <= 0:
        raise ValueError(f"divisor must be positive, got {w}")
    if w & (w - 1) == 0:
        # power of two: p // w == p >> lg(w), expressed at k=48 so the
        # kernel's fixed shift window applies
        return 1 << (48 - (w.bit_length() - 1)), 48, 0
    k = max(w.bit_length(), 1)
    while True:
        m_up = (1 << k) // w + 1
        e_up = m_up * w - (1 << k)
        if P_MAX * e_up < (1 << k):
            M, a = m_up, 0
            break
        m_dn = (1 << k) // w
        e_dn = (1 << k) - m_dn * w
        # e_dn == 0 would make this floor((p+1)/w) — only e_dn >= 1 keeps
        # the error term strictly inside the (r, r+1] bracket
        if m_dn > 0 and e_dn > 0 and (P_MAX + 1) * e_dn <= (1 << k):
            M, a = m_dn, 1
            break
        k += 1
    # postconditions the kernel layout depends on
    if M.bit_length() > 16 * M_LIMBS:
        raise AssertionError(f"magic for w={w} needs {M.bit_length()} bits")
    if not (48 <= k <= 16 * (PROD_LIMBS - 1)):
        # k < 48 can only happen for pathological tiny w bounds; clamp up
        # by scaling M so the kernel's shift window (limbs 3..5 + 0..15
        # bit shift) always applies
        shift_up = 48 - k
        M <<= shift_up
        k = 48
        if M.bit_length() > 16 * M_LIMBS:
            raise AssertionError(f"normalized magic for w={w} overflows")
    return M, k, a


def apply_magic(p, M: int, k: int, a: int):
    """Bignum/numpy-object golden: ((p + a) * M) >> k."""
    p = np.asarray(p, dtype=object)
    return (p + a) * M >> k


def magic_tables(weights: np.ndarray):
    """Vectorized build for a [..., S] int64 weight array.

    Returns dict of int32 arrays, all shaped like ``weights`` plus a limb
    axis where noted:
      m_limbs  [..., S, M_LIMBS]  16-bit limbs of M
      k        [..., S]           shift
      a        [..., S]           increment flag
    Zero/negative weights get an all-zero magic (their slots are masked
    invalid by the caller before the argmin).
    """
    w = np.asarray(weights, dtype=np.int64)
    flat = w.reshape(-1)
    m_limbs = np.zeros((flat.size, M_LIMBS), np.int32)
    ks = np.full(flat.size, 48, np.int32)
    aa = np.zeros(flat.size, np.int32)
    cache: dict[int, tuple[int, int, int]] = {}
    for i, wi in enumerate(flat.tolist()):
        if wi <= 0:
            continue
        got = cache.get(wi)
        if got is None:
            got = cache[wi] = magic_for_divisor(wi)
        M, k, a = got
        for j in range(M_LIMBS):
            m_limbs[i, j] = (M >> (16 * j)) & 0xFFFF
        ks[i] = k
        aa[i] = a
    shape = w.shape
    return {
        "m_limbs": m_limbs.reshape(shape + (M_LIMBS,)),
        "k": ks.reshape(shape),
        "a": aa.reshape(shape),
    }


def straw2_draw_q_np(p: np.ndarray, m_limbs, k, a) -> np.ndarray:
    """Numpy-int64-free golden of the limb pipeline the kernel runs:
    split p into 16-bit limbs, multiply by the magic limbs with base-2**16
    carry propagation, variable-shift the 7-limb product by k, recombine
    the 48-bit quotient as (hi24 << 24) | lo24 in python ints.

    This mirrors the kernel's arithmetic exactly (same limb widths, same
    carry points) so a bug in the layout fails HERE, on the host, first.
    """
    p = np.asarray(p, dtype=object)
    m_limbs = np.asarray(m_limbs, dtype=object)
    k = np.asarray(k, dtype=object)
    a = np.asarray(a, dtype=object)
    pa = p + a
    pl = [(pa >> (16 * j)) & 0xFFFF for j in range(P_LIMBS)]
    # column accumulation: col[c] = sum_{i+j==c} pl[i]*ml[j]
    cols = [np.zeros_like(p) for _ in range(PROD_LIMBS + 1)]
    for i in range(P_LIMBS):
        for j in range(M_LIMBS):
            cols[i + j] = cols[i + j] + pl[i] * m_limbs[..., j]
    # carry propagate to clean 16-bit limbs
    limbs = []
    carry = np.zeros_like(p)
    for c in range(PROD_LIMBS + 1):
        v = cols[c] + carry
        limbs.append(v & 0xFFFF)
        carry = v >> 16
    # variable shift: quotient = product >> k, k in [48, 96]
    total = np.zeros_like(p)
    for c, l in enumerate(limbs):
        total = total + (l << (16 * c))
    q = total >> k
    return q


__all__ = [
    "P_MAX",
    "M_LIMBS",
    "P_LIMBS",
    "PROD_LIMBS",
    "magic_for_divisor",
    "apply_magic",
    "magic_tables",
    "straw2_draw_q_np",
]
