"""TPU draw engine for the batched CRUSH mapper — gather-free, int64-free.

Round-4 verdict item #2: the batched mapper lost to the scalar C++ oracle
by 6.5x.  Profiling attributed the loss to exactly three TPU-hostile
constructs in the XLA glue around the (fast) Pallas hash+ln kernel:

  1. per-iteration row GATHERS (``jnp.take(cm.items, bidx)`` etc.) — TPUs
     have no vector gather; XLA serializes at ~9 ns/element;
  2. the int64 draw (``div64_s64(crush_ln(u) - 2^48, weight)``) — XLA
     emulates 64-bit division in long scalar sequences and the whole
     trace sits under an x64 scope;
  3. int64 intermediates everywhere (weights, scores, argmax), doubling
     vector-register pressure.

This module replaces all three with MXU/VPU-native formulations:

  - **One-hot fat-table gather**: every per-bucket array the choose loop
    needs (item ids, magic-divisor limbs, shift/increment, size, type)
    is decomposed host-side into 8-bit planes and concatenated into ONE
    ``[n_idx, C]`` table; a bucket-row lookup is then a single bf16
    one-hot matmul ``[T, n_idx] @ [n_idx, C]`` (bit-exact: every plane
    value <= 255, which bf16 represents exactly) — the TPU-native gather,
    same trick the Pallas ln kernel uses for its small tables.
  - **Magic-divisor limb draw** (crush/magic_div.py, Granlund-Montgomery):
    weights are map constants, so each divisor's exact magic ``(M, k, a)``
    is precomputed on the host and the kernel-side draw is 16-bit limb
    multiplies + a variable limb shift — all uint32 VPU lanes, no
    division, no int64.  ``draw = -floor(p / w)`` with ``p = 2^48 -
    crush_ln(u)``, so the reference's first-strict-max over draws becomes
    a first-strict-min over 48-bit quotients, compared lexicographically
    on (hi24, lo24) int32 planes.
  - **is_out via plane lookup**: the reweight test needs only
    ``min(w, 0x10000)`` (17 bits -> 3 planes) and a w==0 flag per OSD.

The scalar Python mapper (reference_mapper.py), the C++ oracle, and both
jax engines (this one and the int64 gather engine in batched.py) must
agree bit-for-bit on every input — tests/test_crush_limb.py sweeps them
against each other.  Reference seam: src/crush/mapper.c ::
bucket_straw2_choose / is_out; SURVEY.md §3.3 HOT LOOP #3.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .magic_div import M_LIMBS, magic_tables

I32_MAX = np.int32(0x7FFFFFFF)


# --------------------------------------------------------------- fat table

class LimbTables:
    """Host-built 8-bit-plane tables for the one-hot gathers.

    Layout of the per-bucket fat table ``bucket_tbl`` [n_idx, C]:
      cols [0,      4S)   item ids, 4 planes (uint32 little-endian bytes)
      cols [4S,    12S)   magic M limbs, M_LIMBS(4) x 2 planes each
      cols [12S,   13S)   shift k - 48 (0..48, one plane)
      cols [13S,   14S)   increment a (0/1) + (weight>0) flag packed as
                          a | valid<<1
      cols [14S, 14S+2)   bucket size lo/hi planes
      cols [14S+2, 14S+4) bucket type lo/hi planes
    """

    def __init__(self, items: np.ndarray, weights: np.ndarray,
                 sizes: np.ndarray, types: np.ndarray):
        n_idx, S = items.shape
        self.n_idx, self.S = n_idx, S
        mg = magic_tables(weights)
        m_limbs = mg["m_limbs"]          # [n_idx, S, 4] int32 16-bit limbs
        ks = mg["k"] - 48                # [n_idx, S] in [0, 48]
        aa = mg["a"]                     # [n_idx, S] 0/1
        valid = (weights > 0).astype(np.int32)
        iu = items.astype(np.uint32)
        planes = []
        for b in range(4):
            planes.append(((iu >> (8 * b)) & 0xFF).astype(np.float32))
        for limb in range(M_LIMBS):
            v = m_limbs[:, :, limb]
            planes.append((v & 0xFF).astype(np.float32))
            planes.append(((v >> 8) & 0xFF).astype(np.float32))
        planes.append(ks.astype(np.float32))
        planes.append((aa | (valid << 1)).astype(np.float32))
        tbl = np.concatenate(planes, axis=1)          # [n_idx, 14*S]
        meta = np.stack([
            sizes & 0xFF, (sizes >> 8) & 0xFF,
            types & 0xFF, (types >> 8) & 0xFF,
        ], axis=1).astype(np.float32)                 # [n_idx, 4]
        self.tbl = jnp.asarray(np.concatenate([tbl, meta], axis=1),
                               jnp.bfloat16)
        if np.any(tbl > 255) or np.any(tbl < 0):
            raise AssertionError("fat-table plane out of 8-bit range")

    def split(self, rows: jnp.ndarray):
        """Decode a gathered [T, C] f32 row block back into int32 arrays:
        (items [T,S], m_limbs 4x[T,S], k_s [T,S], a [T,S], valid [T,S],
        size [T], btype [T])."""
        S = self.S
        r = rows.astype(jnp.int32)
        it = (r[:, 0:S]
              | (r[:, S:2 * S] << 8)
              | (r[:, 2 * S:3 * S] << 16)
              | (r[:, 3 * S:4 * S] << 24))
        m = []
        for limb in range(M_LIMBS):
            lo = r[:, (4 + 2 * limb) * S:(5 + 2 * limb) * S]
            hi = r[:, (5 + 2 * limb) * S:(6 + 2 * limb) * S]
            m.append(lo | (hi << 8))
        k_s = r[:, 12 * S:13 * S]
        av = r[:, 13 * S:14 * S]
        a = av & 1
        valid = (av >> 1) & 1
        size = r[:, 14 * S] | (r[:, 14 * S + 1] << 8)
        btype = r[:, 14 * S + 2] | (r[:, 14 * S + 3] << 8)
        return it, m, k_s, a, valid, size, btype


def build_weightvec_planes(weightvec: jnp.ndarray) -> jnp.ndarray:
    """[n_osd] int32/int64 reweights -> [n_osd, 4] bf16 planes of
    wc = min(w, 0x10000) (3 bytes) + (w == 0) flag.  Runs inside the jit
    (reweights are per-call data, unlike the map constants)."""
    w = jnp.clip(weightvec.astype(jnp.int32), 0, 0x10000)
    zero = (weightvec.astype(jnp.int32) == 0).astype(jnp.int32)
    return jnp.stack(
        [w & 0xFF, (w >> 8) & 0xFF, (w >> 16) & 0xFF, zero], axis=1
    ).astype(jnp.bfloat16)


def onehot_rows(idx: jnp.ndarray, tbl: jnp.ndarray) -> jnp.ndarray:
    """[T] int32 indices -> [T, C] f32 rows of the bf16 table via the
    one-hot MXU matmul (exact for 8-bit plane values)."""
    n = tbl.shape[0]
    oh = (
        idx[:, None] == jax.lax.broadcasted_iota(jnp.int32, (1, n), 1)
    ).astype(jnp.bfloat16)
    return jax.lax.dot_general(
        oh, tbl,
        dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )


# ------------------------------------------------------------ limb pipeline

def quotient_planes(hi, lo, m_limbs, k_s, a):
    """(q_hi24, q_lo24) int32 planes of q = floor(p / w) where
    p = 2^48 - (hi<<24 | lo) and the divisor is encoded as magic limbs.

    Mirrors magic_div.straw2_draw_q_np limb-for-limb (same widths, same
    carry points) in uint32 lanes; hi/lo are the Pallas score kernel's
    crush_ln output planes (bits 24..47 / 0..23).
    """
    u = lambda x: x.astype(jnp.uint32)
    MASK16 = jnp.uint32(0xFFFF)
    MASK24 = jnp.uint32(0xFFFFFF)
    # p + a = 2^48 - (hi<<24|lo) + a via 24-bit borrow arithmetic
    t0 = (MASK24 - u(lo)) + jnp.uint32(1) + u(a)
    p_lo = t0 & MASK24
    c0 = t0 >> 24
    t1 = (MASK24 - u(hi)) + c0
    p_hi = t1 & MASK24
    l3 = t1 >> 24                      # 0 or 1 (p == 2^48)
    # 16-bit limbs of p
    pl = [
        p_lo & MASK16,
        (p_lo >> 16) | ((p_hi & jnp.uint32(0xFF)) << 8),
        (p_hi >> 8) & MASK16,
        l3,
    ]
    ml = [u(m) for m in m_limbs]
    # column accumulation of 16x16 partial products, split lo/hi to keep
    # every accumulator far below 2^32
    ncols = 8
    cols = [jnp.zeros_like(pl[0]) for _ in range(ncols + 1)]
    for i in range(4):
        for j in range(4):
            prod = pl[i] * ml[j]
            cols[i + j] = cols[i + j] + (prod & MASK16)
            cols[i + j + 1] = cols[i + j + 1] + (prod >> 16)
    limbs = []
    carry = jnp.zeros_like(pl[0])
    for c in range(ncols + 1):
        v = cols[c] + carry
        limbs.append(v & MASK16)
        carry = v >> 16
    # q = product >> k, k = 48 + k_s with k_s in [0, 48]: take limbs 3..
    # and shift by k_s.  h[i] = limb[3 + i]; indices up to 6 needed.
    h = limbs[3:8] + [jnp.zeros_like(pl[0])]
    ks = u(k_s)
    si = (ks >> 4).astype(jnp.int32)          # 0..3
    sr = ks & jnp.uint32(0xF)

    def pick(base):
        """h[base + si] with si in 0..3, vector select."""
        v = h[base]
        for s in (1, 2, 3):
            v = jnp.where(si == s, h[base + s] if base + s < len(h)
                          else jnp.zeros_like(v), v)
        return v

    def shifted(j):
        lo_l = pick(j)
        hi_l = pick(j + 1)
        # sr == 0 edge: (hi << 16) & 0xFFFF == 0, so the OR is exact
        return ((lo_l >> sr) | ((hi_l << (jnp.uint32(16) - sr)) & MASK16)) \
            & MASK16

    q0, q1, q2 = shifted(0), shifted(1), shifted(2)
    q_lo24 = (q0 | (q1 << 16)) & MASK24
    q_hi24 = ((q1 >> 8) | (q2 << 8)) & MASK24
    return q_hi24.astype(jnp.int32), q_lo24.astype(jnp.int32)


def argmin_planes(q_hi, q_lo, invalid):
    """First index of the lexicographic minimum over axis 1 of the
    (hi24, lo24) planes; `invalid` slots are +inf.  Matches mapper.c's
    first-strict-max scan over draws (draw = -q)."""
    q_hi = jnp.where(invalid, I32_MAX, q_hi)
    q_lo = jnp.where(invalid, I32_MAX, q_lo)
    mh = jnp.min(q_hi, axis=1, keepdims=True)
    cand = q_hi == mh
    q_lo_m = jnp.where(cand, q_lo, I32_MAX)
    ml = jnp.min(q_lo_m, axis=1, keepdims=True)
    first = cand & (q_lo_m == ml)
    return jnp.argmax(first, axis=1).astype(jnp.int32)


# ------------------------------------------------------------ choose pieces

def straw2_choose_limb(cm, score_fn, bucket_idx, x, r, cweights, position):
    """bucket_straw2_choose over lanes — limb-engine twin of
    batched.straw2_choose_b.  Identical output contract: [B] chosen item
    (ITEM_NONE for empty buckets)."""
    from .types import ITEM_NONE

    bidx = jnp.clip(bucket_idx, 0, cm.n_idx - 1)
    if cweights is None:
        tabs = cm.limb_tables
        rows = onehot_rows(bidx, tabs.tbl)
        items, m_limbs, k_s, a, valid, size, _bt = tabs.split(rows)
    else:
        tabs = cweights  # a LimbTables over [P * n_idx] flattened rows
        pos = jnp.minimum(position, tabs.positions - 1)
        rows = onehot_rows(pos * cm.n_idx + bidx, tabs.tbl)
        items, m_limbs, k_s, a, valid, size, _bt = tabs.split(rows)
    hi, lo = score_fn(cm, x, items, r)            # int32 ln planes
    q_hi, q_lo = quotient_planes(hi, lo, m_limbs, k_s, a)
    slot = jnp.arange(items.shape[1])[None, :]
    invalid = (slot >= size[:, None]) | (valid == 0)
    choice = argmin_planes(q_hi, q_lo, invalid)
    picked = jnp.take_along_axis(items, choice[:, None], axis=1)[:, 0]
    return jnp.where(size > 0, picked, ITEM_NONE)


def item_type_limb(cm, item):
    """Type of each item via the fat table's meta columns (devices 0)."""
    idx = jnp.clip(jnp.where(item < 0, -1 - item, 0), 0, cm.n_idx - 1)
    rows = onehot_rows(idx, cm.limb_tables.tbl)
    *_rest, btype = cm.limb_tables.split(rows)
    return jnp.where(item < 0, btype, 0)


def is_out_limb(wplanes, n_osd, item, x):
    """mapper.c :: is_out over lanes, weightvec via plane lookup.
    `wplanes` from build_weightvec_planes; `item` device ids."""
    from .hash import crush_hash32_2

    idx = jnp.clip(item, 0, n_osd - 1)
    rows = onehot_rows(idx, wplanes).astype(jnp.int32)   # [T, 4]
    wc = rows[:, 0] | (rows[:, 1] << 8) | (rows[:, 2] << 16)
    is_zero = rows[:, 3] == 1
    oob = item >= n_osd
    h = (
        crush_hash32_2(x.astype(jnp.uint32), item.astype(jnp.uint32))
        .astype(jnp.int32) & 0xFFFF
    )
    return oob | is_zero | ((wc < 0x10000) & (h >= wc))
