"""ceph_tpu.msg — wire layer (reference: src/msg/async — AsyncMessenger,
AsyncConnection, ProtocolV2; interface Messenger/Connection/Dispatcher in
src/msg/Messenger.h; SURVEY.md §5.8).

Re-design notes: the reference runs epoll event loops with N worker
threads; here each bound messenger has an accept thread and each connection
a reader thread (Python sockets, blocking I/O) — the *interfaces* mirror
the reference so the daemon code above reads the same: `Messenger.create`,
`Connection.send_message`, `Dispatcher.ms_dispatch` / `ms_handle_reset`.
Frames carry a crc32c like ProtocolV2; policies are lossy (clients: a reset
surfaces to the dispatcher, the Objecter resends) vs lossless-peer
(OSD↔OSD: transparent reconnect + replay of unacked frames).
"""
from .message import (
    Message,
    MPing,
    decode_message,
    encode_message,
    register_message,
)
from .messenger import Connection, Dispatcher, Messenger

__all__ = [
    "Connection",
    "Dispatcher",
    "MPing",
    "Message",
    "Messenger",
    "decode_message",
    "encode_message",
    "register_message",
]
