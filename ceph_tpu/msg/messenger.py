"""Threaded TCP messenger (reference: src/msg/async/AsyncMessenger.cc,
AsyncConnection.cc, ProtocolV2.cc; SURVEY.md §5.8).

Wire format, after a banner/identify exchange:
    frame := [u32 len][u32 crc32c(body, seed -1)][body]
    body  := [u8 ftype][payload]
    ftype 0 (message): payload = encode_message() bytes
    ftype 1 (ack):     payload = u64 seq — receiver has consumed through seq
                       (reference: ProtocolV2 ACK frames)
A bad crc, an oversized frame, an undecodable message, or a dispatcher
exception kills the connection, like ProtocolV2.  Acks keep the lossless
replay queue to unacked messages only, so session replay after a reconnect
is short and idempotent.

Policies (reference: Messenger::Policy):
- lossy (client side): a dead connection is reported via ms_handle_reset
  and the caller (Objecter/MonClient) resends at its layer.
- lossless_peer (OSD↔OSD): sends transparently reconnect and replay
  unacked frames; the receiver drops seq <= in_seq duplicates (ProtocolV2
  session replay), giving in-order exactly-once delivery per session.
The connector advertises its policy in the banner and the acceptor adopts
it, so both halves of a session always agree.

Locking: ONE reentrant lock per session (`_Session.lock`) serializes all
of a connection's send state, receive ordering, reconnect, and dispatch.
A dispatcher may therefore send on the connection it was called from
(reentrant), and a stale reader of a replaced socket cannot interleave
with the replacement (it re-checks socket identity under the lock).  The
coarse-grained lock trades throughput for obviousness; the reference gets
the same effect with its per-connection event-loop thread affinity.

Fault injection (common/failpoint.py; docs/fault_injection.md): message
frames pass the `msgr.frame.send` failpoint before hitting the wire (an
error action tears the socket down mid-stream — `ms_inject_socket_failures
= N` is the legacy spelling, routed through the registry as
every(N,error)) and the `msgr.frame.recv` failpoint after decode (an error
action silently swallows the frame, the thrasher's netsplit primitive —
the frame is neither dispatched nor acked, exactly a lossy network).

Auth (reference: ProtocolV2 auth frames + signed frames; SURVEY.md §2.7):
with `auth_cluster_required = cephx` the handshake runs the cephx exchange
(ceph_tpu/auth/cephx.py wire form) in one of two modes — shared-secret
proof (daemons, admin clients) or mon-minted service ticket (limited
clients, validated against the OSDMap's current auth generation) — and
every post-handshake frame then carries a 16-byte HMAC tag over
(per-direction counter || body) under the negotiated per-connection
session key.  A missing or bad tag is connection-fatal, so a
post-handshake frame can be neither forged, tampered with, nor replayed
within a session.
"""
from __future__ import annotations

import hmac as _hmac
import random
import socket
import struct
import threading
import time
from collections import deque

from ..auth.cephx import (
    frame_tag,
    proof_hex,
    session_key_from_nonces,
    validate_ticket,
)
from ..common.crc32c import crc32c
from ..common.lockdep import make_lock
from ..common.tracer import TRACER
from ..common.failpoint import (
    FailpointCrash,
    FailpointError,
    failpoint,
    registry as _registry,
)
from .message import Message, decode_message, encode_message

_TAG_LEN = 16
# handshake lines are bounded; the auth-ticket reply carries a sealed
# ~450-byte hex blob plus proof + nonce, so the auth exchange gets a
# larger budget than the short banner/ident lines
_AUTH_LINE_LIMIT = 4096

_BANNER = b"ceph_tpu msgr v1\n"


def _os_nonce() -> str:
    import os

    return os.urandom(16).hex()

_FRAME_MSG = 0
_FRAME_ACK = 1
# compressed message frame (reference: ProtocolV2 compression frames):
# body = [2][u8 algo_len][algo name][compressed payload].  The RECEIVE
# side is configuration-independent — it decompresses by the named
# algorithm from the registry — so only the sender's ms_compress knob
# governs whether a link compresses (the reference's ms_osd_compress_*
# conf gates the sender the same way)
_FRAME_MSG_Z = 2
# delivery attempts for a message whose dispatcher keeps raising before it
# is dropped-and-acked as poison (at-least-once, bounded)
_POISON_RETRIES = 3

POLICY_LOSSY = "lossy"
POLICY_LOSSLESS_PEER = "lossless_peer"


class _Session:
    """Per-session state shared across socket reincarnations of one peer
    session (reference: ProtocolV2 session state kept over reconnects)."""

    __slots__ = ("in_seq", "lock", "fail_seq", "fail_count")

    def __init__(self):
        self.in_seq = 0
        self.lock = make_lock("msgr::session")
        # poison-message tracking: seq of the last message whose dispatch
        # raised, and how many delivery attempts it has burned
        self.fail_seq = -1
        self.fail_count = 0


class Dispatcher:
    """Upcall interface (reference: src/msg/Dispatcher.h)."""

    def ms_dispatch(self, conn: "Connection", msg: Message) -> bool:
        return False

    def ms_handle_reset(self, conn: "Connection") -> None:
        pass


class Connection:
    """One peer session (reference: AsyncConnection + ProtocolV2 state)."""

    def __init__(self, msgr: "Messenger", sock: socket.socket | None,
                 peer_addr, policy: str, outgoing: bool,
                 session: "_Session | None" = None):
        self.msgr = msgr
        self.sock = sock
        self.peer_addr = peer_addr
        self.peer_name = ""
        self.policy = policy
        self.outgoing = outgoing
        self.out_seq = 0
        # connect incarnation: advertised in the banner so the acceptor can
        # tie socket reincarnations of a lossless session together and keep
        # deduping replayed seqs (reference: ProtocolV2 client_cookie)
        self.connect_id = random.getrandbits(63)
        self._session = session if session is not None else _Session()
        # unacked frames for lossless replay; unbounded — backpressure is
        # the job of higher-layer throttles (objecter_inflight_ops), and a
        # bounded deque here would silently break the no-loss contract
        self._replay: deque[tuple[int, bytes]] = deque()
        self._closed = False
        # per-connection frame-signing key + send counter, reset together
        # with every socket incarnation (fresh handshake = fresh key); the
        # receive counter lives in the reader thread, which is also
        # per-incarnation
        self._frame_key: bytes | None = None
        self._tx_ctr = 0

    @property
    def _lock(self) -> threading.RLock:
        return self._session.lock

    @property
    def in_seq(self) -> int:
        return self._session.in_seq

    @in_seq.setter
    def in_seq(self, v: int) -> None:
        self._session.in_seq = v

    # -- sending ----------------------------------------------------------
    def send_message(self, msg: Message) -> None:
        with self._lock:
            if self._closed:
                raise ConnectionError(f"connection to {self.peer_addr} is down")
            self.out_seq += 1
            msg.seq = self.out_seq
            msg.src = self.msgr.name
            if TRACER.enabled:  # one attribute check when tracing is off
                t_id = getattr(msg, "trace_id", None)
                if t_id is not None:
                    TRACER.tracepoint(
                        "msgr", "send", entity=self.msgr.name,
                        trace_id=t_id, msg=type(msg).__name__,
                        peer=self.peer_name or str(self.peer_addr),
                    )
            payload = encode_message(msg)
            if self.policy == POLICY_LOSSLESS_PEER:
                self._replay.append((self.out_seq, payload))
            try:
                self._send_frame(_FRAME_MSG, payload)
            except OSError:
                if self.policy == POLICY_LOSSLESS_PEER and self.outgoing:
                    self._reconnect_and_replay()
                else:
                    self.mark_down()
                    raise ConnectionError(
                        f"connection to {self.peer_addr} reset"
                    ) from None

    def _send_frame(self, ftype: int, payload: bytes, inject: bool = True) -> None:
        if (inject and ftype == _FRAME_MSG
                and _registry().configured("msgr.frame.send")):
            try:
                failpoint(
                    "msgr.frame.send", cct=self.msgr.cct,
                    entity=self.msgr.name, peer=self.peer_name or None,
                )
            except FailpointCrash:
                raise
            except FailpointError:
                # simulate a peer reset mid-stream (the legacy
                # ms_inject_socket_failures behavior)
                if self.sock is not None:
                    try:
                        self.sock.shutdown(socket.SHUT_RDWR)
                    except OSError:
                        pass
                raise OSError("injected socket failure") from None
        if self.sock is None:
            raise OSError("not connected")
        comp = self.msgr._wire_comp
        if (
            ftype == _FRAME_MSG and comp is not None
            and len(payload) >= self.msgr._wire_min_size
        ):
            z = comp.compress(payload)
            name = self.msgr._wire_comp_name.encode()
            if len(z) + len(name) + 6 < len(payload):
                ftype = _FRAME_MSG_Z
                # declared raw length up front: the receiver bounds its
                # allocation BEFORE inflating (decompression-bomb guard)
                payload = (bytes([len(name)]) + name
                           + struct.pack("<I", len(payload)) + z)
                # messenger-wide counter shared by every connection's send
                # path: the increment must not lose updates under
                # concurrent sends (sessions hold only their own lock)
                with self.msgr._lock:
                    self.msgr.comp_frames_sent += 1
        body = bytes([ftype]) + payload
        frame = struct.pack("<II", len(body), crc32c(body)) + body
        if self._frame_key is not None:
            frame += frame_tag(self._frame_key, self._tx_ctr, body)
            self._tx_ctr += 1
        self.sock.sendall(frame)

    def _send_ack(self, seq: int) -> None:
        with self._lock:
            try:
                self._send_frame(_FRAME_ACK, struct.pack("<Q", seq))
            except OSError:
                pass  # the reconnect path re-acks via dedup

    def _handle_ack(self, seq: int) -> None:
        with self._lock:
            while self._replay and self._replay[0][0] <= seq:
                self._replay.popleft()

    def _reconnect_and_replay(self) -> None:
        """Lossless-peer session replay (reference: ProtocolV2 reconnect).
        Runs under the session lock, so socket swap + in_seq reset are
        atomic with respect to any stale reader's dispatch re-check."""
        last_err: OSError | None = None
        for _ in range(3):
            try:
                sock, fkey = self.msgr._open_socket(
                    self.peer_addr, self.connect_id, self.policy
                )
                self.sock = sock
                self._frame_key, self._tx_ctr = fkey, 0
                # the peer's responding half restarts at seq 1 on a fresh
                # socket (its duplicate requests are dropped, so replies
                # are never duplicated) — restart our receive expectation
                self.in_seq = 0
                self.msgr._start_reader(self)
                for _seq, payload in list(self._replay):
                    self._send_frame(_FRAME_MSG, payload, inject=False)
                return
            except OSError as e:
                last_err = e
                time.sleep(0.05)
        self.mark_down()
        raise ConnectionError(
            f"lossless reconnect to {self.peer_addr} failed: {last_err}"
        ) from None

    def mark_down(self) -> None:
        """Tear down without notifying the dispatcher (reference:
        Connection::mark_down)."""
        self._closed = True
        if self.sock is not None:
            # shutdown() (not just close()) so a reader blocked in recv on
            # this socket wakes immediately and the peer sees FIN
            try:
                self.sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                self.sock.close()
            except OSError:
                pass
        self.msgr._forget(self)

    @property
    def is_connected(self) -> bool:
        return not self._closed and self.sock is not None


class Messenger:
    """reference: Messenger::create + AsyncMessenger."""

    def __init__(self, cct, name: str):
        self.cct = cct
        self.name = name  # entity name, e.g. "osd.3"
        self.myaddr: tuple[str, int] | None = None
        self.dispatchers: list[Dispatcher] = []
        self.default_policy = POLICY_LOSSY
        self._listener: socket.socket | None = None
        self._accept_thread: threading.Thread | None = None
        self._conns: dict[tuple[str, int], Connection] = {}
        self._conns_by_name: dict[str, Connection] = {}
        # (peer_name, connect_id) -> _Session surviving reconnects
        self._sessions: dict[tuple[str, int], _Session] = {}
        self._lock = make_lock("msgr::messenger")
        # stop flag as an Event: a plain bool here is a write/read race
        # between shutdown() and the accept/rx loops (cephrace CR1); the
        # Event is the same idiom Monitor uses for its stop flag
        self._stop_event = threading.Event()
        # cephx-style mutual auth (reference: ProtocolV2 auth frames);
        # engine built lazily from config so tests can flip it per-context
        self._auth = None
        self._auth_checked = False
        # on-wire compression (sender-side knob; see _FRAME_MSG_Z).
        # Default policy restricts the WIRE to zlib — the one algorithm
        # every receiver can construct (stdlib) — because there is no
        # capability negotiation in the handshake: a receiver missing an
        # optional module would fail the frame connection-fatally and
        # the lossless replay would loop.  ms_compress_force overrides
        # for fleets known to carry the module everywhere.
        self._wire_comp = None
        self._wire_comp_name = ""
        self._wire_min_size = 4096
        algo = cct.conf.get("ms_compress") if cct else "none"
        if algo and algo != "none":
            if algo != "zlib" and not (
                cct and cct.conf.get("ms_compress_force")
            ):
                raise ValueError(
                    f"ms_compress={algo!r} needs ms_compress_force=true "
                    f"(no wire negotiation: every peer must carry the "
                    f"module; zlib is the negotiation-free default)"
                )
            from ..compressor import Compressor

            self._wire_comp = Compressor.create(algo)
            self._wire_comp_name = algo
            self._wire_min_size = cct.conf.get("ms_compress_min_size")
        self._wire_decomp: dict[str, object] = {}
        #: frames actually sent compressed (observability/tests)
        self.comp_frames_sent = 0

    def _auth_required(self) -> bool:
        return (
            self.cct is not None
            and self.cct.conf.get("auth_cluster_required") == "cephx"
        )

    def _authenticator(self):
        """Shared-secret engine, or None when no secret is configured —
        which on a cephx-required CONNECTOR means ticket mode (the
        credentials live in cct.tickets), and on a cephx-required ACCEPTOR
        means misconfiguration (every peer is rejected: only secret
        holders can validate anything — fail closed)."""
        # fully under the messenger lock: concurrent handshake threads
        # racing the lazy init was a write/read race on _auth_checked
        # (cephrace CR1); handshakes are rare enough that a fast path
        # is not worth the unsynchronized read
        with self._lock:
            if not self._auth_checked:
                if self._auth_required() \
                        and self.cct.conf.get("auth_shared_secret"):
                    from ..auth import CephxAuthenticator

                    # construct BEFORE marking checked: a bad secret must
                    # stay a loud failure on every connection (fail
                    # closed), never silently disable auth on a
                    # cephx-required messenger
                    self._auth = CephxAuthenticator(
                        self.cct.conf.get("auth_shared_secret")
                    )
                self._auth_checked = True
            return self._auth

    @property
    def auth_service(self) -> str:
        """Service this messenger serves as, announced in the challenge so
        ticket clients pick the right ticket: the entity-name type prefix
        ('osd.3' -> 'osd', the reference's entity_name_t type)."""
        return self.name.split(".", 1)[0]

    # Current auth generation for ticket validation; daemons point this at
    # their OSDMap view (osdmap.auth_gens) so `auth rotate` propagates
    # through the normal map-subscription path (the CephxKeyServer
    # rotating_secrets role).  None -> generation 1 (rotation never used).
    auth_gen_provider = None

    @staticmethod
    def _read_line(sock: socket.socket, limit: int = 512) -> str:
        line = b""
        while not line.endswith(b"\n"):
            if len(line) > limit:
                raise ConnectionError("auth line too long")
            b = sock.recv(1)
            if not b:
                raise ConnectionError("peer closed during auth")
            line += b
        return line.decode().strip()

    @classmethod
    def create(cls, cct, name: str) -> "Messenger":
        return cls(cct, name)

    def _dout(self, level: int, msg: str) -> None:
        if self.cct is not None:
            self.cct.dout("ms", level, f"{self.name}: {msg}")

    # -- setup ------------------------------------------------------------
    def add_dispatcher(self, d: Dispatcher) -> None:
        self.dispatchers.append(d)

    def bind(self, addr: tuple[str, int] = ("127.0.0.1", 0)) -> tuple[str, int]:
        s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        s.bind(addr)
        s.listen(64)
        self._listener = s
        self.myaddr = s.getsockname()
        return self.myaddr

    def start(self) -> None:
        if self._listener is not None and self._accept_thread is None:
            self._accept_thread = threading.Thread(
                target=self._accept_loop, name=f"msgr-{self.name}", daemon=True
            )
            self._accept_thread.start()

    @property
    def _stopped(self) -> bool:
        return self._stop_event.is_set()

    def shutdown(self) -> None:
        self._stop_event.set()
        # take the listener under the lock (two shutdown() racers would
        # double-close), tear it down after release
        with self._lock:
            listener, self._listener = self._listener, None
            conns = list(self._conns.values())
        if listener is not None:
            try:
                listener.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                listener.close()
            except OSError:
                pass
        for c in conns:
            c.mark_down()
        # the accept loop wakes on the closed listener; reap it so a
        # stopped messenger leaves no thread behind (join is idempotent
        # under racing shutdowns; current_thread guards a self-stop)
        if (self._accept_thread is not None
                and self._accept_thread is not threading.current_thread()):
            self._accept_thread.join(timeout=5)
        self._accept_thread = None

    # -- outgoing ---------------------------------------------------------
    def connect(
        self, addr: tuple[str, int], policy: str | None = None
    ) -> Connection:
        """Get-or-create a connection (reference:
        Messenger::connect_to/get_connection).  The blocking dial happens
        outside the messenger lock; a lost creation race closes the extra
        socket and returns the winner."""
        addr = (addr[0], addr[1])
        if self._stopped:
            raise ConnectionError(f"messenger {self.name} is shut down")
        with self._lock:
            conn = self._conns.get(addr)
            if conn is not None and conn.is_connected:
                return conn
        fresh = Connection(
            self, None, addr, policy or self.default_policy, outgoing=True
        )
        sock, fkey = self._open_socket(addr, fresh.connect_id, fresh.policy)
        with self._lock:
            conn = self._conns.get(addr)
            if conn is not None and conn.is_connected:
                try:
                    sock.close()
                except OSError:
                    pass
                return conn
            fresh.sock = sock
            fresh._frame_key = fkey
            self._conns[addr] = fresh
        self._start_reader(fresh)
        return fresh

    def _open_socket(
        self, addr: tuple[str, int], connect_id: int, policy: str
    ) -> tuple[socket.socket, bytes | None]:
        """Dial + banner + (when cephx-required) the auth handshake.
        Returns (socket, frame-signing key or None)."""
        timeout = self.cct.conf.get("ms_connect_timeout") if self.cct else 10.0
        sock = socket.create_connection(addr, timeout=timeout)
        sock.settimeout(None)
        if self.cct is None or self.cct.conf.get("ms_tcp_nodelay"):
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        # banner + identify (reference: ProtocolV2 banner/hello frames; the
        # connect_id plays client_cookie's role, and the policy rides along
        # so the acceptor's half agrees with ours)
        sock.sendall(_BANNER + f"{self.name} {connect_id} {policy}\n".encode())
        try:
            auth = self._authenticator()
        except Exception as e:
            sock.close()
            raise ConnectionError(f"auth misconfigured: {e}") from e
        if not self._auth_required():
            return sock, None
        # mutual cephx-style exchange (ceph_tpu/auth/cephx.py wire form):
        # shared-secret proof when we hold the keyring, service ticket
        # otherwise.  A server WITHOUT auth sends no challenge -> we time
        # out, the same hard failure a cephx-required cluster hands a peer
        try:
            sock.settimeout(timeout)
            kind, snonce, service = self._read_line(
                sock, _AUTH_LINE_LIMIT
            ).split()
            if kind != "auth-challenge":
                raise ConnectionError(f"expected challenge, got {kind}")
            cnonce = _os_nonce()
            if auth is not None:
                sock.sendall(
                    f"auth-proof {auth.proof(snonce, self.name)} {cnonce}\n"
                    .encode()
                )
                fkey = auth.session_key(snonce, cnonce)
            else:
                t = (getattr(self.cct, "tickets", None) or {}).get(service)
                if t is None:
                    raise ConnectionError(
                        f"server requires cephx and no secret or "
                        f"{service!r} ticket is available"
                    )
                skey = bytes.fromhex(t["session_key"])
                sock.sendall(
                    f"auth-ticket {t['ticket']} "
                    f"{proof_hex(skey, snonce, self.name)} {cnonce}\n"
                    .encode()
                )
                # frame key mixes BOTH nonces so every socket incarnation
                # signs under a fresh key — reusing the raw ticket session
                # key would let frames recorded on one incarnation replay
                # on the next at the same counter positions
                fkey = session_key_from_nonces(skey, snonce, cnonce)
            kind, sproof = self._read_line(sock, _AUTH_LINE_LIMIT).split()
            # the server proves as 'cluster': any cluster-secret holder is
            # equally trusted, so the entity name adds nothing (proof
            # mode); in ticket mode it proves possession of the ticket's
            # session key, which only a service-key holder could unseal
            if kind != "auth-ok" or not _hmac.compare_digest(
                proof_hex(skey, cnonce, "cluster")
                if auth is None
                else auth.proof(cnonce, "cluster"),
                sproof,
            ):
                raise ConnectionError("server failed mutual auth")
            sock.settimeout(None)
        except (OSError, ValueError) as e:
            sock.close()
            raise ConnectionError(f"auth handshake failed: {e}") from e
        return sock, fkey

    # -- incoming ---------------------------------------------------------
    def _accept_loop(self) -> None:
        while not self._stopped:
            # snapshot under the lock (shutdown() swaps it to None under
            # the same lock); accept() itself runs outside the lock
            with self._lock:
                listener = self._listener
            if listener is None:
                return
            try:
                sock, peer = listener.accept()
            except OSError as e:
                with self._lock:
                    gone = self._listener is None
                if self._stopped or gone:
                    return
                # transient accept failure (ECONNABORTED, EMFILE burst)
                # must not kill the acceptor
                self._dout(1, f"accept error, retrying: {e}")
                time.sleep(0.01)
                continue
            threading.Thread(  # noqa: CL13 — fire-and-forget by design: a handshake either promotes into a reader (reaped via mark_down) or closes its socket and exits
                target=self._handshake_incoming, args=(sock, peer), daemon=True
            ).start()

    def _handshake_incoming(self, sock: socket.socket, peer) -> None:
        try:
            sock.settimeout(self.cct.conf.get("ms_connect_timeout") if self.cct else 10.0)
            banner = self._read_exact(sock, len(_BANNER))
            if banner != _BANNER:
                sock.close()
                return
            ident = self._read_line(sock)
            sock.settimeout(None)
        except (OSError, ConnectionError):
            sock.close()
            return
        try:
            peer_name, cid_str, policy = ident.split()
            connect_id = int(cid_str)
            if policy not in (POLICY_LOSSY, POLICY_LOSSLESS_PEER):
                raise ValueError(policy)
        except ValueError:
            sock.close()
            return
        fkey: bytes | None = None
        try:
            auth = self._authenticator()
        except Exception as e:
            # misconfigured secret on a cephx-required acceptor: reject
            # every peer loudly rather than failing open
            self._dout(0, f"auth misconfigured, rejecting {peer}: {e}")
            sock.close()
            return
        if self._auth_required():
            if auth is None:
                # cephx required but no secret: an acceptor cannot
                # validate proofs OR tickets — fail closed
                self._dout(0, f"cephx required but no secret; rejecting {peer}")
                sock.close()
                return
            try:
                sock.settimeout(
                    self.cct.conf.get("ms_connect_timeout") if self.cct else 10.0
                )
                snonce = auth.make_nonce()
                sock.sendall(
                    f"auth-challenge {snonce} {self.auth_service}\n".encode()
                )
                parts = self._read_line(sock, _AUTH_LINE_LIMIT).split()
                if not parts:
                    raise ConnectionError("empty auth reply")
                if parts[0] == "auth-proof" and len(parts) == 3:
                    _, proof, cnonce = parts
                    if not auth.verify(snonce, peer_name, proof):
                        raise ConnectionError(f"bad auth proof from {peer_name}")
                    sock.sendall(
                        f"auth-ok {auth.proof(cnonce, 'cluster')}\n".encode()
                    )
                    fkey = auth.session_key(snonce, cnonce)
                elif parts[0] == "auth-ticket" and len(parts) == 4:
                    _, blob, proof, cnonce = parts
                    gen = (self.auth_gen_provider() if self.auth_gen_provider
                           else 1)
                    t = validate_ticket(
                        auth.secret, self.auth_service, gen, blob
                    )
                    if t is None:
                        raise ConnectionError(
                            f"invalid/expired/rotated-out {self.auth_service} "
                            f"ticket from {peer_name}"
                        )
                    skey = bytes.fromhex(t["session_key"])
                    if t.get("entity") != peer_name or not _hmac.compare_digest(
                        proof_hex(skey, snonce, peer_name), proof
                    ):
                        raise ConnectionError(
                            f"ticket session-key proof failed for {peer_name}"
                        )
                    sock.sendall(
                        f"auth-ok {proof_hex(skey, cnonce, 'cluster')}\n"
                        .encode()
                    )
                    # mix both nonces: fresh frame key per incarnation
                    # (see the connector-side comment)
                    fkey = session_key_from_nonces(skey, snonce, cnonce)
                else:
                    raise ConnectionError(f"bad auth reply {parts[:1]}")
                sock.settimeout(None)
            except (OSError, ValueError, ConnectionError) as e:
                self._dout(1, f"auth reject {peer_name}@{peer}: {e}")
                sock.close()
                return
        with self._lock:
            sess = self._sessions.setdefault((peer_name, connect_id), _Session())
            conn = Connection(
                self, sock, peer, policy, outgoing=False, session=sess,
            )
            conn.peer_name = peer_name
            conn.connect_id = connect_id
            conn._frame_key = fkey
            self._conns[peer] = conn
            self._conns_by_name[peer_name] = conn
            if len(self._sessions) > 4096:
                self._evict_sessions_locked()
        self._start_reader(conn)

    def _evict_sessions_locked(self) -> None:
        # bound session-state memory without destroying the dedup state of
        # sessions that still have a live connection
        live = {id(c._session) for c in self._conns.values()}
        for key in list(self._sessions):
            if len(self._sessions) <= 2048:
                break
            if id(self._sessions[key]) not in live:
                del self._sessions[key]

    def _start_reader(self, conn: Connection) -> None:
        threading.Thread(  # noqa: CL13 — fire-and-forget by design: the read loop exits when its socket incarnation dies; shutdown reaps it via mark_down, not join
            target=self._read_loop, args=(conn, conn.sock),
            name=f"msgr-{self.name}-rx", daemon=True,
        ).start()

    def _read_loop(self, conn: Connection, sock: socket.socket) -> None:
        max_len = self.cct.conf.get("ms_max_frame_len") if self.cct else (1 << 28)
        # frame auth state is per socket incarnation: the key was set by
        # the handshake that produced `sock`, and the receive counter
        # starts at 0 exactly when the peer's send counter does
        fkey = conn._frame_key
        rx_ctr = 0
        if fkey is not None:
            from ..auth.cephx import frame_tag
        try:
            while not conn._closed and sock is conn.sock:
                hdr = self._read_exact(sock, 8)
                length, crc = struct.unpack("<II", hdr)
                if length > max_len or length < 1:
                    raise OSError(f"bad frame length ({length})")
                body = self._read_exact(sock, length)
                if crc32c(body) != crc:
                    raise OSError("frame crc mismatch")
                if fkey is not None:
                    tag = self._read_exact(sock, _TAG_LEN)
                    if not _hmac.compare_digest(
                        frame_tag(fkey, rx_ctr, body), tag
                    ):
                        # forged/tampered/replayed frame: connection-fatal
                        # (reference: ProtocolV2 signed-frame mismatch)
                        self._dout(
                            0, f"frame auth tag mismatch from {conn.peer_addr}"
                        )
                        raise OSError("frame auth tag mismatch")
                    rx_ctr += 1
                ftype, payload = body[0], body[1:]
                if ftype == _FRAME_ACK:
                    conn._handle_ack(struct.unpack("<Q", payload)[0])
                    continue
                if ftype == _FRAME_MSG_Z:
                    alen = payload[0]
                    algo = payload[1:1 + alen].decode()
                    (raw_len,) = struct.unpack_from("<I", payload,
                                                    1 + alen)
                    if raw_len > max_len or raw_len < 1:
                        # ms_max_frame_len bounds the INFLATED size too:
                        # a lying header cannot make us allocate beyond
                        # it (decompression-bomb guard)
                        raise OSError(
                            f"bad inflated frame length ({raw_len})")
                    comp = self._wire_decomp.get(algo)
                    if comp is None:
                        from ..compressor import Compressor

                        comp = self._wire_decomp[algo] = \
                            Compressor.create(algo)
                    z = payload[5 + alen:]
                    if not hasattr(comp, "decompress_bounded"):
                        # an unbounded inflate would defeat the bomb
                        # guard (the stream could exceed its declared
                        # size before any post-check): only algorithms
                        # with a bounded inflate may ride the wire
                        raise OSError(
                            f"wire compression {algo!r} lacks bounded "
                            f"inflate")
                    payload = comp.decompress_bounded(z, raw_len)
                    if len(payload) != raw_len:
                        raise OSError(
                            "inflated frame length mismatch "
                            f"({len(payload)} != declared {raw_len})")
                msg = decode_message(payload)
                if TRACER.enabled:  # one attribute check when off
                    t_id = getattr(msg, "trace_id", None)
                    if t_id is not None:
                        TRACER.tracepoint(
                            "msgr", "recv", entity=self.name,
                            trace_id=t_id, msg=type(msg).__name__,
                            peer=msg.src or conn.peer_name or None,
                        )
                if _registry().configured("msgr.frame.recv"):
                    try:
                        failpoint(
                            "msgr.frame.recv", cct=self.cct,
                            entity=self.name,
                            peer=msg.src or conn.peer_name or None,
                        )
                    except FailpointCrash:
                        # crash is CONNECTION-fatal here (the generic
                        # reader handler below absorbs it): one
                        # interpreter hosts many daemons, so there is no
                        # process to kill — docs/fault_injection.md
                        # documents this scoping
                        raise
                    except FailpointError:
                        # the frame vanishes in the "network": neither
                        # dispatched nor acked (the thrasher's netsplit
                        # primitive) — recovery, not replay, heals the gap
                        continue
                sess = conn._session
                with sess.lock:
                    if conn._closed or sock is not conn.sock:
                        # socket was replaced/closed while we were blocked:
                        # this frame belongs to the dead incarnation
                        return
                    if msg.seq <= conn.in_seq:
                        conn._send_ack(conn.in_seq)  # re-ack dropped dup
                        continue
                    if not conn.peer_name:
                        conn.peer_name = msg.src
                # dispatch OUTSIDE the session lock (reference: the
                # DispatchQueue decoupling — fast_dispatch never holds
                # connection locks): dispatchers take their own locks
                # (monc::lock, osd::pg, ...) and daemon code sends —
                # which takes session locks — while holding those, so an
                # upcall under msgr::session is one half of an ABBA
                # inversion lockdep aborts on.  This rx thread is the
                # connection's only reader, so delivery order is
                # untouched.  Dispatch BEFORE advancing in_seq / acking:
                # if the dispatcher raises, the sender must keep its
                # replay entry (an early ack would prune it and lose the
                # message despite the lossless contract — advisor r1).
                # A reconnect racing the dispatch replays the frame on
                # the next incarnation (in_seq unadvanced) — duplicate
                # delivery, the same at-least-once edge crash-replay
                # already forces handlers to absorb via reqid dup
                # caches.  And a DETERMINISTICALLY-failing handler must
                # not reconnect-livelock the peer pair: after
                # _POISON_RETRIES failed deliveries of the same seq the
                # message is dropped-and-acked with a loud log.
                try:
                    self._dispatch(conn, msg)
                except Exception:
                    # the session outlives socket incarnations, so a
                    # replaced socket's rx thread can race this one on
                    # the poison counters — count under the lock
                    with sess.lock:
                        if sess.fail_seq == msg.seq:
                            sess.fail_count += 1
                        else:
                            sess.fail_seq, sess.fail_count = msg.seq, 1
                        fail_count = sess.fail_count
                    # Only an INCOMING conn earns a redelivery by dying:
                    # its dialer holds the unacked frame in _replay and
                    # resends on reconnect.  An outgoing conn receives
                    # replies; the acceptor side drops its replay when
                    # the socket dies, so killing the conn here would
                    # just blackhole the link (reviewer r2) — drop the
                    # message loudly and let protocol retries recover.
                    if not conn.outgoing and fail_count < _POISON_RETRIES:
                        raise  # kill conn; dialer redelivers on reconnect
                    self._dout(
                        0,
                        f"dropping poison message seq={msg.seq} "
                        f"({type(msg).__name__}) after "
                        f"{fail_count} failed dispatch(es)",
                    )
                with sess.lock:
                    if conn._closed or sock is not conn.sock:
                        # the socket died mid-dispatch: leave in_seq
                        # unadvanced so the replacement incarnation's
                        # replay re-delivers (at-least-once, see above)
                        return
                    conn.in_seq = msg.seq
                    if conn.policy == POLICY_LOSSLESS_PEER:
                        conn._send_ack(msg.seq)
        except OSError:
            pass
        except Exception as e:
            # decode failure / dispatcher exception: connection-fatal, like
            # ProtocolV2 treating an undecodable frame as protocol error
            self._dout(0, f"reader failed on {conn.peer_addr}: {e!r}")
        # reader died: an incoming lossless conn's peer will reconnect (new
        # socket, same session); an outgoing lossless conn repairs the
        # session NOW if unacked frames remain — frames written to a socket
        # that died in flight would otherwise only be replayed when the
        # *next* send fails, which may never come.  Only lossy resets
        # surface to the dispatcher.
        if conn._closed or sock is not conn.sock:
            return
        if conn.policy == POLICY_LOSSLESS_PEER:
            if not conn.outgoing:
                conn.mark_down()
                return
            with conn._lock:
                if conn._closed or sock is not conn.sock or not conn._replay:
                    return
                try:
                    conn._reconnect_and_replay()
                except ConnectionError:
                    if not self._stopped:
                        for d in self.dispatchers:
                            d.ms_handle_reset(conn)
            return
        was_open = not conn._closed
        conn.mark_down()
        if was_open and not self._stopped:
            for d in self.dispatchers:
                d.ms_handle_reset(conn)

    @staticmethod
    def _read_exact(sock: socket.socket, n: int) -> bytes:
        buf = b""
        while len(buf) < n:
            chunk = sock.recv(n - len(buf))
            if not chunk:
                raise OSError("connection closed")
            buf += chunk
        return buf

    def _dispatch(self, conn: Connection, msg: Message) -> None:
        for d in self.dispatchers:
            if d.ms_dispatch(conn, msg):
                return

    def get_connection(self, peer_name: str) -> Connection | None:
        """Latest live incoming connection from a named peer (reference:
        Messenger tracks connections per entity)."""
        with self._lock:
            conn = self._conns_by_name.get(peer_name)
            return conn if conn is not None and conn.is_connected else None

    def _forget(self, conn: Connection) -> None:
        with self._lock:
            if self._conns.get(conn.peer_addr) is conn:
                del self._conns[conn.peer_addr]
            if self._conns_by_name.get(conn.peer_name) is conn:
                del self._conns_by_name[conn.peer_name]
