"""Message base class + type registry (reference: src/msg/Message.h ::
Message with ceph_msg_header/footer; concrete types in src/messages/*.h).

A Message is a typed struct that knows how to encode/decode its payload
through BufferList.  Subclasses register a numeric type code — subsystem
modules (osd, mon) register their own types exactly as src/messages/ does
via the decode_message switch.  Type codes follow the reference's
CEPH_MSG_*/MSG_* numbering where one exists.
"""
from __future__ import annotations

from ..common.buffer import BufferList, BufferListIterator

_REGISTRY: dict[int, type["Message"]] = {}


def register_message(cls: type["Message"]) -> type["Message"]:
    """Class decorator: add to the decode switch (reference:
    decode_message() in src/msg/Message.cc)."""
    code = cls.MSG_TYPE
    if code in _REGISTRY and _REGISTRY[code] is not cls:
        raise ValueError(
            f"message type {code} already registered to {_REGISTRY[code].__name__}"
        )
    _REGISTRY[code] = cls
    return cls


class Message:
    MSG_TYPE = 0

    def __init__(self):
        self.seq = 0  # per-connection sequence, stamped at send
        self.src = ""  # sender entity name, stamped at send

    # subclasses override these two
    def encode_payload(self, bl: BufferList) -> None:
        pass

    def decode_payload(self, it: BufferListIterator) -> None:
        pass

    def get_type(self) -> int:
        return self.MSG_TYPE

    def __repr__(self):
        return f"<{type(self).__name__} seq={self.seq} src={self.src!r}>"


def encode_message(msg: Message) -> bytes:
    bl = BufferList()
    bl.append_u16(msg.MSG_TYPE)
    bl.append_u64(msg.seq)
    bl.append_str(msg.src)
    msg.encode_payload(bl)
    return bytes(bl)


def decode_message(payload: bytes) -> Message:
    it = BufferListIterator(payload)
    code = it.get_u16()
    cls = _REGISTRY.get(code)
    if cls is None:
        raise ValueError(f"unknown message type {code}")
    msg = cls.__new__(cls)
    Message.__init__(msg)
    msg.seq = it.get_u64()
    msg.src = it.get_str()
    msg.decode_payload(it)
    return msg


@register_message
class MPing(Message):
    """reference: src/messages/MPing.h — liveness probe."""

    MSG_TYPE = 2  # CEPH_MSG_PING

    def __init__(self, note: str = ""):
        super().__init__()
        self.note = note

    def encode_payload(self, bl: BufferList) -> None:
        bl.append_str(self.note)

    def decode_payload(self, it: BufferListIterator) -> None:
        self.note = it.get_str()
