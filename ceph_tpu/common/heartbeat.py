"""HeartbeatMap — internal thread-liveness watchdog (reference:
src/common/HeartbeatMap.{h,cc}; SURVEY.md §5.2).

Worker threads reset their handle's timeout before each unit of work; a
checker (the daemon tick) calls is_healthy().  A thread past its grace makes
the map unhealthy; past its suicide grace the process aborts — the
reference's deadlock→fail-fast policy.
"""
from __future__ import annotations

import os
import time
from dataclasses import dataclass, field
from .lockdep import LockdepLock, make_lock


@dataclass
class Handle:
    name: str
    grace: float
    suicide_grace: float
    timeout: float = 0.0  # absolute deadline; 0 = idle
    suicide_timeout: float = 0.0

    def reset_timeout(self, now: float | None = None) -> None:
        """Arm before a unit of work (reference: HeartbeatMap::reset_timeout)."""
        now = time.monotonic() if now is None else now
        self.timeout = now + self.grace
        self.suicide_timeout = now + self.suicide_grace if self.suicide_grace else 0.0

    def clear_timeout(self) -> None:
        self.timeout = 0.0
        self.suicide_timeout = 0.0


class SuicideTimeout(SystemExit):
    pass


@dataclass
class HeartbeatMap:
    _workers: list[Handle] = field(default_factory=list)
    _lock: LockdepLock = field(
        default_factory=lambda: make_lock("heartbeat::map"))
    # test seam: by default a suicide raises; daemons may install os.abort
    on_suicide: object = None

    def add_worker(self, name: str, grace: float, suicide_grace: float = 0.0) -> Handle:
        h = Handle(name, grace, suicide_grace)
        with self._lock:
            self._workers.append(h)
        return h

    def remove_worker(self, h: Handle) -> None:
        with self._lock:
            self._workers.remove(h)

    def is_healthy(self, now: float | None = None) -> bool:
        """Scan all workers (reference: HeartbeatMap::is_healthy)."""
        now = time.monotonic() if now is None else now
        healthy = True
        with self._lock:
            workers = list(self._workers)
        for h in workers:
            if h.suicide_timeout and now > h.suicide_timeout:
                if callable(self.on_suicide):
                    self.on_suicide(h)  # type: ignore[operator]
                raise SuicideTimeout(
                    f"heartbeat_map worker {h.name!r} (pid {os.getpid()}) "
                    f"had suicide timeout after {h.suicide_grace}s"
                )
            if h.timeout and now > h.timeout:
                healthy = False
        return healthy
