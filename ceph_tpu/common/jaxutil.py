"""Small JAX config helpers shared across subsystems."""
from __future__ import annotations


def x64_ctx(enabled: bool):
    """Thread-scoped x64 on/off context.  One definition for both sides of
    the CRUSH/Pallas boundary: the mapper traces straw2 under x64 (64-bit
    fixed-point draws), while Pallas kernels must trace with x64 OFF so
    Python literals in BlockSpec index_maps and kernel bodies stay i32 —
    ambient i64 constants fail Mosaic legalization on real TPUs
    (``func.return (i32, i64)``).

    jax.experimental.enable_x64 was removed in jax 0.9; the config State
    object is the surviving spelling, with the experimental fallback for
    older jax.
    """
    try:
        from jax._src.config import enable_x64 as _e

        return _e(enabled)
    except ImportError:  # older jax
        from jax.experimental import enable_x64 as _e

        return _e(enabled)
