"""ceph_tpu.common — runtime foundation (reference: src/common, src/include,
src/log; SURVEY.md §2.7).

The compute path (gf/ops/ec/crush) is JAX; this package is the host runtime
around it: context + layered config, perf counters, subsystem logging with an
in-memory ring, bufferlist, throttles, admin socket, thread-liveness
watchdog, and in-flight op tracking.  crc32c rides the native library
(native/crc32c.cc) with a pure-Python fallback.
"""
from .buffer import BufferList
from .config import Config, Option, OptionTable
from .context import CephContext
from .crc32c import crc32c
from .perf_counters import PerfCounters, PerfCountersBuilder, PerfCountersCollection
from .throttle import Throttle

__all__ = [
    "BufferList",
    "CephContext",
    "Config",
    "Option",
    "OptionTable",
    "PerfCounters",
    "PerfCountersBuilder",
    "PerfCountersCollection",
    "Throttle",
    "crc32c",
]
