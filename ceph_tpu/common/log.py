"""Subsystem logging with an always-on in-memory ring (reference:
src/common/dout.h, src/log/Log.cc, subsystem table src/common/subsys.h;
SURVEY.md §5.5).

Every entry is recorded in the ring regardless of level (the reference
gathers up to each subsystem's "gather" level and dumps the ring on crash);
stderr emission is gated by the per-subsystem `debug_<subsys>` config
option, runtime-updatable through an observer.
"""
from __future__ import annotations

import sys
import time
import traceback
from collections import deque
from dataclasses import dataclass

from .lockdep import make_lock


@dataclass(frozen=True)
class Entry:
    stamp: float
    subsys: str
    level: int
    message: str

    def format(self) -> str:
        ts = time.strftime("%Y-%m-%dT%H:%M:%S", time.localtime(self.stamp))
        frac = int((self.stamp % 1) * 1000)
        return f"{ts}.{frac:03d} {self.level:2d} {self.subsys}: {self.message}"


class Log:
    """Per-process log sink (reference: ceph::logging::Log)."""

    def __init__(self, config=None, ring_size: int = 10000):
        self._config = config
        self._ring: deque[Entry] = deque(maxlen=ring_size)
        self._lock = make_lock("log::ring")
        self._stderr = bool(config and config.get("log_to_stderr"))
        if config is not None:
            names = [
                n for n in config.table.names()
                if n.startswith("debug_")
                or n in ("log_to_stderr", "log_ring_size")
            ]
            config.add_observer(names, self._on_conf_change)

    def _on_conf_change(self, name: str, value) -> None:
        if name == "log_to_stderr":
            self._stderr = bool(value)
        elif name == "log_ring_size":
            with self._lock:
                self._ring = deque(self._ring, maxlen=int(value))

    def level_for(self, subsys: str) -> int:
        if self._config is None:
            return 5
        name = f"debug_{subsys}"
        if name in self._config.table:
            return self._config.get(name)
        return self._config.get("debug_default")

    def dout(self, subsys: str, level: int, message: str) -> None:
        """Submit one entry (reference: the dout(level) << ... macro)."""
        e = Entry(time.time(), subsys, level, message)
        with self._lock:
            self._ring.append(e)
        if self._stderr and level <= self.level_for(subsys):
            print(e.format(), file=sys.stderr)

    def recent(self, n: int | None = None) -> list[Entry]:
        with self._lock:
            entries = list(self._ring)
        return entries if n is None else entries[-n:]

    def dump_recent(self, file=None) -> None:
        """Flush the ring (reference: Log::dump_recent, wired to the crash
        handler so the last N entries survive an abort)."""
        file = file or sys.stderr
        print("--- begin dump of recent log events ---", file=file)
        for e in self.recent():
            print(e.format(), file=file)
        print("--- end dump of recent log events ---", file=file)

    def dump_on_exception(self, exc: BaseException, file=None) -> None:
        file = file or sys.stderr
        traceback.print_exception(exc, file=file)
        self.dump_recent(file=file)
