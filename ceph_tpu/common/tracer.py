"""cephtrace — tracepoints, causal distributed spans, and device
profiling (reference: src/tracing/*.tp LTTng tracepoints,
src/common/tracer.{h,cc} Jaeger spans; SURVEY.md §5.1).

Three layers, all gated on ONE attribute check when disabled:

- **Tracepoints**: ``tracepoint(subsys, event, **fields)`` appends a
  timestamped record to a bounded in-memory ring (the LTTng-userspace
  role); ``span(subsys, name)`` brackets a region and records its
  duration.  Every record carries an ``entity`` label (daemon name) so
  a multi-daemon process (LocalCluster) stays attributable.  Dump via
  ``events()`` / the per-daemon ``dump_tracing`` admin-socket command.

- **Causal spans** (the cephtrace core): a :class:`TraceCtx`
  (trace_id, span_id) is born at ``Objecter.op_submit`` when the
  head-based ``trace_sampling_rate`` coin flip says so, rides wire
  messages as explicit ``trace_id`` / ``parent_span`` FIELDS (named so
  ``send_message``'s framing stamp of ``seq``/``src`` can never shadow
  them — the CL6 ``field-shadow`` trap), and every stage along
  client -> OSD dispatch -> write-batcher admission/queue/flush ->
  encode -> sub-op fan-out -> replica commit -> ack records a
  :class:`Span` into a bounded per-process buffer.  ``assemble_trees``
  rebuilds the causal tree; ``perfetto_export`` emits Chrome-trace /
  Perfetto JSON that loads directly in ui.perfetto.dev.

  **Tail sampling** (cephmeter, ``trace_tail_latency_ms``): an op that
  LOSES the coin flip can still mint a *provisional* context
  (``sampled_ctx(rate, tail=True)``) — its spans buffer aside until
  the op completes, then ``promote``/``discard`` renders the verdict
  (primary: complaint-time/threshold crossing; client: its own e2e;
  promote wins).  A p99 straggler keeps its connected cross-entity
  tree even at ``trace_sampling_rate = 0``
  (docs/observability.md).

- **Device profiling**: ``device_trace(logdir)`` wraps
  ``jax.profiler``'s trace context so TPU hot paths emit XPlanes, and
  ``kernel_annotation(name, trace_ids)`` wraps individual kernel
  launches in named ``jax.profiler`` annotations keyed by trace_id so
  the device trace correlates with host spans.

Stage taxonomy (shared verbatim by ``TrackedOp.mark_event`` offsets,
the ``stage_*`` latency histograms, and span names — one clock,
``trace_now`` = ``time.time``):

==============  ======================================================
``admission``   write-batcher admission-throttle wait
``queue``       stripe queued -> flush started (coalescing wait)
``encode``      fused device encode (one flush; fan-in span)
``subop``       sub-op fan-out -> last shard ack collected
``commit``      local object-store transaction
==============  ======================================================
"""
from __future__ import annotations

import os
import random
import threading
import time

from .lockdep import make_lock
from contextlib import contextmanager, nullcontext

_MAX_EVENTS = 10_000
_MAX_SPANS = 20_000
#: tail sampling: at most this many traces buffered provisionally
#: (awaiting their op's completion verdict) at once
_MAX_PROVISIONAL = 1024
#: spans one provisional trace may buffer (a runaway op must not eat
#: the process)
_MAX_PROV_SPANS = 256
#: promoted/discarded verdicts remembered (late spans of a decided
#: trace route by these)
_MAX_DECIDED = 8192

#: the stage names above, in pipeline order (bench/tests iterate this)
OP_STAGES = ("admission", "queue", "encode", "subop", "commit")

#: background-plane stage taxonomy (cephheal): recovery and scrub spans,
#: the OSD's recovery_*/scrub_* latency histograms, and TrackedOp marks
#: share these names verbatim, exactly like OP_STAGES on the client path
BG_STAGES = (
    "recovery_peer",      # MPGQuery round: peer versions + object lists
    "recovery_pull",      # authoritative-log catch-up (MPGPull wait)
    "recovery_rebuild",   # one shard chunk recomputed (gather + decode)
    "recovery_push",      # push round to one peer (delta or backfill)
    "scrub_read",         # shard ScrubMap collection
    "scrub_compare",      # cross-shard digest comparison
    "scrub_repair",       # flagged-shard rebuild + re-push
)

#: cephread's read-side stage twins (span names and the
#: ``stage_read_*`` histograms share these, exactly like OP_STAGES on
#: the write path) — kept separate because the read path has no
#: admission/queue phases
READ_STAGES = (
    "read_gather",        # chunk fan-out wall time (batched or per-op)
    "read_decode",        # degraded reconstruct (ranged window or full)
)

#: every (subsys, event) tracepoint name the package may emit, as
#: "subsys.event" — the cephlint CL12 catalogue: an emitting site
#: outside this set is a typo'd event nothing can alert on, an entry
#: with no site is a promise the ring never keeps
KNOWN_TRACEPOINTS = frozenset({
    "ops.kernel_fallback_latched",   # codec latched Pallas→XLA downgrade
    "ops.kernel_fallback_cleared",   # latch cleared (asok or retune)
    "placement.epoch_diff",          # remap forecast on osdmap advance
    "balancer.pass",                 # one balancer pass (scores + moves)
    "balancer.skipped",              # pass refused (degraded cluster)
    "balancer.commit_failed",        # one upmap commit the mon refused
    "qos.retune",                    # controller applied a new plan
    "qos.reject",                    # OSD rejected a malformed directive
    "qos.apply",                     # OSD applied a directive
    "recovery.error",                # one failed recovery pass
    "msgr.send",                     # traced message framed to a peer
    "msgr.recv",                     # traced message accepted from a peer
})


def trace_now() -> float:
    """THE clock every tracing consumer shares: wall time, so
    dump_historic_ops offsets, span boundaries, and cross-daemon
    ordering all agree (monotonic clocks are per-process and would
    skew multi-process traces)."""
    return time.time()


def _new_id() -> str:
    return f"{random.getrandbits(64):016x}"


class TraceCtx:
    """Propagated trace context: which trace, and which span children
    attach to.  ``span_id`` is None only for a freshly minted root."""

    __slots__ = ("trace_id", "span_id")

    def __init__(self, trace_id: str, span_id: str | None = None):
        self.trace_id = trace_id
        self.span_id = span_id

    def __repr__(self):
        return f"<TraceCtx {self.trace_id}/{self.span_id}>"


class Span:
    __slots__ = ("trace_id", "span_id", "parent", "name", "entity",
                 "t0", "t1", "tags")

    def __init__(self, trace_id: str, parent: str | None, name: str,
                 entity: str, t0: float):
        self.trace_id = trace_id
        self.span_id = _new_id()
        self.parent = parent
        self.name = name
        self.entity = entity
        self.t0 = t0
        self.t1: float | None = None
        self.tags: dict = {}

    def ctx(self) -> TraceCtx:
        return TraceCtx(self.trace_id, self.span_id)

    def to_dict(self) -> dict:
        return {
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent_span": self.parent,
            "name": self.name,
            "entity": self.entity,
            "t0": self.t0,
            "t1": self.t1,
            "dur_ms": None if self.t1 is None else (self.t1 - self.t0) * 1e3,
            **({"tags": self.tags} if self.tags else {}),
        }


# thread-local "current op" trace state: the op thread sets it once in
# _handle_client_op and the layers below (write batcher, encode, sub-op
# fan-out) read it without threading ctx through every signature
_tls = threading.local()


def set_op_trace(state: dict | None) -> None:
    _tls.op = state


def op_trace() -> dict | None:
    return getattr(_tls, "op", None)


class Tracer:
    def __init__(self):
        self.enabled = False
        self._events: list[tuple] = []
        self._spans: list[Span] = []
        # tail sampling (cephmeter): traces whose head coin flip said NO
        # buffer here until their op completes; promotion moves them
        # into _spans retroactively, a discard drops them.  All three
        # structures are insertion-ordered so bounds evict oldest-first.
        self._provisional: dict[str, list[Span]] = {}
        self._promoted: dict[str, bool] = {}
        self._discarded: dict[str, bool] = {}
        self._lock = make_lock("tracer::ring")

    def enable(self, on: bool = True) -> None:
        self.enabled = on

    # -- tracepoints (the LTTng layer) ---------------------------------
    def tracepoint(self, subsys: str, event: str, entity: str = "",
                   **fields) -> None:
        if not self.enabled:
            return
        with self._lock:
            self._events.append(
                (trace_now(), entity, subsys, event, fields))
            if len(self._events) > _MAX_EVENTS:
                del self._events[: _MAX_EVENTS // 10]

    @contextmanager
    def span(self, subsys: str, name: str, entity: str = "", **fields):
        if not self.enabled:
            yield
            return
        t0 = trace_now()
        try:
            yield
        finally:
            self.tracepoint(
                subsys, name, entity=entity,
                dur_ms=(trace_now() - t0) * 1e3, **fields
            )

    def events(self, subsys: str | None = None,
               entity: str | None = None) -> list[dict]:
        with self._lock:
            evs = list(self._events)
        return [
            {"ts": ts, "entity": ent, "subsys": s, "event": e, **f}
            for ts, ent, s, e, f in evs
            if (subsys is None or s == subsys)
            and (entity is None or ent == entity)
        ]

    # -- causal spans (the cephtrace layer) ----------------------------
    def new_trace(self) -> TraceCtx | None:
        """Mint a root context (the Objecter's head-based sampling
        decision happens BEFORE this call)."""
        if not self.enabled:
            return None
        return TraceCtx(_new_id(), None)

    def begin(self, ctx: TraceCtx | None, name: str, entity: str = "",
              t0: float | None = None, **tags) -> Span | None:
        """Open a child span of ``ctx``; returns None (and every later
        call on None is a no-op) when tracing is off or the op is
        unsampled — the one-attribute-check disabled path."""
        if not self.enabled or ctx is None:
            return None
        sp = Span(ctx.trace_id, ctx.span_id, name, entity,
                  trace_now() if t0 is None else t0)
        if tags:
            sp.tags.update(tags)
        return sp

    def end(self, sp: Span | None, t1: float | None = None, **tags) -> None:
        if sp is None:
            return
        sp.t1 = trace_now() if t1 is None else t1
        if tags:
            sp.tags.update(tags)
        with self._lock:
            buf = self._provisional.get(sp.trace_id)
            if buf is not None:
                # tail-sampling hold: the op's completion verdict
                # (promote/discard) decides this span's fate
                if len(buf) < _MAX_PROV_SPANS:
                    buf.append(sp)
                return
            if sp.trace_id in self._discarded:
                return  # the op completed fast; its late spans drop too
            self._spans.append(sp)
            if len(self._spans) > _MAX_SPANS:
                del self._spans[: _MAX_SPANS // 10]

    # -- tail sampling (retroactive promotion) -------------------------
    def mark_provisional(self, trace_id: str | None) -> None:
        """Register a trace whose head coin flip said no: its spans
        buffer until promote()/discard() renders the verdict.  Bounded —
        the oldest undecided trace is discarded on overflow."""
        if trace_id is None:
            return
        with self._lock:
            if (trace_id in self._provisional
                    or trace_id in self._promoted
                    or trace_id in self._discarded):
                return
            while len(self._provisional) >= _MAX_PROVISIONAL:
                old = next(iter(self._provisional))
                del self._provisional[old]
                self._note_decided_locked(self._discarded, old)
            self._provisional[trace_id] = []

    def is_provisional(self, trace_id: str | None) -> bool:
        if trace_id is None:
            return False
        with self._lock:
            return trace_id in self._provisional

    def _note_decided_locked(self, table: dict, trace_id: str) -> None:
        table[trace_id] = True
        while len(table) > _MAX_DECIDED:
            del table[next(iter(table))]

    def promote(self, trace_id: str | None, reason: str = "") -> bool:
        """Retroactively keep a provisionally buffered trace: its spans
        move into the real buffer and every LATER span of the trace
        records normally.  Idempotent; safe (and a no-op beyond the
        verdict note) on a head-sampled trace.  Returns True when
        buffered spans were actually promoted."""
        if trace_id is None:
            return False
        with self._lock:
            buf = self._provisional.pop(trace_id, None)
            self._discarded.pop(trace_id, None)
            self._note_decided_locked(self._promoted, trace_id)
            if not buf:
                return False
            if reason:
                for sp in buf:
                    sp.tags.setdefault("tail_promoted", reason)
            self._spans.extend(buf)
            if len(self._spans) > _MAX_SPANS:
                del self._spans[: _MAX_SPANS // 10]
            return True

    def discard(self, trace_id: str | None) -> bool:
        """Drop a provisionally buffered trace (the op completed fast).
        A trace ANY participant already promoted stays promoted — the
        primary's complaint-time verdict wins over the client's."""
        if trace_id is None:
            return False
        with self._lock:
            if trace_id in self._promoted:
                return False
            self._provisional.pop(trace_id, None)
            self._note_decided_locked(self._discarded, trace_id)
            return True

    def record(self, ctx: TraceCtx | None, name: str, entity: str = "",
               t0: float | None = None, t1: float | None = None,
               **tags) -> None:
        """One-shot span with explicit boundaries."""
        sp = self.begin(ctx, name, entity, t0=t0, **tags)
        if sp is not None:
            self.end(sp, t1=t1)

    def spans(self, trace_id: str | None = None,
              entity: str | None = None) -> list[dict]:
        with self._lock:
            sps = list(self._spans)
        return [
            s.to_dict() for s in sps
            if (trace_id is None or s.trace_id == trace_id)
            and (entity is None or s.entity == entity)
        ]

    def clear(self) -> None:
        with self._lock:
            self._events.clear()
            self._spans.clear()
            self._provisional.clear()
            self._promoted.clear()
            self._discarded.clear()


TRACER = Tracer()
tracepoint = TRACER.tracepoint
span = TRACER.span


def sampled_ctx(rate: float, tail: bool = False) -> TraceCtx | None:
    """Head-based sampling: one coin flip per logical op, at the
    Objecter (reference: Jaeger's probabilistic sampler).  rate >= 1
    always samples; rate <= 0 never does.

    ``tail=True`` (cephmeter tail sampling, armed by
    ``trace_tail_latency_ms``) turns a losing coin flip into a
    PROVISIONAL context instead of None: every stage still records, but
    the spans buffer aside until the op's completion latency renders
    the promote/discard verdict — a p99 straggler keeps its trace even
    at ``trace_sampling_rate=0``."""
    if not TRACER.enabled:
        return None
    if rate >= 1.0 or (rate > 0.0 and random.random() < rate):
        return TRACER.new_trace()
    if not tail:
        return None
    ctx = TraceCtx(_new_id(), None)
    TRACER.mark_provisional(ctx.trace_id)
    return ctx


# -- trace assembly / export ------------------------------------------

def assemble_trees(spans: list[dict]) -> dict[str, list[dict]]:
    """{trace_id: [root trees]}; tree node = {"span": span_dict,
    "children": [nodes]}.  A span whose parent isn't in its trace's
    span set roots its own subtree (e.g. a dropped buffer segment)."""
    by_trace: dict[str, list[dict]] = {}
    for s in spans:
        by_trace.setdefault(s["trace_id"], []).append(s)
    out: dict[str, list[dict]] = {}
    for tid, sps in by_trace.items():
        nodes = {s["span_id"]: {"span": s, "children": []} for s in sps}
        roots = []
        for s in sps:
            parent = s.get("parent_span")
            if parent is not None and parent in nodes:
                nodes[parent]["children"].append(nodes[s["span_id"]])
            else:
                roots.append(nodes[s["span_id"]])
        out[tid] = roots
    return out


def tree_span_names(node: dict) -> set[str]:
    """All span names reachable from a tree node (connectivity checks)."""
    names = {node["span"]["name"]}
    for child in node["children"]:
        names |= tree_span_names(child)
    return names


def connected_traces(spans: list[dict], root: str = "op_submit",
                     leaf: str = "replica_commit") -> list[str]:
    """trace_ids whose tree reaches `leaf` under a `root` root — the
    ci-gate's "client submit is an ancestor of the replica commit"
    assertion."""
    out = []
    for tid, roots in assemble_trees(spans).items():
        for node in roots:
            if node["span"]["name"] == root and leaf in tree_span_names(node):
                out.append(tid)
                break
    return out


def perfetto_export(spans: list[dict]) -> dict:
    """Chrome-trace/Perfetto JSON: one X (complete) event per span,
    one pid per entity (process_name metadata), one tid per trace so a
    trace's spans nest in one track.  Opens directly in
    ui.perfetto.dev / chrome://tracing."""
    pids: dict[str, int] = {}
    tids: dict[str, int] = {}
    events: list[dict] = []
    for s in spans:
        ent = s.get("entity") or "?"
        if ent not in pids:
            pids[ent] = len(pids) + 1
            events.append({
                "ph": "M", "pid": pids[ent], "name": "process_name",
                "args": {"name": ent},
            })
        tid = tids.setdefault(s["trace_id"], len(tids) + 1)
        if s.get("t1") is None:
            continue  # unfinished span: nothing to draw
        events.append({
            "name": s["name"],
            "cat": "cephtrace",
            "ph": "X",
            "ts": s["t0"] * 1e6,          # microseconds, per the format
            "dur": max(0.0, (s["t1"] - s["t0"]) * 1e6),
            "pid": pids[ent],
            "tid": tid,
            "args": {
                "trace_id": s["trace_id"],
                "span_id": s["span_id"],
                "parent_span": s.get("parent_span"),
                **(s.get("tags") or {}),
            },
        })
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def dump_tracing(entity: str | None = None, fmt: str = "spans") -> object:
    """The `dump_tracing` admin-socket surface: this daemon's spans and
    tracepoint events (entity=None dumps the whole process — useful in
    a LocalCluster where every daemon shares the buffer).  fmt:
    "spans" (default), "perfetto" (Chrome-trace JSON of ALL traces this
    entity touched, with the other daemons' halves included so the
    trees stay connected)."""
    spans = TRACER.spans(entity=entity)
    if fmt == "perfetto":
        if entity is not None:
            touched = {s["trace_id"] for s in spans}
            spans = [s for s in TRACER.spans() if s["trace_id"] in touched]
        return perfetto_export(spans)
    return {
        "entity": entity,
        "enabled": TRACER.enabled,
        "num_spans": len(spans),
        "spans": spans,
        "events": TRACER.events(entity=entity),
    }


# -- device profiling --------------------------------------------------

@contextmanager
def device_trace(logdir: str | None = None):
    """jax.profiler trace context; logdir defaults to $CEPH_TPU_PROFILE.
    A no-op when neither is set, so call sites can wrap hot regions
    unconditionally."""
    logdir = logdir or os.environ.get("CEPH_TPU_PROFILE")
    if not logdir:
        yield
        return
    import jax

    with jax.profiler.trace(logdir):
        yield


def kernel_annotation(name: str, trace_ids=()):
    """Named jax.profiler annotation around a kernel launch, keyed by
    trace_id, so the device trace's XPlanes (TensorBoard/Perfetto)
    correlate with host spans.  Null when tracing is off — kernel
    dispatch stays annotation-free on the hot path."""
    if not TRACER.enabled:
        return nullcontext()
    try:
        import jax
    except ImportError:  # pragma: no cover - jax is baked into the image
        return nullcontext()
    ids = list(trace_ids)
    label = f"cephtrace:{name}"
    if ids:
        label += f"#trace={ids[0]}" + (f"+{len(ids) - 1}" if len(ids) > 1
                                       else "")
    return jax.profiler.TraceAnnotation(label)
