"""Tracepoints + device profiling (reference: src/tracing/*.tp LTTng
tracepoints and src/common/tracer.{h,cc} Jaeger spans; SURVEY.md §5.1).

Two layers, both cheap enough to leave compiled in:

- **Tracepoints**: `tracepoint(subsys, event, **fields)` appends a
  timestamped record to a bounded in-memory ring (the LTTng-userspace
  role); `span(subsys, name)` brackets a region and records its
  duration.  Dump via `events()` — the admin-socket/`dump_historic_ops`
  style surface.  Disabled (the default) they cost one attribute check.
- **Device profiling**: `device_trace(logdir)` wraps `jax.profiler`'s
  trace context so the TPU hot paths (encode kernels, batched CRUSH)
  emit an XPlane trace viewable in TensorBoard/Perfetto — the
  `jax.profiler` equivalent SURVEY §5.1 calls for.  Set
  CEPH_TPU_PROFILE=<dir> to arm it in the bench CLIs.
"""
from __future__ import annotations

import os
import threading
import time

from .lockdep import make_lock
from contextlib import contextmanager

_MAX_EVENTS = 10_000


class Tracer:
    def __init__(self):
        self.enabled = False
        self._events: list[tuple] = []
        self._lock = make_lock("tracer::ring")

    def enable(self, on: bool = True) -> None:
        self.enabled = on

    def tracepoint(self, subsys: str, event: str, **fields) -> None:
        if not self.enabled:
            return
        with self._lock:
            self._events.append((time.monotonic(), subsys, event, fields))
            if len(self._events) > _MAX_EVENTS:
                del self._events[: _MAX_EVENTS // 10]

    @contextmanager
    def span(self, subsys: str, name: str, **fields):
        if not self.enabled:
            yield
            return
        t0 = time.monotonic()
        try:
            yield
        finally:
            self.tracepoint(
                subsys, name, dur_ms=(time.monotonic() - t0) * 1e3, **fields
            )

    def events(self, subsys: str | None = None) -> list[dict]:
        with self._lock:
            evs = list(self._events)
        return [
            {"ts": ts, "subsys": s, "event": e, **f}
            for ts, s, e, f in evs
            if subsys is None or s == subsys
        ]

    def clear(self) -> None:
        with self._lock:
            self._events.clear()


TRACER = Tracer()
tracepoint = TRACER.tracepoint
span = TRACER.span


@contextmanager
def device_trace(logdir: str | None = None):
    """jax.profiler trace context; logdir defaults to $CEPH_TPU_PROFILE.
    A no-op when neither is set, so call sites can wrap hot regions
    unconditionally."""
    logdir = logdir or os.environ.get("CEPH_TPU_PROFILE")
    if not logdir:
        yield
        return
    import jax

    with jax.profiler.trace(logdir):
        yield
