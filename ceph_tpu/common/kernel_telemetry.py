"""cephdev — per-kernel telemetry registry + TPU backend health sentinel
(reference: the mon `DEVICE_HEALTH*`/`SLOW_OPS` device-health scraping of
src/mgr/DaemonHealthMetricCollector.cc + mgr/devicehealth, applied to the
accelerator under the data plane; arXiv:1709.05365's finding that a
degraded device path changes the whole write path's queueing behavior —
so degradation must be a first-class, alertable cluster state, not a
bench footnote).

Two layers, both process-wide (kernel dispatch is per-process, like the
`ec_kernel` override and the cephtrace TRACER):

- **KernelTelemetry** (``TELEMETRY``): one record per kernel entry point
  (``gf_apply``, ``gf_xor``, ``stream_encode``, ``ec_batch_flush``,
  ``crush_do_rule_batch``) — invocation counts, compile-vs-execute wall
  time as log2 histograms (the PR-9 ``TYPE_HISTOGRAM``), bytes in/out,
  achieved GiB/s where the call is a true sync point, and the backend
  that served each call.  Storage IS a shared
  :class:`~ceph_tpu.common.perf_counters.PerfCounters` ("kernel"), so the
  numbers flow through the existing ``perf dump`` -> MMgrReport ->
  prometheus exporter pipeline (HELP text from the PR-9 schema path)
  with zero new wire plumbing.  Fallback latches (the codec's one-shot
  Pallas->XLA downgrade) are recorded with reason + timestamp and feed
  the ``KERNEL_FALLBACK_LATCHED`` health check.  Disabled, every
  instrumented dispatch pays ONE attribute check (measured in PERF.md).

- **BackendSentinel** (``SENTINEL``): a probe thread (constructor-
  injected :class:`SentinelPolicy`, per the ROADMAP's topology-injection
  direction) that checks backend liveness on a FAST timeout — the probe
  runs on a disposable worker thread so a wedged backend hangs the
  worker, never the sentinel or any caller — and latches a
  cluster-visible ``degraded`` state instead of wedging callers.  The
  kernel dispatch policy (``ops.bitplane._want_pallas``) consults the
  latch, so a sick backend downgrades the data path instead of feeding
  it.  The state clears itself when a later probe answers.  Surfaced as
  the mon ``TPU_BACKEND_DEGRADED`` health check (OSD ``_mgr_report`` ->
  mgr status digest -> mon ``_status``), the ``dump_kernel_telemetry``
  admin command, and ``bench.py``'s wedge reporting.

CI / tests force states without hardware: the ``CEPH_TPU_SENTINEL_STATE``
env var (``degraded[:reason]`` / ``ok``) short-circuits the default
probe, and the ``tpu.backend.probe`` failpoint (``error`` arm) fails it
through the registry.  See docs/observability.md.
"""
from __future__ import annotations

import os
import sys
import threading
import time

from .failpoint import failpoint
from .lockdep import make_lock
from .perf_counters import PerfCounters

#: bounded latch/sentinel event log (rare transitions; 256 is weeks)
_MAX_EVENTS = 256


class _KernelStats:
    """Rich per-kernel record behind the PerfCounters mirror (backends
    per call, last-call provenance, achieved GiB/s, host-copy volume)."""

    __slots__ = ("calls", "bytes_in", "bytes_out", "exec_seconds",
                 "compiles", "backends", "last_backend", "last_ts",
                 "last_gibps", "host_copy_bytes", "sync_points")

    def __init__(self):
        self.calls = 0
        self.bytes_in = 0
        self.bytes_out = 0
        self.exec_seconds = 0.0
        self.compiles = 0
        self.backends: dict[str, int] = {}
        self.last_backend: str | None = None
        self.last_ts: float | None = None
        self.last_gibps: float | None = None
        # cephdma: bytes this kernel's dispatch seam copied through host
        # memory (staging packs, host->device commits, device->host
        # materializations) and how many of its calls were sync points
        # (blocked on a device round trip) — the pair the device-pool
        # control-vs-pool audit compares (docs/write_path.md)
        self.host_copy_bytes = 0
        self.sync_points = 0

    def to_dict(self) -> dict:
        return {
            "calls": self.calls,
            "bytes_in": self.bytes_in,
            "bytes_out": self.bytes_out,
            "exec_seconds": self.exec_seconds,
            "compiles": self.compiles,
            "backends": dict(self.backends),
            "last_backend": self.last_backend,
            "last_ts": self.last_ts,
            "last_gibps": self.last_gibps,
            "host_copy_bytes": self.host_copy_bytes,
            "sync_points": self.sync_points,
        }


class KernelTelemetry:
    """Process-wide per-kernel dispatch telemetry (see module docstring).

    The hot-path contract: every instrumented seam does

        if TELEMETRY.enabled:
            ...time + record...

    so disabled telemetry costs one attribute check per dispatch.
    """

    def __init__(self):
        self.enabled = True
        self._lock = make_lock("telemetry::kernels")
        #: shared PerfCounters: daemons add this one object to their
        #: cct.perf so kernel series ride the existing report pipeline
        self.perf = PerfCounters("kernel")
        self._kernels: dict[str, _KernelStats] = {}
        self._declared: set[str] = set()
        self._compile_keys: set[tuple] = set()
        #: kernel -> active fallback latch record (reason, ts, from, to)
        self._fallbacks: dict[str, dict] = {}
        self._events: list[dict] = []

    def enable(self, on: bool = True) -> None:
        self.enabled = on

    # -- recording ---------------------------------------------------------
    def _declare_locked(self, kernel: str) -> _KernelStats:
        ks = self._kernels.get(kernel)
        if ks is None:
            ks = self._kernels[kernel] = _KernelStats()
        if kernel not in self._declared:
            self._declared.add(kernel)
            self.perf._add(f"{kernel}_calls", "u64",
                           f"{kernel} kernel invocations")
            self.perf._add(f"{kernel}_bytes_in", "u64",
                           f"{kernel} input bytes dispatched")
            self.perf._add(f"{kernel}_bytes_out", "u64",
                           f"{kernel} output bytes produced")
            self.perf._add(f"{kernel}_compile", "histogram",
                           f"{kernel} first-shape (compile) wall time")
            self.perf._add(f"{kernel}_execute", "histogram",
                           f"{kernel} steady-state dispatch wall time")
            self.perf._add(f"{kernel}_gibps", "gauge",
                           f"{kernel} last achieved GiB/s (sync calls)")
            self.perf._add(f"{kernel}_host_copy_bytes", "u64",
                           f"{kernel} bytes copied through host memory "
                           f"(staging packs + host<->device transfers "
                           f"this seam performed)")
            self.perf._add(f"{kernel}_sync_points", "u64",
                           f"{kernel} calls that blocked on a device "
                           f"round trip (the deliberate sync points)")
        return ks

    def first_call(self, key: tuple) -> bool:
        """True the first time `key` (kernel + shapes + backend) is seen —
        the compile-vs-execute histogram discriminator (jit recompiles
        per shape, so a fresh shape's wall time includes the compile)."""
        with self._lock:
            if key in self._compile_keys:
                return False
            self._compile_keys.add(key)
            return True

    def record(self, kernel: str, backend: str, seconds: float,
               bytes_in: int = 0, bytes_out: int = 0,
               compiled: bool = False, synced: bool = False,
               host_copy_bytes: int = 0) -> None:
        """One kernel dispatch.  `synced` marks calls whose wall time
        covers a device round-trip (result fetched) — only those yield
        an honest achieved-GiB/s sample; async dispatches record wall
        time only (JAX queues the launch and returns).
        `host_copy_bytes` counts the bytes THIS seam copied through host
        memory during the call (staging packs, host->device commits,
        device->host materializations) — each seam counts only its own
        copies, so summing the counters across kernels stays honest."""
        if not self.enabled:
            return
        now = time.time()
        gibps = None
        if synced and seconds > 0 and bytes_in:
            gibps = bytes_in / seconds / 2**30
        with self._lock:
            ks = self._declare_locked(kernel)
            ks.calls += 1
            ks.bytes_in += int(bytes_in)
            ks.bytes_out += int(bytes_out)
            ks.exec_seconds += seconds
            ks.backends[backend] = ks.backends.get(backend, 0) + 1
            ks.last_backend = backend
            ks.last_ts = now
            if compiled:
                ks.compiles += 1
            if gibps is not None:
                ks.last_gibps = gibps
            ks.host_copy_bytes += int(host_copy_bytes)
            if synced:
                ks.sync_points += 1
        self.perf.inc(f"{kernel}_calls")
        if bytes_in:
            self.perf.inc(f"{kernel}_bytes_in", int(bytes_in))
        if bytes_out:
            self.perf.inc(f"{kernel}_bytes_out", int(bytes_out))
        self.perf.hinc(f"{kernel}_compile" if compiled
                       else f"{kernel}_execute", seconds)
        if gibps is not None:
            self.perf.set(f"{kernel}_gibps", gibps)
        if host_copy_bytes:
            self.perf.inc(f"{kernel}_host_copy_bytes", int(host_copy_bytes))
        if synced:
            self.perf.inc(f"{kernel}_sync_points")

    # -- device-pool mirror (ops/device_pool.py) ---------------------------
    _POOL_COUNTERS = ("hits", "misses", "evictions", "donations")

    def record_pool(self, hits: int = 0, misses: int = 0,
                    evictions: int = 0, donations: int = 0,
                    resident_bytes: int | None = None) -> None:
        """Mirror device-pool stat deltas into the shared PerfCounters so
        `device_pool_*` series ride the same perf dump -> MMgrReport ->
        prometheus pipeline as the kernel records (the pool keeps its own
        authoritative totals; this is the export seam)."""
        if not self.enabled:
            return
        with self._lock:
            if "device_pool_hits" not in self._declared:
                self._declared.add("device_pool_hits")
                for name in self._POOL_COUNTERS:
                    self.perf._add(
                        f"device_pool_{name}", "u64",
                        f"device stripe pool {name} "
                        f"(ops/device_pool.py; docs/write_path.md)")
                self.perf._add(
                    "device_pool_resident_bytes", "gauge",
                    "device stripe pool free-list residency in bytes")
        for name, v in (("hits", hits), ("misses", misses),
                        ("evictions", evictions), ("donations", donations)):
            if v:
                self.perf.inc(f"device_pool_{name}", int(v))
        if resident_bytes is not None:
            self.perf.set("device_pool_resident_bytes", int(resident_bytes))

    # -- fallback latches + event log --------------------------------------
    def record_event(self, kind: str, **fields) -> None:
        """Append one transition event (fallback latch/clear, sentinel
        degrade/recover) to the bounded log; always on — transitions are
        rare and ARE the alertable signal, so they bypass `enabled`."""
        with self._lock:
            self._events.append({"ts": time.time(), "kind": kind, **fields})
            if len(self._events) > _MAX_EVENTS:
                del self._events[: _MAX_EVENTS // 4]

    def record_fallback(self, kernel: str, reason: str,
                        frm: str = "pallas", to: str = "xla") -> None:
        """A kernel latched a fallback backend (the codec's one-shot
        Pallas->XLA downgrade).  Feeds KERNEL_FALLBACK_LATCHED."""
        rec = {"kernel": kernel, "reason": reason, "from": frm, "to": to,
               "ts": time.time()}
        with self._lock:
            self._fallbacks[kernel] = rec
        self.record_event("fallback_latched", **rec)

    def clear_fallback(self, kernel: str | None = None) -> bool:
        """Drop active fallback latches (kernel=None: all).  Returns
        True if anything was latched.  The bitplane module's
        `clear_fallback_latch` composes this with its own un-latch."""
        with self._lock:
            if kernel is None:
                cleared = sorted(self._fallbacks)
                self._fallbacks.clear()
            else:
                cleared = [kernel] if self._fallbacks.pop(kernel, None) \
                    else []
        for k in cleared:
            self.record_event("fallback_cleared", kernel=k)
        return bool(cleared)

    def fallback_latched(self) -> dict:
        """{kernel: latch record} for every active latch ({} = none)."""
        with self._lock:
            return {k: dict(v) for k, v in self._fallbacks.items()}

    def events(self) -> list[dict]:
        with self._lock:
            return [dict(e) for e in self._events]

    # -- introspection -----------------------------------------------------
    def dump(self) -> dict:
        with self._lock:
            kernels = {k: v.to_dict() for k, v in self._kernels.items()}
        return kernels

    def summary(self, kernels=None) -> dict:
        """Compact {kernel: {calls, backends, last_backend, last_gibps}}
        (bench.py attaches this to phase results as silicon provenance)."""
        out = {}
        with self._lock:
            for k, v in self._kernels.items():
                if kernels is not None and k not in kernels:
                    continue
                out[k] = {"calls": v.calls, "backends": dict(v.backends),
                          "last_backend": v.last_backend,
                          "last_gibps": v.last_gibps}
        return out


TELEMETRY = KernelTelemetry()


class BackendDevicePerf:
    """PerfCounters duck type exporting the sentinel's per-device probe
    rows as ``ceph_backend_device_*{device}`` labeled series (cephplace
    satellite — groundwork for the ROADMAP mesh-shrink item: a sick
    chip shows up as its OWN row going unhealthy, not just a process-
    wide degraded flag).  Daemons add the singleton to their cct.perf
    next to TELEMETRY.perf; the rows come live from the sentinel at
    dump time, so there is no write path to race."""

    def __init__(self):
        self.name = "backend"

    def dump(self) -> dict:
        rows = [
            {"labels": {"device": d["device"]},
             "device_ok": int(bool(d.get("ok"))),
             "device_probe_ms": round(float(d.get("latency_ms") or 0.0),
                                      3)}
            for d in SENTINEL.devices()
        ]
        return {
            "per_device": {"__labeled__": True, "rows": rows},
            "devices_seen": len(rows),
        }

    def schema(self) -> dict:
        return {
            "per_device": {
                "type": "labeled",
                "description": "per-accelerator-device probe rows from "
                               "the backend sentinel "
                               "(docs/observability.md)"},
            "device_ok": {
                "type": "gauge",
                "description": "1 = the last sentinel probe reached "
                               "this jax device; 0 = it failed or the "
                               "backend probe as a whole is failing"},
            "device_probe_ms": {
                "type": "gauge",
                "description": "last per-device probe round-trip "
                               "latency (device_put + block) in ms"},
            "devices_seen": {
                "type": "gauge",
                "description": "devices the sentinel has probed"},
        }


DEVICE_PERF = BackendDevicePerf()


# -- backend health sentinel -----------------------------------------------

def default_probe() -> str:
    """Backend liveness probe: returns the platform string or raises.

    Runs on a DISPOSABLE worker thread (a wedged backend hangs the
    worker, not the sentinel).  Overridable without hardware:

    - failpoint ``tpu.backend.probe`` (``error`` arm) fails it through
      the registry;
    - ``CEPH_TPU_SENTINEL_STATE=degraded[:reason]`` fails it,
      ``=ok`` passes it — both WITHOUT touching jax (the CI simulated
      wedge; bench.py's watchdog probe honors the same variable).
    """
    failpoint("tpu.backend.probe")
    forced = os.environ.get("CEPH_TPU_SENTINEL_STATE", "")
    if forced:
        state, _, reason = forced.partition(":")
        if state == "degraded":
            raise RuntimeError(
                reason or "forced degraded (CEPH_TPU_SENTINEL_STATE)")
        return "forced-ok"
    # platform resolves through the policy seam (cephtopo); the policy's
    # own device-list probe is the ambient touch that a wedged runtime
    # hangs on — which is exactly what this disposable worker is for
    from .device_policy import get_device_policy

    return get_device_policy().platform()


def _forced_device_rows(ok: bool, reason: str | None) -> list[dict]:
    """The ONE synthesized-row shape every forced/pinned sentinel path
    emits (env override + runtime force pin) — exporter consumers see
    the same fields either way."""
    return [{"device": "forced:0", "platform": "forced", "ok": ok,
             "latency_ms": 0.0, "error": None if ok else reason}]


def probe_device_rows() -> list[dict]:
    """Per-device probe rows: one entry per ``jax.devices()`` device
    with verdict + round-trip latency (a tiny device_put forced to
    completion).  Runs INSIDE the sentinel's disposable probe worker —
    a wedged device hangs the worker, never a caller.  The
    ``CEPH_TPU_SENTINEL_STATE`` override synthesizes rows without
    touching jax (the CI simulated wedge)."""
    forced = os.environ.get("CEPH_TPU_SENTINEL_STATE", "")
    if forced:
        state, _, reason = forced.partition(":")
        ok = state != "degraded"
        return _forced_device_rows(ok, reason or (
            "forced degraded (CEPH_TPU_SENTINEL_STATE)"))
    import jax
    import numpy as _np

    rows = []
    # RAW topology on purpose: these per-device rows are the INPUT the
    # DevicePolicy's healthy_devices() shrink consumes — probing through
    # the policy would hide exactly the sick chips it must report
    for d in jax.devices():  # noqa: CL9 — sentinel's own disposable-worker probe feeds the policy
        t0 = time.perf_counter()
        try:
            jax.device_put(_np.zeros(8, _np.uint8), d).block_until_ready()
            ok, err = True, None
        except Exception as e:  # one sick device must not hide the rest
            ok, err = False, f"{type(e).__name__}: {e}"
        rows.append({
            "device": f"{d.platform}:{d.id}",
            "platform": d.platform,
            "ok": ok,
            "latency_ms": (time.perf_counter() - t0) * 1e3,
            "error": err,
        })
    return rows


class SentinelPolicy:
    """Constructor-injected sentinel behavior (probe cadence, the fast
    timeout that bounds a wedged probe, and the probe itself) — the same
    injection shape the ROADMAP asks of device topology, so a test can
    hand the sentinel a canned probe and a laptop and a pod slice run
    the same daemon code."""

    __slots__ = ("interval", "timeout", "probe", "boot_timeout",
                 "device_probe")

    def __init__(self, interval: float = 5.0, timeout: float = 2.0,
                 probe=None, boot_timeout: float | None = None,
                 device_probe=None):
        self.interval = float(interval)
        self.timeout = float(timeout)
        self.probe = probe if probe is not None else default_probe
        # per-device rows ride the same worker; an INJECTED headline
        # probe must stay in control of what the worker touches — with
        # a canned probe and no explicit device_probe, rows are
        # synthesized from the canned verdict instead of reaching jax
        if device_probe is not None:
            self.device_probe = device_probe
        elif probe is None:
            self.device_probe = probe_device_rows
        else:
            self.device_probe = None
        # until the runtime has answered ONCE, the probe budget covers
        # cold init (the first jax.devices() on a real TPU routinely
        # takes >2 s bringing the runtime up) — without this grace every
        # cold boot latches a spurious TPU_BACKEND_DEGRADED blip
        self.boot_timeout = (float(boot_timeout) if boot_timeout is not None
                             else max(15.0, 5.0 * self.timeout))


class BackendSentinel:
    """Latched backend health state + the probe loop (see module
    docstring).  Refcounted start: every OSD acquires it at boot with
    its conf-built policy (first acquirer's policy wins — the backend is
    per-process) and releases at shutdown; the loop stops with the last
    daemon."""

    def __init__(self, policy: SentinelPolicy | None = None):
        self._policy = policy or SentinelPolicy()
        self._lock = make_lock("telemetry::sentinel")
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._refs = 0
        #: hot-path flag (ops.bitplane reads it per dispatch): plain
        #: attribute, flipped only inside _transition under _lock
        self.is_degraded = False
        self._forced: tuple[str, str] | None = None
        self._hung_probe: threading.Thread | None = None
        self._answered = False  # any probe ever returned (ok OR error)
        # the probe worker currently inside a per-device sweep (None =
        # idle); a still-ALIVE previous sweep worker suppresses new
        # sweeps, and its eventual answer still lands (the _hung_probe
        # pattern — a lock held across device round-trips could never
        # recover from a wedged device)
        self._sweep_worker: threading.Thread | None = None
        #: per-device probe rows from the last answering cycle (the
        #: ceph_backend_device_*{device} series + dump payload); the
        #: generation counter bumps on every non-sweep write so a
        #: STRAGGLING sweep worker (wedged device answering cycles
        #: later) cannot resurrect rows a reset/force/failure-mark
        #: already superseded
        self._devices: list[dict] = []
        self._dev_gen = 0
        self._st = {
            "state": "unknown", "reason": None, "since": None,
            "platform": None, "last_probe": None, "probes": 0,
            "transitions": 0,
        }

    # -- lifecycle (refcounted) --------------------------------------------
    def acquire(self, policy: SentinelPolicy | None = None) -> None:
        with self._lock:
            self._refs += 1
            if self._thread is not None:
                return
            if policy is not None:
                self._policy = policy
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._loop, name="backend-sentinel", daemon=True)
            t = self._thread
        t.start()

    def release(self) -> None:
        with self._lock:
            self._refs = max(0, self._refs - 1)
            if self._refs:
                return
            self._stop.set()
            t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=5.0)

    def running(self) -> bool:
        with self._lock:
            return self._thread is not None

    # -- state -------------------------------------------------------------
    def degraded(self) -> bool:
        return self.is_degraded

    def state(self) -> dict:
        with self._lock:
            return dict(self._st)

    def devices(self) -> list[dict]:
        """Per-device probe rows from the last answering cycle.  While
        the whole backend probe is failing/hung, the rows are the last
        known set with every verdict flipped to failed — each device is
        suspect until a probe answers again."""
        with self._lock:
            return [dict(d) for d in self._devices]

    def _mark_devices_failed(self, reason: str) -> None:
        """Flip every known row suspect.  Bumps the generation so any
        in-flight sweep's landing is invalidated (the sweep's OWN
        overrun mark is inlined in _probe_cycle instead — there the
        wedged worker's eventual answer is fresher and must land)."""
        with self._lock:
            self._dev_gen += 1
            for d in self._devices:
                d["ok"] = False
                d["error"] = reason

    def reset_state(self) -> None:
        """Back to pristine `unknown` (clears any force pin): tests and
        one-shot tools that must not leak latched state process-wide."""
        with self._lock:
            self._forced = None
            self._hung_probe = None
            self._sweep_worker = None
            self._answered = False
            self.is_degraded = False
            self._devices = []
            self._dev_gen += 1
            self._st = {
                "state": "unknown", "reason": None, "since": None,
                "platform": None, "last_probe": None, "probes": 0,
                "transitions": 0,
            }

    def force(self, state: str | None, reason: str = "") -> None:
        """Test/operator hook: pin the sentinel state ('degraded'/'ok'),
        applied immediately and held against probes until force(None)."""
        with self._lock:
            self._forced = None if state is None else (state, reason)
        if state is not None:
            self._transition(state == "degraded",
                             reason or f"forced {state}",
                             platform=None)

    # -- probing -----------------------------------------------------------
    def probe_once(self) -> dict:
        """One synchronous probe cycle (the loop body; also bench.py's
        entry).  Returns the resulting state dict."""
        self._probe_cycle()
        return self.state()

    def _loop(self) -> None:
        interval = max(0.05, self._policy.interval)
        while not self._stop.wait(timeout=interval):
            try:
                self._probe_cycle()
            except Exception as e:
                # the sentinel must never die to a probe bug; latch the
                # uncertainty instead
                self._transition(True, f"sentinel probe raised: {e!r}",
                                 platform=None)

    def _probe_cycle(self) -> None:
        with self._lock:
            forced = self._forced
            self._st["probes"] += 1
            self._st["last_probe"] = time.time()
            hung = self._hung_probe
        if forced is not None:
            degraded = forced[0] == "degraded"
            reason = forced[1] or f"forced {forced[0]}"
            with self._lock:
                self._devices = _forced_device_rows(not degraded, reason)
                self._dev_gen += 1
            self._transition(degraded, reason, platform=None)
            return
        if hung is not None and hung.is_alive():
            # the previous probe never answered: the backend is still
            # wedged — do not stack more hung workers
            self._mark_devices_failed("backend probe still hung")
            self._transition(True, "backend probe still hung", None)
            return
        box: dict = {}
        headline_done = threading.Event()
        done = threading.Event()

        def work():
            me = threading.current_thread()
            try:
                box["platform"] = self._policy.probe()
            except BaseException as e:
                box["error"] = f"{type(e).__name__}: {e}"
                headline_done.set()
                done.set()
                return
            headline_done.set()
            # per-device rows ride the same disposable worker AFTER the
            # headline verdict is out: N busy devices queueing behind
            # in-flight work must not eat the headline budget and latch
            # a spurious process-wide degraded.  A still-alive previous
            # sweep suppresses stacking (the _hung_probe pattern — a
            # held lock could never recover from a wedged device; a
            # thread marker clears the moment the device answers).
            with self._lock:
                busy = self._sweep_worker
                if busy is not None and busy.is_alive():
                    done.set()
                    return
                self._sweep_worker = me
                gen0 = self._dev_gen
            try:
                dp = self._policy.device_probe
                rows = dp() if dp is not None else [{
                    "device": f"{box['platform']}:0",
                    "platform": box["platform"], "ok": True,
                    "latency_ms": 0.0, "error": None,
                }]
                # land directly under the lock: a sweep that WEDGED on
                # a device and recovers cycles later must still refresh
                # the rows, even though its own probe cycle long moved
                # on — UNLESS a reset/force/failure-mark superseded the
                # generation it started from (stale rows must stay dead).
                # Landing and clearing the worker marker are ONE lock
                # block so the overrun path can never observe
                # landed-but-not-cleared and flip fresh rows to failed.
                with self._lock:
                    if self._dev_gen == gen0:
                        self._devices = list(rows)
                    self._sweep_worker = None
            except BaseException as e:
                box["devices_error"] = f"{type(e).__name__}: {e}"
            finally:
                with self._lock:
                    if self._sweep_worker is me:
                        self._sweep_worker = None
            done.set()

        t = threading.Thread(target=work, name="backend-probe", daemon=True)
        t.start()
        with self._lock:
            # the fast timeout applies once the runtime has answered at
            # least once; a cold process gets the boot grace instead
            timeout = (self._policy.timeout if self._answered
                       else self._policy.boot_timeout)
        if not headline_done.wait(timeout=timeout):
            with self._lock:
                self._hung_probe = t
            self._mark_devices_failed(
                f"backend probe timed out after {timeout}s")
            self._transition(
                True, f"backend probe timed out after {timeout}s", None)
            return
        with self._lock:
            self._hung_probe = None
            self._answered = True
        if "error" in box:
            self._mark_devices_failed(
                f"backend probe failed: {box['error']}")
            self._transition(True, f"backend probe failed: {box['error']}",
                             None)
        else:
            # the sweep gets its OWN grace equal to the probe budget;
            # on overrun the verdict stays healthy but every row flips
            # suspect (a wedged device must not keep reading ok=1), and
            # the wedged worker's eventual answer still refreshes them —
            # the process-wide latch keys off the headline probe only
            if not done.wait(timeout=timeout):
                # check + mark under ONE acquisition: a worker that
                # landed fresh rows and cleared the marker in between
                # must not have them flipped back to failed.  No gen
                # bump — the wedged worker's eventual answer is fresher
                # than this mark and must still land.
                with self._lock:
                    if self._sweep_worker is not None:
                        for d in self._devices:
                            d["ok"] = False
                            d["error"] = "device sweep hung"
            if "devices_error" in box:
                self._mark_devices_failed(
                    f"device sweep failed: {box['devices_error']}")
            self._transition(False, None, box.get("platform"))

    def _transition(self, degraded: bool, reason: str | None,
                    platform: str | None) -> None:
        """Apply a probe outcome; log + event only on EDGES so a wedged
        backend yields one alert, not one per probe."""
        with self._lock:
            was = self._st["state"]
            now_state = "degraded" if degraded else "ok"
            changed = was != now_state
            self._st["state"] = now_state
            self._st["reason"] = reason
            if platform is not None:
                self._st["platform"] = platform
            if changed:
                self._st["since"] = time.time()
                self._st["transitions"] += 1
            self.is_degraded = degraded
        if not changed:
            return
        if degraded:
            print(f"# ceph_tpu: backend sentinel DEGRADED: {reason}",
                  file=sys.stderr)
            TELEMETRY.record_event("sentinel_degraded", reason=reason)
        else:
            if was == "degraded":
                print("# ceph_tpu: backend sentinel recovered",
                      file=sys.stderr)
            TELEMETRY.record_event("sentinel_recovered",
                                   platform=platform)


SENTINEL = BackendSentinel()


def backend_health() -> dict:
    """The per-daemon health blob OSDs ship inside MMgrReport stats —
    the mgr status digest aggregates it and the mon `_health` turns it
    into TPU_BACKEND_DEGRADED / KERNEL_FALLBACK_LATCHED checks."""
    return {
        "sentinel": SENTINEL.state(),
        "fallback": TELEMETRY.fallback_latched(),
    }


def dump_kernel_telemetry() -> dict:
    """The `dump_kernel_telemetry` admin-socket payload."""
    return {
        "enabled": TELEMETRY.enabled,
        "kernels": TELEMETRY.dump(),
        "fallback": TELEMETRY.fallback_latched(),
        "sentinel": SENTINEL.state(),
        # cephplace satellite: one row per jax device with the last
        # probe's verdict + latency (ceph_backend_device_* on the
        # exporter; groundwork for mesh-shrink on a sick chip)
        "devices": SENTINEL.devices(),
        "events": TELEMETRY.events(),
    }
