"""cephheal accounting — per-(pool, codec) repair-bandwidth attribution
(reference: the recovery counters of src/osd/osd_perf_counters plus the
repair-bandwidth framing of regenerating codes, arXiv:1412.3022: the
cost that distinguishes code families is bytes READ from helpers per
byte repaired, and CLAY's sub-chunk repair exists precisely to cut it).

Before cephheal the repair ratio existed only as an offline bench
number (BENCH extra: CLAY(8,4) at 0.344x naive).  This table makes it a
LIVE cluster metric: every shard rebuild records how many helper shards
were read, how many bytes came off them, and how many bytes were
repaired, keyed by (pool, codec) — so ``ceph_recovery_bytes_read /
ceph_recovery_bytes_repaired`` is scrapeable and alertable per pool,
and the future repair-bandwidth-aware recovery scheduler has its
measured read-cost input (ROADMAP "repair-optimal codes" item).

The table duck-types ``PerfCounters`` (``name``/``dump()``/
``schema()``) so one ``cct.perf.add`` makes the labeled rows ride the
existing perf dump -> MMgrReport -> prometheus pipeline with zero new
wire plumbing (the cephmeter/cephdev precedent)::

    ceph_recovery_bytes_read{ceph_daemon="osd.0",pool="1",codec="jax-rs"} 81920
    ceph_recovery_bytes_repaired{...} 20480

Cardinality is naturally bounded by pool count; a defensive cap folds
overflow into a ``_other_`` row (sums preserved, attribution lost).
"""
from __future__ import annotations

from .lockdep import make_lock

#: defensive row bound (pools are few; a runaway pool-create loop must
#: still not grow the report unboundedly)
_MAX_ROWS = 64

#: the fold row overflow collapses into
OTHER_KEY = ("_other_", "_other_")


class _Row:
    __slots__ = ("repairs", "helper_reads", "bytes_read", "bytes_repaired",
                 "full_gathers")

    def __init__(self):
        self.repairs = 0         # shard rebuilds completed
        self.helper_reads = 0    # helper-shard reads feeding them
        self.bytes_read = 0      # bytes fetched from helpers
        self.bytes_repaired = 0  # bytes of rebuilt shard data
        self.full_gathers = 0    # rebuilds that fell back to the full
        #                          (non-plan) gather path

    def merge(self, other: "_Row") -> None:
        self.repairs += other.repairs
        self.helper_reads += other.helper_reads
        self.bytes_read += other.bytes_read
        self.bytes_repaired += other.bytes_repaired
        self.full_gathers += other.full_gathers


class RecoveryAccounting:
    """Bounded per-(pool, codec) repair-bandwidth table (module
    docstring).  One instance per OSD, added to ``cct.perf``."""

    def __init__(self, name: str = "recovery"):
        self.name = name
        self._lock = make_lock("recovery_acct::table")
        self._rows: dict[tuple[str, str], _Row] = {}
        self._other = _Row()

    def _row_locked(self, pool, codec: str) -> _Row:
        key = (str(pool), str(codec))
        row = self._rows.get(key)
        if row is None:
            if len(self._rows) >= _MAX_ROWS:
                return self._other
            row = self._rows[key] = _Row()
        return row

    def record_repair(self, pool, codec: str, helper_reads: int,
                      bytes_read: int, bytes_repaired: int,
                      full_gather: bool = False) -> None:
        """One completed shard rebuild: `helper_reads` helper shards
        were consulted, `bytes_read` bytes fetched off them, and the
        rebuilt shard is `bytes_repaired` bytes.  `full_gather` marks a
        rebuild that could not follow the codec's minimum_to_decode
        plan (stale generations, unreachable helpers) and read broadly
        instead — those rebuilds inflate the live ratio and the flag
        says why."""
        with self._lock:
            row = self._row_locked(pool, codec)
            row.repairs += 1
            row.helper_reads += int(helper_reads)
            row.bytes_read += int(bytes_read)
            row.bytes_repaired += int(bytes_repaired)
            if full_gather:
                row.full_gathers += 1

    def totals(self) -> dict:
        with self._lock:
            agg = _Row()
            for row in self._rows.values():
                agg.merge(row)
            agg.merge(self._other)
            return {"repairs": agg.repairs,
                    "helper_reads": agg.helper_reads,
                    "bytes_read": agg.bytes_read,
                    "bytes_repaired": agg.bytes_repaired,
                    "full_gathers": agg.full_gathers}

    def ratio(self, pool, codec: str) -> float | None:
        """Live bytes_read / bytes_repaired for one (pool, codec) —
        ~k for an MDS code reading k full chunks per repaired chunk,
        sub-k for a regenerating code (the CLAY point)."""
        with self._lock:
            row = self._rows.get((str(pool), str(codec)))
            if row is None or row.bytes_repaired <= 0:
                return None
            return row.bytes_read / row.bytes_repaired

    @staticmethod
    def _row_dict(key: tuple[str, str], row: _Row) -> dict:
        return {
            "labels": {"pool": key[0], "codec": key[1]},
            "repairs": row.repairs,
            "helper_reads": row.helper_reads,
            "bytes_read": row.bytes_read,
            "bytes_repaired": row.bytes_repaired,
            "full_gathers": row.full_gathers,
        }

    # -- PerfCounters duck type (rides cct.perf -> MMgrReport) -------------
    def dump(self) -> dict:
        with self._lock:
            rows = [self._row_dict(k, r) for k, r in sorted(
                self._rows.items())]
            if self._other.repairs:
                rows.append(self._row_dict(OTHER_KEY, self._other))
            return {
                "per_pool": {"__labeled__": True, "rows": rows},
                "tracked_pools": len(self._rows),
            }

    def schema(self) -> dict:
        return {
            "per_pool": {
                "type": "labeled",
                "description": "per-(pool,codec) repair-bandwidth rows "
                               "(cephheal; docs/observability.md)"},
            "repairs": {
                "type": "u64",
                "description": "shard rebuilds completed for this "
                               "(pool,codec)"},
            "helper_reads": {
                "type": "u64",
                "description": "helper-shard reads feeding rebuilds "
                               "(k per repair for an MDS code on the "
                               "plan path; d for CLAY sub-chunk repair)"},
            "bytes_read": {
                "type": "u64",
                "description": "bytes fetched from helper shards for "
                               "rebuilds — the repair bandwidth "
                               "regenerating codes minimize"},
            "bytes_repaired": {
                "type": "u64",
                "description": "bytes of shard data rebuilt; "
                               "bytes_read/bytes_repaired is the live "
                               "repair ratio (~k for RS, sub-k for "
                               "CLAY)"},
            "full_gathers": {
                "type": "u64",
                "description": "rebuilds that abandoned the "
                               "minimum_to_decode plan and gathered "
                               "broadly (stale generations or "
                               "unreachable helpers)"},
        }
