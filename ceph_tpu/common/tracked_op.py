"""In-flight op tracking (reference: src/common/TrackedOp.{h,cc} ::
TrackedOp, OpTracker; SURVEY.md §5.1).

Every op carries a timestamped event list; the tracker keeps in-flight ops
plus a bounded deque of completed ("historic") ops, and flags slow ops by
age.  This is the reference's practical profiler — `dump_historic_ops` shows
per-stage latency — and the admin socket exposes the same three dumps here.

cephmeter additions (PR 11):

- **per-stage durations**: ``stage_add`` accumulates named stage wall
  time (fed by ``OSD._op_stage`` and the write batcher on the same
  ``tracer.trace_now`` clock as the event marks), so a slow op's dump
  says WHICH stage dominated, not just when each ended;
- **slow-op history**: an op that completes slower than the complaint
  time is kept in a separate bounded deque served by
  ``dump_historic_slow_ops`` — with its stage attribution and (when
  cephtrace kept or tail-promoted the trace) the assembled
  cross-entity trace tree;
- **sticky slow accounting**: ``slow_op_count`` adds a decaying
  recent-slow count to the in-flight count, so an op that completes
  slow BETWEEN mgr report polls cannot vanish from SLOW_OPS before the
  digest samples it (the fast-finishing-straggler hole).
"""
from __future__ import annotations

import time
from collections import deque
from .lockdep import make_lock
from .tracer import TRACER, assemble_trees, trace_now


class TrackedOp:
    __slots__ = ("tracker", "desc", "initiated_at", "events", "stages",
                 "trace_id", "src", "_lock")

    def __init__(self, tracker: "OpTracker", desc: str,
                 src: str = "client"):
        self.tracker = tracker
        self.desc = desc
        self.initiated_at = trace_now()
        self.events: list[tuple[float, str]] = [(self.initiated_at, "initiated")]
        # stage -> accumulated seconds (cephmeter per-stage attribution)
        self.stages: dict[str, float] = {}
        # cephtrace context id, when the op rode a (sampled or
        # provisionally buffered) trace — dump_historic_slow_ops uses it
        # to attach the assembled tree
        self.trace_id: str | None = None
        # origin plane (cephheal): "client" ops vs background
        # "recovery"/"scrub" work — background ops keep their own
        # bounded history so a recovery tick can never evict client
        # forensics (and vice versa), but slow ones share the slow-op
        # history so dump_historic_slow_ops covers the whole daemon
        self.src = src
        self._lock = make_lock("optracker::op")

    def mark_event(self, name: str, ts: float | None = None) -> None:
        """`ts` lets a caller that also records a cephtrace span stamp
        BOTH with one clock read (tracer.trace_now) — dump_historic_ops
        per-stage offsets and span boundaries then agree exactly
        (the OSD's _op_stage helper is that caller)."""
        with self._lock:
            self.events.append((trace_now() if ts is None else ts, name))

    def stage_add(self, stage: str, seconds: float) -> None:
        """Accumulate one stage's wall time (several batcher waits or
        sub-op rounds may feed the same stage)."""
        with self._lock:
            self.stages[stage] = self.stages.get(stage, 0.0) + seconds

    def age(self, now: float | None = None) -> float:
        return (time.time() if now is None else now) - self.initiated_at

    def duration(self) -> float:
        """Initiation to last recorded event (== total for a finished
        op, whose final event is the 'done' mark)."""
        with self._lock:
            return self.events[-1][0] - self.initiated_at

    def dominant_stage(self) -> tuple[str, float] | None:
        with self._lock:
            if not self.stages:
                return None
            name = max(self.stages, key=self.stages.get)
            return name, self.stages[name]

    def _dom_suffix(self) -> str:
        """The shared ', dominant stage X (N ms)' tail of every
        SLOW_OPS detail line ('' when no stage recorded)."""
        dom = self.dominant_stage()
        if dom is None:
            return ""
        return f", dominant stage {dom[0]} ({dom[1] * 1e3:.1f} ms)"

    def _desc_tagged(self) -> str:
        """Background ops carry their plane in the detail line so a
        SLOW_OPS report distinguishes a recovery pull from a client op."""
        return self.desc if self.src == "client" \
            else f"[{self.src}] {self.desc}"

    def slow_summary(self, now: float | None = None) -> str:
        """One SLOW_OPS detail line naming the dominant stage."""
        return (f"{self._desc_tagged()}: {self.age(now):.2f}s"
                f"{self._dom_suffix()}")

    def dump(self) -> dict:
        with self._lock:
            events = list(self.events)
            stages = dict(self.stages)
        t0 = self.initiated_at
        out = {
            "description": self.desc,
            "src": self.src,
            "initiated_at": t0,
            "age": self.age(),
            "duration": events[-1][0] - t0,
            "type_data": {
                "events": [
                    {"time": ts, "event": name, "offset": ts - t0}
                    for ts, name in events
                ]
            },
        }
        if stages:
            out["stages"] = {
                s: round(d * 1e3, 3) for s, d in stages.items()
            }
            out["dominant_stage"] = max(stages, key=stages.get)
        if self.trace_id is not None:
            out["trace_id"] = self.trace_id
        return out

    def finish(self) -> None:
        self.mark_event("done")
        self.tracker.unregister(self)

    def __enter__(self) -> "TrackedOp":
        return self

    def __exit__(self, *exc) -> bool:
        self.finish()
        return False


class OpTracker:
    def __init__(self, history_size: int = 20, complaint_time: float = 30.0,
                 recent_slow_window: float = 60.0):
        self._inflight: dict[int, TrackedOp] = {}
        self._history: deque[TrackedOp] = deque(maxlen=history_size)
        # background (recovery/scrub) ops, separately bounded: the
        # per-tick recovery pass must not cycle client ops out of
        # dump_historic_ops, and client bursts must not hide a slow
        # backfill from dump_historic_bg_ops (cephheal)
        self._bg_history: deque[TrackedOp] = deque(
            maxlen=max(1, history_size))
        # completed-slow ops, separately bounded: a burst of fast ops
        # must not push a straggler out of forensic reach
        self._slow_history: deque[TrackedOp] = deque(
            maxlen=max(1, history_size))
        # completion wall-clock stamps of recent slow ops — the sticky
        # SLOW_OPS count (decays after recent_slow_window seconds)
        self._recent_slow: deque[float] = deque(maxlen=1024)
        self._lock = make_lock("optracker::tracker")
        self.complaint_time = complaint_time
        self.recent_slow_window = recent_slow_window

    def create(self, desc: str, src: str = "client") -> TrackedOp:
        op = TrackedOp(self, desc, src=src)
        with self._lock:
            self._inflight[id(op)] = op
        return op

    def unregister(self, op: TrackedOp) -> None:
        slow = (self.complaint_time > 0
                and op.duration() > self.complaint_time)
        with self._lock:
            if self._inflight.pop(id(op), None) is not None:
                if op.src == "client":
                    self._history.append(op)
                else:
                    self._bg_history.append(op)
                if slow:
                    self._slow_history.append(op)
                    self._recent_slow.append(time.time())

    def num_inflight(self) -> int:
        with self._lock:
            return len(self._inflight)

    def dump_ops_in_flight(self) -> dict:
        with self._lock:
            ops = list(self._inflight.values())
        return {"num_ops": len(ops), "ops": [op.dump() for op in ops]}

    def dump_historic_ops(self) -> dict:
        with self._lock:
            ops = list(self._history)
        return {"num_ops": len(ops), "ops": [op.dump() for op in ops]}

    def dump_historic_bg_ops(self) -> dict:
        """Completed background (recovery/scrub) ops — the plane
        dump_historic_ops never saw before cephheal."""
        with self._lock:
            ops = list(self._bg_history)
        return {"num_ops": len(ops), "ops": [op.dump() for op in ops]}

    def dump_historic_slow_ops(self, with_traces: bool = True) -> dict:
        """Completed-slow forensics: stage attribution per op plus (when
        cephtrace kept the spans — head-sampled or tail-promoted) the
        assembled cross-entity trace tree (docs/observability.md)."""
        with self._lock:
            ops = list(self._slow_history)
        out = []
        for op in ops:
            d = op.dump()
            if with_traces and op.trace_id is not None:
                spans = TRACER.spans(trace_id=op.trace_id)
                if spans:
                    d["trace"] = {
                        "trace_id": op.trace_id,
                        "num_spans": len(spans),
                        "entities": sorted({s["entity"] for s in spans}),
                        "tree": assemble_trees(spans).get(op.trace_id, []),
                    }
            out.append(d)
        return {"num_ops": len(out),
                "complaint_time": self.complaint_time, "ops": out}

    def slow_ops(self, now: float | None = None) -> list[TrackedOp]:
        """Ops older than the complaint time (reference: the
        'slow requests' health warning path)."""
        now = time.time() if now is None else now
        with self._lock:
            ops = list(self._inflight.values())
        return [op for op in ops if op.age(now) > self.complaint_time]

    def slow_op_count(self, now: float | None = None) -> int:
        """In-flight slow ops PLUS recently-completed slow ops within
        the decay window — the sticky count SLOW_OPS reports, so a
        straggler that finishes between two mgr report polls still
        surfaces (satellite: no vanishing fast-finishing stragglers)."""
        now = time.time() if now is None else now
        with self._lock:
            while (self._recent_slow
                   and now - self._recent_slow[0] > self.recent_slow_window):
                self._recent_slow.popleft()
            recent = len(self._recent_slow)
        return len(self.slow_ops(now)) + recent

    def slow_summaries(self, now: float | None = None,
                       limit: int = 5) -> list[str]:
        """Detail lines for the SLOW_OPS health check: in-flight slow
        ops first, then the freshest completed stragglers."""
        now = time.time() if now is None else now
        lines = [op.slow_summary(now) for op in self.slow_ops(now)]
        with self._lock:
            recent = list(self._slow_history)
        for op in reversed(recent):
            if len(lines) >= limit:
                break
            lines.append(f"{op._desc_tagged()}: completed in "
                         f"{op.duration():.2f}s{op._dom_suffix()}")
        return lines[:limit]
