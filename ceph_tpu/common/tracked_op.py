"""In-flight op tracking (reference: src/common/TrackedOp.{h,cc} ::
TrackedOp, OpTracker; SURVEY.md §5.1).

Every op carries a timestamped event list; the tracker keeps in-flight ops
plus a bounded deque of completed ("historic") ops, and flags slow ops by
age.  This is the reference's practical profiler — `dump_historic_ops` shows
per-stage latency — and the admin socket exposes the same three dumps here.
"""
from __future__ import annotations

import time
from collections import deque
from .lockdep import make_lock
from .tracer import trace_now


class TrackedOp:
    __slots__ = ("tracker", "desc", "initiated_at", "events", "_lock")

    def __init__(self, tracker: "OpTracker", desc: str):
        self.tracker = tracker
        self.desc = desc
        self.initiated_at = trace_now()
        self.events: list[tuple[float, str]] = [(self.initiated_at, "initiated")]
        self._lock = make_lock("optracker::op")

    def mark_event(self, name: str, ts: float | None = None) -> None:
        """`ts` lets a caller that also records a cephtrace span stamp
        BOTH with one clock read (tracer.trace_now) — dump_historic_ops
        per-stage offsets and span boundaries then agree exactly
        (the OSD's _op_stage helper is that caller)."""
        with self._lock:
            self.events.append((trace_now() if ts is None else ts, name))

    def age(self, now: float | None = None) -> float:
        return (time.time() if now is None else now) - self.initiated_at

    def dump(self) -> dict:
        with self._lock:
            events = list(self.events)
        t0 = self.initiated_at
        return {
            "description": self.desc,
            "initiated_at": t0,
            "age": self.age(),
            "duration": events[-1][0] - t0,
            "type_data": {
                "events": [
                    {"time": ts, "event": name, "offset": ts - t0}
                    for ts, name in events
                ]
            },
        }

    def finish(self) -> None:
        self.mark_event("done")
        self.tracker.unregister(self)

    def __enter__(self) -> "TrackedOp":
        return self

    def __exit__(self, *exc) -> bool:
        self.finish()
        return False


class OpTracker:
    def __init__(self, history_size: int = 20, complaint_time: float = 30.0):
        self._inflight: dict[int, TrackedOp] = {}
        self._history: deque[TrackedOp] = deque(maxlen=history_size)
        self._lock = make_lock("optracker::tracker")
        self.complaint_time = complaint_time

    def create(self, desc: str) -> TrackedOp:
        op = TrackedOp(self, desc)
        with self._lock:
            self._inflight[id(op)] = op
        return op

    def unregister(self, op: TrackedOp) -> None:
        with self._lock:
            if self._inflight.pop(id(op), None) is not None:
                self._history.append(op)

    def num_inflight(self) -> int:
        with self._lock:
            return len(self._inflight)

    def dump_ops_in_flight(self) -> dict:
        with self._lock:
            ops = list(self._inflight.values())
        return {"num_ops": len(ops), "ops": [op.dump() for op in ops]}

    def dump_historic_ops(self) -> dict:
        with self._lock:
            ops = list(self._history)
        return {"num_ops": len(ops), "ops": [op.dump() for op in ops]}

    def slow_ops(self, now: float | None = None) -> list[TrackedOp]:
        """Ops older than the complaint time (reference: the
        'slow requests' health warning path)."""
        now = time.time() if now is None else now
        with self._lock:
            ops = list(self._inflight.values())
        return [op for op in ops if op.age(now) > self.complaint_time]
