"""cephmeter accounting — bounded-cardinality per-(client, pool) I/O
attribution (reference: the mClock client-profile tags in
src/osd/scheduler/mClockScheduler.cc plus the per-client perf queries of
src/mgr/MetricCollector.{h,cc}; arXiv:1709.05365's finding that
PER-TENANT queueing, not compute, dominates online EC at scale).

The op path used to be anonymous: `osd.op`/`op_w_bytes` aggregate every
client into one counter, so neither a QoS controller nor an operator
can see WHO is driving the load.  `IOAccounting` is a per-daemon table
keyed by (client entity, pool id) recording ops, bytes, and the three
latencies that matter for admission control — batcher ``admission``
wait, coalescing ``queue`` wait, and ``e2e`` op latency — as the PR-9
log2 histograms.  The (client, pool) labels ARE the future mClock tags:
a controller that reads these series can hand the same keys straight to
the scheduler's QoS classes.

Cardinality is BOUNDED (a scraper must survive a million clients):

- the table holds at most ``top_k`` live (client, pool) entries;
- on overflow the least-recently-used entry OUTSIDE the top half by
  cumulative ops is evicted (heavy hitters survive a scan of one-op
  clients), and its counts FOLD into a single ``_other_`` bucket —
  sums are preserved, only attribution is lost;
- the prometheus exporter applies a second cap at exposition time
  (mgr/prometheus_module._MAX_LABEL_SETS) with the same fold rule.

The table duck-types ``PerfCounters`` (``name``/``dump()``/
``schema()``) so one ``cct.perf.add(acct)`` makes the labeled series
ride the existing perf dump -> MMgrReport -> prometheus pipeline with
zero new wire plumbing (the cephdev precedent).  Rows render as::

    ceph_client_io_ops{ceph_daemon="osd.0",client="client.a",pool="1"} 12
    ceph_client_io_lat_e2e_bucket{...,le="0.000512"} 9
"""
from __future__ import annotations

from collections import OrderedDict

from .lockdep import make_lock
from .perf_counters import HIST_NUM_BUCKETS, _hist_bucket

#: the per-entry latency histograms (subset of tracer.OP_STAGES plus
#: the end-to-end op latency the client actually feels)
ACCT_STAGES = ("admission", "queue", "e2e")

#: ops that count as writes / reads for the bytes split
_WRITE_OPS = frozenset({"write_full", "write", "append", "delete",
                        "setxattr", "omap_set", "omap_rm", "omap_clear"})
_READ_OPS = frozenset({"read", "stat", "getxattrs", "omap_get", "list"})

#: the fold bucket every evicted / over-cap entry collapses into
OTHER_KEY = ("_other_", "_other_")


def _new_hist() -> dict:
    return {"count": 0, "sum": 0.0, "buckets": [0] * (HIST_NUM_BUCKETS + 1)}


def _hist_add(hist: dict, seconds: float) -> None:
    hist["buckets"][_hist_bucket(seconds)] += 1
    hist["count"] += 1
    hist["sum"] += seconds


def _hist_merge(into: dict, frm: dict) -> None:
    into["count"] += frm["count"]
    into["sum"] += frm["sum"]
    for i, c in enumerate(frm["buckets"]):
        into["buckets"][i] += c


class _Entry:
    __slots__ = ("ops", "ops_w", "ops_r", "bytes_w", "bytes_r", "hists")

    def __init__(self):
        self.ops = 0
        self.ops_w = 0
        self.ops_r = 0
        self.bytes_w = 0
        self.bytes_r = 0
        self.hists = {s: _new_hist() for s in ACCT_STAGES}

    def merge(self, other: "_Entry") -> None:
        self.ops += other.ops
        self.ops_w += other.ops_w
        self.ops_r += other.ops_r
        self.bytes_w += other.bytes_w
        self.bytes_r += other.bytes_r
        for s in ACCT_STAGES:
            _hist_merge(self.hists[s], other.hists[s])


class IOAccounting:
    """Bounded per-(client, pool) accounting table (module docstring).

    Duck-types PerfCounters for PerfCountersCollection.add: the dump is
    one ``per_client`` labeled-rows structure (the prometheus module
    renders it) plus plain ``tracked_clients``/``evictions`` scalars.
    """

    def __init__(self, name: str = "client_io", top_k: int = 64):
        self.name = name
        self.top_k = max(1, int(top_k))
        self._lock = make_lock("client_io::table")
        # LRU order: oldest-touched first (move_to_end on every record)
        self._entries: OrderedDict[tuple[str, str], _Entry] = OrderedDict()
        self._other = _Entry()
        self._evictions = 0

    # -- recording ---------------------------------------------------------
    def _entry_locked(self, client: str, pool) -> _Entry:
        key = (str(client), str(pool))
        e = self._entries.get(key)
        if e is None:
            if len(self._entries) >= self.top_k:
                self._evict_locked()
            e = self._entries[key] = _Entry()
        self._entries.move_to_end(key)
        return e

    def _evict_locked(self) -> None:
        """Fold ONE entry into `_other_`: the least-recently-used entry
        outside the top half by cumulative ops — heavy hitters are
        protected from being cycled out by a scan of one-op clients."""
        protect = self.top_k // 2
        if protect:
            # reversed iteration = most-recently-used first, so a tie on
            # ops protects the FRESH entry and lets stale ones cycle out
            by_ops = sorted(reversed(self._entries.items()),
                            key=lambda kv: kv[1].ops, reverse=True)
            protected = {k for k, _ in by_ops[:protect]}
        else:
            protected = set()
        victim = next((k for k in self._entries if k not in protected),
                      next(iter(self._entries)))
        self._other.merge(self._entries.pop(victim))
        self._evictions += 1

    def record_op(self, client: str, pool, op: str, nbytes: int = 0,
                  e2e: float | None = None) -> None:
        """One completed op: classify read/write, count bytes, feed the
        e2e latency histogram."""
        with self._lock:
            e = self._entry_locked(client, pool)
            e.ops += 1
            if op in _WRITE_OPS:
                e.ops_w += 1
                e.bytes_w += int(nbytes)
            elif op in _READ_OPS:
                e.ops_r += 1
                e.bytes_r += int(nbytes)
            if e2e is not None:
                _hist_add(e.hists["e2e"], e2e)

    def record_stage(self, client: str, pool, stage: str,
                     seconds: float) -> None:
        """One admission/queue stage sample (the write batcher calls
        this from the op thread / flusher with the identity the OSD
        stamped into the op-trace state)."""
        if stage not in ACCT_STAGES:
            return
        with self._lock:
            _hist_add(self._entry_locked(client, pool).hists[stage],
                      seconds)

    def reads_of(self, client: str, pool) -> int:
        """Accumulated read-op count for one (client, pool) identity —
        the cephread hot-object cache's promotion signal (an identity
        folded into `_other_` reads 0: an evicted row was, by
        construction, not a heavy hitter).  Does NOT touch LRU order:
        a promotion probe is not traffic."""
        with self._lock:
            e = self._entries.get((str(client), str(pool)))
            return e.ops_r if e is not None else 0

    # -- introspection -----------------------------------------------------
    def totals(self) -> dict:
        """Aggregate across every entry INCLUDING `_other_` — the
        conservation check (evictions lose attribution, never counts)."""
        with self._lock:
            agg = _Entry()
            for e in self._entries.values():
                agg.merge(e)
            agg.merge(self._other)
            return {"ops": agg.ops, "ops_w": agg.ops_w,
                    "ops_r": agg.ops_r, "bytes_w": agg.bytes_w,
                    "bytes_r": agg.bytes_r,
                    "e2e_count": agg.hists["e2e"]["count"]}

    def _row(self, key: tuple[str, str], e: _Entry) -> dict:
        return {
            "labels": {"client": key[0], "pool": key[1]},
            "ops": e.ops, "ops_w": e.ops_w, "ops_r": e.ops_r,
            "bytes_w": e.bytes_w, "bytes_r": e.bytes_r,
            "lat_admission": {"count": e.hists["admission"]["count"],
                              "sum": e.hists["admission"]["sum"],
                              "buckets": list(e.hists["admission"]["buckets"])},
            "lat_queue": {"count": e.hists["queue"]["count"],
                          "sum": e.hists["queue"]["sum"],
                          "buckets": list(e.hists["queue"]["buckets"])},
            "lat_e2e": {"count": e.hists["e2e"]["count"],
                        "sum": e.hists["e2e"]["sum"],
                        "buckets": list(e.hists["e2e"]["buckets"])},
        }

    # -- PerfCounters duck type (rides cct.perf -> MMgrReport) -------------
    def dump(self) -> dict:
        with self._lock:
            rows = [self._row(k, e) for k, e in sorted(
                self._entries.items(),
                key=lambda kv: kv[1].ops, reverse=True)]
            if self._other.ops or self._other.hists["admission"]["count"] \
                    or self._other.hists["queue"]["count"]:
                rows.append(self._row(OTHER_KEY, self._other))
            return {
                "per_client": {"__labeled__": True, "rows": rows},
                "tracked_clients": len(self._entries),
                "evictions": self._evictions,
            }

    def schema(self) -> dict:
        return {
            "per_client": {
                "type": "labeled",
                "description": "per-(client,pool) I/O accounting rows "
                               "(bounded top-K + LRU + _other_ overflow; "
                               "docs/observability.md)"},
            "ops": {"type": "u64",
                    "description": "client ops attributed to this "
                                   "(client,pool)"},
            "ops_w": {"type": "u64", "description": "attributed writes"},
            "ops_r": {"type": "u64", "description": "attributed reads"},
            "bytes_w": {"type": "u64",
                        "description": "attributed bytes written"},
            "bytes_r": {"type": "u64",
                        "description": "attributed bytes read"},
            "lat_admission": {
                "type": "histogram",
                "description": "per-client write-batcher admission wait"},
            "lat_queue": {
                "type": "histogram",
                "description": "per-client coalescing queue wait"},
            "lat_e2e": {
                "type": "histogram",
                "description": "per-client end-to-end op latency at the "
                               "primary"},
            "tracked_clients": {
                "type": "gauge",
                "description": "live (client,pool) accounting entries"},
            "evictions": {
                "type": "u64",
                "description": "entries folded into _other_ by the "
                               "cardinality bound"},
        }
