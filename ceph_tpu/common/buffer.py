"""BufferList — chained zero-copy buffers (reference: src/include/buffer.h ::
ceph::buffer::list, src/common/buffer.cc).

The type that crosses every I/O interface in the reference — messenger frame
segments, ObjectStore transactions, and the `encode_chunks` host boundary.
Here it wraps a chain of memoryviews: appends never copy, `to_bytes()`
flattens once and caches, and `crc32c` / `substr` / alignment helpers mirror
the reference API surface the runtime layers need.  Little-endian fixed-width
encode/decode helpers replace the reference's encode.h templates for wire and
store formats.
"""
from __future__ import annotations

import struct

from .crc32c import crc32c as _crc32c


class BufferList:
    """Append-only chain of bytes-like segments with lazy flattening."""

    __slots__ = ("_segs", "_len", "_flat")

    def __init__(self, data: bytes | bytearray | memoryview | "BufferList" | None = None):
        self._segs: list[memoryview] = []
        self._len = 0
        self._flat: bytes | None = None
        if data is not None:
            self.append(data)

    # -- building ---------------------------------------------------------
    def append(self, data) -> "BufferList":
        if isinstance(data, BufferList):
            self._segs.extend(data._segs)
            self._len += data._len
        else:
            mv = memoryview(data).cast("B")
            if len(mv):
                self._segs.append(mv)
                self._len += len(mv)
        self._flat = None
        return self

    def append_zero(self, n: int) -> "BufferList":
        return self.append(bytes(n))

    def claim_append(self, other: "BufferList") -> "BufferList":
        """reference: bufferlist::claim_append — move segments, empty other."""
        self.append(other)
        other.clear()
        return self

    def clear(self) -> None:
        self._segs.clear()
        self._len = 0
        self._flat = None

    # -- struct-style encode helpers (little-endian, reference encode.h) --
    def append_u8(self, v: int) -> "BufferList":
        return self.append(struct.pack("<B", v))

    def append_u16(self, v: int) -> "BufferList":
        return self.append(struct.pack("<H", v))

    def append_u32(self, v: int) -> "BufferList":
        return self.append(struct.pack("<I", v))

    def append_u64(self, v: int) -> "BufferList":
        return self.append(struct.pack("<Q", v))

    def append_str(self, s: str | bytes) -> "BufferList":
        b = s.encode() if isinstance(s, str) else bytes(s)
        self.append_u32(len(b))
        return self.append(b)

    # -- reading ----------------------------------------------------------
    def __len__(self) -> int:
        return self._len

    def length(self) -> int:
        return self._len

    def to_bytes(self) -> bytes:
        if self._flat is None:
            self._flat = b"".join(self._segs)
        return self._flat

    def __bytes__(self) -> bytes:
        return self.to_bytes()

    def __eq__(self, other) -> bool:
        if isinstance(other, (bytes, bytearray)):
            return self.to_bytes() == bytes(other)
        if isinstance(other, BufferList):
            return self.to_bytes() == other.to_bytes()
        return NotImplemented

    def __hash__(self):  # flat content identity, like bufferlist operator==
        return hash(self.to_bytes())

    def substr(self, off: int, length: int) -> "BufferList":
        """Zero-copy sub-range (reference: bufferlist::substr_of)."""
        if off < 0 or length < 0 or off + length > self._len:
            raise IndexError(f"substr({off}, {length}) out of range 0..{self._len}")
        out = BufferList()
        pos = 0
        for seg in self._segs:
            if length == 0:
                break
            end = pos + len(seg)
            if end <= off:
                pos = end
                continue
            start = max(off, pos) - pos
            take = min(len(seg) - start, length)
            out.append(seg[start : start + take])
            off += take
            length -= take
            pos = end
        return out

    def crc32c(self, seed: int = 0xFFFFFFFF) -> int:
        crc = seed
        for seg in self._segs:
            crc = _crc32c(seg, crc)
        return crc

    def is_contiguous(self) -> bool:
        return len(self._segs) <= 1

    def rebuild(self) -> None:
        """Coalesce into one segment (reference: bufferlist::rebuild)."""
        flat = self.to_bytes()
        self._segs = [memoryview(flat)] if flat else []

    def rebuild_aligned(self, align: int) -> None:
        """Pad with zeros to a multiple of `align` and coalesce (reference:
        bufferlist::rebuild_aligned — DMA/chunk alignment before encode)."""
        pad = (-self._len) % align
        if pad:
            self.append_zero(pad)
        self.rebuild()

    # -- iterator-style decode --------------------------------------------
    def iterator(self) -> "BufferListIterator":
        return BufferListIterator(self.to_bytes())


class BufferListIterator:
    """Sequential decoder over a flattened BufferList (reference:
    bufferlist::iterator + denc decode)."""

    __slots__ = ("_data", "_off")

    def __init__(self, data: bytes):
        self._data = data
        self._off = 0

    def remaining(self) -> int:
        return len(self._data) - self._off

    def _take(self, n: int) -> bytes:
        if self._off + n > len(self._data):
            raise EOFError(
                f"decode past end: need {n}, have {self.remaining()}"
            )
        out = self._data[self._off : self._off + n]
        self._off += n
        return out

    def get_u8(self) -> int:
        return struct.unpack("<B", self._take(1))[0]

    def get_u16(self) -> int:
        return struct.unpack("<H", self._take(2))[0]

    def get_u32(self) -> int:
        return struct.unpack("<I", self._take(4))[0]

    def get_u64(self) -> int:
        return struct.unpack("<Q", self._take(8))[0]

    def get_bytes(self, n: int) -> bytes:
        return self._take(n)

    def get_str(self) -> str:
        return self._take(self.get_u32()).decode()

    def get_str_bytes(self) -> bytes:
        return self._take(self.get_u32())
