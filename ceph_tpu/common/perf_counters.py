"""PerfCounters — typed runtime metrics (reference:
src/common/perf_counters.{h,cc} :: PerfCounters, PerfCountersBuilder,
PerfCountersCollection; SURVEY.md §5.5).

Counters / gauges / time-averages registered per subsystem on the context,
dumped as nested dicts via the admin socket (`perf dump`) and scraped by the
metrics exporter (ceph_tpu.mgr).  Long-running averages keep (sum, count)
pairs exactly like the reference so consumers can compute rate-correct
averages between two dumps.
"""
from __future__ import annotations

import time
from .lockdep import make_lock

TYPE_U64 = "u64"  # monotonically increasing counter
TYPE_GAUGE = "gauge"  # settable value
TYPE_TIME = "time"  # accumulated seconds
TYPE_LONGRUNAVG = "longrunavg"  # (sum, count)
TYPE_HISTOGRAM = "histogram"  # log2-bucket latency histogram

# log2 bucket boundaries in SECONDS: bucket i counts samples <= 2^i µs
# (1 µs .. ~134 s, then +Inf) — the reference's PerfHistogram uses the
# same power-of-two scale so two dumps subtract bucket-by-bucket
HIST_NUM_BUCKETS = 28
HIST_LE = tuple((1 << i) / 1e6 for i in range(HIST_NUM_BUCKETS))


def _hist_bucket(seconds: float) -> int:
    """Index of the first bucket whose upper bound holds `seconds`;
    HIST_NUM_BUCKETS = overflow (+Inf)."""
    us = seconds * 1e6
    if us <= 1.0:
        return 0
    b = int(us - 1e-9).bit_length()  # 2^(b-1) < us <= 2^b (approx)
    if (1 << b) < us:
        b += 1
    return min(b, HIST_NUM_BUCKETS)


class _Counter:
    __slots__ = ("name", "type", "doc", "value", "sum", "count", "buckets")

    def __init__(self, name: str, ctype: str, doc: str):
        self.name = name
        self.type = ctype
        self.doc = doc
        self.value = 0.0
        self.sum = 0.0
        self.count = 0
        self.buckets = (
            [0] * (HIST_NUM_BUCKETS + 1) if ctype == TYPE_HISTOGRAM else None
        )


class PerfCounters:
    """One subsystem's counter set (reference: PerfCounters)."""

    def __init__(self, name: str):
        self.name = name
        self._counters: dict[str, _Counter] = {}
        self._lock = make_lock("perf::counters")

    def _add(self, name: str, ctype: str, doc: str) -> None:
        # locked: the kernel-telemetry registry declares counters lazily
        # at first dispatch, racing dump()/schema() iterations
        with self._lock:
            if name in self._counters:
                raise ValueError(
                    f"duplicate perf counter {self.name}.{name}")
            self._counters[name] = _Counter(name, ctype, doc)

    def inc(self, name: str, amount: float = 1) -> None:
        c = self._counters[name]
        with self._lock:
            c.value += amount

    def dec(self, name: str, amount: float = 1) -> None:
        c = self._counters[name]
        assert c.type == TYPE_GAUGE, f"dec on non-gauge {name}"
        with self._lock:
            c.value -= amount

    def set(self, name: str, value: float) -> None:
        c = self._counters[name]
        with self._lock:
            c.value = value

    def tinc(self, name: str, seconds: float) -> None:
        """Accumulate elapsed time (reference: PerfCounters::tinc)."""
        c = self._counters[name]
        with self._lock:
            if c.type == TYPE_LONGRUNAVG:
                c.sum += seconds
                c.count += 1
            else:
                c.value += seconds

    def avg(self, name: str, value: float) -> None:
        """Feed a long-running average sample."""
        c = self._counters[name]
        with self._lock:
            c.sum += value
            c.count += 1

    def hinc(self, name: str, seconds: float) -> None:
        """Feed one latency sample into a log2-bucket histogram
        (reference: PerfHistogram::inc)."""
        c = self._counters[name]
        assert c.type == TYPE_HISTOGRAM, f"hinc on non-histogram {name}"
        b = _hist_bucket(seconds)
        with self._lock:
            c.buckets[b] += 1
            c.sum += seconds
            c.count += 1

    def get(self, name: str) -> float:
        return self._counters[name].value

    def time_fn(self, name: str):
        """Context manager timing a block into a time/longrunavg counter."""
        return _Timer(self, name)

    def dump(self) -> dict:
        out: dict = {}
        with self._lock:
            for c in self._counters.values():
                if c.type == TYPE_LONGRUNAVG:
                    out[c.name] = {"avgcount": c.count, "sum": c.sum}
                elif c.type == TYPE_HISTOGRAM:
                    out[c.name] = {
                        "count": c.count,
                        "sum": c.sum,
                        "buckets": list(c.buckets),  # per-bucket, not cumulative
                    }
                elif c.type == TYPE_U64:
                    out[c.name] = int(c.value)
                else:
                    out[c.name] = c.value
        return out

    def schema(self) -> dict:
        with self._lock:
            return {
                c.name: {"type": c.type, "description": c.doc}
                for c in self._counters.values()
            }


class _Timer:
    __slots__ = ("_pc", "_name", "_t0")

    def __init__(self, pc: PerfCounters, name: str):
        self._pc = pc
        self._name = name

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self._pc.tinc(self._name, time.perf_counter() - self._t0)
        return False


class PerfCountersBuilder:
    """Declarative construction (reference: PerfCountersBuilder — the
    add_u64_counter / add_time_avg calls in every daemon's ctor)."""

    def __init__(self, name: str):
        self._pc = PerfCounters(name)

    def add_u64_counter(self, name: str, doc: str = "") -> "PerfCountersBuilder":
        self._pc._add(name, TYPE_U64, doc)
        return self

    def add_u64(self, name: str, doc: str = "") -> "PerfCountersBuilder":
        self._pc._add(name, TYPE_GAUGE, doc)
        return self

    def add_time(self, name: str, doc: str = "") -> "PerfCountersBuilder":
        self._pc._add(name, TYPE_TIME, doc)
        return self

    def add_time_avg(self, name: str, doc: str = "") -> "PerfCountersBuilder":
        self._pc._add(name, TYPE_LONGRUNAVG, doc)
        return self

    def add_time_histogram(self, name: str,
                           doc: str = "") -> "PerfCountersBuilder":
        """Log2-bucket latency histogram (reference: PerfHistogram —
        add_u64_counter_histogram), fed via PerfCounters.hinc."""
        self._pc._add(name, TYPE_HISTOGRAM, doc)
        return self

    def create_perf_counters(self) -> PerfCounters:
        return self._pc


class PerfCountersCollection:
    """All of a process's PerfCounters (reference: PerfCountersCollection on
    CephContext; admin socket `perf dump` renders this)."""

    def __init__(self):
        self._loggers: dict[str, PerfCounters] = {}
        self._lock = make_lock("perf::collection")

    def add(self, pc: PerfCounters) -> PerfCounters:
        with self._lock:
            if pc.name in self._loggers:
                raise ValueError(f"duplicate perf counters {pc.name}")
            self._loggers[pc.name] = pc
        return pc

    def remove(self, name: str) -> None:
        with self._lock:
            self._loggers.pop(name, None)

    def get(self, name: str) -> PerfCounters | None:
        return self._loggers.get(name)

    def dump(self) -> dict:
        with self._lock:
            loggers = list(self._loggers.values())
        return {pc.name: pc.dump() for pc in loggers}

    def schema(self) -> dict:
        with self._lock:
            loggers = list(self._loggers.values())
        return {pc.name: pc.schema() for pc in loggers}
