"""Per-daemon admin socket (reference: src/common/admin_socket.{h,cc} ::
AdminSocket; SURVEY.md §5.5).

A Unix-domain socket served by one background thread.  Protocol: client
sends one JSON object terminated by newline (`{"prefix": "perf dump"}` —
the reference accepts the same shape), server replies with a 4-byte
big-endian length followed by the JSON response, exactly the reference's
framing, so existing tooling habits transfer.
"""
from __future__ import annotations

import json
import os
import socket
import struct
import sys
from threading import Thread
from typing import Callable

from .lockdep import make_lock

Handler = Callable[[dict], object]


class AdminSocket:
    def __init__(self, path: str):
        self.path = path
        self._commands: dict[str, tuple[Handler, str]] = {}
        self._thread: Thread | None = None
        self._sock: socket.socket | None = None
        self._lock = make_lock("common::admin_socket")
        self.register_command("help", self._help, "list available commands")

    # -- registration -----------------------------------------------------
    def register_command(self, prefix: str, handler: Handler, help: str = "") -> None:
        if prefix in self._commands:
            raise ValueError(f"admin socket command {prefix!r} already registered")
        self._commands[prefix] = (handler, help)

    def unregister_command(self, prefix: str) -> None:
        self._commands.pop(prefix, None)

    def _help(self, cmd: dict) -> dict:
        return {p: h for p, (_, h) in sorted(self._commands.items())}

    def execute(self, cmd: dict) -> object:
        """Dispatch one parsed command (also the in-process entry point)."""
        prefix = cmd.get("prefix", "")
        entry = self._commands.get(prefix)
        if entry is None:
            raise KeyError(f"unknown command {prefix!r}; try 'help'")
        return entry[0](cmd)

    # -- server -----------------------------------------------------------
    def start(self) -> None:
        if self._thread is not None:
            return
        if os.path.exists(self.path):
            os.unlink(self.path)
        self._sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        self._sock.bind(self.path)
        self._sock.listen(8)
        self._thread = Thread(target=self._serve, name="admin_socket", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        # take the socket under the lock (two stop() racers would
        # double-close), close it after release
        with self._lock:
            sock, self._sock = self._sock, None
        if sock is not None:
            # closing the listener does NOT wake a thread blocked in
            # accept() on Linux — poke it with one throwaway connection
            # so the serve loop observes the cleared self._sock and exits
            try:
                with socket.socket(socket.AF_UNIX,
                                   socket.SOCK_STREAM) as poke:
                    poke.settimeout(1.0)
                    poke.connect(self.path)
            except OSError:
                pass
            sock.close()
        if os.path.exists(self.path):
            try:
                os.unlink(self.path)
            except OSError:
                pass
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    def _serve(self) -> None:
        while True:
            sock = self._sock
            if sock is None:
                return
            try:
                conn, _ = sock.accept()
            except OSError:  # socket closed by stop()
                return
            if self._sock is None:  # stop()'s wake-up poke, not a client
                conn.close()
                return
            try:
                self._handle(conn)
            except Exception as e:
                # a broken client or a handler bug must not kill the
                # serve loop, but it must not vanish either
                print(f"# admin_socket {self.path}: request failed: "
                      f"{e!r}", file=sys.stderr)
            finally:
                conn.close()

    def _handle(self, conn: socket.socket) -> None:
        # one slow/silent client must not wedge the socket: bound both the
        # wait and the request size
        conn.settimeout(5.0)
        data = b""
        while b"\n" not in data:
            chunk = conn.recv(65536)
            if not chunk:
                break
            data += chunk
            if len(data) > (1 << 20):
                raise ValueError("admin socket request too large")
        line = data.split(b"\n", 1)[0].strip()
        try:
            cmd = json.loads(line) if line else {}
            if isinstance(cmd, str):
                cmd = {"prefix": cmd}
            result = self.execute(cmd)
            body = json.dumps(result, default=str).encode()
        except Exception as e:
            body = json.dumps({"error": str(e)}).encode()
        conn.sendall(struct.pack(">I", len(body)) + body)


def admin_socket_command(path: str, cmd: dict | str, timeout: float = 5.0) -> object:
    """Client side (reference: the `ceph daemon <sock> <cmd>` path)."""
    if isinstance(cmd, str):
        cmd = {"prefix": cmd}
    with socket.socket(socket.AF_UNIX, socket.SOCK_STREAM) as s:
        s.settimeout(timeout)
        s.connect(path)
        s.sendall(json.dumps(cmd).encode() + b"\n")
        hdr = b""
        while len(hdr) < 4:
            chunk = s.recv(4 - len(hdr))
            if not chunk:
                raise ConnectionError("admin socket closed mid-header")
            hdr += chunk
        (n,) = struct.unpack(">I", hdr)
        body = b""
        while len(body) < n:
            chunk = s.recv(n - len(body))
            if not chunk:
                raise ConnectionError("admin socket closed mid-body")
            body += chunk
        return json.loads(body)
