"""cephtopo — the ONE module where device topology is ambient.

The ROADMAP's multi-chip sharded data plane needs the same OSD code to
serve a laptop test (1 CPU device), an 8-chip mesh, and a
sentinel-shrunk degraded mesh.  That is impossible while `jax.devices()`
/ `jax.sharding.Mesh(...)` / `jax.default_backend()` probes are
scattered through the package: each ambient site hard-codes "whatever
this process happens to see" as the topology.  So topology becomes a
value: a ``DevicePolicy`` built ONCE from the daemon's conf
(``device_topology`` / ``device_mesh_shape``) and constructor-injected
into the seams that need it — the OSD daemon, the device stripe pool,
bitplane/pipeline dispatch, ``crush_do_rule_batch``, and
``parallel.mesh``.  cephlint CL9 (qa/analyzer/cl9_topology.py) enforces
the discipline: this file is the one allowlisted module where the
ambient probes may live; everywhere else they are lint errors.

Variants (the ``device_topology`` option):

- ``single`` — one chip: the default device only, mesh size 1.
- ``mesh``   — multi-chip: every healthy device (``device_mesh_shape``
  caps the axis length; 0 = all).
- ``cpu``    — CPU fallback: a 1-device mesh on the cpu platform, and
  ``backend()`` reports ``cpu`` so dispatch (pallas/donation/limb
  engine) takes the host-safe path even when an accelerator exists.
- ``auto``   — ``mesh`` when more than one healthy device is visible,
  else ``single`` (the pre-policy behavior, preserved).

Sentinel-aware: the PR-15 per-device probe rows
(``ceph_backend_device_*``; kernel_telemetry.BackendSentinel.devices())
mark individual sick chips, and ``healthy_devices()`` subtracts them —
a failed probe SHRINKS the mesh and the pool budget instead of wedging
the data plane on a dead chip.  ``failed=`` pins additional devices out
(tests and the degraded-topology smoke inject deterministic failures
without running a sentinel cycle).
"""
from __future__ import annotations

import threading

TOPOLOGIES = ("auto", "single", "mesh", "cpu")


class DevicePolicy:
    """Resolved device-topology policy (see module docstring).

    Cheap value object: every accessor re-resolves against the live
    runtime + sentinel state, so a probe failure between two calls is
    reflected immediately (the mesh a caller already built keeps its
    devices — shrink applies to NEW grants, like OSDMap epochs).
    """

    def __init__(self, topology: str = "auto", mesh_shape: int = 0,
                 failed: tuple[str, ...] | frozenset[str] = ()):
        if topology not in TOPOLOGIES:
            raise ValueError(
                f"device_topology={topology!r}: want one of {TOPOLOGIES}")
        self.topology = topology
        self.mesh_shape = int(mesh_shape)
        #: "platform:id" rows pinned failed regardless of the sentinel
        self._failed = frozenset(failed)

    @classmethod
    def from_conf(cls, conf) -> "DevicePolicy":
        """The two declared knobs, read ONCE at daemon start."""
        return cls(topology=str(conf.get("device_topology")),
                   mesh_shape=int(conf.get("device_mesh_shape")))

    def __repr__(self) -> str:
        return (f"DevicePolicy(topology={self.topology!r}, "
                f"mesh_shape={self.mesh_shape}, "
                f"failed={sorted(self._failed)})")

    # -- the ambient probes: allowed HERE only (CL9 policy allowlist) ------
    def all_devices(self) -> list:
        """The raw runtime device list this variant draws from."""
        import jax

        if self.topology == "cpu":
            # true CPU fallback: prefer the host platform's devices even
            # on an accelerator box; some runtimes expose no cpu client,
            # so fall back to the default list (backend() still reports
            # cpu, which is what dispatch keys on)
            try:
                return list(jax.devices("cpu"))
            except RuntimeError:
                return list(jax.devices())
        return list(jax.devices())

    def backend(self) -> str:
        """The backend name dispatch decisions key on (`_want_pallas`,
        donation, the CRUSH limb/i64 engine pick).  The cpu variant
        pins it to "cpu" — that is the fallback's whole point."""
        import jax

        if self.topology == "cpu":
            return "cpu"
        return jax.default_backend()

    # -- health ------------------------------------------------------------
    def _sentinel_failed(self) -> set[str]:
        """Device rows the backend sentinel's last probe cycle marked
        sick ("platform:id").  Lazy import: kernel_telemetry's probes
        resolve their platform through THIS module."""
        try:
            from .kernel_telemetry import SENTINEL

            rows = SENTINEL.devices()
        except Exception:
            return set()
        return {r.get("device") for r in rows or ()
                if not r.get("ok", True)}

    def healthy_devices(self) -> list:
        """all_devices() minus sentinel-failed and pinned-failed rows.
        Never empty: with EVERY device marked sick the policy keeps
        device 0 — the sentinel's is_degraded latch already reroutes the
        data plane, and a zero-device mesh would just move the wedge."""
        bad = self._failed | self._sentinel_failed()
        devs = self.all_devices()
        keep = [d for d in devs if f"{d.platform}:{d.id}" not in bad]
        return keep or devs[:1]

    # -- grants ------------------------------------------------------------
    def _grant(self, devs: list) -> list:
        """Apply the variant + mesh_shape cap to a candidate list."""
        if not devs:
            return devs
        if self.topology in ("single", "cpu"):
            return devs[:1]
        if self.topology == "auto" and len(devs) == 1:
            return devs[:1]
        if self.mesh_shape > 0:
            return devs[: self.mesh_shape]
        return devs

    def devices(self) -> list:
        """The devices this policy grants: healthy, variant-filtered."""
        return self._grant(self.healthy_devices())

    def default_device(self):
        return self.devices()[0]

    def mesh_size(self) -> int:
        return len(self.devices())

    def platform(self) -> str:
        """Platform of the first granted device (the telemetry probe's
        answer; touching the device list is deliberate — a wedged
        runtime must hang the sentinel's disposable worker here)."""
        return self.default_device().platform

    def mesh(self, n_devices: int | None = None, axis: str = "shard_len"):
        """A jax.sharding.Mesh over the granted devices.  ``n_devices``
        keeps parallel.mesh.make_mesh's historical cap semantics (take
        the first n); the cpu variant always yields a 1-device mesh."""
        devs = self.devices()
        if n_devices is not None:
            devs = devs[:n_devices]
        return mesh_over(devs, axis)

    # -- budgets -----------------------------------------------------------
    def pool_budget(self, max_bytes: int) -> int:
        """The device pool's effective residency bound under this
        policy: the configured max spread evenly over the FULL granted
        mesh, times the devices still healthy.  A sentinel device
        failure thus shrinks the pool's footprint with the mesh instead
        of letting survivors inherit the dead chip's share; a fully
        healthy mesh gets the whole configured bound."""
        full = self._grant(self.all_devices())
        if not full:
            return int(max_bytes)
        per_dev = int(max_bytes) // len(full)
        live = min(len(self.devices()), len(full))
        return max(per_dev, per_dev * live)


def mesh_over(devices, axis: str):
    """Build a 1-axis Mesh over an explicit device list/array.  The
    ``Mesh`` constructor lives here so every construction site in the
    package is inside the policy module (CL9 ambient-mesh); callers that
    re-axis an existing mesh (parallel.mesh.distributed_decode) route
    through this instead of constructing ambiently."""
    import numpy as np
    from jax.sharding import Mesh

    return Mesh(np.asarray(devices).reshape(-1), (axis,))


# -- process-wide injection (first daemon wins, like the sentinel) ---------
_LOCK = threading.Lock()
_POLICY: DevicePolicy | None = None
_conf_applied = False


def configure_device_policy(policy: DevicePolicy) -> DevicePolicy:
    """Install the daemon's policy process-wide.  FIRST daemon in the
    process wins (kernel dispatch and the pool are process-wide, so a
    second daemon must not silently re-topologize them); returns the
    policy actually in force so the caller can hold the real one."""
    global _POLICY, _conf_applied
    with _LOCK:
        if not _conf_applied:
            _conf_applied = True
            _POLICY = policy
        return _POLICY


def get_device_policy() -> DevicePolicy:
    """The process-wide policy; before any daemon configures one, a
    default ``auto`` policy (the historical ambient behavior)."""
    global _POLICY
    with _LOCK:
        if _POLICY is None:
            _POLICY = DevicePolicy()
        return _POLICY


def reset_device_policy() -> None:
    """Drop the process-wide policy (tests / smoke harnesses only)."""
    global _POLICY, _conf_applied
    with _LOCK:
        _POLICY = None
        _conf_applied = False
