"""lockdep — runtime lock-order cycle detection (reference:
src/common/lockdep.cc + common/mutex_debug.h; SURVEY.md §5.2).

Named locks register acquisition-order edges (held -> acquiring) in one
process-global graph; an acquisition that would close a cycle — the ABBA
pattern that deadlocks two threads — raises immediately on the FIRST
occurrence, deterministically, instead of deadlocking intermittently
under load.  Like the reference, ordering is tracked by lock NAME (class
of lock), not instance, so "osd::pg" vs "osd::pgs" ordering violations
are caught regardless of which PG's lock is involved; recursive
re-acquisition of the same named lock by its holder is allowed (RLock
semantics, matching the daemons' usage).

Disabled (the default) the wrappers add one dict lookup per acquire;
enable via lockdep.enable() or the `lockdep` config option at daemon
construction.
"""
from __future__ import annotations

import threading

_enabled = False
_graph_lock = threading.Lock()
# name -> set of names acquired WHILE name was held (order edges)
_order: dict[str, set[str]] = {}
_held = threading.local()

# cephrace seam (qa/race/runtime.py): when a race session is active its
# runtime is installed here and every LockdepLock acquire/release (and
# the Condition save/restore protocol) reports in.  None (the default)
# costs one global load + is-None test per operation.
_race_hooks = None


def set_race_hooks(hooks) -> None:
    global _race_hooks
    _race_hooks = hooks


class LockOrderViolation(RuntimeError):
    pass


def enable() -> None:
    global _enabled
    _enabled = True


def disable() -> None:
    global _enabled
    _enabled = False


def reset() -> None:
    """Clear the recorded order graph (between tests)."""
    with _graph_lock:
        _order.clear()


def enabled() -> bool:
    return _enabled


def _holding() -> list[str]:
    stack = getattr(_held, "stack", None)
    if stack is None:
        stack = _held.stack = []
    return stack


def _would_cycle(frm: str, to: str) -> bool:
    """Is `to` already ordered before `frm` (path to -> ... -> frm)?"""
    seen = set()
    work = [to]
    while work:
        n = work.pop()
        if n == frm:
            return True
        if n in seen:
            continue
        seen.add(n)
        work.extend(_order.get(n, ()))
    return False


def _on_acquire(name: str) -> None:
    stack = _holding()
    if name in stack:  # recursive re-entry of the same class: allowed
        stack.append(name)
        return
    with _graph_lock:
        for held in set(stack):
            if held == name:
                continue
            if _would_cycle(held, name):
                raise LockOrderViolation(
                    f"lock order violation: acquiring {name!r} while "
                    f"holding {held!r}, but {name!r} -> ... -> {held!r} "
                    f"is already recorded"
                )
            _order.setdefault(held, set()).add(name)
    stack.append(name)


def _on_release(name: str) -> None:
    stack = _holding()
    # release order need not be LIFO; drop the most recent entry
    for i in range(len(stack) - 1, -1, -1):
        if stack[i] == name:
            del stack[i]
            return


class LockdepLock:
    """RLock with lockdep order tracking (reference: ceph::mutex which is
    mutex_debug under lockdep builds)."""

    def __init__(self, name: str):
        self.name = name
        # the one legitimately raw lock in the tree: this IS the
        # primitive make_lock wraps
        self._lock = threading.RLock()  # noqa: CL1

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        h = _race_hooks
        if h is not None:
            # may raise DeadlockError on a cycle — but only for an
            # UNBOUNDED acquire; try-locks and timed acquires resolve on
            # their own and must not crash (MonClient.ensure_connection's
            # blocking=False probe exists precisely to never stall)
            h.before_acquire(self, blocking and timeout < 0)
        if _enabled:
            _on_acquire(self.name)
        got = self._lock.acquire(blocking, timeout)
        if not got and _enabled:
            _on_release(self.name)
        if h is not None:
            if got:
                h.after_acquire(self)
            else:
                h.acquire_failed(self)
        return got

    def release(self) -> None:
        h = _race_hooks
        if h is not None:
            h.before_release(self)
        self._lock.release()
        if _enabled:
            _on_release(self.name)

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    # Condition protocol — threading.Condition(make_lock(...)) must fully
    # release a reentrant lock across wait() and restore its recursion
    # depth after; without these Condition falls back to a non-reentrant
    # try-acquire probe that misreads a held RLock as un-owned.  The
    # lockdep held-stack tracks the same save/restore so order edges are
    # not recorded against a lock the thread no longer holds.
    def _is_owned(self) -> bool:
        return self._lock._is_owned()

    def _release_save(self):
        state = self._lock._release_save()
        depth = 0
        if _enabled:
            stack = _holding()
            while self.name in stack:
                stack.remove(self.name)
                depth += 1
        h = _race_hooks
        if h is not None:
            h.cond_release_save(self)
        return (state, depth)

    def _acquire_restore(self, saved) -> None:
        state, depth = saved
        self._lock._acquire_restore(state)
        if _enabled and depth:
            _holding().extend([self.name] * depth)
        h = _race_hooks
        if h is not None:
            h.cond_acquire_restore(self)


def make_lock(name: str) -> LockdepLock:
    return LockdepLock(name)
