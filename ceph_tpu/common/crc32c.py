"""crc32c with the reference's seed convention (reference:
src/common/crc32c.cc :: ceph_crc32c — running crc in, no final inversion).

Fast path is the native library (native/crc32c.cc, SSE4.2 when built with
-march=native); fallback is a table-driven Python implementation so the
framework stays importable where the native toolchain is absent.
"""
from __future__ import annotations

_POLY = 0x82F63B78  # reflected Castagnoli


def _make_table() -> list[int]:
    table = []
    for i in range(256):
        c = i
        for _ in range(8):
            c = (_POLY ^ (c >> 1)) if c & 1 else c >> 1
        table.append(c)
    return table


_TABLE = _make_table()


def _crc32c_py(data, seed: int) -> int:
    crc = seed & 0xFFFFFFFF
    for b in memoryview(data).cast("B"):
        crc = _TABLE[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc


_native = None
_native_checked = False


def crc32c(data, seed: int = 0xFFFFFFFF) -> int:
    """crc32c of a bytes-like object, seeded (default -1, the reference's
    usual seed for frame/checksum computation)."""
    global _native, _native_checked
    if not _native_checked:
        _native_checked = True
        try:
            from .. import native_oracle

            if native_oracle.available():
                _native = native_oracle.crc32c
        except Exception:
            _native = None
    if _native is not None:
        return _native(data, seed)
    return _crc32c_py(data, seed)
