"""Framework option declarations (reference: src/common/options/*.yaml.in —
global.yaml.in, osd.yaml.in, mon.yaml.in; SURVEY.md §5.6).

One flat table; names follow the reference's where the concept matches so
operators recognize them.  Only options the framework's runtime actually
reads are declared — the table grows with the subsystems.
"""
from __future__ import annotations

from .config import Option, OptionTable


def default_options() -> OptionTable:
    return OptionTable(
        [
            # -- identity / logging (reference: global.yaml.in) -----------
            Option("name", str, "client.admin", "entity name, type.id"),
            Option("log_to_stderr", bool, False, "emit log lines to stderr"),
            Option("log_ring_size", int, 10000, "in-memory log ring entries",
                   min=0, runtime=True),
            Option("debug_default", int, 1, "default subsystem debug level",
                   min=0, max=20, runtime=True),
            Option("debug_osd", int, 1, "osd debug level", min=0, max=20,
                   runtime=True),
            Option("debug_mon", int, 1, "mon debug level", min=0, max=20,
                   runtime=True),
            Option("debug_ms", int, 0, "messenger debug level", min=0, max=20,
                   runtime=True),
            Option("debug_ec", int, 1, "erasure-code debug level", min=0,
                   max=20, runtime=True),
            Option("debug_crush", int, 1, "crush debug level", min=0, max=20,
                   runtime=True),
            Option("admin_socket", str, "", "admin socket path ('' disables)"),
            Option("failpoint", str, "",
                   "semicolon-separated name=spec failpoint assignments "
                   "('osd.ec.shard_read=error;msgr.frame.send="
                   "every(5,error)'), applied to the process-wide "
                   "failpoint registry scoped to this daemon's hits "
                   "(common/failpoint.py; docs/fault_injection.md)",
                   runtime=True),
            Option("lockdep", bool, False,
                   "runtime lock-order cycle detection (reference: "
                   "src/common/lockdep.cc)"),
            # -- tracing (reference: jaeger_tracing_enable) ----------------
            Option("trace_enabled", bool, False,
                   "arm cephtrace: distributed op spans (client -> OSD "
                   "-> replicas), stage latency histograms, and the "
                   "dump_tracing admin command (docs/tracing.md).  "
                   "Disabled, the data plane pays one attribute check "
                   "per hook (reference: jaeger_tracing_enable)"),
            Option("trace_sampling_rate", float, 1.0,
                   "head-based sampling: fraction of client ops that "
                   "mint a trace context at Objecter.op_submit (one "
                   "coin flip per logical op; resends ride the original "
                   "decision).  1.0 traces everything, 0.01 is the "
                   "production-viability setting benched in PERF.md",
                   min=0.0, max=1.0, runtime=True),
            Option("trace_tail_latency_ms", float, 0.0,
                   "tail sampling (cephmeter): ops that LOST the head "
                   "coin flip still buffer their spans provisionally, "
                   "and one whose completion latency crosses this many "
                   "milliseconds (or the OSD's osd_op_complaint_time) "
                   "is promoted into the trace buffer retroactively — "
                   "a p99 straggler keeps its trace even at "
                   "trace_sampling_rate=0 (docs/observability.md).  "
                   "0 disables tail sampling", min=0.0, runtime=True),
            # -- messenger (reference: ms_* in global.yaml.in) -------------
            Option("ms_connect_timeout", float, 10.0,
                   "seconds to wait for a connect", min=0.0),
            Option("ms_tcp_nodelay", bool, True, "disable Nagle"),
            Option("ms_compress", str, "none",
                   "on-wire frame compression algorithm (reference: "
                   "ms_osd_compress_mode + compressor registry)",
                   enum=("none", "zlib", "snappy", "zstd", "lz4")),
            Option("ms_compress_force", bool, False,
                   "allow non-zlib wire compression (no handshake "
                   "negotiation: every peer must carry the module)"),
            Option("ms_compress_min_size", int, 4096,
                   "frames below this many payload bytes stay raw "
                   "(reference: ms_osd_compress_min_size)", min=0),
            Option("ms_max_frame_len", int, 1 << 28,
                   "reject frames larger than this", min=4096),
            Option("ms_inject_socket_failures", int, 0,
                   "fault injection: drop the connection every ~N frames "
                   "(0 = off; reference: ms_inject_socket_failures). "
                   "LEGACY surface routed through the failpoint registry "
                   "as 'msgr.frame.send' = every(N,error)",
                   min=0, runtime=True),
            # -- throttles -------------------------------------------------
            Option("objecter_eagain_patience", float, 0.0,
                   "seconds to keep retrying -EAGAIN refusals (degraded "
                   "pg, peering) before surfacing the error; 0 = auto "
                   "(max(60, 2x op timeout))", min=0.0, runtime=True),
            Option("objecter_inflight_op_bytes", int, 100 << 20,
                   "client dirty-data throttle", min=0),
            Option("objecter_inflight_ops", int, 1024,
                   "client in-flight op throttle", min=0),
            # -- osd (reference: osd.yaml.in) ------------------------------
            Option("osd_data", str, "",
                   "data directory for file-backed objectstores "
                   "('' with objectstore=filestore is a config error)"),
            Option("osd_pool_default_size", int, 3, "replica count", min=1),
            Option("osd_pool_default_min_size", int, 0,
                   "min replicas to serve I/O (0 = size - size/2)", min=0),
            Option("osd_pool_default_pg_num", int, 32, "PGs per new pool",
                   min=1),
            Option("osd_heartbeat_interval", float, 2.0,
                   "seconds between peer pings", min=0.05, runtime=True),
            Option("osd_heartbeat_grace", float, 6.0,
                   "seconds without a ping reply before reporting a peer "
                   "(grace/interval silent pings trigger the report)",
                   min=0.1, runtime=True),
            Option("osd_op_thread_timeout", float, 15.0,
                   "healthy-worker watchdog grace: ops executing longer "
                   "than this are logged by the tick loop (reference: "
                   "HeartbeatMap)", min=0.1, runtime=True),
            Option("osd_max_backfills", int, 1,
                   "concurrent backfills per OSD", min=1, runtime=True),
            Option("osd_recovery_max_active", int, 3,
                   "concurrent recovery ops per OSD", min=1, runtime=True),
            Option("osd_repair_cost_aware", bool, True,
                   "plan repair reads against MEASURED per-helper cost "
                   "(cephstorm): helpers whose piggybacked sub-op "
                   "telemetry shows a deep mClock queue or a degraded "
                   "backend sentinel are pruned from the "
                   "minimum_to_decode candidate set, falling back to "
                   "the full set (index order) when telemetry is "
                   "absent/stale or too few cheap helpers remain",
                   runtime=True),
            Option("osd_repair_helper_max_qlen", int, 16,
                   "piggybacked mClock queue depth at/over which a "
                   "helper shard is considered EXPENSIVE for repair "
                   "reads (osd_repair_cost_aware)", min=1,
                   runtime=True),
            Option("osd_repair_telemetry_ttl", float, 30.0,
                   "seconds a peer's piggybacked load row stays fresh "
                   "enough to steer repair planning; older rows are "
                   "ignored (the helper is kept)", min=0.1,
                   runtime=True),
            Option("osd_op_history_size", int, 20,
                   "historic ops kept for dump_historic_ops", min=0,
                   runtime=True),
            Option("osd_op_complaint_time", float, 30.0,
                   "age at which an in-flight op is slow", min=0.0,
                   runtime=True),
            Option("osd_slow_op_window", float, 60.0,
                   "seconds a COMPLETED slow op stays in the sticky "
                   "SLOW_OPS count (cephmeter: a straggler finishing "
                   "between two mgr report polls must not vanish from "
                   "the health check before the digest samples it)",
                   min=0.0, runtime=True),
            Option("osd_client_io_accounting", bool, True,
                   "per-(client,pool) I/O accounting table on every OSD "
                   "(cephmeter: ops/bytes/admission/queue/e2e latency "
                   "histograms as labeled prometheus series — the "
                   "future mClock QoS tags; common/io_accounting.py, "
                   "docs/observability.md).  Disabled = no table, no "
                   "stamping"),
            Option("osd_client_io_top_k", int, 64,
                   "bounded cardinality of the per-OSD accounting "
                   "table: at most this many live (client,pool) "
                   "entries; overflow evicts the least-recently-used "
                   "non-heavy-hitter into the _other_ bucket (sums "
                   "preserved)", min=1),
            Option("osd_mclock_client_classes", bool, True,
                   "cephqos: route client ops through DYNAMIC per-"
                   "(client,pool) mClock classes keyed by the cephmeter "
                   "accounting identity, so the QoS controller can "
                   "retune individual tenants (osd/scheduler.py; "
                   "docs/qos.md).  False = the single static 'client' "
                   "class (pre-cephqos behavior).  Read at daemon "
                   "construction"),
            Option("osd_mclock_client_slots", int, 8,
                   "concurrent client-op executions per OSD for ops in "
                   "DYNAMIC per-client classes: while all slots are "
                   "busy, dynamic classes are ineligible to dequeue, "
                   "so the mClock tags (not thread-spawn order) decide "
                   "who runs next under saturation.  Internal OSD-to-"
                   "OSD forwards and background work are exempt.  0 = "
                   "unbounded (pre-cephqos).  Read at daemon "
                   "construction", min=0),
            Option("osd_mclock_max_client_classes", int, 32,
                   "bounded cardinality of dynamic per-client mClock "
                   "classes per OSD: past the bound the least-recently-"
                   "enqueued class retires into the _default_ catch-all "
                   "(queued ops and stats fold, counts conserved).  "
                   "Read at daemon construction", min=1),
            Option("osd_subop_reply_timeout", float, 10.0,
                   "DEFAULT seconds a primary waits for one shard "
                   "sub-op reply before treating the shard as failed; "
                   "governs waits without an explicit per-path budget "
                   "(client EC write/read fan-out) — scrub/recovery "
                   "paths keep their own longer budgets. Thrash tests "
                   "shrink it so injected partitions stall client ops "
                   "briefly, not for the full default", min=0.1,
                   runtime=True),
            Option("osd_deep_scrub_interval", float, 0.0,
                   "seconds between periodic deep scrubs (0 disables)",
                   min=0.0, runtime=True),
            Option("osd_debug_inject_read_err", bool, False,
                   "fault injection: EC shard reads return EIO "
                   "(reference: bluestore_debug_inject_read_err). "
                   "LEGACY surface routed through the failpoint registry "
                   "as 'osd.ec.shard_read' = error",
                   runtime=True),
            Option("osd_debug_inject_dispatch_delay", float, 0.0,
                   "fault injection: sleep before dispatch (seconds). "
                   "LEGACY surface routed through the failpoint registry "
                   "as 'osd.dispatch' = delay(sec)",
                   min=0.0, runtime=True),
            # -- mon (reference: mon.yaml.in) ------------------------------
            Option("mon_osd_down_out_interval", float, 600.0,
                   "seconds from down to out", min=0.0, runtime=True),
            Option("mon_osd_min_down_reporters", int, 2,
                   "distinct reporters to mark an osd down", min=1,
                   runtime=True),
            Option("mon_tick_interval", float, 1.0, "mon tick seconds",
                   min=0.05),
            Option("mon_max_pg_per_osd", int, 250,
                   "pg-count sanity limit at pool create", min=1),
            # -- auth (reference: auth_* in global.yaml.in) ----------------
            Option("auth_cluster_required", str, "none",
                   "authentication for intra-cluster + client connections",
                   enum=("none", "cephx")),
            Option("auth_shared_secret", str, "",
                   "base64 cluster secret (cephx key analog; "
                   "auth.generate_secret() makes one)"),
            Option("auth_service_ticket_ttl", float, 3600.0,
                   "lifetime of mon-minted service tickets, seconds "
                   "(reference: auth_service_ticket_ttl)", min=0.1,
                   runtime=True),
            Option("rgw_enable_sigv4", bool, False,
                   "require AWS SigV4 request signing at the S3 gateway "
                   "(keys derive from the cephx cluster secret; False = "
                   "anonymous zone, the pre-r4 behavior)"),
            # -- mgr (reference: mgr.yaml.in) ------------------------------
            Option("mgr_addr", str, "",
                   "host:port daemons send MMgrReport to ('' disables)",
                   runtime=True),
            Option("mgr_report_interval", float, 2.0,
                   "seconds between daemon perf reports to the mgr",
                   min=0.1, runtime=True),
            Option("mgr_tick_interval", float, 2.0, "mgr tick seconds",
                   min=0.05),
            Option("mgr_modules", str,
                   "status,prometheus,balancer,iostat,quota,"
                   "metrics_history,qos,progress,placement",
                   "comma-separated modules the mgr hosts"),
            Option("rgw_lc_interval", float, 5.0,
                   "seconds between lifecycle passes (upstream: daily)",
                   min=0.1),
            Option("mgr_digest_interval", float, 2.0,
                   "seconds between mgr->mon status digests", min=0.1),
            Option("mgr_quota_interval", float, 2.0,
                   "seconds between pool-quota enforcement passes", min=0.1),
            Option("mgr_prometheus_port", int, 0,
                   "prometheus exporter port (0 = ephemeral)", min=0),
            Option("mgr_balancer_interval", float, 10.0,
                   "seconds between balancer passes", min=0.1, runtime=True),
            Option("mgr_balancer_active", bool, True,
                   "balancer applies upmaps (false = dry-run)",
                   runtime=True),
            # -- cephplace placement observability (mgr/placement_module)
            Option("mgr_placement_interval", float, 5.0,
                   "seconds between periodic placement scans (each scan "
                   "maps every pool through crush_do_rule_batch, scores "
                   "the distribution vs the weight-proportional ideal, "
                   "and exports ceph_placement_* series; an osdmap "
                   "epoch change scans immediately and forecasts the "
                   "remap as ceph_remap_* / `placement diff`)", min=0.1,
                   runtime=True),
            Option("mgr_placement_max_deviation", float, 8.0,
                   "largest per-OSD deviation from the ideal PG-shard "
                   "share a pool may carry (in PG shards) before the "
                   "mon raises PG_IMBALANCE — only while the balancer "
                   "is idle or off; an actively-converging balancer "
                   "suppresses the check (docs/observability.md)",
                   min=0.0, runtime=True),
            Option("mgr_stale_report_age", float, 30.0,
                   "drop daemon reports older than this", min=1.0),
            # -- cephheal progress (mgr/progress_module.py) ----------------
            Option("mgr_progress_interval", float, 1.0,
                   "seconds between progress-module passes over the "
                   "OSDs' pg_info degraded/misplaced counts (per-PG "
                   "recovery/backfill completion fractions + ETAs; "
                   "`ceph progress`, the `ceph status` recovery line)",
                   min=0.1, runtime=True),
            Option("mgr_recovery_stalled_grace", float, 10.0,
                   "seconds a PG may sit degraded with ~zero drain "
                   "(and no cluster recovery-op rate) before the "
                   "progress module marks it stalled and the mon "
                   "raises RECOVERY_STALLED", min=0.5, runtime=True),
            Option("mgr_metrics_history_samples", int, 512,
                   "samples kept per (daemon, counter) series in the "
                   "mgr metrics-history ring (mgr/metrics_history.py — "
                   "the substrate iostat and the future QoS controller "
                   "query; one sample lands per MMgrReport)", min=2),
            Option("mgr_metrics_history_max_series", int, 8192,
                   "total (daemon, counter) series the metrics-history "
                   "store tracks; series beyond the cap are dropped "
                   "and counted (bounded memory under runaway "
                   "cardinality)", min=1),
            # -- cephqos controller (mgr/qos_module.py; docs/qos.md) -------
            Option("mgr_qos_interval", float, 2.0,
                   "seconds between QoS controller ticks (observe "
                   "telemetry -> plan -> push MQoSSettings)", min=0.1,
                   runtime=True),
            Option("mgr_qos_active", bool, False,
                   "QoS controller pushes retuned settings to OSDs "
                   "(false = observe and export ceph_qos_* series "
                   "only — the balancer's dry-run precedent)",
                   runtime=True),
            Option("mgr_qos_queue_p99_target_ms", float, 50.0,
                   "stage_queue p99 the controller holds the write "
                   "path under: overshoot shrinks the coalescing "
                   "window multiplicatively; headroom lets it follow "
                   "the arrival-matched ideal", min=0.1, runtime=True),
            Option("mgr_qos_queue_p99_recover_frac", float, 0.8,
                   "hysteresis band for window regrowth: after a "
                   "queue-p99 backoff the controller grows the "
                   "coalescing window again only once p99 has "
                   "recovered below this fraction of the target "
                   "(backing off at >target while regrowing at "
                   "<=target limit-cycles the window under steady "
                   "load — the cephstorm oscillation invariant)",
                   min=0.1, max=1.0, runtime=True),
            Option("mgr_qos_window_min_ms", float, 0.5,
                   "lower clamp on controller-set ec_batch_window_ms",
                   min=0.0, runtime=True),
            Option("mgr_qos_window_max_ms", float, 20.0,
                   "upper clamp on controller-set ec_batch_window_ms",
                   min=0.1, runtime=True),
            Option("mgr_qos_stripes_min", int, 8,
                   "lower clamp on controller-set ec_batch_max_stripes",
                   min=1, runtime=True),
            Option("mgr_qos_stripes_max", int, 256,
                   "upper clamp on controller-set ec_batch_max_stripes",
                   min=1, runtime=True),
            Option("mgr_qos_bully_factor", float, 4.0,
                   "a client whose write-op rate exceeds this factor "
                   "x the median of its peers is classed HEAVY (low "
                   "mClock weight, no hard limit — work-conserving)",
                   min=1.0, runtime=True),
            Option("mgr_qos_heavy_weight", float, 5.0,
                   "mClock weight the controller assigns heavy "
                   "clients (vs the per-client default of 10).  The "
                   "default is deliberately gentle — half weight plus "
                   "the victims' reservation floor measured enough to "
                   "triple victim p99 without costing aggregate "
                   "throughput (qa/qos_smoke.py); crank it down for "
                   "harder isolation", min=0.001, runtime=True),
            Option("mgr_qos_victim_reservation", float, 40.0,
                   "ops/s reservation floor the controller assigns "
                   "non-heavy clients while any heavy client is "
                   "present", min=0.0, runtime=True),
            Option("mgr_dashboard_port", int, 0,
                   "dashboard HTTP port (0 = ephemeral)"),
            Option("mgr_devicehealth_self_heal", bool, True,
                   "devicehealth marks failing OSDs out automatically "
                   "(reference: devicehealth self_heal)", runtime=True),
            Option("mgr_devicehealth_mark_out_threshold", int, 8,
                   "cumulative integrity errors before devicehealth "
                   "marks an OSD out", min=1, runtime=True),
            Option("mgr_devicehealth_min_in_ratio", float, 0.75,
                   "refuse self-heal mark-outs that would drop the "
                   "in-OSD ratio below this (reference: "
                   "mon_osd_min_in_ratio)", min=0.0, max=1.0,
                   runtime=True),
            Option("mon_target_pg_per_osd", int, 100,
                   "PGs per OSD the autoscaler aims for (reference: "
                   "mon_target_pg_per_osd)", min=1, runtime=True),
            Option("mgr_pg_autoscale_threshold", float, 3.0,
                   "adjust only when off-target by this factor "
                   "(reference: the autoscaler's 3x rule)", min=1.0,
                   runtime=True),
            Option("mgr_pg_autoscale_interval", float, 15.0,
                   "seconds between autoscaler passes", min=0.1,
                   runtime=True),
            Option("mgr_pg_autoscale_active", bool, False,
                   "autoscaler applies pg_num changes (false = advise)",
                   runtime=True),
            # -- mds (reference: mds.yaml.in) ------------------------------
            Option("debug_mds", int, 1, "mds debug level", min=0, max=20,
                   runtime=True),
            Option("mds_journal_segment_events", int, 128,
                   "journal events per segment before a dirfrag flush + "
                   "trim (reference: mds_log_events_per_segment)", min=1),
            Option("mds_reconnect_timeout", float, 5.0,
                   "seconds a restarted MDS waits for a prior writer "
                   "session to re-flush its buffered caps before evicting "
                   "it (reference: mds_reconnect_timeout)", min=0.0,
                   runtime=True),
            # -- objectstore (reference: bluestore options) ----------------
            Option("objectstore", str, "memstore", "backend for new OSDs",
                   enum=("memstore", "kstore", "filestore", "bluestore")),
            Option("osd_fsck_on_mount", bool, False,
                   "run a store fsck pass at OSD boot, failing the boot "
                   "on errors (reference: bluestore_fsck_on_mount)"),
            Option("bluestore_block_size", int, 1 << 30,
                   "bluestore device-file size in bytes (reference: "
                   "bluestore_block_size)", min=1 << 20),
            Option("objectstore_wal_sync", bool, True,
                   "fsync the WAL on every commit"),
            Option("objectstore_checksum", bool, True,
                   "crc32c-verify payloads on read"),
            Option("objectstore_compression", str, "none",
                   "at-rest object-data compression for file-backed "
                   "stores (reference: bluestore_compression_algorithm)",
                   enum=("none", "zlib", "snappy", "zstd", "lz4")),
            # -- ec / tpu --------------------------------------------------
            Option("ec_batch_window_ms", float, 2.0,
                   "max milliseconds the write batcher holds an EC "
                   "encode batch open waiting for more stripes (the "
                   "absolute coalescing timer; an inter-arrival gap of "
                   "window/8 flushes early once arrivals stop).  0 "
                   "disables coalescing: every op encodes inline "
                   "(osd/write_batcher.py; docs/write_path.md)",
                   min=0.0, runtime=True),
            Option("ec_batch_max_stripes", int, 64,
                   "stripes that flush an encode batch immediately "
                   "(size cap of the write batcher's coalescing window)",
                   min=1, runtime=True),
            Option("ec_batch_max_bytes", int, 8 << 20,
                   "data bytes per fused device encode batch; larger "
                   "flushes split on stripe boundaries and double-"
                   "buffer through ops/pipeline.stream_encode.  Also "
                   "sizes the batcher's admission throttle (4x this) — "
                   "the backpressure that blocks op threads, and "
                   "through them client admission, when the encode "
                   "stage falls behind.  0 = unbounded", min=0,
                   runtime=True),
            Option("ec_batch_client_max_share", float, 0.5,
                   "cephqos: fraction of the write batcher's admission "
                   "budget one (client,pool) identity may hold; ops "
                   "past the share wait for their OWN bytes to drain "
                   "before entering the global FIFO throttle, so one "
                   "bulk streamer cannot crowd small writers out of "
                   "admission (osd/write_batcher.py; docs/qos.md).  "
                   ">= 1.0 disables the per-client share",
                   min=0.01, runtime=True),
            Option("osd_read_batch_window_ms", float, 2.0,
                   "cephread: max milliseconds the READ batcher holds a "
                   "gather/decode batch open waiting for more ops (the "
                   "absolute coalescing timer; an inter-arrival gap of "
                   "window/8 flushes early once arrivals stop).  0 "
                   "disables coalescing: every read gathers and decodes "
                   "inline (osd/read_batcher.py; docs/read_path.md)",
                   min=0.0, runtime=True),
            Option("osd_read_batch_max_ops", int, 64,
                   "read ops that flush a gather batch immediately (size "
                   "cap of the read batcher's coalescing window)",
                   min=1, runtime=True),
            Option("osd_read_batch_max_bytes", int, 8 << 20,
                   "estimated gather + decode bytes per coalesced read "
                   "flush; also sizes the read batcher's admission "
                   "throttle (4x this) — the backpressure that blocks op "
                   "threads when the read plane falls behind.  0 = "
                   "unbounded", min=0, runtime=True),
            Option("osd_read_cache_bytes", int, 0,
                   "cephread: byte bound on the primary's hot-object "
                   "read cache (osd/read_cache.py — LRU, invalidated by "
                   "the write path's version bump and validated against "
                   "the pg log's newest object version on every hit).  "
                   "0 disables the cache", min=0, runtime=True),
            Option("osd_read_cache_promote_ops", int, 8,
                   "cephmeter-driven promotion threshold: an object is "
                   "cached only when its reading (client,pool) identity "
                   "has at least this many accumulated read ops in the "
                   "per-client accounting table (the heavy-hitter rows) "
                   "— a cold scan never churns the cache.  0 promotes "
                   "every full-object read", min=0, runtime=True),
            Option("ec_device_pool", bool, True,
                   "cephdma: device-resident stripe-buffer pool + fully "
                   "async encode path (ops/device_pool.py; "
                   "docs/write_path.md).  On: batcher flushes pack into "
                   "pooled device buffers, encode through the donated "
                   "jit, keep parity device-resident through demux, and "
                   "sync only at each op's encode_wait commit point.  "
                   "Off (or whenever the backend sentinel has latched "
                   "degraded): the historical synchronous flush — pack "
                   "on host, device round trip, fetch on the flusher.  "
                   "Read at daemon start into the process-wide pool and "
                   "re-read per flush by the batcher; an injectargs "
                   "flip also reconfigures the process-wide pool "
                   "(OSD-registered observer — disengages the stream/"
                   "decode/recovery paths too; last write wins, like "
                   "ec_kernel)", runtime=True),
            Option("ec_device_pool_max_bytes", int, 256 << 20,
                   "bound on the device stripe pool's free-list "
                   "residency; past it least-recently-used buffer "
                   "geometries evict.  Read once at daemon start into "
                   "the process-wide pool (first daemon wins, like the "
                   "sentinel policy) — restart to change", min=0),
            Option("kernel_telemetry", bool, True,
                   "per-kernel dispatch telemetry registry "
                   "(common/kernel_telemetry.py): invocation counts, "
                   "compile/execute log2 histograms, bytes, achieved "
                   "GiB/s, backend per call, fallback-latch events — "
                   "dump_kernel_telemetry / prometheus.  Process-wide; "
                   "False disarms it (disabled dispatch pays one "
                   "attribute check, measured in PERF.md)"),
            Option("backend_sentinel_interval", float, 5.0,
                   "seconds between backend liveness probes by the "
                   "health sentinel (latches the TPU_BACKEND_DEGRADED "
                   "cluster state instead of wedging callers; "
                   "docs/observability.md).  0 disables the sentinel.  "
                   "Read ONCE at daemon start into the injected policy "
                   "(first daemon in the process wins) — restart to "
                   "change", min=0.0),
            Option("backend_sentinel_timeout", float, 2.0,
                   "fast-fail budget for one backend probe: a probe "
                   "that has not answered within this latches "
                   "`degraded` (the wedged-tunnel signature is a hang, "
                   "not an error).  A cold process gets a boot grace "
                   "(max(15s, 5x) until the runtime first answers) so "
                   "jax init cannot latch a false degrade.  Read once "
                   "at daemon start, like the interval", min=0.1),
            Option("device_topology", str, "auto",
                   "cephtopo: device-topology policy variant for this "
                   "process (common/device_policy.py): single = default "
                   "chip only; mesh = multi-chip mesh over the healthy "
                   "devices; cpu = CPU-fallback 1-device mesh (dispatch "
                   "treats the backend as cpu — no pallas, no donation, "
                   "no limb engine); auto = mesh when more than one "
                   "healthy device is visible, else single.  Sentinel "
                   "per-device probe failures (ceph_backend_device_*) "
                   "shrink the granted mesh and the pool budget instead "
                   "of wedging.  Read ONCE at daemon start into the "
                   "process-wide injected policy (first daemon wins, "
                   "like the sentinel) — restart to change",
                   enum=("auto", "single", "mesh", "cpu")),
            Option("device_mesh_shape", int, 0,
                   "cephtopo: cap on the mesh axis length (device "
                   "count) the device policy grants; 0 = every healthy "
                   "device.  Read once at daemon start with "
                   "device_topology", min=0),
            Option("ec_kernel", str, "auto",
                   "encode kernel selection for the default (jax) EC "
                   "plugin: oracle/numpy swap the backend, xla/pallas "
                   "force the GF kernel path (process-wide, mirrors "
                   "CEPH_TPU_EC_KERNEL); auto keeps TPU dispatch. "
                   "Applied when a pool's codec is first compiled — set "
                   "it at daemon construction, not injectargs",
                   enum=("auto", "xla", "pallas", "oracle", "numpy")),
        ]
    )
