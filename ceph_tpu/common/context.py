"""CephContext — one process-entity's runtime state (reference:
src/common/ceph_context.{h,cc} :: CephContext; created by global_init in
src/global/global_init.cc, SURVEY.md §3.4).

Bundles the layered config, log, perf-counter collection, heartbeat map and
(optional) admin socket that every daemon and client library hangs off.
Contexts are explicit — no process-global — so tests can run many entities
(mon + N osds + clients) in one interpreter, which is how the ring-2
single-host cluster tests work (SURVEY.md §4).
"""
from __future__ import annotations

import os
from .admin_socket import AdminSocket
from .config import Config, LEVEL_CMDLINE
from .heartbeat import HeartbeatMap
from .log import Log
from .options import default_options
from .perf_counters import PerfCountersCollection


class CephContext:
    def __init__(self, name: str = "client.admin", overrides: dict | None = None):
        self.conf = Config(default_options())
        self.conf.set("name", name, level=LEVEL_CMDLINE)
        if overrides:
            for k, v in overrides.items():
                self.conf.set(k, v, level=LEVEL_CMDLINE)
        self.log = Log(self.conf, ring_size=self.conf.get("log_ring_size"))
        if self.conf.get("lockdep"):
            from . import lockdep

            lockdep.enable()
        self.perf = PerfCountersCollection()
        self.heartbeat_map = HeartbeatMap()
        if self.conf.get("trace_enabled"):
            # the tracer is process-wide (spans carry the entity label,
            # so a LocalCluster's daemons stay attributable); any armed
            # context switches it on for the process
            from .tracer import TRACER

            TRACER.enable(True)
        if not self.conf.get("kernel_telemetry"):
            # the kernel telemetry registry is process-wide like the
            # tracer, but default-ON (observability parity with perf
            # counters); a context disabling it disarms the process —
            # disabled dispatch pays one attribute check (PERF.md)
            from .kernel_telemetry import TELEMETRY

            TELEMETRY.enable(False)
        # mon-minted service tickets for cephx clients without the cluster
        # secret: {service: {"ticket": blob_hex, "session_key": hex}};
        # runtime credentials, not config (reference: the client-side
        # CephXTicketManager)
        self.tickets: dict[str, dict] = {}
        # fault injection: route this context's inject options (legacy +
        # the generic `failpoint` option) through the process-wide
        # failpoint registry, scoped to hits tagged with this context
        from . import failpoint as _failpoint

        _failpoint.bind_config(self)
        self.admin_socket: AdminSocket | None = None
        sock_path = self.conf.get_expanded("admin_socket")
        if sock_path:
            self.admin_socket = AdminSocket(sock_path)
            self._register_default_commands()
            _failpoint.register_admin_commands(self)
            self.admin_socket.start()

    @property
    def name(self) -> str:
        return self.conf.get("name")

    def dout(self, subsys: str, level: int, message: str) -> None:
        self.log.dout(subsys, level, message)

    def _register_default_commands(self) -> None:
        ask = self.admin_socket
        assert ask is not None
        ask.register_command(
            "perf dump", lambda c: self.perf.dump(), "dump perf counters"
        )
        ask.register_command(
            "perf schema", lambda c: self.perf.schema(), "perf counter schema"
        )
        ask.register_command(
            "config show", lambda c: self.conf.show_config(), "show config"
        )
        ask.register_command(
            "config diff", lambda c: self.conf.diff(), "non-default config"
        )
        ask.register_command(
            "config get",
            lambda c: {c["var"]: self.conf.get(c["var"])},
            "config get var=<name>",
        )
        ask.register_command(
            "config set", self._config_set_cmd,
            "config set var=<name> val=<value> (runtime-updatable options only)",
        )
        ask.register_command(
            "log dump", lambda c: [e.format() for e in self.log.recent(100)],
            "recent log ring entries",
        )
        ask.register_command(
            "dump_tracing", self._dump_tracing_cmd,
            "cephtrace spans/events for this daemon "
            "(all=true for the whole process; format=perfetto for "
            "Chrome-trace JSON loadable in ui.perfetto.dev)",
        )
        ask.register_command(
            "dump_kernel_telemetry", self._dump_kernel_telemetry_cmd,
            "per-kernel dispatch telemetry + backend sentinel state "
            "(process-wide; docs/observability.md)",
        )
        ask.register_command(
            "clear_kernel_fallback", self._clear_kernel_fallback_cmd,
            "un-latch the codec's XLA fallback without a restart: the "
            "next auto-mode dispatch retries the Pallas kernel",
        )

    def _dump_kernel_telemetry_cmd(self, cmd: dict) -> object:
        from .kernel_telemetry import dump_kernel_telemetry

        return dump_kernel_telemetry()

    def _clear_kernel_fallback_cmd(self, cmd: dict) -> dict:
        import sys as _sys

        from .kernel_telemetry import TELEMETRY

        cleared = TELEMETRY.clear_fallback()
        # un-latch the bitplane module only if the data plane loaded it:
        # importing ops.bitplane pulls jax into processes (mon-only, CLI)
        # that never run kernels
        bp = _sys.modules.get("ceph_tpu.ops.bitplane")
        if bp is not None:
            cleared = bp.clear_fallback_latch() or cleared
        return {"cleared": bool(cleared)}

    def _dump_tracing_cmd(self, cmd: dict) -> object:
        from .tracer import dump_tracing

        entity = None if cmd.get("all") else self.name
        return dump_tracing(entity=entity,
                            fmt=str(cmd.get("format", "spans")))

    def _config_set_cmd(self, cmd: dict) -> dict:
        # live `config set` honors the option's runtime flag (reference:
        # non-runtime options need a daemon restart; mon `config set` warns)
        name = cmd["var"]
        if not self.conf.table.get(name).runtime:
            raise ValueError(
                f"option {name!r} is not runtime-updatable; restart required"
            )
        return {name: self.conf.set(name, cmd["val"])}

    def shutdown(self) -> None:
        from . import failpoint as _failpoint

        _failpoint.unbind(self)
        if self.admin_socket is not None:
            self.admin_socket.stop()
            self.admin_socket = None
