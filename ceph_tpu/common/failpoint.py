"""Named failpoints — a process-wide, seeded fault-injection registry
(reference: the scattered ceph `*_inject_*` debug options, unified the way
FreeBSD's fail(9) / libfiu structure theirs; qa/tasks/thrashosds.py is the
driver that composes them, here ceph_tpu/qa/thrasher.py).

A subsystem marks an injection site with a NAME and whatever context it
can cheaply supply::

    from ceph_tpu.common.failpoint import failpoint, FailpointError

    try:
        failpoint("osd.store.write_before_commit", entity=self.whoami)
    except FailpointError:
        ...  # behave as if the fault really happened

and an operator (or the thrasher) arms the site with an ACTION SPEC::

    registry().set("osd.store.write_before_commit", "times(2,error)")
    registry().add("msgr.frame.recv", "error",
                   match={"entity": "osd.1", "peer": "osd.4"})  # netsplit

Specs form a tiny combinator language, every stochastic choice drawn from
ONE registry-wide seeded RNG so a failure schedule replays bit-exactly:

    off                    never fire
    error                  raise FailpointError
    error(OSError)         raise a named builtin instead
    delay(0.25)            sleep 0.25 s, then continue
    crash                  raise FailpointCrash (simulated daemon death)
    prob(0.3, SPEC)        fire SPEC with probability 0.3 (seeded RNG)
    times(2, SPEC)         fire SPEC for the first 2 matched hits, then off
    every(5, SPEC)         fire SPEC on every 5th matched hit

Entries are settable three ways (all land in the same registry):
- ``Config``: the ``failpoint`` option ("name=spec;name=spec", scoped to
  that daemon's hits) plus the subsumed legacy options
  ``ms_inject_socket_failures``, ``osd_debug_inject_read_err`` and
  ``osd_debug_inject_dispatch_delay`` (see LEGACY_OPTIONS);
- the admin socket: ``failpoint set|list|rm|seed`` and ``injectargs``;
- ``ceph_tpu.tools.ceph_cli``: ``ceph daemon <asok> failpoint ...`` /
  ``ceph daemon <asok> injectargs --option value``.

The registry is process-wide because a LocalCluster runs many daemons in
one interpreter: cross-daemon schedules (netsplits between OSD pairs) need
one place to stand.  Per-daemon scoping comes from the ``match`` dict —
config/admin-socket entries match on the owning CephContext, thrasher
entries on entity names.
"""
from __future__ import annotations

import random
import threading
import time

from .lockdep import make_lock


class FailpointError(Exception):
    """Default exception an ``error`` action raises at a failpoint site."""


class FailpointCrash(FailpointError):
    """Raised by the ``crash`` action — simulated sudden daemon death.
    Sites re-raise it past their normal fault handling so it propagates
    like a real abort would."""


# builtin exceptions an `error(Name)` spec may raise; a closed set so a
# spec arriving over the admin socket can't name arbitrary attributes
_ERROR_TYPES = {
    "FailpointError": FailpointError,
    "OSError": OSError,
    "IOError": OSError,
    "ConnectionError": ConnectionError,
    "TimeoutError": TimeoutError,
    "RuntimeError": RuntimeError,
    "ValueError": ValueError,
}


class FailpointSpecError(ValueError):
    pass


# -- actions ---------------------------------------------------------------
class _Action:
    """fire(rng) decides whether this hit takes the effect (mutating any
    combinator state); invoke(name) performs it.  Split so the registry
    can run fire() under its lock but invoke() (which may sleep or raise)
    outside it."""

    def fire(self, rng: random.Random) -> bool:
        return True

    def invoke(self, name: str) -> None:
        pass

    def describe(self) -> str:
        return "off"


class _Off(_Action):
    def fire(self, rng):
        return False


class _Error(_Action):
    def __init__(self, exc_name: str = "FailpointError"):
        if exc_name not in _ERROR_TYPES:
            raise FailpointSpecError(
                f"unknown error type {exc_name!r}; one of "
                f"{sorted(_ERROR_TYPES)}"
            )
        self.exc_name = exc_name

    def invoke(self, name):
        raise _ERROR_TYPES[self.exc_name](f"failpoint {name!r} injected error")

    def describe(self):
        return ("error" if self.exc_name == "FailpointError"
                else f"error({self.exc_name})")


class _Delay(_Action):
    def __init__(self, sec: float):
        if sec < 0:
            raise FailpointSpecError(f"negative delay {sec}")
        self.sec = sec

    def invoke(self, name):
        time.sleep(self.sec)

    def describe(self):
        return f"delay({self.sec:g})"


class _Crash(_Action):
    def invoke(self, name):
        raise FailpointCrash(f"failpoint {name!r} injected crash")

    def describe(self):
        return "crash"


class _Prob(_Action):
    def __init__(self, p: float, inner: _Action):
        if not 0.0 <= p <= 1.0:
            raise FailpointSpecError(f"probability {p} outside [0, 1]")
        self.p = p
        self.inner = inner

    def fire(self, rng):
        # draw unconditionally so the RNG stream depends only on the hit
        # sequence, not on nested combinator state — replays stay aligned
        draw = rng.random()
        return draw < self.p and self.inner.fire(rng)

    def invoke(self, name):
        self.inner.invoke(name)

    def describe(self):
        return f"prob({self.p:g},{self.inner.describe()})"


class _Times(_Action):
    """Fire the inner spec for the first n EXECUTIONS, then go dormant."""

    def __init__(self, n: int, inner: _Action):
        if n < 0:
            raise FailpointSpecError(f"negative times count {n}")
        self.n = n
        self.done = 0
        self.inner = inner

    def fire(self, rng):
        if self.done >= self.n:
            return False
        if not self.inner.fire(rng):
            return False
        self.done += 1
        return True

    def invoke(self, name):
        self.inner.invoke(name)

    def describe(self):
        return f"times({self.n},{self.inner.describe()})"


class _Every(_Action):
    """Fire the inner spec on every nth matched hit (legacy
    ms_inject_socket_failures cadence)."""

    def __init__(self, n: int, inner: _Action):
        if n < 1:
            raise FailpointSpecError(f"every() needs n >= 1, got {n}")
        self.n = n
        self.count = 0
        self.inner = inner

    def fire(self, rng):
        self.count += 1
        return self.count % self.n == 0 and self.inner.fire(rng)

    def invoke(self, name):
        self.inner.invoke(name)

    def describe(self):
        return f"every({self.n},{self.inner.describe()})"


def _split_args(body: str) -> list[str]:
    """Split a combinator body on top-level commas only."""
    parts, depth, cur = [], 0, []
    for ch in body:
        if ch == "(":
            depth += 1
        elif ch == ")":
            depth -= 1
            if depth < 0:
                raise FailpointSpecError(f"unbalanced parens in {body!r}")
        if ch == "," and depth == 0:
            parts.append("".join(cur))
            cur = []
        else:
            cur.append(ch)
    if depth:
        raise FailpointSpecError(f"unbalanced parens in {body!r}")
    parts.append("".join(cur))
    return parts


def parse_spec(spec: str) -> _Action:
    """Parse one action spec string into a (stateful) action tree."""
    s = spec.strip()
    if not s:
        raise FailpointSpecError("empty failpoint spec")
    if "(" not in s:
        if s == "off":
            return _Off()
        if s == "error":
            return _Error()
        if s == "crash":
            return _Crash()
        raise FailpointSpecError(f"bad failpoint spec {s!r}")
    head, _, rest = s.partition("(")
    head = head.strip()
    if not rest.endswith(")"):
        raise FailpointSpecError(f"bad failpoint spec {s!r}")
    body = rest[:-1].strip()
    if head == "error":
        return _Error(body)
    if head == "delay":
        try:
            return _Delay(float(body))
        except ValueError as e:
            raise FailpointSpecError(f"bad delay {body!r}") from e
    args = _split_args(body)
    if len(args) != 2:
        raise FailpointSpecError(
            f"{head}() takes (arg, spec), got {len(args)} args in {s!r}"
        )
    inner = parse_spec(args[1])
    try:
        if head == "prob":
            return _Prob(float(args[0]), inner)
        if head == "times":
            return _Times(int(args[0]), inner)
        if head == "every":
            return _Every(int(args[0]), inner)
    except FailpointSpecError:
        raise
    except ValueError as e:
        raise FailpointSpecError(f"bad {head}() argument {args[0]!r}") from e
    raise FailpointSpecError(f"unknown combinator {head!r}")


# -- registry --------------------------------------------------------------
class _Entry:
    __slots__ = ("eid", "spec", "action", "match", "hits")

    def __init__(self, eid: int, spec: str, action: _Action,
                 match: dict | None):
        self.eid = eid
        self.spec = spec
        self.action = action
        self.match = dict(match) if match else None
        self.hits = 0

    def matches(self, ctx: dict) -> bool:
        if self.match is None:
            return True
        return all(ctx.get(k) == v for k, v in self.match.items())


class FailpointRegistry:
    """Process-wide named-failpoint table.  All combinator state and the
    RNG live behind one lock; effects (sleep/raise) run outside it."""

    def __init__(self, seed: int | None = None):
        self._lock = make_lock("failpoint::registry")
        self._entries: dict[str, list[_Entry]] = {}
        self._rng = random.Random(seed)
        self._next_id = 1

    # -- configuration ----------------------------------------------------
    def seed(self, n: int) -> None:
        """Reset the RNG driving prob() so a schedule replays bit-exactly
        (combined with re-arming the same specs in the same order)."""
        with self._lock:
            self._rng = random.Random(n)

    def set(self, name: str, spec: str, match: dict | None = None) -> int:
        """Replace this owner's assignment for `name` ("off" clears it).
        Ownership is the match dict: entries under the same name with a
        DIFFERENT match (another daemon's config, a thrasher netsplit)
        are left alone.  Returns the entry id (0 when cleared)."""
        action = parse_spec(spec)
        norm = dict(match) if match else None
        with self._lock:
            entries = [
                e for e in self._entries.get(name, []) if e.match != norm
            ]
            if not isinstance(action, _Off):
                e = _Entry(self._next_id, spec, action, norm)
                self._next_id += 1
                entries.append(e)
            else:
                e = None
            if entries:
                self._entries[name] = entries
            else:
                self._entries.pop(name, None)
            return e.eid if e else 0

    def add(self, name: str, spec: str, match: dict | None = None) -> int:
        """Append an entry (several matchers can coexist under one name —
        the netsplit shape).  Returns its id for targeted remove()."""
        action = parse_spec(spec)
        if isinstance(action, _Off):
            return 0
        with self._lock:
            e = _Entry(self._next_id, spec, action, match)
            self._next_id += 1
            self._entries.setdefault(name, []).append(e)
            return e.eid

    def remove(self, name: str, eid: int | None = None,
               match: dict | None = None) -> int:
        """Drop entries under `name`: all of them, one by id, or those
        whose match dict equals `match`.  Returns how many went."""
        with self._lock:
            entries = self._entries.get(name, [])
            if eid is None and match is None:
                self._entries.pop(name, None)
                return len(entries)
            keep = [
                e for e in entries
                if not ((eid is not None and e.eid == eid)
                        or (match is not None and e.match == match))
            ]
            removed = len(entries) - len(keep)
            if keep:
                self._entries[name] = keep
            else:
                self._entries.pop(name, None)
            return removed

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()

    def configured(self, name: str) -> bool:
        return name in self._entries

    def list(self) -> dict[str, list[dict]]:
        """Serializable view (the admin-socket `failpoint list` payload)."""
        with self._lock:
            return {
                name: [
                    {
                        "id": e.eid,
                        "spec": e.spec,
                        "state": e.action.describe(),
                        "match": (
                            {k: str(v) for k, v in e.match.items()}
                            if e.match else None
                        ),
                        "hits": e.hits,
                    }
                    for e in entries
                ]
                for name, entries in sorted(self._entries.items())
            }

    # -- the hot path ------------------------------------------------------
    def hit(self, name: str, **ctx) -> None:
        """Evaluate a failpoint site.  The first matching entry whose
        action elects to fire performs its effect: error/crash raise,
        delay sleeps, off does nothing."""
        entries = self._entries.get(name)
        if not entries:
            return
        fired: _Action | None = None
        with self._lock:
            for e in entries:
                if not e.matches(ctx):
                    continue
                e.hits += 1
                if e.action.fire(self._rng):
                    fired = e.action
                    break
        if fired is not None:
            fired.invoke(name)


_registry = FailpointRegistry()


# The catalogue of every failpoint site the daemons mark — the single
# list docs/fault_injection.md's name table and the thrasher's arming
# code are held to.  cephlint CL4 (ceph_tpu/qa/analyzer) statically
# cross-checks sites <-> this set <-> the docs table, so adding a site
# without registering + documenting it fails tier-1.
KNOWN_FAILPOINTS = frozenset({
    "msgr.frame.send",
    "msgr.frame.recv",
    "osd.dispatch",
    "osd.ec.shard_read",
    "osd.write_batcher.flush",
    "osd.read_batcher.gather",
    "osd.recovery.push",
    "osd.recovery.pull",
    "osd.recovery.tick",
    "osd.scrub.start",
    "osd.scrub.shard",
    "osd.store.write_before_commit",
    "osd.store.write_after_commit",
    "mon.paxos.propose",
    "mon.paxos.commit",
    "mon.election.start",
    "mon.tick",
    "tpu.backend.probe",
    "storm.stub.recv",
})


def registry() -> FailpointRegistry:
    return _registry


def failpoint(name: str, **ctx) -> None:
    """Module-level site marker — `failpoint("osd.scrub.shard", ...)`."""
    _registry.hit(name, **ctx)


# -- Config integration ----------------------------------------------------
# Legacy scattered inject options, subsumed: option name -> (failpoint
# name, value -> spec).  The observer installed by bind_config() keeps the
# registry in step with the option, scoped to the owning context's hits.
LEGACY_OPTIONS = {
    "ms_inject_socket_failures": (
        "msgr.frame.send",
        lambda v: f"every({int(v)},error)" if int(v) else "off",
    ),
    "osd_debug_inject_read_err": (
        "osd.ec.shard_read",
        lambda v: "error" if v else "off",
    ),
    "osd_debug_inject_dispatch_delay": (
        "osd.dispatch",
        lambda v: f"delay({float(v)})" if float(v) > 0 else "off",
    ),
}


def parse_failpoint_option(value: str) -> list[tuple[str, str]]:
    """Validate a `failpoint` option string ("name=spec;name=spec") in
    full — every spec must parse — and return its (name, spec) pairs.
    Shared by the config observer and injectargs pre-validation so a bad
    spec can never take effect partially."""
    parts: list[tuple[str, str]] = []
    for part in (value or "").split(";"):
        part = part.strip()
        if not part:
            continue
        name, sep, spec = part.partition("=")
        if not sep:
            raise FailpointSpecError(f"expected name=spec, got {part!r}")
        parse_spec(spec.strip())
        parts.append((name.strip(), spec.strip()))
    return parts


def bind_config(cct) -> None:
    """Route a context's config through the registry: the legacy inject
    options and the generic `failpoint` option, each scoped (via match)
    to hits tagged with this context.  Applies current values
    immediately, then tracks changes through the observer."""
    conf = cct.conf
    match = {"cct": cct}
    # names the `failpoint` option currently owns for this context — so a
    # later shorter option string retires exactly the names it armed
    # (legacy options share the match dict, so retired names re-sync
    # from any still-set legacy option below)
    option_owned: set[str] = set()

    def apply_failpoint_option(value: str) -> None:
        # validated in full before arming anything: a bad spec mid-list
        # must not leave earlier assignments armed but outside
        # option_owned (unretirable through the option)
        parts = parse_failpoint_option(value)
        seen = set()
        for name, spec in parts:
            _registry.set(name, spec, match=match)
            seen.add(name)
        for name in option_owned - seen:
            _registry.remove(name, match=match)
            # a legacy inject option may have replaced (same match) the
            # entry this name tracked; removing it above must not leave
            # that still-set option silently disarmed — re-sync it
            for opt, (fp_name, to_spec) in LEGACY_OPTIONS.items():
                if fp_name == name and opt in conf.table:
                    v = conf.get(opt)
                    if v != conf.table.get(opt).default:
                        _registry.set(fp_name, to_spec(v), match=match)
        option_owned.clear()
        option_owned.update(seen)

    def on_change(name: str, value) -> None:
        if name == "failpoint":
            apply_failpoint_option(value)
            return
        fp_name, to_spec = LEGACY_OPTIONS[name]
        _registry.set(fp_name, to_spec(value), match=match)

    names = [n for n in LEGACY_OPTIONS if n in conf.table] + ["failpoint"]
    conf.add_observer(names, on_change)
    for n in names:
        v = conf.get(n)
        if v != conf.table.get(n).default:
            on_change(n, v)


def unbind(cct) -> None:
    """Drop every registry entry this context's config installed (called
    from CephContext.shutdown so dead daemons don't leave armed
    failpoints behind)."""
    match = {"cct": cct}
    for name in list(_registry.list()):
        _registry.remove(name, match=match)


def apply_runtime_options(cct, pairs) -> dict:
    """Validated runtime config application — the injectargs core,
    shared by the admin-socket command and the QoS controller's
    MQoSSettings push (both are 'injectargs over a different
    transport').  Validates the WHOLE list (existence, runtime flag,
    value parse) before applying anything: a bad option mid-list must
    not leave the earlier ones silently applied behind an error."""
    pairs = [(name, value) for name, value in pairs]
    for name, value in pairs:
        opt = cct.conf.table.get(name)
        if not opt.runtime:
            raise ValueError(
                f"option {name!r} is not runtime-updatable"
            )
        opt.parse(value)
        if name == "failpoint":
            # opt.parse only checks it's a string; the observer
            # raising on a bad spec mid-apply would break the
            # nothing-applied-on-error contract
            parse_failpoint_option(value)
    return {
        name: cct.conf.set(name, value) for name, value in pairs
    }


def register_admin_commands(cct) -> None:
    """`failpoint set|add|rm|list|seed` + `injectargs` on a daemon's admin
    socket (reference: ceph's `ceph daemon ... config set` /
    injectargs)."""
    ask = cct.admin_socket
    match = {"cct": cct}

    def _fp_cmd(cmd: dict):
        sub = cmd.get("sub", "list")
        if sub == "list":
            return _registry.list()
        if sub == "seed":
            _registry.seed(int(cmd["seed"]))
            return {"seeded": int(cmd["seed"])}
        name = cmd.get("name", "")
        if not name:
            raise ValueError("failpoint name required")
        if sub == "set":
            eid = _registry.set(name, cmd.get("spec", "off"), match=match)
            return {name: cmd.get("spec", "off"), "id": eid}
        if sub == "add":
            eid = _registry.add(name, cmd.get("spec", "off"), match=match)
            return {name: cmd.get("spec", "off"), "id": eid}
        if sub == "rm":
            # scoped like set/add: retire THIS daemon's entry only, so an
            # operator's rm can't silently heal a thrasher netsplit or
            # another daemon's config-armed failpoint under the same name
            n = _registry.remove(name, match=match)
            return {"removed": n}
        raise ValueError(f"unknown failpoint subcommand {sub!r}")

    def _injectargs(cmd: dict):
        """`injectargs --name value [--name=value ...]`: runtime config
        application, the reference's `ceph daemon ... injectargs`."""
        argv = (cmd.get("args") or "").split()
        pairs: list[tuple[str, str]] = []
        i = 0
        while i < len(argv):
            arg = argv[i]
            if not arg.startswith("--"):
                raise ValueError(f"expected --option, got {arg!r}")
            body = arg[2:]
            if "=" in body:
                name, _, value = body.partition("=")
                i += 1
            else:
                name = body
                if i + 1 >= len(argv):
                    raise ValueError(f"--{name} needs a value")
                value = argv[i + 1]
                i += 2
            pairs.append((name.replace("-", "_"), value))
        return apply_runtime_options(cct, pairs)

    ask.register_command(
        "failpoint", _fp_cmd,
        "failpoint sub=set|add|rm|list|seed [name=<fp> spec=<spec>] "
        "[seed=<n>] — set/add/rm act on this daemon's entries",
    )
    ask.register_command(
        "injectargs", _injectargs,
        "injectargs args='--option value ...' (runtime options only)",
    )
