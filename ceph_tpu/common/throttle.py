"""Backpressure primitives (reference: src/common/Throttle.{h,cc} ::
Throttle; SURVEY.md §2.7).

Used by the Objecter (in-flight op/byte caps) and the OSD (recovery /
backfill limits).  `get` blocks until the budget fits, FIFO-fair the way the
reference's cond-per-waiter list is; `get_or_fail` never blocks.
"""
from __future__ import annotations

from collections import deque
from threading import Condition

from .lockdep import make_lock


class Throttle:
    def __init__(self, name: str, max_count: int):
        self.name = name
        self._max = max_count
        self._count = 0
        self._lock = make_lock("throttle::budget")
        self._cond = Condition(self._lock)
        self._waitq: deque[object] = deque()  # FIFO ticket queue

    @property
    def max(self) -> int:
        return self._max

    @property
    def current(self) -> int:
        return self._count

    def reset_max(self, max_count: int) -> None:
        with self._cond:
            self._max = max_count
            self._cond.notify_all()

    def _fits(self, c: int) -> bool:
        if self._max <= 0:  # 0 disables throttling, as in the reference
            return True
        return self._count + c <= self._max or self._count == 0

    def get(self, c: int = 1, timeout: float | None = None) -> bool:
        """Block until c units fit, FIFO behind earlier waiters so a large
        request cannot be starved by a stream of small ones; oversized
        requests (> max) are admitted alone rather than deadlocking
        (reference behavior)."""
        assert c >= 0
        ticket = object()
        with self._cond:
            self._waitq.append(ticket)
            try:
                ok = self._cond.wait_for(
                    lambda: self._waitq[0] is ticket and self._fits(c),
                    timeout=timeout,
                )
                if not ok:
                    return False
                self._count += c
                return True
            finally:
                self._waitq.remove(ticket)
                self._cond.notify_all()

    def get_or_fail(self, c: int = 1) -> bool:
        with self._cond:
            if self._waitq or not self._fits(c):
                return False
            self._count += c
            return True

    def put(self, c: int = 1) -> int:
        with self._cond:
            assert self._count >= c, f"throttle {self.name} put {c} > held {self._count}"
            self._count -= c
            self._cond.notify_all()
            return self._count

    def past_midpoint(self) -> bool:
        with self._lock:
            return self._max > 0 and self._count >= self._max / 2
