"""Layered config with a typed option table (reference: src/common/config.{h,cc}
:: md_config_t; option declarations in src/common/options/*.yaml.in).

Sources layer exactly as the reference's: compiled defaults < conf file <
mon centralized config < environment < CLI overrides < runtime `set`.
Options carry type, default, bounds/enum, a `runtime`-updatable flag and a
doc string; observers get change notification (reference: md_config_obs_t).

EC profiles are deliberately NOT here — they are per-pool key=value maps in
the OSDMap (SURVEY.md §5.6), handled by ceph_tpu.ec.registry.
"""
from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Any, Callable

from .lockdep import make_lock

# Source levels, low to high precedence (reference: config layering §5.6).
LEVEL_DEFAULT = 0
LEVEL_FILE = 1
LEVEL_MON = 2
LEVEL_ENV = 3
LEVEL_CMDLINE = 4
LEVEL_OVERRIDE = 5

_LEVEL_NAMES = {
    LEVEL_DEFAULT: "default",
    LEVEL_FILE: "file",
    LEVEL_MON: "mon",
    LEVEL_ENV: "env",
    LEVEL_CMDLINE: "cmdline",
    LEVEL_OVERRIDE: "override",
}


class ConfigError(ValueError):
    pass


@dataclass(frozen=True)
class Option:
    """One declared option (reference: Option in src/common/options.h)."""

    name: str
    type: type  # int | float | bool | str
    default: Any
    doc: str = ""
    min: float | None = None
    max: float | None = None
    enum: tuple[str, ...] | None = None
    runtime: bool = False  # updatable on a live daemon

    def parse(self, value: Any) -> Any:
        try:
            if self.type is bool and isinstance(value, str):
                low = value.strip().lower()
                if low in ("true", "1", "yes", "on"):
                    value = True
                elif low in ("false", "0", "no", "off"):
                    value = False
                else:
                    raise ValueError(value)
            else:
                value = self.type(value)
        except (TypeError, ValueError) as e:
            raise ConfigError(
                f"option {self.name}: cannot parse {value!r} as {self.type.__name__}"
            ) from e
        if self.min is not None and value < self.min:
            raise ConfigError(f"option {self.name}: {value} < min {self.min}")
        if self.max is not None and value > self.max:
            raise ConfigError(f"option {self.name}: {value} > max {self.max}")
        if self.enum is not None and value not in self.enum:
            raise ConfigError(
                f"option {self.name}: {value!r} not in {list(self.enum)}"
            )
        return value


class OptionTable:
    """Declared-options registry (reference: the generated option table)."""

    def __init__(self, options: list[Option] = ()):  # type: ignore[assignment]
        self._options: dict[str, Option] = {}
        for o in options:
            self.add(o)

    def add(self, opt: Option) -> None:
        if opt.name in self._options:
            raise ConfigError(f"duplicate option {opt.name}")
        opt.parse(opt.default)  # defaults must self-validate
        self._options[opt.name] = opt

    def get(self, name: str) -> Option:
        try:
            return self._options[name]
        except KeyError:
            raise ConfigError(f"unknown option {name!r}") from None

    def __contains__(self, name: str) -> bool:
        return name in self._options

    def names(self) -> list[str]:
        return sorted(self._options)


@dataclass
class _Value:
    by_level: dict[int, Any] = field(default_factory=dict)


class Config:
    """Layered values over an OptionTable, with observers."""

    def __init__(self, table: OptionTable, values: dict[str, Any] | None = None):
        self._table = table
        self._values: dict[str, _Value] = {}
        self._observers: list[tuple[tuple[str, ...], Callable[[str, Any], None]]] = []
        self._lock = make_lock("config::values")
        if values:
            for k, v in values.items():
                self.set(k, v, level=LEVEL_OVERRIDE)

    @property
    def table(self) -> OptionTable:
        return self._table

    def get(self, name: str) -> Any:
        opt = self._table.get(name)
        with self._lock:
            val = self._values.get(name)
            if val and val.by_level:
                return val.by_level[max(val.by_level)]
        return opt.default

    def get_expanded(self, name: str) -> Any:
        """get() plus metavariable expansion for path-like string
        options (reference: config $name/$pid expansion in
        md_config_t::expand_meta) — so one cluster-wide override like
        `$name.asok` yields a distinct path per daemon."""
        val = self.get(name)
        if isinstance(val, str) and "$" in val:
            val = (val.replace("$name", str(self.get("name")))
                      .replace("$pid", str(os.getpid())))
        return val

    def __getitem__(self, name: str) -> Any:
        return self.get(name)

    def source(self, name: str) -> str:
        """Which layer supplies the effective value."""
        self._table.get(name)
        with self._lock:
            val = self._values.get(name)
            level = max(val.by_level) if val and val.by_level else LEVEL_DEFAULT
        return _LEVEL_NAMES[level]

    def set(self, name: str, value: Any, level: int = LEVEL_OVERRIDE) -> Any:
        opt = self._table.get(name)
        parsed = opt.parse(value)
        with self._lock:
            before = self.get(name)
            self._values.setdefault(name, _Value()).by_level[level] = parsed
            after = self.get(name)
            observers = list(self._observers) if after != before else []
        for keys, cb in observers:
            if name in keys:
                cb(name, after)
        return parsed

    def rm(self, name: str, level: int) -> None:
        self._table.get(name)
        with self._lock:
            val = self._values.get(name)
            if val:
                val.by_level.pop(level, None)

    # -- sources ----------------------------------------------------------
    def parse_file(self, path: str) -> None:
        """Minimal ini-style conf (reference: ceph.conf): `name = value`
        lines; `[section]` headers are accepted and ignored (the framework
        is single-entity per process); `#`/`;` comments."""
        with open(path) as f:
            for lineno, line in enumerate(f, 1):
                line = line.split("#", 1)[0].split(";", 1)[0].strip()
                if not line or line.startswith("["):
                    continue
                if "=" not in line:
                    raise ConfigError(f"{path}:{lineno}: expected name = value")
                name, value = (s.strip() for s in line.split("=", 1))
                name = name.replace(" ", "_")
                if name in self._table:
                    self.set(name, value, level=LEVEL_FILE)

    def parse_env(self, environ: dict[str, str] | None = None) -> None:
        """CEPH_TPU_<OPTION_NAME> environment overrides."""
        environ = os.environ if environ is None else environ
        for name in self._table.names():
            env_key = "CEPH_TPU_" + name.upper()
            if env_key in environ:
                self.set(name, environ[env_key], level=LEVEL_ENV)

    def parse_argv(self, argv: list[str]) -> list[str]:
        """Consume `--name value` / `--name=value` pairs for declared
        options; returns unrecognized args for the caller's own parser."""
        rest: list[str] = []
        i = 0
        while i < len(argv):
            arg = argv[i]
            if arg.startswith("--"):
                body = arg[2:]
                if "=" in body:
                    name, value = body.split("=", 1)
                    name = name.replace("-", "_")
                    if name in self._table:
                        self.set(name, value, level=LEVEL_CMDLINE)
                        i += 1
                        continue
                else:
                    name = body.replace("-", "_")
                    if name in self._table and i + 1 < len(argv):
                        self.set(name, argv[i + 1], level=LEVEL_CMDLINE)
                        i += 2
                        continue
            rest.append(arg)
            i += 1
        return rest

    # -- observation / introspection --------------------------------------
    def add_observer(self, names: list[str], cb: Callable[[str, Any], None]) -> None:
        """cb(name, new_value) after an effective-value change (reference:
        md_config_obs_t::handle_conf_change)."""
        for n in names:
            self._table.get(n)
        with self._lock:
            self._observers.append((tuple(names), cb))

    def remove_observer(self, cb: Callable[[str, Any], None]) -> None:
        """Deregister a conf-change observer (identity match on cb): a
        stopped daemon must not keep reacting to injectargs through a
        callback that closes over dead state."""
        with self._lock:
            self._observers = [
                (names, c) for names, c in self._observers if c is not cb
            ]

    def show_config(self) -> dict[str, Any]:
        return {n: self.get(n) for n in self._table.names()}

    def diff(self) -> dict[str, dict[str, Any]]:
        """Non-default values with their source (reference: `config diff`)."""
        out = {}
        for n in self._table.names():
            v = self.get(n)
            if v != self._table.get(n).default:
                out[n] = {"value": v, "source": self.source(n)}
        return out
