"""RBD image journaling + mirroring (reference: librbd's journaling
feature — Journal<I> write-ahead event records — and the rbd-mirror
daemon's journal-based one-way replay; SURVEY.md §2.6).

Journal layout (per image, in the image's own pool):

- ``journal.{image}``          header as OMAP keys ("next_tid",
                               "client.{id}" commit positions,
                               "trimmed") — per-key atomic with one
                               writer per key, so the mirror daemon
                               thread and the primary's client thread
                               never lose each other's updates
- ``journal.{image}.{tid:016x}``  one JSON record per event, written
                               BEFORE the mutation applies (write-ahead;
                               every record is an idempotent
                               absolute-state setter, so replay after a
                               crash between append and apply is safe).

Mirroring model (the rbd-mirror daemon, collapsed to a pull replayer):

- enabling mirroring marks the image ``mirror: {enabled, primary,
  global_id}`` and implies the journaling feature;
- a ``MirrorReplayer(src_io, dst_io)`` registers as a journal client on
  each mirror-enabled primary image in the source pool, creates the
  same-name NON-PRIMARY replica in the destination pool (same layout),
  and replays journal records from its commit position — writes,
  resizes, snap create/remove — advancing the position and trimming
  records every registered client has committed;
- non-primary replicas refuse client writes (Image._check_writable);
  ``demote`` then ``promote`` flips the direction for failover, exactly
  the reference's promote/demote workflow (resync after a split-brain
  divergence is out of scope — the reference requires an explicit
  resync request there too).
"""
from __future__ import annotations

import base64
import json
import threading
import uuid

from .rbd import (
    _HEADER_SUFFIX,
    Image,
    ImageNotFound,
    RBD,
    ReadOnlyImage,
)

_JHDR = "journal.{}"
_JREC = "journal.{}.{:016x}"


# ------------------------------------------------------------- journal core

def _jread(io, oid):
    try:
        return json.loads(io.read(oid))
    except (IOError, ValueError):
        return None


LOCAL_CLIENT = "__local__"


# The journal header lives as OMAP KEYS on journal.{image} — per-key
# writes are atomic at the object's primary, and each key has ONE
# writer: "next_tid" belongs to the appending primary handle,
# "client.{id}" to that client, and "trimmed" is monotonic best-effort
# (a stale rewrite only re-deletes already-deleted records).  This is
# what lets the MirrorDaemon thread commit concurrently with client
# appends without a lost update (review r5: the earlier whole-JSON
# header was a read-modify-write that two writers could interleave).

def journal_header(io, image: str) -> dict:
    """READ-ONLY view merging the legacy whole-JSON body (pre-omap
    format) under any omap keys present.  The read path never writes:
    a read-triggered migration would itself be a multi-key RMW two
    threads could interleave (review r5) — the single APPENDER migrates
    in journal_append instead, and omap keys always win over the body
    so a commit landing before that migration is never shadowed."""
    oid = _JHDR.format(image)
    try:
        kv = io.omap_get(oid)
    except IOError:
        kv = {}
    hdr = {"next_tid": 0, "clients": {}, "trimmed": -1}
    legacy = None if kv.get("next_tid") is not None else _jread(io, oid)
    if legacy:
        hdr["next_tid"] = int(legacy.get("next_tid", 0))
        hdr["trimmed"] = int(legacy.get("trimmed", -1))
        hdr["clients"] = {
            str(c): int(p) for c, p in (legacy.get("clients") or {}).items()
        }
    for k, v in kv.items():
        if k == "next_tid":
            hdr["next_tid"] = int(v)
        elif k == "trimmed":
            hdr["trimmed"] = int(v)
        elif k.startswith("client."):
            hdr["clients"][k[len("client."):]] = int(v)
    return hdr


def journal_append(io, image: str, record: dict) -> int:
    """Append one event record; returns its tid.  Record object first,
    next_tid second: a crash between the two leaves an orphan record
    ABOVE next_tid that the next append overwrites — never a pointer at
    a missing record.  Single appender per image (the primary handle),
    so the next_tid read-increment needs no CAS — and that makes this
    the one safe place to migrate a legacy JSON body to omap keys."""
    oid = _JHDR.format(image)
    try:
        kv = io.omap_get(oid)
    except IOError:
        kv = {}
    if kv.get("next_tid") is None:
        # one-time migration of a legacy JSON body by the single writer
        # of next_tid.  Seed ONLY keys absent from the live omap: a
        # client key present there is per-key-owned by its client and a
        # concurrently advanced position must not be regressed from this
        # stale snapshot (review r5).  After migration the body is empty
        # and kv carries next_tid, so this branch never runs again — no
        # per-append body read on the hot path.
        legacy = _jread(io, oid) or {}
        sets = {"next_tid": str(legacy.get("next_tid", 0)).encode()}
        if "trimmed" not in kv:
            sets["trimmed"] = str(legacy.get("trimmed", -1)).encode()
        for cid, pos in (legacy.get("clients") or {}).items():
            if f"client.{cid}" not in kv:
                sets[f"client.{cid}"] = str(pos).encode()
        io.omap_set(oid, sets)
        if legacy:
            io.write_full(oid, b"")
        tid = int(legacy.get("next_tid", 0))
    else:
        tid = int(kv["next_tid"])
    io.write_full(_JREC.format(image, tid), json.dumps(record).encode())
    io.omap_set(oid, {"next_tid": str(tid + 1).encode()})
    return tid


def journal_register(io, image: str, client_id: str) -> int:
    """Register a replay client at the beginning of the RETAINED
    journal.  Safe unconditionally: every record is an idempotent
    absolute-state setter, so re-applying records whose effects the
    bootstrap copy (or the old primary's own history, on failback)
    already carries converges on the same state.  One honest caveat:
    a snap_create replayed AFTER later writes were bootstrap-copied
    snapshots the replica's current state, not the source's
    point-in-time view — the reference's image sync walks snapshots
    explicitly to avoid this; live mirroring (replayer registered
    before the snap) is point-in-time correct."""
    hdr = journal_header(io, image)
    if client_id not in hdr["clients"]:
        io.omap_set(_JHDR.format(image),
                    {f"client.{client_id}": b"-1"})
        return -1
    return hdr["clients"][client_id]


def journal_unregister(io, image: str, client_id: str) -> None:
    """Drop a replay client so its frozen position stops pinning
    retention (a stopped mirror daemon unregisters on the way out)."""
    try:
        io.omap_rm_keys(_JHDR.format(image), [f"client.{client_id}"])
    except IOError:
        pass


# records retained while NO mirror peer is registered: enough for a
# soon-arriving replayer to catch up without a resync, bounded so an
# unmirrored journaled image cannot grow its journal forever (a peer
# registering past the window heals via MirrorReplayer's resync)
RETAIN_NO_PEER = 4096


def journal_commit(io, image: str, client_id: str, tid: int) -> None:
    """Advance a client's commit position and trim committed records
    (MDLog-style expiry).  The LOCAL client (the primary committing its
    own applies) does not gate retention on its own: with no mirror
    peer registered the journal keeps only the last RETAIN_NO_PEER
    records; once a peer exists, the floor is the slowest client.  The
    trim walks only [trimmed+1, floor] — both known from the header."""
    oid = _JHDR.format(image)
    hdr = journal_header(io, image)
    pos = max(hdr["clients"].get(client_id, -1), tid)
    io.omap_set(oid, {f"client.{client_id}": str(pos).encode()})
    hdr["clients"][client_id] = pos
    peers = [v for k, v in hdr["clients"].items() if k != LOCAL_CLIENT]
    if peers:
        floor = min(hdr["clients"].values())
    else:
        floor = hdr["next_tid"] - 1 - RETAIN_NO_PEER
    start = hdr.get("trimmed", -1) + 1
    for rec_tid in range(start, floor + 1):
        try:
            io.remove(_JREC.format(image, rec_tid))
        except IOError:
            pass
    if floor >= start:
        io.omap_set(oid, {"trimmed": str(floor).encode()})


def replay_local_tail(io, img: Image) -> None:
    """Re-apply the primary's own uncommitted journal tail (records
    appended whose apply a crash interrupted) — RBD.open calls this for
    journaled primary images (librbd's open-time journal replay)."""
    image = img.name
    hdr = journal_header(io, image)
    pos = hdr["clients"].get(LOCAL_CLIENT, -1)
    if pos >= hdr["next_tid"] - 1:
        return
    replayer = Image(io, image, img._header, _replaying=True)
    for tid in range(pos + 1, hdr["next_tid"]):
        rec = _jread(io, _JREC.format(image, tid))
        if rec is not None:
            _apply_record(replayer, rec)
    journal_commit(io, image, LOCAL_CLIENT, hdr["next_tid"] - 1)


def _apply_record(img: Image, rec: dict) -> None:
    """Apply one journal record to an image through a replay handle —
    shared by the primary's open-time tail replay and the mirror
    replayer.  Every op is an idempotent absolute-state setter."""
    op = rec["op"]
    if op == "write":
        data = base64.b64decode(rec["data"])
        end = rec["off"] + len(data)
        if end > img.size():
            img.resize(end)  # defensive: record order guarantees this
        img.write(data, rec["off"])
    elif op == "resize":
        img.resize(rec["size"])
    elif op == "snap_create":
        if rec["snap"] not in img.snap_list():
            img.snap_create(rec["snap"])
    elif op == "snap_remove":
        if rec["snap"] in img.snap_list():
            img.snap_remove(rec["snap"])
    elif op == "snap_rollback":
        if rec["snap"] in img.snap_list():
            img.snap_rollback(rec["snap"])
    elif op == "snap_protect":
        if rec["snap"] in img.snap_list():
            img.snap_protect(rec["snap"])
    elif op == "snap_unprotect":
        if rec["snap"] in img.snap_list():
            img.snap_unprotect(rec["snap"])
    # unknown ops are skipped (forward compatibility)


# ---------------------------------------------------------- mirror admin

def _edit_header(io, name: str, fn) -> dict:
    rbd = RBD(io)
    img = rbd.open(name)
    fn(img._header)
    img._save_header()
    return img._header


def mirror_enable(io, name: str) -> dict:
    """Enable journal-based mirroring on an image (implies the
    journaling feature; the image starts as the PRIMARY side)."""

    def fn(h):
        feats = h.setdefault("features", [])
        if "journaling" not in feats:
            feats.append("journaling")
        h.setdefault("mirror", {
            "enabled": True, "primary": True,
            "global_id": uuid.uuid4().hex,
        })
        h["mirror"]["enabled"] = True

    return _edit_header(io, name, fn)


def journal_purge(io, image: str) -> None:
    """Delete the journal header + every retained record (image removal
    and mirror disable; bounded by the header's trimmed/next_tid)."""
    hdr = journal_header(io, image)
    for tid in range(hdr.get("trimmed", -1) + 1, hdr["next_tid"]):
        try:
            io.remove(_JREC.format(image, tid))
        except IOError:
            pass
    try:
        io.remove(_JHDR.format(image))
    except IOError:
        pass


def mirror_disable(io, name: str) -> dict:
    """Tear mirroring down (reference: `rbd mirror image disable`
    removes the journal): drop the feature AND purge the journal, so a
    frozen peer's commit position cannot pin records forever and later
    writes stop journaling (review r5)."""

    def fn(h):
        if h.get("mirror"):
            h["mirror"]["enabled"] = False
        feats = h.get("features") or []
        if "journaling" in feats:
            feats.remove("journaling")

    out = _edit_header(io, name, fn)
    journal_purge(io, name)
    return out


def mirror_demote(io, name: str) -> dict:
    """Primary -> non-primary (step 1 of failover; drain the journal
    with a replayer pass before promoting the other side)."""

    def fn(h):
        mir = h.get("mirror")
        if not mir or not mir.get("enabled"):
            raise ReadOnlyImage(f"{name!r} is not mirror-enabled")
        mir["primary"] = False

    return _edit_header(io, name, fn)


def mirror_promote(io, name: str, force: bool = False) -> dict:
    """Non-primary -> primary (step 2 of failover).  `force` is the
    split-brain override accepted for API parity with `rbd mirror image
    promote --force`; the divergence detection that distinguishes the
    two upstream needs the peer's journal, which a promoted-side-only
    caller may not reach — resync remains the operator's explicit step
    either way, as in the reference."""

    def fn(h):
        mir = h.get("mirror")
        if not mir or not mir.get("enabled"):
            raise ReadOnlyImage(f"{name!r} is not mirror-enabled")
        mir["primary"] = True

    return _edit_header(io, name, fn)


def mirror_image_status(io, name: str) -> dict:
    rbd = RBD(io)
    img = rbd.open(name)
    hdr = journal_header(io, name)
    mir = dict(img._header.get("mirror") or {})
    mir["journal_next_tid"] = hdr["next_tid"]
    mir["journal_clients"] = dict(hdr["clients"])
    return mir


# ---------------------------------------------------------- the replayer

class MirrorReplayer:
    """One-way journal replayer (the rbd-mirror daemon role for one
    pool pair).  `run_once()` pulls every mirror-enabled primary image
    in `src_io`, bootstraps missing replicas, replays new journal
    records onto `dst_io`, commits, and trims."""

    def __init__(self, src_io, dst_io, client_id: str = "rbd-mirror"):
        self.src = src_io
        self.dst = dst_io
        self.client_id = client_id
        self.registered: set[str] = set()  # images we joined as a client

    # -- bootstrap (reference: rbd-mirror image sync) --------------------
    def _bootstrap(self, name: str, src_img: Image) -> None:
        """Full-copy the current image state into a fresh NON-PRIMARY
        replica.  Data is read through the IMAGE (not raw objects), so a
        clone's parent-backed ranges arrive too (review r5: raw head
        reads dropped everything not yet copied up).  Pre-existing
        snapshot NAMES are recreated on the replica so later
        snap_remove/rollback records resolve — their content is the
        bootstrap-time state, not the source's point-in-time view (the
        reference's image sync walks snapshot deltas; documented
        limitation here, same caveat as journal_register)."""
        h = src_img._header
        dst_rbd = RBD(self.dst)
        dst_rbd.create(
            name, h["size"], order=h["order"],
            stripe_unit=h["stripe_unit"], stripe_count=h["stripe_count"],
        )
        dst_img = Image(self.dst, name,
                        json.loads(self.dst.read(name + _HEADER_SUFFIX)),
                        _replaying=True)
        dst_img._header["features"] = list(h.get("features", []))
        dst_img._header["mirror"] = dict(h["mirror"], primary=False)
        dst_img._save_header()
        # snaps whose CREATE record is still retained will be replayed
        # in order (point-in-time correct) — bootstrap must not
        # pre-create them or the replay's exists-guard would skip the
        # correctly-timed create
        jhdr = journal_header(self.src, src_img.name)
        replayed_snaps = set()
        for tid in range(jhdr.get("trimmed", -1) + 1, jhdr["next_tid"]):
            rec = _jread(self.src, _JREC.format(src_img.name, tid))
            if rec and rec.get("op") == "snap_create":
                replayed_snaps.add(rec["snap"])
        self._sync_data(src_img, dst_img, sparse_skip=True,
                        skip_snaps=replayed_snaps)

    def _sync_data(self, src_img: Image, dst_img: Image,
                   sparse_skip: bool,
                   skip_snaps: set | None = None) -> None:
        """Logical full-copy src -> dst in object-size chunks.  Reads go
        through the IMAGE, so a clone's parent-backed ranges arrive too.
        sparse_skip elides all-zero chunks — valid only for a FRESH
        replica; a resync over existing data must overwrite everything
        or stale bytes survive where the source has zeros.  Snapshot
        NAMES are recreated (content = sync-time state, not the
        source's point-in-time view — the reference's image sync walks
        snapshot deltas; documented limitation) except `skip_snaps`,
        whose retained journal records will replay them correctly."""
        h = src_img._header
        if dst_img.size() != h["size"]:
            dst_img.resize(h["size"])
        step = 1 << h["order"]
        for off in range(0, h["size"], step):
            chunk = src_img.read(off, min(step, h["size"] - off))
            if sparse_skip and not chunk.strip(b"\x00"):
                continue
            dst_img.write(chunk, off)
        for snap in src_img.snap_list():
            if snap in (skip_snaps or ()):
                continue
            if snap not in dst_img.snap_list():
                dst_img.snap_create(snap)

    def run_once(self) -> dict:
        """One replay pass; returns {image: records_applied}."""
        src_rbd = RBD(self.src)
        applied: dict[str, int] = {}
        for name in src_rbd.list():
            try:
                src_img = src_rbd.open(name)
            except ImageNotFound:
                continue
            mir = src_img._header.get("mirror")
            if not mir or not mir.get("enabled"):
                continue
            # a demoted source still drains (records appended while it
            # was primary remain), but NEVER replay onto a destination
            # that has been PROMOTED: a force-promote with a live
            # replayer must not let stale source records overwrite the
            # new primary's writes (review r5)
            try:
                dst_probe = RBD(self.dst).open(name)
                if (dst_probe._header.get("mirror") or {}).get(
                        "primary", False):
                    continue
            except ImageNotFound:
                self._bootstrap(name, src_img)
            journal_register(self.src, name, self.client_id)
            self.registered.add(name)
            hdr = journal_header(self.src, name)
            pos = hdr["clients"][self.client_id]
            n = 0
            dst_img = Image(
                self.dst, name,
                json.loads(self.dst.read(name + _HEADER_SUFFIX)),
                _replaying=True,
            )
            if pos < hdr.get("trimmed", -1):
                # our position predates the trim floor: records we need
                # are gone (the primary's local client trims behind
                # itself) — RESYNC the image state and jump forward,
                # the rbd-mirror behavior when a journal is no longer
                # retained for a peer
                self._sync_data(src_img, dst_img, sparse_skip=False)
                journal_commit(self.src, name, self.client_id,
                               hdr["trimmed"])
                pos = hdr["trimmed"]
                applied[name] = applied.get(name, 0)
            for tid in range(pos + 1, hdr["next_tid"]):
                rec = _jread(self.src, _JREC.format(name, tid))
                if rec is None:
                    continue  # trimmed below a racing commit floor
                _apply_record(dst_img, rec)
                n += 1
            if n or pos < hdr["next_tid"] - 1:
                journal_commit(self.src, name, self.client_id,
                               hdr["next_tid"] - 1)
            if n:
                applied[name] = n
        return applied


class MirrorDaemon:
    """The rbd-mirror daemon proper: a background thread driving a
    MirrorReplayer on an interval (reference: the rbd-mirror process
    polling journals per pool peer).  One daemon per directed pool
    pair; run a second one for the reverse direction after a failover."""

    def __init__(self, src_io, dst_io, interval: float = 0.5,
                 client_id: str = "rbd-mirror"):
        self.replayer = MirrorReplayer(src_io, dst_io, client_id)
        self.interval = interval
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self.passes = 0
        self.last_error: str | None = None

    def start(self) -> "MirrorDaemon":
        self._thread = threading.Thread(
            target=self._loop, name="rbd-mirror", daemon=True
        )
        self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop.wait(timeout=self.interval):
            try:
                self.replayer.run_once()
                self.passes += 1  # noqa: CL2 — _loop is the only writer; readers poll
                self.last_error = None
            except Exception as e:  # a flaky pass must not kill the daemon
                self.last_error = repr(e)

    def stop(self, unregister: bool = True) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
        if unregister:
            # a dead peer's frozen commit position must not pin journal
            # retention forever (review r5); records it had not yet
            # replayed are healed by the resync path if it ever returns
            for name in sorted(self.replayer.registered):
                journal_unregister(self.replayer.src, name,
                                   self.replayer.client_id)
