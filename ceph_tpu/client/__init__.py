"""Client stack: Objecter op engine + librados-style API (reference:
src/osdc/Objecter.cc, src/librados; SURVEY.md §2.6)."""
from .objecter import Objecter
from .rados import Rados

__all__ = ["Objecter", "Rados"]
