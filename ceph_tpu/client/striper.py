"""File striper — byte-stream <-> RADOS-object extent mapping (reference:
src/osdc/Striper.cc :: file_to_extents + src/libradosstriper;
SURVEY.md §5.7).

A "file" of bytes is striped over objects exactly the reference way:
stripe units of `su` bytes round-robin across `stripe_count` objects of a
set, each object holding at most `object_size` bytes; sets repeat.  For a
byte range the mapping yields (object name, object offset, length)
extents; StripedObject wraps an IoCtx with write/read/truncate over the
mapping, storing the logical size in the first object's "size" metadata
sidecar object.

    s = StripedObject(io, "vol1", object_size=1 << 22, stripe_unit=1 << 16,
                      stripe_count=4)
    s.write(data, off)
    s.read(off, length)
"""
from __future__ import annotations

from dataclasses import dataclass


@dataclass(frozen=True)
class StripePolicy:
    """reference: ceph_file_layout (su, stripe_count, object_size)."""

    object_size: int = 1 << 22
    stripe_unit: int = 1 << 16
    stripe_count: int = 1

    def __post_init__(self):
        if self.stripe_unit <= 0 or self.object_size <= 0 or self.stripe_count <= 0:
            raise ValueError("layout fields must be positive")
        if self.object_size % self.stripe_unit:
            raise ValueError("object_size must be a multiple of stripe_unit")

    @property
    def stripes_per_object(self) -> int:
        return self.object_size // self.stripe_unit

    def extents(self, off: int, length: int):
        """Yield (objectno, obj_off, len) for a byte range — the
        file_to_extents loop, unrolled per stripe unit then merged for
        contiguous runs within one object."""
        su = self.stripe_unit
        spo = self.stripes_per_object
        sc = self.stripe_count
        out: list[list[int]] = []  # [objectno, obj_off, len] merged
        pos = off
        end = off + length
        while pos < end:
            blockno = pos // su          # stripe unit index in the stream
            stripeno = blockno // sc     # full stripe row
            stripepos = blockno % sc     # which object of the set
            objectsetno = stripeno // spo
            objectno = objectsetno * sc + stripepos
            block_off = pos % su
            obj_off = (stripeno % spo) * su + block_off
            take = min(su - block_off, end - pos)
            if out and out[-1][0] == objectno and \
                    out[-1][1] + out[-1][2] == obj_off:
                out[-1][2] += take
            else:
                out.append([objectno, obj_off, take])
            pos += take
        return [tuple(e) for e in out]


class StripedObject:
    """Striped byte-stream over an IoCtx (reference: libradosstriper's
    RadosStriperImpl, the write/read/truncate subset)."""

    def __init__(self, io, name: str, policy: StripePolicy | None = None,
                 **layout):
        self.io = io
        self.name = name
        self.policy = policy or StripePolicy(**layout)

    def _obj(self, objectno: int) -> str:
        # reference: {name}.{%016x} object naming
        return f"{self.name}.{objectno:016x}"

    def _meta(self) -> str:
        return f"{self.name}.meta"

    # -- size sidecar ------------------------------------------------------
    def size(self) -> int:
        try:
            raw = self.io.read(self._meta())
        except IOError:
            return 0
        return int(raw or b"0")

    def _set_size(self, size: int) -> None:
        self.io.write_full(self._meta(), str(size).encode())

    # -- I/O ---------------------------------------------------------------
    def write(self, data: bytes, off: int = 0) -> None:
        """Read-modify-write each touched object (the framework's object
        store is whole-object; the reference writes sub-object extents
        natively — same bytes land either way)."""
        src = 0  # extents come back in stream order
        for objectno, obj_off, ln in self.policy.extents(off, len(data)):
            try:
                cur = bytearray(self.io.read(self._obj(objectno)))
            except IOError:
                cur = bytearray()
            end = obj_off + ln
            if len(cur) < end:
                cur.extend(b"\0" * (end - len(cur)))
            cur[obj_off:end] = data[src : src + ln]
            src += ln
            self.io.write_full(self._obj(objectno), bytes(cur))
        if off + len(data) > self.size():
            self._set_size(off + len(data))

    def read(self, off: int = 0, length: int | None = None) -> bytes:
        size = self.size()
        if off >= size:
            return b""
        if length is None or off + length > size:
            length = size - off
        parts: list[bytes] = []
        for objectno, obj_off, ln in self.policy.extents(off, length):
            try:
                chunk = self.io.read(self._obj(objectno), off=obj_off,
                                     length=ln)
            except IOError:
                chunk = b""
            if len(chunk) < ln:  # sparse object: logical zeros
                chunk = chunk + b"\0" * (ln - len(chunk))
            parts.append(chunk)
        return b"".join(parts)

    def truncate(self, size: int) -> None:
        """Shrink to `size`: whole objects past it are removed and kept
        objects are cut to their surviving prefix, so a later write that
        re-extends the stream reads zeros (not stale bytes) in the gap —
        POSIX/libradosstriper truncate semantics."""
        old = self.size()
        if size >= old:
            self._set_size(size)
            return
        kept = self.policy.extents(0, size)
        # per-object surviving prefix length (striping interleaves, so an
        # object can hold stream bytes BEYOND `size` below other kept
        # ranges — everything past the last kept extent end must go)
        keep_len: dict[int, int] = {}
        for objectno, obj_off, ln in kept:
            keep_len[objectno] = max(
                keep_len.get(objectno, 0), obj_off + ln
            )
        last_obj = max(
            (e[0] for e in self.policy.extents(0, old)), default=-1
        )
        for objectno in range(last_obj + 1):
            keep = keep_len.get(objectno, 0)
            if keep == 0:
                try:
                    self.io.remove(self._obj(objectno))
                except IOError:
                    pass
                continue
            try:
                cur = self.io.read(self._obj(objectno))
            except IOError:
                continue
            if len(cur) > keep:
                self.io.write_full(self._obj(objectno), bytes(cur[:keep]))
        self._set_size(size)

    def remove(self) -> None:
        last_obj = max(
            (e[0] for e in self.policy.extents(0, max(self.size(), 1))),
            default=-1,
        )
        for objectno in range(last_obj + 1):
            try:
                self.io.remove(self._obj(objectno))
            except IOError:
                pass
        try:
            self.io.remove(self._meta())
        except IOError:
            pass
