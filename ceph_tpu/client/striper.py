"""File striper — byte-stream <-> RADOS-object extent mapping (reference:
src/osdc/Striper.cc :: file_to_extents + src/libradosstriper;
SURVEY.md §5.7).

A "file" of bytes is striped over objects exactly the reference way:
stripe units of `su` bytes round-robin across `stripe_count` objects of a
set, each object holding at most `object_size` bytes; sets repeat.  For a
byte range the mapping yields (object name, object offset, length)
extents; StripedObject wraps an IoCtx with write/read/truncate over the
mapping, storing the logical size in the first object's "size" metadata
sidecar object.

    s = StripedObject(io, "vol1", object_size=1 << 22, stripe_unit=1 << 16,
                      stripe_count=4)
    s.write(data, off)
    s.read(off, length)
"""
from __future__ import annotations

from dataclasses import dataclass


@dataclass(frozen=True)
class StripePolicy:
    """reference: ceph_file_layout (su, stripe_count, object_size)."""

    object_size: int = 1 << 22
    stripe_unit: int = 1 << 16
    stripe_count: int = 1

    def __post_init__(self):
        if self.stripe_unit <= 0 or self.object_size <= 0 or self.stripe_count <= 0:
            raise ValueError("layout fields must be positive")
        if self.object_size % self.stripe_unit:
            raise ValueError("object_size must be a multiple of stripe_unit")

    @property
    def stripes_per_object(self) -> int:
        return self.object_size // self.stripe_unit

    def object_keep_len(self, objectno: int, size: int) -> int:
        """Bytes of object `objectno` that hold stream data below logical
        `size` (0 = none).  Striping interleaves, so this scans just the
        object's own stripe-set window — bounded at stripes_per_object *
        stripe_count units (used by RBD copy-up to clip parent objects to
        the clone overlap)."""
        set_span = self.stripe_count * self.object_size
        lo = (objectno // self.stripe_count) * set_span
        hi = min(size, lo + set_span)
        keep = 0
        if hi > lo:
            for o, obj_off, ln in self.extents(lo, hi - lo):
                if o == objectno:
                    keep = max(keep, obj_off + ln)
        return keep

    def extents(self, off: int, length: int):
        """Yield (objectno, obj_off, len) for a byte range — the
        file_to_extents loop, unrolled per stripe unit then merged for
        contiguous runs within one object."""
        su = self.stripe_unit
        spo = self.stripes_per_object
        sc = self.stripe_count
        out: list[list[int]] = []  # [objectno, obj_off, len] merged
        pos = off
        end = off + length
        while pos < end:
            blockno = pos // su          # stripe unit index in the stream
            stripeno = blockno // sc     # full stripe row
            stripepos = blockno % sc     # which object of the set
            objectsetno = stripeno // spo
            objectno = objectsetno * sc + stripepos
            block_off = pos % su
            obj_off = (stripeno % spo) * su + block_off
            take = min(su - block_off, end - pos)
            if out and out[-1][0] == objectno and \
                    out[-1][1] + out[-1][2] == obj_off:
                out[-1][2] += take
            else:
                out.append([objectno, obj_off, take])
            pos += take
        return [tuple(e) for e in out]


class ExtentIO:
    """Extent-level striped I/O over whole-object IoCtx ops — the shared
    engine under libradosstriper's StripedObject and the FS FileHandle
    (which differ only in object naming and where the logical size lives).

    `namer(objectno) -> oid` supplies the object naming convention.  Size
    bookkeeping stays with the caller; `read` takes the caller's logical
    length (already clamped) and `truncate_data`/`purge` take the old
    logical size."""

    def __init__(self, io, namer, policy: StripePolicy):
        self.io = io
        self.namer = namer
        self.policy = policy
        # self-managed snap-context seq (CephFS realm seq; 0 = none).
        # Passed as a kwarg only when set so snap-unaware io backends
        # (tests' fakes) keep working.
        self.snapc_seq = 0

    def _mut_kw(self) -> dict:
        return {"snapc_seq": self.snapc_seq} if self.snapc_seq else {}

    def write(self, data: bytes, off: int) -> None:
        """Read-modify-write each touched object (the framework's object
        store is whole-object; the reference writes sub-object extents
        natively — same bytes land either way)."""
        src = 0  # extents come back in stream order
        for objectno, obj_off, ln in self.policy.extents(off, len(data)):
            oid = self.namer(objectno)
            try:
                cur = bytearray(self.io.read(oid))
            except IOError:
                cur = bytearray()
            end = obj_off + ln
            if len(cur) < end:
                cur.extend(b"\0" * (end - len(cur)))
            cur[obj_off:end] = data[src : src + ln]
            src += ln
            self.io.write_full(oid, bytes(cur), **self._mut_kw())

    def read(self, off: int, length: int,
             snapid: int | None = None) -> bytes:
        """`snapid` reads the pool-snapshot view of every data object —
        the substrate RBD snapshot reads ride on.  Passed through only
        when set, so snap-unaware io backends (FS data path, tests'
        fakes) keep working."""
        kw = {} if snapid is None else {"snapid": snapid}
        parts: list[bytes] = []
        for objectno, obj_off, ln in self.policy.extents(off, length):
            try:
                chunk = self.io.read(self.namer(objectno), off=obj_off,
                                     length=ln, **kw)
            except IOError:
                chunk = b""
            if len(chunk) < ln:  # sparse object: logical zeros
                chunk = chunk + b"\0" * (ln - len(chunk))
            parts.append(chunk)
        return b"".join(parts)

    def truncate_data(self, old: int, size: int) -> None:
        """Shrink the data objects to logical `size`: whole objects past it
        are removed and kept objects cut to their surviving prefix, so a
        later write that re-extends the stream reads zeros (not stale
        bytes) in the gap — POSIX/libradosstriper truncate semantics.
        (Striping interleaves, so an object can hold stream bytes BEYOND
        `size` below other kept ranges — everything past the last kept
        extent end must go.)"""
        keep_len: dict[int, int] = {}
        for objectno, obj_off, ln in self.policy.extents(0, size):
            keep_len[objectno] = max(
                keep_len.get(objectno, 0), obj_off + ln
            )
        last_obj = max(
            (e[0] for e in self.policy.extents(0, old)), default=-1
        )
        for objectno in range(last_obj + 1):
            keep = keep_len.get(objectno, 0)
            oid = self.namer(objectno)
            if keep == 0:
                try:
                    self.io.remove(oid, **self._mut_kw())
                except IOError:
                    pass
                continue
            try:
                cur = self.io.read(oid)
            except IOError:
                continue
            if len(cur) > keep:
                self.io.write_full(oid, bytes(cur[:keep]),
                                   **self._mut_kw())

    def purge(self, size: int) -> None:
        """Remove every data object of a stream whose logical size was
        `size`."""
        last_obj = max(
            (e[0] for e in self.policy.extents(0, max(size, 1))),
            default=-1,
        )
        for objectno in range(last_obj + 1):
            try:
                self.io.remove(self.namer(objectno), **self._mut_kw())
            except IOError:
                pass


class StripedObject:
    """Striped byte-stream over an IoCtx (reference: libradosstriper's
    RadosStriperImpl, the write/read/truncate subset).  Logical size lives
    in a `.meta` sidecar object."""

    def __init__(self, io, name: str, policy: StripePolicy | None = None,
                 **layout):
        self.io = io
        self.name = name
        self.policy = policy or StripePolicy(**layout)
        # reference: {name}.{%016x} object naming
        self._ext = ExtentIO(
            io, lambda objectno: f"{name}.{objectno:016x}", self.policy
        )

    def _meta(self) -> str:
        return f"{self.name}.meta"

    # -- size sidecar ------------------------------------------------------
    def size(self) -> int:
        try:
            raw = self.io.read(self._meta())
        except IOError:
            return 0
        return int(raw or b"0")

    def _set_size(self, size: int) -> None:
        self.io.write_full(self._meta(), str(size).encode())

    # -- I/O ---------------------------------------------------------------
    def write(self, data: bytes, off: int = 0) -> None:
        self._ext.write(data, off)
        if off + len(data) > self.size():
            self._set_size(off + len(data))

    def read(self, off: int = 0, length: int | None = None) -> bytes:
        size = self.size()
        if off >= size:
            return b""
        if length is None or off + length > size:
            length = size - off
        return self._ext.read(off, length)

    def truncate(self, size: int) -> None:
        old = self.size()
        if size < old:
            self._ext.truncate_data(old, size)
        self._set_size(size)

    def remove(self) -> None:
        self._ext.purge(self.size())
        try:
            self.io.remove(self._meta())
        except IOError:
            pass
