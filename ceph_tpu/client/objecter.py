"""Objecter — client-side op engine (reference: src/osdc/Objecter.cc ::
op_submit / _calc_target / resend-on-epoch-change; SURVEY.md §3.1 first
hop).

The client holds its own OSDMap (pushed by the mon subscription), computes
each op's target primary locally (no metadata server — the CRUSH property),
and resends ops when:
- the reply is -ESTALE-like (-116: wrong primary; the map moved),
- the target connection dies,
- a new map arrives while ops are in flight and their target changed.
"""
from __future__ import annotations

import threading
from time import monotonic as _monotonic

from ..common.lockdep import make_lock
from ..common.throttle import Throttle
from ..common.tracer import TRACER, sampled_ctx, trace_now
from ..msg import Dispatcher, Messenger
from ..msg.messenger import POLICY_LOSSY
from ..osd.osdmap import object_ps
from ..osd.messages import (
    MOSDOp,
    MOSDOpReply,
    MWatchNotify,
    MWatchNotifyAck,
    pack_data,
    unpack_data,
)


class Objecter(Dispatcher):
    def __init__(self, cct, mon_client, name: str = "client"):
        self.cct = cct
        self.mc = mon_client
        self.messenger = Messenger.create(cct, name)
        self.messenger.default_policy = POLICY_LOSSY
        self.messenger.add_dispatcher(self)
        self._lock = make_lock("objecter::lock")
        self._cond = threading.Condition(self._lock)
        self._tid = 0
        # instance nonce: makes reqids unique across Objecter restarts
        # that reset the tid counter (reference: osd_reqid_t's name+inc)
        import uuid

        self._nonce = uuid.uuid4().hex[:12]
        # lingering watches: (pool, oid, cookie) -> {"callback": fn}
        self._watches: dict[tuple, dict] = {}
        self._cookie = 0
        self._relinger_epoch = 0     # newest epoch watches were re-sent at
        self._relingering = False    # single relinger loop at a time
        self._linger_kick = False    # a map arrived while relinging
        self._linger_lock = make_lock("objecter::linger")
        self._replies: dict[int, MOSDOpReply] = {}
        self._outstanding: set[int] = set()
        # admission throttles (reference: Objecter's op budget —
        # objecter_inflight_ops / objecter_inflight_op_bytes).  These
        # are the backpressure sink of the whole write path: an op
        # stalled downstream (e.g. at the OSD write-batcher's queue
        # throttle) keeps its budget here, so sustained overload blocks
        # NEW client ops at admission instead of piling work mid-stack.
        self._op_throttle = Throttle("objecter::inflight_ops", 0)
        self._bytes_throttle = Throttle("objecter::inflight_op_bytes", 0)
        self.mc.subscribe_osdmap(callback=self._on_new_map)

    def _on_new_map(self, m) -> None:
        """Map-push hook: a new map may mean a new primary that has
        never heard of our watches — re-register them off-thread (linger
        resend; runs even for an idle watcher that submits no ops)."""
        if not self._watches:
            return
        with self._linger_lock:
            self._linger_kick = True
        threading.Thread(target=self._relinger_guarded, daemon=True).start()  # noqa: CL13 — fire-and-forget by design: the kick flag dedups to at most one live relinger, and it self-terminates when the flag stays clear

    def _relinger_guarded(self) -> None:
        """At most one relinger loop runs; the `kick` flag (set under
        the lock by every map push) makes the exit decision atomic with
        clearing `_relingering`, so an epoch that arrives mid-flight is
        either handled by this loop's next pass or by the push's own
        thread — never silently skipped."""
        with self._linger_lock:
            if self._relingering:
                return
            self._relingering = True
        try:
            while True:
                with self._linger_lock:
                    self._linger_kick = False
                m = self.mc.osdmap
                target = m.epoch if m is not None else 0
                if target > self._relinger_epoch:
                    self._relinger()
                    self._relinger_epoch = target
                with self._linger_lock:
                    if not self._linger_kick:
                        self._relingering = False
                        return
        finally:
            with self._linger_lock:
                self._relingering = False

    def shutdown(self) -> None:
        self.messenger.shutdown()

    # -- dispatch ----------------------------------------------------------
    def ms_dispatch(self, conn, msg) -> bool:
        if isinstance(msg, MOSDOpReply):
            with self._lock:
                # drop replies for tids nobody waits on any more (late
                # replies after a timeout/retry would otherwise accumulate
                # forever in a long-lived client)
                if msg.tid in self._outstanding:
                    self._replies[msg.tid] = msg
                    self._cond.notify_all()
            return True
        if isinstance(msg, MWatchNotify):
            # watcher side of notify: fire the callback off-thread (a
            # slow callback must not stall the messenger) and ack so the
            # notifier's collect phase completes
            entry = self._watches.get((msg.pool, msg.oid, msg.cookie))
            if entry is not None:
                cb = entry["callback"]
                data = unpack_data(msg.data) or b""

                def run(cb=cb, nid=msg.notify_id, ck=msg.cookie, d=data):
                    try:
                        cb(nid, ck, d)
                    except Exception as e:
                        # user callback: contain it, but leave a trace
                        if self.cct:
                            self.cct.dout(
                                "objecter", 0,
                                f"watch callback cookie={ck} raised: {e!r}")

                threading.Thread(target=run, daemon=True).start()  # noqa: CL13 — fire-and-forget by design: user watch callbacks run off the reader thread and must not be joined from dispatch
            try:
                conn.send_message(MWatchNotifyAck(
                    notify_id=msg.notify_id, pool=msg.pool, oid=msg.oid,
                    cookie=msg.cookie,
                ))
            except (OSError, ConnectionError):
                pass
            return True
        return False

    # -- watch / notify (linger ops) ---------------------------------------
    def watch(self, pool_id: int, oid: str, callback) -> int:
        with self._lock:
            self._cookie += 1
            cookie = self._cookie
        self._watches[(pool_id, oid, cookie)] = {"callback": callback}
        try:
            rep = self.op_submit(pool_id, oid, "watch",
                                 data={"cookie": cookie})
        except Exception:
            # a failed registration must not leave a phantom entry that
            # the next map push re-lingers behind the caller's back
            self._watches.pop((pool_id, oid, cookie), None)
            raise
        if rep.retval != 0:
            self._watches.pop((pool_id, oid, cookie), None)
            raise IOError(f"watch {oid!r}: {rep.retval} {rep.result}")
        return cookie

    def unwatch(self, pool_id: int, oid: str, cookie: int) -> None:
        self._watches.pop((pool_id, oid, cookie), None)
        self.op_submit(pool_id, oid, "unwatch", data={"cookie": cookie})

    def _relinger(self) -> None:
        """Re-register every lingering watch (reference: the Objecter
        resends linger ops after a map change, which is what makes a
        watch survive primary failover — the new primary has no
        in-memory watch state until we tell it)."""
        for (pool_id, oid, cookie) in list(self._watches):
            try:
                self.op_submit(pool_id, oid, "watch",
                               data={"cookie": cookie}, attempts=2)
            except (ConnectionError, OSError):
                pass  # next map change retries

    # -- targeting ---------------------------------------------------------
    # mutations route to write_tier, everything else to read_tier
    # (reference: Objecter::_calc_target's CEPH_OSD_FLAG_WRITE split)
    _WRITE_OPS = frozenset(
        {"write_full", "write", "append", "delete", "setxattr",
         "omap_set", "omap_rm", "omap_clear", "exec", "watch", "unwatch",
         "notify"}
    )

    def _resolve_overlay(self, m, pool_id: int, op: str,
                         ignore_overlay: bool) -> int:
        """Cache-tier overlay redirect (reference: Objecter::_calc_target
        honoring pg_pool_t::read_tier/write_tier unless the op carries
        CEPH_OSD_FLAG_IGNORE_OVERLAY).  Pool listings stay on the pool
        the caller named — `rados ls` on the base enumerates the base."""
        pool = m.pools.get(pool_id)
        if pool is None or ignore_overlay or op in ("list", "scrub", "scrub-noprepair"):
            return pool_id
        tier = pool.write_tier if op in self._WRITE_OPS else pool.read_tier
        if tier >= 0 and tier in m.pools:
            return tier
        return pool_id

    def _min_size_unreachable(self, m, pool_id: int, oid: str,
                              op: str) -> bool:
        """True when the local map proves the object's PG cannot reach
        min_size (fewer than min_size acting shards are up) — the state
        where an EAGAIN retry loop cannot succeed until the map changes."""
        if m is None:
            return False
        pool = m.pools.get(pool_id)
        if pool is None:
            return False
        try:
            ps = (int(oid[4:]) if op in ("list", "scrub", "scrub-noprepair")
                  and oid.startswith(":pg:") else object_ps(oid, pool.pg_num))
            _up, _upp, acting, _primary = m.pg_to_up_acting_osds(pool_id, ps)
        except Exception:
            return False
        reachable = sum(1 for o in acting if o >= 0 and m.is_up(o))
        return reachable < pool.min_size

    def _calc_target(
        self, pool_id: int, oid: str, op: str = ""
    ) -> tuple[int, tuple]:
        """reference: Objecter::_calc_target — pg from the object name,
        primary from the local map."""
        m = self.mc.osdmap
        if m is None:
            raise ConnectionError("no osdmap yet")
        pool = m.pools.get(pool_id)
        if pool is None:
            raise KeyError(f"no pool {pool_id}")
        if op in ("list", "scrub", "scrub-noprepair") and oid.startswith(":pg:"):
            # pg-targeted pseudo-oid — honored by the OSD only for these
            # ops; anything else treats ':pg:*' as a normal name
            ps = int(oid[4:])
        else:
            ps = object_ps(oid, pool.pg_num)
        _up, _upp, _acting, primary = m.pg_to_up_acting_osds(pool_id, ps)
        addr = m.osd_addrs.get(primary)
        if primary < 0 or addr is None:
            raise ConnectionError(f"pg {pool_id}.{ps} has no primary")
        return primary, tuple(addr)

    # -- ops ---------------------------------------------------------------
    def op_submit(self, pool_id: int, oid: str, op: str,
                  data: bytes | None = None, **kw):
        """Submit; blocks for the reply, retrying across map changes.

        Admission rides the objecter_inflight_ops /
        objecter_inflight_op_bytes throttles (common/throttle.py
        Throttle, reference: Objecter's op budget): a full window blocks
        new logical ops until completions drain it, FIFO-fair.  An op
        larger than the whole byte budget is admitted only once the
        window is empty, rather than deadlocking (Throttle's oversize
        rule).
        """
        my_bytes = (len(data)
                    if isinstance(data, (bytes, bytearray, memoryview))
                    else 0)
        conf = self.cct.conf if self.cct else None
        # cephtrace birth: ONE head-based coin flip per logical op (the
        # trace context then rides every resend attempt unchanged);
        # tracing disabled = this single attribute check inside
        # sampled_ctx, nothing else on the path.  trace_tail_latency_ms
        # arms tail sampling: a losing flip still mints a PROVISIONAL
        # context whose spans buffer until this op's completion latency
        # renders the promote/discard verdict (cephmeter).
        root_span = None
        tail_ms = 0.0
        provisional = False
        t_e2e0 = 0.0
        if TRACER.enabled:
            rate = float(conf.get("trace_sampling_rate")) if conf else 1.0
            tail_ms = (float(conf.get("trace_tail_latency_ms"))
                       if conf else 0.0)
            tctx = sampled_ctx(rate, tail=tail_ms > 0.0)
            provisional = TRACER.is_provisional(
                tctx.trace_id if tctx is not None else None)
            root_span = TRACER.begin(
                tctx, "op_submit",
                entity=self.cct.name if self.cct else "client",
                op=op, pool=pool_id, oid=oid, nbytes=my_bytes,
            )
            t_e2e0 = trace_now()
        max_ops = int(conf.get("objecter_inflight_ops")) if conf else 0
        max_bytes = int(conf.get("objecter_inflight_op_bytes")) if conf else 0
        if max_ops != self._op_throttle.max:
            self._op_throttle.reset_max(max_ops)
        if max_bytes != self._bytes_throttle.max:
            self._bytes_throttle.reset_max(max_bytes)
        # one combined admission deadline across both throttles, like
        # the single wait_for this replaced — not timeout twice over
        timeout = kw.get("timeout", 30.0)
        deadline = _monotonic() + timeout
        if not self._op_throttle.get(1, timeout=timeout):
            # throttle-starved ops are exactly what tracing is for: end
            # the root span with the error rather than dropping it (and
            # a provisional trace that starved at admission is a
            # straggler by definition — promote it)
            TRACER.end(root_span, error="inflight-op throttle full")
            if provisional and root_span is not None:
                TRACER.promote(root_span.trace_id, reason="throttle")
            raise ConnectionError(
                f"op {op} {oid!r}: inflight-op throttle full "
                f"({self._op_throttle.current}/{max_ops} ops)")
        remain = max(0.0, deadline - _monotonic())
        if not self._bytes_throttle.get(my_bytes, timeout=remain):
            self._op_throttle.put(1)
            TRACER.end(root_span, error="inflight-byte throttle full")
            if provisional and root_span is not None:
                TRACER.promote(root_span.trace_id, reason="throttle")
            raise ConnectionError(
                f"op {op} {oid!r}: inflight-byte throttle full "
                f"({self._bytes_throttle.current}/{max_bytes} bytes)")
        try:
            rep = self._op_submit(pool_id, oid, op, data=data,
                                  _trace_span=root_span, **kw)
            TRACER.end(root_span, retval=rep.retval)
            return rep
        except BaseException as e:
            TRACER.end(root_span, error=repr(e))
            raise
        finally:
            if provisional and root_span is not None:
                # the client-side tail verdict: a provisional trace
                # whose e2e crossed the threshold is kept; otherwise
                # discard — unless a daemon (complaint-time promotion
                # at the primary) already promoted it, which wins
                e2e_ms = (trace_now() - t_e2e0) * 1e3
                if e2e_ms >= tail_ms:
                    TRACER.promote(root_span.trace_id,
                                   reason="client_e2e")
                else:
                    TRACER.discard(root_span.trace_id)
            self._bytes_throttle.put(my_bytes)
            self._op_throttle.put(1)

    def _op_submit(
        self,
        pool_id: int,
        oid: str,
        op: str,
        data: bytes | None = None,
        off: int = 0,
        length: int = 0,
        timeout: float = 30.0,
        attempts: int = 8,
        snapid: int | None = None,
        ignore_overlay: bool = False,
        snapc_seq: int = 0,
        _trace_span=None,
    ):
        """The retry loop under op_submit's admission throttle."""
        import time as _time

        last = None
        # ONE logical-op id across every resend attempt: a reply lost in
        # flight after the primary applied must not re-execute the op
        # (append would double-append; an RMW would double-apply) — the
        # primary's dup cache answers the resend instead
        with self._lock:
            self._tid += 1
            logical_tid = self._tid
        reqid = f"{self._nonce}:{logical_tid}"
        # -EAGAIN refusals (degraded below min_size, existence unknown,
        # op in flight) are TIME-bounded, not attempt-bounded: recovery
        # may legitimately need longer than 8 quick retries to restore
        # min_size, and the op is already durably logged in the
        # 'applied' case — giving up early turns a pending success into
        # a spurious client error.  objecter_eagain_patience overrides
        # for callers that would rather fail fast against a pool that
        # cannot reach min_size (advisor r3)
        patience = (self.cct.conf.get("objecter_eagain_patience")
                    if self.cct else 0.0)
        if not patience:
            patience = max(60.0, 2 * timeout)
        eagain_deadline = _time.monotonic() + patience
        hard = 0
        while hard < attempts:
            m = self.mc.osdmap
            # overlay redirect re-resolves every attempt: a mid-op
            # set-overlay / remove-overlay retargets the resend
            target_pool = (
                self._resolve_overlay(m, pool_id, op, ignore_overlay)
                if m is not None else pool_id
            )
            # snap context rides every mutation (reference: MOSDOp's
            # SnapContext) so a primary whose map lags a fresh mksnap
            # still clones before overwriting
            snap_seq = 0
            if m is not None and op in ("write_full", "write", "append",
                                        "delete"):
                p = m.pools.get(target_pool)
                # newest LIVE snap, not snap_seq: after the last rmsnap
                # there is nothing left to preserve, and a stale high seq
                # would make primaries mint un-trimmable clones forever
                snap_seq = max(p.snaps, default=0) if p is not None else 0
                # self-managed context (reference: the caller-supplied
                # SnapContext CephFS/RBD ride): the MDS allocates snapids
                # outside the pool registry, so the per-op seq wins
                snap_seq = max(snap_seq, snapc_seq)
            try:
                _osd, addr = self._calc_target(target_pool, oid, op)
            except (ConnectionError, KeyError) as e:
                last = str(e)
                hard += 1
                self._refresh_map(m)
                continue
            with self._lock:
                self._tid += 1
                tid = self._tid
                self._outstanding.add(tid)
            try:
                conn = self.messenger.connect(addr)
                # bytes payloads ride base64; structured payloads (xattr
                # update maps) ride as-is in the JSON body
                wire_data = (
                    pack_data(data)
                    if isinstance(data, (bytes, bytearray, memoryview))
                    else data
                )
                conn.send_message(
                    MOSDOp(
                        tid=tid, pool=target_pool, oid=oid, op=op,
                        data=wire_data,
                        epoch=m.epoch if m else 0, off=off, length=length,
                        snapid=snapid, snap_seq=snap_seq, reqid=reqid,
                        trace_id=(_trace_span.trace_id
                                  if _trace_span is not None else None),
                        parent_span=(_trace_span.span_id
                                     if _trace_span is not None else None),
                    )
                )
            except (OSError, ConnectionError) as e:
                last = str(e)
                hard += 1
                with self._lock:
                    self._outstanding.discard(tid)
                self._refresh_map(m)
                continue
            with self._lock:
                ok = self._cond.wait_for(
                    lambda: tid in self._replies, timeout=timeout
                )
                rep = self._replies.pop(tid, None) if ok else None
                self._outstanding.discard(tid)
            if rep is None:
                last = "op timed out"
                hard += 1
                self._refresh_map(m)
                continue
            if rep.retval == -116:  # wrong primary: map changed under us
                last = "stale map"
                hard += 1
                self._refresh_map(m)
                continue
            if rep.retval == -122:
                # EDQUOT: the pool is over quota — final, no retry (only
                # deletes or a raised quota can clear it)
                return rep
            if rep.retval == -11:  # not enough shards yet; let it settle
                last = rep.result
                if _time.monotonic() >= eagain_deadline:
                    break
                # min_size short-circuit (advisor r3 / r4 verdict #7):
                # when OUR OWN map already shows the PG cannot reach
                # min_size (too few acting shards up), waiting out the
                # full patience is pointless — only a map change can
                # help, so wait for one map push and fail fast if the
                # map still says unreachable
                if self._min_size_unreachable(m, target_pool, oid, op):
                    self._refresh_map(m)
                    m2 = self.mc.osdmap
                    if (
                        (m2 is None or m is None or m2.epoch == m.epoch
                         or self._min_size_unreachable(m2, target_pool,
                                                       oid, op))
                    ):
                        last = (f"{last} (map shows min_size "
                                f"unreachable; failing fast)")
                        break
                    continue
                _time.sleep(0.3)
                self._refresh_map(m)
                continue
            return rep
        raise ConnectionError(f"op {op} {oid!r} failed after retries: {last}")

    def _refresh_map(self, old) -> None:
        """Wait briefly for a newer epoch (reference: the Objecter blocks
        ops on map gaps; subscriptions push the new map)."""
        want = (old.epoch + 1) if old is not None else 1
        try:
            self.mc.wait_for_osdmap(min_epoch=want, timeout=3.0)
        except TimeoutError:
            pass

