"""Rados / IoCtx — the librados-analog public client API (reference:
src/include/rados/librados.hpp :: Rados/IoCtx, src/librados/RadosClient.cc;
SURVEY.md §2.6).

    r = Rados(cct, mon_addrs)
    r.connect()
    io = r.open_ioctx("mypool")
    io.write_full("obj", b"bytes")
    io.read("obj")
    r.shutdown()
"""
from __future__ import annotations

from ..mon.mon_client import MonClient
from ..osd.messages import pack_data, unpack_data
from .objecter import Objecter


class IoCtx:
    """Per-pool I/O context (reference: librados::IoCtx)."""

    def __init__(self, client: "Rados", pool_id: int, pool_name: str):
        self._client = client
        self.pool_id = pool_id
        self.pool_name = pool_name

    def write_full(self, oid: str, data: bytes,
                   snapc_seq: int = 0) -> int:
        rep = self._client.objecter.op_submit(
            self.pool_id, oid, "write_full", data=bytes(data),
            snapc_seq=snapc_seq,
        )
        if rep.retval != 0:
            raise IOError(f"write_full {oid!r}: {rep.retval} {rep.result}")
        return rep.retval

    def write(self, oid: str, data: bytes, off: int = 0) -> int:
        """Ranged write (reference: rados_write): splices `data` into the
        object at `off`, growing it if needed; a gap below `off` on a new
        object reads back as zeros.  On EC pools this is the
        partial-stripe RMW path (parity-delta update)."""
        rep = self._client.objecter.op_submit(
            self.pool_id, oid, "write", data=bytes(data), off=off
        )
        if rep.retval != 0:
            raise IOError(f"write {oid!r}@{off}: {rep.retval} {rep.result}")
        return rep.retval

    def append(self, oid: str, data: bytes) -> int:
        """reference: rados_append — write at the current object size."""
        rep = self._client.objecter.op_submit(
            self.pool_id, oid, "append", data=bytes(data)
        )
        if rep.retval != 0:
            raise IOError(f"append {oid!r}: {rep.retval} {rep.result}")
        return rep.retval

    def read(self, oid: str, off: int = 0, length: int = 0,
             snapid: int | None = None) -> bytes:
        """`snapid` reads the pool-snapshot view (reference: IoCtx
        snap_set_read + read)."""
        rep = self._client.objecter.op_submit(
            self.pool_id, oid, "read", off=off, length=length,
            snapid=snapid,
        )
        if rep.retval != 0:
            raise IOError(f"read {oid!r}: {rep.retval} {rep.result}")
        return unpack_data(rep.data) or b""

    # -- cache tiering (reference: rados cache-flush / cache-evict ops;
    # this IoCtx must be open on the CACHE pool) --------------------------
    def cache_flush(self, oid: str) -> object:
        rep = self._client.objecter.op_submit(
            self.pool_id, oid, "cache_flush", ignore_overlay=True
        )
        if rep.retval != 0:
            raise IOError(f"cache_flush {oid!r}: {rep.retval} {rep.result}")
        return rep.result

    def cache_evict(self, oid: str) -> object:
        rep = self._client.objecter.op_submit(
            self.pool_id, oid, "cache_evict", ignore_overlay=True
        )
        if rep.retval != 0:
            raise IOError(f"cache_evict {oid!r}: {rep.retval} {rep.result}")
        return rep.result

    # -- omap (reference: rados_omap_* — replicated pools only) -----------
    def exec(self, oid: str, cls: str, method: str,
             inp: dict | None = None) -> tuple[int, object]:
        """Run a server-side object-class method at the object's primary
        (reference: rados_exec / librados::IoCtx::exec; classes in
        ceph_tpu/osd/classes.py).  Returns (retval, out) — retval < 0 is
        the METHOD's verdict (e.g. -17 for a failed create guard), which
        callers branch on; transport/cluster failures raise IOError."""
        rep = self._client.objecter.op_submit(
            self.pool_id, oid, "exec",
            data={"cls": cls, "method": method, "in": inp or {}},
        )
        # method verdicts come back wrapped in "cls_out"; anything else
        # with a non-zero retval is a cluster-side refusal (unknown
        # class, EC pool, no pool, min_size)
        if isinstance(rep.result, dict) and "cls_out" in rep.result:
            return rep.retval, rep.result["cls_out"]
        if rep.retval == 0:
            return 0, rep.result  # dup-cache resend of an applied exec
        raise IOError(f"exec {oid!r} {cls}.{method}: "
                      f"{rep.retval} {rep.result}")

    def omap_set(self, oid: str, kv: dict[str, bytes]) -> None:
        rep = self._client.objecter.op_submit(
            self.pool_id, oid, "omap_set",
            data={"keys": {k: pack_data(bytes(v)) for k, v in kv.items()}},
        )
        if rep.retval != 0:
            raise IOError(f"omap_set {oid!r}: {rep.retval} {rep.result}")

    def omap_get(self, oid: str, keys=None) -> dict[str, bytes]:
        """All pairs (keys=None) or just `keys` (reference:
        omap_get_vals_by_keys)."""
        rep = self._client.objecter.op_submit(
            self.pool_id, oid, "omap_get",
            data={"keys": list(keys) if keys is not None else None},
        )
        if rep.retval != 0:
            raise IOError(f"omap_get {oid!r}: {rep.retval} {rep.result}")
        return {k: unpack_data(v) for k, v in rep.result["kv"].items()}

    def omap_get_vals(self, oid: str, after: str = "",
                      max_return: int = 512) -> dict[str, bytes]:
        """Paginated scan: keys strictly greater than `after`, up to
        `max_return` (reference: rados_omap_get_vals)."""
        rep = self._client.objecter.op_submit(
            self.pool_id, oid, "omap_get",
            data={"after": after, "max": max_return},
        )
        if rep.retval != 0:
            raise IOError(f"omap_get_vals {oid!r}: {rep.retval} {rep.result}")
        return {k: unpack_data(v) for k, v in rep.result["kv"].items()}

    def omap_rm_keys(self, oid: str, keys) -> None:
        rep = self._client.objecter.op_submit(
            self.pool_id, oid, "omap_rm", data={"keys": list(keys)},
        )
        if rep.retval != 0:
            raise IOError(f"omap_rm {oid!r}: {rep.retval} {rep.result}")

    def omap_clear(self, oid: str) -> None:
        rep = self._client.objecter.op_submit(
            self.pool_id, oid, "omap_clear", data={},
        )
        if rep.retval != 0:
            raise IOError(f"omap_clear {oid!r}: {rep.retval} {rep.result}")

    # -- watch / notify (reference: rados_watch3 / rados_notify2) ---------
    def watch(self, oid: str, callback) -> int:
        """Register a watch; `callback(notify_id, cookie, data: bytes)`
        fires for each notify.  Returns the watch cookie.  The watch
        lingers: the Objecter re-registers it after a map change, so it
        survives primary failover (reference: linger ops)."""
        return self._client.objecter.watch(self.pool_id, oid, callback)

    def unwatch(self, oid: str, cookie: int) -> None:
        self._client.objecter.unwatch(self.pool_id, oid, cookie)

    def notify(self, oid: str, data: bytes = b"",
               timeout: float = 5.0) -> dict:
        """Fire a notify and collect watcher acks; returns
        {"acked": [cookies], "missed": [cookies]}."""
        rep = self._client.objecter.op_submit(
            self.pool_id, oid, "notify",
            data={"payload": pack_data(bytes(data)), "timeout": timeout},
            timeout=max(30.0, timeout + 10.0),
        )
        if rep.retval != 0:
            raise IOError(f"notify {oid!r}: {rep.retval} {rep.result}")
        return rep.result

    # -- pool snapshots (reference: rados_ioctx_snap_create/remove etc.) --
    def _pool(self):
        m = self._client.mc.osdmap
        if m is None or self.pool_id not in m.pools:
            raise IOError(f"pool {self.pool_id} not in the current map")
        return m.pools[self.pool_id]

    def snap_create(self, name: str) -> int:
        rv, res = self._client.command({
            "prefix": "osd pool mksnap",
            "name": self.pool_name, "snapname": name,
        })
        if rv != 0:
            raise IOError(f"mksnap {name!r}: {rv} {res}")
        sid = res["snapid"]
        # block until OUR map carries the snap: the next write's snap
        # context must include it (reference: librados waits for the
        # map epoch the mon committed)
        self._wait_map(lambda p: p.snap_seq >= sid)
        return sid

    def snap_remove(self, name: str) -> None:
        rv, res = self._client.command({
            "prefix": "osd pool rmsnap",
            "name": self.pool_name, "snapname": name,
        })
        if rv != 0:
            raise IOError(f"rmsnap {name!r}: {rv} {res}")
        removed = res["removed"]
        self._wait_map(lambda p: removed not in p.snaps)

    def _wait_map(self, pred, timeout: float = 10.0) -> None:
        import time as _time

        deadline = _time.monotonic() + timeout
        while _time.monotonic() < deadline:
            m = self._client.mc.osdmap
            p = m.pools.get(self.pool_id) if m is not None else None
            if p is not None and pred(p):
                return
            e = m.epoch if m else 0
            try:
                self._client.mc.wait_for_osdmap(
                    min_epoch=e + 1, timeout=1.0
                )
            except TimeoutError:
                pass
        raise IOError("timed out waiting for the snap map epoch")

    def snap_list(self) -> dict[int, str]:
        self._client.mc.wait_for_osdmap(timeout=10.0)
        return dict(self._pool().snaps)

    def snap_lookup(self, name: str) -> int:
        for sid, n in self.snap_list().items():
            if n == name:
                return sid
        raise KeyError(f"no snap {name!r}")

    def snap_rollback(self, oid: str, snapname: str) -> None:
        """reference: rados_ioctx_snap_rollback — restore the head to the
        snapshot's content (client-side: snap read then write_full)."""
        self.write_full(oid, self.read(oid, snapid=self.snap_lookup(snapname)))

    def remove(self, oid: str, snapc_seq: int = 0) -> None:
        rep = self._client.objecter.op_submit(
            self.pool_id, oid, "delete", snapc_seq=snapc_seq)
        if rep.retval != 0:
            raise IOError(f"remove {oid!r}: {rep.retval} {rep.result}")

    def stat(self, oid: str) -> dict:
        rep = self._client.objecter.op_submit(self.pool_id, oid, "stat")
        if rep.retval != 0:
            raise IOError(f"stat {oid!r}: {rep.retval} {rep.result}")
        return rep.result

    def set_xattr(self, oid: str, name: str, value: bytes) -> None:
        """reference: rados_setxattr."""
        from ..osd.messages import pack_data

        if name.startswith("_"):
            raise IOError(
                f"xattr {name!r}: '_'-prefixed names are reserved for "
                "framework metadata (snapshot bookkeeping)"
            )
        rep = self._client.objecter.op_submit(
            self.pool_id, oid, "setxattr",
            data={name: pack_data(bytes(value))},
        )
        if rep.retval != 0:
            raise IOError(f"setxattr {oid!r}: {rep.retval} {rep.result}")

    def rm_xattr(self, oid: str, name: str) -> None:
        """reference: rados_rmxattr."""
        if name.startswith("_"):
            raise IOError(f"xattr {name!r}: '_'-prefixed names are reserved")
        rep = self._client.objecter.op_submit(
            self.pool_id, oid, "setxattr", data={name: None}
        )
        if rep.retval != 0:
            raise IOError(f"rm_xattr {oid!r}: {rep.retval} {rep.result}")

    def get_xattrs(self, oid: str) -> dict[str, bytes]:
        """reference: rados_getxattrs."""
        rep = self._client.objecter.op_submit(self.pool_id, oid, "getxattrs")
        if rep.retval != 0:
            raise IOError(f"getxattrs {oid!r}: {rep.retval} {rep.result}")
        return {
            k: unpack_data(v) for k, v in (rep.result or {}).items()
            if not k.startswith("_")  # '_'-names are framework-internal
        }

    def get_xattr(self, oid: str, name: str) -> bytes:
        attrs = self.get_xattrs(oid)
        if name not in attrs:
            raise KeyError(name)
        return attrs[name]

    def scrub_pg(self, ps: int, repair: bool = True) -> dict:
        """Deep-scrub one PG on its primary; returns the scrub report.
        repair=False inspects only — divergent replicas are reported,
        not rewritten (reference: `ceph pg deep-scrub` vs `pg repair`
        reaching the primary)."""
        rep = self._client.objecter.op_submit(
            self.pool_id, f":pg:{ps}",
            "scrub" if repair else "scrub-noprepair", timeout=60.0
        )
        if rep.retval != 0:
            raise IOError(f"scrub pg {ps}: {rep.retval} {rep.result}")
        return rep.result

    def scrub(self) -> list[dict]:
        """Deep-scrub every PG of the pool."""
        m = self._client.mc.osdmap
        pool = m.pools[self.pool_id]
        return [self.scrub_pg(ps) for ps in range(pool.pg_num)]

    def list_objects(self) -> list[str]:
        """Walk every PG primary (reference: librados nobjects_begin)."""
        m = self._client.mc.osdmap
        pool = m.pools[self.pool_id]
        oids: set[str] = set()
        for ps in range(pool.pg_num):
            rep = self._client.objecter.op_submit(
                self.pool_id, f":pg:{ps}", "list"
            )
            if rep.retval == 0 and isinstance(rep.result, dict):
                oids.update(rep.result.get("oids") or [])
        return sorted(oids)


class Rados:
    """reference: librados::Rados — cluster handle."""

    def __init__(self, cct, mon_addrs, name: str = "client.admin"):
        self.cct = cct
        self.mc = MonClient(cct, mon_addrs, name=name)
        self.objecter: Objecter | None = None
        self._name = name

    def connect(self, timeout: float = 15.0) -> None:
        self.objecter = Objecter(self.cct, self.mc, name=self._name)
        self.mc.wait_for_osdmap(timeout=timeout)

    def shutdown(self) -> None:
        if self.objecter is not None:
            self.objecter.shutdown()
        self.mc.shutdown()

    def command(self, cmd: dict, timeout: float = 15.0):
        """Mon command passthrough (the `ceph` CLI surface)."""
        return self.mc.command(cmd, timeout=timeout)

    def pool_id(self, name: str) -> int:
        m = self.mc.osdmap
        if m is None:
            raise ConnectionError("not connected")
        for pid, p in m.pools.items():
            if p.name == name:
                return pid
        raise KeyError(f"no pool {name!r}")

    def open_ioctx(self, pool: str | int) -> IoCtx:
        if isinstance(pool, str):
            pid = self.pool_id(pool)
            return IoCtx(self, pid, pool)
        pname = self.mc.osdmap.pools[pool].name if self.mc.osdmap else str(pool)
        return IoCtx(self, pool, pname)
