"""RBD-analog block images over the striper (reference: src/librbd —
librbd::Image create/open/read/write/resize/remove over striped RADOS
objects; SURVEY.md §2.6 gateways).

Scope vs the reference, stated plainly: the data path (an image = a
header object + data striped over `{id}.<objectno>` objects) matches
librbd's native format at the block level; snapshots, clones, journaling,
mirroring, and the kernel client are mon/feature machinery this analog
does not carry.

    rbd = RBD(ioctx)
    rbd.create("vol1", size=1 << 30)
    with rbd.open("vol1") as img:
        img.write(b"...", off)
        img.read(off, length)
        img.resize(2 << 30)
"""
from __future__ import annotations

import json

from .striper import StripedObject, StripePolicy

_HEADER_SUFFIX = ".rbd_header"


class ImageExists(IOError):
    pass


class ImageNotFound(IOError):
    pass


class Image:
    """An open image handle (reference: librbd::Image)."""

    def __init__(self, io, name: str, header: dict):
        self._io = io
        self.name = name
        self._header = header
        self._data = StripedObject(
            io, header["block_name_prefix"],
            StripePolicy(
                object_size=1 << header["order"],
                stripe_unit=header["stripe_unit"],
                stripe_count=header["stripe_count"],
            ),
        )

    # -- metadata -----------------------------------------------------------
    def size(self) -> int:
        return self._header["size"]

    def stat(self) -> dict:
        return dict(self._header)

    # -- I/O ------------------------------------------------------------—--
    def read(self, off: int, length: int) -> bytes:
        if off >= self.size():
            return b""
        length = min(length, self.size() - off)
        data = self._data.read(off, length)
        # unwritten ranges inside the image read as zeros (thin provision)
        return data + b"\0" * (length - len(data))

    def write(self, data: bytes, off: int) -> int:
        if off + len(data) > self.size():
            raise IOError(
                f"write past end of image ({off + len(data)} > {self.size()})"
            )
        self._data.write(data, off)
        return len(data)

    def resize(self, size: int) -> None:
        if size < self.size():
            self._data.truncate(size)
        self._header["size"] = size
        self._io.write_full(
            self.name + _HEADER_SUFFIX, json.dumps(self._header).encode()
        )

    def flush(self) -> None:  # writes are synchronous; parity of API
        pass

    def close(self) -> None:
        pass

    def __enter__(self) -> "Image":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


class RBD:
    """Image administration (reference: librbd::RBD)."""

    def __init__(self, io):
        self._io = io

    def create(self, name: str, size: int, order: int = 22,
               stripe_unit: int | None = None, stripe_count: int = 1) -> None:
        """order: log2 of the object size, default 4 MiB objects — the
        reference's default layout."""
        hdr_oid = name + _HEADER_SUFFIX
        try:
            self._io.read(hdr_oid)
            raise ImageExists(f"image {name!r} exists")
        except ImageExists:
            raise
        except IOError:
            pass
        object_size = 1 << order
        su = stripe_unit or object_size
        StripePolicy(object_size=object_size, stripe_unit=su,
                     stripe_count=stripe_count)  # validate layout
        header = {
            "name": name,
            "size": int(size),
            "order": order,
            "stripe_unit": su,
            "stripe_count": stripe_count,
            "block_name_prefix": f"rbd_data.{name}",
        }
        self._io.write_full(hdr_oid, json.dumps(header).encode())

    def open(self, name: str) -> Image:
        try:
            raw = self._io.read(name + _HEADER_SUFFIX)
        except IOError as e:
            raise ImageNotFound(f"no image {name!r}") from e
        return Image(self._io, name, json.loads(raw))

    def list(self) -> list[str]:
        out = []
        for oid in self._io.list_objects():
            if oid.endswith(_HEADER_SUFFIX):
                out.append(oid[: -len(_HEADER_SUFFIX)])
        return sorted(out)

    def remove(self, name: str) -> None:
        img = self.open(name)
        img._data.remove()
        self._io.remove(name + _HEADER_SUFFIX)
