"""RBD-analog block images over the striper (reference: src/librbd —
librbd::Image create/open/read/write/resize/remove, snapshot
create/rollback/protect, and clone/flatten COW machinery over striped
RADOS objects; SURVEY.md §2.6 gateways).

Design, stated plainly:

- An image is a JSON header object + data striped over
  `rbd_data.{name}.{objectno:016x}` objects (librbd's native layout at
  the block level).
- **Snapshots** ride the pool-snapshot substrate: `snap_create` takes a
  pool snap (named `rbd.{image}@{snap}` — '@' is banned in both names,
  so this cannot collide across images) and records {name -> snapid,
  size} in the header; a
  snap-opened image reads its data objects at that snapid (the OSD's
  per-object clone machinery serves the old bytes).  This replaces
  librbd's self-managed snap context — the visible semantics (point-in-
  time reads, rollback, protection) match.
- **Clones** are COW children at whole-object granularity: the child's
  header carries `parent = {image, snap, snap_id, overlap}`; a read of
  an object the child does not yet own falls through to the parent's
  snap view, and the first write to such an object copies the parent's
  object up first (librbd's copy-up).  `flatten` copies every remaining
  parent object and severs the link.  Children are registered in the
  pool's `rbd_children` omap so `snap_unprotect` can refuse while
  clones exist (reference: cls_rbd's rbd_children directory).
- **Journaling** (reference: librbd's journaling feature): with the
  feature enabled, every mutation (write/resize/snap ops) appends a
  write-ahead record to `journal.{image}.{tid:016x}` objects BEFORE
  applying; `journal.{image}` (the journal header) tracks the next tid
  and each registered client's commit position, and records committed
  by every client are trimmed.  **Mirroring** (reference: the
  rbd-mirror daemon) tails a primary image's journal and replays it
  onto a same-name image in a peer pool: non-primary replicas refuse
  client writes, promote/demote flips the primary side for failover
  (ceph_tpu/client/rbd_mirror.py).  The kernel client remains out of
  scope.

    rbd = RBD(ioctx)
    rbd.create("vol1", size=1 << 30)
    with rbd.open("vol1") as img:
        img.write(b"...", off)
        img.snap_create("s1")
        img.snap_protect("s1")
    rbd.clone("vol1", "s1", "vol2")
"""
from __future__ import annotations

import json

from .striper import ExtentIO, StripePolicy

_HEADER_SUFFIX = ".rbd_header"
_CHILDREN_OID = "rbd_children"


class ImageExists(IOError):
    pass


class ImageNotFound(IOError):
    pass


class ReadOnlyImage(IOError):
    pass


class SnapshotError(IOError):
    pass


class ImageBusy(IOError):
    pass


def _check_name(kind: str, name: str) -> None:
    """Image/snap names must not contain '@' (it separates image from
    snap in the pool-snap encoding and the img@snap spec syntax, like
    the reference refuses it) or be empty."""
    if not name or "@" in name:
        raise ValueError(f"bad {kind} name {name!r}")


def _pool_snap_name(image: str, snap: str) -> str:
    # '@' appears in neither component (_check_name), so this cannot
    # collide across images
    return f"rbd.{image}@{snap}"


def _parent_oid(p: dict, objectno: int) -> str:
    return f"{p['block_name_prefix']}.{objectno:016x}"


def _children_of(io, parent: str, snap: str) -> list[str]:
    """Clone children registered under parent@snap; [] when the
    rbd_children directory object does not exist yet."""
    key = f"{parent}@{snap}"
    try:
        cur = io.omap_get(_CHILDREN_OID, keys=[key]).get(key)
    except IOError:
        return []
    return json.loads(cur.decode()) if cur else []


class Image:
    """An open image handle (reference: librbd::Image).  Pass `snap` at
    open for a read-only point-in-time view.  `_replaying` marks a
    mirror-replay handle: it may mutate a NON-PRIMARY replica and must
    not re-journal the replayed ops."""

    def __init__(self, io, name: str, header: dict, snap: str | None = None,
                 _replaying: bool = False):
        self._io = io
        self.name = name
        self._header = header
        self.snap_name = snap
        self._replaying = _replaying
        if snap is not None:
            if snap not in header.get("snaps", {}):
                raise SnapshotError(f"image {name!r} has no snap {snap!r}")
            self._snap = header["snaps"][snap]
        else:
            self._snap = None
        self._policy = StripePolicy(
            object_size=1 << header["order"],
            stripe_unit=header["stripe_unit"],
            stripe_count=header["stripe_count"],
        )
        # the header is the size authority (librbd keeps no sidecar), so
        # the image drives the extent engine directly — copy-up, rollback
        # and flatten write objects without any logical-size bookkeeping
        self._ext = ExtentIO(io, self._data_oid, self._policy)

    # -- metadata -----------------------------------------------------------
    def size(self) -> int:
        return self._snap["size"] if self._snap else self._header["size"]

    def stat(self) -> dict:
        return dict(self._header)

    def parent_info(self) -> dict | None:
        """(reference: librbd::Image::parent_info) None for non-clones."""
        p = self._header.get("parent")
        return dict(p) if p else None

    def _save_header(self) -> None:
        self._io.write_full(
            self.name + _HEADER_SUFFIX, json.dumps(self._header).encode()
        )

    def _data_oid(self, objectno: int) -> str:
        return f"{self._header['block_name_prefix']}.{objectno:016x}"

    # -- journaling (reference: librbd Journal<I>::append_io_event) --------
    def _journaled(self) -> bool:
        return "journaling" in self._header.get("features", [])

    def _check_writable(self) -> None:
        if self._snap is not None:
            raise ReadOnlyImage(f"{self.name}@{self.snap_name} is read-only")
        mir = self._header.get("mirror")
        if mir and not mir.get("primary", True) and not self._replaying:
            raise ReadOnlyImage(
                f"{self.name!r} is a non-primary mirror replica"
            )

    def _journal_append(self, record: dict):
        """Write-ahead: the record is durable BEFORE the mutation applies.
        A crash between append and apply is healed at the next open —
        RBD.open replays the primary's own uncommitted tail through the
        __local__ journal client (librbd's open-time journal replay);
        every record is an idempotent absolute-state setter.  Returns
        the tid (None when not journaling)."""
        if not self._journaled() or self._replaying:
            return None
        from .rbd_mirror import journal_append

        return journal_append(self._io, self.name, record)

    def _journal_applied(self, tid) -> None:
        """Mark a just-applied record committed for the local side; also
        drives trimming, so an image with no mirror peer registered
        cannot grow its journal without bound (review r5)."""
        if tid is None:
            return
        from .rbd_mirror import LOCAL_CLIENT, journal_commit

        journal_commit(self._io, self.name, LOCAL_CLIENT, tid)

    # -- parent (clone) plumbing -------------------------------------------
    def _object_exists(self, objectno: int) -> bool:
        try:
            self._io.stat(self._data_oid(objectno))
            return True
        except IOError:
            return False

    def _copy_up(self, off: int, length: int) -> None:
        """Whole-object copy-up of every touched object the child does
        not own yet (reference: librbd copy-up before a child write).
        The parent shares this image's layout, so objectno N of the
        parent holds exactly the stream bytes objectno N of the child
        will: one object-level read-at-snap + write_full suffices —
        clipped to the clone overlap, so parent bytes a shrink-then-grow
        resize turned into zeros are not resurrected."""
        p = self._header.get("parent")
        if not p:
            return
        seen: set[int] = set()
        for objectno, _obj_off, _ln in self._policy.extents(off, length):
            if objectno in seen:
                continue
            seen.add(objectno)
            if self._object_exists(objectno):
                continue
            keep = self._policy.object_keep_len(objectno, p["overlap"])
            if keep == 0:
                continue  # entirely past the overlap: reads are zeros
            try:
                pdata = self._io.read(
                    _parent_oid(p, objectno), snapid=p["snap_id"]
                )
            except IOError:
                continue  # parent object absent at snap: nothing to copy
            if pdata[:keep]:
                self._io.write_full(self._data_oid(objectno), pdata[:keep])

    # -- I/O ----------------------------------------------------------------
    def read(self, off: int, length: int) -> bytes:
        if off >= self.size():
            return b""
        length = min(length, self.size() - off)
        p = self._header.get("parent")
        if self._snap is not None:
            if p is None:
                # ExtentIO pads every extent, so no padding needed here
                return self._ext.read(off, length, snapid=self._snap["id"])
            # snap view OF A CLONE: objects the child owned AT the snap
            # are authoritative; the rest falls through to the parent at
            # the overlap recorded when the snap was taken
            return self._read_with_parent(
                off, length, p,
                snapid=self._snap["id"],
                overlap=self._snap.get("overlap", p["overlap"]),
            )
        if p is None:
            return self._ext.read(off, length)
        return self._read_with_parent(off, length, p)

    def _read_with_parent(
        self, off: int, length: int, p: dict,
        snapid: int | None = None, overlap: int | None = None,
    ) -> bytes:
        """Per-extent merge: an object the child owns (copy-up or write
        already happened) is authoritative; otherwise the byte range
        falls through to the parent's snap view, clipped to the clone
        overlap (reference: librbd ObjectReadRequest's parent fallback).

        Ownership is the read attempt itself — a missing object (at head
        or, for a clone's snap view, at `snapid`) raises IOError while an
        existing one returns (possibly short) bytes — memoized per object
        so stripe rows don't re-probe."""
        pext = ExtentIO(
            self._io, lambda objectno: _parent_oid(p, objectno), self._policy
        )
        overlap = p["overlap"] if overlap is None else overlap
        kw = {} if snapid is None else {"snapid": snapid}
        owned: dict[int, bool] = {}
        parts: list[bytes] = []
        pos = off
        for objectno, obj_off, ln in self._policy.extents(off, length):
            chunk = None
            if owned.get(objectno, True):
                try:
                    chunk = self._io.read(
                        self._data_oid(objectno), off=obj_off, length=ln, **kw
                    )
                    owned[objectno] = True
                except IOError:
                    owned[objectno] = False
            if chunk is None:
                if pos < overlap:
                    take = min(ln, overlap - pos)
                    chunk = pext.read(pos, take, snapid=p["snap_id"])
                else:
                    chunk = b""
            parts.append(chunk + b"\0" * (ln - len(chunk)))
            pos += ln
        return b"".join(parts)

    def write(self, data: bytes, off: int) -> int:
        self._check_writable()
        if off + len(data) > self.size():
            raise IOError(
                f"write past end of image ({off + len(data)} > {self.size()})"
            )
        import base64

        tid = self._journal_append({
            "op": "write", "off": int(off),
            "data": base64.b64encode(bytes(data)).decode(),
        })
        self._copy_up(off, len(data))
        self._ext.write(data, off)
        self._journal_applied(tid)
        return len(data)

    def resize(self, size: int) -> None:
        self._check_writable()
        tid = self._journal_append({"op": "resize", "size": int(size)})
        if size < self.size():
            self._ext.truncate_data(self._header["size"], size)
            p = self._header.get("parent")
            if p and size < p["overlap"]:
                # shrinking below the overlap permanently narrows it
                # (reference: librbd shrink adjusts the parent overlap)
                p["overlap"] = size
        self._header["size"] = size
        self._save_header()
        self._journal_applied(tid)

    def flush(self) -> None:  # writes are synchronous; parity of API
        pass

    def close(self) -> None:
        pass

    def __enter__(self) -> "Image":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # -- snapshots ----------------------------------------------------------
    def snap_list(self) -> dict[str, dict]:
        return {n: dict(s) for n, s in self._header.get("snaps", {}).items()}

    def snap_create(self, snap: str) -> int:
        """Point-in-time snapshot (reference: librbd snap_create): a pool
        snap scoped by name to this image + a header record of the id
        and the size at snap time."""
        self._check_writable()
        _check_name("snap", snap)
        snaps = self._header.setdefault("snaps", {})
        if snap in snaps:
            raise SnapshotError(f"snap {snap!r} exists")
        tid = self._journal_append({"op": "snap_create", "snap": snap})
        sid = self._io.snap_create(_pool_snap_name(self.name, snap))
        snaps[snap] = {"id": sid, "size": self._header["size"],
                       "protected": False}
        p = self._header.get("parent")
        if p:
            # a clone's snap view needs the overlap AS OF the snap — a
            # later shrink narrows the live overlap but not this one
            snaps[snap]["overlap"] = p["overlap"]
        self._save_header()
        self._journal_applied(tid)
        return sid

    def snap_remove(self, snap: str) -> None:
        self._check_writable()
        snaps = self._header.get("snaps", {})
        if snap not in snaps:
            raise SnapshotError(f"no snap {snap!r}")
        if snaps[snap].get("protected"):
            raise ImageBusy(f"snap {snap!r} is protected")
        tid = self._journal_append({"op": "snap_remove", "snap": snap})
        self._io.snap_remove(_pool_snap_name(self.name, snap))
        del snaps[snap]
        self._save_header()
        self._journal_applied(tid)

    def snap_protect(self, snap: str) -> None:
        """Required before cloning (reference: librbd snap_protect)."""
        self._check_writable()
        snaps = self._header.get("snaps", {})
        if snap not in snaps:
            raise SnapshotError(f"no snap {snap!r}")
        tid = self._journal_append({"op": "snap_protect", "snap": snap})
        snaps[snap]["protected"] = True
        self._save_header()
        self._journal_applied(tid)

    def snap_unprotect(self, snap: str) -> None:
        self._check_writable()
        snaps = self._header.get("snaps", {})
        if snap not in snaps:
            raise SnapshotError(f"no snap {snap!r}")
        kids = _children_of(self._io, self.name, snap)
        if kids:
            raise ImageBusy(f"snap {snap!r} has clone children: {kids}")
        tid = self._journal_append({"op": "snap_unprotect", "snap": snap})
        snaps[snap]["protected"] = False
        self._save_header()
        self._journal_applied(tid)

    def snap_is_protected(self, snap: str) -> bool:
        snaps = self._header.get("snaps", {})
        if snap not in snaps:
            raise SnapshotError(f"no snap {snap!r}")
        return bool(snaps[snap].get("protected"))

    def snap_rollback(self, snap: str) -> None:
        """Restore the image head to the snapshot state (reference:
        librbd snap_rollback: per-object copy from the snap view)."""
        self._check_writable()
        snaps = self._header.get("snaps", {})
        if snap not in snaps:
            raise SnapshotError(f"no snap {snap!r}")
        tid = self._journal_append({"op": "snap_rollback", "snap": snap})
        s = snaps[snap]
        head_size = self._header["size"]
        span = max(head_size, s["size"], 1)
        last_obj = max(
            (e[0] for e in self._policy.extents(0, span)), default=-1
        )
        for objectno in range(last_obj + 1):
            oid = self._data_oid(objectno)
            try:
                old = self._io.read(oid, snapid=s["id"])
            except IOError:
                old = None
            if old is None:
                try:
                    self._io.remove(oid)
                except IOError:
                    pass
            else:
                self._io.write_full(oid, old)
        self._header["size"] = s["size"]
        self._save_header()
        self._journal_applied(tid)

    # -- clone maintenance ---------------------------------------------------
    def flatten(self) -> None:
        """Copy every not-yet-owned parent object into the child and
        sever the parent link (reference: librbd flatten)."""
        p = self._header.get("parent")
        if not p:
            return
        if p["overlap"] > 0:
            self._copy_up(0, p["overlap"])
        self._header["parent"] = None
        self._save_header()
        RBD(self._io)._unregister_child(p["image"], p["snap"], self.name)


class RBD:
    """Image administration (reference: librbd::RBD)."""

    def __init__(self, io):
        self._io = io

    def create(self, name: str, size: int, order: int = 22,
               stripe_unit: int | None = None, stripe_count: int = 1) -> None:
        """order: log2 of the object size, default 4 MiB objects — the
        reference's default layout."""
        _check_name("image", name)
        hdr_oid = name + _HEADER_SUFFIX
        try:
            self._io.read(hdr_oid)
            raise ImageExists(f"image {name!r} exists")
        except ImageExists:
            raise
        except IOError:
            pass
        object_size = 1 << order
        su = stripe_unit or object_size
        StripePolicy(object_size=object_size, stripe_unit=su,
                     stripe_count=stripe_count)  # validate layout
        header = {
            "name": name,
            "size": int(size),
            "order": order,
            "stripe_unit": su,
            "stripe_count": stripe_count,
            "block_name_prefix": f"rbd_data.{name}",
            "snaps": {},
            "parent": None,
        }
        self._io.write_full(hdr_oid, json.dumps(header).encode())

    def open(self, name: str, snap: str | None = None) -> Image:
        try:
            raw = self._io.read(name + _HEADER_SUFFIX)
        except IOError as e:
            raise ImageNotFound(f"no image {name!r}") from e
        img = Image(self._io, name, json.loads(raw), snap=snap)
        if (
            snap is None and img._journaled()
            and (img._header.get("mirror") or {}).get("primary", True)
        ):
            # open-time journal replay (librbd's Journal open path): a
            # crash between a record's append and its apply left the
            # tail ahead of the image — re-apply it through the local
            # client position so the write-ahead contract holds
            from .rbd_mirror import replay_local_tail

            replay_local_tail(self._io, img)
        return img

    def list(self) -> list[str]:
        out = []
        for oid in self._io.list_objects():
            if oid.endswith(_HEADER_SUFFIX):
                out.append(oid[: -len(_HEADER_SUFFIX)])
        return sorted(out)

    def remove(self, name: str) -> None:
        img = self.open(name)
        if img._header.get("snaps"):
            raise ImageBusy(
                f"image {name!r} has snapshots: "
                f"{sorted(img._header['snaps'])}"
            )
        img._ext.purge(img._header["size"])
        for legacy in (f"{img._header['block_name_prefix']}.meta",):
            # images written by the pre-snapshot format kept a striper
            # size sidecar; sweep it so remove leaves nothing behind
            try:
                self._io.remove(legacy)
            except IOError:
                pass
        if img._journaled():
            # the journal dies with the image (review r5): a leaked
            # header + record tail would replay the OLD image's bytes
            # onto a re-created same-name image at its first open
            from .rbd_mirror import journal_purge

            journal_purge(self._io, name)
        self._io.remove(name + _HEADER_SUFFIX)
        p = img._header.get("parent")
        if p:
            # unregister LAST: a purge failure above must leave the
            # child registered, or the parent could unprotect while a
            # half-removed but still-openable clone depends on its snap
            self._unregister_child(p["image"], p["snap"], name)

    # -- clones --------------------------------------------------------------
    def clone(self, parent: str, snap: str, child: str) -> None:
        """COW child of parent@snap (reference: librbd::RBD::clone; the
        snap must be protected first, like the reference enforces)."""
        _check_name("image", child)
        pimg = self.open(parent)
        snaps = pimg._header.get("snaps", {})
        if snap not in snaps:
            raise SnapshotError(f"no snap {parent}@{snap}")
        if not snaps[snap].get("protected"):
            raise SnapshotError(
                f"snap {parent}@{snap} must be protected to clone"
            )
        s = snaps[snap]
        hdr_oid = child + _HEADER_SUFFIX
        try:
            self._io.read(hdr_oid)
            raise ImageExists(f"image {child!r} exists")
        except ImageExists:
            raise
        except IOError:
            pass
        header = {
            "name": child,
            "size": s["size"],
            "order": pimg._header["order"],
            "stripe_unit": pimg._header["stripe_unit"],
            "stripe_count": pimg._header["stripe_count"],
            "block_name_prefix": f"rbd_data.{child}",
            "snaps": {},
            "parent": {
                "image": parent,
                "snap": snap,
                "snap_id": s["id"],
                "overlap": s["size"],
                "block_name_prefix": pimg._header["block_name_prefix"],
            },
        }
        self._io.write_full(hdr_oid, json.dumps(header).encode())
        self._register_child(parent, snap, child)

    def _register_child(self, parent: str, snap: str, child: str) -> None:
        kids = _children_of(self._io, parent, snap)
        if child not in kids:
            kids.append(child)
        self._io.omap_set(
            _CHILDREN_OID,
            {f"{parent}@{snap}": json.dumps(kids).encode()},
        )

    def _unregister_child(self, parent: str, snap: str, child: str) -> None:
        kids = [k for k in _children_of(self._io, parent, snap) if k != child]
        key = f"{parent}@{snap}"
        if kids:
            self._io.omap_set(_CHILDREN_OID, {key: json.dumps(kids).encode()})
        else:
            try:
                self._io.omap_rm_keys(_CHILDREN_OID, [key])
            except IOError:
                pass

    def children(self, parent: str, snap: str) -> list[str]:
        return _children_of(self._io, parent, snap)
