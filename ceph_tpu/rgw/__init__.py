"""RGW-analog object gateway (reference: src/rgw; SURVEY.md §2.6).

An HTTP gateway speaking the S3 REST dialect's core surface — buckets,
objects, prefix/marker listing, multipart upload — over librados, with
bucket indexes and object data living in RADOS pools exactly as the
reference's .rgw.* pools do.
"""
from .gateway import RGWDaemon

__all__ = ["RGWDaemon"]
