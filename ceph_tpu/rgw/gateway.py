"""RGWDaemon — S3-dialect HTTP gateway over librados (reference:
src/rgw/rgw_main.cc + rgw_rest_s3.cc + rgw_op.cc, the
bucket/object/multipart core; SURVEY.md §2.6).

Layout in RADOS (mirroring the reference's pool split):

- ``rgw_meta`` pool: ``buckets`` (the bucket catalog, one omap key per
  bucket) and one ``idx.{bucket}`` object per bucket — the bucket index
  the reference keeps in .rgw.buckets.index omaps (key ->
  size/etag/mtime).  BOTH are mutated exclusively through the
  server-side ``rgw`` object class (`rados exec`, the cls_rgw role):
  create-if-absent bucket claims and transactional multi-key index
  updates execute at the index object's primary under the PG lock, so
  two concurrent gateways can neither double-create a bucket nor lose
  index entries.
- ``rgw_data`` pool: object payloads, striped via the striper as
  ``{bucket}/{key}`` streams (reference: .rgw.buckets.data with
  manifest-driven striping); multipart parts as
  ``{bucket}/{key}.part.{uploadId}.{n}`` promoted on complete.

Surface: GET / (ListAllMyBuckets), PUT/DELETE/GET /bucket (create,
delete, ListObjects v1 with prefix/marker/max-keys), PUT/GET/HEAD/DELETE
/bucket/key, POST ?uploads / PUT ?partNumber / POST ?uploadId (multipart
create/upload/complete), DELETE ?uploadId (abort), bucket versioning
(PUT/GET ?versioning, ?versionId addressing, delete markers,
ListObjectVersions via ?versions).  Responses are the S3 XML bodies;
ETags are MD5 hex (multipart: MD5-of-MD5s with -N suffix, the S3
convention).  Request signing is AWS SigV4 backed by cephx-derived
keys when `rgw_enable_sigv4` is set; otherwise the gateway serves every
caller, like a reference zone with anonymous access grants.

The SWIFT front (reference: rgw_rest_swift.cc) serves the same bucket
layer at /swift/v1: account/container/object GET/PUT/HEAD/DELETE with
text and ?format=json listings, X-Object-Meta-* metadata (POST
replaces the set), and the /auth/v1.0 token handshake validated
against the same derived secrets when auth is enforced.
"""
from __future__ import annotations

import hashlib
import json
import re
import threading
import time
import uuid
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, parse_qsl, unquote, urlparse

from ..client.striper import StripedObject
from .sigv4 import SigV4Error, verify_request

META_POOL = "rgw_meta"
DATA_POOL = "rgw_data"


def _xml_escape(s: str) -> str:
    return (
        s.replace("&", "&amp;").replace("<", "&lt;").replace(">", "&gt;")
    )


class _Store:
    """Bucket catalog + per-bucket index + striped object data, shared by
    all request threads under one lock (the reference shards this across
    index objects; one gateway-wide lock serves the test scale)."""

    def __init__(self, rados):
        self.rados = rados
        self.meta = rados.open_ioctx(META_POOL)
        self.data = rados.open_ioctx(DATA_POOL)
        self.lock = threading.RLock()
        # uploadId -> {bucket, key, parts}; persisted as mpu.{uid} objects
        # in the meta pool (reference: RGW's multipart upload meta objects
        # in the bucket index namespace) so a gateway restart neither
        # forgets in-flight uploads nor orphans their part data
        self.uploads: dict[str, dict] = {}
        self._migrate_legacy_catalog()
        reaps = []
        for oid in self.meta.list_objects():
            if oid.startswith("mpu."):
                up = self._read_json(self.meta, oid, None)
                if up is not None:
                    up["parts"] = {
                        int(n): v for n, v in up.get("parts", {}).items()
                    }
                    self.uploads[oid[4:]] = up
            elif oid.startswith("reap."):
                reaps.append(oid)
        # finish part deletions a crashed complete_upload left behind
        for oid in reaps:
            r = self._read_json(self.meta, oid, None)
            if r is not None and oid[5:] not in self.uploads:
                self._reap(oid[5:], r["bucket"], r.get("parts", []))

    def _persist_upload(self, uid: str) -> None:
        up = self.uploads[uid]
        body = dict(up, parts={str(n): v for n, v in up["parts"].items()})
        self.meta.write_full(f"mpu.{uid}", json.dumps(body).encode())

    def _drop_upload(self, uid: str) -> None:
        self.uploads.pop(uid, None)
        try:
            self.meta.remove(f"mpu.{uid}")
        except IOError:
            pass

    # -- catalog: omap on `buckets`, mutated via the rgw class ------------
    def _read_json(self, io, oid, default):
        try:
            return json.loads(io.read(oid))
        except (IOError, ValueError):
            return default

    def _migrate_legacy_catalog(self) -> None:
        """Rounds <= 3 kept the catalog as a JSON blob in the `buckets`
        object's DATA; move those entries into the omap (via the same
        atomic class op) so they are neither lost nor silently shadowed
        (advisor r3: never drop a legacy on-disk format quietly)."""
        try:
            legacy = self._read_json(self.meta, "buckets", None)
            if not legacy:
                return
            for name, info in legacy.items():
                self.meta.exec("buckets", "rgw", "dir_entry_create",
                               {"key": name, "val": info})  # -17 dup ok
            self.meta.write_full("buckets", b"")
        except (IOError, ConnectionError, TimeoutError) as e:
            # a degraded cluster must not stop the gateway from starting
            # (every other init-path call tolerates cluster errors); the
            # blob is untouched, so the NEXT start retries the migration
            self.rados.cct.dout("rgw", 0,
                                f"legacy catalog migration deferred: {e}")

    def bucket_exists(self, bucket: str) -> bool:
        try:
            return bucket in self.meta.omap_get("buckets", keys=[bucket])
        except IOError:
            return False

    def buckets(self) -> dict:
        """Full catalog scan (ListAllMyBuckets is unpaginated in S3 v1)."""
        out: dict[str, dict] = {}
        after = ""
        while True:
            try:
                page = self.meta.omap_get_vals("buckets", after=after,
                                               max_return=256)
            except IOError:
                break
            if not page:
                break
            for k in sorted(page):
                after = k
                out[k] = json.loads(page[k])
        return out

    # -- bucket index: omap on idx.{bucket} (reference: the cls_rgw
    # bucket index objects in .rgw.buckets.index — one omap key per
    # object, listed with paginated omap scans; round 2 kept this as a
    # JSON blob, which could not scale past toy listings) ---------------
    def _index_put(self, bucket: str, key: str, ent: dict) -> bool:
        """Server-side transactional update (reference: cls_rgw index
        complete) — atomic at the index object's primary even with many
        gateways.  False = the index is sealed (the bucket was deleted
        by a concurrent gateway after our existence check)."""
        rv, _ = self.meta.exec(f"idx.{bucket}", "rgw", "index_update",
                               {"add": {key: ent}})
        return rv == 0

    def _index_rm(self, bucket: str, key: str) -> None:
        try:
            self.meta.exec(f"idx.{bucket}", "rgw", "index_update",
                           {"rm": [key]})
        except IOError:
            pass

    def _index_get(self, bucket: str, key: str) -> dict | None:
        try:
            kv = self.meta.omap_get(f"idx.{bucket}", keys=[key])
        except IOError:
            return None
        return json.loads(kv[key]) if key in kv else None

    @staticmethod
    def _is_dm_head(ent: dict) -> bool:
        """Current view of a versioned entry is a delete marker."""
        return bool(ent.get("versions")) and bool(ent["versions"][0].get("dm"))

    def _index_list(
        self, bucket: str, prefix: str = "", marker: str = "",
        maxn: int = 1000, live_only: bool = False,
    ) -> tuple[list[tuple[str, dict]], bool]:
        """Sorted (key, entry) pairs after `marker` matching `prefix`,
        at most `maxn`, plus a truncation flag — paginated omap scans,
        never the whole index in one read.  live_only skips entries
        whose CURRENT version is a delete marker BEFORE they count
        toward `maxn` (review r5: filtering after the limit could
        return an empty page mid-listing and end pagination early)."""
        out: list[tuple[str, dict]] = []
        if maxn == 0:
            return out, False  # S3: max-keys=0 lists nothing
        after = marker
        if prefix and prefix[:-1] > marker:
            # sorted keys: nothing below the prefix can match, so start
            # the scan at the prefix minus its last character (strictly
            # below every candidate, including `prefix` itself)
            after = prefix[:-1]
        while True:
            try:
                page = self.meta.omap_get_vals(
                    f"idx.{bucket}", after=after, max_return=256
                )
            except IOError:
                break
            if not page:
                break
            for k in sorted(page):
                after = k
                if k.startswith("\x01"):
                    continue  # reserved index-state keys (seal marker)
                if prefix and not k.startswith(prefix):
                    if k > prefix:
                        return out, False  # sorted: past the prefix range
                    continue
                if k <= marker:
                    continue
                ent = json.loads(page[k])
                if live_only and self._is_dm_head(ent):
                    continue
                if maxn and len(out) >= maxn:
                    return out, True
                out.append((k, ent))
        return out, False

    def iter_index(self, bucket: str, live_only: bool = False):
        """Paginated generator over every (key, entry) of a bucket's
        index — the PUBLIC full-walk used by count_live and the
        radosgw-admin stats (callers must not bind the private
        _index_list pagination contract)."""
        marker = ""
        while True:
            entries, truncated = self._index_list(
                bucket, marker=marker, maxn=1000, live_only=live_only
            )
            yield from entries
            if not truncated or not entries:
                return
            marker = entries[-1][0]

    def count_live(self, bucket: str) -> int:
        """Paginated live-object count (Swift container HEAD)."""
        return sum(1 for _ in self.iter_index(bucket, live_only=True))

    def bucket_stats(self, bucket: str) -> dict:
        """radosgw-admin `bucket stats` rollup: live objects, total
        index entries, version counts, live byte total, versioning."""
        num_entries = num_versions = num_live = size = 0
        for _k, ent in self.iter_index(bucket):
            num_entries += 1
            recs = self._versions_of(ent)
            num_versions += len(recs)
            size += sum(r["size"] for r in recs if not r.get("dm"))
            if not self._is_dm_head(ent):
                num_live += 1
        return {
            "bucket": bucket,
            "num_objects": num_live,
            "num_entries": num_entries,
            "num_versions": num_versions,
            "size_bytes": size,
            "versioning": self.versioning_status(bucket) or "off",
        }

    def update_meta(self, bucket: str, key: str, meta: dict | None) -> bool:
        """Metadata-only update of the CURRENT version (Swift POST):
        no new version, no data rewrite, ETag untouched (review r5 —
        a re-PUT minted spurious versions and clobbered multipart
        ETags)."""
        with self.lock:
            ent = self._index_get(bucket, key)
            if ent is None:
                return False
            if "versions" in ent:
                versions = list(ent["versions"])
                head = dict(versions[0])
                if head.get("dm"):
                    return False
                if meta:
                    head["meta"] = dict(meta)
                else:
                    head.pop("meta", None)
                versions[0] = head
                new_ent = self._ent_from_versions(versions)
            else:
                new_ent = dict(ent)
                if meta:
                    new_ent["meta"] = dict(meta)
                else:
                    new_ent.pop("meta", None)
            return self._index_put(bucket, key, new_ent)

    # -- bucket ops --------------------------------------------------------
    def create_bucket(self, bucket: str) -> bool:
        with self.lock:
            # atomic create-if-absent claim: of N concurrent gateways,
            # exactly one sees rv == 0 (reference: cls_rgw guards)
            rv, _ = self.meta.exec(
                "buckets", "rgw", "dir_entry_create",
                {"key": bucket, "val": {"created": time.time()}},
            )
            if rv == -17:
                return False
            # reset the index object: clears a stale seal / ghost
            # entries a half-completed delete of this name left behind
            self.meta.exec(f"idx.{bucket}", "rgw", "bucket_init", {})
            return True

    def delete_bucket(self, bucket: str) -> int:
        """0 ok, -404 no bucket, -409 not empty.

        Ordering closes the delete/PUT race: the SEAL is the atomic
        check-empty + tombstone on the index object itself (cls
        bucket_seal), so a concurrent PUT either lands its entry before
        the seal (we return -409) or hits the sealed index and fails —
        never a ghost entry in a deleted bucket."""
        with self.lock:
            if not self.bucket_exists(bucket):
                return -404
            rv, _ = self.meta.exec(f"idx.{bucket}", "rgw", "bucket_seal", {})
            if rv == -39:
                return -409
            rv, _ = self.meta.exec("buckets", "rgw", "dir_entry_remove",
                                   {"key": bucket})
            if rv == -2:
                return -404  # lost a delete race with another gateway
            try:
                self.meta.remove(f"idx.{bucket}")
            except IOError:
                pass
            for side in (f"bver.{bucket}", f"cmeta.{bucket}",
                         f"blc.{bucket}"):
                try:
                    self.meta.remove(side)
                except IOError:
                    pass
            # reap the bucket's in-flight multipart uploads (their part
            # objects would otherwise be orphaned in rgw_data)
            for uid in [
                u for u, up in self.uploads.items()
                if up["bucket"] == bucket
            ]:
                self.abort_upload(uid)
            return 0

    # -- object ops --------------------------------------------------------
    def _stream(self, bucket: str, key: str,
                vid: str | None = None) -> StripedObject:
        # versioned data objects carry the version id in the name (the
        # reference keys version instances by instance id in the index
        # and a per-instance rados name); "null"/current data keeps the
        # legacy name so pre-versioning buckets read unchanged
        name = f"{bucket}/{key}" if vid in (None, "null") \
            else f"{bucket}/{key}\x00{vid}"
        return StripedObject(
            self.data, name,
            object_size=1 << 22, stripe_unit=1 << 16, stripe_count=4,
        )

    # -- bucket versioning (reference: RGW versioning — cls_rgw olh/
    # instance entries; round-4 verdict item #9).  Index-entry format:
    # an UNVERSIONED entry is the legacy {"size","etag","mtime"}; once a
    # bucket sees versioning, entries carry "versions": newest-first
    # records {"vid","size","etag","mtime","dm"} with the head mirrored
    # into the legacy fields so listings stay cheap.  Multipart
    # completes always write the null version (out of scope).
    def container_meta(self, bucket: str) -> dict:
        """Swift X-Container-Meta-* storage (rides a bver-style sidecar
        object; S3 has no bucket-metadata surface, so this is
        Swift-only state like upstream's bucket attrs)."""
        return self._read_json(self.meta, f"cmeta.{bucket}", None) or {}

    def set_container_meta(self, bucket: str, meta: dict) -> bool:
        with self.lock:
            if not self.bucket_exists(bucket):
                return False
            self.meta.write_full(
                f"cmeta.{bucket}", json.dumps(meta).encode())
            return True

    def versioning_status(self, bucket: str) -> str | None:
        ver = self._read_json(self.meta, f"bver.{bucket}", None)
        return ver.get("status") if ver else None

    def set_versioning(self, bucket: str, status: str) -> bool:
        with self.lock:
            if not self.bucket_exists(bucket):
                return False
            self.meta.write_full(
                f"bver.{bucket}", json.dumps({"status": status}).encode()
            )
            return True

    # -- bucket lifecycle (reference: RGWLC / RGWLifecycleConfiguration
    # — expiration rules stored as a bucket attr, applied by the lc
    # worker; transitions/storage-classes are out of scope) ------------
    def lifecycle_rules(self, bucket: str) -> list[dict] | None:
        return self._read_json(self.meta, f"blc.{bucket}", None)

    def set_lifecycle(self, bucket: str, rules: list[dict]) -> bool:
        with self.lock:
            if not self.bucket_exists(bucket):
                return False
            self.meta.write_full(
                f"blc.{bucket}", json.dumps(rules).encode())
            return True

    def delete_lifecycle(self, bucket: str) -> None:
        try:
            self.meta.remove(f"blc.{bucket}")
        except IOError:
            pass

    def lc_process(self, now: float | None = None) -> dict:
        """One lifecycle pass over every configured bucket (reference:
        RGWLC::process).  Returns {bucket: expired_count} for the lc
        log.  Current objects past Days are deleted through the normal
        delete path (delete marker under versioning); noncurrent
        versions past NoncurrentDays are dropped from the version
        chain with their backing data."""
        now = time.time() if now is None else now
        out: dict[str, int] = {}
        for bucket in list(self.buckets()):
            rules = self.lifecycle_rules(bucket) or []
            rules = [r for r in rules if r.get("status") != "Disabled"]
            if not rules:
                continue
            n = 0
            for key, ent in list(self.iter_index(bucket)):
                for r in rules:
                    if not key.startswith(r.get("prefix", "")):
                        continue
                    days = r.get("days")
                    if days is not None and self._expire_current(
                            bucket, key, now, days):
                        n += 1
                        break
                    nc_days = r.get("noncurrent_days")
                    if nc_days is not None and "versions" in ent:
                        self._expire_noncurrent(
                            bucket, key, now, nc_days)
            if n:
                out[bucket] = n
        return out

    def _expire_current(self, bucket: str, key: str, now: float,
                        days: float) -> bool:
        """Expire the CURRENT object if still past `days`, re-checked
        under the lock — the pass iterates an unlocked snapshot, and a
        concurrent PUT must not have its fresh bytes deleted."""
        with self.lock:
            ent = self._index_get(bucket, key)
            if ent is None or self._is_dm_head(ent):
                return False
            head = self._versions_of(ent)[0] if "versions" in ent else ent
            if head.get("dm") or now - head.get("mtime", now) \
                    < days * 86400:
                return False
            # delete while STILL holding the lock (reentrant): a PUT
            # landing between the recheck and the delete would otherwise
            # have its fresh bytes removed by the lifecycle worker — a
            # far more surprising loss than any user-initiated
            # delete/put race.  A PUT after the release wins normally.
            self.delete_object(bucket, key)
            return True

    def _expire_noncurrent(self, bucket: str, key: str, now: float,
                           nc_days: float) -> None:
        with self.lock:
            ent = self._index_get(bucket, key)
            if ent is None or "versions" not in ent:
                return
            versions = self._versions_of(ent)
            keep, dead = [versions[0]], []
            for v in versions[1:]:
                # clock starts when the version became noncurrent
                # (nc_at); fall back to mtime for pre-upgrade entries
                if now - v.get("nc_at", v.get("mtime", now)) \
                        >= nc_days * 86400:
                    dead.append(v)
                else:
                    keep.append(v)
            if not dead:
                return
            # trim the index FIRST, then drop the backing streams: a
            # crash between the two then only leaks collectable garbage
            # (unreferenced streams), never index entries pointing at
            # data that is gone (listed-but-unreadable) — same ordering
            # the rmsnap path documents
            self._index_put(bucket, key, self._ent_from_versions(keep))
            for v in dead:
                if not v.get("dm"):
                    self._stream(bucket, key, v["vid"]).remove()

    @staticmethod
    def _versions_of(ent: dict) -> list[dict]:
        if "versions" in ent:
            return list(ent["versions"])
        rec = {
            "vid": "null", "size": ent["size"], "etag": ent["etag"],
            "mtime": ent.get("mtime", 0.0), "dm": False,
        }
        if ent.get("meta"):
            rec["meta"] = ent["meta"]
        return [rec]

    @staticmethod
    def _ent_from_versions(versions: list[dict]) -> dict:
        head = versions[0]
        return {
            "size": head["size"], "etag": head["etag"],
            "mtime": head["mtime"], "versions": versions,
        }

    def put_object(self, bucket: str, key: str, body: bytes,
                   meta: dict | None = None):
        """(etag, version_id|None) — None etag = no bucket.  `meta` is
        opaque user metadata carried on the entry (the Swift
        X-Object-Meta surface; S3 callers pass none)."""
        with self.lock:
            if not self.bucket_exists(bucket):
                return None, None
            status = self.versioning_status(bucket)
            etag = hashlib.md5(body).hexdigest()
            existing = self._index_get(bucket, key)
            if status is None and (existing is None
                                   or "versions" not in existing):
                # never-versioned bucket: legacy single-version path
                s = self._stream(bucket, key)
                s.truncate(0)
                s.write(body)
                ent = {"size": len(body), "etag": etag,
                       "mtime": time.time()}
                if meta:
                    ent["meta"] = dict(meta)
                if not self._index_put(bucket, key, ent):
                    # index sealed: the bucket was deleted under us —
                    # undo the data write instead of orphaning it
                    s.remove()
                    return None, None
                return etag, None
            versions = self._versions_of(existing) if existing else []
            if versions:
                # the old head becomes noncurrent NOW — S3's
                # NoncurrentDays clock starts here, not at its mtime
                versions[0].setdefault("nc_at", time.time())
            rec = {"vid": None, "size": len(body), "etag": etag,
                   "mtime": time.time(), "dm": False}
            if meta:
                rec["meta"] = dict(meta)
            if status == "Enabled":
                rec["vid"] = uuid.uuid4().hex
                s = self._stream(bucket, key, rec["vid"])
            else:
                # suspended (or re-disabled): writes land as the null
                # version, replacing any prior null wherever it sat
                rec["vid"] = "null"
                versions = [v for v in versions if v["vid"] != "null"]
                s = self._stream(bucket, key)
            s.truncate(0)
            s.write(body)
            versions.insert(0, rec)
            if not self._index_put(bucket, key,
                                   self._ent_from_versions(versions)):
                s.remove()
                return None, None
            return etag, rec["vid"]

    def get_object(self, bucket: str, key: str, vid: str | None = None):
        """(body, record) — record carries vid/dm; (None, None) = miss,
        (None, rec) = the addressed version is a delete marker."""
        with self.lock:
            ent = self._index_get(bucket, key)
            if ent is None:
                return None, None
            versions = self._versions_of(ent)
            if vid is None:
                rec = versions[0]
                if rec["dm"]:
                    return None, None  # current view: deleted
                if "versions" not in ent:
                    # never-versioned entry: no version id to expose
                    rec = dict(rec, vid=None)
            else:
                rec = next((v for v in versions if v["vid"] == vid), None)
                if rec is None:
                    return None, None
                if rec["dm"]:
                    return None, rec
            return (self._stream(bucket, key, rec["vid"])
                    .read(0, rec["size"]), rec)

    def head_object(self, bucket: str, key: str, vid: str | None = None):
        with self.lock:
            ent = self._index_get(bucket, key)
            if ent is None:
                return None
            versions = self._versions_of(ent)
            if vid is None:
                rec = versions[0]
                if rec["dm"]:
                    return None
                return dict(rec, vid=None) if "versions" not in ent else rec
            return next((v for v in versions if v["vid"] == vid), None)

    def delete_object(self, bucket: str, key: str, vid: str | None = None):
        """S3 delete semantics (reference: RGW olh delete-marker logic).
        Returns (outcome, version_id): outcome in
          "missing"  — no such key/version
          "deleted"  — a version (or the whole legacy object) is gone
          "marker"   — a delete marker was inserted (versioned delete)
        """
        with self.lock:
            ent = self._index_get(bucket, key)
            status = self.versioning_status(bucket)
            if vid is not None:
                if ent is None:
                    return "missing", None
                versions = self._versions_of(ent)
                rec = next((v for v in versions if v["vid"] == vid), None)
                if rec is None:
                    return "missing", None
                if not rec["dm"]:
                    self._stream(bucket, key, rec["vid"]).remove()
                versions = [v for v in versions if v["vid"] != vid]
                if versions:
                    self._index_put(bucket, key,
                                    self._ent_from_versions(versions))
                else:
                    self._index_rm(bucket, key)
                return "deleted", vid
            if status is None and (ent is None or "versions" not in ent):
                # never-versioned: plain delete
                if ent is None:
                    return "missing", None
                self._stream(bucket, key).remove()
                self._index_rm(bucket, key)
                return "deleted", None
            versions = self._versions_of(ent) if ent else []
            if status == "Enabled":
                mvid = uuid.uuid4().hex
            else:
                # suspended: the null version is REMOVED and replaced by
                # a null delete marker (S3 suspended-delete semantics)
                null = next((v for v in versions if v["vid"] == "null"),
                            None)
                if null is not None and not null["dm"]:
                    self._stream(bucket, key).remove()
                versions = [v for v in versions if v["vid"] != "null"]
                mvid = "null"
            if versions:
                # the displaced head goes noncurrent now (NoncurrentDays
                # clock — same stamp the overwrite path makes)
                versions[0].setdefault("nc_at", time.time())
            versions.insert(0, {
                "vid": mvid, "size": 0, "etag": "", "mtime": time.time(),
                "dm": True,
            })
            self._index_put(bucket, key, self._ent_from_versions(versions))
            return "marker", mvid

    def list_versions(self, bucket: str, prefix: str = "",
                      marker: str = "", maxn: int = 1000):
        """Flattened (key, record, is_latest) rows, key-sorted then
        newest-first (GET ?versions / ListObjectVersions)."""
        entries, truncated = self._index_list(
            bucket, prefix=prefix, marker=marker, maxn=maxn
        )
        rows = []
        for k, ent in entries:
            for i, rec in enumerate(self._versions_of(ent)):
                rows.append((k, rec, i == 0))
        return rows, truncated

    # -- multipart ---------------------------------------------------------
    def create_upload(self, bucket: str, key: str) -> str | None:
        with self.lock:
            if not self.bucket_exists(bucket):
                return None
            uid = uuid.uuid4().hex
            self.uploads[uid] = {"bucket": bucket, "key": key, "parts": {}}
            self._persist_upload(uid)
            return uid

    def put_part(self, uid: str, n: int, body: bytes) -> str | None:
        with self.lock:
            up = self.uploads.get(uid)
            if up is None:
                return None
            etag = hashlib.md5(body).hexdigest()
            s = self._stream(up["bucket"], f"{up['key']}.part.{uid}.{n}")
            s.truncate(0)
            s.write(body)
            up["parts"][n] = {"size": len(body), "etag": etag}
            self._persist_upload(uid)
            return etag

    def complete_upload(self, uid: str):
        """Concatenate parts in part-number order into the final object
        (the reference writes a manifest instead of copying; copy keeps
        the data path simple here).

        Returns ("ok", (bucket, key, etag)) | ("nosuch",) — unknown id or
        bucket deleted under the upload | ("empty",) — zero parts, the
        upload stays alive (S3 rejects the complete without killing it).
        """
        with self.lock:
            up = self.uploads.get(uid)
            if up is None:
                return ("nosuch",)
            if not up["parts"]:
                return ("empty",)
            if not self.bucket_exists(up["bucket"]):
                # bucket vanished: the upload is dead; reap the parts
                self.abort_upload(uid)
                return ("nosuch",)
            bucket, key = up["bucket"], up["key"]
            dst = self._stream(bucket, key)
            dst.truncate(0)
            off = 0
            md5s = b""
            part_names = []
            for n in sorted(up["parts"]):
                name = f"{key}.part.{uid}.{n}"
                body = self._stream(bucket, name).read()
                dst.write(body, off)
                off += len(body)
                md5s += bytes.fromhex(up["parts"][n]["etag"])
                part_names.append(name)
            etag = (
                f"{hashlib.md5(md5s).hexdigest()}-{len(up['parts'])}"
            )
            new_ent = {"size": off, "etag": etag, "mtime": time.time()}
            existing = self._index_get(bucket, key)
            if existing is not None and "versions" in existing:
                # versioned entry: the multipart complete writes the
                # null version (see the versioning note above) — it must
                # not clobber the version history
                versions = [v for v in self._versions_of(existing)
                            if v["vid"] != "null"]
                versions.insert(0, dict(new_ent, vid="null", dm=False))
                new_ent = self._ent_from_versions(versions)
            if not self._index_put(bucket, key, new_ent):
                # bucket deleted mid-complete: reap everything
                dst.remove()
                self.abort_upload(uid)
                return ("nosuch",)
            # Parts are only deleted AFTER the index write and the record
            # drop: a crash anywhere up to here leaves record + parts
            # intact, so a restarted gateway can re-complete idempotently.
            # The reap.{uid} record is written BEFORE the mpu record drop
            # so no crash point orphans the parts without a pointer; the
            # startup sweep ignores reap records whose mpu record still
            # exists, so a crash between the two writes re-completes
            # rather than reaping live parts.
            self.meta.write_full(
                f"reap.{uid}",
                json.dumps({"bucket": bucket, "parts": part_names}).encode(),
            )
            self._drop_upload(uid)
            self._reap(uid, bucket, part_names)
            return ("ok", (bucket, key, etag))

    def _reap(self, uid: str, bucket: str, part_names: list) -> None:
        all_gone = True
        for name in part_names:
            try:
                self._stream(bucket, name).remove()
            except IOError:
                all_gone = False  # transient: retried from the record
        if not all_gone:
            # keep the reap record so a later startup sweep finishes the
            # deletions — dropping it now would orphan the failed parts
            return
        try:
            self.meta.remove(f"reap.{uid}")
        except IOError:
            pass

    def abort_upload(self, uid: str) -> bool:
        with self.lock:
            up = self.uploads.get(uid)
            if up is None:
                return False
            # parts first, record last: a crash mid-abort keeps the
            # record so a restarted gateway can finish the reap
            for n in sorted(up["parts"]):
                self._stream(
                    up["bucket"], f"{up['key']}.part.{uid}.{n}"
                ).remove()
            self._drop_upload(uid)
            return True


class _BadParam(ValueError):
    pass


class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    store: _Store  # injected by RGWDaemon

    def log_message(self, fmt, *args):  # route through cct logging
        self.server.cct.dout("rgw", 10, f"rgw: {fmt % args}")

    # -- helpers -----------------------------------------------------------
    def _path(self) -> tuple[str, str, dict]:
        u = urlparse(self.path)
        parts = u.path.lstrip("/").split("/", 1)
        bucket = unquote(parts[0]) if parts[0] else ""
        key = unquote(parts[1]) if len(parts) > 1 else ""
        return bucket, key, parse_qs(u.query, keep_blank_values=True)

    def _body(self) -> bytes:
        n = int(self.headers.get("Content-Length") or 0)
        return self.rfile.read(n) if n else b""

    def _reply(self, code: int, body: bytes = b"",
               ctype: str = "application/xml",
               headers: dict | None = None) -> None:
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        for k, v in (headers or {}).items():
            self.send_header(k, v)
        self.end_headers()
        if body:
            self.wfile.write(body)

    def _error(self, code: int, s3code: str) -> None:
        body = (
            f'<?xml version="1.0"?><Error><Code>{s3code}</Code>'
            f"</Error>".encode()
        )
        self._reply(code, body)

    def _auth_ok(self, body: bytes) -> bool:
        """SigV4 gate (reference: rgw_auth_s3.cc): with rgw_enable_sigv4
        every request — including each multipart step — must carry a
        valid signature over the canonical request; anonymous and
        bad-signature callers get the S3 error and never reach the
        store.  Auth off = anonymous zone, the pre-r4 behavior."""
        lookup = getattr(self.server, "s3_secret_lookup", None)
        if lookup is None:
            return True
        u = urlparse(self.path)
        try:
            verify_request(
                self.command, unquote(u.path),
                parse_qsl(u.query, keep_blank_values=True),
                dict(self.headers), body, lookup,
            )
            return True
        except SigV4Error as e:
            self.server.cct.dout("rgw", 5, f"sigv4 reject: {e}")
            code = 403 if e.s3code in (
                "AccessDenied", "SignatureDoesNotMatch",
                "InvalidAccessKeyId", "RequestTimeTooSkewed",
            ) else 400
            if self.command == "HEAD":  # no body on HEAD replies
                self.send_response(code)
                self.send_header("Content-Length", "0")
                self.end_headers()
            else:
                self._error(code, e.s3code)
            return False

    # -- Swift front-end (reference: rgw_rest_swift.cc — the second
    # protocol surface over the same bucket/index layer; round-4 verdict
    # missing #4).  Containers ARE buckets; object metadata rides the
    # index entry's `meta` dict as X-Object-Meta-* headers.  Auth is the
    # Swift v1 handshake: GET /auth/v1.0 with X-Auth-User/X-Auth-Key
    # returns an X-Auth-Token (validated against the same cephx-derived
    # per-access-key secrets the S3 SigV4 gate uses when auth is
    # enforced; anonymous zone otherwise, matching the S3 side).
    SWIFT_PREFIX = "/swift/v1"

    def _swift_parts(self):
        u = urlparse(self.path)
        rest = u.path[len(self.SWIFT_PREFIX):].lstrip("/")
        seg = rest.split("/", 1)
        container = unquote(seg[0]) if seg[0] else ""
        obj = unquote(seg[1]) if len(seg) > 1 else ""
        return container, obj, parse_qs(u.query, keep_blank_values=True)

    SWIFT_TOKEN_TTL = 3600.0
    SWIFT_TOKEN_CAP = 4096

    def _swift_reply(self, code: int, body: bytes = b"",
                     headers: dict | None = None,
                     ctype: str = "text/plain") -> None:
        """Swift-side reply that never writes a body on HEAD (an unread
        body desynchronizes the keep-alive stream — same reason the S3
        _auth_ok special-cases HEAD)."""
        if self.command == "HEAD":
            body = b""
        self._reply(code, body, ctype=ctype, headers=headers)

    def _swift_token_ok(self) -> bool:
        if self.server.s3_secret_lookup is None:
            return True  # anonymous zone
        tok = self.headers.get("X-Auth-Token", "")
        ent = self.server.swift_tokens.get(tok)
        if ent is not None and ent[1] > time.time():
            return True
        self.server.swift_tokens.pop(tok, None)  # expired
        self._swift_reply(401, b"Unauthorized")
        return False

    def _swift_auth(self) -> None:
        user = self.headers.get("X-Auth-User", "")
        key = self.headers.get("X-Auth-Key", "")
        lookup = self.server.s3_secret_lookup
        if lookup is not None:
            # Swift subuser convention: "<access>:swift"; the key must
            # match a live generation of that access key's secret
            access = user.split(":", 1)[0]
            try:
                ok = key in (lookup(access) or [])
            except Exception:
                ok = False
            if not user or not ok:
                self._swift_reply(401, b"Unauthorized")
                return
        token = uuid.uuid4().hex
        toks = self.server.swift_tokens
        now = time.time()
        # bounded store with TTL (review r5: tokens lived forever and
        # the dict grew without bound; expiry also re-checks the key
        # against rotated-out generations within an hour)
        if len(toks) >= self.SWIFT_TOKEN_CAP:
            for t in [t for t, (_u, exp) in toks.items() if exp <= now]:
                toks.pop(t, None)
            while len(toks) >= self.SWIFT_TOKEN_CAP:
                toks.pop(next(iter(toks)), None)  # oldest-inserted
        toks[token] = (user or "anonymous", now + self.SWIFT_TOKEN_TTL)
        host, port = self.server.server_address[:2]
        self._reply(200, b"", ctype="text/plain", headers={
            "X-Auth-Token": token,
            "X-Storage-Token": token,
            "X-Storage-Url": f"http://{host}:{port}{self.SWIFT_PREFIX}",
        })

    def _obj_meta_headers(self, ent: dict) -> dict:
        return {
            f"X-Object-Meta-{name}": val
            for name, val in (ent.get("meta") or {}).items()
        }

    def _collect_obj_meta(self) -> dict:
        return {
            k[len("X-Object-Meta-"):]: v
            for k, v in self.headers.items()
            if k.lower().startswith("x-object-meta-")
        }

    def _collect_container_meta(self) -> dict:
        return {
            k[len("X-Container-Meta-"):]: v
            for k, v in self.headers.items()
            if k.lower().startswith("x-container-meta-")
        }

    def _swift_dispatch(self) -> bool:
        """Handle /auth/v1.0 and /swift/v1* for the current verb.
        True = request fully handled (including auth failures)."""
        u = urlparse(self.path)
        if u.path == "/auth/v1.0":
            self._body()
            if self.command == "GET":
                self._swift_auth()
            else:
                self._reply(405, b"", ctype="text/plain")
            return True
        if not (u.path == self.SWIFT_PREFIX
                or u.path.startswith(self.SWIFT_PREFIX + "/")):
            return False
        body = self._body()
        if not self._swift_token_ok():
            return True
        container, obj, q = self._swift_parts()
        fn = getattr(self, f"_swift_{self.command}", None)
        if fn is None:
            self._reply(405, b"", ctype="text/plain")
            return True
        fn(container, obj, q, body)
        return True

    def _swift_GET(self, container, obj, q, body):
        as_json = q.get("format", [""])[0] == "json"
        if not container:
            names = sorted(self.store.buckets())
            if as_json:
                out = json.dumps([{"name": n} for n in names]).encode()
                self._reply(200, out, ctype="application/json")
            elif names:
                self._reply(200, ("\n".join(names) + "\n").encode(),
                            ctype="text/plain")
            else:
                self._reply(204, b"", ctype="text/plain")
            return
        if not obj:
            if not self.store.bucket_exists(container):
                return self._reply(404, b"", ctype="text/plain")
            try:
                limit = self._int_param(q, "limit", 10000)
            except _BadParam:
                return self._reply(412, b"", ctype="text/plain")
            entries, _tr = self.store._index_list(
                container, prefix=q.get("prefix", [""])[0],
                marker=q.get("marker", [""])[0], maxn=limit,
                live_only=True,
            )
            if as_json:
                out = json.dumps([
                    {"name": k, "bytes": e["size"], "hash": e["etag"]}
                    for k, e in entries
                ]).encode()
                self._reply(200, out, ctype="application/json")
            elif entries:
                self._reply(
                    200, ("\n".join(k for k, _ in entries) + "\n").encode(),
                    ctype="text/plain")
            else:
                self._reply(204, b"", ctype="text/plain")
            return
        data, ent = self.store.get_object(container, obj)
        if ent is None or data is None:
            return self._reply(404, b"", ctype="text/plain")
        headers = {"ETag": ent["etag"], **self._obj_meta_headers(ent)}
        self._reply(200, data, ctype="application/octet-stream",
                    headers=headers)

    def _swift_HEAD(self, container, obj, q, body):
        if not container:
            n = len(self.store.buckets())
            return self._reply(204, b"", ctype="text/plain", headers={
                "X-Account-Container-Count": str(n)})
        if not obj:
            if not self.store.bucket_exists(container):
                return self._swift_reply(404)
            # paginated LIVE count: matches what GET lists (markers
            # hidden), no 10k cap (review r5)
            n = self.store.count_live(container)
            headers = {"X-Container-Object-Count": str(n)}
            for k, v in self.store.container_meta(container).items():
                headers[f"X-Container-Meta-{k}"] = v
            return self._reply(204, b"", ctype="text/plain",
                               headers=headers)
        ent = self.store.head_object(container, obj)
        if ent is None:
            return self._swift_reply(404)
        # manual headers: _reply would emit its own Content-Length 0
        # alongside the object size (malformed duplicate header)
        self.send_response(200)
        self.send_header("Content-Type", "application/octet-stream")
        self.send_header("Content-Length", str(ent["size"]))
        self.send_header("ETag", ent["etag"])
        for k, v in self._obj_meta_headers(ent).items():
            self.send_header(k, v)
        self.end_headers()

    def _swift_PUT(self, container, obj, q, body):
        if not container:
            return self._reply(400, b"", ctype="text/plain")
        if not obj:
            created = self.store.create_bucket(container)
            cmeta = self._collect_container_meta()
            if cmeta:
                self.store.set_container_meta(container, cmeta)
            return self._reply(201 if created else 202, b"",
                               ctype="text/plain")
        meta = self._collect_obj_meta()
        etag, _vid = self.store.put_object(container, obj, body,
                                           meta=meta or None)
        if etag is None:
            return self._reply(404, b"", ctype="text/plain")
        self._reply(201, b"", ctype="text/plain", headers={"ETag": etag})

    def _swift_POST(self, container, obj, q, body):
        if container and not obj:
            # container metadata update (Swift POST replaces the set)
            if not self.store.set_container_meta(
                    container, self._collect_container_meta()):
                return self._reply(404, b"", ctype="text/plain")
            return self._reply(204, b"", ctype="text/plain")
        # object metadata update (Swift POST replaces the meta set) —
        # index-only: no new version, data and ETag untouched
        if not container or not obj:
            return self._reply(400, b"", ctype="text/plain")
        if not self.store.update_meta(container, obj,
                                      self._collect_obj_meta() or None):
            return self._reply(404, b"", ctype="text/plain")
        self._reply(202, b"", ctype="text/plain")

    def _swift_DELETE(self, container, obj, q, body):
        if not container:
            return self._reply(400, b"", ctype="text/plain")
        if obj:
            outcome, _v = self.store.delete_object(container, obj)
            return self._reply(
                404 if outcome == "missing" else 204, b"",
                ctype="text/plain")
        rv = self.store.delete_bucket(container)
        code = {0: 204, -404: 404, -409: 409}[rv]
        self._reply(code, b"", ctype="text/plain")

    def _int_param(self, q: dict, name: str, default: int | None = None):
        """Parse an int query param; raises _BadParam -> 400
        InvalidArgument instead of a connection-killing ValueError."""
        vals = q.get(name)
        if not vals:
            return default
        try:
            return int(vals[0])
        except ValueError:
            raise _BadParam(name)

    # -- verbs -------------------------------------------------------------
    def do_GET(self):
        if self._swift_dispatch():
            return
        if not self._auth_ok(self._body()):
            return
        bucket, key, q = self._path()
        if not bucket:
            # ListAllMyBuckets
            items = "".join(
                f"<Bucket><Name>{_xml_escape(n)}</Name></Bucket>"
                for n in sorted(self.store.buckets())
            )
            self._reply(200, (
                '<?xml version="1.0"?><ListAllMyBucketsResult>'
                f"<Buckets>{items}</Buckets></ListAllMyBucketsResult>"
            ).encode())
            return
        if not key:
            if not self.store.bucket_exists(bucket):
                return self._error(404, "NoSuchBucket")
            if "versioning" in q:
                status = self.store.versioning_status(bucket)
                inner = f"<Status>{status}</Status>" if status else ""
                self._reply(200, (
                    '<?xml version="1.0"?>'
                    f"<VersioningConfiguration>{inner}"
                    "</VersioningConfiguration>"
                ).encode())
                return
            if "lifecycle" in q:
                if not self.store.bucket_exists(bucket):
                    return self._error(404, "NoSuchBucket")
                rules = self.store.lifecycle_rules(bucket)
                if rules is None:
                    return self._error(
                        404, "NoSuchLifecycleConfiguration")
                parts = []
                for r in rules:
                    exp = (f"<Expiration><Days>{int(r['days'])}</Days>"
                           "</Expiration>" if r.get("days") is not None
                           else "")
                    nce = (("<NoncurrentVersionExpiration>"
                            f"<NoncurrentDays>"
                            f"{int(r['noncurrent_days'])}"
                            f"</NoncurrentDays>"
                            "</NoncurrentVersionExpiration>")
                           if r.get("noncurrent_days") is not None
                           else "")
                    parts.append(
                        f"<Rule><ID>{_xml_escape(r.get('id', ''))}</ID>"
                        f"<Prefix>{_xml_escape(r.get('prefix', ''))}"
                        f"</Prefix><Status>{r.get('status', 'Enabled')}"
                        f"</Status>{exp}{nce}</Rule>"
                    )
                self._reply(200, (
                    '<?xml version="1.0"?><LifecycleConfiguration>'
                    + "".join(parts) + "</LifecycleConfiguration>"
                ).encode())
                return
            prefix = q.get("prefix", [""])[0]
            marker = q.get("marker", [""])[0]
            try:
                max_keys = self._int_param(q, "max-keys", 1000)
            except _BadParam:
                return self._error(400, "InvalidArgument")
            if max_keys < 0:
                return self._error(400, "InvalidArgument")
            if "versions" in q:
                rows, truncated = self.store.list_versions(
                    bucket, prefix=prefix, marker=marker, maxn=max_keys
                )
                items = []
                for k, rec, latest in rows:
                    tag = "DeleteMarker" if rec["dm"] else "Version"
                    size = ("" if rec["dm"]
                            else f"<Size>{rec['size']}</Size>"
                                 f'<ETag>"{rec["etag"]}"</ETag>')
                    items.append(
                        f"<{tag}><Key>{_xml_escape(k)}</Key>"
                        f"<VersionId>{rec['vid']}</VersionId>"
                        f"<IsLatest>{str(latest).lower()}</IsLatest>"
                        f"{size}</{tag}>"
                    )
                self._reply(200, (
                    '<?xml version="1.0"?><ListVersionsResult>'
                    f"<Name>{_xml_escape(bucket)}</Name>"
                    f"<Prefix>{_xml_escape(prefix)}</Prefix>"
                    f"<IsTruncated>{str(truncated).lower()}</IsTruncated>"
                    f"{''.join(items)}</ListVersionsResult>"
                ).encode())
                return
            # live_only at the store layer: a delete-marker head hides
            # the key BEFORE the max-keys window fills (review r5)
            entries, truncated = self.store._index_list(
                bucket, prefix=prefix, marker=marker, maxn=max_keys,
                live_only=True,
            )
            items = "".join(
                f"<Contents><Key>{_xml_escape(k)}</Key>"
                f"<Size>{ent['size']}</Size>"
                f'<ETag>"{ent["etag"]}"</ETag></Contents>'
                for k, ent in entries
            )
            self._reply(200, (
                '<?xml version="1.0"?><ListBucketResult>'
                f"<Name>{_xml_escape(bucket)}</Name>"
                f"<Prefix>{_xml_escape(prefix)}</Prefix>"
                f"<IsTruncated>{str(truncated).lower()}</IsTruncated>"
                f"{items}</ListBucketResult>"
            ).encode())
            return
        vid = q.get("versionId", [None])[0]
        body, ent = self.store.get_object(bucket, key, vid)
        if ent is None:
            return self._error(404, "NoSuchKey")
        if body is None:  # addressed a delete marker by version id
            return self._error(405, "MethodNotAllowed")
        headers = {"ETag": f'"{ent["etag"]}"'}
        if ent.get("vid"):
            headers["x-amz-version-id"] = ent["vid"]
        self._reply(200, body, ctype="application/octet-stream",
                    headers=headers)

    def do_HEAD(self):
        if self._swift_dispatch():
            return
        if not self._auth_ok(self._body()):
            return
        bucket, key, q = self._path()
        vid = q.get("versionId", [None])[0]
        ent = self.store.head_object(bucket, key, vid) if key else None
        if ent is None:
            self.send_response(404)
            self.send_header("Content-Length", "0")
            self.end_headers()
            return
        if ent.get("dm"):
            # delete marker addressed by version id: mirror the GET
            # path's 405 (S3 refuses both verbs on markers)
            self.send_response(405)
            self.send_header("Content-Length", "0")
            self.send_header("x-amz-delete-marker", "true")
            self.end_headers()
            return
        self.send_response(200)
        self.send_header("Content-Length", str(ent["size"]))
        self.send_header("ETag", f'"{ent["etag"]}"')
        if ent.get("vid"):
            self.send_header("x-amz-version-id", ent["vid"])
        self.end_headers()

    def do_PUT(self):
        if self._swift_dispatch():
            return
        bucket, key, q = self._path()
        # always drain the body: an unread body desynchronizes the
        # HTTP/1.1 keep-alive stream (e.g. CreateBucketConfiguration XML)
        body = self._body()
        if not self._auth_ok(body):
            return
        if not bucket:
            return self._error(400, "InvalidRequest")
        if not key:
            if "versioning" in q:
                m = re.search(rb"<Status>\s*(\w+)\s*</Status>", body)
                status = m.group(1).decode() if m else ""
                if status not in ("Enabled", "Suspended"):
                    return self._error(400, "IllegalVersioningConfigurationException")
                if not self.store.set_versioning(bucket, status):
                    return self._error(404, "NoSuchBucket")
                self._reply(200)
                return
            if "lifecycle" in q:
                rules = []
                for rxml in re.findall(rb"<Rule>(.*?)</Rule>", body,
                                       re.S):
                    def _tag(t, s=rxml):
                        m = re.search(
                            rb"<" + t + rb">\s*(.*?)\s*</" + t + rb">",
                            s, re.S)
                        return m.group(1).decode() if m else None
                    # transitions are out of scope — REJECT rather than
                    # misread their <Days> as an Expiration and delete
                    # data that was meant to move storage classes
                    if re.search(rb"<(NoncurrentVersion)?Transition>",
                                 rxml):
                        return self._error(
                            501, "NotImplemented")
                    rule = {"id": _tag(rb"ID") or "",
                            "prefix": _tag(rb"Prefix") or "",
                            "status": _tag(rb"Status") or "Enabled"}
                    if rule["status"] not in ("Enabled", "Disabled"):
                        return self._error(400, "MalformedXML")
                    # scope day tags to their parent action elements
                    exp = re.search(
                        rb"<Expiration>(.*?)</Expiration>", rxml, re.S)
                    nce = re.search(
                        rb"<NoncurrentVersionExpiration>(.*?)"
                        rb"</NoncurrentVersionExpiration>", rxml, re.S)
                    days = _tag(rb"Days", exp.group(1)) if exp else None
                    ncd = (_tag(rb"NoncurrentDays", nce.group(1))
                           if nce else None)
                    if days is not None:
                        try:
                            rule["days"] = int(days)
                        except ValueError:
                            return self._error(400, "MalformedXML")
                        if rule["days"] <= 0:  # S3: positive integer
                            return self._error(400, "MalformedXML")
                    if ncd is not None:
                        try:
                            rule["noncurrent_days"] = int(ncd)
                        except ValueError:
                            return self._error(400, "MalformedXML")
                        if rule["noncurrent_days"] <= 0:
                            return self._error(400, "MalformedXML")
                    if "days" not in rule \
                            and "noncurrent_days" not in rule:
                        return self._error(400, "MalformedXML")
                    rules.append(rule)
                if not rules:
                    return self._error(400, "MalformedXML")
                if not self.store.set_lifecycle(bucket, rules):
                    return self._error(404, "NoSuchBucket")
                self._reply(200)
                return
            self.store.create_bucket(bucket)  # idempotent, like S3
            self._reply(200)
            return
        if "partNumber" in q and "uploadId" in q:
            try:
                part_n = self._int_param(q, "partNumber")
            except _BadParam:
                return self._error(400, "InvalidArgument")
            etag = self.store.put_part(q["uploadId"][0], part_n, body)
            if etag is None:
                return self._error(404, "NoSuchUpload")
            self._reply(200, headers={"ETag": f'"{etag}"'})
            return
        etag, vid = self.store.put_object(bucket, key, body)
        if etag is None:
            return self._error(404, "NoSuchBucket")
        headers = {"ETag": f'"{etag}"'}
        if vid is not None:
            headers["x-amz-version-id"] = vid
        self._reply(200, headers=headers)

    def do_POST(self):
        if self._swift_dispatch():
            return
        bucket, key, q = self._path()
        body = self._body()  # drain (CompleteMultipartUpload list unused)
        if not self._auth_ok(body):
            return
        if "uploads" in q:
            uid = self.store.create_upload(bucket, key)
            if uid is None:
                return self._error(404, "NoSuchBucket")
            self._reply(200, (
                '<?xml version="1.0"?><InitiateMultipartUploadResult>'
                f"<UploadId>{uid}</UploadId>"
                "</InitiateMultipartUploadResult>"
            ).encode())
            return
        if "uploadId" in q:
            done = self.store.complete_upload(q["uploadId"][0])
            if done[0] == "nosuch":
                return self._error(404, "NoSuchUpload")
            if done[0] == "empty":
                return self._error(400, "InvalidPart")
            b, k, etag = done[1]
            self._reply(200, (
                '<?xml version="1.0"?><CompleteMultipartUploadResult>'
                f"<Key>{_xml_escape(k)}</Key>"
                f'<ETag>"{etag}"</ETag>'
                "</CompleteMultipartUploadResult>"
            ).encode())
            return
        self._error(400, "InvalidRequest")

    def do_DELETE(self):
        if self._swift_dispatch():
            return
        if not self._auth_ok(self._body()):
            return
        bucket, key, q = self._path()
        if bucket and not key and "lifecycle" in q:
            if not self.store.bucket_exists(bucket):
                return self._error(404, "NoSuchBucket")
            self.store.delete_lifecycle(bucket)
            self._reply(204)
            return
        if key and "uploadId" in q:
            if not self.store.abort_upload(q["uploadId"][0]):
                return self._error(404, "NoSuchUpload")
            self._reply(204)
            return
        if key:
            vid = q.get("versionId", [None])[0]
            outcome, ovid = self.store.delete_object(bucket, key, vid)
            if outcome == "missing":
                return self._error(404, "NoSuchKey")
            headers = {}
            if ovid is not None:
                headers["x-amz-version-id"] = ovid
            if outcome == "marker":
                headers["x-amz-delete-marker"] = "true"
            self._reply(204, headers=headers)
            return
        rv = self.store.delete_bucket(bucket)
        if rv == -404:
            return self._error(404, "NoSuchBucket")
        if rv == -409:
            return self._error(409, "BucketNotEmpty")
        self._reply(204)


class RGWDaemon:
    """reference: the radosgw daemon — binds HTTP, serves S3 over its own
    librados client."""

    def __init__(self, cct, mon_addrs, port: int = 0):
        self.cct = cct
        self.mon_addrs = mon_addrs
        self.port = port
        self.httpd: ThreadingHTTPServer | None = None
        self._rados = None
        self._thread: threading.Thread | None = None

    @property
    def addr(self) -> tuple[str, int]:
        assert self.httpd is not None
        return self.httpd.server_address[:2]

    def start(self) -> None:
        from ..client.rados import Rados

        self._rados = Rados(self.cct, self.mon_addrs, name="client.rgw")
        self._rados.connect(timeout=30.0)
        store = _Store(self._rados)
        handler = type("BoundHandler", (_Handler,), {"store": store})
        self.httpd = ThreadingHTTPServer(("127.0.0.1", self.port), handler)
        self.httpd.cct = self.cct
        self.httpd.s3_secret_lookup = None
        self.httpd.swift_tokens = {}  # X-Auth-Token -> account
        if self.cct.conf.get("rgw_enable_sigv4"):
            # fail LOUDLY at start if misconfigured: a sigv4 gateway
            # without the cluster secret could never accept anyone
            from ..auth import CephxAuthenticator
            from .sigv4 import derive_s3_secret

            secret = CephxAuthenticator(
                self.cct.conf.get("auth_shared_secret")
            ).secret
            mc = self._rados.mc

            def lookup(access_key: str) -> list[str]:
                gen = (mc.osdmap.auth_gens.get("rgw", 1)
                       if mc.osdmap is not None else 1)
                return [derive_s3_secret(secret, access_key, g)
                        for g in (gen, gen - 1) if g >= 1]

            self.httpd.s3_secret_lookup = lookup
        self._thread = threading.Thread(
            target=self.httpd.serve_forever, name="rgw-http", daemon=True
        )
        self._thread.start()
        # lifecycle worker (reference: the RGWLC background thread;
        # upstream runs daily, the dev-scale interval is configurable)
        self._lc_stop = threading.Event()

        def _lc_loop():
            interval = float(self.cct.conf.get("rgw_lc_interval"))
            while not self._lc_stop.wait(timeout=interval):
                try:
                    done = store.lc_process()
                    if done:
                        self.cct.dout("rgw", 2, f"lc expired {done}")
                except Exception as e:
                    self.cct.dout("rgw", 1, f"lc pass failed: {e!r}")

        self._lc_thread = threading.Thread(
            target=_lc_loop, name="rgw-lc", daemon=True)
        self._lc_thread.start()

    def shutdown(self) -> None:
        if getattr(self, "_lc_stop", None) is not None:
            self._lc_stop.set()
            self._lc_thread.join(timeout=5)
        if self.httpd is not None:
            try:
                self.httpd.shutdown()
                self.httpd.server_close()
            except Exception as e:
                # a wedged listener must not strand the serve-thread
                # join and rados teardown behind it
                self.cct.dout("rgw", 0, f"httpd shutdown raised: {e!r}")
        if self._thread is not None:
            self._thread.join(timeout=5)
        if self._rados is not None:
            try:
                self._rados.shutdown()
            except Exception as e:
                self.cct.dout("rgw", 0, f"rados shutdown raised: {e!r}")
        # the context goes last: its admin socket serves debug commands
        # right up until the daemon is gone
        self.cct.shutdown()
