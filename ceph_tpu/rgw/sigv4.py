"""AWS Signature Version 4 — signer + verifier (reference:
src/rgw/rgw_auth_s3.cc :: AWSv4ComplMulti / get_v4_canonical_*;
round-3 verdict task #5).

The gateway's S3 credentials are BACKED BY CEPHX: an S3 secret key is
derived from the cephx cluster secret as
HMAC(cluster_secret, "s3:{access_key}:{gen}") with `gen` the OSDMap's
"rgw" auth generation — so keys are provisioned by the mon
(`auth get-s3-key`), never stored, and `auth rotate service=rgw`
invalidates every outstanding key after the usual one-generation grace
(the reference backs S3 keys with RGWUserInfo in RADOS; deriving from
the cephx secret plays that role without a user database).

Correctness is pinned to the AWS-published 'get-vanilla-query' test
vector (tests/test_rgw_sigv4.py) — both halves (sign + verify) must
agree with it bit-for-bit.
"""
from __future__ import annotations

import calendar
import hashlib
import hmac
import time
from urllib.parse import quote

from ..auth.cephx import derive_s3_secret  # noqa: F401  (public surface)

ALGORITHM = "AWS4-HMAC-SHA256"
REGION = "ceph-tpu"
SERVICE = "s3"
# allowed |x-amz-date - now| (reference: RGW_AUTH_GRACE 15 min)
CLOCK_SKEW = 900.0


class SigV4Error(Exception):
    """Carries the S3 error code the gateway should answer with."""

    def __init__(self, s3code: str, detail: str = ""):
        super().__init__(detail or s3code)
        self.s3code = s3code


def _uri_encode(s: str, keep_slash: bool) -> str:
    # AWS canonical encoding: unreserved = A-Za-z0-9-._~; space -> %20
    return quote(s, safe="/-_.~" if keep_slash else "-_.~")


def _canonical_query(params: list[tuple[str, str]]) -> str:
    enc = sorted(
        (_uri_encode(k, False), _uri_encode(v, False)) for k, v in params
    )
    return "&".join(f"{k}={v}" for k, v in enc)


def _hx(b: bytes) -> str:
    return hashlib.sha256(b).hexdigest()


def _hmac(key: bytes, msg: str) -> bytes:
    return hmac.new(key, msg.encode(), hashlib.sha256).digest()


def signing_key(secret: str, date: str, region: str = REGION,
                service: str = SERVICE) -> bytes:
    k = _hmac(("AWS4" + secret).encode(), date)
    k = _hmac(k, region)
    k = _hmac(k, service)
    return _hmac(k, "aws4_request")


def canonical_request(method: str, path: str,
                      params: list[tuple[str, str]],
                      headers: dict[str, str],
                      signed_headers: list[str],
                      payload_hash: str) -> str:
    canon_headers = "".join(
        f"{h}:{' '.join(headers.get(h, '').split())}\n"
        for h in signed_headers
    )
    return "\n".join([
        method.upper(),
        _uri_encode(path, True) or "/",
        _canonical_query(params),
        canon_headers,
        ";".join(signed_headers),
        payload_hash,
    ])


def string_to_sign(amz_date: str, scope: str, creq: str) -> str:
    return "\n".join([ALGORITHM, amz_date, scope, _hx(creq.encode())])


def sign_request(method: str, path: str, params: list[tuple[str, str]],
                 headers: dict[str, str], body: bytes,
                 access_key: str, secret: str,
                 amz_date: str | None = None,
                 region: str = REGION, service: str = SERVICE) -> dict:
    """Client side: returns the headers to add (Authorization,
    x-amz-date, x-amz-content-sha256).  `headers` must already contain
    Host."""
    if amz_date is None:
        amz_date = time.strftime("%Y%m%dT%H%M%SZ", time.gmtime())
    date = amz_date[:8]
    payload_hash = _hx(body)
    hdrs = {k.lower(): v for k, v in headers.items()}
    hdrs["x-amz-date"] = amz_date
    hdrs["x-amz-content-sha256"] = payload_hash
    signed = sorted(hdrs)
    scope = f"{date}/{region}/{service}/aws4_request"
    creq = canonical_request(method, path, params, hdrs, signed,
                             payload_hash)
    sts = string_to_sign(amz_date, scope, creq)
    k = signing_key(secret, date, region, service)
    sig = hmac.new(k, sts.encode(), hashlib.sha256).hexdigest()
    return {
        "x-amz-date": amz_date,
        "x-amz-content-sha256": payload_hash,
        "Authorization": (
            f"{ALGORITHM} Credential={access_key}/{scope}, "
            f"SignedHeaders={';'.join(signed)}, Signature={sig}"
        ),
    }


def _parse_authorization(value: str) -> tuple[str, str, list[str], str]:
    """(access_key, scope, signed_headers, signature) or SigV4Error."""
    try:
        alg, rest = value.split(" ", 1)
        if alg != ALGORITHM:
            raise SigV4Error("InvalidRequest", f"unsupported {alg!r}")
        fields = {}
        for part in rest.split(","):
            k, v = part.strip().split("=", 1)
            fields[k] = v
        cred = fields["Credential"]
        access_key, scope = cred.split("/", 1)
        signed = fields["SignedHeaders"].split(";")
        return access_key, scope, signed, fields["Signature"]
    except SigV4Error:
        raise
    except Exception as e:
        raise SigV4Error("InvalidRequest",
                         f"malformed Authorization: {e}") from e


def verify_request(method: str, path: str, params: list[tuple[str, str]],
                   headers: dict[str, str], body: bytes,
                   secret_lookup, now: float | None = None) -> str:
    """Gateway side: validates the whole SigV4 envelope; returns the
    authenticated access key, or raises SigV4Error with the S3 error
    code to answer.  `secret_lookup(access_key) -> [candidate secrets]`
    (several = auth-generation grace window)."""
    hdrs = {k.lower(): v for k, v in headers.items()}
    auth = hdrs.get("authorization")
    if not auth:
        raise SigV4Error("AccessDenied", "anonymous access disabled")
    access_key, scope, signed, signature = _parse_authorization(auth)
    amz_date = hdrs.get("x-amz-date", "")
    payload_hash = hdrs.get("x-amz-content-sha256", "")
    if not amz_date or not payload_hash:
        raise SigV4Error("InvalidRequest", "missing x-amz-* headers")
    # scope must match this gateway's realm and the request date
    want_scope = f"{amz_date[:8]}/{REGION}/{SERVICE}/aws4_request"
    if scope != want_scope:
        raise SigV4Error("SignatureDoesNotMatch",
                         f"scope {scope!r} != {want_scope!r}")
    for required in ("host", "x-amz-date", "x-amz-content-sha256"):
        if required not in signed:
            raise SigV4Error("SignatureDoesNotMatch",
                             f"{required} not in SignedHeaders")
    # clock skew (reference: 15-min request expiry).  timegm, not
    # mktime-plus-timezone: the latter is an hour off whenever the
    # host's local zone is in DST (review r4 — a total auth outage)
    try:
        t = calendar.timegm(time.strptime(amz_date, "%Y%m%dT%H%M%SZ"))
    except ValueError as e:
        raise SigV4Error("InvalidRequest", f"bad x-amz-date: {e}") from e
    if abs((time.time() if now is None else now) - t) > CLOCK_SKEW:
        raise SigV4Error("RequestTimeTooSkewed", amz_date)
    if payload_hash != "UNSIGNED-PAYLOAD" and _hx(body) != payload_hash:
        raise SigV4Error("XAmzContentSHA256Mismatch", "payload hash")
    creq = canonical_request(method, path, params, hdrs, signed,
                             payload_hash)
    sts = string_to_sign(amz_date, scope, creq)
    secrets = secret_lookup(access_key)
    if not secrets:
        raise SigV4Error("InvalidAccessKeyId", access_key)
    for secret in secrets:
        k = signing_key(secret, amz_date[:8])
        want = hmac.new(k, sts.encode(), hashlib.sha256).hexdigest()
        if hmac.compare_digest(want, signature):
            return access_key
    raise SigV4Error("SignatureDoesNotMatch", "signature mismatch")
