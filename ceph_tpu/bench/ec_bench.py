"""ceph_erasure_code_benchmark-compatible CLI.

Re-creation of the reference's benchmark harness (reference:
src/test/erasure-code/ceph_erasure_code_benchmark.cc :: ErasureCodeBench —
flags --plugin/--parameter/--workload/--size/--iterations/--erasures/
--erasures-generation; prints seconds and bytes), so BASELINE numbers are
produced by a CLI-compatible tool (SURVEY.md §3.5 "the contract for BASELINE
measurements").

Extra over the reference: `--json` emits one machine-readable line, and TPU
runs amortize the dispatch/tunnel latency by chaining iterations on-device
(each iteration consumes the previous result, so nothing is elided; see
--no-chain to force per-iteration dispatch like the reference's loop).

Usage example (BASELINE.json config 2):
    python -m ceph_tpu.bench.ec_bench encode --plugin jax \
        --parameter k=8 --parameter m=4 --parameter technique=cauchy_good \
        --size 1048576 --iterations 64
"""
from __future__ import annotations

import argparse
import json
import sys
import time

import numpy as np

from ..ec.registry import ErasureCodePluginRegistry


def parse_args(argv=None):
    p = argparse.ArgumentParser(prog="ceph_tpu.bench.ec_bench")
    p.add_argument("workload", choices=["encode", "decode", "rmw"])
    p.add_argument("--plugin", "-P", default="jax")
    p.add_argument(
        "--parameter",
        "-p",
        action="append",
        default=[],
        help="profile key=value (repeatable), e.g. -p k=8 -p m=4",
    )
    p.add_argument("--size", "-s", type=int, default=1 << 20, help="object bytes per iteration")
    p.add_argument("--iterations", "-i", type=int, default=16)
    p.add_argument("--erasures", "-e", type=int, default=1)
    p.add_argument(
        "--erasures-generation",
        choices=["random", "exhaustive"],
        default="random",
    )
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--json", action="store_true")
    p.add_argument("--no-chain", action="store_true", help="per-iteration dispatch")
    p.add_argument(
        "--stream", type=int, default=0, metavar="N",
        help="encode N fresh host batches double-buffered (DMA/compute "
        "overlap) instead of chained device-resident iterations",
    )
    p.add_argument(
        "--rmw-width", type=int, default=4096, metavar="BYTES",
        help="rmw workload: bytes of the sub-stripe update window",
    )
    return p.parse_args(argv)


def build_codec(args):
    profile = {"plugin": args.plugin}
    for kv in args.parameter:
        key, _, val = kv.partition("=")
        profile[key] = val
    return ErasureCodePluginRegistry.instance().factory(profile), profile


def run_encode(codec, args) -> dict:
    from .timing import time_chained_encode

    rng = np.random.default_rng(args.seed)
    chunk_size = codec.get_chunk_size(args.size)
    chunks = rng.integers(0, 256, (codec.k, chunk_size), dtype=np.uint8)
    if args.stream:
        # end-to-end streaming throughput INCLUDING host->device DMA,
        # double-buffered (ops/pipeline.py); distinct fresh batches so
        # nothing is cached away
        if getattr(codec, "coding", None) is None or \
                getattr(codec, "backend", None) != "jax":
            raise SystemExit(
                "--stream needs a byte-matrix codec on the jax backend "
                "(bitmatrix techniques / host backends use the default "
                "timing paths)"
            )
        from ..ops.pipeline import stream_encode

        batches = [
            rng.integers(0, 256, (codec.k, chunk_size), dtype=np.uint8)
            for _ in range(args.stream)
        ]
        stream_encode(codec.coding, batches[:1])  # warm/compile
        t0 = time.perf_counter()
        stream_encode(codec.coding, batches)
        seconds = time.perf_counter() - t0
        return {"seconds": seconds, "bytes": args.size * args.stream}
    if (
        getattr(codec, "backend", None) == "jax"
        and getattr(codec, "coding", None) is not None
        and not args.no_chain
    ):  # bitmatrix codecs (no byte coding matrix) take the generic path
        seconds = time_chained_encode(codec.coding, chunks, args.iterations)
    else:
        codec.encode_chunks(chunks)  # warm
        t0 = time.perf_counter()
        for _ in range(args.iterations):
            codec.encode_chunks(chunks)
        seconds = time.perf_counter() - t0
    total = args.size * args.iterations
    return {"seconds": seconds, "bytes": total}


def run_rmw(codec, args) -> dict:
    """Partial-stripe RMW parity-delta workload: each iteration is the
    device-side cost of one OSD ranged write — the parity delta for a
    --rmw-width byte sub-stripe update, i.e. one GF matrix apply over
    just the touched column window (reference: the re-encode inside
    src/osd/ECTransaction.cc :: generate_transactions, expressed as the
    optimized-EC parity-delta; mirrors OSD._ec_rmw).  Reported bytes are
    the UPDATED user bytes, so GiB/s is directly comparable to what a
    full-stripe re-encode of the same update would cost."""
    from .timing import time_chained_encode

    rng = np.random.default_rng(args.seed)
    w = args.rmw_width
    W = codec.get_chunk_size(codec.k * w)
    delta = rng.integers(0, 256, (codec.k, W), dtype=np.uint8)
    if (
        getattr(codec, "backend", None) == "jax"
        and getattr(codec, "coding", None) is not None
        and not args.no_chain
    ):
        seconds = time_chained_encode(codec.coding, delta, args.iterations)
    else:
        codec.encode_chunks(delta)  # warm
        t0 = time.perf_counter()
        for _ in range(args.iterations):
            codec.encode_chunks(delta)
        seconds = time.perf_counter() - t0
    return {"seconds": seconds, "bytes": w * args.iterations,
            "ops": args.iterations}


def run_decode(codec, args) -> dict:
    import itertools

    rng = np.random.default_rng(args.seed)
    k, m, n = codec.k, codec.m, codec.get_chunk_count()
    chunk_size = codec.get_chunk_size(args.size)
    data = rng.integers(0, 256, (k, chunk_size), dtype=np.uint8)
    encoded = codec.encode(set(range(n)), data.tobytes())
    if args.erasures > m:
        raise SystemExit(f"--erasures {args.erasures} > m={m}")
    if args.erasures_generation == "exhaustive":
        patterns = itertools.cycle(
            itertools.combinations(range(n), args.erasures)
        )
    else:
        patterns = iter(
            lambda: tuple(rng.choice(n, size=args.erasures, replace=False)), None
        )
    want = set(range(k))
    t0 = time.perf_counter()
    for _ in range(args.iterations):
        erased = set(int(x) for x in next(patterns))
        have = {i: c for i, c in encoded.items() if i not in erased}
        codec.decode(want, have, chunk_size)
    seconds = time.perf_counter() - t0
    return {"seconds": seconds, "bytes": args.size * args.iterations}


def main(argv=None):
    from ..common.tracer import device_trace as _device_trace
    args = parse_args(argv)
    codec, profile = build_codec(args)
    with _device_trace():  # armed by CEPH_TPU_PROFILE=<logdir>
        runner = {"encode": run_encode, "decode": run_decode,
                  "rmw": run_rmw}[args.workload]
        res = runner(codec, args)
    gibps = res["bytes"] / max(res["seconds"], 1e-12) / 2**30
    if args.json:
        print(
            json.dumps(
                {
                    "workload": args.workload,
                    "profile": profile,
                    "seconds": round(res["seconds"], 6),
                    "bytes": res["bytes"],
                    "GiB_per_s": round(gibps, 3),
                }
            )
        )
    else:
        # reference output shape: "<seconds>\t<bytes>"
        print(f"{res['seconds']:.6f}\t{res['bytes']}")
        print(f"# {gibps:.2f} GiB/s {args.workload} plugin={args.plugin}", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
