"""CRUSH mapping benchmark CLI — BASELINE.json config 5.

The analog of `crushtool --test` timing runs (reference:
src/tools/crushtool.cc + src/crush/CrushTester.cc) over a large x batch:
maps N placement inputs through a rule on the TPU batch mapper and on the
C++ oracle (the compiled-C mapper baseline), reporting maps/s.

Usage:
    python -m ceph_tpu.bench.crush_bench --osds 1024 --hosts 128 \
        --num-pgs 10000000 --numrep 3 [--backend jax|oracle|both] [--json]
"""
from __future__ import annotations

import argparse
import json
import sys
import time

import numpy as np


def parse_args(argv=None):
    p = argparse.ArgumentParser(prog="ceph_tpu.bench.crush_bench")
    p.add_argument("--osds", type=int, default=1024)
    p.add_argument("--hosts", type=int, default=128)
    p.add_argument("--num-pgs", type=int, default=1_000_000, dest="num_pgs")
    p.add_argument("--numrep", type=int, default=3)
    p.add_argument("--rule", type=int, default=0, help="0=firstn replicated, 1=indep EC")
    p.add_argument("--backend", choices=["jax", "oracle", "both"], default="both")
    p.add_argument("--json", action="store_true")
    return p.parse_args(argv)


def main(argv=None):
    args = parse_args(argv)
    from ceph_tpu.crush import CompiledCrushMap, build_hierarchical_map, crush_do_rule_batch

    if args.osds % args.hosts:
        raise SystemExit("--osds must be divisible by --hosts")
    cmap = build_hierarchical_map(args.hosts, args.osds // args.hosts)
    weights = np.full(args.osds, 0x10000, dtype=np.uint32)
    xs = np.arange(args.num_pgs, dtype=np.int64)
    res: dict = {
        "osds": args.osds,
        "hosts": args.hosts,
        "num_pgs": args.num_pgs,
        "numrep": args.numrep,
        "rule": args.rule,
    }

    if args.backend in ("jax", "both"):
        cm = CompiledCrushMap(cmap)
        warm = crush_do_rule_batch(cm, args.rule, xs[:1024], args.numrep, weights)
        np.asarray(warm)  # compile + sync
        t0 = time.perf_counter()
        out = crush_do_rule_batch(cm, args.rule, xs, args.numrep, weights)
        out = np.asarray(out)  # fetch = true barrier
        dt = time.perf_counter() - t0
        res["jax_maps_per_s"] = round(args.num_pgs / dt)
        res["jax_seconds"] = round(dt, 4)
        res["sample"] = out[:2].tolist()

    if args.backend in ("oracle", "both"):
        from ceph_tpu.crush.oracle_bridge import do_rule_batch_oracle

        n = min(args.num_pgs, 1_000_000)  # oracle baseline on a capped batch
        t0 = time.perf_counter()
        out_o = do_rule_batch_oracle(cmap, args.rule, xs[:n], args.numrep, weights)
        dt = time.perf_counter() - t0
        res["oracle_maps_per_s"] = round(n / dt)
        res["oracle_seconds"] = round(dt, 4)
        if args.backend == "both" and "sample" in res:
            match = (out_o[:2] == np.asarray(res["sample"])).all()
            res["bit_exact_vs_oracle"] = bool(
                (out_o == np.asarray(out[: len(out_o)])).all()
            ) if args.num_pgs <= 1_000_000 else bool(match)

    if "jax_maps_per_s" in res and "oracle_maps_per_s" in res:
        res["speedup"] = round(res["jax_maps_per_s"] / res["oracle_maps_per_s"], 2)
    print(json.dumps(res) if args.json else res)
    return 0


if __name__ == "__main__":
    sys.exit(main())
