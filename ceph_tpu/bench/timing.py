"""Shared on-device timing for encode benchmarks.

The tunneled TPU platform has ~70 ms fixed dispatch round-trip and a lazy
block_until_ready, so honest throughput numbers require (a) chaining
iterations on-device with a data dependency (nothing can be elided), and
(b) a scalar-fetch barrier.  Both bench.py and ceph_tpu.bench.ec_bench use
this one implementation so the subtleties can't drift apart.
"""
from __future__ import annotations

import time
from functools import partial

import numpy as np


def make_chained_encode(coding: np.ndarray, kernel: str = "xla"):
    """jitted loop(x, iters) running `iters` dependent encodes of x.

    kernel: 'xla' (ops.bitplane) or 'pallas' (ops.pallas_gf).
    """
    import jax
    import jax.numpy as jnp

    coding = np.ascontiguousarray(coding, dtype=np.uint8)
    m = coding.shape[0]
    if kernel == "pallas":
        from ..ops.pallas_gf import DEFAULT_TILE, _apply_padded, _permuted_bitmatrix

        B = jnp.asarray(_permuted_bitmatrix(coding.tobytes(), coding.shape))

        def apply_fn(x):
            return _apply_padded(B, x, m, coding.shape[1], DEFAULT_TILE, False)

    else:
        from ..ops.bitplane import _apply_bitmatrix, bitmatrix_device

        B = bitmatrix_device(coding.tobytes(), coding.shape)

        def apply_fn(x):
            return _apply_bitmatrix(B, x)

    @partial(jax.jit, static_argnames=("iters",))
    def loop(x, iters):
        def body(_, carry):
            parity = apply_fn(carry)
            return carry.at[:m].set(carry[:m] ^ parity)

        return jax.lax.fori_loop(0, iters, body, x)

    return loop


def time_chained_encode(
    coding: np.ndarray, chunks: np.ndarray, iterations: int, kernel: str = "xla",
    subtract_overhead: bool = False, repeats: int = 1,
) -> float:
    """Seconds for `iterations` chained encodes of chunks [k, L].

    subtract_overhead: measure a 1-iteration run and subtract it, returning
    per-iteration seconds * iterations of pure compute (used by bench.py for
    the headline number); otherwise returns the raw wall time of the loop
    (used by the CLI, matching the reference harness's inclusive timing).
    """
    import jax.numpy as jnp

    loop = make_chained_encode(coding, kernel)
    x = jnp.asarray(chunks)
    if kernel == "pallas":
        # _apply_padded requires tile-aligned lengths; pad once up front.
        # Padded bytes are computed but not counted, so reported throughput
        # can only be under-, never over-stated.
        from ..ops.pallas_gf import DEFAULT_TILE

        pad = (-x.shape[1]) % DEFAULT_TILE
        if pad:
            x = jnp.pad(x, ((0, 0), (0, pad)))
    # warm BOTH computations used in the timed region (loop + scalar fetch):
    # remote compile must not land in the timing
    np.asarray(loop(x, 1)[0, 0])
    np.asarray(loop(x, iterations)[0, 0])
    best = float("inf")
    for _ in range(max(1, repeats)):
        t1 = 0.0
        if subtract_overhead:
            t0 = time.perf_counter()
            np.asarray(loop(x, 1)[0, 0])
            t1 = time.perf_counter() - t0
        t0 = time.perf_counter()
        np.asarray(loop(x, iterations)[0, 0])  # scalar fetch = true barrier
        tN = time.perf_counter() - t0
        if subtract_overhead:
            per = (tN - t1) / (iterations - 1)
            best = min(best, per * iterations)
        else:
            best = min(best, tN)
    return best
