"""Shared on-device timing for encode benchmarks.

The tunneled TPU platform has ~70 ms fixed dispatch round-trip and a lazy
block_until_ready, so honest throughput numbers require (a) chaining
iterations on-device with a data dependency (nothing can be elided), and
(b) a scalar-fetch barrier.  Both bench.py and ceph_tpu.bench.ec_bench use
this one implementation so the subtleties can't drift apart.
"""
from __future__ import annotations

import time
from functools import partial

import numpy as np


def make_chained_encode(coding: np.ndarray, kernel: str = "xla"):
    """(loop, prep): `prep(chunks)` maps a [k, L] host array to the device
    layout the kernel wants; `loop(x, iters)` runs `iters` dependent
    encodes of it.  kernel: 'xla' (ops.bitplane) or 'pallas' (ops.pallas_gf).
    """
    import jax
    import jax.numpy as jnp

    coding = np.ascontiguousarray(coding, dtype=np.uint8)
    rows, n = coding.shape
    if kernel == "pallas":
        from ..ops.pallas_gf import (
            _apply_grouped,
            _kron_matrices,
            _kron_matrices_blocked,
            _pick_group,
            _pick_layout,
        )

        if rows > n:
            raise ValueError("chained pallas bench needs rows <= n")
        G = _pick_group(rows, n)
        # VMEM-bounded layout: fat decode/repair matrices row-block
        # instead of shrinking the tile (round-4 verdict item #4)
        tile, rb = _pick_layout(rows, n, G)
        if rb == 1:
            Bk, Pk = _kron_matrices(coding.tobytes(), coding.shape, G)
        else:
            Bk, Pk, _rows_b = _kron_matrices_blocked(
                coding.tobytes(), coding.shape, G, rb
            )
        B = jnp.asarray(Bk)
        P = jnp.asarray(Pk, jnp.bfloat16)
        xor_rows = rows * G

        def prep(chunks: np.ndarray):
            # pad to a whole number of G*tile segments, then the free
            # row-major regroup to [n*G, L/G].  Padded bytes are computed
            # but not counted by callers, so throughput is understated.
            chunks = np.ascontiguousarray(chunks, dtype=np.uint8)
            pad = (-chunks.shape[1]) % (G * tile)
            if pad:
                chunks = np.pad(chunks, ((0, 0), (0, pad)))
            return jnp.asarray(chunks.reshape(n * G, -1))

        def apply_fn(xg):
            out = _apply_grouped(B, P, xg, rows, n, G, tile, rb, False)
            return out[:xor_rows]

    else:
        from ..ops.bitplane import _apply_bitmatrix, bitmatrix_device

        B = bitmatrix_device(coding.tobytes(), coding.shape)
        xor_rows = rows

        def prep(chunks: np.ndarray):
            return jnp.asarray(np.ascontiguousarray(chunks, dtype=np.uint8))

        def apply_fn(x):
            return _apply_bitmatrix(B, x)

    @partial(jax.jit, static_argnames=("iters",))
    def loop(x, iters):
        def body(_, carry):
            parity = apply_fn(carry)
            return carry.at[:xor_rows].set(carry[:xor_rows] ^ parity)

        return jax.lax.fori_loop(0, iters, body, x)

    return loop, prep


def time_chained_encode(
    coding: np.ndarray, chunks: np.ndarray, iterations: int, kernel: str = "xla",
    subtract_overhead: bool = False, repeats: int = 1,
) -> float:
    """Seconds for `iterations` chained encodes of chunks [k, L].

    subtract_overhead: measure a 1-iteration run and subtract it, returning
    per-iteration seconds * iterations of pure compute (used by bench.py for
    the headline number); otherwise returns the raw wall time of the loop
    (used by the CLI, matching the reference harness's inclusive timing).
    """
    loop, prep = make_chained_encode(coding, kernel)
    x = prep(np.asarray(chunks))
    # warm BOTH computations used in the timed region (loop + scalar fetch):
    # remote compile must not land in the timing
    np.asarray(loop(x, 1)[0, 0])
    np.asarray(loop(x, iterations)[0, 0])
    best_t1 = best_tN = float("inf")
    for _ in range(max(1, repeats)):
        if subtract_overhead:
            t0 = time.perf_counter()
            np.asarray(loop(x, 1)[0, 0])
            best_t1 = min(best_t1, time.perf_counter() - t0)
        t0 = time.perf_counter()
        np.asarray(loop(x, iterations)[0, 0])  # scalar fetch = true barrier
        best_tN = min(best_tN, time.perf_counter() - t0)
    # Subtract the 1-iter run (dispatch + fetch overhead) only when the
    # chained run clearly dominates it.  For tiny per-iteration compute
    # (e.g. a [1, 4] decode-matrix apply) both runs are overhead + noise
    # and naive subtraction goes NEGATIVE (observed: shec -41 GiB/s, r4
    # silicon) — fall back to the raw inclusive time, which understates
    # rather than corrupts.
    if subtract_overhead and best_tN > best_t1 * 1.05:
        per = (best_tN - best_t1) / (iterations - 1)
        return per * iterations
    return best_tN
