"""Benchmark CLIs: ec_bench (ceph_erasure_code_benchmark analog), crush_bench."""
