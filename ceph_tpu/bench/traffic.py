"""Sustained-traffic benchmark: N simulated clients x small EC writes,
batched (write batcher) vs per-op (inline codec), aggregate GiB/s and
p99 latency — the ROADMAP "millions of users" metric.

arXiv:1709.05365 (online EC on large-scale SSD arrays) shows system
throughput under sustained small-write traffic is dominated by the
queueing/batching structure in front of the codec, not the codec
itself; this scenario measures exactly that layer.  Each simulated
client is a closed-loop writer: prepare a 4 KiB stripe, submit to the
encode stage, wait for parity, repeat.  ``batched`` mode drives the
production ``WriteBatcher`` (osd/write_batcher.py) — the identical code
path an OSD primary takes; ``perop`` mode submits through the same
entry with coalescing off (ec_batch_window_ms=0), i.e. today's
one-dispatch-per-stripe path.

cephtrace integration (docs/tracing.md): ``--sampling R`` arms the
tracer and head-samples R of the ops, after which the JSON carries a
per-stage p50/p99 breakdown (admission / queue / encode [/ subop /
commit]) computed from the recorded spans — the p99 number finally
says WHICH stage.  ``--cluster`` runs the same closed-loop writers
against a real LocalCluster EC pool (client -> OSD -> replicas), so
the trace trees span daemons; ``--trace-out FILE`` writes the run's
Perfetto/Chrome-trace JSON (open in ui.perfetto.dev).
``--trace-smoke`` is the CI gate: untraced vs sampling=1.0 cluster
runs, asserting a non-empty CONNECTED trace tree, all five stages in
the breakdown, and <=10% tracing overhead.

Usage (bench.py runs this as its "traffic" phase; qa/ci_gate.sh runs
the tiny smoke configurations):

    python -m ceph_tpu.bench.traffic --clients 32 --seconds 3 --json
    python -m ceph_tpu.bench.traffic --clients 2 --seconds 2 --smoke
    python -m ceph_tpu.bench.traffic --cluster --sampling 1.0 \
        --trace-out /tmp/trace.json --json
    python -m ceph_tpu.bench.traffic --trace-smoke

cephqos additions (docs/qos.md): ``--arrivals poisson --rate R`` makes
each client OPEN-loop (seeded exponential gaps at R ops/s — offered
load independent of service rate, the workload that exposes queueing),
and ``--bully [--qos]`` runs the mixed-population fairness scenario (1
heavy streamer vs N small Poisson writers on a real LocalCluster) that
``qa/qos_smoke.py`` gates controller-on against controller-off.

cephstorm additions (docs/storm_sim.md): every generator takes one
``seed`` (CLI ``--seed``) that derives EVERY random stream in the run
and is recorded in every JSON artifact, so any measured run can be
replayed bit-identically; the ``tenant_*`` functions at the bottom are
the pure multi-tenant workload vocabulary (RGW S3 request mixes,
CephFS metadata storms, RBD snapshot churn; bursty/diurnal arrival
shapes over hot-object populations) the storm planner
(qa/storm/planner.py) draws its client events from.
"""
from __future__ import annotations

import argparse
import json
import math
import os
import sys
import threading
import time

import numpy as np

from ..common.tracer import (
    OP_STAGES,
    TRACER,
    connected_traces,
    perfetto_export,
    sampled_ctx,
    set_op_trace,
    trace_now,
)


#: the one default every traffic artifact records when --seed is absent
DEFAULT_SEED = 1234

#: fixed per-purpose stream ids: two generators never share a stream,
#: and the same (seed, stream, index) always yields the same draws —
#: the replay contract the storm harness's plan_digest depends on
_SEED_STREAMS = {
    "stripes": 0,       # run_traffic's pre-built stripe pool
    "poisson": 1,       # per-client open-loop arrival gaps
    "bully_small": 2,   # per-victim Poisson writers in --bully
    "read_stacks": 3,   # run_read_traffic's survivor-stack pool
    "tenant": 4,        # tenant_next_op draws (storm planner)
}


def derive_rng(seed: int, stream: str, index: int = 0):
    """One independent Generator per (run seed, purpose, actor): numpy
    seeds by entropy-pooling the whole int sequence, so streams never
    collide even when ``seed + i`` arithmetic would."""
    return np.random.default_rng(
        [int(seed), _SEED_STREAMS[stream], int(index)])


def stage_breakdown(spans: list[dict],
                    stages: tuple = OP_STAGES) -> dict:
    """{stage: {p50_ms, p99_ms, n}} over recorded span durations — the
    per-stage half of the bench JSON."""
    by_name: dict[str, list[float]] = {}
    for s in spans:
        if s["name"] in stages and s.get("dur_ms") is not None:
            by_name.setdefault(s["name"], []).append(s["dur_ms"])
    out = {}
    for name, durs in by_name.items():
        durs.sort()
        p50, p99 = _pctiles(durs)
        out[name] = {
            "p50_ms": round(p50, 3),
            "p99_ms": round(p99, 3),
            "n": len(durs),
        }
    return out


def _chunk_len(write_size: int, k: int, align: int = 64) -> int:
    """ErasureCode.get_chunk_size's shape: ceil(size/k), 64-aligned."""
    padded = -(-write_size // k)
    return -(-padded // align) * align


def _pctiles(sorted_vals: list[float]) -> tuple[float | None, float | None]:
    """(p50, p99) of an already-sorted latency list (the one percentile
    idiom every traffic stat shares), None/None when empty."""
    n = len(sorted_vals)
    if not n:
        return None, None
    return sorted_vals[n // 2], sorted_vals[min(n - 1, int(n * 0.99))]


def per_client_stats(lats: list[list[float]]) -> tuple[dict, float | None]:
    """({client: {ops, p50_ms, p99_ms}}, max/min fairness ratio) over
    per-client latency lists — the regression surface the future QoS
    controller is gated on (cephmeter): a controller that starves one
    writer shows up as fairness_ratio >> 1 before it shows up anywhere
    else.  A FULLY starved client still appears (ops=0) and forces
    fairness_ratio to None — total starvation must fail a
    `fairness_ratio <= X` gate, never pass it by omission."""
    rows: dict[str, dict] = {}
    for i, lat in enumerate(lats):
        ls = sorted(lat)
        p50, p99 = _pctiles(ls)
        rows[str(i)] = {
            "ops": len(ls),
            "p50_ms": round(p50 * 1e3, 3) if p50 is not None else None,
            "p99_ms": round(p99 * 1e3, 3) if p99 is not None else None,
        }
    ops = [r["ops"] for r in rows.values()]
    fairness = (round(max(ops) / min(ops), 3)
                if ops and min(ops) > 0 else None)
    return rows, fairness


def run_traffic(
    mode: str,
    n_clients: int = 32,
    seconds: float = 3.0,
    write_size: int = 4096,
    k: int = 8,
    m: int = 4,
    window_ms: float = 2.0,
    max_stripes: int = 64,
    max_bytes: int = 8 << 20,
    qd: int = 4,
    warmup: float = 0.25,
    sampling: float = 0.0,
    arrivals: str = "closed",
    rate: float = 100.0,
    conf_overrides: dict | None = None,
    seed: int = DEFAULT_SEED,
) -> dict:
    """One mode's run; returns ops/GiB-per-s/latency stats.
    sampling > 0 arms cephtrace, head-samples that fraction of ops, and
    adds a per-stage p50/p99 breakdown to the result.

    ``arrivals``: "closed" (the original closed-loop writers — every
    client keeps ``qd`` writes in flight, so offered load tracks
    service rate) or "poisson" (OPEN-loop: each client draws seeded
    exponential inter-arrival gaps at ``rate`` ops/s and submits on
    schedule regardless of completions, up to ``qd`` outstanding —
    offered load is independent of the system, which is the workload
    that exposes queueing; a backlogged client notes its lateness in
    ``sched_lag_ms`` instead of silently slowing down)."""
    from ..common.context import CephContext
    from ..gf.matrix import cauchy_good_coding_matrix
    from ..ops.bitplane import apply_matrix_jax
    from ..osd.write_batcher import WriteBatcher

    assert mode in ("batched", "perop"), mode
    assert arrivals in ("closed", "poisson"), arrivals
    mat = np.ascontiguousarray(cauchy_good_coding_matrix(k, m), np.uint8)
    L = _chunk_len(write_size, k)
    rng = derive_rng(seed, "stripes")
    # a small pool of distinct pre-built stripes per client keeps the
    # generator out of the timed loop while avoiding constant-input
    # caching artifacts
    pool = [rng.integers(0, 256, (k, L), dtype=np.uint8) for _ in range(8)]
    ename = f"client.traffic-{mode}"
    overrides = {
        "ec_batch_window_ms": window_ms if mode == "batched" else 0.0,
        "ec_batch_max_stripes": max_stripes,
        "ec_batch_max_bytes": max_bytes,
        "trace_enabled": sampling > 0.0,
    }
    if conf_overrides:
        overrides.update(conf_overrides)
    cct = CephContext(ename, overrides=overrides)
    if sampling > 0.0:
        TRACER.clear()  # this run's spans only
    batcher = WriteBatcher(cct, entity=ename)
    batcher.start()
    np.asarray(apply_matrix_jax(mat, pool[0]))  # compile/warm the kernel

    stop_at = [0.0]
    start_gate = threading.Event()
    lats: list[list[float]] = [[] for _ in range(n_clients)]
    sched_lag: list[float] = [0.0] * n_clients  # poisson backlog, seconds

    def client(i: int) -> None:
        # closed mode: each simulated client keeps `qd` writes in
        # flight (the async window a real Objecter's inflight budget
        # allows), completing oldest-first — submit-to-parity latency
        # per op.  poisson mode: submissions follow a seeded
        # exponential-gap schedule instead of the completion clock.
        from collections import deque

        my = lats[i]
        inflight: deque = deque()
        n = 0
        arr_rng = derive_rng(seed, "poisson", i)
        next_due = None  # poisson schedule, monotonic clock

        def submit(x):
            root = (TRACER.begin(sampled_ctx(sampling), "op_submit",
                                 entity=ename, client=i)
                    if sampling > 0.0 else None)
            set_op_trace({"ctx": root.ctx(), "tracked": None}
                         if root is not None else None)
            t0 = time.perf_counter()
            p = batcher.encode_submit(mat, x)
            set_op_trace(None)
            return t0, p, root

        def finish(t0, p, root):
            batcher.encode_wait(p)
            TRACER.end(root)
            my.append(time.perf_counter() - t0)

        start_gate.wait(timeout=30.0)
        if arrivals == "poisson":
            next_due = time.monotonic()
        while time.monotonic() < stop_at[0]:
            if arrivals == "poisson":
                now = time.monotonic()
                if now < next_due:
                    time.sleep(min(next_due - now, 0.05))
                    continue
                sched_lag[i] = max(sched_lag[i], now - next_due)
                next_due += float(arr_rng.exponential(1.0 / max(rate, 1e-6)))
                if len(inflight) >= qd:
                    finish(*inflight.popleft())  # cap outstanding
                x = pool[(i + n) % len(pool)]
                n += 1
                inflight.append(submit(x))
                continue
            while len(inflight) < qd and time.monotonic() < stop_at[0]:
                x = pool[(i + n) % len(pool)]
                n += 1
                inflight.append(submit(x))
            if not inflight:  # clock crossed stop_at before any submit
                break
            finish(*inflight.popleft())
        while inflight:
            finish(*inflight.popleft())

    threads = [
        threading.Thread(target=client, args=(i,), daemon=True,
                         name=f"traffic-{i}")
        for i in range(n_clients)
    ]
    for t in threads:
        t.start()
    # warm the batching pipeline itself before the measured interval
    stop_at[0] = time.monotonic() + warmup + seconds
    start_gate.set()
    time.sleep(warmup)
    for lat in lats:
        lat.clear()
    t_begin = time.monotonic()
    for t in threads:
        t.join(timeout=seconds + 30.0)
    elapsed = time.monotonic() - t_begin
    batcher.stop()

    all_lats = sorted(x for lat in lats for x in lat)
    n_ops = len(all_lats)
    p50, p99 = _pctiles(all_lats)
    stats = batcher.stats()
    out = {
        "mode": mode,
        "arrivals": arrivals,
        "seed": seed,
        "clients": n_clients,
        "write_size": write_size,
        "seconds": round(elapsed, 3),
        "ops": n_ops,
        "gibps": round(n_ops * write_size / max(elapsed, 1e-9) / 2**30, 4),
        "p50_ms": round(p50 * 1e3, 3) if p50 is not None else None,
        "p99_ms": round(p99 * 1e3, 3) if p99 is not None else None,
        "flushes": stats["flushes"],
        "stripes_per_flush": round(stats["stripes"] / stats["flushes"], 2)
        if stats["flushes"] else None,
    }
    out["per_client"], out["fairness_ratio"] = per_client_stats(lats)
    if arrivals == "poisson":
        out["target_rate"] = rate
        out["sched_lag_ms"] = round(max(sched_lag) * 1e3, 3)
    if sampling > 0.0:
        spans = TRACER.spans()
        LAST_SPANS[:] = spans
        out["sampling"] = sampling
        out["traces"] = len({s["trace_id"] for s in spans})
        out["stages"] = stage_breakdown(spans)
        TRACER.enable(False)
        TRACER.clear()
    return out


#: spans of the most recent traced run, for --trace-out export
LAST_SPANS: list = []


def run_cluster_traffic(
    n_clients: int = 2,
    seconds: float = 2.0,
    write_size: int = 4096,
    k: int = 2,
    m: int = 1,
    n_osds: int | None = None,
    sampling: float = 0.0,
    conf_overrides: dict | None = None,
    seed: int = DEFAULT_SEED,
) -> dict:
    """Closed-loop writers against a REAL LocalCluster EC pool — the
    full client -> OSD -> replicas -> ack path, so traced runs produce
    cross-daemon trees (op_submit -> osd_op -> admission/queue/encode/
    subop/commit -> replica_commit) and the per-stage breakdown covers
    all five OP_STAGES.  No qd knob: op_submit is synchronous, so each
    writer holds exactly one op in flight."""
    from ..qa.vstart import LocalCluster

    if n_osds is None:
        n_osds = k + m + 1  # room for every shard plus one spare
    TRACER.enable(False)
    TRACER.clear()
    overrides = {"trace_enabled": sampling > 0.0,
                 "trace_sampling_rate": sampling if sampling > 0.0 else 1.0,
                 # extra knobs (e.g. osd_client_io_accounting on/off for
                 # the PERF.md overhead comparison) ride on top
                 **(conf_overrides or {})}
    lats: list[list[float]] = [[] for _ in range(n_clients)]
    payloads = [bytes([i % 251] * write_size) for i in range(16)]
    stop_at = [0.0]
    start_gate = threading.Event()
    # every writer completes one UNTIMED write before the window opens:
    # a fresh cluster's first op can land mid-peering and eat an
    # EAGAIN-retry backoff — cluster warmup, not steady-state traffic,
    # and charging it to whichever side drew it made the trace-smoke
    # overhead comparison bimodal (observed ~1.3 s elapsed swings)
    warm_gate = threading.Barrier(n_clients + 1)

    with LocalCluster(n_mons=1, n_osds=n_osds,
                      conf_overrides=overrides) as cluster:
        cluster.create_ec_pool("traffic", k=k, m=m, pg_num=8)
        client = cluster.client()
        ios = [client.open_ioctx("traffic") for _ in range(n_clients)]

        def writer(i: int) -> None:
            io = ios[i]
            my = lats[i]
            n = 0
            try:
                io.write_full(f"c{i}-0", payloads[i % 16])  # warm, untimed
            except Exception as e:
                # a transient startup failure must not kill the writer
                # (a dead thread would silently halve the measured
                # client count and skew the trace-smoke comparison);
                # the measured loop retries against a settled cluster
                print(f"# traffic: client {i} warm write failed: {e!r}",
                      file=sys.stderr)
            finally:
                try:
                    warm_gate.wait(timeout=30.0)
                except threading.BrokenBarrierError:
                    pass
            start_gate.wait(timeout=30.0)
            while time.monotonic() < stop_at[0]:
                t0 = time.perf_counter()
                io.write_full(f"c{i}-{n % 16}", payloads[(i + n) % 16])
                my.append(time.perf_counter() - t0)
                n += 1

        threads = [
            threading.Thread(target=writer, args=(i,), daemon=True,
                             name=f"traffic-cluster-{i}")
            for i in range(n_clients)
        ]
        for t in threads:
            t.start()
        try:
            warm_gate.wait(timeout=60.0)
        except threading.BrokenBarrierError:
            pass  # a wedged warm write: measure anyway, bounded below
        stop_at[0] = time.monotonic() + seconds
        t_begin = time.monotonic()
        w_begin = trace_now()  # span clock, for the warm-trace filter
        start_gate.set()
        for t in threads:
            t.join(timeout=seconds + 60.0)
        elapsed = time.monotonic() - t_begin
        spans = TRACER.spans()
    if spans:
        # drop the warm writes' traces wholesale (every span of a trace
        # rooted before the gate): their peering-backoff outliers must
        # not feed the stage p50/p99 breakdown or the trace counts any
        # more than the aggregate window they are already excluded from
        root_t0: dict[str, float] = {}
        for s in spans:
            t = s["trace_id"]
            if t not in root_t0 or s["t0"] < root_t0[t]:
                root_t0[t] = s["t0"]
        keep = {t for t, v in root_t0.items() if v >= w_begin}
        spans = [s for s in spans if s["trace_id"] in keep]
    LAST_SPANS[:] = spans
    all_lats = sorted(x for lat in lats for x in lat)
    n_ops = len(all_lats)
    p50, p99 = _pctiles(all_lats)
    out = {
        "mode": "cluster",
        "seed": seed,
        "clients": n_clients,
        "write_size": write_size,
        "rs": f"{k}+{m}",
        "seconds": round(elapsed, 3),
        "ops": n_ops,
        "ops_per_s": round(n_ops / max(elapsed, 1e-9), 1),
        "gibps": round(n_ops * write_size / max(elapsed, 1e-9) / 2**30, 5),
        "p50_ms": round(p50 * 1e3, 3) if p50 is not None else None,
        "p99_ms": round(p99 * 1e3, 3) if p99 is not None else None,
        "sampling": sampling,
    }
    out["per_client"], out["fairness_ratio"] = per_client_stats(lats)
    if sampling > 0.0:
        out["traces"] = len({s["trace_id"] for s in spans})
        out["connected_traces"] = len(connected_traces(spans))
        out["stages"] = stage_breakdown(spans)
        TRACER.enable(False)
        TRACER.clear()
    return out


def run_bully_traffic(
    n_small: int = 3,
    seconds: float = 4.0,
    bully_streams: int = 6,
    bully_size: int = 1 << 16,
    small_size: int = 4096,
    small_rate: float = 10.0,
    k: int = 2,
    m: int = 1,
    n_osds: int | None = None,
    qos: bool = False,
    settle: float = 0.0,
    conf_overrides: dict | None = None,
    seed: int = DEFAULT_SEED,
) -> dict:
    """The mixed-population fairness scenario (ROADMAP closed-loop QoS;
    docs/qos.md): ONE heavy streamer (``client.bully`` — bully_streams
    closed-loop threads of bully_size writes, offered load limited only
    by service rate) against N small writers (``client.small<i>`` —
    open-loop Poisson arrivals at small_rate ops/s of small_size
    writes, the workload a million light tenants offer).  Runs on a
    REAL LocalCluster (mgr hosted) so the cephqos machinery under test
    is the production path: per-client mClock classes, the batcher
    admission share, and — with ``qos=True`` — the live controller
    retuning both from its own telemetry.

    The headline numbers: pooled victim p50/p99 (the gate that carries
    the "controller improves fairness" claim — victims' tails stop
    paying for the bully), ``victim_satisfaction`` (worst per-victim
    achieved/offered ratio — the STARVATION floor: a wedged victim
    scores << 0.5 while a served one sits near 1.0 modulo Poisson
    arrival noise, so it gates as an absolute floor, never as an
    off-vs-on delta), the raw cephmeter ``fairness_ratio`` (max/min
    ops across every client — kept for observability, but NOT a gate
    here: the bully is closed-loop, so making the cluster FASTER grows
    its op count against the rate-capped victims and pushes max/min the
    wrong way), ``bully_dominance`` (bully ops over mean victim ops),
    and aggregate GiB/s (fairness must not be bought with throughput —
    the gate's 10% budget)."""
    from ..qa.vstart import LocalCluster

    if n_osds is None:
        n_osds = k + m + 1
    overrides = {
        "mgr_report_interval": 0.2,
        "mgr_digest_interval": 0.5,
        # controller cadence fast enough to converge inside the run
        "mgr_qos_interval": 0.3,
        "mgr_qos_active": qos,
        "osd_mclock_client_classes": qos,
        # the measured sweet spot (docs/qos.md): 3 execution slots make
        # the tags bite without serializing the bully's streams
        "osd_mclock_client_slots": 3,
        # off = pre-cephqos admission (one FIFO, no per-client share)
        "ec_batch_client_max_share": 0.25 if qos else 1.0,
        **(conf_overrides or {}),
    }
    lats: list[list[float]] = [[] for _ in range(n_small + 1)]  # [0]=bully
    stop_at = [0.0]
    start_gate = threading.Event()
    warm_gate = threading.Barrier(n_small + bully_streams + 1)

    with LocalCluster(n_mons=1, n_osds=n_osds, with_mgr=True,
                      conf_overrides=overrides) as cluster:
        cluster.create_ec_pool("bully", k=k, m=m, pg_num=8)
        bully_payload = b"B" * bully_size
        small_payloads = [bytes([i % 251] * small_size) for i in range(8)]
        bully_io = cluster.client("client.bully").open_ioctx("bully")
        small_ios = [cluster.client(f"client.small{i}").open_ioctx("bully")
                     for i in range(n_small)]

        def bully(stream: int) -> None:
            my = lats[0]
            n = 0
            try:
                bully_io.write_full(f"b{stream}-w", bully_payload)
            except Exception as e:
                print(f"# bully warm write failed: {e!r}", file=sys.stderr)
            finally:
                try:
                    warm_gate.wait(timeout=60.0)
                except threading.BrokenBarrierError:
                    pass
            start_gate.wait(timeout=60.0)
            while time.monotonic() < stop_at[0]:
                t0 = time.perf_counter()
                try:
                    bully_io.write_full(f"b{stream}-{n % 8}", bully_payload)
                except Exception as e:
                    print(f"# bully write failed: {e!r}", file=sys.stderr)
                    return
                my.append(time.perf_counter() - t0)
                n += 1

        def small(i: int) -> None:
            io = small_ios[i]
            my = lats[i + 1]
            rng = derive_rng(seed, "bully_small", i)
            n = 0
            try:
                io.write_full(f"s{i}-w", small_payloads[0])
            except Exception as e:
                print(f"# small {i} warm write failed: {e!r}",
                      file=sys.stderr)
            finally:
                try:
                    warm_gate.wait(timeout=60.0)
                except threading.BrokenBarrierError:
                    pass
            start_gate.wait(timeout=60.0)
            # open-loop Poisson: submit on the arrival schedule with
            # catch-up (a backlogged victim's waits show up as latency,
            # not as silently reduced offered load)
            next_due = time.monotonic()
            while time.monotonic() < stop_at[0]:
                now = time.monotonic()
                if now < next_due:
                    time.sleep(min(next_due - now, 0.02))
                    continue
                next_due += float(
                    rng.exponential(1.0 / max(small_rate, 1e-6)))
                t0 = time.perf_counter()
                try:
                    io.write_full(f"s{i}-{n % 8}", small_payloads[n % 8])
                except Exception as e:
                    print(f"# small {i} write failed: {e!r}",
                          file=sys.stderr)
                    return
                my.append(time.perf_counter() - t0)
                n += 1

        threads = [threading.Thread(target=bully, args=(s,), daemon=True,
                                    name=f"bully-{s}")
                   for s in range(bully_streams)]
        threads += [threading.Thread(target=small, args=(i,), daemon=True,
                                     name=f"small-{i}")
                    for i in range(n_small)]
        for t in threads:
            t.start()
        try:
            warm_gate.wait(timeout=120.0)
        except threading.BrokenBarrierError:
            pass
        # settle: traffic flows UNMEASURED while the controller observes
        # its first report deltas and pushes (qos runs need ~2 report
        # intervals + a controller tick before classes/window land)
        stop_at[0] = time.monotonic() + settle + seconds
        start_gate.set()
        if settle > 0:
            time.sleep(settle)
        for lat in lats:
            lat.clear()
        t_begin = time.monotonic()
        for t in threads:
            t.join(timeout=settle + seconds + 120.0)
        elapsed = max(time.monotonic() - t_begin, 1e-9)
        qos_status = None
        sched_dump = None
        if cluster.mgr is not None:
            try:
                qos_status = cluster.mgr.module("qos").status()
            except KeyError:
                qos_status = None  # qos module not hosted this run
        if cluster.osds:
            sched_dump = next(iter(
                cluster.osds.values())).scheduler.dump()

    bully_ops = len(lats[0])
    small_lats = sorted(x for lat in lats[1:] for x in lat)
    small_ops = len(small_lats)
    # worst-victim satisfaction: each victim offers small_rate ops/s for
    # the whole measured window; the one the scheduler starves hardest
    # defines fairness (a fully served population scores ~1.0)
    offered_each = small_rate * elapsed
    victim_satisfaction = (round(
        min(len(lat) for lat in lats[1:]) / offered_each, 3)
        if lats[1:] and offered_each > 0 else None)
    vp50, vp99 = _pctiles(small_lats)
    bl = sorted(lats[0])
    bp50, bp99 = _pctiles(bl)
    per_client, fairness = per_client_stats(lats)
    agg_bytes = bully_ops * bully_size + small_ops * small_size
    out = {
        "mode": "bully",
        "seed": seed,
        "qos": qos,
        "seconds": round(elapsed, 3),
        "bully_streams": bully_streams,
        "bully_size": bully_size,
        "n_small": n_small,
        "small_rate": small_rate,
        "aggregate_gibps": round(agg_bytes / elapsed / 2**30, 5),
        "bully_ops": bully_ops,
        "bully_p50_ms": round(bp50 * 1e3, 3) if bp50 is not None else None,
        "bully_p99_ms": round(bp99 * 1e3, 3) if bp99 is not None else None,
        "victim_ops": small_ops,
        "victim_offered": round(n_small * small_rate * elapsed, 1),
        "victim_p50_ms": round(vp50 * 1e3, 3) if vp50 is not None else None,
        "victim_p99_ms": round(vp99 * 1e3, 3) if vp99 is not None else None,
        "victim_satisfaction": victim_satisfaction,
        "bully_dominance": (round(bully_ops / (small_ops / n_small), 3)
                            if small_ops else None),
        "fairness_ratio": fairness,
        "per_client": per_client,
        "qos_status": qos_status,
        "op_queue": sched_dump,
    }
    return out


def trace_smoke(n_clients: int = 2, seconds: float = 2.0,
                trace_out: str | None = None,
                seed: int = DEFAULT_SEED) -> tuple[dict, int]:
    """The ci_gate tracing smoke: an untraced cluster run, then a
    sampling=1.0 run.  Fails (rc 1) when the traced run produced no
    connected trace tree, the per-stage breakdown misses one of the
    five OP_STAGES, or tracing costs more than 10% of the untraced
    run's throughput."""
    # throwaway warmup: the first cluster run pays the process-wide XLA
    # compile, which would otherwise be charged to the untraced side
    # and mask (or invert) the real tracing overhead
    run_cluster_traffic(n_clients, 0.5, sampling=0.0, seed=seed)
    untraced = run_cluster_traffic(n_clients, seconds, sampling=0.0,
                                   seed=seed)
    traced = run_cluster_traffic(n_clients, seconds, sampling=1.0,
                                 seed=seed)
    if trace_out:
        with open(trace_out, "w") as f:
            json.dump(perfetto_export(LAST_SPANS), f)
    overhead = None
    if untraced["ops_per_s"]:
        overhead = round(
            1.0 - traced["ops_per_s"] / untraced["ops_per_s"], 4)
    problems = []
    if not traced.get("connected_traces"):
        problems.append("no connected trace tree (client submit -> "
                        "replica commit)")
    missing = [s for s in OP_STAGES if s not in (traced.get("stages") or {})]
    if missing:
        problems.append(f"stage breakdown missing {missing}")
    if overhead is not None and overhead > 0.10:
        problems.append(f"tracing overhead {overhead:.1%} > 10%")
    out = {
        "seed": seed,
        "untraced": untraced,
        "traced": traced,
        "tracing_overhead": overhead,
        "trace_out": trace_out,
        "problems": problems,
    }
    return out, (1 if problems else 0)


def run_scenario(
    n_clients: int = 32,
    seconds: float = 3.0,
    write_size: int = 4096,
    k: int = 8,
    m: int = 4,
    window_ms: float = 2.0,
    max_stripes: int = 64,
    max_bytes: int = 8 << 20,
    qd: int = 4,
    seed: int = DEFAULT_SEED,
) -> dict:
    """Both modes + the headline ratio, flat keys for bench.py's extra."""
    perop = run_traffic("perop", n_clients, seconds, write_size, k, m,
                        window_ms, max_stripes, max_bytes, qd, seed=seed)
    batched = run_traffic("batched", n_clients, seconds, write_size, k, m,
                          window_ms, max_stripes, max_bytes, qd, seed=seed)
    speedup = (round(batched["gibps"] / perop["gibps"], 2)
               if perop["gibps"] else None)
    return {
        "traffic_seed": seed,
        "traffic_clients": n_clients,
        "traffic_qd": qd,
        "traffic_write_size": write_size,
        "traffic_rs": f"{k}+{m}",
        "traffic_batched_gibps": batched["gibps"],
        "traffic_perop_gibps": perop["gibps"],
        "traffic_batch_speedup": speedup,
        "traffic_batched_p99_ms": batched["p99_ms"],
        "traffic_perop_p99_ms": perop["p99_ms"],
        "traffic_batched_p50_ms": batched["p50_ms"],
        "traffic_perop_p50_ms": perop["p50_ms"],
        "traffic_stripes_per_flush": batched["stripes_per_flush"],
        "traffic_batched_ops": batched["ops"],
        "traffic_perop_ops": perop["ops"],
        "traffic_batched_fairness_ratio": batched["fairness_ratio"],
        "traffic_perop_fairness_ratio": perop["fairness_ratio"],
    }


def run_read_traffic(
    mode: str,
    n_clients: int = 32,
    seconds: float = 3.0,
    read_size: int = 4096,
    k: int = 4,
    m: int = 2,
    window_ms: float = 2.0,
    max_ops: int = 64,
    max_bytes: int = 8 << 20,
    qd: int = 4,
    warmup: float = 0.25,
    lose: int = 1,
    seed: int = DEFAULT_SEED,
) -> dict:
    """The READ-side twin of `run_traffic`: N closed-loop degraded
    readers against the production ``ReadBatcher`` decode seam
    (osd/read_batcher.py) — each op is one stripe's survivor stack
    multiplied through the codec's cached decode matrix, i.e. exactly
    the work a degraded GET costs the primary after its chunk gather.
    ``batched`` coalesces every concurrent op's stack into one pooled
    ``apply_matrix_jax`` dispatch per flush; ``perop`` runs the same
    submits with coalescing off (osd_read_batch_window_ms=0), today's
    one-dispatch-per-read path.  The ratio is the read_smoke gate."""
    from ..common.context import CephContext
    from ..ec.registry import ErasureCodePluginRegistry
    from ..ops.bitplane import apply_matrix_jax
    from ..osd.read_batcher import ReadBatcher

    assert mode in ("batched", "perop"), mode
    codec = ErasureCodePluginRegistry.instance().factory(
        {"plugin": "jax", "k": str(k), "m": str(m)})
    L = codec.get_chunk_size(read_size)
    rng = derive_rng(seed, "read_stacks")
    rows = tuple(r for r in range(k + m) if r != lose)[:k]
    dm, dm_key = codec._jax_codec._decode_entry(rows)
    # a pool of distinct degraded stripes (survivor stacks) per client
    stacks = []
    for _ in range(8):
        x = rng.integers(0, 256, (k, L), dtype=np.uint8)
        parity = np.asarray(codec.encode_chunks(x), np.uint8)
        stacks.append(np.ascontiguousarray(
            np.vstack([x, parity])[list(rows)]))
    ename = f"client.readtraffic-{mode}"
    cct = CephContext(ename, overrides={
        "osd_read_batch_window_ms": window_ms if mode == "batched" else 0.0,
        "osd_read_batch_max_ops": max_ops,
        "osd_read_batch_max_bytes": max_bytes,
    })
    batcher = ReadBatcher(cct, io=None, entity=ename)
    batcher.start()
    np.asarray(apply_matrix_jax(dm, stacks[0]))  # compile/warm the kernel

    stop_at = [0.0]
    start_gate = threading.Event()
    lats: list[list[float]] = [[] for _ in range(n_clients)]

    def client(i: int) -> None:
        from collections import deque

        my = lats[i]
        inflight: deque = deque()
        n = 0
        start_gate.wait(timeout=30.0)
        while time.monotonic() < stop_at[0]:
            while len(inflight) < qd and time.monotonic() < stop_at[0]:
                x = stacks[(i + n) % len(stacks)]
                n += 1
                inflight.append(
                    (time.perf_counter(),
                     batcher.decode_submit(dm, x, dm_key)))
            if not inflight:
                break
            t0, p = inflight.popleft()
            batcher.decode_wait(p)
            my.append(time.perf_counter() - t0)
        while inflight:
            t0, p = inflight.popleft()
            batcher.decode_wait(p)
            my.append(time.perf_counter() - t0)

    threads = [
        threading.Thread(target=client, args=(i,), daemon=True,
                         name=f"readtraffic-{i}")
        for i in range(n_clients)
    ]
    for t in threads:
        t.start()
    stop_at[0] = time.monotonic() + warmup + seconds
    start_gate.set()
    time.sleep(warmup)
    for lat in lats:
        lat.clear()
    t_begin = time.monotonic()
    for t in threads:
        t.join(timeout=seconds + 30.0)
    elapsed = time.monotonic() - t_begin
    batcher.stop()

    all_lats = sorted(x for lat in lats for x in lat)
    n_ops = len(all_lats)
    p50, p99 = _pctiles(all_lats)
    stats = batcher.stats()
    op_bytes = k * L  # decoded data bytes delivered per read
    out = {
        "mode": mode,
        "seed": seed,
        "clients": n_clients,
        "read_size": read_size,
        "rs": f"{k}+{m}",
        "seconds": round(elapsed, 3),
        "ops": n_ops,
        "gibps": round(n_ops * op_bytes / max(elapsed, 1e-9) / 2**30, 4),
        "p50_ms": round(p50 * 1e3, 3) if p50 is not None else None,
        "p99_ms": round(p99 * 1e3, 3) if p99 is not None else None,
        "flushes": stats["flushes"],
        "ops_per_flush": round(stats["ops"] / stats["flushes"], 2)
        if stats["flushes"] else None,
        "decode_groups": stats["decode_groups"],
    }
    out["per_client"], out["fairness_ratio"] = per_client_stats(lats)
    return out


def run_read_scenario(
    n_clients: int = 32,
    seconds: float = 3.0,
    read_size: int = 4096,
    k: int = 4,
    m: int = 2,
    window_ms: float = 2.0,
    max_ops: int = 64,
    max_bytes: int = 8 << 20,
    qd: int = 4,
    seed: int = DEFAULT_SEED,
) -> dict:
    """Both read modes + the headline ratio, flat keys (the read-side
    mirror of `run_scenario`; read_smoke's >=3x gate reads these)."""
    perop = run_read_traffic("perop", n_clients, seconds, read_size, k, m,
                             window_ms, max_ops, max_bytes, qd, seed=seed)
    batched = run_read_traffic("batched", n_clients, seconds, read_size,
                               k, m, window_ms, max_ops, max_bytes, qd,
                               seed=seed)
    speedup = (round(batched["gibps"] / perop["gibps"], 2)
               if perop["gibps"] else None)
    return {
        "read_seed": seed,
        "read_clients": n_clients,
        "read_qd": qd,
        "read_size": read_size,
        "read_rs": f"{k}+{m}",
        "read_batched_gibps": batched["gibps"],
        "read_perop_gibps": perop["gibps"],
        "read_batch_speedup": speedup,
        "read_batched_p99_ms": batched["p99_ms"],
        "read_perop_p99_ms": perop["p99_ms"],
        "read_batched_p50_ms": batched["p50_ms"],
        "read_perop_p50_ms": perop["p50_ms"],
        "read_ops_per_flush": batched["ops_per_flush"],
        "read_batched_ops": batched["ops"],
        "read_perop_ops": perop["ops"],
    }


def run_cluster_read_traffic(
    n_clients: int = 4,
    seconds: float = 2.0,
    read_size: int = 4096,
    k: int = 2,
    m: int = 1,
    n_osds: int | None = None,
    scenario: str = "get",
    degraded: bool = False,
    mixed: bool = False,
    working_set: int = 8,
    conf_overrides: dict | None = None,
    seed: int = DEFAULT_SEED,
) -> dict:
    """Closed-loop READERS against a real LocalCluster EC pool — the
    full client -> primary -> gather [-> decode] -> reply path.

    ``scenario``: "get" (GET-heavy: every client hammers one shared hot
    working set — the repeat-read workload the hot-object cache and the
    batcher's fan-out coalescing serve) or "boot" (boot storm: each
    client cold-sweeps its OWN object set in order, the RBD
    many-images-at-once pattern — almost no re-reads, so it measures
    pure gather coalescing).  ``mixed`` interleaves one write_full per
    four ops (cache-invalidation pressure: the cache must never serve
    the pre-write bytes).  ``degraded`` kills one OSD after the preload
    (n_osds defaults to k+m so EVERY read must decode) — the p99 here
    is the read_smoke degraded bar.  Every read is verified against the
    expected payload; ``mismatches`` must stay 0."""
    from ..qa.vstart import LocalCluster

    assert scenario in ("get", "boot"), scenario
    if n_osds is None:
        n_osds = k + m if degraded else k + m + 1
    overrides = {"osd_subop_reply_timeout": 1.5,
                 **(conf_overrides or {})}
    lats: list[list[float]] = [[] for _ in range(n_clients)]
    mismatches = [0] * n_clients
    write_ops = [0] * n_clients
    stop_at = [0.0]
    start_gate = threading.Event()
    warm_gate = threading.Barrier(n_clients + 1)

    with LocalCluster(n_mons=1, n_osds=n_osds,
                      conf_overrides=overrides) as cluster:
        cluster.create_ec_pool("readtraffic", k=k, m=m, pg_num=8)
        client = cluster.client()
        ios = [client.open_ioctx("readtraffic") for _ in range(n_clients)]
        payloads: dict[str, bytes] = {}
        if scenario == "get":
            oids = [f"hot-{j}" for j in range(working_set)]
            for j, oid in enumerate(oids):
                payloads[oid] = bytes([j % 251]) * read_size
                ios[0].write_full(oid, payloads[oid])
            per_client_oids = [oids] * n_clients
        else:
            per_client_oids = []
            for i in range(n_clients):
                mine = [f"img{i}-{j}" for j in range(working_set)]
                for j, oid in enumerate(mine):
                    payloads[oid] = bytes([(i * 17 + j) % 251]) * read_size
                    ios[i].write_full(oid, payloads[oid])
                per_client_oids.append(mine)
        if degraded:
            # drop one OSD and push the map change: with n_osds == k+m
            # there is no spare to backfill onto, so every PG keeps a
            # missing shard and every read takes the decode path (the
            # primaries the victim held move to survivors)
            victim = sorted(cluster.osds)[-1]
            cluster.kill_osd(victim)
            cluster.mark_osd_down_out(victim)

        def reader(i: int) -> None:
            io = ios[i]
            mine = per_client_oids[i]
            my = lats[i]
            n = 0
            try:
                io.read(mine[i % len(mine)])  # warm, untimed
            except Exception as e:
                print(f"# read traffic: client {i} warm read failed: "
                      f"{e!r}", file=sys.stderr)
            finally:
                try:
                    warm_gate.wait(timeout=30.0)
                except threading.BrokenBarrierError:
                    pass
            start_gate.wait(timeout=30.0)
            while time.monotonic() < stop_at[0]:
                oid = mine[(i + n) % len(mine)]
                n += 1
                if mixed and n % 4 == 0:
                    io.write_full(oid, payloads[oid])
                    write_ops[i] += 1
                    continue
                t0 = time.perf_counter()
                got = io.read(oid)
                my.append(time.perf_counter() - t0)
                if got != payloads[oid]:
                    mismatches[i] += 1

        threads = [
            threading.Thread(target=reader, args=(i,), daemon=True,
                             name=f"readtraffic-cluster-{i}")
            for i in range(n_clients)
        ]
        for t in threads:
            t.start()
        try:
            warm_gate.wait(timeout=60.0)
        except threading.BrokenBarrierError:
            pass
        stop_at[0] = time.monotonic() + seconds
        t_begin = time.monotonic()
        start_gate.set()
        for t in threads:
            t.join(timeout=seconds + 60.0)
        elapsed = time.monotonic() - t_begin
        rb = {"flushes": 0, "ops": 0, "inline": 0, "fanouts": 0}
        rc_hits = rc_misses = rc_inserts = 0
        for o in cluster.osds.values():
            s = o.read_batcher.stats()
            for key in rb:
                rb[key] += s[key]
            cs = o.read_cache.stats()
            rc_hits += cs["hits"]
            rc_misses += cs["misses"]
            rc_inserts += cs["inserts"]

    all_lats = sorted(x for lat in lats for x in lat)
    n_ops = len(all_lats)
    p50, p99 = _pctiles(all_lats)
    out = {
        "mode": "cluster-read",
        "seed": seed,
        "scenario": scenario,
        "degraded": degraded,
        "mixed": mixed,
        "clients": n_clients,
        "read_size": read_size,
        "rs": f"{k}+{m}",
        "seconds": round(elapsed, 3),
        "ops": n_ops,
        "ops_per_s": round(n_ops / max(elapsed, 1e-9), 1),
        "gibps": round(n_ops * read_size / max(elapsed, 1e-9) / 2**30, 5),
        "p50_ms": round(p50 * 1e3, 3) if p50 is not None else None,
        "p99_ms": round(p99 * 1e3, 3) if p99 is not None else None,
        "mismatches": sum(mismatches),
        "write_ops": sum(write_ops),
        "read_batcher": rb,
        "read_cache": {"hits": rc_hits, "misses": rc_misses,
                       "inserts": rc_inserts},
    }
    out["per_client"], out["fairness_ratio"] = per_client_stats(lats)
    return out


# --- multi-tenant workload vocabulary (cephstorm) ----------------------
#
# Pure, seeded building blocks the storm planner (qa/storm/planner.py)
# composes into thousand-OSD client traffic.  Three tenant kinds model
# the three Ceph front doors: "s3" (RGW request mixes — GET-heavy over
# bucket/key namespaces, diurnal offered load), "fs" (CephFS metadata
# storms — tiny hot writes against a shallow directory tree, bursty),
# "rbd" (block images under snapshot churn — half-and-half rewrites of
# a fixed block population, wave-shaped load).  Everything here is a
# function of (kind, seed-derived rng, position-in-run): no clocks, no
# globals, so identical seeds yield identical op streams.

TENANT_KINDS = ("s3", "fs", "rbd")

#: op mix per tenant kind: relative write/read weights + payload size.
TENANT_MIX = {
    "s3": {"write": 4, "read": 6, "size": 8192},
    "fs": {"write": 7, "read": 3, "size": 512},
    "rbd": {"write": 5, "read": 5, "size": 4096},
}


def tenant_objects(kind: str, tenant: str, n_objects: int) -> list[str]:
    """The tenant's deterministic object-name population, styled after
    its real namespace (S3 bucket/keys, FS paths, RBD image blocks)."""
    if kind == "s3":
        return [f"{tenant}/bkt{j % 8}/obj{j:05d}" for j in range(n_objects)]
    if kind == "fs":
        return [f"{tenant}/dir{j % 16}/f{j:04d}.dat"
                for j in range(n_objects)]
    if kind == "rbd":
        return [f"{tenant}/img{j % 4}.block{j:06d}"
                for j in range(n_objects)]
    raise ValueError(f"unknown tenant kind {kind!r}")


def arrival_intensity(kind: str, t_frac: float) -> float:
    """Relative offered-load multiplier at position ``t_frac`` in [0,1)
    of the run: a diurnal sine for s3, 1-in-4 duty-cycle bursts for fs
    metadata storms, and alternating snapshot-churn waves for rbd.
    Mean is ~O(1) for every kind so mixes stay comparable."""
    t = t_frac % 1.0
    if kind == "s3":
        return 0.5 + math.sin(math.pi * t) ** 2  # one day-night cycle
    if kind == "fs":
        return 2.5 if (t * 8.0) % 1.0 < 0.25 else 0.5  # 8 bursts
    if kind == "rbd":
        return 1.5 if (t * 4.0) % 1.0 < 0.5 else 0.5  # 4 snapshot waves
    raise ValueError(f"unknown tenant kind {kind!r}")


def tenant_next_op(kind: str, rng, objects: list[str],
                   t_frac: float = 0.0,
                   hot_frac: float = 0.125) -> tuple[str, str, int] | None:
    """Draw one client op for a tenant: ``(op, oid, size)`` with op in
    {"write", "read"}, or None when the tenant's bursty/diurnal shape
    thins this slot out (the planner simply skips the event).  Object
    popularity is hot-skewed: ~70% of draws land on the leading
    ``hot_frac`` of the population (the hot-object pattern the read
    cache and the QoS classes must survive), the rest uniform."""
    peak = 2.5  # max of every arrival_intensity shape
    if rng.random() * peak >= arrival_intensity(kind, t_frac):
        return None
    mix = TENANT_MIX[kind]
    w, r = mix["write"], mix["read"]
    op = "write" if rng.random() * (w + r) < w else "read"
    n_hot = max(1, int(len(objects) * hot_frac))
    if rng.random() < 0.7:
        oid = objects[int(rng.integers(n_hot))]
    else:
        oid = objects[int(rng.integers(len(objects)))]
    return op, oid, mix["size"]


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="sustained small-write traffic: batched vs per-op "
                    "encode (aggregate GiB/s + p99 latency)")
    ap.add_argument("--clients", type=int, default=32)
    ap.add_argument("--seconds", type=float, default=3.0)
    ap.add_argument("--write-size", type=int, default=4096)
    ap.add_argument("-k", type=int, default=None,
                    help="data chunks (default 8; 2 in --cluster mode)")
    ap.add_argument("-m", type=int, default=None,
                    help="parity chunks (default 4; 1 in --cluster mode)")
    ap.add_argument("--window-ms", type=float, default=2.0)
    ap.add_argument("--max-stripes", type=int, default=64)
    ap.add_argument("--max-bytes", type=int, default=8 << 20)
    ap.add_argument("--qd", type=int, default=4,
                    help="per-client async window (writes in flight)")
    ap.add_argument("--arrivals", choices=("closed", "poisson"),
                    default="closed",
                    help="closed-loop writers (default) or open-loop "
                    "Poisson arrivals at --rate ops/s per client")
    ap.add_argument("--rate", type=float, default=None,
                    help="per-client arrival rate, ops/s (default 100 "
                    "for --arrivals poisson against the bare batcher; "
                    "10 for --bully's small writers — a real "
                    "LocalCluster serves ~2 orders of magnitude less "
                    "than the in-process batcher, and an open-loop "
                    "rate past its capacity measures only the backlog)")
    ap.add_argument("--bully", action="store_true",
                    help="mixed-population fairness scenario on a real "
                    "LocalCluster: 1 heavy streamer vs N small Poisson "
                    "writers (--clients = small-writer count); "
                    "--qos arms the closed-loop controller")
    ap.add_argument("--qos", action="store_true",
                    help="with --bully: per-client mClock classes + "
                    "batcher share + live QoS controller")
    ap.add_argument("--reads", action="store_true",
                    help="READ-side traffic: batched vs per-op degraded "
                    "decode through the ReadBatcher (with --cluster: "
                    "real GET traffic against a LocalCluster pool)")
    ap.add_argument("--scenario", choices=("get", "boot"), default="get",
                    help="with --reads --cluster: GET-heavy shared "
                    "working set (default) or per-client boot storm")
    ap.add_argument("--degraded", action="store_true",
                    help="with --reads --cluster: kill one OSD after "
                    "preload so every read decodes (no spare to "
                    "backfill onto)")
    ap.add_argument("--mixed", action="store_true",
                    help="with --reads --cluster: interleave one "
                    "write_full per four reads (cache-invalidation "
                    "pressure); implies --cluster")
    ap.add_argument("--sampling", type=float, default=0.0,
                    help="cephtrace head-sampling rate (0 = tracing "
                    "off); >0 adds a per-stage p50/p99 breakdown")
    ap.add_argument("--cluster", action="store_true",
                    help="drive a real LocalCluster EC pool instead of "
                    "the bare write batcher (cross-daemon traces)")
    ap.add_argument("--trace-out", metavar="FILE",
                    help="write the traced run's Perfetto/Chrome-trace "
                    "JSON here (open in ui.perfetto.dev)")
    ap.add_argument("--trace-smoke", action="store_true",
                    help="CI gate: untraced vs sampling=1.0 cluster "
                    "runs; fail on a disconnected trace tree, a "
                    "missing stage, or >10%% tracing overhead")
    ap.add_argument("--json", action="store_true",
                    help="emit one JSON dict on stdout")
    ap.add_argument("--cpu", action="store_true",
                    help="pin jax to the CPU backend (via jax.config — "
                    "the JAX_PLATFORMS env var is ignored by this box's "
                    "sitecustomize)")
    ap.add_argument("--smoke", action="store_true",
                    help="CI gate: exit 1 when the batched/per-op "
                    "throughput ratio drops below 1.0")
    ap.add_argument("--seed", type=int, default=DEFAULT_SEED,
                    help="derives every random stream in the run "
                    "(stripe pools, Poisson arrivals, bully victims); "
                    "recorded in the JSON so any artifact can be "
                    f"replayed bit-identically (default {DEFAULT_SEED})")
    args = ap.parse_args(argv)
    if args.cpu or os.environ.get("CEPH_TPU_BENCH_FORCE_CPU"):
        import jax

        jax.config.update("jax_platforms", "cpu")
    # cluster-backed modes drive one daemon per shard: default to a
    # geometry a smoke-sized cluster can host (RS(8,4) would mean a
    # 13-daemon in-process cluster — measured pathological)
    if args.k is None:
        args.k = 2 if (args.cluster or args.bully) else 8
    if args.m is None:
        args.m = 1 if (args.cluster or args.bully) else 4
    if args.reads:
        if args.cluster or args.mixed or args.degraded:
            res = run_cluster_read_traffic(
                max(1, args.clients), args.seconds, args.write_size,
                args.k, args.m, scenario=args.scenario,
                degraded=args.degraded, mixed=args.mixed,
                seed=args.seed)
        else:
            res = run_read_scenario(args.clients, args.seconds,
                                    args.write_size, qd=args.qd,
                                    window_ms=args.window_ms,
                                    max_bytes=args.max_bytes,
                                    seed=args.seed)
        if args.json:
            print(json.dumps(res))
        else:
            for key in sorted(res):
                print(f"{key}: {res[key]}")
        if args.smoke:
            ratio = res.get("read_batch_speedup")
            if ratio is None or ratio < 1.0:
                print(f"# read traffic smoke FAILED: batched/per-op "
                      f"ratio {ratio} < 1.0", file=sys.stderr)
                return 1
            print(f"# read traffic smoke OK: batched/per-op ratio "
                  f"{ratio}", file=sys.stderr)
        return 0
    if args.trace_smoke:
        res, rc = trace_smoke(args.clients, args.seconds,
                              trace_out=args.trace_out, seed=args.seed)
        if args.json:
            print(json.dumps(res))
        else:
            for key in sorted(res):
                print(f"{key}: {res[key]}")
        for p in res["problems"]:
            print(f"# trace smoke FAILED: {p}", file=sys.stderr)
        if rc == 0:
            print(f"# trace smoke OK: {res['traced']['connected_traces']} "
                  f"connected traces, overhead {res['tracing_overhead']}",
                  file=sys.stderr)
        return rc
    if args.bully:
        res = run_bully_traffic(n_small=max(1, args.clients),
                                seconds=args.seconds,
                                small_size=args.write_size,
                                small_rate=(args.rate if args.rate
                                            is not None else 10.0),
                                k=args.k, m=args.m, qos=args.qos,
                                settle=1.5 if args.qos else 0.0,
                                seed=args.seed)
    elif args.cluster:
        res = run_cluster_traffic(args.clients, args.seconds,
                                  args.write_size, args.k, args.m,
                                  sampling=args.sampling, seed=args.seed)
    elif args.sampling > 0.0:
        # batcher-only traced run: batched mode with stage breakdown
        # (the 1%-sampling overhead measurement drives this directly)
        res = run_traffic("batched", args.clients, args.seconds,
                          args.write_size, args.k, args.m, args.window_ms,
                          args.max_stripes, args.max_bytes, args.qd,
                          sampling=args.sampling, seed=args.seed)
    elif args.arrivals == "poisson":
        # open-loop single-mode run: offered load independent of
        # service rate (the queueing-exposing workload)
        res = run_traffic("batched", args.clients, args.seconds,
                          args.write_size, args.k, args.m, args.window_ms,
                          args.max_stripes, args.max_bytes, args.qd,
                          arrivals="poisson",
                          rate=(args.rate if args.rate is not None
                                else 100.0),
                          seed=args.seed)
    else:
        res = run_scenario(args.clients, args.seconds, args.write_size,
                           args.k, args.m, args.window_ms, args.max_stripes,
                           args.max_bytes, args.qd, seed=args.seed)
    if args.trace_out and LAST_SPANS:
        with open(args.trace_out, "w") as f:
            json.dump(perfetto_export(LAST_SPANS), f)
    if args.json:
        print(json.dumps(res))
    else:
        for key in sorted(res):
            print(f"{key}: {res[key]}")
    if args.smoke:
        ratio = res.get("traffic_batch_speedup")
        if ratio is None or ratio < 1.0:
            print(f"# traffic smoke FAILED: batched/per-op ratio "
                  f"{ratio} < 1.0", file=sys.stderr)
            return 1
        print(f"# traffic smoke OK: batched/per-op ratio {ratio}",
              file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
