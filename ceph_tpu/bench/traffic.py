"""Sustained-traffic benchmark: N simulated clients x small EC writes,
batched (write batcher) vs per-op (inline codec), aggregate GiB/s and
p99 latency — the ROADMAP "millions of users" metric.

arXiv:1709.05365 (online EC on large-scale SSD arrays) shows system
throughput under sustained small-write traffic is dominated by the
queueing/batching structure in front of the codec, not the codec
itself; this scenario measures exactly that layer.  Each simulated
client is a closed-loop writer: prepare a 4 KiB stripe, submit to the
encode stage, wait for parity, repeat.  ``batched`` mode drives the
production ``WriteBatcher`` (osd/write_batcher.py) — the identical code
path an OSD primary takes; ``perop`` mode submits through the same
entry with coalescing off (ec_batch_window_ms=0), i.e. today's
one-dispatch-per-stripe path.

Usage (bench.py runs this as its "traffic" phase; qa/ci_gate.sh runs
the tiny smoke configuration):

    python -m ceph_tpu.bench.traffic --clients 32 --seconds 3 --json
    python -m ceph_tpu.bench.traffic --clients 2 --seconds 2 --smoke
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

import numpy as np


def _chunk_len(write_size: int, k: int, align: int = 64) -> int:
    """ErasureCode.get_chunk_size's shape: ceil(size/k), 64-aligned."""
    padded = -(-write_size // k)
    return -(-padded // align) * align


def run_traffic(
    mode: str,
    n_clients: int = 32,
    seconds: float = 3.0,
    write_size: int = 4096,
    k: int = 8,
    m: int = 4,
    window_ms: float = 2.0,
    max_stripes: int = 64,
    max_bytes: int = 8 << 20,
    qd: int = 4,
    warmup: float = 0.25,
) -> dict:
    """One mode's closed-loop run; returns ops/GiB-per-s/latency stats."""
    from ..common.context import CephContext
    from ..gf.matrix import cauchy_good_coding_matrix
    from ..ops.bitplane import apply_matrix_jax
    from ..osd.write_batcher import WriteBatcher

    assert mode in ("batched", "perop"), mode
    mat = np.ascontiguousarray(cauchy_good_coding_matrix(k, m), np.uint8)
    L = _chunk_len(write_size, k)
    rng = np.random.default_rng(1234)
    # a small pool of distinct pre-built stripes per client keeps the
    # generator out of the timed loop while avoiding constant-input
    # caching artifacts
    pool = [rng.integers(0, 256, (k, L), dtype=np.uint8) for _ in range(8)]
    cct = CephContext(
        f"client.traffic-{mode}",
        overrides={
            "ec_batch_window_ms": window_ms if mode == "batched" else 0.0,
            "ec_batch_max_stripes": max_stripes,
            "ec_batch_max_bytes": max_bytes,
        },
    )
    batcher = WriteBatcher(cct, entity=f"client.traffic-{mode}")
    batcher.start()
    np.asarray(apply_matrix_jax(mat, pool[0]))  # compile/warm the kernel

    stop_at = [0.0]
    start_gate = threading.Event()
    lats: list[list[float]] = [[] for _ in range(n_clients)]

    def client(i: int) -> None:
        # each simulated client keeps `qd` writes in flight (the async
        # window a real Objecter's inflight budget allows), completing
        # oldest-first — submit-to-parity latency per op
        from collections import deque

        my = lats[i]
        inflight: deque = deque()
        n = 0
        start_gate.wait(timeout=30.0)
        while time.monotonic() < stop_at[0]:
            while len(inflight) < qd and time.monotonic() < stop_at[0]:
                x = pool[(i + n) % len(pool)]
                n += 1
                inflight.append(
                    (time.perf_counter(), batcher.encode_submit(mat, x))
                )
            if not inflight:  # clock crossed stop_at before any submit
                break
            t0, p = inflight.popleft()
            batcher.encode_wait(p)
            my.append(time.perf_counter() - t0)
        while inflight:
            t0, p = inflight.popleft()
            batcher.encode_wait(p)
            my.append(time.perf_counter() - t0)

    threads = [
        threading.Thread(target=client, args=(i,), daemon=True,
                         name=f"traffic-{i}")
        for i in range(n_clients)
    ]
    for t in threads:
        t.start()
    # warm the batching pipeline itself before the measured interval
    stop_at[0] = time.monotonic() + warmup + seconds
    start_gate.set()
    time.sleep(warmup)
    for lat in lats:
        lat.clear()
    t_begin = time.monotonic()
    for t in threads:
        t.join(timeout=seconds + 30.0)
    elapsed = time.monotonic() - t_begin
    batcher.stop()

    all_lats = sorted(x for lat in lats for x in lat)
    n_ops = len(all_lats)
    stats = batcher.stats()
    out = {
        "mode": mode,
        "clients": n_clients,
        "write_size": write_size,
        "seconds": round(elapsed, 3),
        "ops": n_ops,
        "gibps": round(n_ops * write_size / max(elapsed, 1e-9) / 2**30, 4),
        "p50_ms": round(all_lats[n_ops // 2] * 1e3, 3) if n_ops else None,
        "p99_ms": round(all_lats[min(n_ops - 1, int(n_ops * 0.99))] * 1e3, 3)
        if n_ops else None,
        "flushes": stats["flushes"],
        "stripes_per_flush": round(stats["stripes"] / stats["flushes"], 2)
        if stats["flushes"] else None,
    }
    return out


def run_scenario(
    n_clients: int = 32,
    seconds: float = 3.0,
    write_size: int = 4096,
    k: int = 8,
    m: int = 4,
    window_ms: float = 2.0,
    max_stripes: int = 64,
    max_bytes: int = 8 << 20,
    qd: int = 4,
) -> dict:
    """Both modes + the headline ratio, flat keys for bench.py's extra."""
    perop = run_traffic("perop", n_clients, seconds, write_size, k, m,
                        window_ms, max_stripes, max_bytes, qd)
    batched = run_traffic("batched", n_clients, seconds, write_size, k, m,
                          window_ms, max_stripes, max_bytes, qd)
    speedup = (round(batched["gibps"] / perop["gibps"], 2)
               if perop["gibps"] else None)
    return {
        "traffic_clients": n_clients,
        "traffic_qd": qd,
        "traffic_write_size": write_size,
        "traffic_rs": f"{k}+{m}",
        "traffic_batched_gibps": batched["gibps"],
        "traffic_perop_gibps": perop["gibps"],
        "traffic_batch_speedup": speedup,
        "traffic_batched_p99_ms": batched["p99_ms"],
        "traffic_perop_p99_ms": perop["p99_ms"],
        "traffic_batched_p50_ms": batched["p50_ms"],
        "traffic_perop_p50_ms": perop["p50_ms"],
        "traffic_stripes_per_flush": batched["stripes_per_flush"],
        "traffic_batched_ops": batched["ops"],
        "traffic_perop_ops": perop["ops"],
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="sustained small-write traffic: batched vs per-op "
                    "encode (aggregate GiB/s + p99 latency)")
    ap.add_argument("--clients", type=int, default=32)
    ap.add_argument("--seconds", type=float, default=3.0)
    ap.add_argument("--write-size", type=int, default=4096)
    ap.add_argument("-k", type=int, default=8)
    ap.add_argument("-m", type=int, default=4)
    ap.add_argument("--window-ms", type=float, default=2.0)
    ap.add_argument("--max-stripes", type=int, default=64)
    ap.add_argument("--max-bytes", type=int, default=8 << 20)
    ap.add_argument("--qd", type=int, default=4,
                    help="per-client async window (writes in flight)")
    ap.add_argument("--json", action="store_true",
                    help="emit one JSON dict on stdout")
    ap.add_argument("--cpu", action="store_true",
                    help="pin jax to the CPU backend (via jax.config — "
                    "the JAX_PLATFORMS env var is ignored by this box's "
                    "sitecustomize)")
    ap.add_argument("--smoke", action="store_true",
                    help="CI gate: exit 1 when the batched/per-op "
                    "throughput ratio drops below 1.0")
    args = ap.parse_args(argv)
    if args.cpu or os.environ.get("CEPH_TPU_BENCH_FORCE_CPU"):
        import jax

        jax.config.update("jax_platforms", "cpu")
    res = run_scenario(args.clients, args.seconds, args.write_size,
                       args.k, args.m, args.window_ms, args.max_stripes,
                       args.max_bytes, args.qd)
    if args.json:
        print(json.dumps(res))
    else:
        for key in sorted(res):
            print(f"{key}: {res[key]}")
    if args.smoke:
        ratio = res.get("traffic_batch_speedup")
        if ratio is None or ratio < 1.0:
            print(f"# traffic smoke FAILED: batched/per-op ratio "
                  f"{ratio} < 1.0", file=sys.stderr)
            return 1
        print(f"# traffic smoke OK: batched/per-op ratio {ratio}",
              file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
