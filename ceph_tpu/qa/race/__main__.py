"""CLI: python -m ceph_tpu.qa.race --seed N --scenario thrash|mon_churn|ec_io

Exit-code contract (mirrors cephlint's, and what qa/ci_gate.sh branches
on):

    0   clean: no active findings (stale race-baseline entries only warn
        — a race is schedule-dependent, one seed not reproducing it is
        not proof the debt was paid)
    1   active findings
    2   usage errors, unreadable baseline, scenario crash

The schedule plan and the scenario workload both derive purely from
--seed; --format=json includes the plan and the trace digest so a
finding's schedule can be re-run bit-for-bit.
"""
from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

from ..analyzer.core import BaselineError, format_baseline
from . import report as race_report
from .scenarios import DEFAULT_EVENTS, SCENARIOS, run_scenario


def main(argv: list[str] | None = None) -> int:
    try:
        return _main(argv)
    except BrokenPipeError:
        # `cephrace --list-targets | head` closing the pipe is not an
        # error — and the console-script entry point calls main()
        # directly, so the guard must live here, not under __main__.
        # Re-point stdout at devnull so the interpreter's exit-time
        # flush doesn't raise the same error again (CPython would exit
        # 120 on an unraisable flush failure).
        import os

        try:
            os.dup2(os.open(os.devnull, os.O_WRONLY), sys.stdout.fileno())
        except OSError:
            pass
        return 0


def _main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m ceph_tpu.qa.race",
        description="cephrace: dynamic data-race (CR1), deadlock (CR2) "
                    "and lost-wakeup (CR3) detection over a seeded "
                    "scenario, with PCT-style schedule exploration",
        epilog="exit status: 0 clean; 1 findings; 2 usage/scenario "
               "errors.  The same --seed replays the same schedule plan "
               "and workload.")
    ap.add_argument("--seed", type=int, default=1)
    ap.add_argument("--scenario", choices=sorted(SCENARIOS),
                    default="thrash")
    ap.add_argument("--events", type=int, default=None,
                    help="scenario length (default: per-scenario, e.g. "
                         f"{DEFAULT_EVENTS})")
    ap.add_argument("--sched", choices=("perturb", "serialize", "none"),
                    default="perturb",
                    help="schedule exploration mode (serialize is for "
                         "fixture-sized workloads; cluster scenarios "
                         "want perturb)")
    ap.add_argument("--depth", type=int, default=3,
                    help="PCT preemption depth d (d-1 priority change "
                         "points)")
    ap.add_argument("--format", choices=("text", "json", "sarif"),
                    default="text")
    ap.add_argument("--baseline", default=None, metavar="FILE",
                    help="baseline file (default: qa/race/baseline.toml)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="report baselined findings too")
    ap.add_argument("--write-baseline", default=None, metavar="FILE",
                    help="write active findings as a pinned baseline "
                         "(edit each reason before committing!)")
    ap.add_argument("--list-targets", action="store_true",
                    help="print the statically-discovered instrumentation "
                         "targets and exit")
    args = ap.parse_args(argv)

    if args.list_targets:
        from .instrument import discover_targets

        for cls in discover_targets():
            print(f"{cls.__module__}.{cls.__name__}")
        return 0

    try:
        rt, extras = run_scenario(args.scenario, args.seed,
                                  events=args.events, sched=args.sched,
                                  depth=args.depth)
    except BaselineError as e:
        print(f"cephrace: error: {e}", file=sys.stderr)
        return 2
    except Exception as e:
        print(f"cephrace: scenario {args.scenario!r} failed: "
              f"{type(e).__name__}: {e}", file=sys.stderr)
        return 2

    try:
        rep = race_report.build_report(
            rt.findings,
            baseline_file=Path(args.baseline) if args.baseline else None,
            use_baseline=not args.no_baseline)
    except BaselineError as e:
        print(f"cephrace: error: {e}", file=sys.stderr)
        return 2

    if args.write_baseline:
        Path(args.write_baseline).write_text(format_baseline(
            rep.findings, reason="FIXME: justify or fix"))
        print(f"cephrace: wrote {len(rep.findings)} entries to "
              f"{args.write_baseline}")
        return 0

    if args.format == "json":
        doc = rep.to_json()
        doc["run"] = extras
        print(json.dumps(doc, indent=2, sort_keys=True))
    else:
        out = race_report.render(rep, args.format)
        if out:
            print(out)
        if args.format == "text":
            print(f"cephrace: scenario={args.scenario} seed={args.seed} "
                  f"sched={args.sched} trace={extras['trace_events']} "
                  f"events digest={extras['trace_digest']}")
    return 0 if rep.clean else 1


if __name__ == "__main__":
    sys.exit(main())
