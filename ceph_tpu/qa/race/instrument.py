"""Instrumentation: where cephrace's probes physically attach.

Three layers, all installed/removed as one reversible set by
``install``/``uninstall`` (driven by runtime.race_session):

1. **Lock seam** — common.lockdep gets the active runtime as its hook
   object; every LockdepLock acquire/release (and the Condition
   save/restore protocol) reports in.  This is free coverage for every
   ``make_lock`` in the tree — including the common/ primitives the
   CL1 raw-lock sweep converted.
2. **threading / queue patches** — Thread.start/join (fork/join
   happens-before + scheduler registration), Condition wait/notify
   (signal edges + the lost-wakeup heuristic + held-set tracking for
   bare Conditions whose inner lock lockdep cannot see), Queue put/get
   (hand-off edges).  Wrappers pass straight through for threads the
   runtime never registered, so pytest/JAX internals are untouched.
3. **Class patches** — ``__setattr__``/``__getattribute__`` wrappers on
   the multi-threaded class families.  The target list is computed from
   cephlint's cross-file symbol table (``discover_targets``): a class is
   instrumented iff its family spawns threads or owns locks — the same
   ``family_threaded`` predicate CL2 uses — and it lives in the
   concurrency dirs.  No hand-curated list; when a new daemon class
   grows a lock, it becomes a detector target on the next run
   automatically.  Only family roots are patched (a patched base already
   covers its subclasses through attribute lookup).
"""
from __future__ import annotations

import functools
import queue as queue_mod
import threading
from dataclasses import dataclass, field
from pathlib import Path

from ...common import lockdep
from ...common.lockdep import LockdepLock
from .runtime import DeadlockError, active

#: Condition calls whose CALLER lives in these stdlib files are library
#: internals — Event.wait/set (threading.py, also the scheduler's own
#: gates), queue.Queue's not_empty/not_full (queue.py).  Instrumenting
#: them would recurse into the scheduler and hand the lost-wakeup
#: heuristic queue-internal notifies it must not see; the Thread/Queue
#: patches already model those edges at the right abstraction level.
import queue as _queue_file
import threading as _threading_file

_STDLIB_SYNC_FILES = (_threading_file.__file__, _queue_file.__file__)


def _internal_caller() -> bool:
    import sys

    return sys._getframe(2).f_code.co_filename in _STDLIB_SYNC_FILES

#: the subsystems whose shared state the detector watches (the dirs the
#: tentpole names); common/ enters via the lock seam, not attr tracking
DEFAULT_DIRS = ("msg", "mon", "osd", "store", "client", "fs")


# -- target discovery (static analysis feeds the dynamic detector) ----------

@functools.lru_cache(maxsize=4)
def _discover_names(dirs: tuple[str, ...]) -> tuple[tuple[str, str], ...]:
    """(modname, classname) pairs of multi-threaded family members under
    `dirs`, via the cephlint symbol table."""
    from ..analyzer.core import Config, collect_modules
    from ..analyzer.symbols import SymbolTable

    pkg_dir = Path(__file__).resolve().parents[2]
    cfg = Config.discover([str(pkg_dir)])
    mods = collect_modules(cfg)
    sym = SymbolTable.build(mods)
    out = []
    for ci in sym.classes.values():
        top = ci.path.split("/", 1)[0] if "/" in ci.path else ""
        if top not in dirs:
            continue
        if not sym.family_threaded(ci):
            continue
        out.append((ci.module, ci.name))
    return tuple(sorted(set(out)))


def discover_targets(dirs: tuple[str, ...] | None = None) -> tuple[type, ...]:
    """Resolve the statically-discovered names to live classes."""
    import importlib

    root_pkg = __package__.split(".")[0]          # "ceph_tpu"
    classes: list[type] = []
    for modname, clsname in _discover_names(tuple(dirs or DEFAULT_DIRS)):
        try:
            mod = importlib.import_module(f"{root_pkg}.{modname}")
            cls = getattr(mod, clsname, None)
        except Exception as e:  # noqa: CL7 — a gated-dep module must not kill discovery
            import sys

            print(f"cephrace: skipping target {modname}.{clsname}: {e!r}",
                  file=sys.stderr)
            continue
        if isinstance(cls, type):
            classes.append(cls)
    # family roots only: a patched base covers subclasses via lookup
    roots = [c for c in classes
             if not any(o is not c and issubclass(c, o) for o in classes)]
    return tuple(roots)


# -- patch bookkeeping -------------------------------------------------------

@dataclass
class _ClassPatch:
    cls: type
    had_setattr: bool
    orig_setattr: object
    had_getattribute: bool
    orig_getattribute: object


@dataclass
class Patches:
    classes: list[_ClassPatch] = field(default_factory=list)
    thread_start: object = None
    thread_join: object = None
    cond_wait: object = None
    cond_wait_for: object = None
    cond_notify: object = None
    cond_notify_all: object = None
    cond_enter: object = None
    cond_exit: object = None
    q_put: object = None
    q_get: object = None


def _patch_class(cls: type) -> _ClassPatch:
    orig_set = cls.__setattr__          # resolved through the MRO
    orig_get = cls.__getattribute__
    patch = _ClassPatch(
        cls=cls,
        had_setattr="__setattr__" in cls.__dict__,
        orig_setattr=cls.__dict__.get("__setattr__"),
        had_getattribute="__getattribute__" in cls.__dict__,
        orig_getattribute=cls.__dict__.get("__getattribute__"),
    )

    def __setattr__(self, name, value, _orig=orig_set):
        rt = active()
        if rt is not None and not name.startswith("__"):
            rt.on_access(self, name, True)
        _orig(self, name, value)

    def __getattribute__(self, name, _orig=orig_get):
        value = _orig(self, name)
        if name.startswith("_") and (name.startswith("__")
                                     or name.startswith("_race")):
            return value
        rt = active()
        if rt is not None and not callable(value):
            rt.on_access(self, name, False)
        return value

    cls.__setattr__ = __setattr__
    cls.__getattribute__ = __getattribute__
    return patch


def _unpatch_class(p: _ClassPatch) -> None:
    if p.had_setattr:
        p.cls.__setattr__ = p.orig_setattr
    else:
        try:
            del p.cls.__setattr__
        except AttributeError:
            pass
    if p.had_getattribute:
        p.cls.__getattribute__ = p.orig_getattribute
    else:
        try:
            del p.cls.__getattribute__
        except AttributeError:
            pass


# -- threading / queue patches ----------------------------------------------

def _cond_inner(cond) -> object | None:
    return getattr(cond, "_lock", None)


def install(rt, targets: tuple[type, ...]) -> Patches:
    patches = Patches()

    lockdep.set_race_hooks(rt)

    # Thread.start: snapshot the creator's clock into the child; wrap run
    # so the child registers itself, waits for its first schedule grant,
    # and reports exit (with its final clock, for join edges).
    orig_start = threading.Thread.start
    orig_join = threading.Thread.join
    patches.thread_start = orig_start
    patches.thread_join = orig_join

    def start(self):
        r = active()
        parent = r.thread_state() if r is not None else None
        if r is None or parent is None:
            return orig_start(self)
        child_ts = r.make_thread_state(self.name)
        self._race_ts = child_ts
        r.on_thread_start(parent, child_ts)
        # register with the scheduler HERE, on the parent side: priority
        # assignment follows registration order, and children adopting
        # themselves on first run would race for it (nondeterministic
        # plans from the same seed).  adopt's own register is idempotent.
        if r.scheduler is not None:
            r.scheduler.register(child_ts.tid)
        orig_run = self.run

        def _race_run():
            r2 = active()
            if r2 is r:
                r.adopt_thread_state(child_ts)
                if r.scheduler is not None:
                    r.scheduler.yield_point(child_ts.tid)
            try:
                orig_run()
            except DeadlockError:
                pass   # already recorded as a CR2 finding
            finally:
                if active() is r:
                    r.on_thread_exit(child_ts)

        self.run = _race_run
        return orig_start(self)

    def join(self, timeout=None):
        r = active()
        ts = r.thread_state() if r is not None else None
        if r is None or ts is None:
            return orig_join(self, timeout)
        r.block_begin(ts)
        try:
            return orig_join(self, timeout)
        finally:
            r.block_end(ts)
            child_ts = getattr(self, "_race_ts", None)
            if child_ts is not None and not self.is_alive():
                r.on_thread_join(ts, child_ts)

    threading.Thread.start = start
    threading.Thread.join = join

    # Condition: wait/notify edges + lost-wakeup bookkeeping.  For a bare
    # Condition (inner lock invisible to lockdep) the enter/exit/wait
    # wrappers also maintain the held-lock set and deadlock owner map —
    # otherwise attribute writes under ``with self._cond:`` would look
    # lockless and the lockset machine would cry wolf.
    orig_wait = threading.Condition.wait
    orig_wait_for = threading.Condition.wait_for
    orig_notify = threading.Condition.notify
    orig_notify_all = threading.Condition.notify_all
    orig_enter = threading.Condition.__enter__
    orig_exit = threading.Condition.__exit__
    patches.cond_wait = orig_wait
    patches.cond_wait_for = orig_wait_for
    patches.cond_notify = orig_notify
    patches.cond_notify_all = orig_notify_all
    patches.cond_enter = orig_enter
    patches.cond_exit = orig_exit

    def cond_enter(self):
        r = active()
        ts = r.thread_state() if r is not None else None
        inner = _cond_inner(self)
        if r is None or ts is None or inner is None \
                or isinstance(inner, LockdepLock) or _internal_caller():
            return orig_enter(self)      # lockdep hooks cover LockdepLock
        r.before_acquire(inner)
        got = orig_enter(self)
        r.after_acquire(inner)
        return got

    def cond_exit(self, *exc):
        r = active()
        ts = r.thread_state() if r is not None else None
        inner = _cond_inner(self)
        if r is not None and ts is not None and inner is not None \
                and not isinstance(inner, LockdepLock) \
                and not _internal_caller():
            r.before_release(inner)
        return orig_exit(self, *exc)

    def wait(self, timeout=None):
        r = active()
        ts = r.thread_state() if r is not None else None
        if r is None or ts is None or _internal_caller():
            return orig_wait(self, timeout)
        inner = _cond_inner(self)
        bare = inner is not None and not isinstance(inner, LockdepLock)
        pre_lost = r.on_wait_begin(self)
        if bare:
            r.cond_release_save(inner)
        r.block_begin(ts)
        ok = None
        try:
            ok = orig_wait(self, timeout)
            return ok
        finally:
            r.block_end(ts)
            if bare:
                r.cond_acquire_restore(inner)
            r.on_wait_end(self, bool(ok), pre_lost)

    def wait_for(self, predicate, timeout=None):
        # wait_for is the tree's dominant wait idiom (throttle, OSD
        # cond, MonClient, Objecter) and its INTERNAL self.wait calls
        # are deliberately passed through as stdlib-internal — so the
        # whole call gets one bracket here: one on_wait_begin/end for
        # CR3 (a wait_for timeout after a no-waiter notify is exactly a
        # lost wakeup) and one block_begin/end so a serialized thread
        # parks without keeping the token.
        r = active()
        ts = r.thread_state() if r is not None else None
        if r is None or ts is None or _internal_caller():
            return orig_wait_for(self, predicate, timeout)
        inner = _cond_inner(self)
        bare = inner is not None and not isinstance(inner, LockdepLock)
        pre_lost = r.on_wait_begin(self)
        if bare:
            r.cond_release_save(inner)
        r.block_begin(ts)
        ok = None
        try:
            ok = orig_wait_for(self, predicate, timeout)
            return ok
        finally:
            r.block_end(ts)
            if bare:
                r.cond_acquire_restore(inner)
            r.on_wait_end(self, bool(ok), pre_lost)

    def notify(self, n=1):
        r = active()
        if r is not None and r.thread_state() is not None \
                and not _internal_caller():
            r.on_notify(self)
        return orig_notify(self, n)

    def notify_all(self):
        r = active()
        if r is not None and r.thread_state() is not None \
                and not _internal_caller():
            r.on_notify(self)
        return orig_notify_all(self)

    threading.Condition.wait = wait
    threading.Condition.wait_for = wait_for
    threading.Condition.notify = notify
    threading.Condition.notify_all = notify_all
    threading.Condition.__enter__ = cond_enter
    threading.Condition.__exit__ = cond_exit

    # Queue: hand-off happens-before via one joined clock per queue
    orig_put = queue_mod.Queue.put
    orig_get = queue_mod.Queue.get
    patches.q_put = orig_put
    patches.q_get = orig_get

    def put(self, item, block=True, timeout=None):
        r = active()
        ts = r.thread_state() if r is not None else None
        if r is None or ts is None:
            return orig_put(self, item, block, timeout)
        r.on_queue_put(self)   # clock into the queue BEFORE the item lands
        if block:
            r.block_begin(ts)
        try:
            return orig_put(self, item, block, timeout)
        finally:
            if block:
                r.block_end(ts)

    def get(self, block=True, timeout=None):
        r = active()
        ts = r.thread_state() if r is not None else None
        if r is None or ts is None:
            return orig_get(self, block, timeout)
        if block:
            r.block_begin(ts)
        ok = False
        try:
            item = orig_get(self, block, timeout)
            ok = True
            return item
        finally:
            if block:
                r.block_end(ts)
            r.on_queue_get(self, ok)

    queue_mod.Queue.put = put
    queue_mod.Queue.get = get

    for cls in targets:
        patches.classes.append(_patch_class(cls))
    return patches


def uninstall(patches: Patches) -> None:
    lockdep.set_race_hooks(None)
    if patches.thread_start is not None:
        threading.Thread.start = patches.thread_start
        threading.Thread.join = patches.thread_join
    if patches.cond_wait is not None:
        threading.Condition.wait = patches.cond_wait
        threading.Condition.wait_for = patches.cond_wait_for
        threading.Condition.notify = patches.cond_notify
        threading.Condition.notify_all = patches.cond_notify_all
        threading.Condition.__enter__ = patches.cond_enter
        threading.Condition.__exit__ = patches.cond_exit
    if patches.q_put is not None:
        queue_mod.Queue.put = patches.q_put
        queue_mod.Queue.get = patches.q_get
    for p in patches.classes:
        _unpatch_class(p)
