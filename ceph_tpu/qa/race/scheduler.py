"""Seeded PCT-style schedule exploration.

PCT (probabilistic concurrency testing) in its classic form: give every
thread a distinct random priority, pick d-1 schedule points at which the
running thread's priority drops below everyone else's, and always run
the highest-priority enabled thread.  A bug of preemption depth d is
found with probability >= 1/(n * k^(d-1)) — so short seeded runs explore
interleavings a plain run essentially never hits.

Both schedulers here derive their entire plan (per-registration-order
priorities, change points, change values) purely from the seed, exactly
like qa/thrasher.py's ``plan()``: the plan IS the replay artifact.

Two enforcement modes:

- ``PerturbScheduler`` — production mode, safe under a full LocalCluster:
  at each instrumented sync point the current thread sleeps a delay
  proportional to how far it is from the top priority.  No global token,
  no risk of stalling a thread that blocks outside instrumented points.
  The *decisions* are deterministic; the resulting trace is only as
  deterministic as the host's threading.

- ``SerializeScheduler`` — fixture mode: one global token; every
  registered thread runs alone between sync points and hands the token
  to the highest-priority runnable thread.  Blocking operations bracket
  themselves with ``block_begin``/``block_end`` so the token never sits
  inside a real wait.  With deterministic per-thread programs this makes
  the whole event trace bit-for-bit reproducible from the seed — the
  property tests/test_race.py gates.
"""
from __future__ import annotations

import random
import threading

_READY = 0      # waiting at a sync point for the token
_RUNNING = 1    # holds the token
_BLOCKED = 2    # inside a real blocking operation (token released)
_DONE = 3

#: safety valve: a serialized thread never waits for the token longer
#: than this before proceeding anyway (records a breach — determinism is
#: formally broken but the run survives a scheduler bug or an
#: uninstrumented blocking call)
_GRANT_TIMEOUT = 10.0


class SchedulerPlan:
    """The pure-from-seed part, shared by both modes."""

    def __init__(self, seed: int, depth: int = 3, horizon: int = 4096,
                 max_threads: int = 64):
        self.seed = seed
        self.depth = depth
        self.horizon = horizon
        # string seeds hash deterministically across processes (tuple
        # seeds would go through PYTHONHASHSEED-salted hash())
        rng = random.Random(f"cephrace-sched-{seed}")
        # distinct priorities handed out in registration order; higher
        # wins.  A second block of low values serves the change points.
        pr = list(range(1000, 1000 + max_threads))
        rng.shuffle(pr)
        self.priorities = pr
        k = max(0, depth - 1)
        points = sorted(rng.sample(range(1, horizon), k)) if k else []
        self.change_points = points
        self.change_values = [rng.randrange(0, 100) for _ in points]

    def describe(self) -> dict:
        return {
            "seed": self.seed,
            "depth": self.depth,
            "priorities": self.priorities[:16],
            "change_points": self.change_points,
            "change_values": self.change_values,
        }


class _SchedulerBase:
    #: True when the scheduler guarantees one-thread-at-a-time between
    #: sync points; the runtime then routes attribute READS through
    #: yield_point too (a read emitted off-token would land in the trace
    #: at raw CPU timing, breaking same-seed replay)
    serialize_mode = False

    def __init__(self, seed: int, depth: int = 3, horizon: int = 4096):
        self.plan = SchedulerPlan(seed, depth, horizon)
        self._prio: dict[int, int] = {}
        self._next_reg = 0
        self._point = 0
        self._next_change = 0
        self._lock = threading.Lock()
        self.breaches = 0

    def register(self, tid: int) -> None:
        with self._lock:
            if tid in self._prio:
                return
            pr = self.plan.priorities
            self._prio[tid] = pr[self._next_reg % len(pr)]
            self._next_reg += 1

    def _advance_point_locked(self, tid: int) -> None:
        """Global sync-point counter + PCT priority change points."""
        self._point += 1
        cps = self.plan.change_points
        if self._next_change < len(cps) and self._point >= cps[self._next_change]:
            self._prio[tid] = self.plan.change_values[self._next_change]
            self._next_change += 1

    # interface the runtime drives; overridden per mode
    def yield_point(self, tid: int) -> None:
        raise NotImplementedError

    def block_begin(self, tid: int) -> None:
        pass

    def block_end(self, tid: int) -> None:
        pass

    def thread_exit(self, tid: int) -> None:
        pass

    def shutdown(self) -> None:
        pass


class PerturbScheduler(_SchedulerBase):
    """Priority-biased sleep injection (cluster-safe)."""

    def __init__(self, seed: int, depth: int = 3, horizon: int = 4096,
                 base_delay: float = 0.0005, max_delay: float = 0.004):
        super().__init__(seed, depth, horizon)
        self.base_delay = base_delay
        self.max_delay = max_delay

    def yield_point(self, tid: int) -> None:
        with self._lock:
            if tid not in self._prio:
                return
            self._advance_point_locked(tid)
            prio = self._prio[tid]
            ranked = sorted(self._prio.values(), reverse=True)
            rank = ranked.index(prio)
        if rank:
            import time

            time.sleep(min(self.base_delay * rank, self.max_delay))


class _SThread:
    __slots__ = ("state", "gate")

    def __init__(self) -> None:
        self.state = _READY
        self.gate = threading.Event()


class SerializeScheduler(_SchedulerBase):
    """Cooperative single-token serialization (fixture mode)."""

    serialize_mode = True

    def __init__(self, seed: int, depth: int = 3, horizon: int = 4096):
        super().__init__(seed, depth, horizon)
        self._threads: dict[int, _SThread] = {}
        self._current: int | None = None
        self._active = True

    def register(self, tid: int) -> None:
        super().register(tid)
        with self._lock:
            if tid in self._threads:
                return
            st = _SThread()
            self._threads[tid] = st
            if self._current is None:
                self._current = tid
                st.state = _RUNNING
                st.gate.set()

    def yield_point(self, tid: int) -> None:
        if not self._active:
            return
        with self._lock:
            st = self._threads.get(tid)
            if st is None:
                return
            # only the TOKEN HOLDER's yields advance the schedule-point
            # counter: a thread merely ARRIVING at its first yield (or
            # re-parking) is bootstrap-timing noise, and counting it
            # would land the PCT change points on different points of
            # the schedule run-to-run — breaking same-seed replay
            if self._current == tid:
                self._advance_point_locked(tid)
            st.state = _READY
            st.gate.clear()
            self._grant_locked()
        self._await_gate(tid)

    def block_begin(self, tid: int) -> None:
        """Called before a real blocking op: hand the token off so the
        thread we may be waiting FOR can run."""
        if not self._active:
            return
        with self._lock:
            st = self._threads.get(tid)
            if st is None:
                return
            st.state = _BLOCKED
            st.gate.clear()
            if self._current == tid:
                self._current = None
            self._grant_locked()

    def block_end(self, tid: int) -> None:
        if not self._active:
            return
        with self._lock:
            st = self._threads.get(tid)
            if st is None:
                return
            st.state = _READY
            # defensively drop any stale grant before re-granting: an
            # already-set gate would let _await_gate fall through while
            # the token went to another thread (two live runners)
            st.gate.clear()
            self._grant_locked()
        self._await_gate(tid)

    def thread_exit(self, tid: int) -> None:
        with self._lock:
            st = self._threads.get(tid)
            if st is None:
                return
            st.state = _DONE
            if self._current == tid:
                self._current = None
            self._grant_locked()

    def shutdown(self) -> None:
        """Release everyone (end of scenario / teardown)."""
        with self._lock:
            self._active = False
            for st in self._threads.values():
                st.gate.set()

    # -- internals ----------------------------------------------------------
    def _grant_locked(self) -> None:
        if self._current is not None:
            cur = self._threads[self._current]
            if cur.state == _RUNNING:
                return
        ready = [(self._prio[t], t) for t, st in self._threads.items()
                 if st.state == _READY]
        if not ready:
            self._current = None
            return
        _, chosen = max(ready)
        self._current = chosen
        st = self._threads[chosen]
        st.state = _RUNNING
        st.gate.set()

    def _await_gate(self, tid: int) -> None:
        st = self._threads[tid]
        if not st.gate.wait(timeout=_GRANT_TIMEOUT):
            # safety valve (see _GRANT_TIMEOUT): proceed un-granted
            with self._lock:
                self.breaches += 1
                st.state = _RUNNING
                if self._current is None:
                    self._current = tid
                st.gate.set()


def make_scheduler(mode: str, seed: int, depth: int = 3) -> _SchedulerBase:
    if mode == "serialize":
        return SerializeScheduler(seed, depth)
    if mode == "perturb":
        return PerturbScheduler(seed, depth)
    raise ValueError(f"unknown scheduler mode {mode!r}")
