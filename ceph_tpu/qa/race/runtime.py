"""The cephrace runtime: per-thread vector-clock state, sync-event
recording, the lockset machine, actual-deadlock detection, and the
lost-wakeup heuristic.

One RaceRuntime is active at a time (module global, like lockdep's
graph).  It is driven from four directions:

- ``common.lockdep`` calls the LockHooks protocol on every LockdepLock
  acquire/release (and through the Condition save/restore protocol);
- ``instrument.py``'s class patches call ``on_read``/``on_write`` for
  attribute traffic of the multi-threaded families;
- ``instrument.py``'s threading/queue patches call the thread, queue
  and condition event methods;
- the scheduler is consulted at every sync point (``yield_point``) and
  around real blocking operations (``block_begin``/``block_end``).

Happens-before edges modelled (release -> acquire in each case):

    lock release        -> same lock's next acquire
    Thread.start        -> first event of the child
    child's last event  -> Thread.join return
    Queue.put           -> any later Queue.get on that queue (the queue
                           carries one joined clock: an over-approximation
                           that can only SUPPRESS reports, never add one)
    Condition.notify    -> a wait that returns after it

Deadlock: a waits-for graph over *instances* (thread -> lock-owner),
checked before each blocking LockdepLock acquire; a cycle raises
DeadlockError in the acquiring thread (deterministic, instead of
hanging the run) and records a CR2 finding.  This complements lockdep:
lockdep orders lock *names* and must see both orders; the waits-for
check catches the schedule the PCT scheduler actually steered into,
including single-name instance deadlocks lockdep's recursion allowance
ignores.
"""
from __future__ import annotations

import sys
import threading
from contextlib import contextmanager
from dataclasses import dataclass, field
from pathlib import Path

from .events import Event, Trace, VectorClock
from .lockset import Access, LocksetMachine

_PKG_ROOT = Path(__file__).resolve().parents[2]   # .../ceph_tpu
_RACE_DIR = str(Path(__file__).resolve().parent)

# plumbing frames a finding must never be attributed to: this package,
# the lockdep seam, and the stdlib sync modules our patches wrap
import queue as _queue_mod

_SKIP_FILES = (threading.__file__, _queue_mod.__file__,
               str(_PKG_ROOT / "common" / "lockdep.py"))


class DeadlockError(RuntimeError):
    """Raised in the thread whose acquire would close a waits-for cycle."""


@dataclass
class RaceFinding:
    """A runtime finding, pre-report (report.py turns these into the
    analyzer's Finding type for noqa/baseline/SARIF)."""

    code: str          # CR1 data race | CR2 deadlock | CR3 lost wakeup
    path: str          # package-relative posix path of the primary site
    line: int
    ident: str         # stable baseline key
    message: str


class _ThreadState:
    __slots__ = ("tid", "vc", "held", "held_tokens", "name", "cs_activity",
                 "lock_block_pending")

    def __init__(self, tid: int, name: str):
        self.tid = tid
        self.name = name
        self.vc = VectorClock()
        self.vc.tick(tid)
        # lock token -> recursion count (tokens are stable per-lock labels)
        self.held: dict[str, int] = {}
        self.held_tokens: frozenset | None = frozenset()  # cache
        # lock token -> cond ids waited/notified while holding it (the
        # lost-wakeup heuristic's evidence of signal-related activity)
        self.cs_activity: dict[str, set[int]] = {}
        # True between before_acquire's block_begin and the matching
        # block_end: re-entrant and bounded acquires skip block_begin,
        # and an UNMATCHED block_end would hand the serialize token away
        # while this thread keeps running (two live threads = broken
        # replay)
        self.lock_block_pending = False

    def tokens(self) -> frozenset:
        if self.held_tokens is None:
            self.held_tokens = frozenset(self.held)
        return self.held_tokens


class _SyncVC:
    """Clock attached to a lock / queue / condition object."""

    __slots__ = ("vc",)

    def __init__(self) -> None:
        self.vc = VectorClock()


class RaceRuntime:
    """See module docstring.  Not re-entrant: one active instance."""

    def __init__(self, seed: int, scheduler=None, max_events: int = 500_000):
        self.seed = seed
        self.scheduler = scheduler
        self.trace = Trace(max_events=max_events)
        self.machine = LocksetMachine()
        self.findings: list[RaceFinding] = []
        self._finding_keys: set[tuple] = set()
        self._state = threading.Lock()   # guards everything below
        self._threads: dict[int, _ThreadState] = {}   # python ident -> state
        self._next_tid = 0
        self._seq = 0
        # per-object deterministic labels: lock/queue/cond/instance
        self._labels: dict[int, str] = {}
        self._label_counts: dict[str, int] = {}
        self._sync_vcs: dict[int, _SyncVC] = {}
        # deadlock: lock token -> owning tid; tid -> (token, owner tid)
        self._owners: dict[str, int] = {}
        self._waiting: dict[int, tuple[str, int]] = {}
        # lost wakeup: cond key -> [waiters, unconsumed_notifies]
        self._conds: dict[int, list[int]] = {}
        # lock token -> cond ids whose inner lock it is (for the
        # critical-section clearing rule below)
        self._lock_conds: dict[str, set[int]] = {}
        self._reentry = threading.local()

    # -- registration & labels ----------------------------------------------
    def register_thread(self, name: str | None = None) -> _ThreadState:
        ident = threading.get_ident()
        with self._state:
            ts = self._threads.get(ident)
            if ts is None:
                ts = _ThreadState(self._next_tid,
                                  name or threading.current_thread().name)
                self._next_tid += 1
                self._threads[ident] = ts
                if self.scheduler is not None:
                    self.scheduler.register(ts.tid)
            return ts

    def adopt_thread_state(self, ts: _ThreadState) -> None:
        """Bind a pre-created state (child thread start hand-off) to the
        calling thread."""
        with self._state:
            self._threads[threading.get_ident()] = ts
            if self.scheduler is not None:
                self.scheduler.register(ts.tid)

    def make_thread_state(self, name: str) -> _ThreadState:
        with self._state:
            ts = _ThreadState(self._next_tid, name)
            self._next_tid += 1
            return ts

    def thread_state(self) -> _ThreadState | None:
        return self._threads.get(threading.get_ident())

    def _label_locked(self, obj, stem: str) -> str:
        lab = self._labels.get(id(obj))
        if lab is None:
            n = self._label_counts.get(stem, 0)
            self._label_counts[stem] = n + 1
            lab = f"{stem}#{n}"
            self._labels[id(obj)] = lab
        return lab

    def _sync_vc_locked(self, obj) -> _SyncVC:
        sv = self._sync_vcs.get(id(obj))
        if sv is None:
            sv = self._sync_vcs[id(obj)] = _SyncVC()
        return sv

    # -- trace ---------------------------------------------------------------
    def _emit_locked(self, tid: int, kind: str, target: str,
                     where: str = "") -> None:
        self.trace.append(Event(self._seq, tid, kind, target, where))
        self._seq += 1

    def _site(self, depth: int = 2) -> tuple[str, int, str]:
        """(package-relative path, line, function) of the first frame
        outside qa/race — the instrumented call site."""
        f = sys._getframe(depth)
        while f is not None and (
            f.f_code.co_filename.startswith(_RACE_DIR)
            or f.f_code.co_filename in _SKIP_FILES
        ):
            f = f.f_back
        if f is None:
            return ("?", 0, "?")
        fn = f.f_code.co_filename
        try:
            rel = Path(fn).resolve().relative_to(_PKG_ROOT).as_posix()
        except ValueError:
            rel = Path(fn).name
        return (rel, f.f_lineno, f.f_code.co_name)

    def _add_finding(self, f: RaceFinding) -> None:
        key = (f.code, f.ident)
        with self._state:
            if key in self._finding_keys:
                return
            self._finding_keys.add(key)
            self.findings.append(f)

    # -- scheduler glue -------------------------------------------------------
    def _yield(self, ts: _ThreadState) -> None:
        if self.scheduler is not None:
            self.scheduler.yield_point(ts.tid)

    def block_begin(self, ts: _ThreadState) -> None:
        if self.scheduler is not None:
            self.scheduler.block_begin(ts.tid)

    def block_end(self, ts: _ThreadState) -> None:
        if self.scheduler is not None:
            self.scheduler.block_end(ts.tid)

    # -- lock hooks (driven by common.lockdep) -------------------------------
    def lock_token(self, lock) -> str:
        with self._state:
            return self._label_locked(lock, getattr(lock, "name", "lock"))

    def before_acquire(self, lock, unbounded: bool = True) -> None:
        ts = self.thread_state()
        if ts is None:
            return
        self._yield(ts)
        token = self.lock_token(lock)
        with self._state:
            if ts.held.get(token):
                return    # recursive re-entry cannot deadlock
            if not unbounded:
                # try-lock / timed acquire: resolves on its own, so it
                # neither raises nor contributes a waits-for edge (a
                # bounded wait in the graph would fabricate cycles for
                # OTHER threads' checks)
                return
            owner = self._owners.get(token)
            if owner is not None and owner != ts.tid:
                cycle = self._deadlock_cycle_locked(ts.tid, owner, token)
                if cycle is not None:
                    path, line, fn = self._site(2)
                    names = " -> ".join(cycle)
                    self._emit_locked(ts.tid, "deadlock", names,
                                      f"{path}:{line}")
                    f = RaceFinding(
                        "CR2", path, line, f"deadlock:{names}",
                        f"deadlock: acquiring {token} in {fn} closes the "
                        f"waits-for cycle [{names}]")
                    if (f.code, f.ident) not in self._finding_keys:
                        self._finding_keys.add((f.code, f.ident))
                        self.findings.append(f)
                    raise DeadlockError(f.message)
                self._waiting[ts.tid] = (token, owner)
        self.block_begin(ts)
        ts.lock_block_pending = True

    def after_acquire(self, lock) -> None:
        ts = self.thread_state()
        if ts is None:
            return
        token = self.lock_token(lock)
        # block_end ONLY when before_acquire actually ran block_begin
        # (re-entrant and bounded acquires skip it; lock_block_pending
        # is thread-local so the unlocked check is safe)
        if ts.lock_block_pending:
            ts.lock_block_pending = False
            self.block_end(ts)
        with self._state:
            self._waiting.pop(ts.tid, None)
            n = ts.held.get(token, 0)
            ts.held[token] = n + 1
            ts.held_tokens = None
            if n == 0:
                self._owners[token] = ts.tid
                sv = self._sync_vc_locked(lock)
                ts.vc.join(sv.vc)
                ts.vc.tick(ts.tid)
                self._emit_locked(ts.tid, "acquire", token)

    def acquire_failed(self, lock) -> None:
        """Non-blocking/timed acquire that did not get the lock."""
        ts = self.thread_state()
        if ts is None:
            return
        if ts.lock_block_pending:
            ts.lock_block_pending = False
            self.block_end(ts)
        with self._state:
            self._waiting.pop(ts.tid, None)

    def before_release(self, lock) -> None:
        ts = self.thread_state()
        if ts is None:
            return
        token = self.lock_token(lock)
        with self._state:
            n = ts.held.get(token, 0)
            if n <= 1:
                ts.held.pop(token, None)
                self._owners.pop(token, None)
                sv = self._sync_vc_locked(lock)
                sv.vc.join(ts.vc)
                ts.vc.tick(ts.tid)
                self._cs_clear_locked(ts, token)
                self._emit_locked(ts.tid, "release", token)
            else:
                ts.held[token] = n - 1
            ts.held_tokens = None

    # Condition-protocol save/restore on a LockdepLock: the lock is fully
    # released across wait() without passing through release()/acquire()
    def cond_release_save(self, lock) -> None:
        ts = self.thread_state()
        if ts is None:
            return
        token = self.lock_token(lock)
        with self._state:
            if token in ts.held:
                ts.held.pop(token, None)
                ts.held_tokens = None
                self._owners.pop(token, None)
                sv = self._sync_vc_locked(lock)
                sv.vc.join(ts.vc)
                ts.vc.tick(ts.tid)
                self._cs_clear_locked(ts, token)
                self._emit_locked(ts.tid, "release", token)

    def cond_acquire_restore(self, lock) -> None:
        ts = self.thread_state()
        if ts is None:
            return
        token = self.lock_token(lock)
        with self._state:
            ts.held[token] = ts.held.get(token, 0) + 1
            ts.held_tokens = None
            self._owners[token] = ts.tid
            sv = self._sync_vc_locked(lock)
            ts.vc.join(sv.vc)
            ts.vc.tick(ts.tid)
            self._emit_locked(ts.tid, "acquire", token)

    def _deadlock_cycle_locked(self, me: int, owner: int,
                               want: str) -> list[str] | None:
        """Follow tid -> (wanted lock, owner) edges from `owner`; a path
        back to `me` plus the new me->owner edge is a cycle.  Returns the
        lock tokens along it."""
        path = [want]
        seen = {me}
        cur = owner
        while True:
            if cur in seen:
                return path if cur == me else None
            seen.add(cur)
            nxt = self._waiting.get(cur)
            if nxt is None:
                return None
            path.append(nxt[0])
            cur = nxt[1]

    # -- thread lifecycle (driven by instrument's Thread patches) ------------
    def on_thread_start(self, parent_ts: _ThreadState,
                        child_ts: _ThreadState) -> None:
        with self._state:
            child_ts.vc.join(parent_ts.vc)
            child_ts.vc.tick(child_ts.tid)
            parent_ts.vc.tick(parent_ts.tid)
            self._emit_locked(parent_ts.tid, "thread_start",
                              f"t{child_ts.tid}")

    def on_thread_exit(self, ts: _ThreadState) -> None:
        with self._state:
            self._emit_locked(ts.tid, "thread_exit", f"t{ts.tid}")
        if self.scheduler is not None:
            self.scheduler.thread_exit(ts.tid)

    def on_thread_join(self, joiner: _ThreadState,
                       child_ts: _ThreadState) -> None:
        with self._state:
            joiner.vc.join(child_ts.vc)
            joiner.vc.tick(joiner.tid)
            self._emit_locked(joiner.tid, "thread_join", f"t{child_ts.tid}")

    # -- queues ---------------------------------------------------------------
    def on_queue_put(self, q) -> None:
        ts = self.thread_state()
        if ts is None:
            return
        self._yield(ts)
        with self._state:
            lab = self._label_locked(q, "queue")
            sv = self._sync_vc_locked(q)
            sv.vc.join(ts.vc)
            ts.vc.tick(ts.tid)
            self._emit_locked(ts.tid, "q_put", lab)

    def on_queue_get(self, q, ok: bool) -> None:
        ts = self.thread_state()
        if ts is None:
            return
        with self._state:
            lab = self._label_locked(q, "queue")
            if ok:
                sv = self._sync_vc_locked(q)
                ts.vc.join(sv.vc)
                ts.vc.tick(ts.tid)
                self._emit_locked(ts.tid, "q_get", lab)

    # -- conditions ------------------------------------------------------------
    def _mark_cond_activity_locked(self, ts: _ThreadState, cond) -> None:
        """Tie this cond to its inner lock's token and record that the
        current critical section did signal-related work on it.  A later
        release of that lock by a thread that did NEITHER wait NOR
        notify proves the predicate was observable without the signal —
        any pending no-waiter notify was not lost, just unneeded (the
        while-recheck idiom), so it stops counting."""
        inner = getattr(cond, "_lock", None)
        if inner is None:
            return
        token = self._label_locked(inner, getattr(inner, "name", "lock"))
        self._lock_conds.setdefault(token, set()).add(id(cond))
        if token in ts.held:
            ts.cs_activity.setdefault(token, set()).add(id(cond))

    def _cs_clear_locked(self, ts: _ThreadState, token: str) -> None:
        conds = self._lock_conds.get(token)
        if not conds:
            ts.cs_activity.pop(token, None)
            return
        active = ts.cs_activity.pop(token, set())
        for cid in conds:
            if cid not in active:
                st = self._conds.get(cid)
                if st and st[0] == 0:
                    st[1] = 0

    def on_notify(self, cond, n_woken_hint: int | None = None) -> None:
        ts = self.thread_state()
        if ts is None:
            return
        self._yield(ts)
        with self._state:
            lab = self._label_locked(cond, "cond")
            sv = self._sync_vc_locked(cond)
            sv.vc.join(ts.vc)
            ts.vc.tick(ts.tid)
            st = self._conds.setdefault(id(cond), [0, 0])
            if st[0] == 0:
                # a notify with no waiter has no memory: if somebody was
                # relying on it, it is lost the moment it fires
                st[1] += 1
            self._mark_cond_activity_locked(ts, cond)
            self._emit_locked(ts.tid, "notify", lab)

    def on_wait_begin(self, cond) -> int:
        ts = self.thread_state()
        if ts is None:
            return 0
        self._yield(ts)
        with self._state:
            lab = self._label_locked(cond, "cond")
            st = self._conds.setdefault(id(cond), [0, 0])
            st[0] += 1
            self._mark_cond_activity_locked(ts, cond)
            self._emit_locked(ts.tid, "cond_wait", lab)
            return st[1]

    def on_wait_end(self, cond, got_it, pre_lost: int) -> None:
        ts = self.thread_state()
        if ts is None:
            return
        with self._state:
            lab = self._label_locked(cond, "cond")
            st = self._conds.setdefault(id(cond), [0, 0])
            st[0] = max(0, st[0] - 1)
            if got_it:
                sv = self._sync_vc_locked(cond)
                ts.vc.join(sv.vc)
                ts.vc.tick(ts.tid)
                self._emit_locked(ts.tid, "cond_wake", lab)
                return
            self._emit_locked(ts.tid, "cond_timeout", lab)
            lost = st[1] > 0 and pre_lost > 0
            if lost:
                st[1] = 0   # one report per pending notify, not per retry
        if lost:
            path, line, fn = self._site(2)
            self._add_finding(RaceFinding(
                "CR3", path, line, f"lost-wakeup:{fn}",
                f"lost wakeup: wait in {fn} timed out although a notify "
                f"on the same condition fired with no waiter present "
                f"before the wait began (signal has no memory — set the "
                f"predicate under the lock and re-check it, or notify "
                f"after the waiter registers)"))

    # -- attribute traffic (driven by instrument's class patches) -------------
    def on_access(self, obj, attr: str, is_write: bool) -> None:
        ts = self._threads.get(threading.get_ident())
        if ts is None:
            return
        if getattr(self._reentry, "busy", False):
            return
        self._reentry.busy = True
        try:
            # writes always yield (the interleavings races live in);
            # reads only under a serializing scheduler, where an
            # off-token read event would break trace replay
            if is_write or (self.scheduler is not None
                            and self.scheduler.serialize_mode):
                self._yield(ts)
            path, line, fn = self._site(3)
            where = f"{path}:{line} in {fn}"
            with self._state:
                cls_name = type(obj).__name__
                lab = self._label_locked(obj, cls_name)
                var = self.machine.var_for(id(obj), f"{lab}.{attr}",
                                           cls_name, attr)
                acc = Access(tid=ts.tid, is_write=is_write,
                             locks=ts.tokens(), vc_snap=ts.vc.snapshot(),
                             where=where)
                self._emit_locked(ts.tid, "write" if is_write else "read",
                                  f"{lab}.{attr}", f"{path}:{line}")
                cand = self.machine.record(var, acc, ts.vc)
            if cand is not None:
                self._add_finding(RaceFinding(
                    "CR1", path, line,
                    f"race:{cls_name}.{attr}",
                    f"data race ({cand.kind}) on {cls_name}.{attr}: "
                    f"{'write' if acc.is_write else 'read'} at {where} with "
                    f"lock(s) {{{', '.join(sorted(acc.locks)) or 'none'}}} "
                    f"conflicts with prior "
                    f"{'write' if cand.prior.is_write else 'read'} at "
                    f"{cand.prior.where} holding "
                    f"{{{', '.join(sorted(cand.prior.locks)) or 'none'}}}; "
                    f"no common lock and no happens-before edge"))
        finally:
            self._reentry.busy = False


# -- module-global active runtime ------------------------------------------

_ACTIVE: RaceRuntime | None = None


def active() -> RaceRuntime | None:
    return _ACTIVE


def _set_active(rt: RaceRuntime | None) -> None:
    global _ACTIVE
    _ACTIVE = rt


@contextmanager
def race_session(seed: int, scheduler=None, targets=None,
                 target_dirs=None, max_events: int = 500_000):
    """Install the full detector (lockdep hooks, threading/queue patches,
    class instrumentation) around a block:

        with race_session(seed=7, scheduler=make_scheduler("perturb", 7)) as rt:
            ... run scenario ...
        report = build_report(rt)

    `targets` overrides class discovery (fixtures); by default the
    instrumentation list comes from the cephlint symbol table
    (instrument.discover_targets)."""
    from . import instrument

    if _ACTIVE is not None:
        raise RuntimeError("a race_session is already active")
    rt = RaceRuntime(seed, scheduler=scheduler, max_events=max_events)
    if targets is None:
        targets = instrument.discover_targets(dirs=target_dirs)
    patches = instrument.install(rt, targets)
    rt.register_thread("main")
    _set_active(rt)
    try:
        yield rt
    finally:
        _set_active(None)
        instrument.uninstall(patches)
        if scheduler is not None:
            scheduler.shutdown()
