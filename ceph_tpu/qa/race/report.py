"""cephrace reporting — the analyzer's suppression machinery, reused.

Runtime RaceFindings become analyzer ``Finding``s (codes CR1/CR2/CR3)
and flow through the exact same layers cephlint findings do:

- ``# noqa: CR1`` on the access line (the line the detector attributed
  the primary site to);
- pinned ``qa/race/baseline.toml`` entries with a mandatory reason;
- text / json / SARIF rendering (tool name ``cephrace``).

One deliberate difference from cephlint: STALE baseline entries warn but
never fail.  A race finding is schedule-dependent — one seed not
reproducing a baselined race is expected, not proof the debt was paid.
Baseline entries here are retired by hand when the underlying code is
fixed.
"""
from __future__ import annotations

from pathlib import Path

from ..analyzer import core

_PKG_ROOT = Path(__file__).resolve().parents[2]      # .../ceph_tpu
RACE_BASELINE = Path(__file__).resolve().parent / "baseline.toml"


def to_findings(raw) -> list[core.Finding]:
    out = [core.Finding(code=f.code, path=f.path, line=f.line,
                        ident=f.ident, message=f.message)
           for f in raw]
    out.sort(key=lambda f: (f.path, f.line, f.code, f.ident))
    return out


def _noqa_hit(f: core.Finding) -> bool:
    p = _PKG_ROOT / f.path
    try:
        lines = p.read_text().splitlines()
    except OSError:
        return False
    if not (1 <= f.line <= len(lines)):
        return False
    codes = core.noqa_codes(lines[f.line - 1])
    if codes is None:
        return False
    return not codes or f.code in codes


def build_report(raw_findings, baseline_file: Path | None = None,
                 use_baseline: bool = True) -> core.Report:
    """RaceFinding list -> core.Report with noqa/baseline applied."""
    if baseline_file is None:
        baseline_file = RACE_BASELINE
    entries = []
    if use_baseline and baseline_file and Path(baseline_file).exists():
        entries = core.parse_baseline(Path(baseline_file).read_text(),
                                      str(baseline_file))

    def match(f: core.Finding):
        # a race finding's reported path is whichever of the two access
        # sites the schedule surfaced first — entries may pin it, or use
        # path = "*" to match the ident wherever it lands
        for e in entries:
            if e["code"] == f.code and e["ident"] == f.ident \
                    and e["path"] in ("*", f.path):
                return e
        return None

    report = core.Report(findings=[])
    hit: set[int] = set()
    for f in to_findings(raw_findings):
        if _noqa_hit(f):
            report.noqa.append(f)
            continue
        e = match(f)
        if e is not None:
            hit.add(id(e))
            report.baselined.append(f)
            continue
        report.findings.append(f)
    # stale entries are informational only (see module docstring)
    report.stale_baseline = [e for e in entries if id(e) not in hit]
    return report


def render(report: core.Report, fmt: str = "text",
           sarif_prefix: str = "") -> str:
    if fmt == "text":
        out = report.render_text()
        # the summary line says cephlint; relabel without duplicating
        # the renderer
        return out.replace("cephlint:", "cephrace:")
    return core.render(report, fmt, sarif_prefix, tool="cephrace",
                       info_uri="docs/race_detection.md")
