"""Eraser-style lockset state machine with a happens-before filter.

Per shared variable (one instrumented object attribute) the classic
Eraser states:

    VIRGIN ──first access──> EXCLUSIVE(owner)
    EXCLUSIVE ──read by 2nd thread──> SHARED          (reads only: benign)
    EXCLUSIVE/SHARED ──write by 2nd thread──> SHARED_MOD

In SHARED/SHARED_MOD every access intersects the variable's candidate
lockset C(v) with the accessor's held locks.  A SHARED_MOD access with
C(v) = {} is an Eraser candidate race; pure Eraser would report it, but
fork/join, queue hand-off, and condition signalling all order accesses
without a common lock.  So candidates are filtered through vector
clocks: the report fires only when the current access is concurrent
with (not ordered after) the last conflicting access — the RaceTrack /
ThreadSanitizer-v1 hybrid that keeps Eraser's schedule-insensitivity
for genuinely unordered accesses while staying quiet for message-passing
discipline.

Locks are identified by *instance* (the runtime passes stable per-lock
tokens), not lockdep name: two PGs' same-named locks must not count as
a common lock.
"""
from __future__ import annotations

from dataclasses import dataclass, field

VIRGIN = 0
EXCLUSIVE = 1
SHARED = 2
SHARED_MOD = 3

_STATE_NAMES = {VIRGIN: "virgin", EXCLUSIVE: "exclusive",
                SHARED: "shared-read", SHARED_MOD: "shared-modified"}


@dataclass
class Access:
    """One attribute access, as the runtime saw it."""

    tid: int
    is_write: bool
    locks: frozenset          # tokens of locks held at the access
    vc_snap: tuple            # accessor's VectorClock.snapshot()
    where: str                # "rel/path.py:lineno in func"


@dataclass
class VarState:
    label: str                # "ClassName#ordinal.attr" (trace label)
    cls_name: str
    attr: str
    state: int = VIRGIN
    owner: int = -1
    lockset: frozenset | None = None   # None = universe (not yet narrowed)
    last_write: Access | None = None
    last_reads: dict[int, Access] = field(default_factory=dict)  # tid -> last
    reported: bool = False


@dataclass
class CandidateRace:
    var: VarState
    prior: Access
    current: Access
    kind: str                 # "write-write" | "read-write" | "write-read"


class LocksetMachine:
    """Owns every VarState; `record` returns a CandidateRace when an
    access is an unordered empty-lockset conflict (at most one per
    variable — later hits on the same variable stay quiet)."""

    def __init__(self) -> None:
        self.vars: dict[tuple[int, str], VarState] = {}

    def var_for(self, obj_key: int, label: str, cls_name: str,
                attr: str) -> VarState:
        v = self.vars.get((obj_key, attr))
        if v is None:
            v = VarState(label=label, cls_name=cls_name, attr=attr)
            self.vars[(obj_key, attr)] = v
        return v

    def record(self, v: VarState, acc: Access,
               current_vc) -> CandidateRace | None:
        """Advance v's state machine with `acc`; `current_vc` is the
        accessor's live VectorClock (used for the dominates test)."""
        try:
            if v.state == VIRGIN:
                v.state = EXCLUSIVE
                v.owner = acc.tid
                return None
            if v.state == EXCLUSIVE and acc.tid == v.owner:
                return None
            if v.state == EXCLUSIVE:
                # second thread arrives: leave EXCLUSIVE.  The candidate
                # lockset starts from THIS access's held set (Eraser
                # refinement: the first thread's accesses predate
                # sharing, so init writes don't poison the lockset), and
                # a read lands in SHARED — the classic init-then-shared-
                # read-only pattern stays benign until someone WRITES
                # after sharing.
                v.state = SHARED_MOD if acc.is_write else SHARED
                v.lockset = acc.locks
            else:
                if acc.is_write:
                    v.state = SHARED_MOD
                ls = v.lockset if v.lockset is not None else acc.locks
                v.lockset = ls & acc.locks
            if v.state != SHARED_MOD or v.reported:
                return None
            if v.lockset:          # a common lock still protects v
                return None
            prior = self._conflicting(v, acc)
            if prior is None:
                return None
            # happens-before filter: ordered accesses are not a race even
            # with an empty lockset (queue hand-off, fork/join, cond)
            if current_vc.dominates(prior.vc_snap):
                return None
            v.reported = True
            kind = ("write-write" if prior.is_write and acc.is_write
                    else "write-read" if prior.is_write else "read-write")
            return CandidateRace(var=v, prior=prior, current=acc, kind=kind)
        finally:
            if acc.is_write:
                v.last_write = acc
            else:
                v.last_reads[acc.tid] = acc

    @staticmethod
    def _conflicting(v: VarState, acc: Access) -> Access | None:
        """The most relevant prior conflicting access from ANOTHER thread:
        for a read, the last write; for a write, the last write else any
        last read."""
        lw = v.last_write
        if lw is not None and lw.tid != acc.tid:
            return lw
        if not acc.is_write:
            return None
        for tid, r in v.last_reads.items():
            if tid != acc.tid:
                return r
        return None

    @staticmethod
    def state_name(state: int) -> str:
        return _STATE_NAMES[state]
