"""Seeded scenarios the race CLI and the tier-1 gate drive.

Each scenario is a deterministic *plan* of cluster work executed under
an installed race_session; the PCT scheduler supplies the interleaving
pressure.  Scenario RNG is seeded with a string (hash-stable across
processes) so the workload — like the Thrasher's — replays from the
seed.

    thrash     LocalCluster + qa/thrasher.py events (kills, netsplits,
               EC EIO, corruption, mon churn) — the widest seam sweep
    mon_churn  repeated elections racing client I/O and mon commands —
               the mon send-loop / elector / paxos surface
    ec_io      EC writes/reads with seeded shard-read EIO — the OSD
               EC backend + recovery surface
"""
from __future__ import annotations

import random

from .runtime import DeadlockError, race_session
from .scheduler import make_scheduler


def _thrash(seed: int, events: int) -> dict:
    from ..thrasher import Thrasher
    from ..vstart import LocalCluster

    with LocalCluster(n_mons=3, n_osds=4) as c:
        c.create_ec_pool("race", k=2, m=1)
        th = Thrasher(c, seed, pool="race")
        th.run(events)
        th.quiesce()
    return {"thrash_events": events, "acked_writes": len(th.acked),
            "workload_digest": th.plan_digest(events)}


def _mon_churn(seed: int, events: int) -> dict:
    from ..vstart import LocalCluster

    rng = random.Random(f"cephrace-mon-churn-{seed}")
    churns = 0
    with LocalCluster(n_mons=3, n_osds=2) as c:
        c.create_replicated_pool("race_rc", size=2)
        io = c.client().open_ioctx("race_rc")
        for i in range(events):
            name = chr(ord("a") + rng.randrange(c.n_mons))
            mon = c.mons.get(name)
            if mon is not None and rng.random() < 0.7:
                mon.elector.start_election()
                churns += 1
            io.write_full(f"churn-{i}", bytes([i & 0xFF]) * 256)
            if rng.random() < 0.5:
                try:
                    io.read(f"churn-{rng.randrange(i + 1)}")
                except (IOError, OSError, TimeoutError, KeyError):
                    pass   # mid-election turbulence is the point
    return {"mon_churn_events": events, "elections": churns}


def _ec_io(seed: int, events: int) -> dict:
    from ...common.failpoint import registry
    from ..vstart import LocalCluster

    rng = random.Random(f"cephrace-ec-io-{seed}")
    eios = 0
    with LocalCluster(n_mons=1, n_osds=4) as c:
        c.create_ec_pool("race_ec", k=2, m=1)
        io = c.client().open_ioctx("race_ec")
        for i in range(events):
            if rng.random() < 0.4:
                osd = rng.randrange(c.n_osds)
                registry().add("osd.ec.shard_read", "times(1,error)",
                               match={"entity": f"osd.{osd}"})
                eios += 1
            payload = bytes(rng.getrandbits(8) for _ in range(512))
            io.write_full(f"ec-{i}", payload)
            got = io.read(f"ec-{i}")
            assert got == payload, f"ec readback mismatch on ec-{i}"
    return {"ec_io_events": events, "eio_injected": eios}


SCENARIOS = {
    "thrash": _thrash,
    "mon_churn": _mon_churn,
    "ec_io": _ec_io,
}

DEFAULT_EVENTS = {"thrash": 8, "mon_churn": 6, "ec_io": 10}


def run_scenario(name: str, seed: int, events: int | None = None,
                 sched: str = "perturb", depth: int = 3,
                 targets=None, target_dirs=None):
    """Run one scenario under the full detector; returns
    (RaceRuntime, scenario-extras dict)."""
    fn = SCENARIOS[name]
    n = events if events is not None else DEFAULT_EVENTS[name]
    scheduler = make_scheduler(sched, seed, depth) if sched != "none" else None
    with race_session(seed, scheduler=scheduler, targets=targets,
                      target_dirs=target_dirs) as rt:
        try:
            extras = fn(seed, n)
        except DeadlockError as e:
            # the cycle closed at an acquire made by the scenario's own
            # (main) thread: the CR2 finding is already recorded — this
            # is the detector SUCCEEDING, not the scenario crashing, so
            # the run must still report
            extras = {"scenario_aborted": f"deadlock: {e}"}
    extras["scenario"] = name
    extras["seed"] = seed
    extras["sched"] = sched
    if scheduler is not None:
        extras["sched_plan"] = scheduler.plan.describe()
        extras["sched_breaches"] = scheduler.breaches
    extras["trace_events"] = len(rt.trace.events)
    extras["trace_digest"] = rt.trace.digest()
    return rt, extras
