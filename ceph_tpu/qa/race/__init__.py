"""cephrace — dynamic data-race, deadlock, and lost-wakeup detection
(the runtime twin of cephlint's static CL1/CL2; docs/race_detection.md).

cephlint proves what it can about lock discipline from the AST; cephrace
watches an actual seeded run.  The division of labor:

- cephlint CL2 resolves which class families are multi-threaded from its
  cross-file symbol table.  cephrace *imports that answer* as its
  instrumentation target list (instrument.discover_targets) — static
  analysis feeds the dynamic detector, no hand-curated class list.
- common/lockdep.py's LockdepLock seam, threading.Thread/Condition and
  queue.Queue are instrumented to emit a sync-event trace with vector
  clocks (runtime.RaceRuntime).
- An Eraser-style lockset state machine (lockset.py) runs over attribute
  accesses of the instrumented classes; candidate races are filtered
  through happens-before so fork/join- or queue-ordered accesses stay
  quiet (the hybrid that keeps Eraser's sensitivity without its false
  positives).
- A seeded PCT-style scheduler (scheduler.py) perturbs interleavings at
  the instrumented sync points, so a short tier-1 run explores schedules
  a plain run never hits; the schedule plan is a pure function of the
  seed, replayable like qa/thrasher.py.
- Reporting reuses the analyzer's Finding/noqa/baseline/SARIF machinery
  (report.py; codes CR1 data race, CR2 deadlock, CR3 lost wakeup).

CLI: ``python -m ceph_tpu.qa.race --seed N --scenario thrash|mon_churn|ec_io``.
"""
from .events import VectorClock
from .runtime import DeadlockError, RaceRuntime, race_session

__all__ = ["VectorClock", "RaceRuntime", "DeadlockError", "race_session"]
