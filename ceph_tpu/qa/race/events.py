"""Vector clocks and the sync-event trace.

The trace is the detector's ground truth AND its replay artifact: every
record is built only from deterministic inputs (thread indices assigned
in registration order, lock names, per-class instance ordinals, monotone
sequence numbers) — no wall clocks, no memory addresses — so two runs of
the same seeded schedule produce byte-identical traces (the Thrasher's
replay property, extended to synchronization).
"""
from __future__ import annotations

from dataclasses import dataclass, field


class VectorClock:
    """Classic vector clock over small integer thread ids.

    Mutating ops (tick/join) are called only by the owning thread or
    under the runtime's state lock; snapshots taken for per-variable
    epochs are immutable tuples.
    """

    __slots__ = ("_c",)

    def __init__(self, init: dict[int, int] | None = None):
        self._c: dict[int, int] = dict(init) if init else {}

    def tick(self, tid: int) -> None:
        self._c[tid] = self._c.get(tid, 0) + 1

    def join(self, other: "VectorClock") -> None:
        oc = other._c
        c = self._c
        for k, v in oc.items():
            if v > c.get(k, 0):
                c[k] = v

    def copy(self) -> "VectorClock":
        return VectorClock(self._c)

    def snapshot(self) -> tuple[tuple[int, int], ...]:
        return tuple(sorted(self._c.items()))

    def get(self, tid: int) -> int:
        return self._c.get(tid, 0)

    def dominates(self, snap: tuple[tuple[int, int], ...]) -> bool:
        """True iff this clock has seen every component of `snap`
        (i.e. the snapshotted event happens-before the current state)."""
        c = self._c
        for tid, v in snap:
            if v > c.get(tid, 0):
                return False
        return True

    def __repr__(self) -> str:  # debug only
        return f"VC{dict(sorted(self._c.items()))}"


@dataclass
class Event:
    """One sync/memory event.  `seq` is the global trace order; all other
    fields are schedule-deterministic labels."""

    seq: int
    tid: int
    kind: str      # acquire|release|thread_start|thread_join|q_put|q_get|
                   # cond_wait|cond_wake|cond_timeout|notify|read|write|
                   # sched (scheduler decisions)
    target: str    # lock name, queue label, "ClassName#ordinal.attr", ...
    where: str = ""  # "rel/path.py:lineno" of the instrumented call site

    def as_tuple(self) -> tuple:
        return (self.seq, self.tid, self.kind, self.target, self.where)


@dataclass
class Trace:
    """Bounded in-memory event log (the whole run for tier-1-sized
    scenarios; the cap only guards pathological soaks)."""

    max_events: int = 500_000
    events: list[Event] = field(default_factory=list)
    dropped: int = 0

    def append(self, ev: Event) -> None:
        if len(self.events) >= self.max_events:
            self.dropped += 1
            return
        self.events.append(ev)

    def as_tuples(self) -> list[tuple]:
        return [e.as_tuple() for e in self.events]

    def digest(self) -> str:
        """Stable content hash for replay comparison in logs/CLI output."""
        import hashlib

        h = hashlib.sha256()
        for e in self.events:
            h.update(repr(e.as_tuple()).encode())
        return h.hexdigest()[:16]
