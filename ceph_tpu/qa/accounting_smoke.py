"""cephmeter CI smoke: per-client accounting + slow-op forensics end to
end (qa/ci_gate.sh step 7; ISSUE 11 acceptance).

Drives the WHOLE surface through the production path, no shortcuts:

1. a 2-client LocalCluster (mgr hosted) with ``trace_sampling_rate=0``
   and tail sampling armed — two named clients write through an EC
   pool;
2. the prometheus exporter must render per-(client,pool) **labeled**
   series for BOTH clients, and the per-client ``bytes_w`` sums must
   equal the aggregate ``osd.op_w_bytes`` within tolerance (attribution
   conserves bytes);
3. the ``perf history`` mon command must answer with per-daemon samples
   from the mgr's metrics-history digest;
4. a failpoint-delayed op (``osd.write_batcher.flush`` = delay) must
   cross the complaint time and surface in ``dump_historic_slow_ops``
   over a real admin socket — with per-stage attribution, a dominant
   stage, and (tail promotion: the head coin flip said NO to every op)
   an assembled trace artifact spanning more than one entity.

Exit 0 on success; 1 with a `problems` list otherwise.  Prints one JSON
summary on stdout (the gate archives it next to the SARIF artifacts).
"""
from __future__ import annotations

import json
import os
import sys
import time


from .smoke_util import scrape as _scrape, wait_for as _wait


def _labeled_value(body: str, metric: str, **labels) -> float:
    """Sum of a labeled metric's samples matching every given label."""
    total = 0.0
    for line in body.splitlines():
        if not line.startswith(metric + "{"):
            continue
        if all(f'{k}="{v}"' in line for k, v in labels.items()):
            total += float(line.rsplit(" ", 1)[1])
    return total


def main() -> int:
    import jax

    # this box's sitecustomize pins the tunneled TPU backend and IGNORES
    # the JAX_PLATFORMS env var; config.update is the reliable spelling
    jax.config.update("jax_platforms", "cpu")

    import tempfile

    from ..common.admin_socket import admin_socket_command
    from ..common.failpoint import registry as fp_registry
    from ..common.tracer import TRACER
    from ..qa.vstart import LocalCluster

    problems: list[str] = []
    summary: dict = {}
    asok_dir = tempfile.mkdtemp(prefix="ceph_tpu_acct_")
    TRACER.enable(False)
    TRACER.clear()
    overrides = {
        "mgr_report_interval": 0.2,
        "mgr_digest_interval": 0.2,
        "mgr_stale_report_age": 30.0,
        "trace_enabled": True,
        "trace_sampling_rate": 0.0,   # head sampling OFF: tail must win
        "trace_tail_latency_ms": 150.0,
        "osd_op_complaint_time": 0.3,
        "osd_slow_op_window": 120.0,
        "admin_socket": os.path.join(asok_dir, "$name.asok"),
    }
    n_writes, wsize = 12, 4096

    with LocalCluster(n_mons=1, n_osds=4, with_mgr=True,
                      conf_overrides=overrides) as c:
        c.create_ec_pool("acct", k=2, m=1, pg_num=8)
        alpha = c.client("client.alpha").open_ioctx("acct")
        beta = c.client("client.beta").open_ioctx("acct")
        for i in range(n_writes):
            alpha.write_full(f"a{i}", b"a" * wsize)
            beta.write_full(f"b{i}", b"b" * wsize)

        # -- labeled series on the exporter ---------------------------
        url = c.mgr.module("prometheus").url
        # accounting counts len(b64_payload) * 3 // 4 — the same basis
        # as the aggregate op_w_bytes counter it must reconcile with
        expect = n_writes * (((wsize + 2) // 3 * 4) * 3 // 4)

        def labeled_ready() -> bool:
            body = _scrape(url)
            return (_labeled_value(body, "ceph_client_io_ops",
                                   client="client.alpha") >= n_writes
                    and _labeled_value(body, "ceph_client_io_ops",
                                       client="client.beta") >= n_writes)

        if not _wait(labeled_ready, timeout=20.0):
            problems.append("labeled per-client series never reached the "
                            "exporter")
        body = _scrape(url)
        a_bytes = _labeled_value(body, "ceph_client_io_bytes_w",
                                 client="client.alpha")
        b_bytes = _labeled_value(body, "ceph_client_io_bytes_w",
                                 client="client.beta")
        agg = _labeled_value(body, "ceph_osd_op_w_bytes")
        summary["alpha_bytes_w"] = a_bytes
        summary["beta_bytes_w"] = b_bytes
        summary["aggregate_op_w_bytes"] = agg
        if agg <= 0:
            problems.append("aggregate op_w_bytes is zero")
        elif abs((a_bytes + b_bytes) - agg) > 0.05 * agg:
            problems.append(
                f"per-client bytes {a_bytes}+{b_bytes} do not sum to the "
                f"aggregate {agg} within 5%")
        if abs(a_bytes - expect) > 0.05 * max(expect, 1):
            problems.append(f"alpha bytes_w {a_bytes} != expected "
                            f"~{expect}")

        # -- perf history through the mon -----------------------------
        def history_ready() -> bool:
            rv, res = c.mon_command({"prefix": "perf history"})
            return rv == 0 and bool((res or {}).get("daemons"))

        if not _wait(history_ready, timeout=15.0):
            problems.append("`perf history` never answered with daemons")
        else:
            rv, res = c.mon_command(
                {"prefix": "perf history", "name": "osd.op"})
            if rv != 0 or not res.get("daemons"):
                problems.append(f"`perf history osd.op` failed: {rv} {res}")
            else:
                summary["history_daemons"] = sorted(res["daemons"])

        # -- failpoint-delayed op -> dump_historic_slow_ops -----------
        fp_registry().set("osd.write_batcher.flush", "times(1,delay(0.5))")
        try:
            alpha.write_full("slowpoke", b"s" * wsize)
        finally:
            fp_registry().set("osd.write_batcher.flush", "off")

        def find_slow() -> dict | None:
            for i in c.osds:
                asok = os.path.join(asok_dir, f"osd.{i}.asok")
                try:
                    dump = admin_socket_command(
                        asok, "dump_historic_slow_ops")
                except (OSError, ValueError):
                    continue
                for op in dump.get("ops", []):
                    if "slowpoke" in op.get("description", ""):
                        return op
            return None

        slow_op = None
        if not _wait(lambda: find_slow() is not None, timeout=10.0):
            problems.append("delayed op never surfaced in "
                            "dump_historic_slow_ops")
        else:
            slow_op = find_slow()
        if slow_op is not None:
            summary["slow_op"] = {
                "description": slow_op.get("description"),
                "duration": slow_op.get("duration"),
                "dominant_stage": slow_op.get("dominant_stage"),
                "trace_entities":
                    (slow_op.get("trace") or {}).get("entities"),
                "trace_spans":
                    (slow_op.get("trace") or {}).get("num_spans"),
            }
            if not slow_op.get("stages"):
                problems.append("slow op carries no per-stage attribution")
            if not slow_op.get("dominant_stage"):
                problems.append("slow op names no dominant stage")
            trace = slow_op.get("trace") or {}
            if not trace.get("num_spans"):
                problems.append(
                    "slow op has no trace artifact (tail promotion with "
                    "trace_sampling_rate=0 failed)")
            elif len(trace.get("entities") or []) < 2:
                problems.append(
                    f"slow op's trace is not cross-entity: "
                    f"{trace.get('entities')}")

    TRACER.enable(False)
    TRACER.clear()
    summary["problems"] = problems
    print(json.dumps(summary, indent=2, default=str))
    return 1 if problems else 0


if __name__ == "__main__":
    sys.exit(main())
