"""Backend-health CI smoke: forced wedge -> health checks raise ->
recovery -> checks clear (qa/ci_gate.sh step 5; ISSUE 10 acceptance).

Drives the WHOLE surface through the production path, no shortcuts:

1. arm the simulated wedge through the sentinel's env probe override
   (``CEPH_TPU_SENTINEL_STATE=degraded:...`` — the probe never touches
   jax) and latch a codec fallback through the telemetry registry;
2. start a LocalCluster (mgr hosted) with a fast sentinel cadence and
   wait for ``health detail`` to report **TPU_BACKEND_DEGRADED** and
   **KERNEL_FALLBACK_LATCHED** — i.e. OSD probe -> `_mgr_report` ->
   status-module digest -> mon `_health`, end to end;
3. scrape the mgr prometheus exporter and assert ``ceph_health_status``
   is 1 (WARN) with a ``ceph_health_detail`` series per check;
4. smoke-check the ``dump_kernel_telemetry`` admin-command JSON schema
   over a real admin socket;
5. flip the probe override to ``ok`` + clear the fallback latch via the
   ``clear_kernel_fallback`` admin command, and wait for BOTH checks to
   clear.

Exit 0 on success; 1 with a `problems` list otherwise.  Prints one JSON
summary on stdout (the gate archives it next to the SARIF artifacts).
"""
from __future__ import annotations

import contextlib
import json
import os
import sys
import time


from .smoke_util import assert_no_leaked_threads, wait_for as _wait


def main() -> int:
    import jax

    # this box's sitecustomize pins the tunneled TPU backend and IGNORES
    # the JAX_PLATFORMS env var; config.update is the reliable spelling
    # (tests/conftest.py) — the smoke must never touch the tunnel
    jax.config.update("jax_platforms", "cpu")

    os.environ["CEPH_TPU_SENTINEL_STATE"] = "degraded:ci simulated wedge"

    from ..common.admin_socket import admin_socket_command
    from ..common.kernel_telemetry import TELEMETRY
    from ..qa.vstart import LocalCluster

    problems: list[str] = []
    summary: dict = {}
    TELEMETRY.record_fallback(
        "gf_apply", "ci simulated mosaic failure", frm="pallas", to="xla")

    import tempfile

    asok_dir = tempfile.mkdtemp(prefix="ceph_tpu_health_")
    overrides = {
        "backend_sentinel_interval": 0.2,
        "backend_sentinel_timeout": 0.5,
        "mgr_report_interval": 0.2,
        "mgr_digest_interval": 0.2,
        "mgr_stale_report_age": 30.0,
        "admin_socket": os.path.join(asok_dir, "$name.asok"),
    }

    def checks() -> dict:
        rv, res = c.mon_command({"prefix": "health detail"})
        if rv != 0 or not isinstance(res, dict):
            return {}
        return (res.get("health") or {}).get("checks") or {}

    # Runtime twin of the CL13/CL14 lints: every thread bring-up starts
    # must be gone after teardown.  Held open across the whole cluster
    # lifecycle; closed below so a leak lands in `problems` (the JSON
    # summary still renders) instead of a bare traceback.
    leak_gate = contextlib.ExitStack()
    leak_gate.enter_context(assert_no_leaked_threads())
    with LocalCluster(n_mons=1, n_osds=2, with_mgr=True,
                      conf_overrides=overrides) as c:
        # -- raise ----------------------------------------------------
        if not _wait(lambda: {"TPU_BACKEND_DEGRADED",
                              "KERNEL_FALLBACK_LATCHED"} <= set(checks()),
                     timeout=20.0):
            problems.append(
                f"wedged checks did not raise; got {sorted(checks())}")
        summary["raised_checks"] = sorted(checks())

        # -- prometheus while degraded --------------------------------
        try:
            import urllib.request

            url = c.mgr.module("prometheus").url
            body = urllib.request.urlopen(url, timeout=10).read().decode()
            if "ceph_health_status 1" not in body:
                problems.append("prometheus: ceph_health_status != 1 "
                                "while degraded")
            for name in ("TPU_BACKEND_DEGRADED", "KERNEL_FALLBACK_LATCHED"):
                if f'ceph_health_detail{{name="{name}"' not in body:
                    problems.append(f"prometheus: no ceph_health_detail "
                                    f"series for {name}")
        except Exception as e:
            problems.append(f"prometheus scrape failed: {e!r}")

        # -- dump_kernel_telemetry schema over the admin socket -------
        asok = os.path.join(asok_dir, "osd.0.asok")
        try:
            dump = admin_socket_command(asok, "dump_kernel_telemetry")
            for key in ("enabled", "kernels", "fallback", "sentinel",
                        "events"):
                if key not in dump:
                    problems.append(
                        f"dump_kernel_telemetry missing {key!r}")
            if (dump.get("sentinel") or {}).get("state") != "degraded":
                problems.append("dump_kernel_telemetry sentinel state "
                                f"!= degraded: {dump.get('sentinel')}")
            if "gf_apply" not in (dump.get("fallback") or {}):
                problems.append("dump_kernel_telemetry fallback latch "
                                "missing gf_apply")
            summary["telemetry_kernels"] = sorted(dump.get("kernels") or {})
        except Exception as e:
            problems.append(f"dump_kernel_telemetry failed: {e!r}")

        # -- recover --------------------------------------------------
        os.environ["CEPH_TPU_SENTINEL_STATE"] = "ok"
        try:
            res = admin_socket_command(asok, "clear_kernel_fallback")
            if not res.get("cleared"):
                problems.append(f"clear_kernel_fallback: {res}")
        except Exception as e:
            problems.append(f"clear_kernel_fallback failed: {e!r}")
        if not _wait(lambda: not ({"TPU_BACKEND_DEGRADED",
                                   "KERNEL_FALLBACK_LATCHED"}
                                  & set(checks())), timeout=20.0):
            problems.append(
                f"checks did not clear after recovery; "
                f"still {sorted(checks())}")
        summary["cleared_checks"] = sorted(checks())

    try:
        leak_gate.close()
    except AssertionError as e:
        problems.append(str(e))

    summary["problems"] = problems
    print(json.dumps(summary, indent=2, default=str))
    return 1 if problems else 0


if __name__ == "__main__":
    sys.exit(main())
