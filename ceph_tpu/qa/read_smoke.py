"""cephread CI smoke: the coalesced READ plane end to end (qa/ci_gate.sh
step 13; ISSUE 17 acceptance).

Five gates, one JSON summary:

1. **batched >= 3x per-op** — the in-process decode-plane scenario
   (``bench/traffic.py run_read_scenario``): 32 closed-loop CPU clients
   issuing 1 KiB degraded reads, batched plane vs the historical one
   dispatch per op.  Small hot-object GETs are the coalescing sweet
   spot (per-op decode dispatch is fixed-cost); the bar is the ISSUE's
   >= 3x aggregate throughput ratio.  One retry absorbs CI-host noise.
2. **GET-heavy cluster scenario** — a real ``LocalCluster``, shared hot
   working set, read cache armed: every byte verified, the hot set
   promotes (cache hits move) and reads ride coalesced flushes.
3. **boot storm** — per-client private image sets (zero cross-client
   locality): still zero mismatches, still coalesced.
4. **degraded p99** — one OSD down and out with no spare, every PG
   decoding forever: reads stay correct and p99 stays under a loose
   CI bar (the point is "no timeout-shaped cliff", not a perf number).
5. **ranged degraded decode accounting** — a chunk-interior ranged read
   with a dead data shard dispatches exactly k x window bytes into the
   decode kernel (``read_batch_decode`` telemetry), not k x L.

Exit 0 on success; 1 with a `problems` list otherwise.  Prints one JSON
summary on stdout (the gate archives it as read_smoke.json).
"""
from __future__ import annotations

import json
import sys

SPEEDUP_BAR = 3.0
DEGRADED_P99_BAR_MS = 500.0


def _decode_bytes_in() -> int:
    from ..common.kernel_telemetry import TELEMETRY

    return TELEMETRY.dump().get("read_batch_decode", {}).get("bytes_in", 0)


def check_speedup(summary: dict, problems: list[str]) -> None:
    from ..bench.traffic import run_read_scenario

    best: dict = {}
    for attempt in range(2):
        res = run_read_scenario(n_clients=32, seconds=2.0, read_size=1024)
        if not best or res["read_batch_speedup"] > best["read_batch_speedup"]:
            best = res
        if best["read_batch_speedup"] >= SPEEDUP_BAR:
            break
    summary["speedup"] = {
        k: best[k] for k in
        ("read_batch_speedup", "read_batched_gibps", "read_perop_gibps",
         "read_batched_p99_ms", "read_perop_p99_ms", "read_ops_per_flush",
         "read_clients", "read_size")
    }
    if best["read_batch_speedup"] < SPEEDUP_BAR:
        problems.append(
            f"batched read plane only {best['read_batch_speedup']}x per-op "
            f"(bar: >= {SPEEDUP_BAR}x)")
    if best["read_ops_per_flush"] < 2.0:
        problems.append(
            f"flushes barely coalesce ({best['read_ops_per_flush']} "
            f"ops/flush)")


def check_get_heavy(summary: dict, problems: list[str]) -> None:
    from ..bench.traffic import run_cluster_read_traffic

    res = run_cluster_read_traffic(
        n_clients=4, seconds=1.5, read_size=4096, scenario="get",
        conf_overrides={"osd_read_cache_bytes": 1 << 20,
                        "osd_read_cache_promote_ops": 4})
    summary["get_heavy"] = {k: res[k] for k in
                            ("ops", "ops_per_s", "p99_ms", "mismatches",
                             "read_batcher", "read_cache")}
    if res["mismatches"]:
        problems.append(
            f"GET scenario returned {res['mismatches']} corrupt reads")
    if res["ops"] <= 0:
        problems.append("GET scenario completed no reads")
    if res["read_batcher"]["flushes"] <= 0:
        problems.append("GET scenario never flushed the read batcher")
    if res["read_cache"]["hits"] <= 0:
        problems.append(
            "hot working set never promoted into the read cache "
            f"(hits=0, inserts={res['read_cache']['inserts']})")


def check_boot_storm(summary: dict, problems: list[str]) -> None:
    from ..bench.traffic import run_cluster_read_traffic

    res = run_cluster_read_traffic(
        n_clients=4, seconds=1.5, read_size=4096, scenario="boot")
    summary["boot_storm"] = {k: res[k] for k in
                             ("ops", "ops_per_s", "p99_ms", "mismatches",
                              "read_batcher")}
    if res["mismatches"]:
        problems.append(
            f"boot storm returned {res['mismatches']} corrupt reads")
    if res["ops"] <= 0:
        problems.append("boot storm completed no reads")
    if res["read_batcher"]["ops"] <= 0:
        problems.append("boot storm never crossed the read batcher")


def check_degraded_p99(summary: dict, problems: list[str]) -> None:
    from ..bench.traffic import run_cluster_read_traffic

    res = run_cluster_read_traffic(
        n_clients=4, seconds=1.5, read_size=4096, k=2, m=1, degraded=True)
    summary["degraded"] = {k: res[k] for k in
                           ("ops", "ops_per_s", "p50_ms", "p99_ms",
                            "mismatches")}
    if res["mismatches"]:
        problems.append(
            f"degraded reads returned {res['mismatches']} corrupt payloads")
    if res["ops"] <= 0:
        problems.append("degraded scenario completed no reads")
    if res["p99_ms"] > DEGRADED_P99_BAR_MS:
        problems.append(
            f"degraded read p99 {res['p99_ms']}ms over the "
            f"{DEGRADED_P99_BAR_MS}ms bar")


def check_ranged_accounting(summary: dict, problems: list[str]) -> None:
    import numpy as np

    from ..ec.registry import ErasureCodePluginRegistry
    from ..osd.osdmap import object_ps
    from .vstart import LocalCluster

    conf = {"osd_subop_reply_timeout": 1.5}
    with LocalCluster(n_mons=1, n_osds=6, conf_overrides=conf) as c:
        c.create_ec_pool("rs", k=4, m=2, pg_num=4)
        io = c.client().open_ioctx("rs")
        rng = np.random.default_rng(17)
        payload = rng.integers(0, 256, 8192, np.uint8).tobytes()
        io.write_full("obj", payload)
        m = c._leader().osdmon.osdmap
        pid = next(i for i, p in m.pools.items() if p.name == "rs")
        ps = object_ps("obj", m.pools[pid].pg_num)
        _up, _upp, acting, primary = m.pg_to_up_acting_osds(pid, ps)
        victim = next(acting[j] for j in range(4)
                      if acting[j] >= 0 and acting[j] != primary)
        c.kill_osd(victim)
        codec = ErasureCodePluginRegistry.instance().factory(
            {"plugin": "jax", "k": "4", "m": "2"})
        chunk = codec.get_chunk_size(len(payload))
        off, ln = chunk + 37, 101            # interior of data chunk 1
        b0 = _decode_bytes_in()
        got = io.read("obj", off=off, length=ln)
        ranged_in = _decode_bytes_in() - b0
        summary["ranged"] = {"window_bytes": ln, "chunk_bytes": chunk,
                             "decode_bytes_in": ranged_in,
                             "expected_bytes_in": 4 * ln}
        if got != payload[off:off + ln]:
            problems.append("ranged degraded read returned wrong bytes")
        if ranged_in != 4 * ln:
            problems.append(
                f"ranged degraded decode dispatched {ranged_in} bytes "
                f"into the kernel, expected k x window = {4 * ln}")


def main(argv=None) -> int:
    problems: list[str] = []
    summary: dict = {"scenario": "read_smoke"}
    for check in (check_speedup, check_get_heavy, check_boot_storm,
                  check_degraded_p99, check_ranged_accounting):
        try:
            check(summary, problems)
        except Exception as exc:  # a crashed stage is a failed gate
            problems.append(f"{check.__name__} crashed: {exc!r}")
    summary["problems"] = problems
    print(json.dumps(summary, indent=2, default=str))
    return 1 if problems else 0


if __name__ == "__main__":
    sys.exit(main())
