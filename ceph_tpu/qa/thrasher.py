"""Deterministic seeded Thrasher + cluster InvariantChecker (reference:
qa/tasks/thrashosds.py / ceph_manager.py's Thrasher, rebuilt on the
failpoint registry; docs/fault_injection.md).

The thrasher separates PLANNING from EXECUTION:

- ``plan(n_events)`` derives an event schedule purely from the seed and
  the thrasher's own bookkeeping (which OSDs it has killed, which pairs
  it has split, which objects it has written).  No cluster state, no
  clocks, no thread timing feeds it, so the same seed yields the same
  event log bit-for-bit, every time — the replay property chaos findings
  need to be debuggable.
- ``run(n_events)`` executes that schedule against a LocalCluster:
  kill/revive (real daemon death), netsplits (failpoint-dropped frames
  between OSD pairs), EC shard EIO, at-rest shard corruption, mon
  election churn — interleaved with client writes and reads whose
  acknowledged payloads are remembered for the checker.

Outcomes (did a write ack? did a read succeed?) are deliberately NOT part
of the event log: they depend on scheduling and wall clocks.  The log is
the schedule; the ``acked`` dict is the contract the InvariantChecker
holds the cluster to after quiesce:

    1. zero acknowledged-write loss (every acked payload reads back),
    2. every PG of the pool active+clean (LocalCluster._all_clean),
    3. a clean scrub (after one repair pass heals injected corruption),
    4. replay determinism (re-planning the same seed reproduces the log).

    with LocalCluster(n_mons=3, n_osds=5) as c:
        c.create_ec_pool("th", k=2, m=1)
        th = Thrasher(c, seed=1234, pool="th")
        th.run(24)
        th.quiesce()
        InvariantChecker(c, "th").check(th)
"""
from __future__ import annotations

import random
import time
import zlib

from ..common.failpoint import registry


def _pairs(alive: set[int]) -> list[tuple[int, int]]:
    """All (low, high) OSD pairs over the alive set, sorted."""
    ordered = sorted(alive)
    return [
        (a, b) for i, a in enumerate(ordered) for b in ordered[i + 1:]
    ]


# event kinds in FIXED declaration order — the weighted draw walks this
# list, so reordering it changes every schedule (bump seeds if you must)
_KINDS = (
    ("write", 5),
    ("read", 2),
    ("kill", 3),
    ("revive", 3),
    ("netsplit", 2),
    ("heal", 2),
    ("ec_eio", 2),
    ("corrupt", 2),
    ("mon_churn", 1),
)


class Thrasher:
    """Seeded chaos driver.  `cluster` may be None for plan-only use
    (the seed-determinism tests); then `n_osds`/`n_mons` describe the
    topology the schedule is for."""

    def __init__(self, cluster, seed: int, pool: str = "thrash",
                 n_osds: int | None = None, n_mons: int | None = None,
                 max_dead: int = 1, max_splits: int = 1,
                 object_size: int = 1024):
        self.cluster = cluster
        self.seed = seed
        self.pool = pool
        self.n_osds = n_osds if n_osds is not None else cluster.n_osds
        self.n_mons = n_mons if n_mons is not None else cluster.n_mons
        self.max_dead = max_dead
        self.max_splits = max_splits
        self.object_size = object_size
        self.events: list[tuple] = []
        #: oid -> payload for every write the cluster ACKED
        self.acked: dict[str, bytes] = {}
        self._payloads: dict[str, bytes] = {}
        self._fp_tokens: list[tuple[str, int]] = []   # (name, entry id)
        self._split_tokens: dict[tuple[int, int], list] = {}
        self._io = None
        self._client = None

    # -- planning (pure) ---------------------------------------------------
    def plan(self, n_events: int) -> list[tuple]:
        """Deterministic schedule of `n_events` events for this seed.
        Also (re)fills self._payloads with each planned write's bytes."""
        rng = random.Random(self.seed)
        alive = set(range(self.n_osds))
        dead: set[int] = set()
        splits: set[tuple[int, int]] = set()
        written: list[str] = []
        self._payloads = {}  # noqa: CL11 — reset of the expected-state mirror verify() reads; same (seed, shape) rebuilds it identically
        events: list[tuple] = []
        wseq = 0

        def write_event():
            nonlocal wseq
            oid = f"thrash-{self.seed}-{wseq}"
            wseq += 1
            payload = bytes(rng.getrandbits(8)
                            for _ in range(self.object_size))
            self._payloads[oid] = payload
            written.append(oid)
            return ("write", oid, self.object_size,
                    zlib.crc32(payload) & 0xFFFFFFFF)

        # prime: the first event is always a write so read/corrupt events
        # have targets whatever the seed says
        events.append(write_event())
        while len(events) < n_events:
            kinds, weights = [], []
            for kind, w in _KINDS:
                if kind == "kill" and not (
                    len(dead) < self.max_dead and len(alive) > 1
                ):
                    continue
                if kind == "revive" and not dead:
                    continue
                if kind == "netsplit":
                    # only pairs not already split are eligible — a
                    # duplicate pair would double-arm the drop entries
                    # and leak the first set past heal/quiesce
                    unsplit = [
                        p for p in _pairs(alive) if p not in splits
                    ]
                    if len(splits) >= self.max_splits or not unsplit:
                        continue
                if kind == "heal" and not splits:
                    continue
                if kind in ("ec_eio", "corrupt") and not alive:
                    continue
                if kind == "corrupt" and not written:
                    continue
                if kind == "read" and not written:
                    continue
                if kind == "mon_churn" and self.n_mons < 2:
                    continue
                kinds.append(kind)
                weights.append(w)
            kind = rng.choices(kinds, weights=weights)[0]
            if kind == "write":
                events.append(write_event())
            elif kind == "read":
                events.append(("read", rng.choice(written)))
            elif kind == "kill":
                victim = rng.choice(sorted(alive))
                alive.discard(victim)
                dead.add(victim)
                events.append(("kill", victim))
            elif kind == "revive":
                back = rng.choice(sorted(dead))
                dead.discard(back)
                alive.add(back)
                events.append(("revive", back))
            elif kind == "netsplit":
                pair = rng.choice(
                    [p for p in _pairs(alive) if p not in splits]
                )
                splits.add(pair)
                events.append(("netsplit",) + pair)
            elif kind == "heal":
                pair = rng.choice(sorted(splits))
                splits.discard(pair)
                events.append(("heal",) + pair)
            elif kind == "ec_eio":
                osd = rng.choice(sorted(alive))
                events.append(("ec_eio", osd, rng.randint(1, 4)))
            elif kind == "corrupt":
                events.append(
                    ("corrupt", rng.choice(sorted(alive)),
                     rng.choice(written))
                )
            elif kind == "mon_churn":
                events.append(
                    ("mon_churn", chr(ord("a") + rng.randrange(self.n_mons)))
                )
        return events

    def plan_digest(self, n_events: int) -> str:
        """Stable fingerprint of plan(n_events) — cheap cross-process
        replay verification (cephrace embeds it in its run metadata so a
        finding's workload can be matched to a re-run bit-for-bit)."""
        import hashlib

        h = hashlib.sha256()
        for ev in self.plan(n_events):
            h.update(repr(ev).encode())
        return h.hexdigest()[:16]

    # -- execution ---------------------------------------------------------
    def run(self, n_events: int) -> list[tuple]:
        """Plan and execute `n_events`; returns the event log (identical
        to plan(n_events) for the same seed, by construction)."""
        assert self.cluster is not None, "plan-only thrasher (no cluster)"
        events = self.plan(n_events)
        self.events = []
        self._client = self.cluster.client(f"client.thrash-{self.seed}")
        self._io = self._client.open_ioctx(self.pool)
        for ev in events:
            self.events.append(ev)
            self._execute(ev)
        return self.events

    def _execute(self, ev: tuple) -> None:
        c = self.cluster
        kind = ev[0]
        if kind == "write":
            _, oid, _size, _crc = ev
            payload = self._payloads[oid]
            try:
                self._io.write_full(oid, payload)
            except (IOError, OSError, TimeoutError):
                return  # not acked: the checker must not expect it
            self.acked[oid] = payload
        elif kind == "read":
            oid = ev[1]
            try:
                got = self._io.read(oid)
            except (IOError, OSError, TimeoutError, KeyError):
                return  # unreadable mid-chaos is legal; silent loss isn't
            if oid in self.acked:
                assert got == self.acked[oid], (
                    f"acked write {oid} read back wrong mid-thrash"
                )
        elif kind == "kill":
            osd = ev[1]
            if osd in c.osds:
                c.kill_osd(osd)
                self._mon_cmd_retry(
                    {"prefix": "osd down", "id": osd},
                    {"prefix": "osd out", "id": osd},
                )
        elif kind == "revive":
            osd = ev[1]
            if osd not in c.osds:
                c.revive_osd(osd)
                self._mon_cmd_retry({"prefix": "osd in", "id": osd})
        elif kind == "netsplit":
            a, b = ev[1], ev[2]
            if (a, b) in self._split_tokens:
                return  # already split: never orphan armed entries
            reg = registry()
            toks = []
            for src, dst in ((a, b), (b, a)):
                toks.append(reg.add(
                    "msgr.frame.recv", "error",
                    match={"entity": f"osd.{src}", "peer": f"osd.{dst}"},
                ))
            self._split_tokens[(a, b)] = toks
        elif kind == "heal":
            self._heal(ev[1], ev[2])
        elif kind == "ec_eio":
            osd, n = ev[1], ev[2]
            eid = registry().add(
                "osd.ec.shard_read", f"times({n},error)",
                match={"entity": f"osd.{osd}"},
            )
            self._fp_tokens.append(("osd.ec.shard_read", eid))
        elif kind == "corrupt":
            self._corrupt(ev[1], ev[2])
        elif kind == "mon_churn":
            mon = c.mons.get(ev[1])
            if mon is not None:
                mon.elector.start_election()

    def _heal(self, a: int, b: int) -> None:
        toks = self._split_tokens.pop((a, b), [])
        for eid in toks:
            registry().remove("msgr.frame.recv", eid=eid)

    def _corrupt(self, osd_id: int, oid: str) -> None:
        """Scribble over ONE stored copy of `oid` on `osd_id` without
        touching its digest xattr — exactly the at-rest rot deep scrub
        exists to find (and repair from the surviving shards)."""
        from ..store.object_store import Transaction

        osd = self.cluster.osds.get(osd_id)
        if osd is None:
            return
        try:
            for cid in osd.store.list_collections():
                if oid not in osd.store.list_objects(cid):
                    continue
                t = Transaction()
                t.write(cid, oid, 0, b"\xde\xad\xbe\xef" * 4)
                osd.store.queue_transaction(t)
                return
        except (IOError, OSError, KeyError):
            pass  # racing a kill/delete: the corruption just didn't land

    def _mon_cmd_retry(self, *cmds: dict, tries: int = 3) -> None:
        """Mon commands ride through election churn: retry a few times,
        then give up (failure detection will converge on its own)."""
        for cmd in cmds:
            for i in range(tries):
                try:
                    rv, _res = self.cluster.mon_command(cmd)
                    if rv == 0:
                        break
                except (IOError, OSError, TimeoutError):
                    pass
                time.sleep(0.5 * (i + 1))

    # -- teardown ----------------------------------------------------------
    def quiesce(self, timeout: float = 90.0) -> None:
        """Withdraw every injection, revive every victim, and wait for
        the pool to settle — the precondition for invariant checks."""
        c = self.cluster
        for a, b in list(self._split_tokens):
            self._heal(a, b)
        for name, eid in self._fp_tokens:
            registry().remove(name, eid=eid)
        self._fp_tokens.clear()
        for osd in range(self.n_osds):
            if osd not in c.osds:
                c.revive_osd(osd)
            self._mon_cmd_retry({"prefix": "osd in", "id": osd})
        c.wait_clean(self.pool, timeout=timeout)


class InvariantChecker:
    """Post-quiesce cluster invariants (the thrasher's acceptance gate)."""

    def __init__(self, cluster, pool: str):
        self.cluster = cluster
        self.pool = pool

    def _pool_pgs(self):
        leader = self.cluster._leader()
        m = leader.osdmon.osdmap
        pid = next(i for i, p in m.pools.items() if p.name == self.pool)
        return m, pid, m.pools[pid]

    def check(self, thrasher: Thrasher, timeout: float = 90.0) -> dict:
        """Assert all four invariants; returns a small report dict."""
        report = {
            "acked_writes": len(thrasher.acked),
            "scrub_errors_repaired": 0,
        }
        # 1. PGs active+clean (version-agreeing, content-complete shards)
        self.cluster.wait_clean(self.pool, timeout=timeout)
        # 2. zero acknowledged-write loss
        io = thrasher._io
        for oid in sorted(thrasher.acked):
            got = io.read(oid)
            assert got == thrasher.acked[oid], (
                f"acknowledged write {oid} lost or corrupted after quiesce"
            )
        # 3. scrub: one repair pass may heal injected at-rest corruption;
        # the verification pass must then be spotless
        m, pid, pool = self._pool_pgs()
        for repair in (True, False):
            errors = []
            for ps in range(pool.pg_num):
                _up, _upp, acting, primary = m.pg_to_up_acting_osds(pid, ps)
                posd = self.cluster.osds[primary]
                rep = posd.scrub_pg(pid, ps, repair=repair)
                errors.extend(rep["errors"])
                if repair:
                    report["scrub_errors_repaired"] += rep["repaired"]
            if not repair:
                assert errors == [], f"scrub inconsistencies: {errors}"
        # 4. replay determinism: the same seed re-plans to the same log
        replay = Thrasher(
            None, thrasher.seed, pool=thrasher.pool,
            n_osds=thrasher.n_osds, n_mons=thrasher.n_mons,
            max_dead=thrasher.max_dead, max_splits=thrasher.max_splits,
            object_size=thrasher.object_size,
        ).plan(len(thrasher.events))
        assert replay == thrasher.events, (
            "replay with the same seed diverged from the executed log"
        )
        return report
