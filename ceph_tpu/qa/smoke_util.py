"""Shared helpers for the qa smoke scripts (ci_gate steps): poll a
predicate, scrape the prometheus exporter, read a gauge line.  One
implementation — the smokes were each re-forking these verbatim, and a
fix to e.g. the exposition-line parsing must not need four edits."""
from __future__ import annotations

import time


def wait_for(pred, timeout: float, step: float = 0.2):
    """Poll `pred` until truthy or the deadline passes; one final call
    after the deadline so a slow-but-correct state still counts."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(step)
    return pred()


def scrape(url: str) -> str:
    """One prometheus exporter scrape, decoded."""
    import urllib.request

    return urllib.request.urlopen(url, timeout=10).read().decode()


def gauge(body: str, metric: str) -> float | None:
    """First sample of `metric` (bare or labeled) in an exposition
    body, or None when the series is absent."""
    for line in body.splitlines():
        if line.startswith(metric + " ") or line.startswith(metric + "{"):
            return float(line.rsplit(" ", 1)[1])
    return None
