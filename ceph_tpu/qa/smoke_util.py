"""Shared helpers for the qa smoke scripts (ci_gate steps): poll a
predicate, scrape the prometheus exporter, read a gauge line, and the
thread-leak bracket for cluster start/stop.  One implementation — the
smokes were each re-forking these verbatim, and a fix to e.g. the
exposition-line parsing must not need four edits."""
from __future__ import annotations

import contextlib
import threading
import time


def wait_for(pred, timeout: float, step: float = 0.2):
    """Poll `pred` until truthy or the deadline passes; one final call
    after the deadline so a slow-but-correct state still counts."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(step)
    return pred()


#: thread-name prefixes a clean teardown may still leave behind for a
#: moment: deliberately-abandoned sentinel probes (a hung backend probe
#: is NOT joinable by design — kernel_telemetry self-terminates it) and
#: per-op fire-and-forget helpers that carry their own deadlines
LEAK_ALLOW = ("backend-probe",)


@contextlib.contextmanager
def assert_no_leaked_threads(grace: float = 10.0,
                             allow: tuple[str, ...] = LEAK_ALLOW):
    """The runtime twin of cephlint CL13/CL14: every thread the body
    starts (cluster bring-up, per-op helpers) must be gone again after
    its teardown, modulo the `allow` prefixes.  Polls up to `grace`
    seconds — join(timeout=...) teardowns finish asynchronously — then
    raises AssertionError naming the zombies."""
    before = set(threading.enumerate())

    def leaked():
        return [t for t in threading.enumerate()
                if t.is_alive() and t not in before
                and not t.name.startswith(allow)]

    yield
    wait_for(lambda: not leaked(), grace)
    left = leaked()
    if left:
        raise AssertionError(
            "leaked threads after teardown: "
            + ", ".join(sorted(t.name for t in left)))


def scrape(url: str) -> str:
    """One prometheus exporter scrape, decoded."""
    import urllib.request

    return urllib.request.urlopen(url, timeout=10).read().decode()


def gauge(body: str, metric: str) -> float | None:
    """First sample of `metric` (bare or labeled) in an exposition
    body, or None when the series is absent."""
    for line in body.splitlines():
        if line.startswith(metric + " ") or line.startswith(metric + "{"):
            return float(line.rsplit(" ", 1)[1])
    return None
